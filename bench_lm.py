#!/usr/bin/env python
"""Transformer-LM single-chip benchmark — tok/s + MFU for the flagship
GPT-style model (111M params: 12 layers, d_model 768, vocab 32000),
fwd+bwd+AdamW per step.

Method: K steps per jitted fori_loop (host dispatch off the timed path),
host-readback sync (block_until_ready is unreliable through device
tunnels), per-config median over R timed windows, and all configs run
INTERLEAVED in ONE process — absolute throughput on a shared chip
drifts +-30% between runs, so only in-process A/B is trustworthy.

Input: tokens flow through the real input pipeline (horovod_tpu/data
sharded loader over a data.synthetic token source, docs/data.md), one
loader batch per fori_loop step, pre-staged as a [K,B,S] device stack
so per-step host dispatch stays off the timed path.

MFU uses the MODEL-FLOPs convention (6·N·T + attention FLOPs), NOT
XLA cost_analysis: with rematerialization the executed-FLOP count
includes recomputation, which would inflate "utilization" for doing
redundant work. Peak bf16 from the device kind (bench.py table).

Usage:
    python bench_lm.py                 # default config sweep, one JSON line
    python bench_lm.py --configs base,tuned
"""

import argparse
import json
import os
import time
from functools import partial

import numpy as np

# Each host call through the axon tunnel carries ~90-100 ms of fixed
# RPC/sync overhead (measured round 4, experiments/hbm_probe.py) — K
# must be large enough that it amortizes below the noise floor. K=20 at
# the seq-8192 step (~0.22 s) keeps it under 2%.
K = int(os.environ.get("HVD_BENCH_LM_K", 20))
WINDOWS = int(os.environ.get("HVD_BENCH_LM_WINDOWS", 3))

# (name, dict of TransformerConfig overrides + batch). The cumulative
# tuning ladder measured on v5e (docs/benchmarks.md LM section and
# BENCH_LM.json, round-4 K=20 methodology + flash-kernel retune):
# 46.4k -> 145.1k tok/s (18.4% -> 57.4% model MFU) in one interleaved
# run. Dead ends kept out: remat (full or dots policy)
# at batch 16/32 always lost to batch-8 no-remat, and batch>=16
# without flash OOMs (the XLA attention score tensors + fp32 logits
# exceed the 15.75G HBM).
CONFIGS = {
    # Round-2 recorded configuration (the ladder's baseline row).
    # Every pre-flash ladder row pins use_flash=False: the auto-select
    # now turns flash on from seq 1024, which would smuggle the flash
    # step into earlier rows and make the ladder non-cumulative.
    "base": dict(n_heads=12, batch=8, remat=True, use_flash=False),
    # head_dim 128 (MXU-filling contraction).
    "heads128": dict(n_heads=6, batch=8, remat=True, use_flash=False),
    # + no recompute (activations fit HBM at seq 2048).
    "noremat": dict(n_heads=6, batch=8, remat=False, use_flash=False),
    # + bf16 logits matmul (softmax stays fp32).
    "bf16logits": dict(n_heads=6, batch=8, remat=False,
                       logits_bf16=True, use_flash=False),
    # + chunked cross-entropy: the fp32 [B,S,V] never materializes.
    # use_flash pinned OFF so this row isolates the loss change (the
    # auto-select would otherwise already turn flash on at seq 2048).
    "chunked": dict(n_heads=6, batch=8, remat=False,
                    logits_bf16=True, loss_chunk=512, use_flash=False),
    # + Pallas flash attention (the 512-block kernel crossover is ~1k).
    "flash": dict(n_heads=6, batch=8, remat=False,
                  logits_bf16=True, loss_chunk=512, use_flash=True),
    # batch-16 variant (fits only once flash kills the score tensor);
    # measured within ~15% of batch-8 "flash" across runs, sometimes
    # ahead, sometimes behind — batch is a weak knob past batch 8.
    "tuned": dict(n_heads=6, batch=16, remat=False,
                  logits_bf16=True, loss_chunk=512, use_flash=True),
    # Long-context row (seq 8192, batch 2 — pass --seq 8192): the
    # round-2 recorded config (left) vs + bf16 logits + chunked loss.
    "long_base": dict(n_heads=6, batch=2, remat=False, use_flash=True),
    "long_tuned": dict(n_heads=6, batch=2, remat=False, use_flash=True,
                       logits_bf16=True, loss_chunk=512),
    # In-process A/B control: "flash" minus the flash kernel (batch 8).
    "tuned_xla_attn": dict(n_heads=6, batch=8, remat=False,
                           logits_bf16=True, loss_chunk=512,
                           use_flash=False),
}

# The documented seq-2048 cumulative ladder (docs/benchmarks.md table).
LADDER = ["base", "heads128", "noremat", "bf16logits", "chunked",
          "flash", "tuned", "tuned_xla_attn"]

CONFIGS.update({
    # Long-context lever ladder at seq 8192 (round-4, VERDICT r3 #6):
    # flash backward block size and loss-chunk sweeps on top of
    # long_tuned, plus a batch-4 row (more rows amortize per-step
    # fixed work).
    "long_fb256": dict(n_heads=6, batch=2, remat=False, use_flash=True,
                       logits_bf16=True, loss_chunk=512,
                       flash_block=256),
    "long_fb1024": dict(n_heads=6, batch=2, remat=False, use_flash=True,
                        logits_bf16=True, loss_chunk=512,
                        flash_block=1024),
    "long_lc2048": dict(n_heads=6, batch=2, remat=False, use_flash=True,
                        logits_bf16=True, loss_chunk=2048),
    "long_batch4": dict(n_heads=6, batch=4, remat=False, use_flash=True,
                        logits_bf16=True, loss_chunk=512),
    # Single row for the 16k demonstration (`--seq 16384 --configs
    # long16k`): batch 1 is what fits; flash + chunked loss are what
    # make it fit at all.
    "long16k": dict(n_heads=6, batch=1, remat=False, use_flash=True,
                    logits_bf16=True, loss_chunk=512),
    # Width demonstration (`--configs wide`, seq 2048): a 392M-param
    # shape whose [1536, 6144] FFN tiles actually fill the MXU — shows
    # the ~57% plateau of the 111M ladder is the model shape, not the
    # framework (docs/benchmarks.md "next lever is model width").
    "wide": dict(d_model=1536, d_ff=6144, batch=8, remat=False,
                 use_flash=True, logits_bf16=True, loss_chunk=512),
    # ~1B-param follow-through (`--configs wide1b`, VERDICT r4 #8):
    # does the measured width lever (64.7% MFU at 392M) hold at a
    # realistic scale, and what binds next? 20 layers x d 2048
    # (head_dim 128) + tied embeddings = 1.03B params. fp32 AdamW
    # state is 3 x 4.1 GB, so remat is back on (activations must
    # shrink to fit the 15.75G HBM) and batch drops to 4.
    "wide1b": dict(d_model=2048, d_ff=8192, n_layers=20, n_heads=16,
                   batch=4, remat=True, use_flash=True,
                   logits_bf16=True, loss_chunk=512),
    # Next-lever probes on the 1B shape (measured round 5): dots-policy
    # remat at batch 2 wins (16.7k tok/s, 59.2% MFU — saving matmul
    # outputs recovers ~3 MFU points over full remat at batch 4);
    # batch 8 full-remat loses (14.6k, 51.8%); dots at batch 4 fails to
    # compile (exceeds HBM — fp32 AdamW state 12.3 GB + dots-saved
    # activations). The binding constraint after width is optimizer-
    # state memory: sharding it (ZeRO-style over 'dp') or bf16 moments
    # is what would let remat off entirely at 1B.
    "wide1b_dots": dict(d_model=2048, d_ff=8192, n_layers=20, n_heads=16,
                        batch=2, remat=True, remat_policy="dots",
                        use_flash=True, logits_bf16=True, loss_chunk=512),
    "wide1b_b8": dict(d_model=2048, d_ff=8192, n_layers=20, n_heads=16,
                      batch=8, remat=True, use_flash=True,
                      logits_bf16=True, loss_chunk=512),
    # bf16 first moment: frees ~2 GB of AdamW state and halves mu's
    # read+write traffic in the optimizer update. At batch 4 + dots it
    # STILL exceeds HBM (measured: compile fails — the dots-saved
    # activations are the bigger term); the batch-2 row below measures
    # the bandwidth side.
    "wide1b_dotsmu": dict(d_model=2048, d_ff=8192, n_layers=20,
                          n_heads=16, batch=2, remat=True,
                          remat_policy="dots", mu_bf16=True,
                          use_flash=True, logits_bf16=True,
                          loss_chunk=512),
})


def model_flops_per_step(n_params, batch, seq, n_layers, d_model):
    """6·N·T parameter FLOPs + causal attention FLOPs (fwd is
    2·B·S²·d per layer for QK^T+AV halved by causality; bwd doubles)."""
    tokens = batch * seq
    param_f = 6.0 * n_params * tokens
    attn_fwd = n_layers * 2.0 * batch * seq * seq * d_model / 2.0 * 2.0
    return param_f + 3.0 * attn_fwd


def bench_config(name, overrides, seq, peak):
    import jax
    import jax.numpy as jnp
    import optax

    from horovod_tpu.models import transformer as tfm

    batch = overrides.pop("batch")
    # Optimizer-side knob (not a TransformerConfig field): bf16 first
    # moment — halves mu's HBM share (the 1B memory lever's cheap half;
    # optax stores nu in fp32 regardless).
    mu_bf16 = overrides.pop("mu_bf16", False)
    base = dict(vocab=32000, d_model=768, n_layers=12, d_ff=3072,
                max_seq=seq, dtype=jnp.bfloat16)
    base.update(overrides)  # rows may resize the model (e.g. "wide")
    cfg = tfm.TransformerConfig(**base)
    params = tfm.init_params(cfg, jax.random.PRNGKey(0))
    n_params = sum(int(np.prod(l.shape))
                   for l in jax.tree_util.tree_leaves(params))
    # Tokens come through the real input pipeline (horovod_tpu/data:
    # synthetic source -> sharded loader), not a jax.random bypass —
    # one loader batch per fori_loop step, staged as a [K,B,S] stack up
    # front so the timed window keeps measuring the device, not host
    # dispatch (docs/data.md). Seeded: every run draws the same stack.
    from horovod_tpu import data as hvd_data
    src = hvd_data.synthetic("tokens", n=max(K * batch, 256),
                             seq_len=seq, vocab=base["vocab"], seed=1)
    loader = hvd_data.build_loader(src, batch_size=batch, rank=0,
                                   world_size=1, seed=1)
    tokens_k = jnp.asarray(np.stack(
        [next(loader).data[0] for _ in range(K)]))   # [K, B, S] int32
    opt = optax.adamw(3e-4, mu_dtype=jnp.bfloat16 if mu_bf16 else None)
    state = opt.init(params)

    def loss_fn(p, tokens):
        targets = jnp.roll(tokens, -1, axis=1)
        return tfm.loss_fn(p, tokens, targets, cfg)

    @partial(jax.jit, donate_argnums=(0, 1))
    def train_k(p, s):
        def body(i, carry):
            p, s = carry
            _, g = jax.value_and_grad(loss_fn)(p, tokens_k[i])
            up, s = opt.update(g, s, p)
            return optax.apply_updates(p, up), s
        return jax.lax.fori_loop(0, K, body, (p, s))

    # 3 warm calls: compile, then reach the jit donation/sharding
    # fixpoint (a recompile lands on call ~2-3 otherwise — bench.py
    # learned the same lesson; a mid-window recompile skews a median
    # of only 3 windows).
    for _ in range(3):
        params, state = train_k(params, state)
    float(jnp.sum(params["ln_f"]))          # force sync (tunnel-safe)
    rates = []
    for _ in range(WINDOWS):
        t0 = time.perf_counter()
        params, state = train_k(params, state)
        float(jnp.sum(params["ln_f"]))
        dt = time.perf_counter() - t0
        rates.append(batch * seq * K / dt)
    tok_s = float(np.median(rates))
    flops = model_flops_per_step(n_params, batch, seq, cfg.n_layers,
                                 cfg.d_model)
    tf_s = tok_s / (batch * seq) * flops / 1e12
    return {"tok_s": round(tok_s, 0), "tflops": round(tf_s, 1),
            "mfu": round(tf_s / peak, 4) if peak else 0.0,
            "params_m": round(n_params / 1e6, 1), "batch": batch,
            "heads": cfg.n_heads, "remat": cfg.remat}


def main():
    ap = argparse.ArgumentParser()
    # Default = the 8-row seq-2048 ladder; the long_* sweep rows are
    # seq-8192-only and run via the explicit --configs list the docs
    # show (at 2048 they would waste minutes and skew the recorded
    # configs dict).
    ap.add_argument("--configs", default=",".join(LADDER))
    ap.add_argument("--seq", type=int, default=2048)
    args = ap.parse_args()

    import jax
    from bench import peak_tflops
    peak = peak_tflops(jax.devices()[0])

    results = {}
    for name in args.configs.split(","):
        try:
            results[name] = bench_config(name, dict(CONFIGS[name]),
                                         args.seq, peak)
        except Exception as e:
            # A sweep row that OOMs (e.g. a flash block past the VMEM
            # budget) must not kill the other rows' measurements.
            print(f"# {name}: FAILED {str(e)[:200]}", flush=True)
            continue
        print(f"# {name}: {results[name]}", flush=True)
    if not results:
        print(json.dumps({"metric": "transformer_lm_tok_s",
                          "error": "every requested config failed",
                          "seq": args.seq}))
        raise SystemExit(1)
    best = max(results, key=lambda n: results[n]["tok_s"])
    # One-line-JSON schema convention (bench.py): value over a recorded
    # baseline, keyed on sequence length — the round-2 numbers for this
    # model were 44.3k tok/s at seq 2048 and 21.5k at 8192
    # (docs/benchmarks.md LM section). Unknown seq -> no ratio rather
    # than a ratio against the wrong baseline.
    baselines = {2048: 44300.0, 8192: 21500.0}
    out = {
        "metric": "transformer_lm_tok_s",
        "value": results[best]["tok_s"],
        "unit": "tok/s",
        "mfu": results[best]["mfu"],
        "seq": args.seq, "best_config": best, "peak_tflops": peak,
        "configs": results,
    }
    # The recorded baselines are for the 111M ladder shape; a row that
    # resizes the model (e.g. "wide") must not record a ratio against
    # the wrong model's baseline.
    resized = any(key in CONFIGS[best]
                  for key in ("d_model", "d_ff", "n_layers", "vocab"))
    if args.seq in baselines and not resized:
        out["vs_baseline"] = round(
            results[best]["tok_s"] / baselines[args.seq], 3)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
