"""Packaging with a native-core build step.

The reference's setup.py is a 896-line probing build (MPI flags, CUDA/
NCCL discovery, per-framework extensions, linker version scripts —
setup.py:294-870). On TPU the data plane is XLA, so the only native
artifact is the control-plane core, compiled by the same
``horovod_tpu.runtime.build`` module the lazy in-process loader uses —
one build recipe, not two.

The build is best-effort at install time: without a toolchain the wheel
still installs and the runtime rebuilds (or falls back to the Python
control plane) on first use.
"""

import importlib.util
import os
import sys

from setuptools import setup
from setuptools.command.build_py import build_py
from setuptools.dist import Distribution


def _load_native_builder():
    """Load runtime/build.py directly by path: it is stdlib-only, while
    importing it as horovod_tpu.runtime.build would execute the package
    __init__ (which imports jax — absent from PEP 517 isolated build
    environments)."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "horovod_tpu", "runtime", "build.py")
    spec = importlib.util.spec_from_file_location("_hvdtpu_native_build",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class BuildWithNativeCore(build_py):
    def run(self):
        # Build FIRST: build_py copies package data (which includes the
        # .so) into build_lib, so the artifact must exist in the source
        # tree before the copy or the wheel ships stale/missing binaries.
        try:
            builder = _load_native_builder()
            path = builder.build(verbose=True)
            print(f"built native core: {path}")
        except Exception as e:  # toolchain-less install stays usable
            print(f"warning: native core not built ({e}); the runtime "
                  "will build it on first use or fall back to the "
                  "Python control plane", file=sys.stderr)
        super().run()


class BinaryDistribution(Distribution):
    """The wheel carries a compiled .so: mark it platform-specific so a
    linux-x86_64 build is never installed as py3-none-any on another
    platform (where the runtime would find a wrong-arch binary newer
    than its sources and refuse to rebuild)."""

    def has_ext_modules(self):
        return True


setup(cmdclass={"build_py": BuildWithNativeCore},
      distclass=BinaryDistribution)
