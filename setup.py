"""Packaging with a native-core build step.

The reference's setup.py is a 896-line probing build (MPI flags, CUDA/
NCCL discovery, per-framework extensions, linker version scripts —
setup.py:294-870). On TPU the data plane is XLA, so the only native
artifact is the control-plane core, compiled by the same
``horovod_tpu.runtime.build`` module the lazy in-process loader uses —
one build recipe, not two.

The build is best-effort at install time: without a toolchain the wheel
still installs and the runtime rebuilds (or falls back to the Python
control plane) on first use.
"""

import sys

from setuptools import setup
from setuptools.command.build_py import build_py


class BuildWithNativeCore(build_py):
    def run(self):
        # Build FIRST: build_py copies package data (which includes the
        # .so) into build_lib, so the artifact must exist in the source
        # tree before the copy or the wheel ships stale/missing binaries.
        try:
            sys.path.insert(0, ".")
            from horovod_tpu.runtime.build import build
            path = build(verbose=True)
            print(f"built native core: {path}")
        except Exception as e:  # toolchain-less install stays usable
            print(f"warning: native core not built ({e}); the runtime "
                  "will build it on first use or fall back to the "
                  "Python control plane", file=sys.stderr)
        super().run()


setup(cmdclass={"build_py": BuildWithNativeCore})
