#!/usr/bin/env python
"""Framework-shim throughput on a real model — the tracked config every
published chip number so far bypassed (VERDICT r4 missing #2; BASELINE
tracks "BERT-Large fine-tune (Keras, Tensor-Fusion bucketed grad
allreduce)"; reference methodology docs/benchmarks.md:40-63).

Four arms, each in its own subprocess (backend env isolation), all on
whatever accelerator is attached (the real chip under axon):

  jax        — pure-JAX 111M GPT train step (models/transformer), both
               per-call (K=1, the dispatch shape every shim has) and
               K-chained (the bench_lm headline shape). The K=1 row is
               the honest control for the shims: through the axon
               tunnel each host->device call carries ~100 ms, which is
               plumbing every per-step framework loop pays.
  keras_fit  — the SAME 111M architecture as a Keras 3 model (jax
               backend) trained with model.fit under
               horovod_tpu.keras.DistributedOptimizer.
  torch      — GPT-style torch model (torch is CPU-only here) under
               horovod_tpu.torch.DistributedOptimizer: grads cross the
               DLPack boundary into the TPU engine each step. Control:
               the identical model/step WITHOUT the shim — the delta is
               the whole shim+engine+chip round trip.
  bucketed   — BERT-Large-shaped gradient set (393 tensors, ~340M
               params fp32) through the Keras shim's bucketed batch
               path (_engine_allreduce_batch) on the chip: the
               Tensor-Fusion bucketed grad-allreduce config itself.

Writes BENCH_SHIMS.json and prints it.
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))

ITERS = int(os.environ.get("SHIM_BENCH_ITERS", 5))
WARM = int(os.environ.get("SHIM_BENCH_WARM", 3))

# The matched 111M config (bench_lm ladder shape, short-seq variant so
# the Keras/torch python loops turn steps in seconds).
SEQ, BATCH = 512, 8
ARCH = dict(vocab=32000, d_model=768, n_layers=12, n_heads=12, d_ff=3072)

COMMON = f"""
import json, os, sys, time
sys.path.insert(0, {REPO!r})
import numpy as np
SEQ, BATCH = {SEQ}, {BATCH}
ARCH = {ARCH!r}
ITERS, WARM = {ITERS}, {WARM}
"""

ARM_JAX = COMMON + """
import jax, jax.numpy as jnp, optax
from functools import partial
from horovod_tpu.models import transformer as tfm

cfg = tfm.TransformerConfig(vocab=ARCH["vocab"], d_model=ARCH["d_model"],
                            n_layers=ARCH["n_layers"], d_ff=ARCH["d_ff"],
                            max_seq=SEQ, dtype=jnp.bfloat16)
params = tfm.init_params(cfg, jax.random.PRNGKey(0))
n_params = sum(int(np.prod(l.shape))
               for l in jax.tree_util.tree_leaves(params))
tokens = jax.random.randint(jax.random.PRNGKey(1), (BATCH, SEQ), 0,
                            ARCH["vocab"])
targets = jnp.roll(tokens, -1, axis=1)
opt = optax.adamw(3e-4)
state = opt.init(params)

@partial(jax.jit, donate_argnums=(0, 1), static_argnums=(2,))
def train_k(p, s, k):
    def body(_, carry):
        p, s = carry
        _, g = jax.value_and_grad(
            lambda p: tfm.loss_fn(p, tokens, targets, cfg))(p)
        up, s = opt.update(g, s, p)
        return optax.apply_updates(p, up), s
    return jax.lax.fori_loop(0, k, body, (p, s))

def run(k, iters):
    global params, state
    for _ in range(WARM):
        params, state = train_k(params, state, k)
    float(jnp.sum(params["ln_f"]))
    rates = []
    for _ in range(iters):
        t0 = time.perf_counter()
        params, state = train_k(params, state, k)
        float(jnp.sum(params["ln_f"]))
        rates.append(BATCH * SEQ * k / (time.perf_counter() - t0))
    return float(np.median(rates))

tok_k1 = run(1, ITERS * 3)
tok_k10 = run(10, ITERS)
print(json.dumps({"arm": "jax", "tok_s_per_call": round(tok_k1, 0),
                  "tok_s_chained10": round(tok_k10, 0),
                  "params_m": round(n_params / 1e6, 1),
                  "backend": jax.default_backend()}))
"""

ARM_KERAS = COMMON + """
os.environ["KERAS_BACKEND"] = "jax"
import jax
import keras
import horovod_tpu.keras as hvd_keras

hvd_keras.init()

def block(x, i):
    h = keras.layers.MultiHeadAttention(
        num_heads=ARCH["n_heads"], key_dim=ARCH["d_model"] // ARCH["n_heads"],
        name=f"attn{i}")(x, x, use_causal_mask=True)
    x = keras.layers.LayerNormalization(name=f"ln1_{i}")(x + h)
    h = keras.layers.Dense(ARCH["d_ff"], activation="gelu",
                           name=f"ffi{i}")(x)
    h = keras.layers.Dense(ARCH["d_model"], name=f"ffo{i}")(h)
    return keras.layers.LayerNormalization(name=f"ln2_{i}")(x + h)

inp = keras.Input((SEQ,), dtype="int32")
x = keras.layers.Embedding(ARCH["vocab"], ARCH["d_model"])(inp)
for i in range(ARCH["n_layers"]):
    x = block(x, i)
out = keras.layers.Dense(ARCH["vocab"], name="unembed")(x)
model = keras.Model(inp, out)

opt = hvd_keras.DistributedOptimizer(keras.optimizers.AdamW(3e-4))
model.compile(optimizer=opt,
              loss=keras.losses.SparseCategoricalCrossentropy(
                  from_logits=True))

rng = np.random.RandomState(0)
steps = ITERS + WARM
xs = rng.randint(0, ARCH["vocab"], size=(BATCH * steps, SEQ)).astype("int32")
ys = np.roll(xs, -1, axis=1)

model.fit(xs[:BATCH * WARM], ys[:BATCH * WARM], batch_size=BATCH,
          epochs=1, verbose=0)                      # compile + warm
t0 = time.perf_counter()
model.fit(xs[BATCH * WARM:], ys[BATCH * WARM:], batch_size=BATCH,
          epochs=1, verbose=0)
dt = time.perf_counter() - t0
print(json.dumps({"arm": "keras_fit",
                  "tok_s": round(BATCH * SEQ * ITERS / dt, 0),
                  "params_m": round(model.count_params() / 1e6, 1),
                  "backend": keras.backend.backend(),
                  "wrapped": type(model.optimizer).__name__}))
"""

ARM_TORCH = COMMON + """
if os.environ.get("FORCE_CPU") == "1":
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax
    jax.config.update("jax_platforms", "cpu")
import torch, torch.nn as nn, torch.nn.functional as F
use_shim = os.environ.get("TORCH_SHIM") == "1"

D, L, H, V, S, B = 512, 8, 8, ARCH["vocab"], 256, 2
torch.manual_seed(0)

class Block(nn.Module):
    def __init__(self):
        super().__init__()
        self.attn = nn.MultiheadAttention(D, H, batch_first=True)
        self.ln1, self.ln2 = nn.LayerNorm(D), nn.LayerNorm(D)
        self.ff = nn.Sequential(nn.Linear(D, 4 * D), nn.GELU(),
                                nn.Linear(4 * D, D))
    def forward(self, x, mask):
        h, _ = self.attn(x, x, x, attn_mask=mask, need_weights=False)
        x = self.ln1(x + h)
        return self.ln2(x + self.ff(x))

class GPT(nn.Module):
    def __init__(self):
        super().__init__()
        self.emb = nn.Embedding(V, D)
        self.blocks = nn.ModuleList(Block() for _ in range(L))
        self.out = nn.Linear(D, V)
    def forward(self, idx):
        mask = torch.triu(torch.full((S, S), float("-inf")), 1)
        x = self.emb(idx)
        for b in self.blocks:
            x = b(x, mask)
        return self.out(x)

model = GPT()
n_params = sum(p.numel() for p in model.parameters())
opt = torch.optim.SGD(model.parameters(), lr=1e-3)
if use_shim:
    import horovod_tpu.torch as hvd
    hvd.init()
    opt = hvd.DistributedOptimizer(
        opt, named_parameters=model.named_parameters())
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)

idx = torch.randint(0, V, (B, S))
tgt = torch.roll(idx, -1, 1)

def step():
    opt.zero_grad()
    loss = F.cross_entropy(model(idx).reshape(-1, V), tgt.reshape(-1))
    loss.backward()
    opt.step()

for _ in range(WARM):
    step()
rates = []
for _ in range(ITERS):
    t0 = time.perf_counter()
    step()
    rates.append(B * S / (time.perf_counter() - t0))
stats = {}
extra = {}
backend = "none"
if use_shim:
    import jax
    import horovod_tpu
    from horovod_tpu.utils import interop
    backend = jax.default_backend()

    def counters():
        snap = horovod_tpu.metrics_snapshot()

        def val(fam, key=""):
            return snap.get(fam, {}).get("values", {}).get(key, 0)

        return {
            "compile_misses": val("hvdtpu_executor_cache_misses_total"),
            "compile_hits": val("hvdtpu_executor_cache_hits_total"),
            "bucket_fires_hook": val("hvdtpu_torch_bucket_fires_total",
                                     'trigger="hook"'),
            "bucket_fires_flush": val("hvdtpu_torch_bucket_fires_total",
                                      'trigger="flush"'),
            "bucket_bytes": val("hvdtpu_torch_bucket_bytes_total"),
        }

    # Steady-state counter deltas over ONE step: interop split proves
    # the DLPack path carries the gradients; compile_misses == 0 proves
    # the per-bucket programs are REUSED, not rebuilt.
    interop.reset_stats()
    before = counters()
    step()
    after = counters()
    stats = interop.stats()
    extra = {
        "buckets": len(getattr(opt, "_buckets", [])),
        "dlpack_available": bool(interop.transfer_egress_supported()),
        "one_step": {k: round(after[k] - before[k], 1) for k in before},
    }
arm = "torch_plain"
if use_shim:
    arm = "torch_shim_cpu" if os.environ.get("FORCE_CPU") == "1" \
        else "torch_shim"
row = {"arm": arm,
       "tok_s": round(float(np.median(rates)), 1),
       "params_m": round(n_params / 1e6, 1),
       "grad_mb_per_step": round(n_params * 4 / 2**20, 1),
       "backend": backend,
       "interop_one_step": stats}
row.update(extra)
print(json.dumps(row))
"""

ARM_BUCKETED = COMMON + """
import horovod_tpu as hvd
from horovod_tpu.keras import _engine_allreduce_batch
hvd.init()

# BERT-Large (340M): 24 layers x (4 x 1024x1024 attn + 1024x4096 +
# 4096x1024 ffn + biases + 2 LN pairs) + embeddings.
shapes = [(30522, 1024), (512, 1024), (2, 1024), (1024,), (1024,)]
for _ in range(24):
    shapes += [(1024, 1024)] * 4 + [(1024,)] * 4
    shapes += [(1024, 4096), (4096,), (4096, 1024), (1024,)]
    shapes += [(1024,), (1024,)] * 2
rng = np.random.RandomState(0)
grads = [rng.randn(*s).astype(np.float32) for s in shapes]
names = [f"bert.{i}" for i in range(len(grads))]
nbytes = sum(g.nbytes for g in grads)

for _ in range(WARM):
    _engine_allreduce_batch(grads, names, None)
rates = []
for _ in range(ITERS):
    t0 = time.perf_counter()
    _engine_allreduce_batch(grads, names, None)
    rates.append(time.perf_counter() - t0)
import jax
med = float(np.median(rates))
print(json.dumps({"arm": "bucketed_bert_large",
                  "tensors": len(grads),
                  "params_m": round(nbytes / 4 / 1e6, 1),
                  "step_s": round(med, 3),
                  "gb_s": round(nbytes / 1e9 / med, 2),
                  "backend": jax.default_backend()}))
"""


def run_arm(code: str, extra_env=None, timeout=3600) -> dict:
    env = dict(os.environ)
    env.update(extra_env or {})
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=timeout,
                         cwd=REPO)
    if out.returncode != 0:
        raise RuntimeError(f"arm failed:\n{out.stderr[-3000:]}")
    return json.loads(out.stdout.strip().splitlines()[-1])


ARMS = {
    "jax": (ARM_JAX, None),
    "keras_fit": (ARM_KERAS, None),
    "torch_plain": (ARM_TORCH, {"TORCH_SHIM": "0"}),
    "torch_shim": (ARM_TORCH, {"TORCH_SHIM": "1"}),
    "torch_shim_cpu": (ARM_TORCH, {"TORCH_SHIM": "1", "FORCE_CPU": "1"}),
    "bucketed": (ARM_BUCKETED, None),
}


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--arms", default="all",
        help="comma list of arms to re-measure (%s); arms not listed "
             "are carried forward from the existing BENCH_SHIMS.json "
             "with a carried_from_previous_run marker, so a torch-only "
             "re-run does not have to repay the heavy jax/keras "
             "control arms" % ",".join(ARMS))
    args = ap.parse_args(argv)
    selected = (set(ARMS) if args.arms == "all"
                else set(a.strip() for a in args.arms.split(",")))
    unknown = selected - set(ARMS)
    if unknown:
        ap.error(f"unknown arms: {sorted(unknown)}")
    prior = {}
    path = os.path.join(REPO, "BENCH_SHIMS.json")
    if os.path.exists(path):
        with open(path) as f:
            prior = json.load(f).get("rows", {})

    rows = {}
    for name, (code, extra_env) in ARMS.items():
        if name in selected:
            rows[name] = dict(run_arm(code, extra_env))
            rows[name].pop("carried_from_previous_run", None)
        elif name in prior:
            rows[name] = dict(prior[name], carried_from_previous_run=True)

    j, k = rows.get("jax"), rows.get("keras_fit")
    tp = rows["torch_plain"]
    result = {
        "metric": "framework_shim_throughput",
        # The re-measured / carried split, pinned at the top level so
        # the contract test (tests/test_bench_shims_contract.py) can
        # tell which rows describe THIS machine and which are stale
        # history (e.g. chip rows carried on a CPU-only box).
        "measured_arms": sorted(n for n in rows if n in selected),
        "carried_arms": sorted(n for n in rows if n not in selected),
        "value": (round(k["tok_s"] / j["tok_s_per_call"], 3)
                  if j and k else None),
        "unit": "keras-fit / pure-jax-per-call tok rate",
        "torch_shim_retention_chip": round(
            rows["torch_shim"]["tok_s"] / tp["tok_s"], 3),
        "torch_shim_retention_cpu": round(
            rows["torch_shim_cpu"]["tok_s"] / tp["tok_s"], 3),
        "rows": rows,
        "note": ("per-call rows share the per-call dispatch floor of "
                 "whatever link fronts the accelerator; chained10 is "
                 "the bench_lm headline shape no per-step framework "
                 "loop can use. The torch shim rows run the bucketed "
                 "hot path (docs/torch.md): gradients pack into "
                 "size-targeted buckets fired during backward, one "
                 "engine call + one DLPack crossing each way per "
                 "bucket per step, per-bucket programs reused across "
                 "steps (one_step.compile_misses == 0 in steady "
                 "state). The cpu row is the same shim with a memcpy "
                 "boundary and isolates the shim's intrinsic cost; "
                 "interop_one_step proves which boundary path carried "
                 "the gradients."),
    }
    with open(path, "w") as f:
        f.write(json.dumps(result) + "\n")
    print(json.dumps(result))


if __name__ == "__main__":
    main()
