"""Keras shim on the JAX backend — the TPU-native Keras path.

The main keras tests run on the torch backend (tests/test_keras.py);
Keras fixes its backend at import, so the jax-backend path gets its own
subprocess here: DistributedOptimizer inside Keras 3's jitted jax train
step routes gradients through the inline psum (keras/__init__.py:68-87).

Marked slow (subprocess + keras/jax startup).
"""

import os
import subprocess
import sys
import textwrap

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SCRIPT = textwrap.dedent("""
    import os
    os.environ["KERAS_BACKEND"] = "jax"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import keras

    import horovod_tpu as hvd
    import horovod_tpu.keras as hvd_keras

    hvd.init()
    assert hvd.size() == 8

    keras.utils.set_random_seed(0)
    model = keras.Sequential([
        keras.layers.Input((8,)),
        keras.layers.Dense(16, activation="relu"),
        keras.layers.Dense(2),
    ])
    opt = hvd_keras.DistributedOptimizer(
        keras.optimizers.SGD(learning_rate=0.1))
    model.compile(optimizer=opt, loss="mse", jit_compile=True)

    x = np.random.rand(32, 8).astype("float32")
    y = np.random.rand(32, 2).astype("float32")
    before = [np.array(w) for w in model.get_weights()]
    hist = model.fit(x, y, batch_size=16, epochs=2, verbose=0,
                     shuffle=False)
    after = model.get_weights()
    assert any(not np.allclose(b, a) for b, a in zip(before, after)), \\
        "weights did not move"
    losses = hist.history["loss"]
    assert losses[-1] < losses[0], f"loss did not decrease: {losses}"

    # Replicated virtual ranks: wrapped == unwrapped steps must match.
    keras.utils.set_random_seed(0)
    ref = keras.Sequential([
        keras.layers.Input((8,)),
        keras.layers.Dense(16, activation="relu"),
        keras.layers.Dense(2),
    ])
    ref.compile(optimizer=keras.optimizers.SGD(learning_rate=0.1),
                loss="mse", jit_compile=True)
    ref.fit(x, y, batch_size=16, epochs=2, verbose=0,
            shuffle=False)
    for a, b in zip(after, ref.get_weights()):
        np.testing.assert_allclose(np.array(a), np.array(b),
                                   rtol=1e-4, atol=1e-5)
    print("KERAS-JAX OK")
""")


def test_keras_jax_backend_fit():
    pytest.importorskip("keras")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, "-c", _SCRIPT],
                          capture_output=True, text=True, timeout=420,
                          env=env, cwd=REPO)
    assert proc.returncode == 0, (
        f"stdout:\n{proc.stdout[-2000:]}\nstderr:\n{proc.stderr[-3000:]}")
    assert "KERAS-JAX OK" in proc.stdout
