"""Keras shim on the JAX backend — the TPU-native Keras path.

The main keras tests run on the torch backend (tests/test_keras.py);
Keras fixes its backend at import, so the jax-backend path gets its own
subprocess here: DistributedOptimizer inside Keras 3's jitted jax train
step routes gradients through the inline psum (keras/__init__.py:68-87).

Marked slow (subprocess + keras/jax startup).
"""

import os
import subprocess
import sys
import textwrap

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SCRIPT = textwrap.dedent("""
    import os
    os.environ["KERAS_BACKEND"] = "jax"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import keras

    import horovod_tpu as hvd
    import horovod_tpu.keras as hvd_keras

    hvd.init()
    assert hvd.size() == 8

    keras.utils.set_random_seed(0)
    model = keras.Sequential([
        keras.layers.Input((8,)),
        keras.layers.Dense(16, activation="relu"),
        keras.layers.Dense(2),
    ])
    opt = hvd_keras.DistributedOptimizer(
        keras.optimizers.SGD(learning_rate=0.1))
    model.compile(optimizer=opt, loss="mse", jit_compile=True)

    x = np.random.rand(32, 8).astype("float32")
    y = np.random.rand(32, 2).astype("float32")
    before = [np.array(w) for w in model.get_weights()]
    hist = model.fit(x, y, batch_size=16, epochs=2, verbose=0,
                     shuffle=False)
    after = model.get_weights()
    assert any(not np.allclose(b, a) for b, a in zip(before, after)), \\
        "weights did not move"
    losses = hist.history["loss"]
    assert losses[-1] < losses[0], f"loss did not decrease: {losses}"

    # Replicated virtual ranks: wrapped == unwrapped steps must match.
    keras.utils.set_random_seed(0)
    ref = keras.Sequential([
        keras.layers.Input((8,)),
        keras.layers.Dense(16, activation="relu"),
        keras.layers.Dense(2),
    ])
    ref.compile(optimizer=keras.optimizers.SGD(learning_rate=0.1),
                loss="mse", jit_compile=True)
    ref.fit(x, y, batch_size=16, epochs=2, verbose=0,
            shuffle=False)
    for a, b in zip(after, ref.get_weights()):
        np.testing.assert_allclose(np.array(a), np.array(b),
                                   rtol=1e-4, atol=1e-5)
    print("KERAS-JAX OK")
""")


def test_keras_jax_backend_fit():
    pytest.importorskip("keras")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, "-c", _SCRIPT],
                          capture_output=True, text=True, timeout=420,
                          env=env, cwd=REPO)
    assert proc.returncode == 0, (
        f"stdout:\n{proc.stdout[-2000:]}\nstderr:\n{proc.stderr[-3000:]}")
    assert "KERAS-JAX OK" in proc.stdout


_SHARD_MAP_SCRIPT = textwrap.dedent("""
    import os
    os.environ["KERAS_BACKEND"] = "jax"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu.keras import _jax_inline_allreduce

    hvd.init()
    mesh = hvd.mesh()

    # 1) Under shard_map with a 'dp' axis: psum-average across shards.
    def f(g):
        return _jax_inline_allreduce(g[0])

    xs = jnp.arange(8.0).reshape(8, 1)
    out = jax.shard_map(f, mesh=mesh, in_specs=P("dp"),
                        out_specs=P(), check_vma=False)(xs)
    np.testing.assert_allclose(np.asarray(out), 3.5)  # mean(0..7)
    print("PSUM-PATH OK")

    # 2) Under shard_map with a DIFFERENTLY-NAMED axis: must raise with
    # rename guidance, never silently train divergent shards.
    from jax.sharding import Mesh
    mesh2 = Mesh(np.array(jax.devices()), ("replica",))
    try:
        jax.shard_map(f, mesh=mesh2, in_specs=P("replica"),
                      out_specs=P(), check_vma=False)(xs)
        raise SystemExit("expected RuntimeError for wrong axis name")
    except RuntimeError as e:
        assert "'dp'" in str(e) and "replica" in str(e), e
    print("WRONG-AXIS OK")

    # 3) Plain jit, single process, replicated grads: pass-through is the
    # identity (XLA/the shardings own the reduction) — NOT a double
    # division by world size.
    @jax.jit
    def g(x):
        return _jax_inline_allreduce(x)

    val = jnp.full((3,), 5.0)
    np.testing.assert_allclose(np.asarray(g(val)), 5.0)
    print("PASSTHROUGH OK")
""")


def test_keras_jax_psum_passthrough_decisions():
    """VERDICT r1 weak #3: the Keras-JAX pass-through logic makes
    silently-wrong-if-misjudged decisions (keras/__init__.py
    _jax_inline_allreduce); pin each branch — psum under 'dp',
    loud failure under a misnamed axis, identity pass-through in a plain
    single-process jit."""
    pytest.importorskip("keras")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, "-c", _SHARD_MAP_SCRIPT],
                          capture_output=True, text=True, timeout=420,
                          env=env, cwd=REPO)
    assert proc.returncode == 0, (
        f"stdout:\n{proc.stdout[-2000:]}\nstderr:\n{proc.stderr[-3000:]}")
    for tag in ("PSUM-PATH OK", "WRONG-AXIS OK", "PASSTHROUGH OK"):
        assert tag in proc.stdout


_COMPRESSION_SCRIPT = textwrap.dedent("""
    import os
    os.environ["KERAS_BACKEND"] = "jax"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import keras

    import horovod_tpu as hvd
    import horovod_tpu.keras as hvd_keras
    from horovod_tpu.compression import Compression

    hvd.init()

    # Eager apply (run_eagerly): gradients cross the engine with fp16
    # compression; training must still converge to the uncompressed
    # result within half precision tolerance.
    keras.utils.set_random_seed(0)
    model = keras.Sequential([
        keras.layers.Input((4,)), keras.layers.Dense(1)])
    opt = hvd_keras.DistributedOptimizer(
        keras.optimizers.SGD(learning_rate=0.05),
        compression=Compression.fp16)
    model.compile(optimizer=opt, loss="mse", run_eagerly=True)
    x = np.random.rand(16, 4).astype("float32")
    y = (x @ np.array([[1.0], [-2.0], [0.5], [3.0]],
                      dtype=np.float32)).astype("float32")
    h = model.fit(x, y, batch_size=8, epochs=3, verbose=0, shuffle=False)
    losses = h.history["loss"]
    assert losses[-1] < losses[0], losses
    print("FP16-COMPRESSION OK")

    # broadcast_global_variables syncs weights + optimizer slots.
    hvd_keras.broadcast_global_variables(0, model=model)
    print("BCAST OK")

    # Host-value helpers mirror _keras/__init__.py:78-90.
    assert float(hvd_keras.allreduce(2.0, average=False)) == 2.0 * hvd.size()
    assert hvd_keras.allgather([1.0]).shape == (hvd.size(),)
    assert float(hvd_keras.broadcast(7.0, 0)) == 7.0
    print("HOST-VALUES OK")
""")


def test_keras_jax_compression_and_host_values():
    pytest.importorskip("keras")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, "-c", _COMPRESSION_SCRIPT],
                          capture_output=True, text=True, timeout=420,
                          env=env, cwd=REPO)
    assert proc.returncode == 0, (
        f"stdout:\n{proc.stdout[-2000:]}\nstderr:\n{proc.stderr[-3000:]}")
    for tag in ("FP16-COMPRESSION OK", "BCAST OK", "HOST-VALUES OK"):
        assert tag in proc.stdout
