"""Example-as-smoke-test — the reference CI sed-shrinks and runs its real
examples under ``mpirun -np 2`` (.travis.yml:113-157). Here each example
runs as a real subprocess on the virtual CPU mesh with shrunken step
counts; pass criterion is exit 0 plus the expected progress output.

Marked slow: each example pays interpreter + jax startup (~20-60 s).
"""

import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = os.path.join(REPO, "examples")


def _needs(module):
    """Skip when the example's framework isn't installed — the same
    importorskip convention the unit suites use (tests/test_keras.py:14).
    Examples run as subprocesses, so importorskip alone can't gate them."""
    pytest.importorskip(module)


# Names of examples that needed their retry this run. One or two
# scheduling hiccups on a shared box are expected noise; more means the
# retry is masking genuine flakiness — fail the run so "suite green"
# keeps meaning something (round-4 VERDICT weak #5).
_retries_used = []
_MAX_RETRIES_PER_RUN = 2


@pytest.fixture(scope="module", autouse=True)
def _retry_budget():
    yield
    assert len(_retries_used) <= _MAX_RETRIES_PER_RUN, (
        f"{len(_retries_used)} examples needed their retry this run "
        f"({', '.join(_retries_used)}) — above the "
        f"{_MAX_RETRIES_PER_RUN}-retry noise budget; the retry is "
        "masking real flakiness, investigate instead of re-running")


def _run(name, env_extra=None, args=(), timeout=420, devices=8):
    env = dict(os.environ)
    # Other test modules set KERAS_BACKEND at import (collection) time;
    # examples must see a clean slate and choose their own backend.
    env.pop("KERAS_BACKEND", None)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": f"--xla_force_host_platform_device_count={devices}",
        "STEPS": "8", "EPOCHS": "1",
    })
    env.update(env_extra or {})
    # One retry: these spawn full framework subprocesses on a shared
    # 1-core box, where XLA's 40 s collective-rendezvous skew timeout
    # occasionally trips under full-suite load. A deterministic breakage
    # still fails twice; a scheduling hiccup passes on the second try.
    details = []
    for _ in (0, 1):
        try:
            proc = subprocess.run(
                [sys.executable, os.path.join(EXAMPLES, name), *args],
                capture_output=True, text=True, timeout=timeout, env=env,
                cwd=EXAMPLES)
        except subprocess.TimeoutExpired as e:
            def _txt(b):
                return (b.decode() if isinstance(b, bytes) else (b or ""))
            details.append(f"timed out after {timeout}s\n"
                           f"stdout:\n{_txt(e.stdout)[-2000:]}\n"
                           f"stderr:\n{_txt(e.stderr)[-2000:]}")
            continue  # a hang is the same flake class as a crash
        if proc.returncode == 0:
            if details:  # first attempt failed, retry saved it
                _retries_used.append(name)
            return proc.stdout
        details.append(f"exit {proc.returncode}\n"
                       f"stdout:\n{proc.stdout[-2000:]}\n"
                       f"stderr:\n{proc.stderr[-2000:]}")
    pytest.fail(f"{name} failed twice:\n--- attempt 1 ---\n{details[0]}\n"
                f"--- attempt 2 ---\n{details[1]}")


class TestExamples:
    def test_jax_mnist(self):
        out = _run("jax_mnist.py")
        assert "loss" in out and "checkpoint written" in out

    def test_jax_mnist_file_data(self, tmp_path):
        """Rank-sharded FILE-reading input pipeline (VERDICT r2 #6): the
        example must genuinely read per-rank shard files from disk."""
        out = _run("jax_mnist_file_data.py",
                   {"DATA_DIR": str(tmp_path / "shards"), "STEPS": "8"})
        assert "reading" in out and "shard files" in out
        assert "loss" in out and "done:" in out
        import glob as _g
        assert len(_g.glob(str(tmp_path / "shards" / "*.npz"))) == 8

    def test_jax_pipeline_end_to_end(self, tmp_path):
        """The full-pipeline example (VERDICT r3 #8, the reference's
        keras_spark_rossmann.py scope): ETL -> rank-sharded train ->
        rank-0 checkpoint -> restore/resume -> inference writing a
        predictions file. PIPELINE_OK prints only if the resumed loss
        continued descending AND holdout RMSE reached the noise floor."""
        data = tmp_path / "pipeline"
        out = _run("jax_pipeline_end_to_end.py",
                   {"DATA_DIR": str(data), "STEPS": "25", "EPOCHS": "2",
                    "N_ROWS": "8000"}, devices=1)
        assert "[etl]" in out  # 'wrote' first run, 'reusing' on retry
        assert "[resume] restored" in out
        assert "PIPELINE_OK" in out
        assert (data / "predictions.csv").exists()
        assert (data / "checkpoints" / "2.pkl").exists()

    def test_jax_mnist_eager(self):
        # 2 virtual devices: the eager fused collective rendezvous has a
        # 40 s skew timeout, and 8 conv workloads sharing one CPU core
        # can exceed it (real meshes have a core per device).
        out = _run("jax_mnist_eager.py", {"STEPS": "4"}, devices=2)
        assert "loss" in out

    def test_jax_word2vec(self):
        out = _run("jax_word2vec.py", {"STEPS": "30"})
        assert "nce loss" in out and "nearest" in out

    def test_pytorch_mnist(self):
        _needs("torch")
        out = _run("pytorch_mnist.py")
        assert "acc" in out

    def test_mxnet_mnist(self):
        out = _run("mxnet_mnist.py")
        assert "acc" in out

    def test_mxnet_imagenet_resnet50(self):
        out = _run("mxnet_imagenet_resnet50.py",
                   args=("--batch-size", "2", "--image-size", "32"))
        assert "loss" in out

    def test_pytorch_imagenet_resnet50(self):
        _needs("torch")
        out = _run("pytorch_imagenet_resnet50.py",
                   args=("--epochs", "1", "--batch-size", "2",
                         "--image-size", "32",
                         "--batches-per-allreduce", "2"))
        assert "epoch 0" in out

    def test_tensorflow_mnist(self):
        _needs("tensorflow")
        # 2 devices: TF + JAX on one CPU core is contention-flaky at 8
        # (same reasoning as test_jax_mnist_eager).
        out = _run("tensorflow_mnist.py", {"STEPS": "6"}, devices=2)
        assert "loss" in out and "checkpoint written" in out

    def test_pytorch_synthetic_benchmark(self):
        _needs("torch")
        out = _run("pytorch_synthetic_benchmark.py",
                   args=("--model", "resnet18", "--batch-size", "2",
                         "--image-size", "32", "--num-iters", "1",
                         "--num-batches-per-iter", "1",
                         "--num-warmup-batches", "1"))
        assert "Img/sec" in out

    def test_runner_end_to_end(self):
        out = _run("runner_end_to_end.py",
                   {"NP": "2",
                    "XLA_FLAGS": "--xla_force_host_platform_device_count=1"})
        assert "rank 0" in out and "rank 1" in out
        assert "sample predictions" in out

    def test_tensorflow_mnist_eager(self):
        _needs("tensorflow")
        out = _run("tensorflow_mnist_eager.py", {"STEPS": "6"}, devices=2)
        assert "loss" in out

    def test_tensorflow_mnist_estimator(self):
        _needs("tensorflow")
        out = _run("tensorflow_mnist_estimator.py", {"STEPS": "8"},
                   devices=2)
        assert "DONE" in out

    def test_keras_mnist(self):
        _needs("keras")
        _needs("torch")  # the example's default Keras backend
        out = _run("keras_mnist.py", timeout=600,
                   env_extra={"KERAS_BACKEND": "torch"})
        assert "accuracy" in out
