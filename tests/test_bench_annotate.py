"""bench.py stalled-window annotation (VERDICT r5 weak #3): wall-time
outlier windows are flagged in the JSON so cross-round ci95 comparisons
can exclude tunnel stalls; raw windows stay untouched."""

import importlib.util
import os

import numpy as np


def _load_bench():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(root, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestAnnotateStalledWindows:
    def test_flags_single_stall(self):
        bench = _load_bench()
        # The VERDICT r5 shape: nine ~6.6 s windows, one 16.7 s stall.
        windows = [6.6, 6.5, 6.7, 6.6, 6.4, 16.7, 6.6, 6.5, 6.7, 6.6]
        stalled, ok = bench.annotate_stalled_windows(windows)
        assert stalled == [5]
        assert len(ok) == 9 and 5 not in ok

    def test_clean_run_flags_nothing(self):
        bench = _load_bench()
        stalled, ok = bench.annotate_stalled_windows(
            [6.6, 6.5, 6.7, 6.6, 6.55])
        assert stalled == []
        assert ok == [0, 1, 2, 3, 4]

    def test_uniformly_slow_run_is_not_stalled(self):
        """A run that is slow everywhere has no outliers to trim —
        flagging every window would silently empty the trimmed stats."""
        bench = _load_bench()
        stalled, ok = bench.annotate_stalled_windows([60.0])
        assert stalled == []
        assert ok == [0]

    def test_trimmed_ci_recovers(self):
        """The motivating number: one stall blows the naive ci95 by two
        orders of magnitude; the trimmed CI stays at the clean run's
        scale."""
        bench = _load_bench()
        rates = [2500, 2510, 2490, 2505, 613, 2495, 2508, 2502, 2498,
                 2506]
        walls = [6.6, 6.6, 6.6, 6.6, 16.7, 6.6, 6.6, 6.6, 6.6, 6.6]
        stalled, ok = bench.annotate_stalled_windows(walls)
        assert stalled == [4]
        full_ci = 1.96 * np.std(rates)
        trimmed_ci = 1.96 * np.std([rates[i] for i in ok])
        assert full_ci > 50 * trimmed_ci

    def test_custom_factor(self):
        bench = _load_bench()
        windows = [1.0, 1.0, 1.0, 1.4]
        assert bench.annotate_stalled_windows(windows)[0] == []
        assert bench.annotate_stalled_windows(windows,
                                              stall_factor=1.3) == (
            [3], [0, 1, 2])
