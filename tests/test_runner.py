"""Launcher/runner tests — RPC wire auth, host parsing, end-to-end
function-mode launches (the reference tests the Spark runner end-to-end on
a local cluster the same way, test/test_spark.py:52-70)."""

import os
import socket
import subprocess
import sys
import threading

import pytest

from horovod_tpu.runner import network, parse_hosts
from horovod_tpu.runner.host_hash import host_hash
from horovod_tpu.runner.launcher import expand_slots
from horovod_tpu.runner.network import (AuthenticationError, BasicClient,
                                        BasicService, Wire)
from horovod_tpu.runner.secret import (decode_key, encode_key,
                                       make_secret_key)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Wire / auth
# ---------------------------------------------------------------------------

class TestWire:
    def _pair(self):
        a, b = socket.socketpair()
        return a, b

    def test_roundtrip(self):
        key = make_secret_key()
        wire = Wire(key)
        a, b = self._pair()
        obj = {"hello": [1, 2, 3], "x": "y"}
        wire.write(a, obj)
        assert wire.read(b) == obj
        a.close(); b.close()

    def test_tampered_payload_rejected(self):
        key = make_secret_key()
        wire = Wire(key)
        a, b = self._pair()
        wire.write(a, ["payload"])
        raw = bytearray(b.recv(65536))
        raw[-1] ^= 0xFF  # flip a bit in the pickle
        c, d = self._pair()
        c.sendall(bytes(raw))
        with pytest.raises(AuthenticationError):
            wire.read(d)
        for s in (a, b, c, d):
            s.close()

    def test_wrong_key_rejected(self):
        a, b = self._pair()
        Wire(make_secret_key()).write(a, "secret message")
        with pytest.raises(AuthenticationError):
            Wire(make_secret_key()).read(b)
        a.close(); b.close()

    def test_key_codec(self):
        key = make_secret_key()
        assert decode_key(encode_key(key)) == key


# ---------------------------------------------------------------------------
# Service / client
# ---------------------------------------------------------------------------

class _EchoRequest:
    def __init__(self, value):
        self.value = value


class _EchoService(BasicService):
    def _handle(self, req, client_address):
        return ("echo", req.value)


class TestService:
    def test_request_response(self):
        key = make_secret_key()
        svc = _EchoService("echo", key)
        try:
            client = BasicClient([("127.0.0.1", svc.port)], key)
            assert client.ping()
            assert client.request(_EchoRequest(42)) == ("echo", 42)
        finally:
            svc.shutdown()

    def test_wrong_key_client_rejected(self):
        key = make_secret_key()
        svc = _EchoService("echo", key)
        try:
            bad = BasicClient([("127.0.0.1", svc.port)],
                              make_secret_key(), attempts=1, timeout=2.0)
            with pytest.raises(ConnectionError):
                bad.request(_EchoRequest(1))
        finally:
            svc.shutdown()

    def test_concurrent_clients(self):
        key = make_secret_key()
        svc = _EchoService("echo", key)
        results = []
        try:
            def call(i):
                c = BasicClient([("127.0.0.1", svc.port)], key)
                results.append(c.request(_EchoRequest(i)))
            threads = [threading.Thread(target=call, args=(i,))
                       for i in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert sorted(v for _, v in results) == list(range(8))
        finally:
            svc.shutdown()


# ---------------------------------------------------------------------------
# Host parsing / hashing
# ---------------------------------------------------------------------------

class TestHosts:
    def test_parse_hosts(self):
        assert parse_hosts("a:2,b:3") == [("a", 2), ("b", 3)]
        assert parse_hosts("localhost") == [("localhost", 1)]
        assert parse_hosts("a:1, b:2") == [("a", 1), ("b", 2)]

    def test_expand_slots_contiguous(self):
        ranks = expand_slots([("a", 2), ("b", 2)], 4)
        assert ranks == ["a", "a", "b", "b"]

    def test_expand_slots_insufficient(self):
        with pytest.raises(ValueError):
            expand_slots([("a", 1)], 2)

    def test_host_hash_stable(self):
        assert host_hash() == host_hash()
        assert host_hash("x") != host_hash("y")


# ---------------------------------------------------------------------------
# End-to-end function mode (horovod.spark.run parity)
# ---------------------------------------------------------------------------

_NO_JAX_ENV = {
    # keep workers light: they only read env vars
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
}


@pytest.mark.slow
class TestPortProbe:
    def test_probe_reports_busy_and_free(self):
        """remote_bootstrap --probe distinguishes a port with a live
        listener from a free one (ADVICE r1: remote rank-0 ports were
        drawn blind with no liveness check)."""
        import socket
        from horovod_tpu.runner.remote_bootstrap import probe_ports
        from horovod_tpu.runner.network import find_free_port
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.bind(("", 0))
        srv.listen(1)
        busy_port = srv.getsockname()[1]
        free_port = find_free_port()
        try:
            res = probe_ports([busy_port, free_port])
            assert busy_port in res["busy"]
            assert free_port in res["free"]
        finally:
            srv.close()


class TestRun:
    def test_run_collects_results_in_rank_order(self):
        from horovod_tpu.runner import run

        # Defined locally so cloudpickle ships it by value (as with a user
        # script's __main__ functions).
        def fn():
            import os
            return (int(os.environ["HOROVOD_TPU_PROCESS_ID"]),
                    int(os.environ["HOROVOD_TPU_NUM_PROCESSES"]))

        results = run(fn, np=2, extra_env=dict(_NO_JAX_ENV),
                      start_timeout=300, run_timeout=300)
        assert results == [(0, 2), (1, 2)]

    def test_run_propagates_worker_error(self):
        from horovod_tpu.runner import run

        def fn():
            import os
            if os.environ["HOROVOD_TPU_PROCESS_ID"] == "1":
                raise RuntimeError("boom on rank 1")
            return "ok"

        with pytest.raises(RuntimeError, match="rank 1"):
            run(fn, np=2, extra_env=dict(_NO_JAX_ENV),
                start_timeout=300, run_timeout=300)

    def test_run_initializes_jax_world(self):
        from horovod_tpu.runner import run

        def fn():
            import horovod_tpu as hvd
            hvd.init()
            return (hvd.rank(), hvd.size(), hvd.process_count())

        results = run(fn, np=2, extra_env=dict(_NO_JAX_ENV),
                      start_timeout=600, run_timeout=600)
        # 1 CPU device per process ⇒ rank == process id, size == 2.
        assert results == [(0, 2, 2), (1, 2, 2)]


@pytest.mark.slow
class TestCLI:
    def test_cli_tags_output_per_rank(self):
        env = dict(os.environ)
        env.update(_NO_JAX_ENV)
        proc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
             sys.executable, "-c",
             "import os; print('rank', os.environ['HOROVOD_TPU_PROCESS_ID'])"],
            capture_output=True, text=True, timeout=300, env=env, cwd=REPO)
        assert proc.returncode == 0, proc.stderr
        assert "[0]<stdout>:rank 0" in proc.stdout
        assert "[1]<stdout>:rank 1" in proc.stdout

    def test_cli_failfast_nonzero_exit(self):
        env = dict(os.environ)
        env.update(_NO_JAX_ENV)
        proc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
             sys.executable, "-c",
             "import os, sys, time\n"
             "sys.exit(3) if os.environ['HOROVOD_TPU_PROCESS_ID'] == '1' "
             "else time.sleep(60)"],
            capture_output=True, text=True, timeout=300, env=env, cwd=REPO)
        assert proc.returncode == 3
