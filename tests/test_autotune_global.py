"""Global-autotuner unit tests (docs/autotune.md).

Fast-tier coverage of the new subsystem: the typed knob space, the
deterministic successive-halving + GP search, the guarded online driver
(keep / revert / rollback against a stubbed measurement), the safe
apply plane's refusal contract, the per-slot spec_tokens AIMD
controller, the windowed step-time reader over fabricated history
files, and — the regression this PR must never reintroduce — the
wire-epoch arbiter serializing the adaptation ladder and the tuner on
ONE epoch list, exercised both directly and over the coordinator RPC.

The slow tier complements this file: tests/test_autotune_e2e.py runs
the cold-start search on the real bench workload and the multiprocess
fusion-flip test drives a mid-run tuner move through live engines.
"""

import json
import os
import threading

import pytest

from horovod_tpu.autotune import (ApplyPlane, AutoTuner, GaussianProcess,
                                  Knob, KnobRegistry, SpecTokensController,
                                  WindowedStepTime, default_registry,
                                  enumerate_configs, rungs_for,
                                  seed_gp_for_cycle_time,
                                  seed_points_from_legacy_log,
                                  successive_halving)
from horovod_tpu.observability import flight_recorder as _fr


def _autotune_events():
    return [e[2] for e in _fr.recorder()._snapshot() if e[1] == "autotune"]


# --------------------------------------------------------------------------
# Knob space
# --------------------------------------------------------------------------


class TestKnobs:
    def test_stock_registry_covers_every_subsystem(self):
        reg = default_registry()
        assert set(reg.names()) == {
            "dcn_wire_spec", "fusion_threshold_mb", "torch_bucket_mb",
            "pipeline_schedule", "num_microbatches", "spec_tokens",
            "cycle_time_ms"}
        # zb-h1 is in the schedule domain — the point the search should
        # find at scale.
        assert "zb-h1" in reg.get("pipeline_schedule").domain
        assert reg.get("pipeline_schedule").safety == "rebuild"
        assert reg.get("spec_tokens").safety == "slot"
        assert [k.name for k in reg.continuous()] == ["cycle_time_ms"]
        assert len(reg.discrete()) == 6
        defaults = reg.defaults()
        assert defaults["pipeline_schedule"] == "1f1b"
        assert defaults["fusion_threshold_mb"] == 64

    def test_include_filters(self):
        reg = default_registry(include=("fusion_threshold_mb",))
        assert reg.names() == ["fusion_threshold_mb"]
        assert "dcn_wire_spec" not in reg

    def test_validation(self):
        with pytest.raises(ValueError, match="kind"):
            Knob("k", "fuzzy", (1, 2), 1, "live", "engine_param")
        with pytest.raises(ValueError, match="safety"):
            Knob("k", "discrete", (1, 2), 1, "yolo", "engine_param")
        with pytest.raises(ValueError, match="apply_via"):
            Knob("k", "discrete", (1, 2), 1, "live", "side_door")
        with pytest.raises(ValueError, match="lo < hi"):
            Knob("k", "continuous", (5.0, 1.0), 2.0, "live",
                 "engine_param")
        with pytest.raises(ValueError, match="empty domain"):
            Knob("k", "discrete", (), 1, "live", "engine_param")
        with pytest.raises(ValueError, match="outside its domain"):
            Knob("k", "discrete", (1, 2), 3, "live", "engine_param")

    def test_clamp(self):
        cont = Knob("c", "continuous", (1.0, 10.0), 5.0, "live",
                    "engine_param")
        assert cont.clamp(0.0) == 1.0
        assert cont.clamp(99.0) == 10.0
        assert cont.clamp(3.5) == 3.5
        disc = Knob("d", "discrete", (8, 16), 8, "live", "engine_param")
        assert disc.clamp(16) == 16
        with pytest.raises(ValueError, match="domain"):
            disc.clamp(12)

    def test_duplicate_registration_rejected(self):
        reg = KnobRegistry()
        k = Knob("d", "discrete", (8, 16), 8, "live", "engine_param")
        reg.register(k)
        with pytest.raises(ValueError, match="already registered"):
            reg.register(k)


# --------------------------------------------------------------------------
# Search: successive halving + config enumeration
# --------------------------------------------------------------------------


class TestSearch:
    def test_enumerate_is_deterministic_domain_order(self):
        a = Knob("a", "discrete", (1, 2), 1, "live", "engine_param")
        b = Knob("b", "discrete", ("x", "y"), "x", "live",
                 "engine_param")
        cfgs = enumerate_configs([a, b])
        assert cfgs == [{"a": 1, "b": "x"}, {"a": 1, "b": "y"},
                        {"a": 2, "b": "x"}, {"a": 2, "b": "y"}]

    def test_enumerate_constraint(self):
        a = Knob("a", "discrete", (1, 2, 3), 1, "live", "engine_param")
        cfgs = enumerate_configs([a], constraint=lambda c: c["a"] != 2)
        assert [c["a"] for c in cfgs] == [1, 3]

    def test_halving_rung_structure(self):
        cands = [{"x": i} for i in range(16)]
        best, trials = successive_halving(
            cands, lambda cfg, budget: float(cfg["x"]), eta=2,
            base_budget=2)
        assert best == {"x": 15}
        per_rung = {}
        budgets = {}
        for t in trials:
            per_rung[t.rung] = per_rung.get(t.rung, 0) + 1
            budgets[t.rung] = t.budget
        assert per_rung == {0: 16, 1: 8, 2: 4, 3: 2, 4: 1}
        assert budgets == {0: 2, 1: 4, 2: 8, 3: 16, 4: 32}
        assert rungs_for(16) == 5

    def test_halving_tie_breaks_keep_candidate_order(self):
        cands = [{"x": i} for i in range(4)]
        best, _ = successive_halving(cands, lambda cfg, budget: 1.0)
        assert best == {"x": 0}

    def test_halving_rejects_bad_inputs(self):
        with pytest.raises(ValueError, match="at least one"):
            successive_halving([], lambda c, b: 0.0)
        with pytest.raises(ValueError, match="eta"):
            successive_halving([{"x": 1}], lambda c, b: 0.0, eta=1)


# --------------------------------------------------------------------------
# GP + legacy-log seeding
# --------------------------------------------------------------------------


class TestGaussianProcess:
    def test_interpolates_observations(self):
        gp = GaussianProcess([(0.0, 100.0)])
        gp.observe([50.0], -1.0)
        mean, _ = gp.predict([50.0])
        assert mean == pytest.approx(-1.0, abs=1e-3)

    def test_suggest_is_deterministic(self):
        gp = GaussianProcess([(1.0, 100.0)])
        gp.observe([10.0], -2.0)
        gp.observe([90.0], -1.0)
        a, b = gp.suggest(), gp.suggest()
        assert a == b
        assert 1.0 <= a[0] <= 100.0

    def test_empty_gp_has_infinite_ei(self):
        gp = GaussianProcess([(0.0, 1.0)])
        assert gp.expected_improvement([0.5]) == float("inf")

    def test_legacy_log_parses_and_seeds(self, tmp_path):
        log = tmp_path / "autotune.csv"
        log.write_text(
            "fusion_mb,cycle_ms,hier_allreduce,hier_allgather,score\n"
            "64,10.0,1,0,-0.5\n"
            "garbage,row\n"
            "32,20.0,0,1,-0.8\n")
        pts = seed_points_from_legacy_log(str(log))
        assert len(pts) == 2
        assert pts[0][0]["cycle_time_ms"] == 10.0
        assert pts[0][1] == -0.5
        gp = GaussianProcess([(1.0, 100.0)])
        assert seed_gp_for_cycle_time(gp, str(log)) == 2
        assert len(gp) == 2

    def test_legacy_log_missing_or_foreign_is_cold_start(self, tmp_path):
        assert seed_points_from_legacy_log(
            str(tmp_path / "nope.csv")) == []
        bad = tmp_path / "bad.csv"
        bad.write_text("time,loss\n1,2\n")
        assert seed_points_from_legacy_log(str(bad)) == []


# --------------------------------------------------------------------------
# Apply plane: the safety contract
# --------------------------------------------------------------------------


class TestApplyPlane:
    def test_refuses_serving_slot_and_rebuild_even_when_injected(self):
        reg = default_registry()
        plane = ApplyPlane(rebuild=lambda cfg: None,
                           set_engine_param=lambda n, v: None)
        assert not plane.supports(reg.get("pipeline_schedule"))
        assert not plane.supports(reg.get("spec_tokens"))
        with pytest.raises(ValueError, match="rebuild"):
            plane.apply(reg.get("pipeline_schedule"), "zb-h1")
        with pytest.raises(ValueError, match="serving slot"):
            plane.apply(reg.get("spec_tokens"), 2)

    def test_missing_hook_is_unsupported_not_guessed(self):
        reg = default_registry()
        plane = ApplyPlane()
        assert not plane.supports(reg.get("dcn_wire_spec"))
        with pytest.raises(ValueError, match="no mechanism injected"):
            plane.apply(reg.get("dcn_wire_spec"), "bf16")

    def test_routes_by_apply_via(self):
        reg = default_registry()
        calls = []
        plane = ApplyPlane(
            set_wire=lambda v: calls.append(("wire", v)),
            set_fusion=lambda v: calls.append(("fusion", v)),
            set_bucket_mb=lambda v: calls.append(("bucket", v)),
            set_engine_param=lambda n, v: calls.append(("engine", n, v)))
        plane.apply(reg.get("dcn_wire_spec"), "bf16")
        plane.apply(reg.get("fusion_threshold_mb"), 32)
        plane.apply(reg.get("torch_bucket_mb"), 16)
        plane.apply(reg.get("cycle_time_ms"), 5.0)
        assert calls == [("wire", "bf16"), ("fusion", 32),
                         ("bucket", 16), ("engine", "cycle_time_ms", 5.0)]


# --------------------------------------------------------------------------
# The guarded online driver
# --------------------------------------------------------------------------


def _tuner(measurements, **kw):
    """AutoTuner over the fusion knob with a scripted measurement and a
    recording fusion hook; returns (tuner, applied_values)."""
    applied = []
    it = iter(measurements)
    ticks = iter(range(10_000))
    kw.setdefault("registry", default_registry(
        include=("fusion_threshold_mb",)))
    t = AutoTuner(plane=ApplyPlane(set_fusion=applied.append),
                  measure=lambda budget: next(it),
                  clock=lambda: float(next(ticks)), **kw)
    return t, applied


class TestAutoTunerMoves:
    def test_clear_win_is_kept(self):
        t, applied = _tuner([1.0, 0.80])
        move = t.try_move("fusion_threshold_mb", 32)
        assert move.outcome == "kept"
        assert t.current["fusion_threshold_mb"] == 32
        assert applied == [32]
        events = [p for p in _autotune_events() if p[1] ==
                  "fusion_threshold_mb"]
        assert [p[0] for p in events[-2:]] == ["move", "keep"]

    def test_no_gain_is_reverted_through_the_same_mechanism(self):
        t, applied = _tuner([1.0, 0.999])
        move = t.try_move("fusion_threshold_mb", 32)
        assert move.outcome == "reverted" and move.detail == "no_gain"
        assert t.current["fusion_threshold_mb"] == 64
        assert applied == [32, 64]

    def test_regression_rolls_back(self):
        t, applied = _tuner([1.0, 2.0])
        move = t.try_move("fusion_threshold_mb", 32)
        assert move.outcome == "rolled_back"
        # Restored the pre-move value through the same injected hook.
        assert t.current["fusion_threshold_mb"] == 64
        assert applied == [32, 64]
        events = [p for p in _autotune_events()
                  if p[1] == "fusion_threshold_mb" and p[0] == "rollback"]
        assert events, "rollback must land in the flight recorder"

    def test_blind_move_is_not_kept(self):
        # No measurement at all (history plane absent): never keep.
        t, applied = _tuner([None, None])
        move = t.try_move("fusion_threshold_mb", 32)
        assert move.outcome == "reverted"
        assert t.current["fusion_threshold_mb"] == 64

    def test_run_sweeps_domain_and_skips_current(self):
        # Constant step time: every candidate reverts, but the sweep
        # still visits every non-current domain value exactly once.
        t, applied = _tuner([1.0] * 100)
        moves = t.run()
        assert [m.new for m in moves] == [16, 32, 128]
        assert all(m.outcome == "reverted" for m in moves)
        assert applied == [16, 64, 32, 64, 128, 64]
        assert _autotune_events()[-1][0] == "pass_done"

    def test_run_skips_unsupported_knobs(self):
        it = iter([1.0] * 100)
        t = AutoTuner(plane=ApplyPlane(),
                      measure=lambda b: next(it))
        assert t.run() == []

    def test_run_continuous_knob_takes_gp_suggestion(self):
        applied = []
        it = iter([1.0] * 10)
        t = AutoTuner(registry=default_registry(
                          include=("cycle_time_ms",)),
                      plane=ApplyPlane(set_engine_param=lambda n, v:
                                       applied.append((n, v))),
                      measure=lambda b: next(it))
        moves = t.run()
        assert len(moves) == 1
        assert applied[0][0] == "cycle_time_ms"
        assert 1.0 <= applied[0][1] <= 100.0
        assert len(t._gp) == 1  # the measurement fed the posterior

    def test_seed_log_warm_starts_continuous_knob(self, tmp_path):
        log = tmp_path / "legacy.csv"
        log.write_text(
            "fusion_mb,cycle_ms,hier_allreduce,hier_allgather,score\n"
            "64,10.0,1,0,-0.5\n64,40.0,1,0,-0.9\n")
        t = AutoTuner(registry=default_registry(
                          include=("cycle_time_ms",)),
                      seed_log=str(log))
        assert len(t._gp) == 2


class TestTuneRebuild:
    def test_converges_to_best_config_under_constraint(self):
        t = AutoTuner(registry=default_registry(
            include=("pipeline_schedule", "num_microbatches")))

        def score(cfg, budget):
            base = 1.0 if cfg["pipeline_schedule"] == "zb-h1" else 0.0
            return base + cfg["num_microbatches"] / 100.0

        best, trials = t.tune_rebuild(
            score, constraint=lambda c: c["num_microbatches"] >= 8)
        assert best == {"pipeline_schedule": "zb-h1",
                        "num_microbatches": 32}
        assert t.current["pipeline_schedule"] == "zb-h1"
        assert t.current["num_microbatches"] == 32
        # 12 constrained candidates -> 12 + 6 + 3 + 1 scored trials.
        assert len(trials) == 22
        assert trials[-1].budget > trials[0].budget
        events = _autotune_events()
        assert events[-1][0] == "converged"
        assert any(p[0] == "trial" for p in events)


# --------------------------------------------------------------------------
# Windowed step time over the history plane
# --------------------------------------------------------------------------


def _write_history(directory, rank, values):
    path = os.path.join(directory, f"history-rank{rank}.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"history": 1, "label": f"rank{rank}",
                            "rank": rank, "world": 2}) + "\n")
        for i, v in enumerate(values):
            f.write(json.dumps({
                "t_us": (i + 1) * 1_000_000,
                "s": {'hvdtpu_step_seconds{framework="jax"}|mean': v,
                      'hvdtpu_allreduce_seconds|mean': 99.0}}) + "\n")
    return path


class TestWindowedStepTime:
    def test_means_last_window_across_ranks(self, tmp_path):
        _write_history(str(tmp_path), 0, [9.0, 1.0, 2.0])
        _write_history(str(tmp_path), 1, [9.0, 3.0, 4.0])
        src = WindowedStepTime([str(tmp_path)], window=2)
        # Last 2 samples of each rank; the allreduce series is ignored.
        assert src.read() == pytest.approx((1 + 2 + 3 + 4) / 4)

    def test_missing_history_reads_none(self, tmp_path):
        assert WindowedStepTime([str(tmp_path)]).read() is None

    def test_foreign_series_only_reads_none(self, tmp_path):
        path = os.path.join(str(tmp_path), "history-rank0.jsonl")
        with open(path, "w") as f:
            f.write(json.dumps({"history": 1, "rank": 0}) + "\n")
            f.write(json.dumps({
                "t_us": 1_000_000,
                "s": {"hvdtpu_allreduce_seconds|mean": 1.0}}) + "\n")
        assert WindowedStepTime([path]).read() is None


# --------------------------------------------------------------------------
# Per-slot spec_tokens AIMD controller
# --------------------------------------------------------------------------


class TestSpecTokensController:
    def test_optimistic_start_then_multiplicative_backoff(self):
        c = SpecTokensController(4)
        assert c.slot_k(7) == 4  # optimistic start at the cap
        ks = [c.observe(7, proposed=4, accepted=0) for _ in range(4)]
        # EWMA decays 1.0 -> .5 -> .25 -> .125 -> .0625: halves twice.
        assert ks == [4, 4, 2, 1]
        events = [p for p in _autotune_events() if p[0] == "spec_backoff"]
        assert len(events) >= 2

    def test_additive_raise_after_recovery(self):
        c = SpecTokensController(4)
        for _ in range(4):
            c.observe(0, 4, 0)
        assert c.slot_k(0) == 1
        ks = [c.observe(0, 1, 1) for _ in range(6)]
        # AIMD: +1 per good step once the EWMA clears the raise bar.
        assert ks[-1] == 4
        assert sorted(set(ks)) == list(range(ks[0], 5))

    def test_plain_step_probe(self):
        c = SpecTokensController(8, probe_every=16)
        for _ in range(8):
            c.observe(3, 8, 0)
        assert c.slot_k(3) == 1
        for i in range(15):
            assert c.note_plain_step(3) == 1
        assert c.note_plain_step(3) == 2  # 16th plain step probes
        st = c._slots[3]
        assert st.ewma >= 0.5 and st.plain_steps == 0
        assert any(p[0] == "spec_probe" for p in _autotune_events())

    def test_note_plain_step_noop_above_k1(self):
        c = SpecTokensController(4, probe_every=2)
        for _ in range(5):
            assert c.note_plain_step(0) == 4

    def test_width_is_batch_max(self):
        c = SpecTokensController(6)
        for _ in range(8):
            c.observe(0, 6, 0)
        assert c.slot_k(0) == 1 and c.slot_k(1) == 6
        assert c.width([0, 1]) == 6
        assert c.width([0]) == 1
        assert c.width([]) == 6
        c.reset(1)
        assert 1 not in c._slots

    def test_k_max_validation(self):
        with pytest.raises(ValueError, match="k_max"):
            SpecTokensController(0)


# --------------------------------------------------------------------------
# Satellite: the wire-epoch arbiter serializes ladder + tuner
# --------------------------------------------------------------------------


class TestWireEpochArbiter:
    def _arb(self):
        from horovod_tpu.ops.control_plane import WireEpochArbiter
        seq = {"v": 0}
        arb = WireEpochArbiter(threading.Lock(), lambda: seq["v"])
        return arb, seq

    def test_noop_rejected(self):
        arb, _ = self._arb()
        assert arb.propose_wire("tuner", "") == {
            "accepted": False, "from_seq": 0, "reason": "noop"}
        arb.propose_wire("tuner", "bf16")
        assert arb.propose_wire("ladder", "bf16")["reason"] == "noop"

    def test_tuner_rejected_against_pending_ladder(self):
        arb, _ = self._arb()
        assert arb.propose_wire("ladder", "bf16")["accepted"]
        res = arb.propose_wire("tuner", "int8x256")
        assert res == {"accepted": False, "from_seq": 0,
                       "reason": "conflict_with_ladder"}
        assert arb.wire_epochs == [(0, "bf16")]

    def test_ladder_replaces_pending_tuner(self):
        arb, _ = self._arb()
        assert arb.propose_wire("tuner", "bf16")["accepted"]
        res = arb.propose_wire("ladder", "int8x256")
        assert res == {"accepted": True, "from_seq": 0,
                       "reason": "replaced_tuner"}
        # The tuner's unplanned entry is GONE, not shadowed: ranks must
        # never see two values stamped at one seq.
        assert arb.wire_epochs == [(0, "int8x256")]
        assert arb._wire_src == ["ladder"]

    def test_same_source_restamps(self):
        arb, _ = self._arb()
        arb.propose_wire("ladder", "bf16")
        res = arb.propose_wire("ladder", "int8x256")
        assert res["accepted"] and res["reason"] == "ok"
        assert arb.wire_epochs == [(0, "bf16"), (0, "int8x256")]

    def test_planned_seq_frees_the_next_epoch(self):
        arb, seq = self._arb()
        arb.propose_wire("ladder", "bf16")
        seq["v"] = 3  # groups got planned; the pending seq moved on
        res = arb.propose_wire("tuner", "int8x256")
        assert res == {"accepted": True, "from_seq": 3, "reason": "ok"}
        assert arb.wire_epochs == [(0, "bf16"), (3, "int8x256")]

    def test_fusion_list_is_independent(self):
        arb, _ = self._arb()
        assert arb.propose_wire("ladder", "bf16")["accepted"]
        res = arb.propose_fusion("tuner", 1 << 20)
        assert res["accepted"] and res["reason"] == "ok"
        assert arb.fusion_epochs == [(0, 1 << 20)]


class TestCoordinatorTunerMoves:
    """Satellite regression: both planes live on one coordinator — the
    ladder and the tuner must serialize through the arbiter, and every
    rank's fetched params must carry ONE consistent epoch list."""

    @pytest.fixture
    def svc(self):
        from horovod_tpu.ops.control_plane import CoordinatorService
        from horovod_tpu.runner.secret import make_secret_key
        s = CoordinatorService(nproc=2, key=make_secret_key(),
                               fusion_threshold=1024, native=False)
        yield s
        s.shutdown()

    def _clients(self, svc):
        from horovod_tpu.ops.control_plane import CoordinatorClient
        return (CoordinatorClient([("127.0.0.1", svc.port)], svc.key, 0),
                CoordinatorClient([("127.0.0.1", svc.port)], svc.key, 1))

    def _plan_one(self, svc, c0, c1, name):
        req = {"name": name, "op": 0, "dtype": "float32", "shape": (4,),
               "root_rank": -1}
        c0.announce([req])
        c1.announce([req])
        assert c0.fetch(wait_s=2.0).groups
        return c1.fetch(wait_s=2.0)

    def test_rpc_moves_arbitrate_against_the_ladder(self, svc):
        c0, c1 = self._clients(svc)
        # Tuner stamps a fusion epoch: fractional MB lands in bytes.
        res = c0.tuner_move("fusion_threshold_mb", 0.0005)
        assert res["accepted"] and res["from_seq"] == 0
        assert svc.fusion_threshold == int(0.0005 * (1 << 20))
        # Tuner stamps a wire epoch, then the ladder reacts in the SAME
        # planning gap: health outranks optimization.
        assert c0.tuner_move("dcn_wire_spec", "bf16")["accepted"]
        lad = svc._publish_wire_epoch("int8x256")
        assert lad["reason"] == "replaced_tuner"
        # And the tuner cannot take the seq back...
        res = c0.tuner_move("dcn_wire_spec", "fp8x256")
        assert res == {"accepted": False, "from_seq": 0,
                       "reason": "conflict_with_ladder"}
        # ...nor restamp the ladder's value as its own (noop).
        assert c0.tuner_move(
            "dcn_wire_spec", "int8x256")["reason"] == "noop"
        assert c0.tuner_move("warp_speed", 9)["reason"] == "unknown_knob"
        # Planning a group moves the pending seq; the tuner is free
        # again at the NEXT epoch boundary.
        resp = self._plan_one(svc, c0, c1, "t0")
        res = c0.tuner_move("dcn_wire_spec", "bf16")
        assert res["accepted"] and res["from_seq"] == 1
        # Every rank's fetch now ships the one arbitrated history.
        resp0 = self._plan_one(svc, c0, c1, "t1")
        assert resp0.params["wire_epochs"] == [[0, "int8x256"],
                                               [1, "bf16"]]
        assert resp0.params["fusion_epochs"] == [[0, 524]]
        assert resp0.params["fusion_threshold"] == 524

    def test_cycle_time_moves_apply_live(self, svc):
        c0, c1 = self._clients(svc)
        res = c0.tuner_move("cycle_time_ms", 7.5)
        assert res == {"accepted": True, "from_seq": -1,
                       "reason": "live"}
        resp = self._plan_one(svc, c0, c1, "u0")
        assert resp.params["cycle_time_ms"] == 7.5
