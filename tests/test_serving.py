"""Serving tier (docs/serving.md): block-sliced KV cache, incremental
decode parity against the full-context forward, the resharded
checkpoint→inference-mesh loader, continuous-batching scheduler edges,
and the HTTP front end. The train→save→serve acceptance e2e and the
SIGTERM drain live in test_serving_e2e.py (slow tier)."""

import http.client
import json
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.checkpoint import CheckpointEngine
from horovod_tpu.models import transformer as tfm
from horovod_tpu.parallel.mesh import create_mesh
from horovod_tpu.serving import (BlockAllocator, DrainingError,
                                 InferenceEngine, PrefixCache,
                                 QueueFullError, ServingConfig,
                                 blocks_needed, config_from_manifest,
                                 load_params, prefix_hashes,
                                 serving_config, transformer_extra)
from horovod_tpu.serving.kv_cache import SCRATCH_BLOCK


def _cfg(**over):
    kw = dict(vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
              max_seq=64, dtype=jnp.float32, remat=False)
    kw.update(over)
    return tfm.TransformerConfig(**kw)


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    params = tfm.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture(scope="module")
def mesh1():
    return create_mesh(devices=jax.devices()[:1], tp=1)


def _engine(params, cfg, mesh, **over):
    kw = dict(block_size=4, kv_blocks=40, max_batch_slots=4,
              max_queue=8, max_new_tokens=8, min_prefill_bucket=8)
    draft_params = over.pop("draft_params", None)
    draft_cfg = over.pop("draft_cfg", None)
    kw.update(over)
    return InferenceEngine(params, cfg, mesh, ServingConfig(**kw),
                           draft_params=draft_params,
                           draft_cfg=draft_cfg)


class TestBlockAllocator:
    def test_scratch_is_never_handed_out(self):
        a = BlockAllocator(8)
        got = a.alloc(7)
        assert got is not None and SCRATCH_BLOCK not in got
        assert a.alloc(1) is None          # pool exactly exhausted

    def test_all_or_nothing(self):
        a = BlockAllocator(5)
        assert a.alloc(5) is None          # only 4 allocatable
        assert a.free == 4                 # failed alloc took nothing
        got = a.alloc(3)
        assert len(got) == 3 and a.free == 1

    def test_release_recycles(self):
        a = BlockAllocator(4)
        got = a.alloc(3)
        a.release(got)
        assert a.free == 3
        assert len(a.alloc(3)) == 3

    def test_double_free_and_scratch_release_raise(self):
        a = BlockAllocator(4)
        got = a.alloc(2)
        a.release(got)
        with pytest.raises(ValueError, match="double free"):
            a.release([got[0]])
        with pytest.raises(ValueError, match="scratch"):
            a.release([SCRATCH_BLOCK])

    def test_blocks_needed(self):
        # prompt + max_new - 1 cached positions (the last generated
        # token is never fed back), ceil-divided by block size
        assert blocks_needed(4, 1, 4) == 1
        assert blocks_needed(4, 2, 4) == 2
        assert blocks_needed(5, 8, 4) == 3
        with pytest.raises(ValueError):
            blocks_needed(0, 4, 4)

    def test_refcount_shared_block_survives_first_release(self):
        """The prefix-cache contract: a block with two holders returns
        to the free list only when the LAST one lets go."""
        a = BlockAllocator(4)
        got = a.alloc(2)
        a.incref(got[0])
        assert a.refcount(got[0]) == 2
        a.release(got)                 # first holder gone
        assert a.free == 2             # got[1] freed, got[0] still held
        assert a.refcount(got[0]) == 1
        assert a.decref(got[0]) is True
        assert a.free == 3

    def test_incref_free_or_scratch_block_raises(self):
        a = BlockAllocator(4)
        with pytest.raises(ValueError, match="free"):
            a.incref(1)                # never allocated
        with pytest.raises(ValueError, match="scratch"):
            a.incref(SCRATCH_BLOCK)
        got = a.alloc(1)
        a.release(got)
        with pytest.raises(ValueError, match="free"):
            a.incref(got[0])           # already returned to the pool


class TestPrefixHashes:
    def test_chained_and_deterministic(self):
        toks = list(range(20))
        h1 = prefix_hashes(toks, 4)
        h2 = prefix_hashes(list(toks), 4)
        assert h1 == h2                       # process-stable (hashlib)
        assert len(h1) == 4                   # last token never hashed
        # a prefix, not a window: same block content after a different
        # prefix hashes differently
        other = prefix_hashes([99] + toks[1:], 4)
        assert other[0] != h1[0] and other[1] != h1[1]
        # agreeing prompts share exactly their common full blocks
        div = prefix_hashes(toks[:8] + [99] * 12, 4)
        assert div[:2] == h1[:2] and div[2] != h1[2]

    def test_short_prompt_has_no_shareable_blocks(self):
        assert prefix_hashes([1, 2, 3, 4], 4) == []   # needs len > bs
        assert len(prefix_hashes([1, 2, 3, 4, 5], 4)) == 1


class TestPrefixCacheUnit:
    def test_lookup_increfs_and_longest_prefix(self):
        a = BlockAllocator(8)
        pc = PrefixCache(a)
        blocks = a.alloc(3)
        h = prefix_hashes(list(range(13)), 4)
        for hj, b in zip(h, blocks):
            pc.insert(hj, b)
        assert a.refcount(blocks[0]) == 2      # caller + cache
        got = pc.lookup(h[:2] + [b"nope"])
        assert got == blocks[:2]
        assert a.refcount(blocks[0]) == 3      # + the lookup's hold
        assert a.refcount(blocks[2]) == 2      # not matched past miss

    def test_insert_first_writer_wins(self):
        a = BlockAllocator(8)
        pc = PrefixCache(a)
        b1, b2 = a.alloc(2)
        h = prefix_hashes(list(range(5)), 4)[0]
        assert pc.insert(h, b1) is True
        assert pc.insert(h, b2) is False       # no double-index
        assert pc.lookup([h]) == [b1]

    def test_evict_one_drops_lru_and_frees_idle_blocks(self):
        a = BlockAllocator(8)
        pc = PrefixCache(a)
        blocks = a.alloc(2)
        h = prefix_hashes(list(range(9)), 4)
        pc.insert(h[0], blocks[0])
        pc.insert(h[1], blocks[1])
        a.release(blocks)                      # sequences finished
        assert a.free == 5                     # cache still holds both
        pc.lookup([h[0]])                      # freshen h[0]; +1 hold
        assert pc.evict_one() is True          # drops h[1] (LRU)
        assert a.free == 6
        assert pc.lookup([h[1]]) == []
        assert len(pc) == 1

    def test_max_entries_bounds_the_index(self):
        a = BlockAllocator(16)
        pc = PrefixCache(a, max_entries=2)
        blocks = a.alloc(3)
        h = prefix_hashes(list(range(13)), 4)
        for hj, b in zip(h, blocks):
            pc.insert(hj, b)
        assert len(pc) == 2
        assert pc.lookup([h[0]]) == []         # the LRU entry fell out


class TestDecodeParity:
    """apply_decode through the block-sliced cache must reproduce the
    full-context apply at EVERY position (rtol — fp reassociation
    only)."""

    def test_prefill_matches_full_apply(self, model):
        cfg, params = model
        tok = jax.random.randint(jax.random.PRNGKey(1), (1, 12), 0, 64)
        ref = tfm.apply(params, tok, cfg)
        cache = tfm.init_cache(cfg, n_blocks=10, block_size=4)
        tables = jnp.arange(1, 7, dtype=jnp.int32)[None, :]
        logits, _ = tfm.apply_decode(params, tok, jnp.zeros((1,), jnp.int32),
                                     tables, cache, cfg)
        np.testing.assert_allclose(np.asarray(logits), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_incremental_matches_full_apply(self, model):
        """Token-by-token decode crosses block boundaries (block 4,
        sequence 11) and must match the monolithic forward at every
        position."""
        cfg, params = model
        tok = jax.random.randint(jax.random.PRNGKey(2), (1, 11), 0, 64)
        ref = np.asarray(tfm.apply(params, tok, cfg))
        cache = tfm.init_cache(cfg, n_blocks=10, block_size=4)
        tables = jnp.arange(1, 7, dtype=jnp.int32)[None, :]
        for i in range(11):
            lg, cache = tfm.apply_decode(
                params, tok[:, i:i + 1], jnp.array([i], jnp.int32),
                tables, cache, cfg)
            np.testing.assert_allclose(np.asarray(lg[:, 0]), ref[:, i],
                                       rtol=1e-4, atol=1e-5)

    def test_prefill_then_decode(self, model):
        cfg, params = model
        tok = jax.random.randint(jax.random.PRNGKey(3), (1, 10), 0, 64)
        ref = np.asarray(tfm.apply(params, tok, cfg))
        cache = tfm.init_cache(cfg, n_blocks=10, block_size=4)
        tables = jnp.arange(1, 7, dtype=jnp.int32)[None, :]
        lg, cache = tfm.apply_decode(params, tok[:, :6],
                                     jnp.zeros((1,), jnp.int32),
                                     tables, cache, cfg)
        np.testing.assert_allclose(np.asarray(lg), ref[:, :6],
                                   rtol=1e-5, atol=1e-5)
        for i in range(6, 10):
            lg, cache = tfm.apply_decode(
                params, tok[:, i:i + 1], jnp.array([i], jnp.int32),
                tables, cache, cfg)
            np.testing.assert_allclose(np.asarray(lg[:, 0]), ref[:, i],
                                       rtol=1e-4, atol=1e-5)

    def test_padded_prefill_ignores_padding(self, model):
        """A bucket-padded prompt produces the same logits at the real
        positions — padding writes land in scratch/future blocks behind
        the causal mask."""
        cfg, params = model
        tok = jax.random.randint(jax.random.PRNGKey(4), (1, 5), 0, 64)
        ref = np.asarray(tfm.apply(params, tok, cfg))
        cache = tfm.init_cache(cfg, n_blocks=10, block_size=4)
        tables = jnp.arange(1, 7, dtype=jnp.int32)[None, :]
        padded = jnp.concatenate(
            [tok, jnp.zeros((1, 3), tok.dtype)], axis=1)
        lg, _ = tfm.apply_decode(params, padded,
                                 jnp.zeros((1,), jnp.int32),
                                 tables, cache, cfg)
        np.testing.assert_allclose(np.asarray(lg[:, :5]), ref,
                                   rtol=1e-5, atol=1e-5)

    def test_rejects_sp_and_moe(self, model):
        cfg, params = model
        cache = tfm.init_cache(cfg, 4, 4)
        tok = jnp.zeros((1, 1), jnp.int32)
        with pytest.raises(ValueError, match="sequence parallelism"):
            tfm.apply_decode(params, tok, jnp.zeros((1,), jnp.int32),
                             jnp.ones((1, 2), jnp.int32), cache,
                             _cfg(sp_axis="sp"))


class TestDecodeParityTP:
    def test_tp2_matches_single_device(self, model):
        """Tensor-parallel decode (heads over 'tp', shard_map) equals
        the single-device incremental path."""
        cfg, params = model
        cfg_tp = _cfg(tp_axis="tp")
        mesh = create_mesh(devices=jax.devices()[:2], tp=2)
        specs = tfm.param_specs(cfg_tp)
        cspecs = tfm.cache_specs(cfg_tp)

        def put(tree, sp):
            return jax.tree_util.tree_map(
                lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
                tree, sp, is_leaf=lambda x: isinstance(x, P))

        sp_params = put(params, specs)
        sp_cache = put(tfm.init_cache(cfg_tp, 10, 4), cspecs)
        fn = jax.jit(jax.shard_map(
            lambda p, c, t, s, bt: tfm.apply_decode(p, t, s, bt, c,
                                                    cfg_tp),
            mesh=mesh, in_specs=(specs, cspecs, P(), P(), P()),
            out_specs=(P(), cspecs), check_vma=False))

        tok = jax.random.randint(jax.random.PRNGKey(5), (2, 7), 0, 64)
        tables = jnp.array([[1, 2, 3], [4, 5, 6]], jnp.int32)
        lg, sp_cache = fn(sp_params, sp_cache, tok,
                          jnp.zeros((2,), jnp.int32), tables)
        ref = np.asarray(tfm.apply(params, tok, cfg))
        np.testing.assert_allclose(np.asarray(lg), ref, rtol=1e-4,
                                   atol=1e-5)
        # one decode step on both sequences
        nxt = jnp.array([[9], [17]], jnp.int32)
        lg2, _ = fn(sp_params, sp_cache, nxt,
                    jnp.full((2,), 7, jnp.int32), tables)
        full = np.asarray(tfm.apply(
            params, jnp.concatenate([tok, nxt], axis=1), cfg))
        np.testing.assert_allclose(np.asarray(lg2[:, 0]), full[:, 7],
                                   rtol=1e-4, atol=1e-5)


class TestQuantizedKV:
    """Quantized-KV parity matrices (docs/serving.md#speed-levers):
    prefill is EXACT vs the fp32 pool (this chunk attends at full
    precision; a from-empty prefill has no past to dequantize), and
    incremental decode stays within wire tolerance — at tp=1 and under
    tp=2 shard_map."""

    # Per-format wire tolerance: e4m3's 3-bit mantissa (~6% per value)
    # is an order coarser than int8's 1/127 step, and two layers of
    # attention compound it.
    TOL = {"int8": dict(rtol=5e-2, atol=5e-2),
           "fp8": dict(rtol=1e-1, atol=5e-1)}

    @pytest.mark.parametrize("kv", ["int8", "fp8"])
    def test_prefill_exact_vs_fp32_pool(self, model, kv):
        cfg, params = model
        tok = jax.random.randint(jax.random.PRNGKey(11), (1, 12), 0, 64)
        cache_f = tfm.init_cache(cfg, 10, 4)
        cache_q = tfm.init_cache(cfg, 10, 4, kv)
        tables = jnp.arange(1, 7, dtype=jnp.int32)[None, :]
        ref, _ = tfm.apply_decode(params, tok, jnp.zeros((1,), jnp.int32),
                                  tables, cache_f, cfg)
        lg, _ = tfm.apply_decode(params, tok, jnp.zeros((1,), jnp.int32),
                                 tables, cache_q, cfg, kv_quant=kv,
                                 exact_chunk=True)
        np.testing.assert_array_equal(np.asarray(lg), np.asarray(ref))

    @pytest.mark.parametrize("kv", ["int8", "fp8"])
    def test_incremental_decode_within_wire_tolerance(self, model, kv):
        """Token-by-token decode re-reads PAST tokens quantized; the
        logits track the fp32 pool within the wire format's error."""
        cfg, params = model
        tok = jax.random.randint(jax.random.PRNGKey(12), (1, 11), 0, 64)
        ref = np.asarray(tfm.apply(params, tok, cfg))
        cache = tfm.init_cache(cfg, 10, 4, kv)
        tables = jnp.arange(1, 7, dtype=jnp.int32)[None, :]
        for i in range(11):
            lg, cache = tfm.apply_decode(
                params, tok[:, i:i + 1], jnp.array([i], jnp.int32),
                tables, cache, cfg, kv_quant=kv)
            np.testing.assert_allclose(np.asarray(lg[:, 0]), ref[:, i],
                                       **self.TOL[kv])

    def test_tp2_shard_map_parity(self, model):
        """The tp=2 leg of the matrix: head-sharded quantized decode —
        scales travel with their heads, so the quantization blocks are
        IDENTICAL to tp=1 and the only extra error is the psum's fp
        reassociation."""
        cfg, params = model
        cfg_tp = _cfg(tp_axis="tp")
        mesh = create_mesh(devices=jax.devices()[:2], tp=2)
        specs = tfm.param_specs(cfg_tp)
        cspecs = tfm.cache_specs(cfg_tp, "int8")

        def put(tree, sp):
            return jax.tree_util.tree_map(
                lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
                tree, sp, is_leaf=lambda x: isinstance(x, P))

        sp_params = put(params, specs)
        sp_cache = put(tfm.init_cache(cfg_tp, 10, 4, "int8"), cspecs)

        def fwd(exact):
            return jax.jit(jax.shard_map(
                lambda p, c, t, s, bt: tfm.apply_decode(
                    p, t, s, bt, c, cfg_tp, kv_quant="int8",
                    exact_chunk=exact),
                mesh=mesh, in_specs=(specs, cspecs, P(), P(), P()),
                out_specs=(P(), cspecs), check_vma=False))

        tok = jax.random.randint(jax.random.PRNGKey(13), (2, 7), 0, 64)
        tables = jnp.array([[1, 2, 3], [4, 5, 6]], jnp.int32)
        lg, sp_cache = fwd(True)(sp_params, sp_cache, tok,
                                 jnp.zeros((2,), jnp.int32), tables)
        ref = np.asarray(tfm.apply(params, tok, cfg))
        # prefill: fp-reassociation tolerance only (same as the fp32
        # tp=2 parity test) — quantization contributes nothing
        np.testing.assert_allclose(np.asarray(lg), ref, rtol=1e-4,
                                   atol=1e-5)
        nxt = jnp.array([[9], [17]], jnp.int32)
        lg2, _ = fwd(False)(sp_params, sp_cache, nxt,
                            jnp.full((2,), 7, jnp.int32), tables)
        full = np.asarray(tfm.apply(
            params, jnp.concatenate([tok, nxt], axis=1), cfg))
        np.testing.assert_allclose(np.asarray(lg2[:, 0]), full[:, 7],
                                   **self.TOL["int8"])

    def test_engine_greedy_output_matches_fp32(self, model, mesh1):
        cfg, params = model
        rng = np.random.RandomState(21)
        prompts = [list(rng.randint(0, 64, int(n)))
                   for n in rng.randint(3, 12, 4)]
        ref = [_engine(params, cfg, mesh1).generate(p) for p in prompts]
        for kv in ("int8", "fp8"):
            eng = _engine(params, cfg, mesh1, kv_quant=kv)
            assert [eng.generate(p) for p in prompts] == ref

    def test_quantized_pool_4x_sequences_at_fixed_hbm(self):
        """The capacity claim: at one fixed byte budget, the int8 pool
        admits ~4x the sequences of the fp32 pool (3.76x at head_dim
        64 — the fp32 scales are the overhead)."""
        cfg = tfm.TransformerConfig(
            vocab=32, d_model=128, n_heads=2, n_layers=1, d_ff=64,
            max_seq=32, dtype=jnp.float32, remat=False)
        params = tfm.init_params(cfg, jax.random.PRNGKey(3))
        mesh = create_mesh(devices=jax.devices()[:1], tp=1)
        budget = 8 * tfm.kv_bytes_per_block(cfg, 8)   # 8 fp32 blocks
        admitted = {}
        bytes_at_admit = {}
        for kv in (None, "int8"):
            per = tfm.kv_bytes_per_block(cfg, 8, kv)
            n_blocks = budget // per + 1          # + scratch
            eng = InferenceEngine(params, cfg, mesh, ServingConfig(
                block_size=8, kv_blocks=n_blocks, max_batch_slots=16,
                max_queue=32, max_new_tokens=8, min_prefill_bucket=8,
                kv_quant=kv))
            for _ in range(16):
                eng.submit([1] * 9, max_new_tokens=8)   # 2 blocks each
            eng.step()
            admitted[kv] = eng.active_count
            bytes_at_admit[kv] = eng._alloc.in_use * per
            eng.run_until_idle()
        assert admitted[None] == 4
        assert admitted["int8"] >= 15
        assert admitted["int8"] / admitted[None] >= 3.5
        # both pools genuinely sit under the same byte budget
        assert max(bytes_at_admit.values()) <= budget

    def test_kv_bytes_resident_gauge(self, model, mesh1):
        cfg, params = model
        eng = _engine(params, cfg, mesh1, kv_quant="int8")
        eng.submit([1] * 9, max_new_tokens=8)
        eng.step()
        snap = hvd.metrics_snapshot()
        expect = eng._alloc.in_use * tfm.kv_bytes_per_block(
            cfg, 4, "int8")
        assert snap["hvdtpu_serving_kv_bytes_resident"]["values"][""] \
            == expect
        eng.run_until_idle()


class TestSpeculativeDecode:
    """Greedy speculative decode must be TOKEN-IDENTICAL to the
    non-speculative engine — with a perfect drafter (the flagship
    itself: exercises long accepted chains) and with a random tiny
    drafter (exercises rejection + rollback on nearly every step)."""

    @pytest.fixture(scope="class")
    def drafter(self):
        dcfg = tfm.TransformerConfig(
            vocab=64, d_model=16, n_heads=2, n_layers=1, d_ff=32,
            max_seq=64, dtype=jnp.float32, remat=False)
        return dcfg, tfm.init_params(dcfg, jax.random.PRNGKey(9))

    def _prompts(self, seed=0, n=5):
        rng = np.random.RandomState(seed)
        return [list(rng.randint(0, 64, int(m)))
                for m in rng.randint(3, 12, n)]

    def test_self_drafter_token_identical(self, model, mesh1):
        cfg, params = model
        ref_eng = _engine(params, cfg, mesh1)
        spec = _engine(params, cfg, mesh1, spec_tokens=4,
                       draft_params=params, draft_cfg=cfg)
        for p in self._prompts(31):
            assert spec.generate(p) == ref_eng.generate(p)

    def test_random_drafter_token_identical(self, model, mesh1,
                                            drafter):
        """A drafter that proposes mostly garbage still yields exactly
        the flagship's greedy output — only slower. This is the
        rollback correctness test."""
        cfg, params = model
        dcfg, dparams = drafter
        ref_eng = _engine(params, cfg, mesh1)
        spec = _engine(params, cfg, mesh1, spec_tokens=3,
                       draft_params=dparams, draft_cfg=dcfg)
        prompts = self._prompts(32)
        reqs = [spec.submit(p, max_new_tokens=7) for p in prompts]
        spec.run_until_idle()
        batched = [r.result() for r in reqs]
        assert batched == [ref_eng.generate(p, max_new_tokens=7)
                           for p in prompts]

    def test_draft_counters_and_bounds(self, model, mesh1, drafter):
        cfg, params = model
        dcfg, dparams = drafter
        before = hvd.metrics_snapshot()
        spec = _engine(params, cfg, mesh1, spec_tokens=4,
                       draft_params=dparams, draft_cfg=dcfg)
        out = spec.generate([5, 9, 2], max_new_tokens=6)
        assert len(out) == 6            # budget-exact despite chunks
        snap = hvd.metrics_snapshot()

        def delta(name):
            return (snap[name]["values"].get("", 0)
                    - before.get(name, {"values": {}})["values"]
                    .get("", 0))

        prop = delta("hvdtpu_serving_draft_proposed_tokens_total")
        acc = delta("hvdtpu_serving_draft_accepted_tokens_total")
        assert prop > 0 and 0 <= acc <= prop

    def test_eos_inside_accepted_chunk_truncates(self, model, mesh1):
        cfg, params = model
        probe = _engine(params, cfg, mesh1).generate([6] * 4,
                                                     max_new_tokens=8)
        eos = probe[1]
        ref = _engine(params, cfg, mesh1, eos_id=eos).generate(
            [6] * 4, max_new_tokens=8)
        spec = _engine(params, cfg, mesh1, eos_id=eos, spec_tokens=4,
                       draft_params=params, draft_cfg=cfg)
        out = spec.generate([6] * 4, max_new_tokens=8)
        assert out == ref and out[-1] == eos and len(out) < 8

    def test_temperature_slot_samples_exact_distribution(self, model,
                                                         mesh1):
        """A sampled request under a speculative engine advances one
        seeded draw per step from the true next-token logits — the
        same stream the non-speculative engine consumes."""
        cfg, params = model
        ref = _engine(params, cfg, mesh1, temperature=1.0,
                      seed=5).generate([5, 6, 7], max_new_tokens=6)
        spec = _engine(params, cfg, mesh1, temperature=1.0, seed=5,
                       spec_tokens=4, draft_params=params,
                       draft_cfg=cfg)
        assert spec.generate([5, 6, 7], max_new_tokens=6) == ref

    def test_config_validation(self, model, mesh1, drafter):
        cfg, params = model
        dcfg, dparams = drafter
        with pytest.raises(ValueError, match="drafter"):
            _engine(params, cfg, mesh1, spec_tokens=4)
        with pytest.raises(ValueError, match="BOTH"):
            InferenceEngine(params, cfg, mesh1, ServingConfig(),
                            draft_params=dparams)
        with pytest.raises(ValueError, match="vocab"):
            bad = tfm.TransformerConfig(
                vocab=32, d_model=16, n_heads=2, n_layers=1, d_ff=32,
                max_seq=64, dtype=jnp.float32, remat=False)
            _engine(params, cfg, mesh1, spec_tokens=4,
                    draft_params=tfm.init_params(
                        bad, jax.random.PRNGKey(0)), draft_cfg=bad)
        with pytest.raises(ValueError, match=">= 2"):
            _engine(params, cfg, mesh1, spec_tokens=1,
                    draft_params=dparams, draft_cfg=dcfg)

    def test_all_levers_compose(self, model, mesh1):
        """all-on (quantized pool + drafter + prefix cache) still
        produces the quantized engine's greedy outputs — speculation
        and sharing are exact; only quantization may move logits."""
        cfg, params = model
        rng = np.random.RandomState(33)
        system = [int(t) for t in rng.randint(0, 64, 9)]
        prompts = [system + [int(t) for t in rng.randint(0, 64, 3)]
                   for _ in range(3)]
        quant = _engine(params, cfg, mesh1, kv_quant="int8")
        ref = [quant.generate(p, max_new_tokens=6) for p in prompts]
        allon = _engine(params, cfg, mesh1, kv_quant="int8",
                        prefix_cache=True, spec_tokens=4,
                        draft_params=params, draft_cfg=cfg)
        assert [allon.generate(p, max_new_tokens=6)
                for p in prompts] == ref


class TestPrefixCacheEngine:
    def test_second_request_hits_and_matches_uncached(self, model,
                                                      mesh1):
        cfg, params = model
        ref = _engine(params, cfg, mesh1).generate([7] * 13,
                                                   max_new_tokens=6)
        eng = _engine(params, cfg, mesh1, prefix_cache=True)
        before = hvd.metrics_snapshot()
        assert eng.generate([7] * 13, max_new_tokens=6) == ref
        mid = hvd.metrics_snapshot()
        assert eng.generate([7] * 13, max_new_tokens=6) == ref
        after = hvd.metrics_snapshot()

        def hits(snap):
            return snap["hvdtpu_serving_prefix_cache_hits_total"][
                "values"].get("", 0)

        # 13-token prompt at block 4: blocks 0..2 shareable (the last
        # token is never shared); first pass misses, second hits all 3
        assert hits(mid) - hits(before) == 0
        assert hits(after) - hits(mid) == 3

    def test_divergent_tail_shares_prefix_only(self, model, mesh1):
        cfg, params = model
        plain = _engine(params, cfg, mesh1)
        eng = _engine(params, cfg, mesh1, prefix_cache=True)
        system = [3] * 8                        # two full blocks
        a, b = system + [1, 2, 3], system + [4, 5, 6]
        assert eng.generate(a, max_new_tokens=5) == \
            plain.generate(a, max_new_tokens=5)
        before = hvd.metrics_snapshot()
        assert eng.generate(b, max_new_tokens=5) == \
            plain.generate(b, max_new_tokens=5)
        snap = hvd.metrics_snapshot()
        name = "hvdtpu_serving_prefix_cache_hits_total"
        assert snap[name]["values"][""] \
            - before[name]["values"].get("", 0) == 2

    def test_sharing_reduces_resident_blocks(self, model, mesh1):
        cfg, params = model
        eng = _engine(params, cfg, mesh1, prefix_cache=True,
                      max_batch_slots=2)
        r1 = eng.submit([9] * 13, max_new_tokens=4)   # 4 blocks
        r2 = eng.submit([9] * 13, max_new_tokens=4)
        eng.step()
        # uncached: 8 blocks; shared: r2 reuses r1's 3 prefix blocks
        assert eng._alloc.in_use == 5
        eng.run_until_idle()
        assert r1.result() == r2.result()
        # finished sequences release their holds; the cache keeps the
        # 3 indexed prefix blocks resident for the next request
        assert eng._alloc.in_use == 3

    def test_pool_pressure_evicts_cached_blocks(self, model, mesh1):
        """A full pool reclaims idle cached prefix blocks (LRU) rather
        than deferring admission forever."""
        cfg, params = model
        # pool of 6: one request needs 4 blocks, its prompt caches 3
        eng = _engine(params, cfg, mesh1, kv_blocks=7,
                      prefix_cache=True)
        plain = _engine(params, cfg, mesh1)
        a, b = [5] * 13, [6] * 13
        assert eng.generate(a, max_new_tokens=4) == \
            plain.generate(a, max_new_tokens=4)
        assert len(eng._prefix) == 3 and eng._alloc.free == 3
        # b also needs 4 blocks: exactly one of a's cached blocks (the
        # LRU) must be evicted for the admission to fit
        assert eng.generate(b, max_new_tokens=4) == \
            plain.generate(b, max_new_tokens=4)
        assert len(eng._prefix) == 5    # a1, a2 + b's three
        assert eng._alloc.in_use == 5 and eng._alloc.free == 1

    def test_short_prompt_never_shares(self, model, mesh1):
        cfg, params = model
        eng = _engine(params, cfg, mesh1, prefix_cache=True)
        before = hvd.metrics_snapshot()
        eng.generate([2, 3, 4], max_new_tokens=3)   # < one full block
        eng.generate([2, 3, 4], max_new_tokens=3)
        snap = hvd.metrics_snapshot()
        for name in ("hvdtpu_serving_prefix_cache_hits_total",
                     "hvdtpu_serving_prefix_cache_misses_total"):
            assert snap[name]["values"].get("", 0) \
                == before[name]["values"].get("", 0)


class TestLoader:
    def _save_ws4(self, tmp_path, cfg, params):
        """Commit a simulated 4-host tensor-parallel checkpoint (the
        bench's process_fn trick: 8 devices / 2 per 'host')."""
        train_cfg = _cfg(tp_axis="tp")
        mesh = create_mesh(dp=2, tp=4)
        specs = tfm.param_specs(train_cfg)
        sharded = jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            params, specs, is_leaf=lambda x: isinstance(x, P))
        engines = [CheckpointEngine(
            str(tmp_path), process_index=p, process_count=4,
            process_fn=lambda d: d.id // 2, barrier=lambda n: None)
            for p in range(4)]
        for e in engines:
            e.save(sharded, 7, extra=transformer_extra(train_cfg))
        for e in engines:
            e.wait()

    def test_resharded_restore_ws4_to_ws2_and_ws1(self, tmp_path, model):
        cfg, params = model
        self._save_ws4(tmp_path, cfg, params)
        ref = jax.tree_util.tree_leaves(params)
        for n in (2, 1):
            mesh = create_mesh(devices=jax.devices()[:n], tp=n)
            man = CheckpointEngine(str(tmp_path)).restore_manifest()
            scfg = serving_config(config_from_manifest(man), mesh)
            assert scfg.tp_axis == ("tp" if n > 1 else None)
            assert scfg.n_heads == cfg.n_heads   # recorded explicitly
            loaded = jax.tree_util.tree_leaves(
                load_params(str(tmp_path), scfg, mesh))
            for a, b in zip(loaded, ref):
                np.testing.assert_array_equal(np.asarray(a),
                                              np.asarray(b))

    def test_config_roundtrip_requires_extra(self, tmp_path, model):
        cfg, params = model
        eng = CheckpointEngine(str(tmp_path), process_count=1,
                               barrier=lambda n: None)
        eng.save(params, 1, block=True)   # no transformer_extra
        with pytest.raises(KeyError, match="transformer_config"):
            config_from_manifest(eng.restore_manifest())


class TestSchedulerEdges:
    @pytest.fixture(scope="class")
    def served(self, model, mesh1):
        """One engine reused across edge tests (jit programs compile
        once); each test uses fresh requests."""
        cfg, params = model
        return _engine(params, cfg, mesh1)

    def test_batched_equals_sequential(self, model, mesh1, served):
        """The continuous batch never perturbs a request's greedy
        output: per-slot compute is independent (disjoint blocks +
        causal mask)."""
        cfg, params = model
        rng = np.random.RandomState(0)
        prompts = [list(rng.randint(0, 64, int(n)))
                   for n in rng.randint(3, 12, 5)]
        reqs = [served.submit(p, max_new_tokens=6) for p in prompts]
        served.run_until_idle()
        batched = [r.result() for r in reqs]
        solo = _engine(params, cfg, mesh1, max_batch_slots=1)
        sequential = [solo.generate(p, max_new_tokens=6)
                      for p in prompts]
        assert batched == sequential

    def test_queue_full_rejects(self, model, mesh1):
        cfg, params = model
        eng = _engine(params, cfg, mesh1, max_queue=2)
        eng.submit([1, 2, 3])
        eng.submit([4, 5, 6])
        before = hvd.metrics_snapshot()[
            "hvdtpu_serving_requests_total"]["values"].get(
            'status="rejected"', 0)
        with pytest.raises(QueueFullError):
            eng.submit([7, 8, 9])
        after = hvd.metrics_snapshot()[
            "hvdtpu_serving_requests_total"]["values"]['status="rejected"']
        assert after == before + 1
        eng.run_until_idle()   # drain so the jitted cache isn't donated

    def test_kv_exhaustion_defers_admission_without_corruption(
            self, model, mesh1, served):
        """A request the pool cannot cover stays QUEUED — live
        sequences keep decoding and their output is byte-identical to
        an uncontended run."""
        cfg, params = model
        # pool: 7 usable blocks; each request needs 4 (prompt 9 +
        # max_new 8 - 1 = 16 tokens / block 4)
        eng = _engine(params, cfg, mesh1, kv_blocks=8,
                      max_batch_slots=4)
        p1, p2 = [1] * 9, [2] * 9
        r1 = eng.submit(p1)
        r2 = eng.submit(p2)
        assert eng.step()               # admits r1 only; r2 can't fit
        assert eng.active_count == 1 and eng.queue_depth == 1
        assert r2.status == "queued"
        eng.run_until_idle()
        out1, out2 = r1.result(), r2.result()
        assert out1 == served.generate(p1)
        assert out2 == served.generate(p2)
        assert eng._alloc.in_use == 0   # everything returned

    def test_oversized_request_rejected_upfront(self, model, mesh1):
        cfg, params = model
        eng = _engine(params, cfg, mesh1, kv_blocks=4)
        with pytest.raises(ValueError, match="KV blocks"):
            eng.submit([1] * 9, max_new_tokens=8)   # needs 4, pool has 3
        with pytest.raises(ValueError, match="max_seq"):
            eng.submit([1] * 60, max_new_tokens=8)

    def test_mid_stream_eviction_on_max_tokens(self, model, mesh1,
                                               served):
        """A short request leaves the batch while a long one keeps
        decoding; the freed blocks re-admit a third request
        mid-flight."""
        cfg, params = model
        eng = _engine(params, cfg, mesh1, kv_blocks=10,
                      max_batch_slots=2)
        long = eng.submit([3] * 5, max_new_tokens=8)   # 3 blocks
        short = eng.submit([4] * 5, max_new_tokens=3)  # 2 blocks
        third = eng.submit([5] * 5, max_new_tokens=3)  # waits for a slot
        eng.step()
        assert eng.active_count == 2 and third.status == "queued"
        while not short.done:
            eng.step()
        assert not long.done            # still decoding mid-stream
        eng.run_until_idle()
        assert long.result() == served.generate([3] * 5,
                                                max_new_tokens=8)
        assert short.result() == served.generate([4] * 5,
                                                 max_new_tokens=3)
        assert third.result() == served.generate([5] * 5,
                                                 max_new_tokens=3)

    def test_eos_stops_generation(self, model, mesh1):
        cfg, params = model
        eng = _engine(params, cfg, mesh1)
        probe = eng.generate([6] * 4, max_new_tokens=8)
        eos = probe[1]   # force EOS at the second generated token
        eng2 = _engine(params, cfg, mesh1, eos_id=eos)
        out = eng2.generate([6] * 4, max_new_tokens=8)
        assert out == probe[:out.index(eos) + 1]
        assert out[-1] == eos and len(out) < 8

    def test_drain_completes_accepted_rejects_new(self, model, mesh1):
        """Acceptance is a promise: drain finishes BOTH the live slot
        and the still-queued request (zero requests dropped by a
        drain) and only refuses submissions made after it began."""
        cfg, params = model
        eng = _engine(params, cfg, mesh1, max_batch_slots=1)
        active = eng.submit([7] * 4, max_new_tokens=4)
        queued = eng.submit([8] * 4, max_new_tokens=4)
        eng.step()   # admit the first
        eng.drain()
        assert active.status == "completed" and len(active.result()) == 4
        assert queued.status == "completed" and len(queued.result()) == 4
        with pytest.raises(DrainingError):
            eng.submit([9] * 4)

    def test_temperature_sampling_is_seeded(self, model, mesh1):
        cfg, params = model
        outs = []
        for _ in range(2):
            eng = _engine(params, cfg, mesh1, temperature=1.0, seed=3)
            outs.append(eng.generate([5, 6, 7], max_new_tokens=6))
        assert outs[0] == outs[1]   # same seed, same stream

    def test_expired_deadline_fails_at_admission(self, model, mesh1):
        """A queued request whose deadline already passed is failed
        with DEADLINE_ERROR instead of being admitted (the router maps
        this to 504 and never retries it)."""
        from horovod_tpu.serving import DEADLINE_ERROR
        cfg, params = model
        eng = _engine(params, cfg, mesh1)
        live = eng.submit([1, 2, 3], max_new_tokens=2)
        expired = eng.submit([4, 5, 6], deadline_s=-0.001)
        eng.run_until_idle()
        assert live.status == "completed"
        assert expired.status == "failed" \
            and expired.error == DEADLINE_ERROR
        with pytest.raises(RuntimeError, match="deadline"):
            expired.result()

    def test_next_tokens_streams_incrementally(self, model, mesh1):
        """The token-watch consumer sees every token, in order, across
        prefill + decode steps — the primitive the streaming HTTP path
        and the router's mid-stream resume are built on."""
        cfg, params = model
        eng = _engine(params, cfg, mesh1)
        req = eng.submit([3, 1, 4], max_new_tokens=5)
        got = []
        steps = 0
        while not (req.done and len(got) == len(req.tokens)):
            if not req.done:
                eng.step()
                steps += 1
                assert steps < 100
            got.extend(req.next_tokens(len(got), timeout=5.0))
        assert got == req.result()
        assert req.next_tokens(len(got), timeout=0.5) == []  # terminal

    def test_retry_after_tracks_drain_rate(self, model, mesh1):
        cfg, params = model
        eng = _engine(params, cfg, mesh1, max_queue=16)
        assert eng.retry_after_s() == 1    # cold: no completions yet
        for _ in range(4):
            eng.generate([1, 2], max_new_tokens=2)
        # 4 completions in the 10 s window → 0.4/s; no backlog → 1 s
        assert eng.retry_after_s() == 1
        for _ in range(8):                 # backlog, scheduler parked
            eng.submit([1, 2], max_new_tokens=2)
        # ceil(8 outstanding / 0.4 per s) = 20 s
        assert eng.retry_after_s() == 20
        eng.run_until_idle()


class TestDrainPrefillRace:
    def test_sigterm_during_slow_prefill_drains_accepted(
            self, model, mesh1, monkeypatch):
        """Regression (fleet PR): a drain beginning while an admitted
        request is mid-PREFILL must also complete the request still
        queued behind it — under the old fail-the-queue drain, whether
        that second request survived depended on scheduler timing. The
        slow_prefill fault pins the race window open
        deterministically."""
        from horovod_tpu.adaptation import faults
        cfg, params = model
        monkeypatch.setenv("HOROVOD_TPU_FAULT_SPEC",
                           "rank=0:slow_prefill=300ms")
        monkeypatch.setenv("HOROVOD_TPU_REPLICA_ID", "0")
        faults.reset()
        try:
            eng = _engine(params, cfg, mesh1, max_batch_slots=1)
            assert eng._inj is not None
            r1 = eng.submit([7] * 4, max_new_tokens=4)
            r2 = eng.submit([8] * 4, max_new_tokens=4)
            stop = threading.Event()

            def loop():   # the serving scheduler thread
                while not stop.is_set():
                    if not eng.step():
                        time.sleep(0.005)

            t = threading.Thread(target=loop, daemon=True)
            t.start()
            time.sleep(0.05)   # r1's 300 ms prefill is now in flight
            eng.drain()        # "SIGTERM" lands mid-prefill
            stop.set()
            t.join(timeout=10)
            assert r1.status == "completed" and len(r1.result()) == 4
            assert r2.status == "completed" and len(r2.result()) == 4
            snap = hvd.metrics_snapshot()
            fired = snap["hvdtpu_fault_injections_total"]["values"].get(
                'kind="slow_prefill"', 0)
            assert fired >= 1   # the race window was genuinely open
        finally:
            faults.reset()


class TestTorchServingPath:
    def test_torch_checkpoint_serves_through_manifest(
            self, tmp_path, model, mesh1):
        """--framework torch wiring: a checkpoint committed by
        torch.checkpoint_hook (model subtree + optimizer noise, arch
        in the manifest extra) loads bit-exact through
        load_params(key_prefix=TORCH_MODEL_PREFIX) and decodes
        identically to the jax-native engine."""
        torch = pytest.importorskip("torch")
        import horovod_tpu.torch as hvd_torch
        from horovod_tpu.serving import TORCH_MODEL_PREFIX

        cfg, params = model
        # A torch training job whose state dict mirrors the flagship
        # tree (the documented contract, docs/serving.md#torch).
        host = jax.tree_util.tree_map(
            lambda x: torch.from_numpy(np.asarray(x).copy()), params)

        class Model:
            def state_dict(self):
                return host

        class Opt:
            def state_dict(self):   # optimizer leaves must be skipped
                return {"state": {"momentum":
                                  torch.zeros(cfg.d_model)}}

        save = hvd_torch.checkpoint_hook(
            str(tmp_path), model=Model(), optimizer=Opt(), every=1,
            extra=transformer_extra(cfg))
        save(3, block=True)

        man = CheckpointEngine(str(tmp_path)).restore_manifest()
        assert man["step"] == 3
        scfg = serving_config(config_from_manifest(man), mesh1)
        loaded = load_params(str(tmp_path), scfg, mesh1,
                             key_prefix=TORCH_MODEL_PREFIX)
        for a, b in zip(jax.tree_util.tree_leaves(loaded),
                        jax.tree_util.tree_leaves(params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        out = _engine(loaded, scfg, mesh1).generate(
            [1, 2, 3], max_new_tokens=4)
        ref = _engine(params, cfg, mesh1).generate(
            [1, 2, 3], max_new_tokens=4)
        assert out == ref

    def test_unprefixed_load_still_rejects_unknown_leaves(
            self, tmp_path, model, mesh1):
        """The torch subtree-select must not weaken the jax path: a
        checkpoint with foreign leaves and no prefix fails loudly."""
        cfg, params = model
        eng = CheckpointEngine(str(tmp_path), process_count=1,
                               barrier=lambda n: None)
        eng.save({"not_params": np.zeros(3)}, 1, block=True,
                 extra=transformer_extra(cfg))
        man = eng.restore_manifest()
        scfg = serving_config(config_from_manifest(man), mesh1)
        with pytest.raises(KeyError, match="param_specs"):
            load_params(str(tmp_path), scfg, mesh1)


class TestServingMetrics:
    def test_counters_and_gauges_populated(self, model, mesh1):
        cfg, params = model
        eng = _engine(params, cfg, mesh1)
        eng.generate([1, 2, 3, 4], max_new_tokens=4)
        snap = hvd.metrics_snapshot()
        assert snap["hvdtpu_serving_ttft_seconds"]["values"][""][
            "count"] >= 1
        assert snap["hvdtpu_serving_tpot_seconds"]["values"][""][
            "count"] >= 1
        gen = snap["hvdtpu_serving_tokens_total"]["values"][
            'kind="generated"']
        assert gen >= 4
        assert snap["hvdtpu_serving_kv_blocks_total"]["values"][""] > 0
        assert snap["hvdtpu_serving_compiles_total"]["values"][
            'phase="decode"'] >= 1


class TestServerHTTP:
    @pytest.fixture()
    def served(self, model, mesh1):
        from horovod_tpu.serving.server import ServingServer
        cfg, params = model
        eng = _engine(params, cfg, mesh1, max_batch_slots=2,
                      max_new_tokens=4)
        srv = ServingServer(eng, port=0, host="127.0.0.1")
        srv.start()
        yield eng, srv
        srv.shutdown()

    def _post(self, port, body, timeout=120):
        conn = http.client.HTTPConnection("127.0.0.1", port,
                                          timeout=timeout)
        conn.request("POST", "/generate", json.dumps(body),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())

    def test_generate_and_healthz(self, served, model, mesh1):
        cfg, params = model
        eng, srv = served
        conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=30)
        conn.request("GET", "/healthz")
        resp = conn.getresponse()
        health = json.loads(resp.read())
        assert resp.status == 200 and health["status"] == "serving"
        assert health["kv_blocks_total"] == 39
        # the prefix-hash granularity the fleet router scrapes
        assert health["block_size"] == 4
        assert health["prefix_cache"] is False

        status, body = self._post(srv.port, {"tokens": [1, 2, 3]})
        assert status == 200
        reference = _engine(params, cfg, mesh1).generate(
            [1, 2, 3], max_new_tokens=4)
        assert body["tokens"] == reference
        assert body["ttft_ms"] > 0 and body["latency_ms"] >= \
            body["ttft_ms"]

    def test_bad_request_400_and_404(self, served):
        _, srv = served
        status, body = self._post(srv.port, {"tokens": "nope"})
        assert status == 400
        status, _ = self._post(srv.port, {"tokens": [1],
                                          "max_new_tokens": 10 ** 6})
        assert status == 400
        conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=30)
        conn.request("GET", "/nothing")
        assert conn.getresponse().status == 404

    def test_queue_full_is_429_with_retry_after(self, model, mesh1):
        """Saturate the bounded queue with the scheduler loop parked
        (server never started) — the next HTTP submit must 429, with a
        Retry-After hint derived from the queue drain rate."""
        from horovod_tpu.serving.server import ServingServer
        cfg, params = model
        eng = _engine(params, cfg, mesh1, max_queue=1)
        srv = ServingServer(eng, port=0, host="127.0.0.1")
        srv._http_thread.start()   # HTTP only: no scheduler drains
        try:
            eng.submit([1, 2, 3])          # fills the queue
            conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                              timeout=30)
            conn.request("POST", "/generate",
                         json.dumps({"tokens": [4, 5, 6]}),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            body = json.loads(resp.read())
            assert resp.status == 429 and "queue full" in body["error"]
            assert int(resp.getheader("Retry-After")) >= 1
            snap = hvd.metrics_snapshot()
            assert snap["hvdtpu_serving_http_requests_total"]["values"][
                'code="429",route="generate"'] >= 1
        finally:
            eng.run_until_idle()
            srv._httpd.shutdown()
            srv._httpd.server_close()

    def test_streaming_generate_matches_unary(self, served, model,
                                              mesh1):
        """"stream": true returns NDJSON token lines whose assembled
        sequence equals the unary reply for the same prompt."""
        cfg, params = model
        eng, srv = served
        conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=120)
        conn.request("POST", "/generate",
                     json.dumps({"tokens": [2, 7, 1], "stream": True}),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200
        assert resp.getheader("Content-Type") == "application/x-ndjson"
        lines = [json.loads(ln) for ln in resp.read().splitlines()
                 if ln.strip()]
        assert "id" in lines[0]
        done = lines[-1]
        assert done["done"] and done["status"] == "completed"
        toks = [ln["t"] for ln in lines[1:-1]]
        assert done["n"] == len(toks)
        status, unary = self._post(srv.port, {"tokens": [2, 7, 1]})
        assert status == 200 and unary["tokens"] == toks

    def test_expired_deadline_is_504(self, served):
        _, srv = served
        status, body = self._post(
            srv.port, {"tokens": [1, 2], "deadline_ms": 0})
        assert status == 504 and "deadline" in body["error"]

    def test_readyz_flips_on_drain_healthz_stays_live(self, model,
                                                      mesh1):
        """Liveness/readiness split: once a drain is requested,
        /readyz answers 503 (the router stops admitting) while
        /healthz stays 200 — a supervisor must not shoot a replica
        that is cleanly finishing promised work."""
        from horovod_tpu.serving.server import ServingServer
        cfg, params = model
        eng = _engine(params, cfg, mesh1)
        srv = ServingServer(eng, port=0, host="127.0.0.1")
        srv._http_thread.start()
        try:
            conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                              timeout=30)
            conn.request("GET", "/readyz")
            r = conn.getresponse()
            assert r.status == 200 and \
                json.loads(r.read())["status"] == "ready"
            srv._stop.set()                    # drain requested
            conn.request("GET", "/readyz")
            r = conn.getresponse()
            assert r.status == 503
            assert r.getheader("Connection") == "close"
            assert json.loads(r.read())["status"] == "draining"
            conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                              timeout=30)
            conn.request("GET", "/healthz")
            r = conn.getresponse()
            assert r.status == 200             # alive, just draining
            assert json.loads(r.read())["status"] == "draining"
        finally:
            srv._httpd.shutdown()
            srv._httpd.server_close()

    def test_draining_503_carries_connection_close(self, model, mesh1):
        from horovod_tpu.serving.server import ServingServer
        cfg, params = model
        eng = _engine(params, cfg, mesh1)
        srv = ServingServer(eng, port=0, host="127.0.0.1")
        srv._http_thread.start()
        try:
            eng._draining = True               # drain began
            conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                              timeout=30)
            conn.request("POST", "/generate",
                         json.dumps({"tokens": [1, 2]}),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            assert resp.status == 503
            assert resp.getheader("Connection") == "close"
        finally:
            srv._httpd.shutdown()
            srv._httpd.server_close()


class TestSpecAdapt:
    """Per-slot adaptive draft length (spec_adapt=True, docs/autotune.md):
    the engine's AIMD controller backs a hopeless drafter off to k=1 —
    falling back to the plain decode path — while keeping greedy output
    TOKEN-IDENTICAL to the non-adaptive engine, and probes back up on
    the plain-step clock."""

    @pytest.fixture(scope="class")
    def zero_drafter(self):
        # All-zero weights: argmax token 0 every position — proposals
        # essentially never match the flagship, the deterministic
        # worst-case acceptance the controller must survive.
        dcfg = tfm.TransformerConfig(
            vocab=64, d_model=16, n_heads=2, n_layers=1, d_ff=32,
            max_seq=64, dtype=jnp.float32, remat=False)
        dparams = jax.tree_util.tree_map(
            lambda x: x * 0.0, tfm.init_params(dcfg, jax.random.PRNGKey(9)))
        return dcfg, dparams

    def test_requires_drafter(self, model, mesh1):
        cfg, params = model
        with pytest.raises(ValueError, match="drafter"):
            _engine(params, cfg, mesh1, spec_tokens=4, spec_adapt=True)

    def test_backs_off_to_plain_decode_token_identical(
            self, model, mesh1, zero_drafter):
        from horovod_tpu.observability import flight_recorder as _fr
        cfg, params = model
        dcfg, dparams = zero_drafter
        ref_eng = _engine(params, cfg, mesh1, max_new_tokens=24)
        eng = _engine(params, cfg, mesh1, spec_tokens=4, spec_adapt=True,
                      max_new_tokens=24, draft_params=dparams,
                      draft_cfg=dcfg)
        n0 = len(_fr.recorder()._snapshot())
        prompts = [[7, 3, 11], [2] * 5, [40, 1]]
        reqs = [eng.submit(p, max_new_tokens=24) for p in prompts]
        eng.run_until_idle()
        out = [r.result() for r in reqs]
        # Adaptation changes THROUGHPUT, never tokens.
        assert out == [ref_eng.generate(p, max_new_tokens=24)
                       for p in prompts]
        # Every slot's k collapsed to the floor (a later probe may have
        # lifted it back to 2 — never beyond under zero acceptance).
        ctl = eng._spec_ctl
        ks = [s.k_eff for s in ctl._slots.values()]
        assert ks and max(ks) <= 2
        events = [p for _, kind, p in _fr.recorder()._snapshot()[n0:]
                  if kind == "autotune"]
        floors = [p for p in events
                  if p[0] == "spec_backoff" and p[2] == "1"]
        assert len({p[5] for p in floors}) == len(prompts)

    def test_probe_fires_on_the_plain_step_clock(
            self, model, mesh1, zero_drafter):
        from horovod_tpu.observability import flight_recorder as _fr
        cfg, params = model
        dcfg, dparams = zero_drafter
        eng = _engine(params, cfg, mesh1, spec_tokens=4, spec_adapt=True,
                      max_new_tokens=64, draft_params=dparams,
                      draft_cfg=dcfg)
        n0 = len(_fr.recorder()._snapshot())
        # One long request: back off (~4 spec steps), then enough plain
        # steps to trip the probe_every=16 clock at least once.
        eng.generate([5, 9, 2], max_new_tokens=40)
        events = [p for _, kind, p in _fr.recorder()._snapshot()[n0:]
                  if kind == "autotune"]
        assert any(p[0] == "spec_probe" for p in events)

    def test_adaptive_self_drafter_keeps_full_width(self, model, mesh1):
        # A perfect drafter (the flagship itself) never backs off: the
        # controller's optimistic k sticks at the cap.
        cfg, params = model
        eng = _engine(params, cfg, mesh1, spec_tokens=4, spec_adapt=True,
                      draft_params=params, draft_cfg=cfg)
        ref_eng = _engine(params, cfg, mesh1)
        p = [1, 2, 3]
        assert eng.generate(p) == ref_eng.generate(p)
        assert all(s.k_eff == 4 for s in eng._spec_ctl._slots.values())


class TestChunkedPrefill:
    """Chunked prefill (docs/serving.md#chunked-prefill): long prompts
    consumed as bucket-shaped chunks with at most one chunk between
    consecutive batched decode ticks. Greedy output must be
    TOKEN-IDENTICAL to the monolithic-prefill engine across every
    lever combination, including mid-sequence eviction and pool
    exhaustion."""

    @pytest.fixture(scope="class")
    def drafter(self):
        dcfg = tfm.TransformerConfig(
            vocab=64, d_model=16, n_heads=2, n_layers=1, d_ff=32,
            max_seq=64, dtype=jnp.float32, remat=False)
        return dcfg, tfm.init_params(dcfg, jax.random.PRNGKey(9))

    def _prompts(self, seed=7):
        rng = np.random.RandomState(seed)
        # multi-chunk (33, 41, 17) and single-chunk (3, 9) prompts mixed
        return [[int(t) for t in rng.randint(0, 64, n)]
                for n in (33, 3, 17, 9, 41)]

    @pytest.mark.parametrize("levers", [
        dict(),
        dict(kv_quant="int8"),
        dict(kv_quant="fp8"),
        dict(prefix_cache=True),
        dict(spec=True),
        dict(kv_quant="int8", prefix_cache=True, spec=True),
    ], ids=["plain", "int8", "fp8", "prefix", "spec", "all_on"])
    def test_token_identical_to_unchunked(self, model, mesh1, drafter,
                                          levers):
        cfg, params = model
        levers = dict(levers)
        if levers.pop("spec", False):
            dcfg, dparams = drafter
            levers.update(spec_tokens=3, draft_params=dparams,
                          draft_cfg=dcfg)
        ref = _engine(params, cfg, mesh1, **levers)
        chunked = _engine(params, cfg, mesh1, prefill_chunk=8,
                          kv_blocks=64, **levers)
        prompts = self._prompts()
        reqs = [chunked.submit(p, max_new_tokens=6) for p in prompts]
        chunked.run_until_idle()
        assert [r.result() for r in reqs] == \
            [ref.generate(p, max_new_tokens=6) for p in prompts]

    def test_decode_proceeds_between_chunks(self, model, mesh1):
        """The tentpole property: a 5-chunk prompt never stalls a live
        decode — the short request emits one token per scheduler step
        the whole way through the long prompt's chunk sequence."""
        cfg, params = model
        ref = _engine(params, cfg, mesh1)
        eng = _engine(params, cfg, mesh1, prefill_chunk=8)
        short = eng.submit([5, 6, 7], max_new_tokens=8)
        eng.step()                          # admit + 1 chunk + token 1
        long = eng.submit([9] * 33, max_new_tokens=4)
        eng.step()                          # admit long: chunk 1 of 5
        assert long.prefill_pos is not None
        while long.prefill_pos is not None:
            before = len(short.tokens)
            eng.step()
            if not short.done:
                assert len(short.tokens) == before + 1   # no stall
        eng.run_until_idle()
        assert short.result() == ref.generate([5, 6, 7],
                                              max_new_tokens=8)
        assert long.result() == ref.generate([9] * 33,
                                             max_new_tokens=4)

    def test_chunk_metrics_and_tick_histogram(self, model, mesh1):
        cfg, params = model
        before = hvd.metrics_snapshot()
        eng = _engine(params, cfg, mesh1, prefill_chunk=8)
        eng.generate([1] * 33, max_new_tokens=4)
        snap = hvd.metrics_snapshot()

        def delta(name):
            return (snap[name]["values"].get("", 0)
                    - before.get(name, {"values": {}})["values"]
                    .get("", 0))

        # 33 tokens at chunk cap 8 → 5 chunks (8+8+8+8+1)
        assert delta("hvdtpu_serving_prefill_chunks_total") == 5
        assert snap["hvdtpu_serving_decode_tick_seconds"]["values"][
            ""]["count"] >= 1

    def test_pool_exhaustion_defers_admission_mid_sequence(
            self, model, mesh1):
        """While a long prompt is mid-chunk-sequence, a request the
        pool cannot cover stays QUEUED; it admits once the long one
        completes and both outputs match uncontended runs."""
        cfg, params = model
        ref = _engine(params, cfg, mesh1)
        # usable pool 12: long takes ceil((33+4-1)/4)=9, p2 needs 4
        eng = _engine(params, cfg, mesh1, prefill_chunk=8,
                      kv_blocks=13)
        long = eng.submit([3] * 33, max_new_tokens=4)
        eng.step()
        assert long.prefill_pos is not None
        p2 = eng.submit([4] * 9, max_new_tokens=8)
        eng.step()
        assert p2.status == "queued"        # 3 free < 4 needed
        eng.run_until_idle()
        assert long.result() == ref.generate([3] * 33,
                                             max_new_tokens=4)
        assert p2.result() == ref.generate([4] * 9, max_new_tokens=8)
        assert eng._alloc.in_use == 0

    def test_eviction_mid_chunk_sequence_is_clean(self, model, mesh1):
        """A live request finishes and is EVICTED (table row reset to
        scratch) while another is mid-chunk-sequence — the remaining
        chunks and the final outputs are unperturbed."""
        cfg, params = model
        ref = _engine(params, cfg, mesh1)
        eng = _engine(params, cfg, mesh1, prefill_chunk=8)
        short = eng.submit([5] * 4, max_new_tokens=3)
        eng.step()                     # short: prefill + tokens 1, 2
        long = eng.submit([6] * 41, max_new_tokens=4)
        steps = 0
        while not short.done:          # short evicts mid-sequence
            eng.step()
            steps += 1
            assert steps < 50
        assert long.prefill_pos is not None
        eng.run_until_idle()
        assert short.result() == ref.generate([5] * 4,
                                              max_new_tokens=3)
        assert long.result() == ref.generate([6] * 41,
                                             max_new_tokens=4)

    def test_budget_halves_chunk_under_measured_cost(
            self, model, mesh1, monkeypatch):
        """The chunk budget policy: with a tick budget set, the next
        chunk length halves (down to the smallest bucket) while the
        measured per-bucket prefill cost exceeds the budget."""
        monkeypatch.setenv("HOROVOD_TPU_SERVING_TICK_BUDGET_MS", "50")
        cfg, params = model
        eng = _engine(params, cfg, mesh1, prefill_chunk=32)
        assert eng._chunk_len(100) == 32    # unmeasured: optimistic
        eng._note_chunk_cost(32, 0.2)       # 200 ms > 50 ms budget
        assert eng._chunk_len(100) == 16
        eng._note_chunk_cost(16, 0.08)
        assert eng._chunk_len(100) == 8
        eng._note_chunk_cost(8, 0.2)        # floor: smallest bucket
        assert eng._chunk_len(100) == 8
        # EWMA blends; cheap remeasures re-open the larger bucket
        eng._note_chunk_cost(32, 0.0)
        eng._note_chunk_cost(32, 0.0)
        eng._note_chunk_cost(32, 0.0)
        eng._note_chunk_cost(32, 0.0)
        assert eng._chunk_cost[32] == pytest.approx(0.0125)
        assert eng._chunk_len(100) == 32

    def test_retry_after_accounts_chunk_backlog(self, model, mesh1):
        cfg, params = model
        cold = _engine(params, cfg, mesh1, prefill_chunk=8,
                       max_queue=16)
        # cold engine, measured chunk cost, one queued 5-chunk prompt:
        # the hint is the chunk backlog alone (no drain rate yet)
        cold._chunk_cost[8] = 0.5
        cold.submit([2] * 33, max_new_tokens=2)
        assert cold.retry_after_s() == 3    # ceil(5 * 0.5)
        cold.run_until_idle()
        eng = _engine(params, cfg, mesh1, prefill_chunk=8,
                      max_queue=16)
        for _ in range(4):
            eng.generate([1, 2], max_new_tokens=2)
        eng._chunk_cost[8] = 0.5
        for _ in range(2):
            eng.submit([2] * 33, max_new_tokens=2)
        # ceil(2 outstanding / 0.4 per s + 10 chunks * 0.5 s) = 10
        assert eng.retry_after_s() == 10
        eng.run_until_idle()

    def test_long_prompt_burst_fault_injects_requests(
            self, model, mesh1, monkeypatch):
        """The declarative long_prompt_burst clause fires once when
        the serving tick enters its window: the engine submits the
        synthetic prompts itself and completes them."""
        from horovod_tpu.adaptation import faults
        monkeypatch.setenv("HOROVOD_TPU_FAULT_SPEC",
                           "rank=*:long_prompt_burst=2x33:from_step=2")
        monkeypatch.delenv("HOROVOD_TPU_REPLICA_ID", raising=False)
        faults.reset()
        try:
            cfg, params = model
            eng = _engine(params, cfg, mesh1, prefill_chunk=8,
                          kv_blocks=64)
            before = hvd.metrics_snapshot()
            eng.generate([1, 2, 3], max_new_tokens=8)
            eng.run_until_idle()            # finish the injected pair
            snap = hvd.metrics_snapshot()
            assert snap["hvdtpu_fault_injections_total"]["values"][
                'kind="long_prompt_burst"'] - before.get(
                "hvdtpu_fault_injections_total",
                {"values": {}})["values"].get(
                'kind="long_prompt_burst"', 0) == 2
            done = 'status="completed"'
            fam = "hvdtpu_serving_requests_total"
            assert snap[fam]["values"][done] \
                - before[fam]["values"].get(done, 0) == 3
        finally:
            faults.reset()


class TestPrefillSpans:
    """The pure chunk-planning helper the engine and benches share."""

    def test_spans_cover_exactly_once(self):
        spans = tfm.prefill_spans(33, 8)
        assert spans == [(0, 8), (8, 8), (16, 8), (24, 8), (32, 1)]
        assert sum(n for _, n in spans) == 33
        assert tfm.prefill_spans(8, 8) == [(0, 8)]
        assert tfm.prefill_spans(0, 8) == []

    def test_offset_start(self):
        assert tfm.prefill_spans(5, 4, start=10) == [(10, 4), (14, 1)]

    def test_validation(self):
        with pytest.raises(ValueError):
            tfm.prefill_spans(-1, 8)
        with pytest.raises(ValueError):
            tfm.prefill_spans(8, 0)


class TestSessionAffinityEngine:
    """Session KV leases (docs/serving.md#session-affinity): a
    completed request tagged with a session_id parks its KV blocks in
    a lease; the session's next turn resumes from them instead of
    re-prefilling — token-identically."""

    def test_second_turn_reuses_lease_token_identical(self, model,
                                                      mesh1):
        cfg, params = model
        ref = _engine(params, cfg, mesh1)
        eng = _engine(params, cfg, mesh1)
        ctx = [7] * 9
        r1 = eng.submit(ctx, max_new_tokens=4, session_id="conv")
        eng.run_until_idle()
        t1 = r1.result()
        assert eng.session_ids() == ["conv"]
        before = hvd.metrics_snapshot()
        turn2 = ctx + t1 + [9, 11]
        r2 = eng.submit(turn2, max_new_tokens=4, session_id="conv")
        eng.run_until_idle()
        # the lease covers context + every generated token but the
        # last (never fed back) — strictly more than the prefix cache
        # could index (it never covers generated tokens)
        assert r2.cached_tokens == len(ctx) + len(t1) - 1
        assert r2.result() == ref.generate(turn2, max_new_tokens=4)
        assert r1.result() == ref.generate(ctx, max_new_tokens=4)
        snap = hvd.metrics_snapshot()
        hits = "hvdtpu_serving_session_hits_total"
        assert snap[hits]["values"].get("", 0) \
            - before[hits]["values"].get("", 0) == 1
        assert eng.session_ids() == ["conv"]   # lease re-formed

    def test_divergent_turn_releases_lease_and_matches(self, model,
                                                       mesh1):
        cfg, params = model
        ref = _engine(params, cfg, mesh1)
        eng = _engine(params, cfg, mesh1)
        r1 = eng.submit([7] * 9, max_new_tokens=4, session_id="conv")
        eng.run_until_idle()
        # a prompt that does NOT extend the lease's tokens: full
        # re-prefill, stale blocks released, output exact
        div = [1, 2, 3, 4, 5]
        r2 = eng.submit(div, max_new_tokens=4, session_id="conv")
        eng.run_until_idle()
        assert r2.cached_tokens == 0
        assert r2.result() == ref.generate(div, max_new_tokens=4)
        assert eng.session_ids() == ["conv"]   # re-formed on the new turn
        eng2 = _engine(params, cfg, mesh1)
        assert eng2._alloc.in_use == 0

    def test_free_pressure_demotes_lease_to_prefix_cache(self, model,
                                                         mesh1):
        """Eviction under pool pressure is a DEMOTION: the lease's
        full blocks become refcounted prefix-cache entries (a later
        same-context prompt still shares them); the partial tail block
        returns to the pool."""
        cfg, params = model
        eng = _engine(params, cfg, mesh1, prefix_cache=True)
        r = eng.submit([5] * 6, max_new_tokens=8, session_id="s1")
        eng.run_until_idle()
        # lease tokens = 6 + 7 = 13 over 4 blocks; prompt indexed one
        # full block in the prefix cache at prefill time
        assert eng.session_ids() == ["s1"]
        assert len(eng._prefix) == 1 and eng._alloc.in_use == 4
        assert eng._free_pressure()     # 1st: drops the idle prefix entry
        assert eng.session_ids() == ["s1"]
        assert eng._free_pressure()     # 2nd: demotes the lease
        assert eng.session_ids() == []
        # 3 full blocks of the 13 lease tokens live on as cache
        # entries; the tail block was freed
        assert len(eng._prefix) == 3 and eng._alloc.in_use == 3

    def test_pool_pressure_evicts_lease_end_to_end(self, model, mesh1):
        cfg, params = model
        ref = _engine(params, cfg, mesh1)
        # usable pool 12; the idle lease holds 4; b needs 9 → evict
        eng = _engine(params, cfg, mesh1, kv_blocks=13)
        r1 = eng.submit([5] * 13, max_new_tokens=4, session_id="s1")
        eng.run_until_idle()
        assert eng.session_ids() == ["s1"] and eng._alloc.in_use == 4
        before = hvd.metrics_snapshot()
        b = [6] * 33
        r2 = eng.submit(b, max_new_tokens=4)
        eng.run_until_idle()
        assert r2.result() == ref.generate(b, max_new_tokens=4)
        assert eng.session_ids() == []
        snap = hvd.metrics_snapshot()
        ev = "hvdtpu_serving_session_evictions_total"
        assert snap[ev]["values"].get("", 0) \
            - before[ev]["values"].get("", 0) == 1

    def test_lease_cap_evicts_lru(self, model, mesh1):
        cfg, params = model
        eng = _engine(params, cfg, mesh1, session_leases=2)
        for i, sid in enumerate(("a", "b", "c")):
            eng.submit([i + 1] * 5, max_new_tokens=2, session_id=sid)
            eng.run_until_idle()
        assert eng.session_ids() == ["b", "c"]   # LRU-oldest first

    def test_sessions_disabled_by_zero(self, model, mesh1):
        cfg, params = model
        ref = _engine(params, cfg, mesh1)
        eng = _engine(params, cfg, mesh1, session_leases=0)
        r = eng.submit([3] * 7, max_new_tokens=4, session_id="x")
        eng.run_until_idle()
        assert r.result() == ref.generate([3] * 7, max_new_tokens=4)
        assert eng.session_ids() == [] and eng._alloc.in_use == 0

    def test_lease_composes_with_chunked_prefill(self, model, mesh1):
        cfg, params = model
        ref = _engine(params, cfg, mesh1)
        eng = _engine(params, cfg, mesh1, prefill_chunk=8)
        ctx = [3] * 20
        r1 = eng.submit(ctx, max_new_tokens=6, session_id="c")
        eng.run_until_idle()
        turn2 = ctx + r1.result() + [1, 2]
        r2 = eng.submit(turn2, max_new_tokens=6, session_id="c")
        eng.run_until_idle()
        assert r2.cached_tokens == len(ctx) + 5   # lease hit
        assert r2.result() == ref.generate(turn2, max_new_tokens=6)
        assert r1.result() == ref.generate(ctx, max_new_tokens=6)


class TestServerSessionHTTP:
    def test_session_id_flows_and_healthz_advertises(self, model,
                                                     mesh1):
        from horovod_tpu.serving.server import ServingServer
        cfg, params = model
        eng = _engine(params, cfg, mesh1, max_new_tokens=4)
        srv = ServingServer(eng, port=0, host="127.0.0.1")
        srv.start()
        try:
            conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                              timeout=120)
            conn.request("POST", "/generate",
                         json.dumps({"tokens": [4] * 9,
                                     "session_id": "conv-1"}),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            assert resp.status == 200
            json.loads(resp.read())
            conn.request("GET", "/healthz")
            h = json.loads(conn.getresponse().read())
            assert h["sessions"] == ["conv-1"]
            assert h["session_leases"] == 8
            # the header spelling works too and reuses the lease
            conn.request("POST", "/generate",
                         json.dumps({"tokens": [4] * 9}),
                         {"Content-Type": "application/json",
                          "X-Session-Id": "conv-2"})
            assert conn.getresponse().status == 200
            conn.request("GET", "/healthz")
            h = json.loads(conn.getresponse().read())
            assert set(h["sessions"]) == {"conv-1", "conv-2"}
        finally:
            srv.shutdown()
