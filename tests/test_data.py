"""Pod-scale input pipeline (ISSUE 13, docs/data.md): deterministic
sharded loaders, prefetch-to-device, exactly-once resumable cursors,
and distributed batch norm."""

import os
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu import data
from horovod_tpu.data import sharding as shd


# ---------------------------------------------------------------------------
# Epoch plan determinism
# ---------------------------------------------------------------------------

class TestEpochPermutation:
    def test_pure_function_of_seed_and_epoch(self):
        a = shd.epoch_permutation(100, seed=7, epoch=3)
        b = shd.epoch_permutation(100, seed=7, epoch=3)
        np.testing.assert_array_equal(a, b)

    def test_epochs_and_seeds_differ(self):
        base = shd.epoch_permutation(100, seed=7, epoch=0)
        assert not np.array_equal(base, shd.epoch_permutation(100, 7, 1))
        assert not np.array_equal(base, shd.epoch_permutation(100, 8, 0))

    def test_is_a_permutation(self):
        p = shd.epoch_permutation(257, seed=0, epoch=5)
        np.testing.assert_array_equal(np.sort(p), np.arange(257))

    def test_no_shuffle_is_sequential(self):
        np.testing.assert_array_equal(
            shd.epoch_permutation(10, 3, 2, shuffle=False), np.arange(10))

    def test_drop_remainder_is_world_independent(self):
        # The usable count depends on (n, batch) only — the property the
        # elastic exactly-once contract rests on.
        for w in (1, 2, 4, 8):
            assert shd.usable_samples(70, 4) == 68, w


# ---------------------------------------------------------------------------
# Sources
# ---------------------------------------------------------------------------

class TestSources:
    def test_array_source_pairs(self):
        xs = np.arange(20).reshape(10, 2).astype(np.float32)
        ys = np.arange(10).astype(np.int32)
        src = data.as_source((xs, ys))
        got = src.take(np.array([3, 1]))
        np.testing.assert_array_equal(got[0], xs[[3, 1]])
        np.testing.assert_array_equal(got[1], ys[[3, 1]])

    def test_array_source_length_mismatch(self):
        with pytest.raises(ValueError, match="axis-0 length"):
            data.ArraySource(np.zeros((4, 2)), np.zeros((5,)))

    def test_file_list_source(self, tmp_path):
        paths = []
        for i in range(6):
            p = tmp_path / f"s{i}.npy"
            np.save(p, np.full((3,), float(i)))
            paths.append(str(p))
        src = data.as_source(paths)
        assert len(src) == 6
        (batch,) = src.take(np.array([4, 0, 5]))
        np.testing.assert_array_equal(batch[:, 0], [4.0, 0.0, 5.0])

    def test_callable_source_needs_length(self):
        fn = lambda ids: np.asarray(ids, np.float32) * 2  # noqa: E731
        with pytest.raises(ValueError, match="length"):
            data.as_source(fn)
        src = data.as_source(fn, length=9)
        assert len(src) == 9
        (b,) = src.take(np.array([1, 4]))
        np.testing.assert_array_equal(b, [2.0, 8.0])

    def test_synthetic_sample_is_pure_function_of_id(self):
        # Same id -> same sample regardless of which batch asks: the
        # property the exactly-once multiset checks rely on.
        a = data.synthetic("image", n=50, image_size=4, seed=3)
        b = data.synthetic("image", n=50, image_size=4, seed=3)
        ia, la = a.take(np.array([7, 3, 7]))
        ib, lb = b.take(np.array([7]))
        np.testing.assert_array_equal(ia[0], ia[2])
        np.testing.assert_array_equal(ia[0], ib[0])
        assert la[0] == lb[0]

    def test_synthetic_tokens_shape_and_range(self):
        src = data.synthetic("tokens", n=10, seq_len=16, vocab=100,
                             seed=1)
        (t,) = src.take(np.array([0, 9]))
        assert t.shape == (2, 16) and t.dtype == np.int32
        assert t.min() >= 0 and t.max() < 100

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="synthetic kind"):
            data.synthetic("video")


# ---------------------------------------------------------------------------
# Sharded loader
# ---------------------------------------------------------------------------

def _collect_epoch(src, *, world, batch, seed, **kw):
    """Run one epoch on `world` fresh loaders; returns per-rank id lists."""
    loaders = [data.build_loader(src, batch_size=batch, rank=r,
                                 world_size=world, seed=seed, epochs=1,
                                 **kw)
               for r in range(world)]
    out = [[] for _ in range(world)]
    for r, ld in enumerate(loaders):
        for b in ld:
            out[r].extend(b.ids.tolist())
    return out


class TestShardedLoader:
    def test_one_epoch_is_a_disjoint_cover(self):
        src = data.synthetic("image", n=70, image_size=4, seed=0)
        per_rank = _collect_epoch(src, world=2, batch=4, seed=11)
        flat = [i for ids in per_rank for i in ids]
        assert len(flat) == shd.usable_samples(70, 4) == 68
        assert len(set(flat)) == 68
        assert not (set(per_rank[0]) & set(per_rank[1]))
        ds = data.ShardedDataset(src, batch_size=4, seed=11)
        assert sorted(flat) == sorted(ds.epoch_ids(0).tolist())

    def test_identical_across_launches(self):
        # Two independent "launches" (fresh loaders) produce the same
        # per-rank batch sequence — the determinism contract.
        src = data.synthetic("image", n=64, image_size=4, seed=0)
        a = _collect_epoch(src, world=4, batch=4, seed=9)
        b = _collect_epoch(src, world=4, batch=4, seed=9)
        assert a == b

    def test_static_shapes_including_filler(self):
        # 3 microbatches on a world of 2: the final global step hands
        # rank 1 a zero-weight filler with the SAME static shapes.
        src = data.synthetic("image", n=12, image_size=4, num_classes=3,
                             seed=0)
        ld = data.build_loader(src, batch_size=4, rank=1, world_size=2,
                               seed=1, epochs=1)
        batches = list(ld)
        assert [b.weight for b in batches] == [4, 0]
        filler = batches[-1]
        assert filler.data[0].shape == (4, 4, 4, 3)
        assert filler.ids.size == 0
        np.testing.assert_array_equal(filler.data[0], 0.0)

    def test_epoch_rolls_over_with_new_permutation(self):
        src = data.synthetic("image", n=16, image_size=4, seed=0)
        ld = data.build_loader(src, batch_size=4, rank=0, world_size=1,
                               seed=2, epochs=2)
        ids = [b.ids.tolist() for b in ld]
        assert len(ids) == 8
        e0, e1 = sum(ids[:4], []), sum(ids[4:], [])
        assert sorted(e0) == sorted(e1) == list(range(16))
        assert e0 != e1   # reshuffled per epoch

    def test_transform_applies_per_batch(self):
        src = data.synthetic("image", n=16, image_size=4, seed=0)
        ld = data.build_loader(
            src, batch_size=4, rank=0, world_size=1, seed=2, epochs=1,
            transform=lambda arrs: (arrs[0] * 0 + 7.0,) + arrs[1:])
        b = next(ld)
        np.testing.assert_array_equal(b.data[0], 7.0)

    def test_drop_remainder_false_rejected(self):
        with pytest.raises(ValueError, match="drop_remainder"):
            data.build_loader(np.zeros((10, 2)), batch_size=4,
                              rank=0, world_size=1, drop_remainder=False)

    def test_zero_microbatches_rejected(self):
        with pytest.raises(ValueError, match="zero whole"):
            data.build_loader(np.zeros((3, 2)), batch_size=4,
                              rank=0, world_size=1)

    def test_rank_outside_world_rejected(self):
        with pytest.raises(ValueError, match="outside world"):
            data.build_loader(np.zeros((8, 2)), batch_size=4, rank=2,
                              world_size=2)

    def test_metrics_families_registered(self):
        src = data.synthetic("image", n=8, image_size=4, seed=0)
        ld = data.build_loader(src, batch_size=4, rank=0, world_size=1,
                               seed=0, epochs=1)
        list(ld)
        snap = hvd.metrics_snapshot()
        for fam in ("hvdtpu_data_samples_total",
                    "hvdtpu_data_batches_total",
                    "hvdtpu_data_epochs_total",
                    "hvdtpu_data_load_seconds_total"):
            assert fam in snap, fam
        assert snap["hvdtpu_data_samples_total"]["values"][""] >= 8


# ---------------------------------------------------------------------------
# Cursor / exactly-once resume
# ---------------------------------------------------------------------------

class TestCursorResume:
    def test_cursor_roundtrip_continues_exactly(self):
        src = data.synthetic("image", n=40, image_size=4, seed=0)
        ld = data.build_loader(src, batch_size=4, rank=0, world_size=1,
                               seed=5)
        seen = [next(ld).ids.tolist() for _ in range(3)]
        cur = ld.commit_cursor()
        resumed = data.build_loader(src, batch_size=4, rank=0,
                                    world_size=1, seed=5).restore(cur)
        ref = data.build_loader(src, batch_size=4, rank=0, world_size=1,
                                seed=5)
        for _ in range(3):
            next(ref)
        for _ in range(4):
            np.testing.assert_array_equal(next(resumed).ids,
                                          next(ref).ids)
        assert seen  # consumed prefix untouched by the resume

    def test_exactly_once_across_world_shrink_and_regrow(self):
        # 2 ranks consume 4 global steps, commit; 1 rank consumes 3
        # more from the cursor, commits; 2 ranks finish the epoch. The
        # union is exactly one clean epoch — no duplicate, no gap.
        src = data.synthetic("image", n=100, image_size=4, seed=0)
        ds = data.ShardedDataset(src, batch_size=4, seed=21)
        consumed = []

        l2 = [data.build_loader(src, batch_size=4, rank=r, world_size=2,
                                seed=21) for r in range(2)]
        for _ in range(4):
            for ld in l2:
                consumed.extend(next(ld).ids.tolist())
        cur = l2[0].commit_cursor()

        l1 = data.build_loader(src, batch_size=4, rank=0, world_size=1,
                               seed=21).restore(cur)
        for _ in range(3):
            consumed.extend(next(l1).ids.tolist())
        cur = l1.commit_cursor()

        l2b = [data.build_loader(src, batch_size=4, rank=r,
                                 world_size=2, seed=21, epochs=1
                                 ).restore(cur) for r in range(2)]
        for ld in l2b:
            for b in ld:
                consumed.extend(b.ids.tolist())

        assert len(consumed) == ds.usable == 100
        assert sorted(consumed) == sorted(ds.epoch_ids(0).tolist())

    def test_restore_counts_skips_and_notes_recorder(self):
        from horovod_tpu.observability import flight_recorder as fr

        src = data.synthetic("image", n=40, image_size=4, seed=0)
        ld = data.build_loader(src, batch_size=4, rank=0, world_size=1,
                               seed=5)
        for _ in range(2):
            next(ld)
        cur = ld.commit_cursor()
        before = hvd.metrics_snapshot()[
            "hvdtpu_data_resume_skips_total"]["values"].get("", 0.0)
        data.build_loader(src, batch_size=4, rank=0, world_size=1,
                          seed=5).restore(cur)
        after = hvd.metrics_snapshot()[
            "hvdtpu_data_resume_skips_total"]["values"][""]
        assert after - before == 8.0
        kinds = [(k, p) for _, k, p in list(fr.recorder()._ring)
                 if k == "data"]
        assert any(p[0] == "cursor_commit" for _, p in kinds)
        assert any(p[0] == "resume" for _, p in kinds)

    def test_mismatched_plan_rejected(self):
        src = data.synthetic("image", n=40, image_size=4, seed=0)
        cur = data.build_loader(src, batch_size=4, rank=0, world_size=1,
                                seed=5).commit_cursor()
        with pytest.raises(ValueError, match="exactly-once"):
            data.build_loader(src, batch_size=4, rank=0, world_size=1,
                              seed=6).restore(cur)
        with pytest.raises(ValueError, match="exactly-once"):
            data.build_loader(src, batch_size=8, rank=0, world_size=1,
                              seed=5).restore(cur)

    def test_cursor_rides_elastic_state(self, tmp_path):
        # The integration path docs/data.md#exactly-once shows: the
        # cursor is a tree in the same ElasticState commit as the model.
        src = data.synthetic("image", n=40, image_size=4, seed=0)
        ld = data.build_loader(src, batch_size=4, rank=0, world_size=1,
                               seed=5)
        next(ld), next(ld)
        state = hvd.ElasticState(directory=str(tmp_path),
                                 params={"w": jnp.zeros((2,))},
                                 data=ld.commit_cursor())
        state.commit(2)
        fresh = hvd.ElasticState(directory=str(tmp_path),
                                 params={"w": jnp.ones((2,))},
                                 data=data.build_loader(
                                     src, batch_size=4, rank=0,
                                     world_size=1, seed=5).cursor())
        fresh.restore()
        resumed = data.build_loader(src, batch_size=4, rank=0,
                                    world_size=1, seed=5
                                    ).restore(fresh.data)
        assert resumed.offset == 2 and resumed.epoch == 0

    def test_cursor_rides_sharded_checkpoint_engine(self, tmp_path):
        # The tentpole path: the cursor checkpoints through the PR 4
        # sharded engine (ElasticState backend="sharded") like any
        # other replicated tree.
        src = data.synthetic("image", n=40, image_size=4, seed=0)
        ld = data.build_loader(src, batch_size=4, rank=0, world_size=1,
                               seed=5)
        for _ in range(3):
            next(ld)
        st = hvd.ElasticState(directory=str(tmp_path),
                              backend="sharded",
                              params={"w": jnp.arange(4.0)},
                              data=ld.commit_cursor())
        st.commit(3, block=True)
        fresh = hvd.ElasticState(
            directory=str(tmp_path), backend="sharded",
            params={"w": jnp.zeros(4)},
            data=data.build_loader(src, batch_size=4, rank=0,
                                   world_size=1, seed=5).cursor())
        fresh.restore()
        resumed = data.build_loader(src, batch_size=4, rank=0,
                                    world_size=1, seed=5
                                    ).restore(fresh.data)
        assert resumed.offset == 3 and resumed.epoch == 0
        np.testing.assert_array_equal(np.asarray(fresh.params["w"]),
                                      np.arange(4.0))

    def test_postmortem_surfaces_last_cursor(self, tmp_path):
        from horovod_tpu.observability import flight_recorder as fr
        from horovod_tpu.tools import postmortem

        fr.reset()
        rec = fr.recorder()
        rec.configure(rank=0, world=1)
        rec.note("data", ("cursor_commit", 2, 14, 0))
        rec.note("data", ("cursor_commit", 3, 6, 0))
        path = rec.dump("exception", directory=str(tmp_path))
        dump = postmortem.load_dump(path)
        report = postmortem.analyze([dump])
        assert report["per_rank"]["0"]["data_cursor"] == {
            "epoch": 3, "offset": 6}
        text = postmortem.format_report(report)
        assert "epoch 3 offset 6" in text
        fr.reset()


# ---------------------------------------------------------------------------
# Prefetch-to-device
# ---------------------------------------------------------------------------

class TestPrefetch:
    def _loader(self, n=32, batch=4, **kw):
        src = data.synthetic("image", n=n, image_size=4, seed=0)
        return data.build_loader(src, batch_size=batch, rank=0,
                                 world_size=1, seed=3, epochs=1, **kw)

    def test_batches_arrive_on_device_in_order(self):
        ref = [b.ids.tolist() for b in self._loader()]
        got = []
        for b in data.prefetch_to_device(self._loader(), depth=2):
            assert isinstance(b.data[0], jax.Array)
            got.append(b.ids.tolist())
        assert got == ref

    def test_mesh_shorthand_shards_over_dp(self):
        mesh = Mesh(np.array(jax.devices()[:4]), ("dp",))
        pf = data.prefetch_to_device(self._loader(), mesh, depth=2)
        b = next(pf)
        want = NamedSharding(mesh, P("dp"))
        assert b.data[0].sharding.is_equivalent_to(want, b.data[0].ndim)
        pf.close()

    def test_overlaps_a_slow_source(self):
        # With a 30 ms source and a 30 ms consumer, serial would take
        # ~2x the prefetched wall time; assert the overlap is real but
        # leave slack for CI scheduling noise.
        delay = 0.03
        steps = 6
        ld = self._loader(
            n=steps * 4,
            transform=lambda a: (time.sleep(delay), a)[1])
        t0 = time.perf_counter()
        n = 0
        for _ in data.prefetch_to_device(ld, depth=2):
            time.sleep(delay)   # the "step"
            n += 1
        wall = time.perf_counter() - t0
        assert n == steps
        assert wall < 2 * steps * delay * 0.95, wall

    def test_source_exception_propagates(self):
        def boom(arrs):
            raise RuntimeError("bad decode")
        pf = data.prefetch_to_device(self._loader(transform=boom))
        with pytest.raises(RuntimeError, match="bad decode"):
            next(pf)

    def test_depth_validated_and_gauges_set(self):
        with pytest.raises(ValueError, match="depth"):
            data.prefetch_to_device(self._loader(), depth=0)
        list(data.prefetch_to_device(self._loader(), depth=3))
        snap = hvd.metrics_snapshot()
        assert snap["hvdtpu_data_prefetch_depth"]["values"][""] == 3.0
        assert "hvdtpu_data_prefetch_occupancy" in snap
        assert snap["hvdtpu_data_wait_seconds_total"]["values"][""] > 0
        assert snap["hvdtpu_data_h2d_seconds_total"]["values"][""] > 0

    def test_stage_marks_timer(self):
        from horovod_tpu.observability import StepTimer
        timer = StepTimer("data_test_stage", batch_size=4)
        b = next(self._loader())
        timer.begin()
        staged = data.stage(b, timer=timer)
        timer.end()
        assert isinstance(staged.data[0], jax.Array)
        assert timer.last_phases["h2d"] > 0


class TestStepTimerH2DCredit:
    def test_credit_moves_gap_from_input_to_h2d(self):
        from horovod_tpu.observability import StepTimer
        timer = StepTimer("data_test_credit", batch_size=1)
        with timer:
            pass
        time.sleep(0.08)            # pre-step gap: 50/50 source vs copy
        timer.credit_h2d(0.04)
        with timer:
            time.sleep(0.01)
        ph = timer.last_phases
        assert 0.03 <= ph["h2d"] <= 0.06, ph
        assert ph["input"] >= 0.02, ph
        assert ph["input"] + ph["h2d"] >= 0.07, ph

    def test_credit_capped_at_actual_gap(self):
        from horovod_tpu.observability import StepTimer
        timer = StepTimer("data_test_cap", batch_size=1)
        with timer:
            pass
        timer.credit_h2d(10.0)      # absurd credit, tiny real gap
        with timer:
            pass
        ph = timer.last_phases
        assert ph["h2d"] <= 0.05, ph

    def test_credit_cleared_between_steps(self):
        from horovod_tpu.observability import StepTimer
        timer = StepTimer("data_test_clear", batch_size=1)
        with timer:
            pass
        time.sleep(0.03)
        timer.credit_h2d(0.03)
        with timer:
            pass
        first_h2d = timer.last_phases["h2d"]
        time.sleep(0.03)
        with timer:
            pass
        assert first_h2d > 0
        assert timer.last_phases["h2d"] == 0.0


# ---------------------------------------------------------------------------
# Distributed batch norm
# ---------------------------------------------------------------------------

class TestSyncBatchNorm:
    """Acceptance (ISSUE 13): dp=4 distributed BN matches single-device
    BN on the concatenated batch at rtol 1e-5, forward and gradients,
    via the fused (single-psum) collective path."""

    B, C = 16, 6

    def _mesh(self):
        return Mesh(np.array(jax.devices()[:4]), ("dp",))

    def _vars(self):
        rng = np.random.RandomState(0)
        return {
            "params": {
                "scale": jnp.asarray(rng.rand(self.C).astype(np.float32)
                                     + 0.5),
                "bias": jnp.asarray(rng.randn(self.C).astype(np.float32)),
            },
            "batch_stats": {"mean": jnp.zeros(self.C),
                            "var": jnp.ones(self.C)},
        }

    def _x(self):
        return jnp.asarray(np.random.RandomState(1).randn(
            self.B, 5, 5, self.C).astype(np.float32))

    def test_forward_matches_concatenated_batch(self):
        import flax.linen as nn
        from horovod_tpu.data.sync_bn import SyncBatchNorm

        x, variables = self._x(), self._vars()
        ref = nn.BatchNorm(use_running_average=False, momentum=0.9,
                           epsilon=1e-5, dtype=jnp.float32)
        y_ref, upd_ref = ref.apply(variables, x, mutable=["batch_stats"])
        sbn = SyncBatchNorm(use_running_average=False, axis_name="dp")
        f = jax.jit(jax.shard_map(
            lambda xs: sbn.apply(variables, xs, mutable=["batch_stats"]),
            mesh=self._mesh(), in_specs=P("dp"),
            out_specs=(P("dp"), P())))
        y, upd = f(x)
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                                   rtol=1e-5, atol=1e-6)
        # Running statistics fold the identical global moments.
        for k in ("mean", "var"):
            np.testing.assert_allclose(
                np.asarray(upd["batch_stats"][k]),
                np.asarray(upd_ref["batch_stats"][k]), rtol=1e-5)

    def test_gradients_match_concatenated_batch(self):
        import flax.linen as nn
        from horovod_tpu.data.sync_bn import SyncBatchNorm

        x, variables = self._x(), self._vars()
        stats = variables["batch_stats"]
        ref = nn.BatchNorm(use_running_average=False, momentum=0.9,
                           epsilon=1e-5, dtype=jnp.float32)

        def loss_ref(params, xs):
            y, _ = ref.apply({"params": params, "batch_stats": stats},
                             xs, mutable=["batch_stats"])
            return jnp.sum(jnp.sin(y))

        g_ref, gx_ref = jax.grad(loss_ref, argnums=(0, 1))(
            variables["params"], x)

        sbn = SyncBatchNorm(use_running_average=False, axis_name="dp")
        from horovod_tpu.parallel import collectives as coll

        def loss_dist(params, xs):
            def shard(xx):
                y, _ = sbn.apply(
                    {"params": params, "batch_stats": stats}, xx,
                    mutable=["batch_stats"])
                return coll.psum(jnp.sum(jnp.sin(y)), "dp")
            return jax.shard_map(shard, mesh=self._mesh(),
                                 in_specs=P("dp"), out_specs=P())(xs)

        g, gx = jax.grad(loss_dist, argnums=(0, 1))(
            variables["params"], x)
        for k in g_ref:
            np.testing.assert_allclose(np.asarray(g[k]),
                                       np.asarray(g_ref[k]),
                                       rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_ref),
                                   rtol=1e-5, atol=1e-6)

    def test_single_psum_on_the_wire(self):
        # The fused path: ONE all-reduce carrying the concatenated
        # [sum, sum_sq] buffer — not one per moment.
        from horovod_tpu.data.sync_bn import sync_batch_norm

        variables = self._vars()

        def shard(xs):
            y, _, _ = sync_batch_norm(
                xs, variables["params"]["scale"],
                variables["params"]["bias"], axis_name="dp")
            return y

        f = jax.jit(jax.shard_map(shard, mesh=self._mesh(),
                                  in_specs=P("dp"), out_specs=P("dp")))
        text = f.lower(self._x()).as_text()
        assert text.count("all_reduce") == 1, text

    def test_inference_uses_running_stats_without_collective(self):
        from horovod_tpu.data.sync_bn import SyncBatchNorm

        variables = self._vars()
        sbn = SyncBatchNorm(use_running_average=True, axis_name="dp")
        # No mapped context at all: running-average mode must not touch
        # the axis.
        y = sbn.apply(variables, self._x())
        assert y.shape == self._x().shape

    def test_local_mode_without_axis(self):
        import flax.linen as nn
        from horovod_tpu.data.sync_bn import SyncBatchNorm

        variables = self._vars()
        x = self._x()
        y, _ = SyncBatchNorm(use_running_average=False,
                             axis_name=None).apply(
            variables, x, mutable=["batch_stats"])
        y_ref, _ = nn.BatchNorm(use_running_average=False, momentum=0.9,
                                epsilon=1e-5, dtype=jnp.float32).apply(
            variables, x, mutable=["batch_stats"])
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                                   rtol=1e-5, atol=1e-6)


class TestModelAdoption:
    """The conv zoo takes distributed BN by constructor flag, sharing
    the local models' parameter trees (checkpoints interchangeable)."""

    def _mesh(self):
        return Mesh(np.array(jax.devices()[:4]), ("dp",))

    def test_resnet_sync_bn_matches_concatenated_batch(self):
        from horovod_tpu.models import ResNet

        kw = dict(stage_sizes=[1], num_classes=4, num_filters=8,
                  dtype=jnp.float32)
        x = jnp.asarray(np.random.RandomState(0).rand(
            8, 16, 16, 3).astype(np.float32))
        local = ResNet(**kw)
        variables = local.init(jax.random.PRNGKey(0), x[:2], train=False)
        y_ref, _ = local.apply(variables, x, train=True,
                               mutable=["batch_stats"])

        dist = ResNet(bn_axis_name="dp", **kw)
        # Same parameter tree: a local checkpoint loads into the
        # sync-BN model unchanged.
        dv = dist.init(jax.random.PRNGKey(0), x[:2], train=False)
        assert jax.tree_util.tree_structure(dv) == \
            jax.tree_util.tree_structure(variables)

        f = jax.jit(jax.shard_map(
            lambda xs: dist.apply(variables, xs, train=True,
                                  mutable=["batch_stats"])[0],
            mesh=self._mesh(), in_specs=P("dp"), out_specs=P("dp")))
        np.testing.assert_allclose(np.asarray(f(x)), np.asarray(y_ref),
                                   rtol=1e-4, atol=1e-4)

    def test_resnet_fused_plus_sync_rejected(self):
        from horovod_tpu.models import ResNet

        x = jnp.zeros((2, 16, 16, 3))
        model = ResNet(stage_sizes=[1], num_filters=8, num_classes=4,
                       bn_impl="jnp", bn_axis_name="dp")
        with pytest.raises(ValueError, match="bn_impl='flax'"):
            model.init(jax.random.PRNGKey(0), x, train=False)

    def test_vgg_sync_bn_param_tree_matches_local(self):
        from horovod_tpu.models import VGG

        cfg = ((1, 4), (1, 8))
        x = jnp.zeros((2, 8, 8, 3))
        local = VGG(cfg=cfg, num_classes=4, use_bn=True,
                    dtype=jnp.float32)
        dist = VGG(cfg=cfg, num_classes=4, use_bn=True,
                   dtype=jnp.float32, bn_axis_name="dp")
        vl = local.init({"params": jax.random.PRNGKey(0)}, x,
                        train=False)
        vd = dist.init({"params": jax.random.PRNGKey(0)}, x,
                       train=False)
        assert jax.tree_util.tree_structure(vl) == \
            jax.tree_util.tree_structure(vd)

    def test_inception_convbn_sync_matches_local(self):
        from horovod_tpu.models.inception import ConvBN

        x = jnp.asarray(np.random.RandomState(0).rand(
            8, 8, 8, 3).astype(np.float32))
        local = ConvBN(8, (3, 3), dtype=jnp.float32)
        variables = local.init(jax.random.PRNGKey(0), x[:2], train=False)
        y_ref = local.apply(variables, x, train=True,
                            mutable=["batch_stats"])[0]
        dist = ConvBN(8, (3, 3), dtype=jnp.float32, bn_axis_name="dp")
        f = jax.jit(jax.shard_map(
            lambda xs: dist.apply(variables, xs, train=True,
                                  mutable=["batch_stats"])[0],
            mesh=self._mesh(), in_specs=P("dp"), out_specs=P("dp")))
        np.testing.assert_allclose(np.asarray(f(x)), np.asarray(y_ref),
                                   rtol=1e-5, atol=1e-5)
