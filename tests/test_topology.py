"""Topology/init tests — parity with the reference's rank/size assertions
(test/test_tensorflow.py:44-57 ``test_horovod_rank``/``test_horovod_size``
against the launcher env)."""

import jax
import pytest

import horovod_tpu as hvd


def test_size_matches_devices():
    assert hvd.size() == len(jax.devices()) == 8


def test_local_size_single_process():
    assert hvd.local_size() == 8
    assert hvd.process_count() == 1
    assert hvd.process_rank() == 0


def test_rank_is_leader_device():
    assert hvd.rank() == 0
    assert hvd.local_rank() == 0


def test_mesh_axes():
    m = hvd.mesh()
    assert m.axis_names == ("dp",)
    assert m.devices.size == 8
    hm = hvd.hierarchical_mesh()
    assert hm.axis_names == ("dcn", "ici")
    assert hm.devices.size == 8


def test_mpi_threads_supported():
    assert hvd.mpi_threads_supported() is True


def test_uninitialized_raises():
    # A pristine module must raise before init (common/__init__.py:90-154).
    import horovod_tpu.topology as topo
    saved = topo._topology
    topo._topology = None
    try:
        with pytest.raises(hvd.NotInitializedError):
            hvd.rank()
        with pytest.raises(hvd.NotInitializedError):
            hvd.size()
    finally:
        topo._topology = saved


def test_init_idempotent():
    t1 = hvd.init()
    t2 = hvd.init()
    assert t1 is t2
