"""Flight-recorder overhead guard (slow tier) — the always-on ring
buffer must stay invisible: ``bench_engine.py --recorder`` A/Bs a
2-process fused-allreduce + StepTimer loop with recording enabled vs
disabled (the BENCH_METRICS in-process interleaved method, p25 of
pooled per-step wall times) and this guard holds the step-time
overhead under 1%, regenerating ``BENCH_RECORDER.json``.

One re-measure is allowed before failing — a shared CI box can stay
saturated through one window (the BENCH_METRICS precedent)."""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

BUDGET = 0.01


def _run_bench(out_path: str, rounds: int) -> dict:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "bench_engine.py"),
         "--recorder", "--recorder-rounds", str(rounds),
         "--out", out_path],
        capture_output=True, text=True, timeout=600, cwd=root)
    assert proc.returncode == 0, proc.stderr[-3000:]
    return json.loads(open(out_path).read())


def test_recorder_overhead_under_1_percent(tmp_path):
    out = tmp_path / "bench_recorder.json"
    result = _run_bench(str(out), rounds=6)
    if result["overhead_frac"] >= BUDGET:   # one re-measure
        result = _run_bench(str(out), rounds=6)

    # Regenerate the committed artifact from the accepted run.
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(root, "BENCH_RECORDER.json"), "w") as f:
        json.dump(result, f, indent=2, sort_keys=True)
        f.write("\n")

    assert result["rows"]["recorder_on"]["step_time_ms"] > 0
    assert result["overhead_frac"] < BUDGET, (
        f"always-on flight recorder cost {result['overhead_frac']:.2%} "
        f"of the 2-process step time (on "
        f"{result['rows']['recorder_on']['step_time_ms']} ms vs off "
        f"{result['rows']['recorder_off']['step_time_ms']} ms; "
        f"budget {BUDGET:.0%})")
