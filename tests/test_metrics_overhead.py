"""Metrics overhead guard (slow tier) — the registry's contract is
"near-zero cost": run the fused-allreduce hot loop with metrics enabled
vs. disabled and hold the wall-clock overhead under 3%, writing
``BENCH_METRICS.json`` (seeded tensor contents; same artifact shape as
``BENCH_COMPRESSION.json``).

Methodology: the two modes run INTERLEAVED repeats with ALTERNATING
order (A/B, B/A, ...) so machine drift and cache-warming hit both modes
equally; each step is timed individually and the per-mode estimate is
the 25th percentile of the pooled per-step times — multi-millisecond
scheduler/XLA-dispatch hiccups land in the upper tail, while the
metrics cost, being systematic, shifts the whole distribution. One
re-measure is allowed before failing (a shared CI box can stay
saturated through one window). Measured mutator costs are ~0.3 µs per
counter inc / ~0.5 µs per histogram observe against a multi-millisecond
fused step, so a persistent >3% reading indicates a real hot-path
regression, not noise."""

import json
import os
import time

import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.observability import enabled, set_enabled

pytestmark = pytest.mark.slow

REPEATS = 8
STEPS = 80
N_TENSORS = 8
N_ELEMENTS = 1024


def _hot_loop(tensors, steps: int) -> list:
    """The eager engine's fused-allreduce hot path: burst-enqueue the
    group, wait all — the per-step pattern of a synchronous training
    loop (and the path every metric hook sits on: enqueue accounting,
    drain, phase histograms, group execution). Returns per-step wall
    times."""
    from horovod_tpu.ops import collective as _coll
    eng = _coll.engine()
    out = []
    for step in range(steps):
        t0 = time.perf_counter()
        with eng.burst():
            handles = [
                hvd.allreduce_async(t, average=True,
                                    name=f"bench.metrics.{step}.{i}")
                for i, t in enumerate(tensors)]
        for h in handles:
            h.wait()
        out.append(time.perf_counter() - t0)
    return out


def _measure(tensors):
    times = {"enabled": [], "disabled": []}
    try:
        for rep in range(REPEATS):
            order = (True, False) if rep % 2 == 0 else (False, True)
            for mode in order:
                set_enabled(mode)
                times["enabled" if mode else "disabled"].extend(
                    _hot_loop(tensors, STEPS))
    finally:
        set_enabled(True)
    t_on = float(np.percentile(times["enabled"], 25))
    t_off = float(np.percentile(times["disabled"], 25))
    return t_on, t_off


def test_metrics_overhead_under_3_percent():
    rng = np.random.RandomState(0)
    tensors = [jnp.asarray(rng.standard_normal(N_ELEMENTS)
                           .astype(np.float32))
               for _ in range(N_TENSORS)]
    assert enabled(), "guard must A/B from the enabled default"

    _hot_loop(tensors, 10)         # warmup: compile + caches
    t_on, t_off = _measure(tensors)
    overhead = t_on / t_off - 1.0
    if overhead >= 0.03:           # one re-measure before failing
        t_on, t_off = _measure(tensors)
        overhead = t_on / t_off - 1.0

    out = {
        "metric": "metrics_overhead",
        "note": ("fused-allreduce hot loop, metrics enabled vs disabled; "
                 "p25 of pooled per-step wall times over interleaved "
                 "alternating repeats (wall-clock, informational); guard "
                 "asserts enabled/disabled < 1.03"),
        "steps": STEPS,
        "tensors_per_step": N_TENSORS,
        "elements_per_tensor": N_ELEMENTS,
        "repeats": REPEATS,
        "rows": {
            "enabled": {"step_time_ms": round(t_on * 1000.0, 4)},
            "disabled": {"step_time_ms": round(t_off * 1000.0, 4)},
        },
        "overhead_frac": round(overhead, 6),
    }
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(root, "BENCH_METRICS.json"), "w") as f:
        json.dump(out, f, indent=2, sort_keys=True)
        f.write("\n")

    assert overhead < 0.03, (
        f"metrics recording cost {overhead:.2%} of the hot loop "
        f"(p25 step time enabled {t_on * 1e3:.3f} ms vs disabled "
        f"{t_off * 1e3:.3f} ms; budget 3%)")
