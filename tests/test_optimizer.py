"""DistributedOptimizer tests — mirror of test_torch.py's
``test_horovod_optimizer`` end-to-end step and the gradient-hook semantics
(torch/__init__.py:95-151), recast for optax."""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd


def _loss(params, x, y):
    pred = x @ params["w"] + params["b"]
    return jnp.mean((pred - y) ** 2)


def test_eager_distributed_step_matches_local():
    """With identical data on every rank, a distributed step equals the
    single-process step (allreduce-average of identical grads is identity)."""
    params = {"w": jnp.ones((3, 1)), "b": jnp.zeros((1,))}
    x = jnp.arange(12.0).reshape(4, 3)
    y = jnp.ones((4, 1))

    grads = jax.grad(_loss)(params, x, y)

    opt = optax.sgd(0.1)
    dopt = hvd.DistributedOptimizer(opt)

    s_local = opt.init(params)
    u_local, _ = opt.update(grads, s_local, params)

    s_dist = dopt.init(params)
    u_dist, _ = dopt.update(grads, s_dist, params)

    for a, b in zip(jax.tree_util.tree_leaves(u_local),
                    jax.tree_util.tree_leaves(u_dist)):
        assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_allreduce_gradients_average_eager():
    grads = {"w": jnp.full((4,), 2.0), "b": jnp.full((2,), 4.0)}
    out = hvd.allreduce_gradients(grads, average=True)
    assert np.allclose(np.asarray(out["w"]), 2.0)
    assert np.allclose(np.asarray(out["b"]), 4.0)
    out = hvd.allreduce_gradients(grads, average=False)
    assert np.allclose(np.asarray(out["w"]), 2.0 * hvd.size())


def test_allreduce_gradients_in_shard_map():
    """In-jit path: grads computed per-shard, psum'd over the mesh axis —
    the TPU-idiomatic DistributedOptimizer lowering."""
    mesh = hvd.mesh()
    n = hvd.size()

    def per_shard(g):
        return hvd.allreduce_gradients(g, average=True, axis_name="dp")

    f = jax.jit(jax.shard_map(
        per_shard, mesh=mesh, in_specs=P("dp"), out_specs=P(),
        check_vma=False))
    x = jnp.arange(n, dtype=jnp.float32).reshape(n)
    out = f(x)
    assert np.allclose(np.asarray(out), x.mean())


def test_backward_passes_per_step_eager():
    """Gradient accumulation: only every Nth update applies
    (torch/__init__.py:71-73,114-130)."""
    params = {"w": jnp.zeros((2,))}
    dopt = hvd.DistributedOptimizer(optax.sgd(1.0),
                                    backward_passes_per_step=2)
    state = dopt.init(params)
    g = {"w": jnp.ones((2,))}

    u1, state = dopt.update(g, state, params)
    assert np.allclose(np.asarray(u1["w"]), 0.0)  # accumulating, no step
    u2, state = dopt.update(g, state, params)
    # mean of two grads of 1.0 = 1.0; sgd(1.0) update = -1.0
    assert np.allclose(np.asarray(u2["w"]), -1.0)


def test_distributed_step_in_jit_sharded_data():
    """Full jitted SPMD training step over sharded batch: grads come out of
    jnp.mean over the global batch (XLA inserts the collective); one step
    must equal the equivalent single-device step on the full batch."""
    mesh = hvd.mesh()
    n = hvd.size()
    params = {"w": jnp.ones((3, 1)), "b": jnp.zeros((1,))}
    opt = optax.sgd(0.1)

    rng = np.random.RandomState(0)
    x = rng.rand(n * 2, 3).astype(np.float32)
    y = rng.rand(n * 2, 1).astype(np.float32)

    xs = jax.device_put(x, NamedSharding(mesh, P("dp")))
    ys = jax.device_put(y, NamedSharding(mesh, P("dp")))

    @jax.jit
    def step(params, state, x, y):
        grads = jax.grad(_loss)(params, x, y)
        updates, state = opt.update(grads, state, params)
        return optax.apply_updates(params, updates), state

    p1, _ = step(params, opt.init(params), xs, ys)
    p2, _ = step(params, opt.init(params), jnp.asarray(x), jnp.asarray(y))
    for a, b in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p2)):
        assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_compression_fp16_roundtrip():
    x = jnp.asarray(np.linspace(-2, 2, 64), jnp.float32)
    out = hvd.allreduce(x, average=True, compression=hvd.Compression.fp16)
    assert out.dtype == jnp.float32
    assert np.allclose(np.asarray(out), np.asarray(x), atol=1e-2)


def test_compression_bf16_roundtrip():
    x = jnp.asarray(np.linspace(-2, 2, 64), jnp.float32)
    out = hvd.allreduce(x, average=True, compression=hvd.Compression.bf16)
    assert out.dtype == jnp.float32
    assert np.allclose(np.asarray(out), np.asarray(x), atol=2e-2)
