"""Keras-on-TensorFlow-backend integration, in a subprocess.

The in-process Keras backend is pinned to torch by tests/test_keras.py
(one backend per process in Keras 3), so the tensorflow-backend path —
Keras ``model.fit`` tracing the shim's allreduce through ``tf.function``
via the py_function bridge — runs in a fresh interpreter here. This is
the analogue of the reference's separate test_tensorflow_keras.py.
"""

import os
import subprocess
import sys
import textwrap

import pytest

SCRIPT = textwrap.dedent("""
    import os
    os.environ["KERAS_BACKEND"] = "tensorflow"
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import keras
    import horovod_tpu as hvd
    import horovod_tpu.keras as hvd_keras
    import horovod_tpu.tensorflow as hvd_tf

    hvd.init()
    assert hvd.size() == 8, hvd.size()

    keras.utils.set_random_seed(0)
    model = keras.Sequential([
        keras.layers.Input((8,)),
        keras.layers.Dense(16, activation="relu"),
        keras.layers.Dense(2),
    ])
    opt = hvd_keras.DistributedOptimizer(
        keras.optimizers.SGD(learning_rate=0.1))
    model.compile(optimizer=opt, loss="mse")   # default: tf.function traced
    x = np.random.rand(16, 8).astype("float32")
    y = np.random.rand(16, 2).astype("float32")
    before = [np.array(w) for w in model.get_weights()]
    model.fit(x, y, batch_size=8, epochs=1, verbose=0,
              callbacks=[
                  hvd_keras.callbacks.BroadcastGlobalVariablesCallback(0),
                  hvd_keras.callbacks.MetricAverageCallback(),
              ])
    after = model.get_weights()
    assert any(not np.allclose(b, a) for b, a in zip(before, after))

    # tf-shim DistributedOptimizer on a keras optimizer
    opt2 = hvd_tf.DistributedOptimizer(keras.optimizers.SGD(0.05))
    assert opt2._hvd_wrapped
    import tensorflow as tf
    v = tf.Variable([1.0, 2.0])
    with tf.GradientTape() as tape:
        loss = tf.reduce_sum(v * v)
    g = tape.gradient(loss, [v])
    opt2.apply_gradients(zip(g, [v]))
    assert not np.allclose(v.numpy(), [1.0, 2.0])
    print("KERAS_TF_OK")
""")


@pytest.mark.slow
def test_keras_tensorflow_backend_fit():
    env = dict(os.environ)
    env.pop("PYTEST_CURRENT_TEST", None)
    proc = subprocess.run(
        [sys.executable, "-c", SCRIPT], capture_output=True, text=True,
        timeout=540, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert "KERAS_TF_OK" in proc.stdout, (
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr[-4000:]}")
