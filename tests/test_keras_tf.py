"""Keras-on-TensorFlow-backend integration (reference
test_tensorflow_keras.py role).

The in-process Keras backend is pinned to torch by tests/test_keras.py
(one backend per process in Keras 3), so the tensorflow-backend path —
Keras ``model.fit`` tracing the shim's allreduce through ``tf.function``
via the py_function bridge — runs in a fresh interpreter. The subprocess
runs ONCE per module (it pays ~1 min of framework startup); each test
asserts its own marker from the captured output, so a failure names the
exact broken behavior:

  - the gradient path actually crosses the collective engine during
    ``model.fit`` (bridge-call counting), and training under the
    DistributedOptimizer matches plain SGD on the identical-rank SP mesh
    (allreduce-of-identical-grads must be the identity);
  - ``BroadcastGlobalVariablesCallback`` really broadcasts (op counted)
    and preserves root values;
  - ``MetricAverageCallback`` routes epoch metrics through allreduce;
  - the tf-shim ``DistributedOptimizer`` applies gradients;
  - the functional ``allreduce``/``allgather``/``broadcast`` API works
    on TF-backend tensors with the documented semantics.
"""

import os
import subprocess
import sys
import textwrap

import pytest

pytestmark = pytest.mark.slow

SCRIPT = textwrap.dedent("""
    import os
    os.environ["KERAS_BACKEND"] = "tensorflow"
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import keras
    import tensorflow as tf
    import horovod_tpu as hvd
    import horovod_tpu.keras as hvd_keras
    import horovod_tpu.tensorflow as hvd_tf
    from horovod_tpu import ops as _ops

    hvd.init()
    assert hvd.size() == 8, hvd.size()

    # Count engine submissions (the collective-in-the-path assertions).
    counts = {"allreduce": 0, "broadcast": 0}
    _orig_ar = _ops.allreduce_async
    _orig_bc = _ops.broadcast_async
    def _ar(t, **kw):
        counts["allreduce"] += 1
        return _orig_ar(t, **kw)
    def _bc(t, root_rank=0, **kw):
        counts["broadcast"] += 1
        return _orig_bc(t, root_rank=root_rank, **kw)
    _ops.allreduce_async = _ar
    _ops.broadcast_async = _bc
    import horovod_tpu.keras as hk
    hk._ops.allreduce_async = _ar
    hk._ops.broadcast_async = _bc

    def build():
        keras.utils.set_random_seed(0)
        m = keras.Sequential([
            keras.layers.Input((8,)),
            keras.layers.Dense(16, activation="relu"),
            keras.layers.Dense(2),
        ])
        return m

    x = np.random.RandomState(1).rand(16, 8).astype("float32")
    y = np.random.RandomState(2).rand(16, 2).astype("float32")

    # --- 1: gradient path crosses the engine AND matches plain SGD -----
    ref = build()
    ref.compile(optimizer=keras.optimizers.SGD(0.1), loss="mse")
    ref.fit(x, y, batch_size=8, epochs=1, shuffle=False, verbose=0)

    before_ar = counts["allreduce"]
    dist = build()
    dist.compile(optimizer=hvd_keras.DistributedOptimizer(
        keras.optimizers.SGD(learning_rate=0.1)), loss="mse")
    dist.fit(x, y, batch_size=8, epochs=1, shuffle=False, verbose=0)
    n_grad_ar = counts["allreduce"] - before_ar
    assert n_grad_ar >= 4, f"no collective in the fit path ({n_grad_ar})"
    for wr, wd in zip(ref.get_weights(), dist.get_weights()):
        # Every SP virtual rank sees identical data, so the averaged
        # gradient equals the local one: training must match plain SGD.
        assert np.allclose(wr, wd, atol=1e-5), (wr, wd)
    print("TFK1_GRAD_PATH_OK", n_grad_ar)

    # --- 2: broadcast callback really broadcasts all weights -----------
    before_bc = counts["broadcast"]
    m2 = build()
    m2.compile(optimizer=hvd_keras.DistributedOptimizer(
        keras.optimizers.SGD(0.1)), loss="mse")
    w_pre = [np.array(w) for w in m2.get_weights()]
    m2.fit(x, y, batch_size=8, epochs=1, verbose=0, steps_per_epoch=1,
           callbacks=[hvd_keras.callbacks.BroadcastGlobalVariablesCallback(
               0)])
    assert counts["broadcast"] - before_bc >= len(w_pre), counts
    print("TFK2_BROADCAST_OK", counts["broadcast"] - before_bc)

    # --- 3: metric averaging goes through allreduce --------------------
    m3 = build()
    m3.compile(optimizer=hvd_keras.DistributedOptimizer(
        keras.optimizers.SGD(0.1)), loss="mse", metrics=["mae"])
    before_ar = counts["allreduce"]
    hist = m3.fit(x, y, batch_size=8, epochs=1, verbose=0,
                  callbacks=[hvd_keras.callbacks.MetricAverageCallback()])
    # Gradient allreduces (4 weights) + one per logged metric at epoch
    # end (loss + mae).
    n_metric_ar = counts["allreduce"] - before_ar
    assert n_metric_ar >= 4 + 2, n_metric_ar
    assert np.isfinite(hist.history["loss"][0])
    print("TFK3_METRIC_AVG_OK", n_metric_ar)

    # --- 4: tf-shim DistributedOptimizer applies gradients -------------
    opt2 = hvd_tf.DistributedOptimizer(keras.optimizers.SGD(0.05))
    assert opt2._hvd_wrapped
    v = tf.Variable([1.0, 2.0])
    with tf.GradientTape() as tape:
        loss = tf.reduce_sum(v * v)
    g = tape.gradient(loss, [v])
    opt2.apply_gradients(zip(g, [v]))
    assert not np.allclose(v.numpy(), [1.0, 2.0])
    print("TFK4_TF_SHIM_OK")

    # --- 5: functional collectives on TF-backend tensors ----------------
    s = hvd_keras.allreduce(tf.constant([1.0, 2.0]), average=False)
    assert np.allclose(np.asarray(s), [8.0, 16.0]), s   # x size
    a = hvd_keras.allreduce(tf.constant([4.0]), average=True)
    assert np.allclose(np.asarray(a), [4.0]), a
    g8 = hvd_keras.allgather(tf.constant([[1.0, 2.0]]))
    assert np.asarray(g8).shape == (8, 2), g8
    b = hvd_keras.broadcast(tf.constant([3.0, 4.0]), root_rank=0)
    assert np.allclose(np.asarray(b), [3.0, 4.0]), b
    print("TFK5_FUNCTIONAL_OK")
""")


@pytest.fixture(scope="module")
def tf_backend_run():
    env = dict(os.environ)
    env.pop("PYTEST_CURRENT_TEST", None)
    proc = subprocess.run(
        [sys.executable, "-c", SCRIPT], capture_output=True, text=True,
        timeout=540, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    return proc


def _check(proc, marker):
    assert marker in proc.stdout, (
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr[-4000:]}")


def test_fit_gradient_path_uses_collective_and_matches_sgd(tf_backend_run):
    _check(tf_backend_run, "TFK1_GRAD_PATH_OK")


def test_broadcast_callback_broadcasts_all_weights(tf_backend_run):
    _check(tf_backend_run, "TFK2_BROADCAST_OK")


def test_metric_average_callback_allreduces_metrics(tf_backend_run):
    _check(tf_backend_run, "TFK3_METRIC_AVG_OK")


def test_tf_shim_distributed_optimizer_applies(tf_backend_run):
    _check(tf_backend_run, "TFK4_TF_SHIM_OK")


def test_functional_collectives_on_tf_tensors(tf_backend_run):
    _check(tf_backend_run, "TFK5_FUNCTIONAL_OK")
