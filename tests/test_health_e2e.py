"""Health-plane acceptance (ISSUE 15, docs/health.md) — slow tier.

1. Injected-degradation e2e: a 4-process job with a ``slow_h2d`` fault
   ramping mid-run on rank 1. The offending rank's own detector fires
   a ``step_time_regression`` alert within a few detector windows, and
   the alert is visible in all three durable surfaces: the
   flight-recorder dump, ``hvdtpu_health_alerts_total``, and the
   ``tools/health`` report rendered from the merged per-rank history
   files.
2. Baseline A/B: two real StepTimer training loops (BENCH_LM-style,
   real sampler, real files) — one with a 20% injected step-time
   regression. ``tools/health --baseline`` ranks step time as the top
   regression; two identical runs report no regressions.
"""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from horovod_tpu.runner.api import run as plain_run  # noqa: E402

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_BASE_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
    "HOROVOD_TPU_DISABLE_NATIVE": "1",
    "HOROVOD_CYCLE_TIME": "1",
}


def _make_degradation_worker():
    """Worker built inside a closure so cloudpickle ships it by value
    (the test module is not importable from the spawned workers)."""

    def worker(steps, fault_from):
        import time

        import jax.numpy as jnp

        import horovod_tpu as hvd
        from horovod_tpu.observability import StepTimer
        from horovod_tpu.observability import history as _history

        hvd.init()
        timer = StepTimer("e2e", batch_size=8)
        x = jnp.ones((64,), jnp.float32)
        for step in range(steps):
            with timer:
                # ONE collective per step so the fault injector's
                # enqueue tick counter == the step counter (slow_h2d
                # from_step=N ramps at step N exactly).
                hvd.allreduce(x, name=f"he2e.{step}", average=False)
                time.sleep(0.008)
        sampler = _history.sampler()
        if sampler is not None:
            sampler.final_flush()
        snap = hvd.metrics_snapshot(prefix="hvdtpu_health_")
        alerts = (snap.get("hvdtpu_health_alerts_total")
                  or {"values": {}})["values"]
        monitor = sampler.monitor if sampler is not None else None
        return {
            "rank": hvd.process_rank(),
            "alert_counts": alerts,
            "alerts": ([a.to_dict() for a in monitor.alerts]
                       if monitor is not None else []),
            "sampled": sampler is not None,
        }

    return worker


class TestInjectedDegradationE2E:
    def test_slow_h2d_fires_regression_alert_everywhere(self, tmp_path):
        """ACCEPTANCE: the alert lands in the flight recorder dump, in
        hvdtpu_health_alerts_total, and in the tools/health report
        from the merged history files — naming the offending rank."""
        hist = tmp_path / "hist"
        blackbox = tmp_path / "blackbox"
        steps, fault_from = 260, 110
        interval = 0.15
        env = dict(_BASE_ENV)
        env.update({
            "HOROVOD_TPU_HISTORY": str(hist),
            "HOROVOD_TPU_HISTORY_INTERVAL": str(interval),
            "HOROVOD_TPU_BLACKBOX": str(blackbox),
            # slow_h2d ramping mid-run on rank 1: ~10ms steps become
            # ~60ms — a 5x regression the EWMA must catch within a
            # few windows.
            "HOROVOD_TPU_FAULT_SPEC":
                f"rank=1:slow_h2d=50ms:from_step={fault_from}",
        })
        results = plain_run(_make_degradation_worker(),
                            args=(steps, fault_from), np=4,
                            extra_env=env, start_timeout=600)
        by_rank = {r["rank"]: r for r in results}
        assert all(r["sampled"] for r in results)

        # (1) The offending rank's own detector fired, naming itself.
        r1 = by_rank[1]
        reg = [a for a in r1["alerts"]
               if a["kind"] == "step_time_regression"]
        assert reg, f"rank 1 fired no regression alert: {r1['alerts']}"
        assert reg[0]["rank"] == 1
        assert reg[0]["value"] > reg[0]["baseline"] * 1.2
        # Within 3 detector windows of the live plane noticing: the
        # evidence window is bounded (EWMA warmup + a few samples),
        # not the whole run.
        key = 'kind="step_time_regression",severity="warning"'
        assert r1["alert_counts"].get(key, 0) >= 1

        # (2) Flight-recorder dump (exit dump carries the ring).
        dump = blackbox / "blackbox-rank1.jsonl"
        assert dump.exists()
        events = [json.loads(line) for line in open(dump)][1:]
        alert_events = [e for e in events if e.get("kind") == "alert"]
        assert any(e["alert"] == "step_time_regression"
                   and e["who"] == 1 for e in alert_events), \
            f"no alert event in rank 1's dump: {alert_events}"

        # (3) tools/health over the merged per-rank history files:
        # offline replay of the same detectors names rank 1.
        proc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.tools.health",
             str(hist), "--json", "--top", "100"],
            capture_output=True, text=True, timeout=300, cwd=ROOT)
        assert proc.returncode == 0, proc.stderr[-3000:]
        report = json.loads(proc.stdout)
        assert len(report["labels"]) == 4     # all four ranks merged
        tool_reg = [a for a in report["alerts"]
                    if a["kind"] == "step_time_regression"]
        assert any(a["label"] == "rank1" and a["rank"] == 1
                   for a in tool_reg), report["alerts"]
        # ... and ranks step time among the top regressions for rank1.
        top = [r for r in report["top_regressions"]
               if r["label"] == "rank1"]
        assert top and any("step_seconds" in r["series"] for r in top)

        # Human rendering mentions the verdict too.
        proc_txt = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.tools.health",
             str(hist)],
            capture_output=True, text=True, timeout=300, cwd=ROOT)
        assert proc_txt.returncode == 0
        assert "step_time_regression" in proc_txt.stdout


_LM_ARM_SCRIPT = r"""
import sys, time
import horovod_tpu  # noqa: F401  (registry import path)
from horovod_tpu.observability import StepTimer
from horovod_tpu.observability import history as _history

hist_dir, step_s = sys.argv[1], float(sys.argv[2])
timer = StepTimer("lm", batch_size=32)
sampler = _history.HistorySampler(hist_dir, "rank0", interval_s=0.05,
                                  meta=lambda: {"rank": 0, "world": 1,
                                                "clock_synced": True})
sampler.start()
for step in range(140):
    with timer:
        time.sleep(step_s)
sampler.stop()
print("DONE")
"""


class TestBaselineABE2E:
    def _run_arm(self, hist_dir, step_s):
        env = dict(os.environ)
        env.update(_BASE_ENV)
        proc = subprocess.run(
            [sys.executable, "-c", _LM_ARM_SCRIPT, str(hist_dir),
             str(step_s)],
            capture_output=True, text=True, timeout=300, cwd=ROOT,
            env=env)
        assert proc.returncode == 0, proc.stderr[-3000:]
        assert "DONE" in proc.stdout

    def _baseline_report(self, cur, base):
        proc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.tools.health",
             str(cur), "--baseline", str(base), "--json"],
            capture_output=True, text=True, timeout=300, cwd=ROOT)
        assert proc.returncode == 0, proc.stderr[-3000:]
        return json.loads(proc.stdout)

    def test_injected_20pct_regression_ranks_step_time_top(
            self, tmp_path):
        """ACCEPTANCE: tools/health --baseline on two BENCH_LM-style
        runs with a 20% injected step-time regression ranks step time
        as the top regression; identical runs report no alerts."""
        self._run_arm(tmp_path / "base", 0.010)
        self._run_arm(tmp_path / "slow", 0.012)
        self._run_arm(tmp_path / "same", 0.010)

        report = self._baseline_report(tmp_path / "slow",
                                       tmp_path / "base")
        b = report["baseline"]
        assert b["verdict"] == "regressions"
        # Step time tops the ranking (the |mean of hvdtpu_step_seconds
        # or its per-phase attribution twin — both ARE step time and
        # regressed identically; nothing else may outrank them).
        top = b["regressions"][0]
        assert top["series"].startswith("hvdtpu_step_")
        assert top["change_frac"] == pytest.approx(0.2, abs=0.06)
        step_rows = [r for r in b["regressions"]
                     if r["series"].startswith("hvdtpu_step_seconds")]
        assert step_rows, b["regressions"]
        assert step_rows[0]["change_frac"] == pytest.approx(
            0.2, abs=0.06)

        same = self._baseline_report(tmp_path / "same",
                                     tmp_path / "base")
        assert same["baseline"]["verdict"] == "no_regressions"
        # ... and the healthy arms fired no live detector alerts.
        assert same["alerts"] == []
