"""Producer-fence policy tests (VERDICT r3 item 2).

The eager engine used to block on EVERY input's producer before
launching a fused collective — the fix for an XLA CPU rendezvous
deadlock (two threads enqueueing mesh-wide programs with no global
order; observed 4-of-8 on this mesh), at the cost of compute/collective
overlap. The fence is now scoped to where the hazard exists: processes
addressing >1 device. These tests pin (a) the deadlock scenario stays
fixed on the multi-device mesh, (b) the fence is OFF for single-device
processes (the real-pod shape, where the overlap matters), (c) the env
override works both ways.
"""

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import horovod_tpu as hvd
from horovod_tpu.ops import collective


class TestFencePolicy:
    def test_fence_on_for_multi_device(self, monkeypatch):
        eng = collective.engine()
        monkeypatch.delenv("HOROVOD_TPU_PRODUCER_FENCE", raising=False)
        monkeypatch.setattr(eng, "_fence_decision", None)  # re-resolve
        assert jax.local_device_count() > 1  # conftest's 8-device mesh
        assert eng._fence_producers() is True

    def test_env_override(self, monkeypatch):
        """The knob is read-once (resolved on first use, like every
        other engine knob); tests reset the cached decision to exercise
        both values."""
        eng = collective.engine()
        monkeypatch.setenv("HOROVOD_TPU_PRODUCER_FENCE", "0")
        monkeypatch.setattr(eng, "_fence_decision", None)
        assert eng._fence_producers() is False
        monkeypatch.setenv("HOROVOD_TPU_PRODUCER_FENCE", "1")
        monkeypatch.setattr(eng, "_fence_decision", None)
        assert eng._fence_producers() is True
        # cached now: a mutated env no longer flips the decision
        monkeypatch.setenv("HOROVOD_TPU_PRODUCER_FENCE", "0")
        assert eng._fence_producers() is True

    def test_fence_off_for_single_device(self):
        """One device per process (the real-pod shape): launches land in
        one FIFO queue, rendezvous inversion is impossible, fence off —
        run in a subprocess with a 1-device platform."""
        script = r"""
import os
import jax
jax.config.update("jax_platforms", "cpu")
import horovod_tpu as hvd
from horovod_tpu.ops import collective
hvd.init()
assert jax.local_device_count() == 1
assert collective.engine()._fence_producers() is False
print("OK")
"""
        env = dict(os.environ)
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("HOROVOD_TPU_PRODUCER_FENCE", None)
        proc = subprocess.run(
            [sys.executable, "-c", script], env=env, capture_output=True,
            text=True, timeout=120,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "OK" in proc.stdout


class TestOrderedLaunch:
    """HOROVOD_TPU_ORDERED_LAUNCH prototype (VERDICT r4 next #4):
    enqueue-ordering under a process-global launch lock instead of the
    completion fence. The 4-of-8 producer-feeding rendezvous scenario
    must pass with it on; the unrelated-stream scenario still aborts
    even fully locked (measured, experiments/ordered_launch_hazard.log
    — PJRT CPU fans out post-call), which is why the fence remains the
    default."""

    def test_knob_default_off(self, monkeypatch):
        eng = collective.engine()
        monkeypatch.delenv("HOROVOD_TPU_ORDERED_LAUNCH", raising=False)
        monkeypatch.setattr(eng, "_ordered_decision", None)
        assert eng._ordered_launch() is False

    def test_launch_lock_reentrant_and_exported(self):
        import horovod_tpu.ops as ops
        with ops.launch_lock():
            with ops.launch_lock():   # reentrant by design
                pass

    def test_rendezvous_regression_with_ordered_launch_on(self):
        """The producer-feeding scenario under ordered-launch: producers
        wrapped in launch_lock(), engine launching under the same lock,
        no completion fence. Runs in a subprocess (the knob is read-once
        engine state)."""
        script = r"""
import os, time
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["HOROVOD_TPU_ORDERED_LAUNCH"] = "1"
os.environ["HOROVOD_TPU_PRODUCER_FENCE"] = "0"
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
import horovod_tpu as hvd
from horovod_tpu.ops import launch_lock
from jax.sharding import NamedSharding, PartitionSpec as P
hvd.init()
mesh = hvd.mesh()

@jax.jit
def producer(x, i):
    return jnp.tanh(x) * 0 + i

x = jax.device_put(jnp.ones((256,), jnp.float32), NamedSharding(mesh, P()))
for round_i in range(10):
    with launch_lock():
        ys = [producer(x, float(i)) for i in range(4)]
    hs = [hvd.allreduce_async(y, name=f"ol.{round_i}.{i}", average=False)
          for i, y in enumerate(ys)]
    for i, h in enumerate(hs):
        np.testing.assert_allclose(np.asarray(h.wait(timeout=30.0)),
                                   float(i) * hvd.size())
print("ORDERED_OK")
"""
        env = dict(os.environ)
        proc = subprocess.run(
            [sys.executable, "-c", script], env=env, capture_output=True,
            text=True, timeout=300,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "ORDERED_OK" in proc.stdout


class TestRendezvousScenario:
    def test_mesh_producers_feeding_eager_collectives(self):
        """The observed 4-of-8 deadlock scenario (VERDICT r2): a
        replicated mesh-wide jit PRODUCES the tensors, and its async
        dispatch is still fanning out across the per-device queues when
        the engine launches the fused collective on those outputs. The
        producer fence (active on this multi-device mesh) must retire
        the producer before the launch, so every round completes; a
        regression that drops the fence on multi-device wedges this
        test (XLA CPU aborts the rendezvous after its 40 s timeout).

        Scope note (measured, round 4): an UNRELATED mesh-wide jit
        stream running concurrently with eager collectives deadlocks
        regardless of the fence — no fence on producers can order two
        threads' unrelated launches. That pattern is outside the eager
        engine's contract on multi-device-per-process meshes (use the
        jit optimizer path); the fence's contract is exactly the
        producer-feeding pattern below."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = hvd.mesh()

        @jax.jit
        def producer(x, i):
            # replicated all-device program, like the replicated-param
            # train steps that fed eager allreduce_gradients when the
            # 4-of-8 deadlock was observed
            return jnp.tanh(x) * 0 + i

        x = jax.device_put(jnp.ones((256,), jnp.float32),
                           NamedSharding(mesh, P()))

        deadline = time.monotonic() + 120
        for round_i in range(10):
            assert time.monotonic() < deadline, "collective rounds wedged"
            # dispatch returns while the mesh-wide producer may still be
            # in flight; the engine must fence before its own launch
            ys = [producer(x, float(i)) for i in range(4)]
            hs = [hvd.allreduce_async(y, name=f"rdv.{round_i}.{i}",
                                      average=False)
                  for i, y in enumerate(ys)]
            outs = [h.wait(timeout=30.0) for h in hs]
            for i, o in enumerate(outs):
                np.testing.assert_allclose(np.asarray(o),
                                           float(i) * hvd.size())
