"""Checkpoint convention helpers: rank-0 save + broadcast-on-restore
(SURVEY.md §5.4 — the reference's restart recipe as one call each)."""

import numpy as np
import pytest

import jax.numpy as jnp

import horovod_tpu as hvd
from horovod_tpu.utils.checkpoint import restore_checkpoint, save_checkpoint


@pytest.fixture(autouse=True)
def _init():
    hvd.init()
    yield


def _state():
    return {"params": {"w": jnp.arange(6.0).reshape(2, 3),
                       "b": jnp.zeros(3)},
            "step": 7}


class TestCheckpoint:
    def test_save_restore_roundtrip(self, tmp_path):
        state = _state()
        out = save_checkpoint(state, str(tmp_path / "ckpt"))
        assert out is not None  # single process == rank 0
        restored = restore_checkpoint(str(tmp_path / "ckpt"))
        assert int(restored["step"]) == 7
        assert np.allclose(np.asarray(restored["params"]["w"]),
                           np.arange(6.0).reshape(2, 3))

    def test_stepped_checkpoints(self, tmp_path):
        state = _state()
        save_checkpoint(state, str(tmp_path / "run"), step=3)
        state["step"] = 9
        save_checkpoint(state, str(tmp_path / "run"), step=4)
        r3 = restore_checkpoint(str(tmp_path / "run"), step=3)
        r4 = restore_checkpoint(str(tmp_path / "run"), step=4)
        assert int(r3["step"]) == 7 and int(r4["step"]) == 9

    @pytest.mark.slow
    def test_multiprocess_restore_broadcasts(self, tmp_path):
        """Rank 0 reads the file; every rank resumes identical state."""
        from horovod_tpu.runner.api import run

        # Rank 0 writes a checkpoint up front (shared tmp filesystem).
        save_checkpoint(_state(), str(tmp_path / "mp"))

        def worker(path):
            import numpy as np

            import horovod_tpu as hvd
            from horovod_tpu.utils.checkpoint import restore_checkpoint

            hvd.init()
            state = restore_checkpoint(path)
            return (hvd.process_rank(), int(state["step"]),
                    float(np.asarray(state["params"]["w"]).sum()))

        env = {"JAX_PLATFORMS": "cpu",
               "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}
        results = run(worker, args=(str(tmp_path / "mp"),), np=2,
                      extra_env=env, start_timeout=300)
        assert sorted(r[0] for r in results) == [0, 1]
        for _, step, wsum in results:
            assert step == 7 and wsum == 15.0
