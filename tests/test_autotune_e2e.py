"""Global-autotuner end-to-end guards (slow tier, docs/autotune.md).

Two acceptance criteria live here:

  - COLD START: the successive-halving search over pipeline schedule x
    microbatch count (the bench_engine --autotune workload) must land
    within 5% of the hand-picked BENCH_PIPELINE best, with the
    converged config recorded in the flight recorder — and the
    deterministic half of BENCH_AUTOTUNE.json (search space, rung
    schedule, candidate/trial counts) must reproduce exactly, run over
    run, against the committed file.
  - GUARDED APPLY: a move that regresses measured step time is rolled
    back through the SAME coordinator-stamped mechanism that applied
    it, leaving the live fleet's knob (and its epoch history) at the
    pre-move value.
"""

import json
import os
import sys

import pytest

pytestmark = pytest.mark.slow

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestColdStartBench:
    @pytest.fixture(scope="class")
    def bench(self):
        sys.path.insert(0, _REPO)
        try:
            from bench_engine import run_autotune_bench
        finally:
            sys.path.pop(0)
        # base_budget=1 halves the committed file's per-rung windows but
        # leaves every deterministic field except the budget ladder
        # untouched — the reproducibility contract under test.
        return run_autotune_bench(base_budget=1)

    def test_converges_within_5pct_of_hand_picked(self, bench):
        m = bench["measured"]
        assert m["gap_to_best_frac"] <= 0.05, m
        assert m["within_5pct_of_hand_picked"] is True

    def test_converged_config_is_in_the_flight_recorder(self, bench):
        m = bench["measured"]
        assert m["flight_converged"] is True
        # The flight note's config string names the converged values.
        conv = m["flight_converged_config"]
        assert str(m["converged"]["pipeline_schedule"]) in conv
        assert str(m["converged"]["num_microbatches"]) in conv

    def test_deterministic_block_reproduces_committed_bench(self, bench):
        with open(os.path.join(_REPO, "BENCH_AUTOTUNE.json")) as f:
            committed = json.load(f)["deterministic"]
        det = bench["deterministic"]
        # Budget-independent fields must match the committed bench
        # exactly; the budget ladder scales with base_budget.
        for key in ("search_space", "constraint", "n_candidates", "eta",
                    "rungs", "trials_per_rung", "n_trials",
                    "hand_picked", "workload"):
            assert det[key] == committed[key], key
        assert det["budget_per_rung"] == [
            b // committed["base_budget"] * det["base_budget"]
            for b in committed["budget_per_rung"]]

    def test_trial_ledger_matches_the_rung_schedule(self, bench):
        det, m = bench["deterministic"], bench["measured"]
        per_rung = {}
        for t in m["trials"]:
            per_rung[t["rung"]] = per_rung.get(t["rung"], 0) + 1
        assert [per_rung[r] for r in sorted(per_rung)] \
            == det["trials_per_rung"]
        assert sum(per_rung.values()) == det["n_trials"]


class TestGuardedApplyRollback:
    def test_injected_regression_rolls_back_through_the_coordinator(self):
        """E2E across the real planes: the tuner applies fusion moves
        via coordinator RPC (epoch-stamped by the arbiter); an injected
        measurement regression trips the health guard; the rollback
        re-stamps the pre-move value so the fleet's authoritative knob
        ends where it started."""
        from horovod_tpu.autotune import (ApplyPlane, AutoTuner,
                                          default_registry)
        from horovod_tpu.observability import flight_recorder as _fr
        from horovod_tpu.ops.control_plane import (CoordinatorClient,
                                                   CoordinatorService)
        from horovod_tpu.runner.secret import make_secret_key

        svc = CoordinatorService(nproc=1, key=make_secret_key(),
                                 fusion_threshold=64 << 20, native=False)
        try:
            client = CoordinatorClient([("127.0.0.1", svc.port)],
                                       svc.key, 0)
            state = {"fusion_mb": 64}

            def set_fusion(mb):
                verdict = client.tuner_move("fusion_threshold_mb", mb)
                assert verdict["accepted"], verdict
                state["fusion_mb"] = mb

            def measure(budget):
                # Injected regression: ANY departure from the baseline
                # cap doubles measured step time.
                return 2.0 if state["fusion_mb"] != 64 else 1.0

            n0 = len(_fr.recorder()._snapshot())
            tuner = AutoTuner(
                registry=default_registry(
                    include=("fusion_threshold_mb",)),
                plane=ApplyPlane(set_fusion=set_fusion),
                measure=measure)
            moves = tuner.run()
            # Every candidate regressed; every move rolled back.
            assert [m.new for m in moves] == [16, 32, 128]
            assert all(m.outcome == "rolled_back" for m in moves)
            assert tuner.current["fusion_threshold_mb"] == 64
            # The fleet's authoritative knob is back at the pre-move
            # value, restored through the same epoch mechanism (the
            # history keeps every stamp; later entries win).
            assert svc.fusion_threshold == 64 << 20
            epochs = svc._fusion_epochs
            assert epochs[-1][1] == 64 << 20
            assert len(epochs) == 6  # 3 applies + 3 rollback restamps
            events = [p for _, kind, p in _fr.recorder()._snapshot()[n0:]
                      if kind == "autotune" and p[0] == "rollback"]
            assert len(events) == 3
        finally:
            svc.shutdown()
