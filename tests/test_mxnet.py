"""MXNet shim tests — structural mirror of the reference's test_mxnet.py
(449 LoC, 12 tests): dtype x dimension sweeps for the three collectives,
in-place variants, DistributedOptimizer update, broadcast_parameters for
dict and ParameterDict (with deferred-init skip).

Virtual-rank semantics (tests/test_ops.py): every device is a rank and
eager inputs are replicated, so allreduce(x, average=False) == size * x.
"""

import numpy as np
import pytest

import horovod_tpu as hvd
import horovod_tpu.mxnet as hvd_mx
from horovod_tpu.mxnet import nd
from horovod_tpu.mxnet.ndarray import DeferredInitializationError

SWEEP_DTYPES = [np.uint8, np.int8, np.int32, np.float16, np.float32]


@pytest.fixture(autouse=True)
def _init():
    hvd.init()
    yield


def _rand(shape, dtype):
    if np.issubdtype(dtype, np.integer):
        return nd.array(np.random.randint(0, 10, shape), dtype=dtype)
    return nd.array(np.random.rand(*shape), dtype=dtype)


class TestMXAllreduce:
    @pytest.mark.parametrize("dtype", SWEEP_DTYPES)
    @pytest.mark.parametrize("dim", [1, 2, 3])
    def test_allreduce_sum(self, dtype, dim):
        t = _rand([17] * dim, dtype)
        out = hvd_mx.allreduce(t, average=False)
        expected = t.asnumpy().astype(np.float64) * hvd.size()
        assert out.dtype == dtype
        tol = 1e-2 if dtype == np.float16 else 1e-5
        # Integer dtypes wrap identically on every rank; compare modulo.
        got = out.asnumpy().astype(np.float64)
        if np.issubdtype(dtype, np.integer):
            expected = expected.astype(dtype).astype(np.float64)
        assert np.allclose(got, expected, rtol=tol, atol=tol)

    def test_allreduce_average(self):
        t = nd.array(np.random.rand(5, 5), dtype=np.float32)
        out = hvd_mx.allreduce(t, average=True)
        assert np.allclose(out.asnumpy(), t.asnumpy(), rtol=1e-5, atol=1e-6)
        # input unmodified
        assert out is not t

    def test_allreduce_inplace(self):
        t = nd.array(np.ones((4, 4)), dtype=np.float32)
        ret = hvd_mx.allreduce_(t, average=False)
        assert ret is t
        assert np.allclose(t.asnumpy(), hvd.size() * np.ones((4, 4)))

    def test_allreduce_multi_fused(self):
        tensors = [nd.array(np.full((8,), i + 1.0), dtype=np.float32)
                   for i in range(5)]
        hvd_mx.allreduce_multi_(tensors, average=False, name_prefix="mx.mk")
        for i, t in enumerate(tensors):
            assert np.allclose(t.asnumpy(), hvd.size() * (i + 1.0))


class TestMXAllgather:
    @pytest.mark.parametrize("dtype", SWEEP_DTYPES)
    @pytest.mark.parametrize("dim", [1, 2, 3])
    def test_allgather(self, dtype, dim):
        t = _rand([17] * dim, dtype)
        out = hvd_mx.allgather(t)
        assert out.shape == (17 * hvd.size(),) + tuple([17] * (dim - 1))
        got = out.asnumpy()
        ref = t.asnumpy()
        for r in range(hvd.size()):
            assert np.array_equal(got[r * 17:(r + 1) * 17], ref)

    def test_allgather_64bit_exact(self):
        vals = np.array([[2 ** 40 + 3, -7], [1, 2 ** 52 + 1]], dtype=np.int64)
        out = hvd_mx.allgather(nd.array(vals, dtype=np.int64))
        assert out.dtype == np.int64
        assert np.array_equal(out.asnumpy()[:2], vals)


class TestMXBroadcast:
    @pytest.mark.parametrize("dtype", SWEEP_DTYPES)
    @pytest.mark.parametrize("dim", [1, 2, 3])
    def test_broadcast(self, dtype, dim):
        t = _rand([17] * dim, dtype)
        out = hvd_mx.broadcast(t, root_rank=0)
        assert out.dtype == dtype
        assert np.array_equal(out.asnumpy(), t.asnumpy())
        assert out is not t

    def test_broadcast_inplace(self):
        t = nd.array(np.arange(12.0).reshape(3, 4), dtype=np.float32)
        ref = t.asnumpy()
        ret = hvd_mx.broadcast_(t, root_rank=0)
        assert ret is t
        assert np.array_equal(t.asnumpy(), ref)

    def test_broadcast_float64_exact(self):
        vals = np.array([1e300, -2.5e-308, 3.14], dtype=np.float64)
        t = nd.array(vals, dtype=np.float64)
        out = hvd_mx.broadcast(t, root_rank=0)
        assert out.dtype == np.float64
        assert np.array_equal(out.asnumpy(), vals)


class _SGD:
    """MXNet-style optimizer stub: update(index, weight, grad, state)
    applies weight -= lr * grad (mx.optimizer.Optimizer surface)."""

    def __init__(self, learning_rate=0.1):
        self.learning_rate = learning_rate

    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        if isinstance(index, (tuple, list)):
            for w, g in zip(weight, grad):
                w[:] = w.asnumpy() - self.learning_rate * g.asnumpy()
        else:
            weight[:] = (weight.asnumpy()
                         - self.learning_rate * grad.asnumpy())

    def update_multi_precision(self, index, weight, grad, state):
        self.update(index, weight, grad, state)

    def set_learning_rate(self, lr):
        self.learning_rate = lr


class TestMXDistributedOptimizer:
    def test_update_averages_then_delegates(self):
        opt = hvd_mx.DistributedOptimizer(_SGD(learning_rate=0.5))
        w = nd.array(np.ones(4), dtype=np.float32)
        g = nd.array(np.full(4, 2.0), dtype=np.float32)
        opt.update(0, w, g, opt.create_state(0, w))
        # averaged grad == local grad under replication; w -= 0.5*2
        assert np.allclose(w.asnumpy(), np.zeros(4))
        assert np.allclose(g.asnumpy(), np.full(4, 2.0))

    def test_update_index_list(self):
        opt = hvd_mx.DistributedOptimizer(_SGD(learning_rate=1.0))
        ws = [nd.array(np.ones(3), dtype=np.float32) for _ in range(3)]
        gs = [nd.array(np.full(3, float(i)), dtype=np.float32)
              for i in range(3)]
        opt.update([0, 1, 2], ws, gs, [None] * 3)
        for i, w in enumerate(ws):
            assert np.allclose(w.asnumpy(), 1.0 - float(i))

    def test_getattr_delegates(self):
        opt = hvd_mx.DistributedOptimizer(_SGD(learning_rate=0.25))
        assert opt.learning_rate == 0.25
        opt.set_learning_rate(0.125)
        assert opt._optimizer.learning_rate == 0.125


class _Param:
    """gluon Parameter stub: data() returns the NDArray or raises
    DeferredInitializationError before init."""

    def __init__(self, arr=None):
        self._arr = arr

    def data(self):
        if self._arr is None:
            raise DeferredInitializationError("not initialized")
        return self._arr


class _ParamDict:
    """gluon ParameterDict stub — NOT a dict subclass (gluon's isn't):
    exposes items() yielding (name, Parameter)."""

    def __init__(self, params):
        self._params = params

    def items(self):
        return self._params.items()

    def __getitem__(self, k):
        return self._params[k]


class TestMXBroadcastParameters:
    def test_dict(self):
        params = {"b": nd.array(np.full(4, 2.0), dtype=np.float32),
                  "a": nd.array(np.arange(3.0), dtype=np.float32)}
        before = {k: v.asnumpy() for k, v in params.items()}
        hvd_mx.broadcast_parameters(params, root_rank=0)
        for k in params:
            assert np.array_equal(params[k].asnumpy(), before[k])

    def test_parameter_dict_with_deferred_init(self):
        pd = _ParamDict({
            "w": _Param(nd.array(np.ones(5), dtype=np.float32)),
            "deferred": _Param(None),
            "b": _Param(nd.array(np.zeros(2), dtype=np.float32)),
        })
        hvd_mx.broadcast_parameters(pd, root_rank=0)  # must not raise
        assert np.array_equal(pd["w"].data().asnumpy(), np.ones(5))

    def test_invalid_type_raises(self):
        with pytest.raises(ValueError, match="invalid params"):
            hvd_mx.broadcast_parameters([1, 2, 3])
