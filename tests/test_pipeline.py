"""Pipeline-schedule + hierarchical-reduction tests (docs/pipeline.md).

Numerics strategy mirrors test_parallel.py: every schedule's loss AND
stage gradients must match an unsharded single-program oracle (jax
autodiff through the composed stages) at rtol 1e-5, on pp=2 and pp=4 CPU
meshes with 4/8 microbatches; the hierarchical in-slice/cross-slice
reduction must match the flat allreduce it replaces."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from horovod_tpu.parallel import create_mesh
from horovod_tpu.parallel.mesh import axis_kinds, dcn_axes, ici_axes
from horovod_tpu.parallel.collectives import (cross_slice_bytes,
                                              hierarchical_psum)
from horovod_tpu.parallel.pipeline import (PipelineSchedule,
                                           pipeline_apply,
                                           pipeline_value_and_grad,
                                           schedule_info)
from horovod_tpu.parallel.train import build_train_step
from horovod_tpu.models import transformer as tfm


def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _loss_fn(y):
    return jnp.mean(y.astype(jnp.float32) ** 2)


def _make_stages(n_total, d, seed=0):
    rng = np.random.RandomState(seed)
    return [{"w": jnp.asarray(rng.randn(d, d), jnp.float32) * 0.5,
             "b": jnp.asarray(rng.randn(d), jnp.float32) * 0.1}
            for _ in range(n_total)]


def _reference(stages, x_mb):
    """Single-program oracle: autodiff through the composed stages."""
    def total(stages):
        losses = []
        for j in range(x_mb.shape[0]):
            h = x_mb[j]
            for p in stages:
                h = _stage_fn(p, h)
            losses.append(_loss_fn(h))
        return jnp.mean(jnp.asarray(losses))
    return jax.value_and_grad(total)(stages)


def _pack_stages(stages, n, V):
    """Per-rank packing: rank r holds chunk-stages v*n + r, leaves
    [n, V, ...] (V=1 leaves [n, ...])."""
    def pack(*ls):
        arr = jnp.stack(ls)                       # [n*V, ...] chunk order
        if V == 1:
            return arr
        return arr.reshape((V, n) + arr.shape[1:]).swapaxes(0, 1)
    return jax.tree_util.tree_map(pack, *stages)


def _run_pipeline(schedule, n, m, V=1, d=4, mb=2, seed=0):
    mesh = create_mesh(devices=jax.devices()[:n], pp=n)
    stages = _make_stages(n * V, d, seed)
    x = jnp.asarray(np.random.RandomState(100 + seed).randn(m, mb, d),
                    jnp.float32)
    packed = _pack_stages(stages, n, V)

    def run(p_local, x):
        p = jax.tree_util.tree_map(lambda l: l[0], p_local)
        loss, g = pipeline_value_and_grad(
            _stage_fn, _loss_fn, p, x, axis_name="pp",
            schedule=schedule, num_virtual=V)
        return loss, jax.tree_util.tree_map(lambda l: l[None], g)

    f = jax.jit(jax.shard_map(
        run, mesh=mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P("pp"), packed), P()),
        out_specs=(P(), P("pp")), check_vma=False))
    loss, grads = f(packed, x)
    ref_loss, ref_grads = _reference(stages, x)
    return loss, grads, ref_loss, ref_grads, stages


def _grad_errs(grads, ref_grads, n, V):
    errs = []
    for c in range(n * V):
        r, v = c % n, c // n
        got = jax.tree_util.tree_map(
            lambda l: l[r] if V == 1 else l[r][v], grads)
        ref = ref_grads[c]
        for a, b in zip(jax.tree_util.tree_leaves(got),
                        jax.tree_util.tree_leaves(ref)):
            denom = max(float(jnp.max(jnp.abs(b))), 1e-9)
            errs.append(float(jnp.max(jnp.abs(a - b))) / denom)
    return max(errs)


class TestScheduleInfo:
    """Static tick/bubble accounting — the numbers the
    hvdtpu_pipeline_bubble_share gauge and BENCH_PIPELINE.json report."""

    @pytest.mark.parametrize("n", [2, 4])
    @pytest.mark.parametrize("m", [4, 8, 16])
    def test_bubble_ordering(self, n, m):
        g = schedule_info("gpipe", n, m).bubble_share
        o = schedule_info("1f1b", n, m).bubble_share
        i = schedule_info("interleaved", n, m,
                          num_virtual=2).bubble_share
        assert i < o < g

    def test_bubble_shrinks_with_microbatches(self):
        for sched, kw in [("gpipe", {}), ("1f1b", {}),
                          ("interleaved", {"num_virtual": 2}),
                          ("zb-h1", {})]:
            shares = [schedule_info(sched, 4, m, **kw).bubble_share
                      for m in (4, 8, 16, 32)]
            assert shares == sorted(shares, reverse=True), (sched, shares)

    def test_zb_h1_closed_form(self):
        # Backward split cB = cBx + cBw (even halves): only cBx rides
        # the fill/drain skew, so with cB=2 the bubble is
        # 2(n-1)/(3m + 2(n-1)) — 1/3 at n=m=4 vs 1f1b's 3/7.
        s = schedule_info("zb-h1", 4, 4)
        assert s.bubble_share == pytest.approx(1 / 3)
        assert s.ticks == {"warmup": 3, "steady": 4, "drain": 3}
        assert schedule_info("zb-h1", 4, 16).bubble_share == \
            pytest.approx(6 / 54)

    @pytest.mark.parametrize("n", [2, 4])
    @pytest.mark.parametrize("m", [4, 8, 16])
    def test_zb_h1_strictly_below_1f1b(self, n, m):
        # The acceptance bar: at equal microbatch counts the static
        # bubble is STRICTLY below 1f1b's for every n > 1.
        zb = schedule_info("zb-h1", n, m).bubble_share
        o = schedule_info("1f1b", n, m).bubble_share
        assert zb < o, (n, m, zb, o)

    def test_1f1b_closed_form(self):
        # Residual stashing removes the recompute: bubble is exactly
        # the fill fraction (n-1)/(m+n-1).
        s = schedule_info("1f1b", 4, 12)
        assert s.bubble_share == pytest.approx(3 / 15)
        i = schedule_info("interleaved", 4, 12, num_virtual=3)
        assert i.bubble_share == pytest.approx(3 / 39)

    def test_tick_budgets(self):
        s = schedule_info("1f1b", 4, 8)
        assert s.ticks == {"warmup": 3, "steady": 8, "drain": 3}
        i = schedule_info("interleaved", 2, 4, num_virtual=3)
        assert i.ticks == {"warmup": 5, "steady": 8, "drain": 5}
        g = schedule_info("gpipe", 4, 8)
        assert g.ticks["warmup"] == g.ticks["drain"] == 11

    def test_validation(self):
        with pytest.raises(ValueError, match="unknown"):
            schedule_info("dualpipe", 4, 8)
        with pytest.raises(ValueError, match="zb-h1"):
            schedule_info("zb-h1", 4, 2)
        with pytest.raises(ValueError, match="multiple"):
            schedule_info("interleaved", 4, 6, num_virtual=2)
        with pytest.raises(ValueError, match="multiple"):
            schedule_info("interleaved", 4, 2, num_virtual=2)
        with pytest.raises(ValueError, match="num_virtual"):
            schedule_info("interleaved", 4, 8, num_virtual=1)


class TestForwardPipeline:
    """pipeline_apply: the relay replication must equal both the old
    psum path and the unsharded composition."""

    @pytest.mark.parametrize("mode", ["relay", "psum"])
    def test_matches_composition(self, mode):
        n, m, d, mb = 4, 5, 4, 2
        mesh = create_mesh(devices=jax.devices()[:n], pp=n)
        stages = _make_stages(n, d)
        packed = _pack_stages(stages, n, 1)
        x = jnp.asarray(np.random.RandomState(7).randn(m, mb, d),
                        jnp.float32)

        def run(p_local, x):
            p = jax.tree_util.tree_map(lambda l: l[0], p_local)
            return pipeline_apply(_stage_fn, p, x, axis_name="pp",
                                  replicate_output=mode)

        f = jax.jit(jax.shard_map(
            run, mesh=mesh,
            in_specs=(jax.tree_util.tree_map(lambda _: P("pp"), packed),
                      P()),
            out_specs=P(), check_vma=False))
        out = f(packed, x)
        h = x
        for p in stages:
            h = jax.vmap(lambda xx, p=p: _stage_fn(p, xx))(h)
        assert float(jnp.max(jnp.abs(out - h))) < 1e-6

    def test_relay_equals_psum_bitwise(self):
        n, m, d, mb = 4, 6, 4, 2
        mesh = create_mesh(devices=jax.devices()[:n], pp=n)
        stages = _make_stages(n, d, seed=3)
        packed = _pack_stages(stages, n, 1)
        x = jnp.asarray(np.random.RandomState(8).randn(m, mb, d),
                        jnp.float32)
        outs = {}
        for mode in ("relay", "psum"):
            def run(p_local, x, mode=mode):
                p = jax.tree_util.tree_map(lambda l: l[0], p_local)
                return pipeline_apply(_stage_fn, p, x, axis_name="pp",
                                      replicate_output=mode)
            f = jax.jit(jax.shard_map(
                run, mesh=mesh,
                in_specs=(jax.tree_util.tree_map(lambda _: P("pp"),
                                                 packed), P()),
                out_specs=P(), check_vma=False))
            outs[mode] = np.asarray(f(packed, x))
        # Both replications move the SAME last-stage values (psum adds
        # exact zeros; relay copies) — bitwise equal.
        assert np.array_equal(outs["relay"], outs["psum"])

    def test_bad_replicate_kwarg(self):
        with pytest.raises(ValueError, match="relay"):
            mesh = create_mesh(devices=jax.devices()[:2], pp=2)
            jax.jit(jax.shard_map(
                lambda x: pipeline_apply(_stage_fn, {"w": x[0]}, x,
                                         replicate_output="bcast"),
                mesh=mesh, in_specs=(P(),), out_specs=P(),
                check_vma=False))(jnp.ones((2, 2, 2)))


class TestScheduleParity:
    """The flagship guarantee: every schedule's loss and per-stage
    gradients equal the single-program reference at rtol 1e-5."""

    @pytest.mark.parametrize("schedule", ["gpipe", "1f1b", "zb-h1"])
    @pytest.mark.parametrize("n,m", [(2, 4), (2, 8), (4, 4), (4, 8)])
    def test_matches_single_program(self, schedule, n, m):
        loss, grads, ref_loss, ref_grads, _ = _run_pipeline(
            schedule, n, m)
        assert abs(float(loss) - float(ref_loss)) <= \
            1e-5 * max(abs(float(ref_loss)), 1e-9)
        assert _grad_errs(grads, ref_grads, n, 1) < 1e-5

    @pytest.mark.parametrize("n,m,V", [(2, 4, 2), (4, 4, 2), (4, 8, 2),
                                       (2, 8, 3)])
    def test_interleaved_matches_single_program(self, n, m, V):
        loss, grads, ref_loss, ref_grads, _ = _run_pipeline(
            "interleaved", n, m, V=V)
        assert abs(float(loss) - float(ref_loss)) <= \
            1e-5 * max(abs(float(ref_loss)), 1e-9)
        assert _grad_errs(grads, ref_grads, n, V) < 1e-5

    def test_1f1b_fewer_microbatches_than_stages(self):
        loss, grads, ref_loss, ref_grads, _ = _run_pipeline("1f1b", 4, 3)
        assert abs(float(loss) - float(ref_loss)) <= 1e-5
        assert _grad_errs(grads, ref_grads, 4, 1) < 1e-5

    def test_schedules_agree_with_each_other(self):
        """gpipe, 1f1b and zb-h1 are the same math on different
        schedules — they must agree with each other as tightly as with
        the oracle (zb-h1's Bx and W come from the same VJP closure the
        fused backward calls)."""
        l1, g1, _, _, _ = _run_pipeline("gpipe", 4, 8, seed=5)
        for sched in ("1f1b", "zb-h1"):
            l2, g2, _, _, _ = _run_pipeline(sched, 4, 8, seed=5)
            assert abs(float(l1) - float(l2)) < 1e-6, sched
            for a, b in zip(jax.tree_util.tree_leaves(g1),
                            jax.tree_util.tree_leaves(g2)):
                assert float(jnp.max(jnp.abs(a - b))) < 1e-6, sched

    def test_zb_h1_needs_enough_microbatches(self):
        with pytest.raises(ValueError, match="zb-h1"):
            _run_pipeline("zb-h1", 4, 3)

    def test_unknown_schedule_rejected(self):
        mesh = create_mesh(devices=jax.devices()[:2], pp=2)
        with pytest.raises(ValueError, match="unknown"):
            jax.jit(jax.shard_map(
                lambda x: pipeline_value_and_grad(
                    _stage_fn, _loss_fn, {"w": jnp.eye(2)}, x,
                    schedule="dualpipe"),
                mesh=mesh, in_specs=(P(),), out_specs=(P(), P()),
                check_vma=False))(jnp.ones((2, 2, 2)))


def _head_loss(lp, y, tgt):
    return jnp.mean((y @ lp["w"] - tgt) ** 2)


def _run_pipeline_heads(schedule, n, m, d=4, mb=2, seed=0):
    """Pipeline run with the loss-head extensions armed: trainable
    loss_params, per-microbatch loss_aux targets, and input grads."""
    mesh = create_mesh(devices=jax.devices()[:n], pp=n)
    stages = _make_stages(n, d, seed)
    rng = np.random.RandomState(200 + seed)
    x = jnp.asarray(rng.randn(m, mb, d), jnp.float32)
    tgt = jnp.asarray(rng.randn(m, mb, d), jnp.float32)
    lp = {"w": jnp.asarray(rng.randn(d, d), jnp.float32) * 0.3}
    packed = _pack_stages(stages, n, 1)

    def run(p_local, lp, x, tgt):
        p = jax.tree_util.tree_map(lambda l: l[0], p_local)
        loss, g, extras = pipeline_value_and_grad(
            _stage_fn, _head_loss, p, x, axis_name="pp",
            schedule=schedule, loss_aux=tgt, loss_params=lp,
            return_input_grads=True)
        return (loss, jax.tree_util.tree_map(lambda l: l[None], g),
                extras["loss_params_grads"], extras["input_grads"])

    f = jax.jit(jax.shard_map(
        run, mesh=mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P("pp"), packed),
                  P(), P(), P()),
        out_specs=(P(), P("pp"), P(), P()), check_vma=False))
    loss, grads, lp_g, x_g = f(packed, lp, x, tgt)

    def total(stages, lp, x):
        losses = []
        for j in range(m):
            h = x[j]
            for p in stages:
                h = _stage_fn(p, h)
            losses.append(_head_loss(lp, h, tgt[j]))
        return jnp.mean(jnp.asarray(losses))

    ref_loss, (ref_g, ref_lp_g, ref_x_g) = jax.value_and_grad(
        total, argnums=(0, 1, 2))(stages, lp, x)
    return (loss, grads, lp_g, x_g), (ref_loss, ref_g, ref_lp_g, ref_x_g)


class TestPipelineLossHeads:
    """The loss-head extensions (docs/pipeline.md): trainable
    loss_params gradients psum'd from the last stage, per-microbatch
    loss_aux, and stage-0 input grads — on the fused AND the
    split-backward (zb-h1) schedules."""

    @pytest.mark.parametrize("schedule", ["gpipe", "1f1b", "zb-h1"])
    def test_heads_match_oracle(self, schedule):
        n, m = 4, 8
        (loss, grads, lp_g, x_g), (ref_loss, ref_g, ref_lp_g, ref_x_g) \
            = _run_pipeline_heads(schedule, n, m)
        assert abs(float(loss) - float(ref_loss)) <= \
            1e-5 * max(abs(float(ref_loss)), 1e-9)
        assert _grad_errs(grads, ref_g, n, 1) < 1e-5
        for a, b in zip(jax.tree_util.tree_leaves(lp_g),
                        jax.tree_util.tree_leaves(ref_lp_g)):
            denom = max(float(jnp.max(jnp.abs(b))), 1e-9)
            assert float(jnp.max(jnp.abs(a - b))) / denom < 1e-5
        denom = max(float(jnp.max(jnp.abs(ref_x_g))), 1e-9)
        assert float(jnp.max(jnp.abs(x_g - ref_x_g))) / denom < 1e-5


class TestPipelineTrainStep:
    """build_pipeline_train_step cuts the flagship transformer over
    'pp' automatically; one optimizer step must match the unsharded
    single-program step (tied embedding: input-path pullback + softmax
    head) at rtol 1e-5."""

    def _cfg(self):
        return tfm.TransformerConfig(
            vocab=64, d_model=16, n_heads=2, n_layers=4, d_ff=32,
            max_seq=8, dtype=jnp.float32, use_flash=False, remat=False)

    def _parity(self, schedule, n, V=1, m=4):
        import optax
        from horovod_tpu.parallel.train import (build_pipeline_train_step,
                                                from_pipeline_params,
                                                to_pipeline_params)
        cfg = self._cfg()
        B, S = 8, cfg.max_seq
        rng = np.random.RandomState(11)
        tokens = jnp.asarray(rng.randint(0, cfg.vocab, (B, S)), jnp.int32)
        targets = jnp.asarray(rng.randint(0, cfg.vocab, (B, S)),
                              jnp.int32)
        params = tfm.init_params(cfg, jax.random.PRNGKey(0))
        opt = optax.sgd(0.05)

        # Single-program oracle: one SGD step on the flat layout.
        loss_ref, grads_ref = jax.value_and_grad(
            lambda p: tfm.loss_fn(p, tokens, targets, cfg))(params)
        updates, _ = opt.update(grads_ref, opt.init(params), params)
        params_ref = optax.apply_updates(params, updates)

        mesh = create_mesh(devices=jax.devices()[:n], pp=n)
        make, shard_params, shard_batch = build_pipeline_train_step(
            cfg, mesh, opt, schedule=schedule, num_virtual=V)
        pparams = to_pipeline_params(cfg, params, n, V)
        opt_state = opt.init(pparams)
        step, _ = make(pparams, opt_state)
        pparams = shard_params(pparams)
        tok_mb = shard_batch(tokens.reshape(m, B // m, S))
        tgt_mb = shard_batch(targets.reshape(m, B // m, S))
        pparams, opt_state, loss = step(pparams, opt_state, tok_mb,
                                        tgt_mb)
        assert abs(float(loss) - float(loss_ref)) <= \
            1e-5 * max(abs(float(loss_ref)), 1e-9), schedule
        back = from_pipeline_params(cfg, jax.device_get(pparams), n, V)
        flat_a = jax.tree_util.tree_leaves(back)
        flat_b = jax.tree_util.tree_leaves(params_ref)
        for a, b in zip(flat_a, flat_b):
            denom = max(float(jnp.max(jnp.abs(b))), 1e-9)
            assert float(jnp.max(jnp.abs(a - b))) / denom < 1e-5, schedule

    @pytest.mark.parametrize("schedule", ["1f1b", "zb-h1"])
    def test_flagship_step_matches_single_program(self, schedule):
        self._parity(schedule, n=4)

    def test_interleaved_flagship_step(self):
        self._parity("interleaved", n=2, V=2)

    def test_rejects_non_pp_mesh(self):
        import optax
        from horovod_tpu.parallel.train import build_pipeline_train_step
        cfg = self._cfg()
        mesh = create_mesh(devices=jax.devices()[:4], dp=4)
        with pytest.raises(ValueError, match="pp"):
            build_pipeline_train_step(cfg, mesh, optax.sgd(0.1))

    def test_rejects_indivisible_layers(self):
        import optax
        from horovod_tpu.parallel.train import build_pipeline_train_step
        cfg = tfm.TransformerConfig(
            vocab=64, d_model=16, n_heads=2, n_layers=6, d_ff=32,
            max_seq=8, dtype=jnp.float32, use_flash=False, remat=False)
        mesh = create_mesh(devices=jax.devices()[:4], pp=4)
        with pytest.raises(ValueError, match="divide"):
            build_pipeline_train_step(cfg, mesh, optax.sgd(0.1))


class TestPipelineWithDataParallel:
    """pp × dp: per-dp-shard pipelines + gradient reduction over the
    data axes — hierarchical (in-slice 'dp' then cross-slice 'dcn')
    against the flat allreduce it replaces, identical gradients."""

    def _run(self, reduction):
        n, m, d, mb = 2, 4, 4, 2
        mesh = create_mesh(pp=n, dcn=2, dp=2)
        stages = _make_stages(n, d, seed=9)
        packed = _pack_stages(stages, n, 1)
        # Global batch: [m, dcn*dp*mb, d]; each data shard pipelines its
        # own microbatch slice.
        x = jnp.asarray(np.random.RandomState(11).randn(m, 4 * mb, d),
                        jnp.float32)

        def run(p_local, x_local):
            p = jax.tree_util.tree_map(lambda l: l[0], p_local)
            loss, g = pipeline_value_and_grad(
                _stage_fn, _loss_fn, p, x_local, axis_name="pp",
                schedule="1f1b")
            loss = lax.pmean(loss, ("dcn", "dp"))
            if reduction == "hier":
                g = jax.tree_util.tree_map(
                    lambda t: hierarchical_psum(t, "dp", "dcn",
                                                average=True), g)
            else:
                g = jax.tree_util.tree_map(
                    lambda t: lax.pmean(t, ("dcn", "dp")), g)
            return loss, jax.tree_util.tree_map(lambda l: l[None], g)

        f = jax.jit(jax.shard_map(
            run, mesh=mesh,
            in_specs=(jax.tree_util.tree_map(lambda _: P("pp"), packed),
                      P(None, ("dcn", "dp"))),
            out_specs=(P(), P("pp")), check_vma=False))
        loss, grads = f(packed, x)
        return float(loss), grads, stages, x

    def test_hierarchical_equals_flat(self):
        loss_h, g_h, _, _ = self._run("hier")
        loss_f, g_f, _, _ = self._run("flat")
        assert abs(loss_h - loss_f) < 1e-7
        for a, b in zip(jax.tree_util.tree_leaves(g_h),
                        jax.tree_util.tree_leaves(g_f)):
            assert float(jnp.max(jnp.abs(a - b))) < 1e-6

    def test_matches_oracle(self):
        loss_h, g_h, stages, x = self._run("hier")
        ref_loss, ref_grads = _reference(stages, x)
        assert abs(loss_h - float(ref_loss)) <= 1e-5
        assert _grad_errs(g_h, ref_grads, 2, 1) < 1e-5


class TestHierarchicalCollectives:
    def test_hierarchical_psum_equals_flat(self):
        mesh = create_mesh(dcn=2, dp=4)
        x = jnp.asarray(np.random.RandomState(0).randn(777), jnp.float32)
        flat = jax.jit(jax.shard_map(
            lambda v: lax.psum(v, ("dcn", "dp")), mesh=mesh,
            in_specs=P(), out_specs=P(), check_vma=False))(x)
        hier = jax.jit(jax.shard_map(
            lambda v: hierarchical_psum(v, "dp", "dcn"), mesh=mesh,
            in_specs=P(), out_specs=P(), check_vma=False))(x)
        err = float(jnp.max(jnp.abs(flat - hier)))
        assert err < 1e-5 * float(jnp.max(jnp.abs(flat)))

    def test_hierarchical_psum_wire_quantized(self):
        mesh = create_mesh(dcn=2, dp=4)
        x = jnp.asarray(np.random.RandomState(1).randn(512), jnp.float32)
        flat = jax.jit(jax.shard_map(
            lambda v: lax.psum(v, ("dcn", "dp")), mesh=mesh,
            in_specs=P(), out_specs=P(), check_vma=False))(x)
        q = jax.jit(jax.shard_map(
            lambda v: hierarchical_psum(v, "dp", "dcn", wire="int8x256"),
            mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False))(x)
        rel = float(jnp.max(jnp.abs(q - flat)) / jnp.max(jnp.abs(flat)))
        assert rel < 1e-2   # int8 wire tolerance (docs/compression.md)

    def test_average(self):
        mesh = create_mesh(dcn=2, dp=4)
        x = jnp.ones((64,), jnp.float32)
        out = jax.jit(jax.shard_map(
            lambda v: hierarchical_psum(v, "dp", "dcn", average=True),
            mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False))(x)
        np.testing.assert_allclose(np.asarray(out), 1.0, rtol=1e-6)

    def test_cross_slice_bytes(self):
        flat = cross_slice_bytes(1000, 4, hierarchical=False)
        hier = cross_slice_bytes(1000, 4)
        wired = cross_slice_bytes(1000, 4, wire="int8x256")
        assert flat == 4000
        assert hier == 1000            # 250 fp32 elements
        assert wired < hier < flat


class TestMeshTopology:
    def test_cpu_mesh_is_all_ici(self):
        mesh = create_mesh(dcn=2, dp=4)
        assert set(axis_kinds(mesh).values()) == {"ici"}
        assert dcn_axes(mesh) == ()
        assert set(ici_axes(mesh)) == {"dcn", "dp"}

    def test_env_override(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_TPU_DCN_AXES", "dcn")
        mesh = create_mesh(dcn=2, dp=4)
        assert axis_kinds(mesh) == {"dcn": "dcn", "dp": "ici"}
        assert dcn_axes(mesh) == ("dcn",)
        assert ici_axes(mesh) == ("dp",)


class TestTrainStepHierarchical:
    """build_train_step(dcn_axis=...): the two-stage reduction trains
    identically to the flat reduction and the single-device step."""

    def _setup(self):
        import optax
        cfg = tfm.TransformerConfig(
            vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq=32, dtype=jnp.float32, remat=False)
        params = tfm.init_params(cfg, jax.random.PRNGKey(0))
        tok = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, 64)
        tgt = jnp.roll(tok, -1, axis=1)
        return cfg, params, tok, tgt, optax.sgd(0.1)

    def _train(self, cfg, mesh, params, tok, tgt, opt, **kw):
        make, shard_p, shard_b = build_train_step(cfg, mesh, opt, **kw)
        state = opt.init(params)
        step, _ = make(params, state)
        p, _, loss = step(shard_p(params), state, shard_b(tok),
                          shard_b(tgt))
        return [np.asarray(l, np.float32)
                for l in jax.tree_util.tree_leaves(p)], float(loss)

    def test_hierarchical_equals_flat_and_single_device(self):
        cfg, params, tok, tgt, opt = self._setup()
        mesh = create_mesh(dcn=2, dp=4)
        l_hier, loss_h = self._train(cfg, mesh, params, tok, tgt, opt,
                                     dcn_axis="dcn")
        l_flat, loss_f = self._train(cfg, mesh, params, tok, tgt, opt,
                                     dcn_axis="dcn",
                                     dcn_hierarchical=False)
        assert abs(loss_h - loss_f) < 1e-5
        err = max(np.max(np.abs(a - b)) for a, b in zip(l_hier, l_flat))
        assert err < 1e-5, f"hier vs flat divergence {err}"
        mesh1 = create_mesh(devices=jax.devices()[:1], dp=1)
        l1, loss1 = self._train(cfg, params=params, mesh=mesh1, tok=tok,
                                tgt=tgt, opt=opt)
        assert abs(loss_h - loss1) < 1e-5
        err1 = max(np.max(np.abs(a - b)) for a, b in zip(l_hier, l1))
        assert err1 < 1e-4, f"hier vs single-device divergence {err1}"

    def test_auto_discovery_uses_env_override(self, monkeypatch):
        cfg, params, tok, tgt, opt = self._setup()
        mesh = create_mesh(dcn=2, dp=4)
        monkeypatch.setenv("HOROVOD_TPU_DCN_AXES", "dcn")
        l_auto, loss_a = self._train(cfg, mesh, params, tok, tgt, opt,
                                     dcn_axis="auto")
        l_expl, loss_e = self._train(cfg, mesh, params, tok, tgt, opt,
                                     dcn_axis="dcn")
        assert loss_a == loss_e
        for a, b in zip(l_auto, l_expl):
            assert np.array_equal(a, b)

    def test_bad_dcn_axis_rejected(self):
        cfg, params, tok, tgt, opt = self._setup()
        mesh = create_mesh(dcn=2, dp=4)
        with pytest.raises(ValueError, match="not a mesh axis"):
            build_train_step(cfg, mesh, opt, dcn_axis="nope")

    def test_zero1_rejected_with_dcn(self):
        from horovod_tpu.parallel.zero import zero1_init
        cfg, params, tok, tgt, opt = self._setup()
        mesh = create_mesh(dcn=2, dp=4)
        make, _, _ = build_train_step(cfg, mesh, opt, dcn_axis="dcn")
        with pytest.raises(ValueError, match="ZeRO-1"):
            make(params, zero1_init(opt, params, n_shards=4))


class TestPipelineObservability:
    def test_bubble_gauge_and_recorder_event(self):
        from horovod_tpu import metrics_snapshot
        from horovod_tpu.observability import flight_recorder as fr
        _run_pipeline("1f1b", 2, 4)
        snap = metrics_snapshot().get("hvdtpu_pipeline_bubble_share", {})
        vals = snap.get("values", {})
        got = {k: v for k, v in vals.items() if 'schedule="1f1b"' in k}
        assert got, vals
        expect = schedule_info("1f1b", 2, 4).bubble_share
        assert list(got.values())[0] == pytest.approx(expect, abs=1e-5)
        ticks = metrics_snapshot().get("hvdtpu_pipeline_ticks", {}).get(
            "values", {})
        assert any('phase="steady"' in k for k in ticks)
        events = [e for e in list(fr.recorder()._ring)
                  if e[1] == "pipeline"]
        assert events, "pipeline build must leave a flight-recorder event"
        payload = events[-1][2]
        assert payload[0] == "1f1b" and payload[1] == 2 and payload[2] == 4

    def test_postmortem_attributes_pipelined_step(self, tmp_path):
        from horovod_tpu.observability import flight_recorder as fr
        from horovod_tpu.tools import postmortem
        fr.reset()
        rec = fr.recorder()
        rec.configure(rank=0, world=1)
        rec.note("pipeline", ("1f1b", 4, 8, 1, 3, 8, 3, 0.2727))
        rec.note("step", (5,))
        path = rec.dump("exception", directory=str(tmp_path))
        dump = postmortem.load_dump(path)
        report = postmortem.analyze([dump])
        row = report["per_rank"]["0"]
        assert row["pipeline_schedule"] == "1f1b"
        assert "schedule 1f1b" in row["death_phase"]
        assert "3/8/3" in row["death_phase"]
        fr.reset()


@pytest.mark.slow
class TestBenchPipelineReproducible:
    def test_bench_pipeline_smoke_and_determinism(self, tmp_path):
        """bench_engine.py --pipeline regenerates BENCH_PIPELINE rows
        reproducibly (seeded, static bubble/byte accounting) and the
        acceptance ordering holds: 1f1b and interleaved bubble strictly
        below gpipe at every microbatch count, shrinking as microbatch
        count grows, numerics parity vs the single-program reference at
        rtol 1e-5, and the hierarchical reduction moving strictly fewer
        cross-slice bytes than flat with identical gradients."""
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        outs = []
        for i in range(2):
            out = tmp_path / f"bench{i}.json"
            subprocess.run(
                [sys.executable, os.path.join(root, "bench_engine.py"),
                 "--pipeline", "--pipeline-microbatches", "4,8",
                 "--out", str(out)],
                check=True, capture_output=True, text=True, timeout=600,
                cwd=root)
            outs.append(json.loads(out.read_text()))
        a, b = outs

        def strip_ms(obj):
            if isinstance(obj, dict):
                return {k: strip_ms(v) for k, v in obj.items()
                        if not k.endswith("_ms")}
            return obj

        assert strip_ms(a["bubble"]) == strip_ms(b["bubble"])
        for sched, rows in a["bubble"].items():
            for mkey, row in rows.items():
                assert row["parity_max_rel_err"] <= 1e-5, (sched, mkey)
        for m in ("4", "8"):
            gp = a["bubble"]["gpipe"][m]["bubble_share"]
            fb = a["bubble"]["1f1b"][m]["bubble_share"]
            il = a["bubble"]["interleaved"][m]["bubble_share"]
            assert il < fb < gp
        assert a["bubble"]["1f1b"]["8"]["bubble_share"] < \
            a["bubble"]["1f1b"]["4"]["bubble_share"]
        hier = a["hierarchical"]
        assert hier["hier"]["dcn_bytes_per_step"] < \
            hier["flat"]["dcn_bytes_per_step"]
        assert hier["hier_int8"]["dcn_bytes_per_step"] < \
            hier["hier"]["dcn_bytes_per_step"]
        assert hier["hier"]["grad_max_abs_diff_vs_flat"] < 1e-5
        assert strip_ms(hier) == strip_ms(b["hierarchical"])
