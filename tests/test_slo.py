"""SLO observability plane (docs/serving.md#slo): target resolution,
bounded tenant cardinality, verdict judging, the per-tenant label on
the serving families, the open-loop load generator's determinism and
drop accounting, and the goodput report tool. The fleet-level e2e
(tenant + verdict through router → replica → trace → flight recorder)
lives in test_fleet_e2e.py (slow tier)."""

import json
import threading
import time

import pytest

import jax
import jax.numpy as jnp

import horovod_tpu as hvd
from horovod_tpu.models import transformer as tfm
from horovod_tpu.parallel.mesh import create_mesh
from horovod_tpu.serving import (InferenceEngine, QueueFullError,
                                 ServingConfig)
from horovod_tpu.serving import loadgen as _loadgen
from horovod_tpu.serving import slo as _slo
from horovod_tpu.tools import slo as _slo_tool


@pytest.fixture(autouse=True)
def _fresh_slo_state():
    _slo._reset_policy()
    _slo._reset_tenants()
    yield
    _slo._reset_policy()
    _slo._reset_tenants()


# --------------------------------------------------------------------------
# Target parsing + policy resolution
# --------------------------------------------------------------------------

class TestParseSlo:
    def test_none_passes_through(self):
        assert _slo.parse_slo(None) is None

    def test_valid_dict(self):
        t = _slo.parse_slo({"ttft_ms": 500, "tpot_ms": 50.5})
        assert t.ttft_ms == 500.0 and t.tpot_ms == 50.5
        assert bool(t)

    def test_partial_dict(self):
        t = _slo.parse_slo({"ttft_ms": 100})
        assert t.ttft_ms == 100.0 and t.tpot_ms is None
        assert t.to_dict() == {"ttft_ms": 100.0}

    @pytest.mark.parametrize("bad", [
        "fast", 42, ["ttft_ms"],
        {"ttft_ms": 0}, {"ttft_ms": -1}, {"ttft_ms": True},
        {"ttft_ms": "500"}, {"deadline_ms": 5},
    ])
    def test_invalid_raises(self, bad):
        with pytest.raises(ValueError):
            _slo.parse_slo(bad)


class TestSloPolicy:
    def test_no_config_no_env_resolves_none(self, monkeypatch):
        monkeypatch.delenv("HOROVOD_TPU_SLO_TTFT_MS", raising=False)
        monkeypatch.delenv("HOROVOD_TPU_SLO_TPOT_MS", raising=False)
        monkeypatch.delenv("HOROVOD_TPU_SLO_CONFIG", raising=False)
        p = _slo.SloPolicy()
        assert p.resolve("anyone", None) is None

    def test_request_beats_tenant_beats_default(self, tmp_path,
                                                monkeypatch):
        cfg = tmp_path / "slo.json"
        cfg.write_text(json.dumps({
            "tenants": {"interactive": {"ttft_ms": 200}},
            "default": {"ttft_ms": 1000, "tpot_ms": 80},
        }))
        monkeypatch.delenv("HOROVOD_TPU_SLO_TTFT_MS", raising=False)
        monkeypatch.delenv("HOROVOD_TPU_SLO_TPOT_MS", raising=False)
        p = _slo.SloPolicy(config_path=str(cfg))
        # Field-wise overlay: tenant names ttft, default fills tpot.
        t = p.resolve("interactive", None)
        assert (t.ttft_ms, t.tpot_ms) == (200.0, 80.0)
        # Request field wins over both.
        t = p.resolve("interactive", {"ttft_ms": 50})
        assert (t.ttft_ms, t.tpot_ms) == (50.0, 80.0)
        # Unknown tenant falls through to default.
        t = p.resolve("stranger", None)
        assert (t.ttft_ms, t.tpot_ms) == (1000.0, 80.0)

    def test_env_fills_default(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_TPU_SLO_TTFT_MS", "750")
        monkeypatch.delenv("HOROVOD_TPU_SLO_TPOT_MS", raising=False)
        monkeypatch.delenv("HOROVOD_TPU_SLO_CONFIG", raising=False)
        p = _slo.SloPolicy()
        t = p.resolve(None, None)
        assert t.ttft_ms == 750.0 and t.tpot_ms is None

    def test_unreadable_config_is_ignored(self, tmp_path, monkeypatch):
        bad = tmp_path / "broken.json"
        bad.write_text("{not json")
        monkeypatch.delenv("HOROVOD_TPU_SLO_TTFT_MS", raising=False)
        monkeypatch.delenv("HOROVOD_TPU_SLO_TPOT_MS", raising=False)
        p = _slo.SloPolicy(config_path=str(bad))
        assert p.resolve("x", None) is None


class TestTenantCardinality:
    def test_cap_and_overflow(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_TPU_MAX_TENANTS", "2")
        assert _slo.resolve_tenant("a") == "a"
        assert _slo.resolve_tenant("b") == "b"
        assert _slo.resolve_tenant("c") == _slo.OVERFLOW_TENANT
        # Sticky: tenants that made the table keep their label, the
        # overflow mapping is remembered too.
        assert _slo.resolve_tenant("a") == "a"
        assert _slo.resolve_tenant("c") == _slo.OVERFLOW_TENANT

    def test_no_name_is_default(self):
        assert _slo.resolve_tenant(None) == _slo.DEFAULT_TENANT
        assert _slo.resolve_tenant("") == _slo.DEFAULT_TENANT

    def test_registry_cardinality_is_bounded(self, monkeypatch):
        """The satellite contract: a client fabricating tenant names
        cannot grow the registry — every name past the cap counts into
        the one "other" child."""
        monkeypatch.setenv("HOROVOD_TPU_MAX_TENANTS", "3")
        fam = _slo.metrics()["goodput"]
        for i in range(50):
            fam.labels(tenant=_slo.resolve_tenant(f"attacker{i}")).inc()
        vals = hvd.metrics_snapshot()["hvdtpu_slo_goodput_total"][
            "values"]
        named = {k for k in vals if "attacker" in k}
        # 3 named tenants + the overflow bucket, never 50 children.
        assert len(named) == 3
        assert vals['tenant="other"'] >= 47.0


class TestJudge:
    def test_met(self):
        t = _slo.SloTargets(ttft_ms=100, tpot_ms=50)
        v = _slo.judge(t, ttft_s=0.05, tpot_s=0.01)
        assert v["slo_met"] and not v["ttft_violation"]
        assert v["ttft_ms"] == 50.0
        assert v["target_ttft_ms"] == 100

    def test_ttft_miss(self):
        t = _slo.SloTargets(ttft_ms=10)
        v = _slo.judge(t, ttft_s=0.05, tpot_s=None)
        assert not v["slo_met"] and v["ttft_violation"]
        assert not v["tpot_violation"]

    def test_single_token_tpot_trivially_passes(self):
        t = _slo.SloTargets(tpot_ms=1)
        v = _slo.judge(t, ttft_s=0.5, tpot_s=None)
        assert v["slo_met"]

    def test_verdict_summary(self):
        assert _slo.verdict_summary(None) == "-"
        assert _slo.verdict_summary({"slo_met": True}) == "met"
        assert _slo.verdict_summary(
            {"slo_met": False, "ttft_violation": True,
             "tpot_violation": True}) == "ttft,tpot"


# --------------------------------------------------------------------------
# Engine: verdict stamping, per-tenant labels, shed accounting
# --------------------------------------------------------------------------

def _cfg(**over):
    kw = dict(vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
              max_seq=64, dtype=jnp.float32, remat=False)
    kw.update(over)
    return tfm.TransformerConfig(**kw)


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    params = tfm.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture(scope="module")
def mesh1():
    return create_mesh(devices=jax.devices()[:1], tp=1)


def _engine(params, cfg, mesh, **over):
    kw = dict(block_size=4, kv_blocks=40, max_batch_slots=4,
              max_queue=8, max_new_tokens=8, min_prefill_bucket=8)
    kw.update(over)
    return InferenceEngine(params, cfg, mesh, ServingConfig(**kw))


class TestEngineSlo:
    def test_untenanted_request_keeps_pretenant_shape(self, model,
                                                      mesh1):
        cfg, params = model
        eng = _engine(params, cfg, mesh1)
        req = eng.submit([1, 2, 3])
        eng.run_until_idle()
        assert req.status == "completed"
        assert req.tenant is None and req.slo_verdict is None
        snap = hvd.metrics_snapshot()
        assert 'status="completed"' in \
            snap["hvdtpu_serving_requests_total"]["values"]
        # The unlabeled ttft child took the observation.
        assert snap["hvdtpu_serving_ttft_seconds"]["values"][""][
            "count"] >= 1

    def test_met_and_missed_verdicts(self, model, mesh1):
        cfg, params = model
        eng = _engine(params, cfg, mesh1)
        ok = eng.submit([1, 2, 3], tenant="gold",
                        slo={"ttft_ms": 1e6, "tpot_ms": 1e6})
        bad = eng.submit([4, 5, 6], tenant="gold",
                         slo={"ttft_ms": 1e-4})
        eng.run_until_idle()
        assert ok.slo_verdict["slo_met"] is True
        assert bad.slo_verdict["slo_met"] is False
        assert bad.slo_verdict["ttft_violation"] is True
        assert bad.slo_verdict["target_ttft_ms"] == 1e-4
        snap = hvd.metrics_snapshot()
        good = snap["hvdtpu_slo_goodput_total"]["values"]
        viol = snap["hvdtpu_slo_violations_total"]["values"]
        assert good['tenant="gold"'] >= 1.0
        assert viol['reason="ttft",tenant="gold"'] >= 1.0
        # Tenant-labelled children on the serving histograms, and the
        # violation histogram's exemplar names the violating request.
        assert snap["hvdtpu_serving_ttft_seconds"]["values"][
            'tenant="gold"']["count"] >= 2
        ex = snap["hvdtpu_slo_violation_seconds"]["values"][
            'tenant="gold"'].get("exemplar")
        assert ex and ex["trace_id"] == bad.trace_id
        # Per-tenant token accounting followed the completions.
        assert snap["hvdtpu_slo_tokens_total"]["values"][
            'tenant="gold"'] >= 2.0

    def test_tenant_without_slo_is_counted_not_judged(self, model,
                                                      mesh1,
                                                      monkeypatch):
        monkeypatch.delenv("HOROVOD_TPU_SLO_TTFT_MS", raising=False)
        monkeypatch.delenv("HOROVOD_TPU_SLO_TPOT_MS", raising=False)
        monkeypatch.delenv("HOROVOD_TPU_SLO_CONFIG", raising=False)
        cfg, params = model
        eng = _engine(params, cfg, mesh1)
        req = eng.submit([7, 8, 9], tenant="bronze")
        eng.run_until_idle()
        assert req.tenant == "bronze"
        assert req.slo is None and req.slo_verdict is None
        snap = hvd.metrics_snapshot()
        assert snap["hvdtpu_serving_requests_total"]["values"][
            'status="completed",tenant="bronze"'] >= 1.0

    def test_queue_full_records_shed_with_tenant(self, model, mesh1):
        cfg, params = model
        eng = _engine(params, cfg, mesh1, max_queue=1)
        # Stall admission by never running the scheduler; fill the
        # queue, then overflow it with a tenanted request.
        eng.submit([1, 2, 3])
        with pytest.raises(QueueFullError):
            for i in range(10):
                eng.submit([1, 2, 3 + i], tenant="burst",
                           slo={"ttft_ms": 100})
        snap = hvd.metrics_snapshot()
        viol = snap["hvdtpu_slo_violations_total"]["values"]
        assert viol['reason="shed",tenant="burst"'] >= 1.0
        eng.run_until_idle()


# --------------------------------------------------------------------------
# Open-loop load generator
# --------------------------------------------------------------------------

_MIX = [
    _loadgen.TenantSpec("interactive", weight=3.0, prompt_len=(4, 8),
                        max_new_tokens=4, slo={"ttft_ms": 500}),
    _loadgen.TenantSpec("bulk", weight=1.0, prompt_len=(24, 32),
                        max_new_tokens=16),
]


class TestLoadgenSchedule:
    def test_fixed_seed_is_byte_identical(self):
        a = _loadgen.build_schedule(8.0, 3.0, 123, _MIX)
        b = _loadgen.build_schedule(8.0, 3.0, 123, _MIX)
        assert [x.to_dict() for x in a] == [x.to_dict() for x in b]
        assert _loadgen.schedule_checksum(a) \
            == _loadgen.schedule_checksum(b)
        # And a different seed is a different schedule.
        c = _loadgen.build_schedule(8.0, 3.0, 124, _MIX)
        assert _loadgen.schedule_checksum(c) \
            != _loadgen.schedule_checksum(a)

    def test_constant_process_spacing(self):
        a = _loadgen.build_schedule(4.0, 2.0, 7, _MIX,
                                    process="constant")
        gaps = {round(b.t_s - x.t_s, 6) for x, b in zip(a, a[1:])}
        assert gaps == {0.25}

    def test_mix_and_prompt_shapes(self):
        a = _loadgen.build_schedule(20.0, 5.0, 99, _MIX)
        tenants = {x.tenant for x in a}
        assert tenants == {"interactive", "bulk"}
        for x in a:
            spec = next(s for s in _MIX if s.name == x.tenant)
            lo, hi = spec.prompt_len
            assert lo <= len(x.tokens) <= hi
            assert x.slo == spec.slo

    def test_save_load_round_trip(self, tmp_path):
        a = _loadgen.build_schedule(6.0, 2.0, 11, _MIX)
        path = str(tmp_path / "sched.jsonl")
        _loadgen.save_schedule(a, path)
        b = _loadgen.load_schedule(path)
        assert b == a
        assert _loadgen.schedule_checksum(b) \
            == _loadgen.schedule_checksum(a)

    def test_validation(self):
        with pytest.raises(ValueError):
            _loadgen.build_schedule(0.0, 1.0, 1, _MIX)
        with pytest.raises(ValueError):
            _loadgen.build_schedule(1.0, 1.0, 1, [])
        with pytest.raises(ValueError):
            _loadgen.build_schedule(1.0, 1.0, 1, _MIX,
                                    process="uniform")


class TestLoadgenRun:
    def test_open_loop_drop_accounting_sums_to_offered(self):
        """A sender slower than the arrival rate with a 2-wide window
        MUST drop — and offered == sent + dropped, with every drop
        accounted by reason."""
        sched = _loadgen.build_schedule(50.0, 1.0, 5, _MIX,
                                        process="constant")
        release = threading.Event()

        def stuck_sender(arrival):
            release.wait(timeout=10.0)
            return {"ttft_ms": 1.0, "latency_ms": 2.0}

        t0 = time.perf_counter()
        # Fire the release after the schedule has fully played out.
        threading.Timer(1.2, release.set).start()
        run = _loadgen.run_schedule(sched, sender=stuck_sender,
                                    max_inflight=2, timeout_s=15.0)
        assert run["offered"] == len(sched)
        assert run["sent"] + run["dropped"] == run["offered"]
        assert run["dropped"] > 0
        assert run["drop_reasons"] == {
            _loadgen.DROP_REASON_INFLIGHT: run["dropped"]}
        dropped_rows = [r for r in run["results"]
                        if r["status"] == "dropped"]
        assert len(dropped_rows) == run["dropped"]
        assert all(r["drop_reason"] == _loadgen.DROP_REASON_INFLIGHT
                   for r in dropped_rows)
        # Open loop: the wall tracks the schedule, not the stuck
        # sender x offered (a closed loop would take ~offered/2 x wait).
        assert time.perf_counter() - t0 < 10.0

    def test_summarize_goodput_counts_drops_against_offered(self):
        sched = _loadgen.build_schedule(40.0, 1.0, 6, _MIX,
                                        process="constant")

        def fast_sender(arrival):
            out = {"ttft_ms": 5.0, "latency_ms": 9.0,
                   "tenant": arrival.tenant}
            if arrival.slo is not None:
                out["slo"] = {"slo_met": True}
            return out

        run = _loadgen.run_schedule(sched, sender=fast_sender,
                                    max_inflight=256, timeout_s=15.0)
        s = _loadgen.summarize(run)
        assert s["totals"]["offered"] == run["offered"]
        assert s["totals"]["dropped"] == 0
        assert s["totals"]["goodput_frac"] == 1.0
        offered = sum(t["offered"] for t in s["tenants"].values())
        assert offered == run["offered"]
        inter = s["tenants"]["interactive"]
        assert inter["slo_met"] == inter["completed"]
        assert inter["ttft_p99_ms"] == 5.0


# --------------------------------------------------------------------------
# Goodput report tool
# --------------------------------------------------------------------------

class TestSloTool:
    def _fake_run(self, goodput_frac, p99, rps, name):
        n = 20
        good = int(round(n * goodput_frac))
        results = []
        for i in range(n):
            met = i < good
            results.append({
                "tenant": "t", "t_s": i * 0.05,
                "status": "completed",
                "ttft_ms": p99 if not met else p99 / 10,
                "latency_ms": p99, "slo": {"slo_met": met}})
        return {"offered": n, "sent": n, "dropped": 0,
                "drop_reasons": {}, "wall_s": 1.0,
                "offered_rps": rps, "name": name,
                "results": results}

    def test_knee_detection(self, tmp_path):
        paths = []
        for i, (frac, p99, rps) in enumerate(
                [(1.0, 50, 4), (0.95, 200, 10), (0.5, 2000, 25)]):
            p = tmp_path / f"run{i}.json"
            p.write_text(json.dumps(
                self._fake_run(frac, p99, rps, f"rps{rps}")))
            paths.append(str(p))
        report = _slo_tool.build_report(paths, target_ttft_ms=500.0)
        assert [a["name"] for a in report["arms"]] \
            == ["rps4", "rps10", "rps25"]
        assert report["knee"]["name"] == "rps25"
        text = _slo_tool.format_report(report)
        assert "<-- knee" in text and "rps25" in text

    def test_no_knee(self, tmp_path):
        p = tmp_path / "run.json"
        p.write_text(json.dumps(self._fake_run(1.0, 50, 4, "rps4")))
        report = _slo_tool.build_report([str(p)],
                                        target_ttft_ms=500.0)
        assert report["knee"] is None
        assert "no knee" in _slo_tool.format_report(report)

    def test_baseline_regression_exit_code(self, tmp_path,
                                           capsys):
        cur = tmp_path / "cur.json"
        base = tmp_path / "base.json"
        cur.write_text(json.dumps(self._fake_run(0.6, 900, 10,
                                                 "rps10")))
        base.write_text(json.dumps(self._fake_run(1.0, 60, 10,
                                                  "rps10")))
        rc = _slo_tool.main([str(cur), "--baseline", str(base)])
        assert rc == 3
        assert "REGRESSED" in capsys.readouterr().out
        # And the other way round is clean (an improvement).
        rc = _slo_tool.main([str(base), "--baseline", str(cur)])
        assert rc == 0


# --------------------------------------------------------------------------
# Export: comma-separated prefix union (the fleet scrape shape)
# --------------------------------------------------------------------------

class TestPrefixUnion:
    def test_metrics_json_comma_prefix(self):
        import urllib.request

        from horovod_tpu.observability import MetricsServer
        from horovod_tpu.observability import registry as _reg
        _reg.registry().counter("hvdtpu_slotest_a_total", "x").inc()
        _reg.registry().counter("hvdtpu_slotest2_b_total", "x").inc()
        _reg.registry().counter("hvdtpu_slotest3_c_total", "x").inc()
        srv = MetricsServer(0)
        try:
            url = (f"http://127.0.0.1:{srv.port}/metrics.json"
                   f"?prefix=hvdtpu_slotest_,hvdtpu_slotest2_")
            with urllib.request.urlopen(url, timeout=10) as resp:
                snap = json.loads(resp.read())
            assert "hvdtpu_slotest_a_total" in snap
            assert "hvdtpu_slotest2_b_total" in snap
            assert "hvdtpu_slotest3_c_total" not in snap
        finally:
            srv.stop()
