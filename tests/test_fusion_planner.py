"""Fusion planner and executor-cache tests.

The single-pass bucket-by-fusion-key planner must reproduce the
reference's greedy look-ahead grouping (operations.cc:2149-2265) exactly
— same members, same order — without the O(n²) full rescan per group;
the executor must neither recompile nor re-transfer for steady-state
(same shapes, already-replicated) inputs.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import horovod_tpu as hvd
from horovod_tpu import executor as _exec
from horovod_tpu.ops import collective as _coll
from horovod_tpu.ops.collective import (ALLGATHER, ALLREDUCE, BROADCAST,
                                        _Request)


def _req(name, op=ALLREDUCE, n=16, dtype=np.float32, wire=None,
         sharded=False, root_rank=0, average=False, prescale=1.0,
         postscale=1.0, per_rank=None):
    tensor = None if per_rank is not None else np.zeros((n,), dtype)
    return _Request(name, op, tensor, handle=None, per_rank=per_rank,
                    root_rank=root_rank, average=average, prescale=prescale,
                    postscale=postscale, sharded=sharded, wire=wire)


def _reference_plan(batch, threshold):
    """The seed's greedy O(n²) planner, kept verbatim as the behavioral
    oracle (with the wire key the new planner also matches on)."""
    groups = []
    remaining = list(batch)
    while remaining:
        head = remaining.pop(0)
        group = [head]
        total = head.nbytes
        keep = []
        for req in remaining:
            if (req.op == head.op and req.dtype == head.dtype
                    and req.wire == head.wire
                    and req.sharded == head.sharded
                    and req.root_rank == head.root_rank
                    and req.average == head.average
                    and req.prescale == head.prescale
                    and req.postscale == head.postscale
                    and req.per_rank is None and head.per_rank is None
                    and total + req.nbytes <= threshold):
                group.append(req)
                total += req.nbytes
            else:
                keep.append(req)
        remaining = keep
        groups.append(group)
    return groups


def _names(groups):
    return [[r.name for r in g] for g in groups]


@pytest.fixture
def engine():
    eng = _coll.CollectiveEngine.__new__(_coll.CollectiveEngine)
    eng.fusion_threshold = 64 * 1024 * 1024
    return eng


class TestPlannerEquivalence:
    def test_mixed_dtypes_and_ops(self, engine):
        batch = [
            _req("a0", ALLREDUCE, 16, np.float32),
            _req("g0", ALLGATHER, 8, np.float32),
            _req("a1", ALLREDUCE, 16, np.float32),
            _req("i0", ALLREDUCE, 16, np.int32),
            _req("b0", BROADCAST, 4, np.float32, root_rank=2),
            _req("a2", ALLREDUCE, 16, np.float16),
            _req("b1", BROADCAST, 4, np.float32, root_rank=2),
            _req("i1", ALLREDUCE, 16, np.int32),
            _req("b2", BROADCAST, 4, np.float32, root_rank=1),
        ]
        got = _names(engine._plan_fusion(batch))
        want = _names(_reference_plan(batch, engine.fusion_threshold))
        assert got == want
        assert got == [["a0", "a1"], ["g0"], ["i0", "i1"], ["b0", "b1"],
                       ["a2"], ["b2"]]

    def test_wire_formats_do_not_cross_fuse(self, engine):
        batch = [
            _req("p0", n=64),
            _req("q0", n=64, wire="int8x256"),
            _req("p1", n=64),
            _req("q1", n=64, wire="int8x256"),
            _req("f0", n=64, wire="fp8x256"),
        ]
        got = _names(engine._plan_fusion(batch))
        assert got == [["p0", "p1"], ["q0", "q1"], ["f0"]]
        assert got == _names(_reference_plan(batch,
                                             engine.fusion_threshold))

    def test_threshold_look_ahead(self, engine):
        """The reference's look-ahead: a request skipped for size lets a
        LATER smaller request still join the earlier group."""
        engine.fusion_threshold = 5 * 4  # 5 fp32 elements
        batch = [_req("a", n=3), _req("big", n=4), _req("c", n=2)]
        got = _names(engine._plan_fusion(batch))
        want = _names(_reference_plan(batch, engine.fusion_threshold))
        assert got == want == [["a", "c"], ["big"]]

    def test_oversized_head_is_singleton(self, engine):
        engine.fusion_threshold = 4
        batch = [_req("huge", n=100), _req("t0", n=1), _req("t1", n=100)]
        got = _names(engine._plan_fusion(batch))
        want = _names(_reference_plan(batch, engine.fusion_threshold))
        assert got == want

    def test_per_rank_never_fuses(self, engine):
        batch = [
            _req("a0", ALLGATHER, 8),
            _req("r0", ALLGATHER, per_rank=[np.zeros((2,), np.float32),
                                            np.zeros((3,), np.float32)]),
            _req("a1", ALLGATHER, 8),
        ]
        got = _names(engine._plan_fusion(batch))
        want = _names(_reference_plan(batch, engine.fusion_threshold))
        assert got == want == [["a0", "a1"], ["r0"]]

    @pytest.mark.parametrize("seed", range(6))
    def test_randomized_equivalence(self, engine, seed):
        rng = np.random.RandomState(seed)
        engine.fusion_threshold = int(rng.choice([64, 512, 4096, 1 << 26]))
        batch = []
        for i in range(rng.randint(1, 60)):
            kind = rng.randint(4)
            if kind == 3 and rng.rand() < 0.2:
                batch.append(_req(
                    f"r{i}", ALLGATHER,
                    per_rank=[np.zeros((rng.randint(1, 4),), np.float32)
                              for _ in range(2)]))
                continue
            batch.append(_req(
                f"t{i}",
                op=[ALLREDUCE, ALLGATHER, BROADCAST][rng.randint(3)],
                n=int(rng.randint(1, 200)),
                dtype=[np.float32, np.float16, np.int32][rng.randint(3)],
                wire=[None, "int8x256", "fp8x256"][rng.randint(3)],
                root_rank=int(rng.randint(2)),
                average=bool(rng.randint(2)),
                prescale=float(rng.choice([1.0, 0.5])),
            ))
        got = _names(engine._plan_fusion(batch))
        want = _names(_reference_plan(batch, engine.fusion_threshold))
        assert got == want

    def test_wire_bytes_counted_against_threshold(self, engine):
        """Planning counts WIRE bytes: two 1024-element fp32 tensors are
        8 KiB logical but ~2 KiB on the int8 wire — a threshold between
        the two must fuse the quantized pair and split the fp32 pair."""
        wire_pair_bytes = 2 * (1024 + 16 * 4)
        engine.fusion_threshold = wire_pair_bytes
        quantized = [_req("q0", n=1024, wire="int8x256"),
                     _req("q1", n=1024, wire="int8x256")]
        plain = [_req("p0", n=1024), _req("p1", n=1024)]
        assert _names(engine._plan_fusion(quantized)) == [["q0", "q1"]]
        assert _names(engine._plan_fusion(plain)) == [["p0"], ["p1"]]


class TestExecutorSteadyState:
    def test_cache_and_device_put_counters(self):
        """Second identical fused allreduce: program cache hit, and
        already-replicated inputs (the previous outputs) skip
        device_put entirely — the steady-state hot loop is transfer- and
        compile-free."""
        ex = _exec.CollectiveExecutor(mesh=hvd.mesh())
        xs = [jnp.full((64,), float(i + 1)) for i in range(3)]
        out1 = ex.allreduce_fused(xs)
        misses1, puts1 = ex.cache_misses, ex.device_put_count
        assert misses1 >= 1 and puts1 == len(xs)
        out2 = ex.allreduce_fused(out1)
        assert ex.cache_misses == misses1          # no recompile
        assert ex.device_put_count == puts1        # no re-transfer
        assert ex.cache_hits >= 1
        np.testing.assert_allclose(
            np.asarray(out2[0]), np.asarray(xs[0]) * hvd.size() ** 2)

    def test_wire_key_separates_programs(self):
        ex = _exec.CollectiveExecutor(mesh=hvd.mesh())
        xs = [jnp.full((512,), 0.5)]
        ex.allreduce_fused(xs)
        m = ex.cache_misses
        ex.allreduce_fused(xs, wire="int8x256")
        assert ex.cache_misses == m + 1            # distinct program
        ex.allreduce_fused(xs, wire="int8x256")
        assert ex.cache_misses == m + 1            # then cached
