"""Shared-memory data plane unit tests (ops/shm_transport.py) — the
same-host fast path for eager fused collectives (the reference's MPI
shared-memory CPU path). Protocol-level tests run the per-rank state
machines in threads; the cross-process integration runs in
tests/test_multiprocess.py (the runner exports HOROVOD_TPU_ALL_LOCAL=1,
so every all-local MP test exercises this plane end to end).
"""

import threading

import numpy as np
import pytest

from horovod_tpu.ops.shm_transport import ShmTransport, ShmTimeout


def _fleet(n, tag):
    return [ShmTransport(r, n, tag=tag) for r in range(n)]


def _run_all(fns):
    out = [None] * len(fns)
    errs = []

    def call(i, fn):
        try:
            out[i] = fn()
        except BaseException as e:  # pragma: no cover - surfaced below
            errs.append(e)

    ts = [threading.Thread(target=call, args=(i, fn))
          for i, fn in enumerate(fns)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(30)
    if errs:
        raise errs[0]
    return out


class TestShmTransport:
    def test_allreduce_sums_across_ranks(self):
        fleet = _fleet(4, "test-ar")
        try:
            bufs = [np.full((1024,), float(r + 1), np.float32)
                    for r in range(4)]
            outs = _run_all([lambda t=t, b=b: t.allreduce(b)
                             for t, b in zip(fleet, bufs)])
            for o in outs:
                assert np.allclose(o, 1 + 2 + 3 + 4)
        finally:
            for t in fleet:
                t.close()

    def test_sequence_reuse_same_bucket(self):
        """Back-to-back ops on one bucket must not read stale payloads."""
        fleet = _fleet(2, "test-seq")
        try:
            for step in range(5):
                bufs = [np.full((257,), float(step * 10 + r), np.float64)
                        for r in range(2)]
                outs = _run_all([lambda t=t, b=b: t.allreduce(b)
                                 for t, b in zip(fleet, bufs)])
                expect = (step * 10) + (step * 10 + 1)
                for o in outs:
                    assert np.allclose(o, expect), (step, o[:3])
        finally:
            for t in fleet:
                t.close()

    def test_distinct_buckets_coexist(self):
        fleet = _fleet(2, "test-bkt")
        try:
            for n in (64, 4096, 64):  # revisit the first bucket
                bufs = [np.full((n,), 1.0, np.float32) for _ in range(2)]
                outs = _run_all([lambda t=t, b=b: t.allreduce(b)
                                 for t, b in zip(fleet, bufs)])
                for o in outs:
                    assert o.shape == (n,) and np.allclose(o, 2.0)
        finally:
            for t in fleet:
                t.close()

    def test_broadcast_from_root(self):
        fleet = _fleet(3, "test-bc")
        try:
            payload = np.arange(100, dtype=np.float32)
            bufs = [payload if r == 1 else np.zeros((100,), np.float32)
                    for r in range(3)]
            outs = _run_all([lambda t=t, b=b: t.broadcast(b, 1)
                             for t, b in zip(fleet, bufs)])
            for o in outs:
                assert np.array_equal(o, payload)
        finally:
            for t in fleet:
                t.close()

    def test_dead_peer_times_out_loudly(self, monkeypatch):
        from horovod_tpu.ops import shm_transport as st
        monkeypatch.setattr(st, "_SPIN_DEADLINE_S", 0.2)
        t0 = ShmTransport(0, 2, tag="test-dead")
        try:
            with pytest.raises(ShmTimeout):
                t0.allreduce(np.ones((16,), np.float32))
        finally:
            t0.close()

    def test_close_unlinks_own_segments(self):
        import glob
        t0 = ShmTransport(0, 1, tag="test-clean")
        t0.allreduce(np.ones((16,), np.float32))
        assert glob.glob("/dev/shm/hvdtpu_test-clean_*")
        t0.close()
        assert not glob.glob("/dev/shm/hvdtpu_test-clean_*")
