"""Per-request serving-trace overhead guard (slow tier) — the request
trace capture must stay out of the decode hot path: ``bench_serving.py
--reqtrace`` A/Bs the BENCH_SERVING load (8 slots, 8 concurrent
requests) with tracing toggled IN-process in paired alternating-order
rounds (the BENCH_TRACE methodology: separate jobs differ by ±5%
job-to-job, swamping the budget; pooled per-request latencies, 25th
percentile) and this guard holds the per-request latency overhead under
3%, regenerating ``BENCH_REQTRACE.json``.

One re-measure is allowed before failing — a shared CI box can stay
saturated through one window (the BENCH_METRICS precedent)."""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

BUDGET = 0.03


def _run_bench(out_path: str, rounds: int) -> dict:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "bench_serving.py"),
         "--reqtrace", "--reqtrace-rounds", str(rounds),
         "--out", out_path],
        capture_output=True, text=True, timeout=900, cwd=root)
    assert proc.returncode == 0, proc.stderr[-3000:]
    return json.loads(open(out_path).read())


def test_reqtrace_overhead_under_3_percent(tmp_path):
    out = tmp_path / "bench_reqtrace.json"
    result = _run_bench(str(out), rounds=6)
    if result["overhead_frac"] >= BUDGET:   # one re-measure
        result = _run_bench(str(out), rounds=6)

    # Regenerate the committed artifact from the accepted run.
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(root, "BENCH_REQTRACE.json"), "w") as f:
        json.dump(result, f, indent=2, sort_keys=True)
        f.write("\n")

    assert result["rows"]["tracing_on"]["request_p25_ms"] > 0
    assert result["trace_files"] == 6
    assert result["overhead_frac"] < BUDGET, (
        f"request tracing cost {result['overhead_frac']:.2%} of the "
        f"per-request serving latency (on "
        f"{result['rows']['tracing_on']['request_p25_ms']} ms vs off "
        f"{result['rows']['tracing_off']['request_p25_ms']} ms; "
        f"budget {BUDGET:.0%})")
