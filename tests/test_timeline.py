"""Timeline test — structural mirror of the reference's
test/test_timeline.py:41-57: run collectives with HOROVOD_TIMELINE set,
then grep the Chrome-trace JSON for the negotiation and execution phases."""

import json
import os
import subprocess
import sys

SCRIPT = r"""
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import horovod_tpu as hvd
from horovod_tpu.ops import collective

hvd.init()
hvd.allreduce(jnp.ones((16, 16)), name="timeline.test.allreduce")
hvd.allgather(jnp.ones((4, 4)), name="timeline.test.allgather")
hvd.broadcast(jnp.ones((4,)), 0, name="timeline.test.broadcast")
collective.engine().shutdown()   # flush + close the timeline writer
"""


def test_timeline_records_phases(tmp_path):
    tl = tmp_path / "timeline.json"
    env = dict(os.environ)
    env["HOROVOD_TIMELINE"] = str(tl)
    env["HOROVOD_TIMELINE_MARK_CYCLES"] = "1"
    proc = subprocess.run([sys.executable, "-c", SCRIPT], env=env,
                          capture_output=True, text=True, timeout=300,
                          cwd=os.path.dirname(os.path.dirname(__file__)))
    assert proc.returncode == 0, proc.stderr[-2000:]
    text = tl.read_text()
    # Negotiation + op phases (reference test_timeline.py greps
    # NEGOTIATE_ALLREDUCE / ALLREDUCE / CYCLE_START).
    assert "NEGOTIATE_ALLREDUCE" in text
    assert '"ALLREDUCE"' in text
    assert "NEGOTIATE_ALLGATHER" in text
    assert "NEGOTIATE_BROADCAST" in text
    assert "CYCLE_START" in text
    assert "XLA_ALLREDUCE" in text
    # Tensor names became Chrome "processes" (timeline.cc:70-90 parity).
    assert "timeline.test.allreduce" in text

    # Every line between the brackets must be valid JSON records.
    body = text.strip()
    assert body.startswith("[")
    records = [ln.rstrip(",") for ln in body.splitlines()[1:] if ln.strip()
               and ln.strip() not in ("[", "]")]
    for ln in records[:50]:
        json.loads(ln)
