"""Timeline test — structural mirror of the reference's
test/test_timeline.py:41-57: run collectives with HOROVOD_TIMELINE set,
then grep the Chrome-trace JSON for the negotiation and execution phases."""

import json
import os
import subprocess
import sys

import pytest

SCRIPT = r"""
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import horovod_tpu as hvd
from horovod_tpu.ops import collective

hvd.init()
hvd.allreduce(jnp.ones((16, 16)), name="timeline.test.allreduce")
hvd.allgather(jnp.ones((4, 4)), name="timeline.test.allgather")
hvd.broadcast(jnp.ones((4,)), 0, name="timeline.test.broadcast")
collective.engine().shutdown()   # flush + close the timeline writer
"""


def test_timeline_records_phases(tmp_path):
    tl = tmp_path / "timeline.json"
    env = dict(os.environ)
    env["HOROVOD_TIMELINE"] = str(tl)
    env["HOROVOD_TIMELINE_MARK_CYCLES"] = "1"
    proc = subprocess.run([sys.executable, "-c", SCRIPT], env=env,
                          capture_output=True, text=True, timeout=300,
                          cwd=os.path.dirname(os.path.dirname(__file__)))
    assert proc.returncode == 0, proc.stderr[-2000:]
    text = tl.read_text()
    # Negotiation + op phases (reference test_timeline.py greps
    # NEGOTIATE_ALLREDUCE / ALLREDUCE / CYCLE_START).
    assert "NEGOTIATE_ALLREDUCE" in text
    assert '"ALLREDUCE"' in text
    assert "NEGOTIATE_ALLGATHER" in text
    assert "NEGOTIATE_BROADCAST" in text
    assert "CYCLE_START" in text
    assert "XLA_ALLREDUCE" in text
    # Tensor names became Chrome "processes" (timeline.cc:70-90 parity).
    assert "timeline.test.allreduce" in text

    # Every line between the brackets must be valid JSON records.
    body = text.strip()
    assert body.startswith("[")
    records = [ln.rstrip(",") for ln in body.splitlines()[1:] if ln.strip()
               and ln.strip() not in ("[", "]")]
    for ln in records[:50]:
        json.loads(ln)


class TestPythonTimeline:
    """The Python timeline writer covers the two paths the native core
    cannot: the Python control-plane fallback and multi-process mode."""

    def test_python_fallback_timeline(self, tmp_path):
        tl = tmp_path / "py_timeline.json"
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            "HOROVOD_TPU_DISABLE_NATIVE": "1",
            "HOROVOD_TPU_TIMELINE": str(tl),
            "PYTHONPATH": os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))),
        })
        script = (
            "import jax; jax.config.update('jax_platforms', 'cpu')\n"
            "import jax.numpy as jnp\n"
            "import horovod_tpu as hvd\n"
            "from horovod_tpu.ops import collective\n"
            "hvd.init()\n"
            "hvd.allreduce(jnp.ones((8, 8)), name='pytl.allreduce')\n"
            "hvd.broadcast(jnp.ones((4,)), 0, name='pytl.broadcast')\n"
            "collective.engine().shutdown()\n")
        proc = subprocess.run([sys.executable, "-c", script],
                              capture_output=True, text=True, timeout=300,
                              env=env)
        assert proc.returncode == 0, proc.stderr[-2000:]
        text = tl.read_text()
        events = json.loads(text)   # valid catapult JSON
        assert any(e.get("name") == "NEGOTIATE_ALLREDUCE" for e in events)
        assert any(e.get("name") == "XLA_ALLREDUCE" for e in events)
        assert "pytl.allreduce" in text and "pytl.broadcast" in text

    @pytest.mark.slow
    def test_multiprocess_timeline(self, tmp_path):
        """Rank 0 writes the timeline in multi-process mode (reference:
        rank-0-only, operations.cc:1824-1829)."""
        from horovod_tpu.runner.api import run

        tl = tmp_path / "mp_timeline.json"

        def worker(path):
            import os

            import jax.numpy as jnp

            import horovod_tpu as hvd
            from horovod_tpu.ops import collective

            os.environ["HOROVOD_TPU_TIMELINE"] = path
            hvd.init()
            hvd.allreduce(jnp.ones((8,)), name="mptl.sum")
            hvd.allgather(jnp.ones((2, 2)), name="mptl.gather")
            collective.engine().shutdown()
            return hvd.process_rank()

        env = {"JAX_PLATFORMS": "cpu",
               "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}
        results = run(worker, args=(str(tl),), np=2, extra_env=env,
                      start_timeout=300)
        assert sorted(results) == [0, 1]
        text = tl.read_text()
        assert "NEGOTIATE_ALLREDUCE" in text
        assert "XLA_ALLREDUCE" in text and "XLA_ALLGATHER" in text
        assert "mptl.sum" in text and "mptl.gather" in text
