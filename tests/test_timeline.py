"""Timeline test — structural mirror of the reference's
test/test_timeline.py:41-57: run collectives with HOROVOD_TIMELINE set,
then grep the Chrome-trace JSON for the negotiation and execution phases."""

import json
import os
import subprocess
import sys

import pytest

SCRIPT = r"""
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import horovod_tpu as hvd
from horovod_tpu.ops import collective

hvd.init()
hvd.allreduce(jnp.ones((16, 16)), name="timeline.test.allreduce")
hvd.allgather(jnp.ones((4, 4)), name="timeline.test.allgather")
hvd.broadcast(jnp.ones((4,)), 0, name="timeline.test.broadcast")
collective.engine().shutdown()   # flush + close the timeline writer
"""


def test_timeline_records_phases(tmp_path):
    tl = tmp_path / "timeline.json"
    env = dict(os.environ)
    env["HOROVOD_TIMELINE"] = str(tl)
    env["HOROVOD_TIMELINE_MARK_CYCLES"] = "1"
    proc = subprocess.run([sys.executable, "-c", SCRIPT], env=env,
                          capture_output=True, text=True, timeout=300,
                          cwd=os.path.dirname(os.path.dirname(__file__)))
    assert proc.returncode == 0, proc.stderr[-2000:]
    text = tl.read_text()
    # Negotiation + op phases (reference test_timeline.py greps
    # NEGOTIATE_ALLREDUCE / ALLREDUCE / CYCLE_START).
    assert "NEGOTIATE_ALLREDUCE" in text
    assert '"ALLREDUCE"' in text
    assert "NEGOTIATE_ALLGATHER" in text
    assert "NEGOTIATE_BROADCAST" in text
    assert "CYCLE_START" in text
    assert "XLA_ALLREDUCE" in text
    # Tensor names became Chrome "processes" (timeline.cc:70-90 parity).
    assert "timeline.test.allreduce" in text

    # Every line between the brackets must be valid JSON records.
    body = text.strip()
    assert body.startswith("[")
    records = [ln.rstrip(",") for ln in body.splitlines()[1:] if ln.strip()
               and ln.strip() not in ("[", "]")]
    for ln in records[:50]:
        json.loads(ln)


class TestPythonTimeline:
    """The Python timeline writer covers the two paths the native core
    cannot: the Python control-plane fallback and multi-process mode."""

    def test_python_fallback_timeline(self, tmp_path):
        tl = tmp_path / "py_timeline.json"
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            "HOROVOD_TPU_DISABLE_NATIVE": "1",
            "HOROVOD_TPU_TIMELINE": str(tl),
            "PYTHONPATH": os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))),
        })
        script = (
            "import jax; jax.config.update('jax_platforms', 'cpu')\n"
            "import jax.numpy as jnp\n"
            "import horovod_tpu as hvd\n"
            "from horovod_tpu.ops import collective\n"
            "hvd.init()\n"
            "hvd.allreduce(jnp.ones((8, 8)), name='pytl.allreduce')\n"
            "hvd.broadcast(jnp.ones((4,)), 0, name='pytl.broadcast')\n"
            "collective.engine().shutdown()\n")
        proc = subprocess.run([sys.executable, "-c", script],
                              capture_output=True, text=True, timeout=300,
                              env=env)
        assert proc.returncode == 0, proc.stderr[-2000:]
        text = tl.read_text()
        events = json.loads(text)   # valid catapult JSON
        assert any(e.get("name") == "NEGOTIATE_ALLREDUCE" for e in events)
        assert any(e.get("name") == "XLA_ALLREDUCE" for e in events)
        assert "pytl.allreduce" in text and "pytl.broadcast" in text

    @pytest.mark.slow
    def test_multiprocess_timeline(self, tmp_path):
        """Rank 0 writes the timeline in multi-process mode (reference:
        rank-0-only, operations.cc:1824-1829)."""
        from horovod_tpu.runner.api import run

        tl = tmp_path / "mp_timeline.json"

        def worker(path):
            import os

            import jax.numpy as jnp

            import horovod_tpu as hvd
            from horovod_tpu.ops import collective

            os.environ["HOROVOD_TPU_TIMELINE"] = path
            hvd.init()
            hvd.allreduce(jnp.ones((8,)), name="mptl.sum")
            hvd.allgather(jnp.ones((2, 2)), name="mptl.gather")
            collective.engine().shutdown()
            return hvd.process_rank()

        env = {"JAX_PLATFORMS": "cpu",
               "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}
        results = run(worker, args=(str(tl),), np=2, extra_env=env,
                      start_timeout=300)
        assert sorted(results) == [0, 1]
        text = tl.read_text()
        assert "NEGOTIATE_ALLREDUCE" in text
        assert "XLA_ALLREDUCE" in text and "XLA_ALLGATHER" in text
        assert "mptl.sum" in text and "mptl.gather" in text


JIT_SCRIPT = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import optax
import horovod_tpu as hvd
from horovod_tpu.ops import collective

logdir = sys.argv[1]

hvd.init()
mesh = hvd.mesh()
from jax.sharding import NamedSharding, PartitionSpec as P

params = {"w": jnp.ones((16, 16))}
opt = hvd.DistributedGradientTransformation(optax.sgd(0.1))
opt_state = opt.init(params)
x = jax.device_put(jnp.ones((8, 16)), NamedSharding(mesh, P("dp")))

@jax.jit
def train_step(params, opt_state, x):
    def loss(p):
        return jnp.sum((x @ p["w"]) ** 2)
    grads = jax.grad(loss)(params)
    updates, opt_state = opt.update(grads, opt_state, params)
    return optax.apply_updates(params, updates), opt_state

params, opt_state = train_step(params, opt_state, x)  # compile outside
with jax.profiler.trace(logdir):
    for _ in range(2):
        with hvd.timeline_jit_step("train"):
            params, opt_state = train_step(params, opt_state, x)
        jax.block_until_ready(params)
collective.engine().shutdown()   # close the timeline writer
"""


class TestJitPathTimeline:
    """VERDICT r3 #3: the jit path (in-jit psum via
    DistributedGradientTransformation) must be visible in the timeline —
    XLA_STEP brackets from hvd.timeline_jit_step plus the device lanes
    of a jax.profiler capture merged into the same Chrome trace."""

    @pytest.mark.parametrize("native", ["0", "1"])
    def test_jit_step_brackets_and_profiler_merge(self, tmp_path, native):
        tl = tmp_path / "timeline.json"
        logdir = tmp_path / "profile"
        env = dict(os.environ)
        env["HOROVOD_TIMELINE"] = str(tl)
        env["HOROVOD_TPU_DISABLE_NATIVE"] = (
            "0" if native == "1" else "1")
        proc = subprocess.run(
            [sys.executable, "-c", JIT_SCRIPT, str(logdir)], env=env,
            capture_output=True, text=True, timeout=300,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        assert proc.returncode == 0, proc.stderr[-3000:]

        from horovod_tpu.ops import timeline_jit
        events = timeline_jit._load_timeline(str(tl))
        # XLA_STEP brackets exist under a jit:: process
        jit_pids = {e["pid"] for e in events
                    if e.get("name") == "process_name"
                    and str(e.get("args", {}).get("name", ""))
                    .startswith("jit::")}
        assert jit_pids, "no jit:: process in the timeline"
        steps = [e for e in events
                 if e.get("name") == "XLA_STEP" and e.get("ph") == "B"]
        assert len(steps) >= 2, "expected one XLA_STEP span per step"

        out = timeline_jit.merge_profiler_trace(str(tl), str(logdir))
        merged = json.load(open(out))
        # profiler lanes are merged, re-based above the engine's pids
        # (on TPU these include '/device:TPU:*' with the programs'
        # device time; the pure-CPU test backend exposes '/host:CPU')
        lanes = [e for e in merged
                 if e.get("name") == "process_name"
                 and e.get("pid", 0) >= timeline_jit._PID_GAP]
        assert lanes, "no profiler lanes merged into the timeline"
        # and the merged duration events are ts-anchored at the first
        # XLA_STEP bracket, not on the profiler's own clock base
        anchor = steps[0]["ts"]
        prof_x = [e for e in merged if e.get("ph") == "X"
                  and e.get("pid", 0) >= timeline_jit._PID_GAP]
        assert prof_x, "no duration events merged"
        assert min(e["ts"] for e in prof_x) >= anchor - 1


class TestCycleMarkerScope:
    def test_mark_cycle_emits_global_scope_instant(self, tmp_path):
        """Chrome/Perfetto render "ph": "i" instant events thread-scoped
        unless "s" says otherwise; cycle markers are trace-wide
        boundaries, so they must carry "s": "g" (Trace Event Format
        §Instant Events). Asserts the emitted JSON directly."""
        from horovod_tpu.ops.timeline_py import PyTimeline

        path = tmp_path / "cycles.json"
        tl = PyTimeline(str(path))
        tl.mark_cycle()
        tl.mark_cycle()
        tl.close()
        events = json.loads(path.read_text())
        cycles = [e for e in events
                  if e.get("name") == "CYCLE_START" and e.get("ph") == "i"]
        assert len(cycles) == 2
        for e in cycles:
            assert e.get("s") == "g", e
        # The _cycles pseudo-process is still named for the viewer.
        assert any(e.get("ph") == "M"
                   and e.get("args", {}).get("name") == "_cycles"
                   for e in events)


class TestWriterExitSafety:
    """Satellite: events buffered in the writer deque must not be lost
    when a rank exits without a clean shutdown() (crash/SIGTERM paths of
    the elastic driver)."""

    def test_atexit_flushes_unclosed_writer(self, tmp_path):
        """Interpreter exit without close(): the atexit hook drains the
        deque and terminates the JSON array."""
        path = tmp_path / "atexit.json"
        script = (
            "import sys\n"
            "sys.path.insert(0, sys.argv[2])\n"
            "from horovod_tpu.ops.timeline_py import PyTimeline\n"
            "tl = PyTimeline(sys.argv[1])\n"
            "for i in range(200):\n"
            "    tl.negotiate_start(f'exit.t{i}', 'allreduce')\n"
            "    tl.negotiate_end(f'exit.t{i}', group=i)\n"
            "sys.exit(0)\n")   # NO close() — atexit must flush
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        proc = subprocess.run([sys.executable, "-c", script, str(path),
                               root],
                              capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stderr[-2000:]
        events = json.loads(path.read_text())   # strict parse: complete
        assert sum(e.get("ph") == "B" for e in events) == 200
        assert any(e.get("args", {}).get("group") == 199 for e in events)

    def test_killed_writer_leaves_valid_prefix(self, tmp_path):
        """SIGKILL mid-stream: the file must be valid JSON up to the
        last drained event (the tolerant loader the merge tool uses),
        with every drained record intact — no torn lines."""
        import signal
        import time as _time

        path = tmp_path / "killed.json"
        script = (
            "import sys, time\n"
            "sys.path.insert(0, sys.argv[2])\n"
            "from horovod_tpu.ops.timeline_py import PyTimeline\n"
            "tl = PyTimeline(sys.argv[1])\n"
            "for i in range(500):\n"
            "    tl.negotiate_start(f'kill.t{i}', 'allreduce')\n"
            "    tl.negotiate_end(f'kill.t{i}', group=i)\n"
            "time.sleep(0.5)\n"           # let the drain thread flush
            "print('DRAINED', flush=True)\n"
            "time.sleep(60)\n")
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        proc = subprocess.Popen([sys.executable, "-c", script, str(path),
                                 root],
                                stdout=subprocess.PIPE, text=True)
        try:
            assert proc.stdout.readline().strip() == "DRAINED"
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=30)
        finally:
            if proc.poll() is None:
                proc.kill()
        _time.sleep(0.1)
        from horovod_tpu.ops import timeline_jit
        events = timeline_jit._load_timeline(str(path))  # tolerant parse
        bs = [e for e in events if e.get("ph") == "B"]
        assert len(bs) == 500   # everything drained before the kill
        for e in events[:50]:
            assert "ph" in e or e.get("name") in ("process_name",
                                                  "horovod_tpu_trace_meta")


class TestPerRankCapture:
    """Tentpole: HOROVOD_TPU_TIMELINE with a {rank} placeholder makes
    EVERY rank write a trace, each carrying a clock header + sidecar for
    the offline merger (docs/tracing.md)."""

    def test_placeholder_resolution(self, monkeypatch):
        from horovod_tpu.utils import env as _env
        monkeypatch.setenv("HOROVOD_TPU_TIMELINE", "/tmp/t.{rank}.json")
        assert _env.resolved_timeline_path(0) == "/tmp/t.0.json"
        assert _env.resolved_timeline_path(3) == "/tmp/t.3.json"
        monkeypatch.setenv("HOROVOD_TPU_TIMELINE", "/tmp/t.json")
        assert _env.resolved_timeline_path(0) == "/tmp/t.json"
        assert _env.resolved_timeline_path(1) is None   # rank-0-only mode

    def test_single_process_placeholder_writes_rank0_with_meta(
            self, tmp_path):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            "HOROVOD_TPU_DISABLE_NATIVE": "1",
            "HOROVOD_TPU_TIMELINE": str(tmp_path / "t.{rank}.json"),
            "PYTHONPATH": os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))),
        })
        script = (
            "import jax; jax.config.update('jax_platforms', 'cpu')\n"
            "import jax.numpy as jnp\n"
            "import horovod_tpu as hvd\n"
            "from horovod_tpu.ops import collective\n"
            "hvd.init()\n"
            "hvd.allreduce(jnp.ones((8,)), name='prk.allreduce')\n"
            "collective.engine().shutdown()\n")
        proc = subprocess.run([sys.executable, "-c", script],
                              capture_output=True, text=True, timeout=300,
                              env=env)
        assert proc.returncode == 0, proc.stderr[-2000:]
        path = tmp_path / "t.0.json"
        events = json.loads(path.read_text())
        meta = [e for e in events
                if e.get("name") == "horovod_tpu_trace_meta"]
        assert meta, "no clock header in the per-rank trace"
        args = meta[-1]["args"]
        assert args["rank"] == 0 and args["clock_synced"] is True
        assert args["start_mono_us"] > 0
        # Sidecar for the merge tool (and for native-writer parity).
        sidecar = json.loads((tmp_path / "t.0.json.clock.json")
                             .read_text())
        assert sidecar["rank"] == 0
        # Fused-group ids recorded on the NEGOTIATE spans.
        assert any("group" in (e.get("args") or {}) for e in events
                   if e.get("ph") in ("E", "X"))


class TestMergeCli:
    """The timeline_jit merge CLI on SYNTHETIC inputs: no profiler run,
    no engine — just a timeline file and a fake jax.profiler capture
    directory, exercising exactly what the CLI does."""

    def _make_inputs(self, tmp_path):
        import gzip

        tl = tmp_path / "timeline.json"
        # An unterminated file (PyTimeline.close's slow-writer escape
        # hatch) — _load_timeline must tolerate the missing bracket.
        tl.write_text(
            '[\n'
            '{"name": "process_name", "ph": "M", "pid": 0,'
            ' "args": {"name": "jit::train"}},\n'
            '{"ph": "B", "ts": 1000, "pid": 0, "tid": 0,'
            ' "name": "XLA_STEP"},\n'
            '{"ph": "E", "ts": 5000, "pid": 0, "tid": 0},\n')
        profdir = tmp_path / "profile" / "plugins" / "profile" / "run1"
        profdir.mkdir(parents=True)
        capture = {
            "traceEvents": [
                {"name": "process_name", "ph": "M", "pid": 3,
                 "args": {"name": "/device:TPU:0"}},
                {"ph": "X", "ts": 777000, "dur": 300, "pid": 3,
                 "tid": 1, "name": "fusion.1"},
                {"ph": "X", "ts": 777400, "dur": 200, "pid": 3,
                 "tid": 1, "name": "all-reduce.2"},
            ]}
        with gzip.open(profdir / "host.trace.json.gz", "wt") as f:
            json.dump(capture, f)
        return tl, tmp_path / "profile"

    def test_cli_merges_and_interleaves(self, tmp_path, capsys):
        from horovod_tpu.ops import timeline_jit

        tl, profdir = self._make_inputs(tmp_path)
        out = tmp_path / "merged.json"
        timeline_jit._main([str(tl), str(profdir), "-o", str(out)])
        assert capsys.readouterr().out.strip() == str(out)

        merged = json.loads(out.read_text())
        # Both streams present: the timeline's own events...
        assert any(e.get("name") == "XLA_STEP" for e in merged)
        # ...and the capture's device lanes, pid-rebased above the gap.
        prof = [e for e in merged
                if e.get("pid", 0) >= timeline_jit._PID_GAP]
        assert any(e.get("name") == "all-reduce.2" for e in prof)
        assert any(e.get("args", {}).get("name") == "/device:TPU:0"
                   for e in prof if e.get("ph") == "M")
        # Interleaved on ONE clock: the capture's earliest event is
        # anchored at the first XLA_STEP bracket (ts 1000), so its
        # duration events sit inside the step span, not at ts 777000.
        prof_x = [e for e in prof if e.get("ph") == "X"]
        assert prof_x
        assert min(e["ts"] for e in prof_x) == 1000
        assert max(e["ts"] for e in prof_x) <= 5000

    def test_cli_default_output_path(self, tmp_path, capsys):
        from horovod_tpu.ops import timeline_jit

        tl, profdir = self._make_inputs(tmp_path)
        timeline_jit._main([str(tl), str(profdir)])
        printed = capsys.readouterr().out.strip()
        assert printed == str(tl) + ".merged.json"
        json.loads(open(printed).read())

    def test_cli_missing_capture_errors(self, tmp_path):
        from horovod_tpu.ops import timeline_jit

        tl = tmp_path / "t.json"
        tl.write_text("[\n]")
        empty = tmp_path / "empty"
        empty.mkdir()
        with pytest.raises(FileNotFoundError):
            timeline_jit._main([str(tl), str(empty)])
