"""64-bit dtype coverage under ``jax_enable_x64`` (reference
mpi_message.h:26-37 reduces int64/float64 natively).

Without x64 the engine REFUSES narrowed 64-bit inputs with
enable-x64 guidance (collective.py); these tests prove the advertised
escape hatch actually works: with x64 on, int64/float64/uint64 ride the
wire end to end with genuine 64-bit arithmetic (values that a silent
float32/int32 narrowing could not represent). x64 must be set before
JAX initializes, so the suite runs in a fresh interpreter.
"""

import os
import subprocess
import sys
import textwrap

import pytest

pytestmark = pytest.mark.slow

SCRIPT = textwrap.dedent("""
    import os
    os.environ["JAX_ENABLE_X64"] = "1"
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    import numpy as np
    import horovod_tpu as hvd

    hvd.init()
    n = hvd.size()
    assert n == 8

    # float64 allreduce: needs > 24 mantissa bits — float32 would lose
    # the +1 against 2**30 exactly.
    base = float(2 ** 30)
    x = jnp.asarray([base + 1.0, 1.0 / 3.0], jnp.float64)
    s = hvd.allreduce(x, average=False, name="x64.f64.sum")
    assert np.asarray(s).dtype == np.float64
    assert np.asarray(s)[0] == n * base + n, np.asarray(s)
    a = hvd.allreduce(x, average=True, name="x64.f64.avg")
    assert np.allclose(np.asarray(a), np.asarray(x), rtol=0, atol=0)
    print("X64_F64_ALLREDUCE_OK")

    # int64 allreduce: values beyond int32 range.
    big = 2 ** 40 + 7
    i = jnp.asarray([big, -big], jnp.int64)
    si = hvd.allreduce(i, average=False, name="x64.i64.sum")
    assert np.asarray(si).dtype == np.int64
    assert np.asarray(si)[0] == n * big, np.asarray(si)
    print("X64_I64_ALLREDUCE_OK")

    # allgather keeps 64-bit payloads intact.
    g = hvd.allgather(jnp.asarray([[big]], jnp.int64), name="x64.i64.ag")
    assert np.asarray(g).dtype == np.int64
    assert np.asarray(g).shape == (n, 1)
    assert (np.asarray(g) == big).all()
    gf = hvd.allgather(jnp.asarray([[base + 1.0]], jnp.float64),
                       name="x64.f64.ag")
    assert np.asarray(gf).dtype == np.float64
    assert (np.asarray(gf) == base + 1.0).all()
    print("X64_ALLGATHER_OK")

    # broadcast of uint64 (PRNG-key-adjacent) and float64.
    b = hvd.broadcast(jnp.asarray([2 ** 63 - 1, 5], jnp.uint64),
                      root_rank=0, name="x64.u64.bc")
    assert np.asarray(b).dtype == np.uint64
    assert np.asarray(b)[0] == 2 ** 63 - 1
    print("X64_BROADCAST_OK")

    # Fused mixed-64-bit burst through one engine cycle.
    hs = [hvd.allreduce_async(jnp.full((3,), float(base + k), jnp.float64),
                              average=False, name=f"x64.burst.{k}")
          for k in range(4)]
    for k, h in enumerate(hs):
        out = np.asarray(hvd.synchronize(h))
        assert out[0] == n * (base + k), (k, out)
    print("X64_FUSED_OK")
""")


@pytest.fixture(scope="module")
def x64_run():
    env = dict(os.environ)
    env.pop("PYTEST_CURRENT_TEST", None)
    proc = subprocess.run(
        [sys.executable, "-c", SCRIPT], capture_output=True, text=True,
        timeout=420, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    return proc


def _check(proc, marker):
    assert marker in proc.stdout, (
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr[-4000:]}")


def test_float64_allreduce_exact(x64_run):
    _check(x64_run, "X64_F64_ALLREDUCE_OK")


def test_int64_allreduce_beyond_int32(x64_run):
    _check(x64_run, "X64_I64_ALLREDUCE_OK")


def test_allgather_64bit_payloads(x64_run):
    _check(x64_run, "X64_ALLGATHER_OK")


def test_broadcast_uint64(x64_run):
    _check(x64_run, "X64_BROADCAST_OK")


def test_fused_mixed_64bit_burst(x64_run):
    _check(x64_run, "X64_FUSED_OK")
