"""Serving-tier acceptance (slow tier): the full train→save→serve
path on the flagship Transformer, graceful SIGTERM drain of the HTTP
server with the flight-recorder ``exit`` dump, and BENCH_SERVING
reproducibility."""

import json
import os
import re
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.checkpoint import CheckpointEngine
from horovod_tpu.models import transformer as tfm
from horovod_tpu.parallel.mesh import create_mesh
from horovod_tpu.parallel.train import build_train_step
from horovod_tpu.serving import (InferenceEngine, ServingConfig,
                                 config_from_manifest, load_params,
                                 serving_config, transformer_extra)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
class TestTrainSaveServeE2E:
    def test_flagship_roundtrip(self, tmp_path):
        """Train a few steps tensor-parallel on the 8-device mesh,
        commit through the sharded engine simulating world size 4,
        serve on a 2-device tp mesh, and the continuous-batched greedy
        decode matches a single-device reference decode
        token-for-token."""
        import optax

        cfg = tfm.TransformerConfig(
            vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq=64, dtype=jnp.float32, tp_axis="tp", remat=False)
        params = tfm.init_params(cfg, jax.random.PRNGKey(0))
        mesh = create_mesh(dp=2, tp=4)
        make, shard_p, shard_b = build_train_step(cfg, mesh,
                                                  optax.adam(1e-2))
        opt_state = optax.adam(1e-2).init(params)
        step, _ = make(params, opt_state)
        tok = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 64)
        tgt = jnp.roll(tok, -1, axis=1)
        p, s = shard_p(params), opt_state
        tk, tg = shard_b(tok), shard_b(tgt)
        losses = []
        for _ in range(5):
            p, s, loss = step(p, s, tk, tg)
            losses.append(float(loss))
        assert losses[-1] < losses[0]

        # --- save: simulated 4-host layout (2 devices per "host")
        ckpt = str(tmp_path / "ckpt")
        engines = [CheckpointEngine(
            ckpt, process_index=i, process_count=4,
            process_fn=lambda d: d.id // 2, barrier=lambda n: None)
            for i in range(4)]
        for e in engines:
            e.save(p, 5, extra=transformer_extra(cfg))
        for e in engines:
            e.wait()

        # --- serve: resharded restore onto a ws-2 inference mesh
        mesh2 = create_mesh(devices=jax.devices()[:2], tp=2)
        man = CheckpointEngine(ckpt).restore_manifest()
        assert man["step"] == 5
        scfg = serving_config(config_from_manifest(man), mesh2)
        served = load_params(ckpt, scfg, mesh2)
        sconf = ServingConfig(block_size=4, kv_blocks=48,
                              max_batch_slots=4, max_new_tokens=10)
        engine = InferenceEngine(served, scfg, mesh2, sconf)

        rng = np.random.RandomState(3)
        prompts = [list(rng.randint(0, 64, int(n)))
                   for n in rng.randint(4, 12, 4)]
        reqs = [engine.submit(pr) for pr in prompts]
        engine.run_until_idle()
        batched = [r.result() for r in reqs]

        # --- reference: single-device decode from the trained params
        host_params = jax.device_get(p)
        cfg1 = serving_config(config_from_manifest(man),
                              create_mesh(devices=jax.devices()[:1],
                                          tp=1))
        ref_engine = InferenceEngine(
            host_params, cfg1,
            create_mesh(devices=jax.devices()[:1], tp=1), sconf)
        reference = [ref_engine.generate(pr) for pr in prompts]
        assert batched == reference   # token-for-token


@pytest.mark.slow
class TestSigtermDrain:
    def _write_checkpoint(self, ckpt):
        cfg = tfm.TransformerConfig(
            vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq=128, dtype=jnp.float32, tp_axis="tp", remat=False)
        params = tfm.init_params(cfg, jax.random.PRNGKey(0))
        mesh = create_mesh(dp=2, tp=4)
        specs = tfm.param_specs(cfg)
        sharded = jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            params, specs, is_leaf=lambda x: isinstance(x, P))
        engines = [CheckpointEngine(
            ckpt, process_index=i, process_count=4,
            process_fn=lambda d: d.id // 2, barrier=lambda n: None)
            for i in range(4)]
        for e in engines:
            e.save(sharded, 1, extra=transformer_extra(cfg))
        for e in engines:
            e.wait()

    def test_graceful_drain_with_exit_dump(self, tmp_path):
        """SIGTERM mid-generation: the in-flight request completes, the
        process exits 0, and the flight recorder's final dump says
        ``exit`` (a drained shutdown, not a death —
        docs/postmortem.md)."""
        ckpt = str(tmp_path / "ckpt")
        bb = str(tmp_path / "bb")
        self._write_checkpoint(ckpt)

        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
            "HOROVOD_TPU_BLACKBOX": bb,
        })
        proc = subprocess.Popen(
            [sys.executable, "-m", "horovod_tpu.serving",
             "--checkpoint-dir", ckpt, "--tp", "2", "--port", "0",
             "--block-size", "4", "--kv-blocks", "64", "--slots", "2",
             "--max-new-tokens", "64"],
            env=env, cwd=ROOT, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        try:
            port = None
            t0 = time.time()
            for line in proc.stdout:
                m = re.search(r"ready on :(\d+)", line)
                if m:
                    port = int(m.group(1))
                    break
                assert time.time() - t0 < 300, "server never came up"
            assert port

            result = {}

            def go():
                import http.client
                conn = http.client.HTTPConnection("127.0.0.1", port,
                                                  timeout=300)
                conn.request("POST", "/generate",
                             json.dumps({"tokens": [1, 2, 3]}))
                resp = conn.getresponse()
                result["status"] = resp.status
                result["body"] = json.loads(resp.read())

            t = threading.Thread(target=go)
            t.start()
            time.sleep(4)   # let it admit and decode a few tokens
            proc.send_signal(signal.SIGTERM)
            t.join(timeout=300)
            rc = proc.wait(timeout=300)
        finally:
            if proc.poll() is None:
                proc.kill()

        assert rc == 0, proc.stdout.read()
        # the in-flight generation was drained to completion
        assert result["status"] == 200
        assert len(result["body"]["tokens"]) == 64

        dump = os.path.join(bb, "blackbox-rank0.jsonl")
        lines = [json.loads(ln) for ln in open(dump)]
        assert lines[0]["reason"] == "exit"
        serving_events = [e for e in lines[1:]
                          if e.get("kind") == "serving"]
        assert [e["event"] for e in serving_events] == ["drain",
                                                        "drained"]


@pytest.mark.slow
class TestServingBenchReproducible:
    def test_bench_serving_determinism_and_headline(self, tmp_path):
        """bench_serving.py regenerates BENCH_SERVING reproducibly
        (seeded token counts/checksums identical across runs) and
        supports the acceptance claim: continuous-batched decode ≥ 2x
        sequential throughput at 8 concurrent requests, with batched
        output token-identical to sequential."""
        outs = []
        for i in range(2):
            out = tmp_path / f"bench{i}.json"
            subprocess.run(
                [sys.executable, os.path.join(ROOT, "bench_serving.py"),
                 "--out", str(out)],
                check=True, capture_output=True, text=True,
                timeout=900, cwd=ROOT)
            outs.append(json.loads(out.read_text()))
        a, b = outs
        for arm in ("batched", "sequential"):
            assert a[arm]["prompt_tokens"] == b[arm]["prompt_tokens"]
            assert a[arm]["generated_tokens"] == \
                b[arm]["generated_tokens"]
            assert a[arm]["output_checksum"] == \
                b[arm]["output_checksum"]
            assert a[arm]["decode_steps"] == b[arm]["decode_steps"]
        # batching never changes the greedy outputs
        assert a["outputs_equal"] and b["outputs_equal"]
        # continuous batching needs ~8x fewer decode dispatches
        assert a["batched"]["decode_steps"] * 4 <= \
            a["sequential"]["decode_steps"]
        # the headline wall-clock claim, both runs
        for run in outs:
            assert run["batched_vs_sequential_ratio"] >= 2.0, run


@pytest.mark.slow
class TestBenchSpeedReproducible:
    def test_bench_speed_determinism_and_headlines(self, tmp_path):
        """bench_serving.py --speed regenerates BENCH_SPEED
        reproducibly (the trained weights are seeded, decode is
        greedy, so every count/checksum/counter is identical across
        runs) and supports the speed-lever acceptance claims:
        speculative decode is token-identical and faster, the prefix
        cache skips most prefill work and cuts TTFT, the quantized
        pool holds the same sequences in < 0.30x the bytes."""
        outs = []
        for i in range(2):
            out = tmp_path / f"speed{i}.json"
            proc = subprocess.run(
                [sys.executable, os.path.join(ROOT, "bench_serving.py"),
                 "--speed", "--out", str(out)],
                capture_output=True, text=True, timeout=1800, cwd=ROOT)
            assert proc.returncode == 0, (
                f"--speed run {i} failed:\n{proc.stderr[-3000:]}")
            outs.append(json.loads(out.read_text()))
        a, b = outs
        deterministic = ("generated_tokens", "prefill_tokens",
                         "output_checksum", "decode_steps",
                         "kv_bytes_resident", "prefix_hits",
                         "prefix_misses", "draft_proposed",
                         "draft_accepted")
        for arm in a["arms"]:
            for key in deterministic:
                assert a["arms"][arm][key] == b["arms"][arm][key], \
                    (arm, key)
        for run in outs:
            h = run["headlines"]
            # exactness claims (seeded-deterministic)
            assert h["speculative_outputs_equal_baseline"]
            assert h["quantized_outputs_equal_fp32"]
            assert h["all_on_outputs_equal_quantized"]
            assert h["draft_acceptance"] >= 0.8
            # the prefix cache provably skipped most prompt prefill
            assert h["prefix_prefill_tokens_ratio"] <= 0.5
            # byte accounting is exact: int8 payload + fp32 scales
            assert h["quantized_kv_bytes_ratio"] <= 0.30
            # wall-clock claims, held loosely here (the committed
            # BENCH_SPEED.json records the measured 1.5x+ / 0.7x):
            # a loaded CI box must not flake the guard
            assert h["speculative_speedup"] >= 1.1, h
            assert h["prefix_ttft_p50_ratio"] <= 1.0, h
