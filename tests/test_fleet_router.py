"""Fleet router unit tests against STUB replicas (docs/serving.md#fleet):
queue-depth-aware admission scoring, draining-replica exclusion,
deadline expiry → 504 without retry, and both failover shapes —
pre-first-token re-prefill and mid-stream resume — all over real HTTP
but with no model and no jax compute. The full-fleet acceptance e2e
(real replicas, injected crashes, postmortem) is test_fleet_e2e.py
(slow tier)."""

import http.client
import json
import socket
import threading
import time

import pytest

from horovod_tpu.observability import metrics_snapshot
from horovod_tpu.serving.fleet import ReplicaEndpoint
from horovod_tpu.serving.router import (ReplicaView, Router,
                                        StaticBackends, pick_replica)

# Deterministic stub "generation": token i of a reply to a prompt of
# length L is (L + i) % 97. Crucially suffix-consistent: re-prefilling
# prompt+emitted continues the exact sequence — the same contract
# greedy decode gives the real router.


def stub_tokens(prompt_len: int, n: int):
    return [(prompt_len + i) % 97 for i in range(n)]


class StubReplica:
    """A fake serving replica: /readyz, /healthz (scrape fallback),
    /generate streaming the deterministic stub sequence. Behavior
    knobs are plain attributes, mutable mid-test."""

    def __init__(self, queue_depth=0, active=0, slots=8, ready=True,
                 die_after=None, reject=None, token_delay_s=0.0):
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)
        self.queue_depth = queue_depth
        self.active = active
        self.slots = slots
        self.ready = ready
        self.die_after = die_after      # close stream after N tokens
        self.reject = reject            # HTTP code to refuse with
        self.token_delay_s = token_delay_s
        self.requests = []              # bodies of /generate calls
        self.request_ids = []           # X-Request-Id header per call
        self.sessions = []              # leases, like a real replica
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def _json(self, code, payload, headers=None):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, str(v))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                path = self.path.split("?")[0]
                if path == "/readyz":
                    if outer.ready:
                        self._json(200, {"status": "ready"})
                    else:
                        self._json(503, {"status": "draining"})
                elif path == "/healthz":
                    self._json(200, {
                        "status": "serving",
                        "queue_depth": outer.queue_depth,
                        "active_requests": outer.active,
                        "batch_slots": outer.slots,
                        "sessions": list(outer.sessions),
                    })
                else:
                    self._json(404, {})

            def do_POST(self):  # noqa: N802
                n = int(self.headers.get("Content-Length", "0"))
                body = json.loads(self.rfile.read(n) or b"{}")
                outer.requests.append(body)
                outer.request_ids.append(
                    self.headers.get("X-Request-Id"))
                if outer.reject:
                    self._json(outer.reject,
                               {"error": f"stub {outer.reject}"},
                               headers={"Retry-After": 1}
                               if outer.reject == 429 else None)
                    return
                self.send_response(200)
                self.send_header("Content-Type",
                                 "application/x-ndjson")
                self.end_headers()
                self.wfile.write(b'{"id": 0}\n')
                toks = stub_tokens(len(body["tokens"]),
                                   int(body["max_new_tokens"]))
                for i, t in enumerate(toks):
                    if outer.die_after is not None \
                            and i >= outer.die_after:
                        # Mid-stream death: hang up with no done line.
                        self.wfile.flush()
                        self.connection.close()
                        return
                    if outer.token_delay_s:
                        time.sleep(outer.token_delay_s)
                    self.wfile.write(
                        json.dumps({"t": t}).encode() + b"\n")
                    self.wfile.flush()
                self.wfile.write(json.dumps(
                    {"done": True, "status": "completed",
                     "n": len(toks), "ttft_ms": 1.0,
                     "latency_ms": 2.0}).encode() + b"\n")
                sid = body.get("session_id")
                if sid and sid not in outer.sessions:
                    outer.sessions.append(sid)   # lease formed

            def log_message(self, *args):
                pass

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self._httpd.server_address[1]
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()


def _router(stubs, **kw):
    backends = StaticBackends([
        ReplicaEndpoint(index=i, host="127.0.0.1", port=s.port)
        for i, s in enumerate(stubs)])
    kw.setdefault("scrape_interval_s", 0.05)
    r = Router(backends, port=0, host="127.0.0.1", **kw)
    r.start()
    return r


def _post(port, body, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port,
                                      timeout=timeout)
    conn.request("POST", "/generate", json.dumps(body),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    return resp.status, json.loads(resp.read())


def _counter(name, labels):
    fam = metrics_snapshot().get(name, {"values": {}})["values"]
    return fam.get(labels, 0)


class TestRoutingPolicy:
    """pick_replica in isolation — the pure scoring function."""

    def _views(self, *specs):
        out = []
        for i, (ready, q, a, s) in enumerate(specs):
            out.append(ReplicaView(
                endpoint=ReplicaEndpoint(index=i, host="h", port=i),
                ready=ready, ok=True, queue_depth=q, active=a,
                slots=s))
        return out

    def test_lowest_outstanding_work_per_slot_wins(self):
        views = self._views((True, 4, 8, 8),    # score 1.5
                            (True, 0, 2, 8),    # score 0.25  ← winner
                            (True, 0, 6, 8))    # score 0.75
        assert pick_replica(views).endpoint.index == 1

    def test_queue_depth_dominates_when_slots_full(self):
        views = self._views((True, 9, 8, 8),
                            (True, 1, 8, 8))    # same active, shorter q
        assert pick_replica(views).endpoint.index == 1

    def test_draining_replica_excluded(self):
        views = self._views((False, 0, 0, 8),   # idle but draining
                            (True, 5, 8, 8))
        assert pick_replica(views).endpoint.index == 1

    def test_unscraped_replica_not_routed_blind(self):
        views = self._views((True, 0, 0, 8), (True, 5, 8, 8))
        views[0].ok = False                      # no successful scrape
        assert pick_replica(views).endpoint.index == 1

    def test_exclusion_and_nobody_left(self):
        views = self._views((True, 0, 0, 8), (True, 0, 0, 8))
        assert pick_replica(views, exclude={0}).endpoint.index == 1
        assert pick_replica(views, exclude={0, 1}) is None

    def test_tie_breaks_round_robin(self):
        views = self._views((True, 0, 0, 8), (True, 0, 0, 8))
        picked = {pick_replica(views, rr=r).endpoint.index
                  for r in (0, 1)}
        assert picked == {0, 1}

    def test_warmth_breaks_equal_load_toward_warm_replica(self):
        """Prefix-aware admission: identical load, replica 1 has the
        prompt's prefix cached — it wins regardless of rr."""
        views = self._views((True, 2, 2, 8), (True, 2, 2, 8))
        for r in (0, 1, 2):
            assert pick_replica(views, rr=r,
                                warmth={1: 1.0}).endpoint.index == 1

    def test_warmth_cannot_override_heavy_load_gap(self):
        """Warmth is worth at most one slot's outstanding work — a
        fully-warm but backed-up replica still loses to an idle cold
        one (a cache hit never justifies queueing behind a deep
        backlog)."""
        views = self._views((True, 16, 8, 8),   # score 3.0, warm
                            (True, 0, 0, 8))    # score 0.0, cold
        assert pick_replica(views,
                            warmth={0: 1.0}).endpoint.index == 1

    def test_no_warmth_map_is_the_legacy_policy(self):
        views = self._views((True, 4, 8, 8), (True, 0, 2, 8))
        assert pick_replica(views).endpoint.index == \
            pick_replica(views, warmth={}).endpoint.index == 1


class TestReplicaWarmthTracking:
    def _view(self):
        return ReplicaView(
            endpoint=ReplicaEndpoint(index=0, host="h", port=1),
            ready=True, ok=True)

    def test_longest_prefix_fraction(self):
        from horovod_tpu.serving import prefix_hashes
        v = self._view()
        h = prefix_hashes(list(range(33)), 8)    # 4 full blocks
        v.note_dispatch(h[:2])
        assert v.warmth(h) == 0.5                # blocks 0-1 warm
        assert v.warmth(prefix_hashes([9] * 33, 8)) == 0.0
        v.note_dispatch(h)
        assert v.warmth(h) == 1.0
        assert v.warmth([]) == 0.0               # unhashable prompt

    def test_warmth_is_prefix_not_membership(self):
        """A hash routed here only counts while every EARLIER block
        matches too — mirroring the replica-side longest-prefix
        lookup."""
        from horovod_tpu.serving import prefix_hashes
        v = self._view()
        h = prefix_hashes(list(range(33)), 8)
        v.note_dispatch([h[1]])                  # block 1 without 0
        assert v.warmth(h) == 0.0

    def test_lru_bound(self):
        from horovod_tpu.serving import router as router_mod
        v = self._view()
        v.note_dispatch([bytes([i % 256, i // 256]) for i in
                         range(router_mod._WARMTH_ENTRIES + 50)])
        assert len(v.warm) == router_mod._WARMTH_ENTRIES


class TestRouterHTTP:
    def test_repeat_prompt_sticks_to_warm_replica(self):
        """Prefix-aware routing over HTTP: with two equally-idle
        replicas, the second request for the same (multi-block) prompt
        lands on whichever replica served the first — its prefix cache
        is warm — and the dispatch-warmth counter says so."""
        stubs = [StubReplica(), StubReplica()]
        router = _router(stubs)
        try:
            warm0 = _counter("hvdtpu_fleet_dispatch_warmth_total",
                             'state="warm"')
            prompt = list(range(40))         # 2 full 16-token blocks
            for _ in range(3):
                status, _ = _post(router.port,
                                  {"tokens": prompt,
                                   "max_new_tokens": 2})
                assert status == 200
            served = [len(s.requests) for s in stubs]
            assert sorted(served) == [0, 3]  # all three stuck together
            assert _counter("hvdtpu_fleet_dispatch_warmth_total",
                            'state="warm"') - warm0 == 2
        finally:
            router.shutdown()
            for s in stubs:
                s.stop()

    def test_routes_to_least_loaded_and_completes(self):
        busy = StubReplica(queue_depth=6, active=8)
        idle = StubReplica(queue_depth=0, active=1)
        router = _router([busy, idle])
        try:
            status, body = _post(router.port,
                                 {"tokens": [1, 2, 3],
                                  "max_new_tokens": 5})
            assert status == 200
            assert body["tokens"] == stub_tokens(3, 5)
            assert body["replica"] == 1 and body["retries"] == 0
            assert len(idle.requests) == 1 and not busy.requests
            # the replica saw the router's streaming dialect
            assert idle.requests[0]["stream"] is True
        finally:
            router.shutdown()
            busy.stop()
            idle.stop()

    def test_draining_replica_gets_no_traffic(self):
        draining = StubReplica(ready=False)          # readyz 503
        ready = StubReplica(queue_depth=3, active=8)  # busy but ready
        router = _router([draining, ready])
        try:
            for _ in range(3):
                status, _ = _post(router.port,
                                  {"tokens": [5], "max_new_tokens": 2})
                assert status == 200
            assert not draining.requests
            assert len(ready.requests) == 3
        finally:
            router.shutdown()
            draining.stop()
            ready.stop()

    def test_deadline_expired_is_504_without_retry(self):
        stub = StubReplica()
        router = _router([stub])
        try:
            before = _counter("hvdtpu_fleet_requests_total",
                              'outcome="expired"')
            status, body = _post(router.port,
                                 {"tokens": [1], "max_new_tokens": 4,
                                  "deadline_ms": -1})
            assert status == 504
            assert "deadline" in body["error"]
            assert not stub.requests       # never dispatched, no retry
            assert _counter("hvdtpu_fleet_requests_total",
                            'outcome="expired"') == before + 1
        finally:
            router.shutdown()
            stub.stop()

    def test_failover_before_first_token(self):
        """A dead backend (connection refused) is transparently
        retried on the healthy one — the client sees one clean 200."""
        dead_port = socket.socket()
        dead_port.bind(("127.0.0.1", 0))
        port = dead_port.getsockname()[1]
        dead_port.close()                  # nothing listens here now
        alive = StubReplica(queue_depth=5, active=8)  # worse score
        backends = StaticBackends([
            ReplicaEndpoint(index=0, host="127.0.0.1", port=port),
            ReplicaEndpoint(index=1, host="127.0.0.1",
                            port=alive.port)])
        router = Router(backends, port=0, host="127.0.0.1",
                        scrape_interval_s=0.05)
        # Hand-plant a stale-but-ready view of the dead backend so the
        # router genuinely dispatches to it first (a real crash window:
        # the replica died after the last scrape).
        router._scrape_cycle()
        v = router._views[0]
        v.ready = v.ok = True
        v.queue_depth = v.active = 0.0
        router._http_thread.start()
        try:
            before = _counter("hvdtpu_fleet_failovers_total",
                              'phase="prefill"')
            status, body = _post(router.port,
                                 {"tokens": [7, 8], "max_new_tokens": 3})
            assert status == 200
            assert body["tokens"] == stub_tokens(2, 3)
            assert body["retries"] >= 1
            assert _counter("hvdtpu_fleet_failovers_total",
                            'phase="prefill"') >= before + 1
        finally:
            router._stop.set()
            router._httpd.shutdown()
            router._httpd.server_close()
            alive.stop()

    def test_midstream_death_resumes_seamlessly(self):
        """Replica 0 dies after 3 tokens (stream breaks, no done
        line); the router re-prefills prompt+emitted on replica 1 and
        the client's assembled output is identical to an uncontended
        run."""
        flaky = StubReplica(die_after=3)               # preferred: idle
        backup = StubReplica(queue_depth=2, active=4)
        router = _router([flaky, backup])
        try:
            before = _counter("hvdtpu_fleet_failovers_total",
                              'phase="midstream"')
            status, body = _post(router.port,
                                 {"tokens": [1, 2, 3, 4],
                                  "max_new_tokens": 8})
            assert status == 200
            assert body["tokens"] == stub_tokens(4, 8)   # seamless
            assert body["retries"] >= 1
            # the resume carried prompt+emitted and the REMAINING budget
            resume = backup.requests[-1]
            assert resume["tokens"] == [1, 2, 3, 4] + stub_tokens(4, 3)
            assert resume["max_new_tokens"] == 5
            assert _counter("hvdtpu_fleet_failovers_total",
                            'phase="midstream"') >= before + 1
        finally:
            router.shutdown()
            flaky.stop()
            backup.stop()

    def test_stable_request_id_across_midstream_failover(self):
        """ONE request identity end-to-end
        (docs/serving.md#request-tracing): the id the router ships in
        X-Request-Id on the first dispatch is REUSED — not re-minted —
        on the failover re-dispatch, and comes back to the client in
        the response body. A client-supplied X-Request-Id is honored
        verbatim."""
        flaky = StubReplica(die_after=3)               # preferred: idle
        backup = StubReplica(queue_depth=2, active=4)
        router = _router([flaky, backup])
        try:
            # Router-minted id: same on both hops, returned to client.
            status, body = _post(router.port,
                                 {"tokens": [1, 2, 3],
                                  "max_new_tokens": 8})
            assert status == 200
            assert body["trace_id"]
            assert flaky.request_ids[-1] == body["trace_id"]
            assert backup.request_ids[-1] == body["trace_id"]

            # Client-supplied id: honored verbatim across the failover.
            flaky.die_after = 2
            conn = http.client.HTTPConnection("127.0.0.1", router.port,
                                              timeout=30)
            conn.request("POST", "/generate",
                         json.dumps({"tokens": [5, 6],
                                     "max_new_tokens": 6,
                                     "stream": True}),
                         {"Content-Type": "application/json",
                          "X-Request-Id": "client-chose-this"})
            resp = conn.getresponse()
            lines = [json.loads(ln) for ln in resp.read().splitlines()
                     if ln.strip()]
            assert lines[0]["trace_id"] == "client-chose-this"
            assert lines[-1]["done"] and \
                lines[-1]["trace_id"] == "client-chose-this"
            assert [ln["t"] for ln in lines[1:-1]] == stub_tokens(2, 6)
            # Whichever replicas this hop touched (flaky may still sit
            # in the first failover's exclusion window) saw the
            # client's id, never a re-minted one.
            assert backup.request_ids[-1] == "client-chose-this"
        finally:
            router.shutdown()
            flaky.stop()
            backup.stop()

    def test_midstream_resume_streams_to_client(self):
        """Same failover, but the CLIENT is streaming: the token lines
        it reads across the replica death form the uninterrupted
        sequence, ending in one done line."""
        flaky = StubReplica(die_after=2)
        backup = StubReplica(queue_depth=2, active=4)
        router = _router([flaky, backup])
        try:
            conn = http.client.HTTPConnection("127.0.0.1", router.port,
                                              timeout=30)
            conn.request("POST", "/generate",
                         json.dumps({"tokens": [9, 9, 9],
                                     "max_new_tokens": 6,
                                     "stream": True}),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            assert resp.status == 200
            lines = [json.loads(ln) for ln in resp.read().splitlines()
                     if ln.strip()]
            assert "id" in lines[0]
            toks = [ln["t"] for ln in lines[1:-1]]
            assert toks == stub_tokens(3, 6)
            assert lines[-1]["done"] and \
                lines[-1]["status"] == "completed"
            assert lines[-1]["retries"] >= 1
        finally:
            router.shutdown()
            flaky.stop()
            backup.stop()

    def test_fleet_wide_queue_full_gives_up_with_retry_after(self):
        stubs = [StubReplica(reject=429), StubReplica(reject=429)]
        router = _router([s for s in stubs], max_attempts=3)
        try:
            before = _counter("hvdtpu_fleet_retries_total",
                              'reason="queue_full"')
            status, body = _post(router.port,
                                 {"tokens": [1], "max_new_tokens": 2})
            assert status == 503
            assert _counter("hvdtpu_fleet_retries_total",
                            'reason="queue_full"') > before
        finally:
            router.shutdown()
            for s in stubs:
                s.stop()

    def test_router_health_and_ready_endpoints(self):
        stub = StubReplica(queue_depth=2, active=3)
        router = _router([stub])
        try:
            conn = http.client.HTTPConnection("127.0.0.1", router.port,
                                              timeout=10)
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            h = json.loads(resp.read())
            assert resp.status == 200 and h["ready_replicas"] == 1
            assert h["replicas"][0]["queue_depth"] == 2
            conn.request("GET", "/readyz")
            assert conn.getresponse().status == 200
            stub.ready = False
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                conn.request("GET", "/readyz")
                r = conn.getresponse()
                r.read()
                if r.status == 503:
                    break
                time.sleep(0.05)
            assert r.status == 503
        finally:
            router.shutdown()
            stub.stop()


class TestServingFaultGrammar:
    """The serving clauses of HOROVOD_TPU_FAULT_SPEC parse, repr and
    window like the training ones (docs/adaptation.md)."""

    def test_parse_serving_clauses(self):
        from horovod_tpu.adaptation.faults import parse_spec
        cs = parse_spec("rank=1:replica_crash_at=30:gen=0; "
                        "rank=*:slow_decode=50ms:from_step=5; "
                        "rank=2:slow_prefill=200ms; "
                        "rank=0:drop_health:from_step=3:until_step=9")
        assert cs[0].replica_crash_at == 30 and cs[0].gen == 0
        assert cs[1].slow_decode_s == pytest.approx(0.05)
        assert cs[1].rank is None and cs[1].from_step == 5
        assert cs[2].slow_prefill_s == pytest.approx(0.2)
        assert cs[3].drop_health and not cs[3].in_window(2)
        assert cs[3].in_window(3) and not cs[3].in_window(9)
        # round-trips through repr for the log line
        assert "replica_crash_at=30" in repr(cs[0])
        assert "slow_decode=50ms" in repr(cs[1])

    def test_bad_serving_fields_fail_loudly(self):
        from horovod_tpu.adaptation.faults import parse_spec
        with pytest.raises(ValueError, match="drop_health"):
            parse_spec("rank=0:drop_health=nope")
        with pytest.raises(ValueError, match="unknown fault-spec"):
            parse_spec("rank=0:replica_crash=5")

    def test_replica_id_targets_injector_rank(self, monkeypatch):
        from horovod_tpu.adaptation import faults
        monkeypatch.setenv("HOROVOD_TPU_FAULT_SPEC",
                           "rank=2:slow_decode=1ms")
        monkeypatch.setenv("HOROVOD_TPU_REPLICA_ID", "2")
        faults.reset()
        try:
            inj = faults.injector()
            assert inj is not None and inj.rank == 2
            monkeypatch.setenv("HOROVOD_TPU_REPLICA_ID", "1")
            faults.reset()
            assert faults.injector() is None   # targets replica 2 only
        finally:
            faults.reset()


class TestSessionAffinityRouting:
    """Session pinning (docs/serving.md#session-affinity): the router
    learns which replica holds a session's KV lease — from /healthz
    and from its own completed dispatches — and pins that session's
    next turn there; failover falls back to normal dispatch and the
    lease re-forms on the surviving replica."""

    def _wait_scraped(self, router, pred, timeout=5.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with router._views_lock:
                if pred(router._views):
                    return
            time.sleep(0.02)
        raise AssertionError("scrape never observed the condition")

    def test_session_pins_to_lease_holder_despite_load(self):
        """The advertised lease outweighs a load gap the plain policy
        would never cross: the session's turn lands on the busier
        replica that holds its KV."""
        busy = StubReplica(queue_depth=5, active=8)   # score 1.625
        idle = StubReplica(queue_depth=0, active=1)   # score 0.125
        busy.sessions = ["conv"]
        router = _router([busy, idle])
        try:
            self._wait_scraped(
                router, lambda vs: "conv" in vs[0].sessions)
            status, body = _post(router.port,
                                 {"tokens": [1, 2, 3],
                                  "max_new_tokens": 2,
                                  "session_id": "conv"})
            assert status == 200 and body["replica"] == 0
            # a session-less request still takes the idle replica
            status, body = _post(router.port,
                                 {"tokens": [1, 2, 3],
                                  "max_new_tokens": 2})
            assert status == 200 and body["replica"] == 1
        finally:
            router.shutdown()
            busy.stop()
            idle.stop()

    def test_completed_dispatch_pins_before_next_scrape(self):
        """The router shadows the lease it just created: turn 2 of a
        session sticks to turn 1's replica even with equal load and a
        prompt too short for prefix warmth."""
        stubs = [StubReplica(), StubReplica()]
        router = _router(stubs, scrape_interval_s=60.0)
        try:
            for _ in range(4):
                status, _ = _post(router.port,
                                  {"tokens": [4, 5],
                                   "max_new_tokens": 2,
                                   "session_id": "chat-9"})
                assert status == 200
            served = [len(s.requests) for s in stubs]
            assert sorted(served) == [0, 4]   # all four stuck together
        finally:
            router.shutdown()
            for s in stubs:
                s.stop()

    def test_session_failover_reforms_lease_on_survivor(self):
        """The pinned replica dies mid-stream: the failover resume
        completes the reply token-identically on the survivor (the
        session_id rides the re-dispatch, so the lease re-forms
        there), and the next turn pins to the survivor."""
        flaky = StubReplica(die_after=3)              # preferred: idle
        backup = StubReplica(queue_depth=2, active=4)
        router = _router([flaky, backup])
        try:
            status, body = _post(router.port,
                                 {"tokens": [1, 2, 3, 4],
                                  "max_new_tokens": 8,
                                  "session_id": "conv"})
            assert status == 200
            assert body["tokens"] == stub_tokens(4, 8)   # seamless
            assert body["retries"] >= 1
            resume = backup.requests[-1]
            assert resume["session_id"] == "conv"
            assert backup.sessions == ["conv"]        # lease re-formed
            flaky.die_after = None
            status, body = _post(router.port,
                                 {"tokens": [9, 9],
                                  "max_new_tokens": 2,
                                  "session_id": "conv"})
            assert status == 200 and body["replica"] == 1
        finally:
            router.shutdown()
            flaky.stop()
            backup.stop()

    def test_header_spelling_reaches_replica(self):
        stub = StubReplica()
        router = _router([stub])
        try:
            conn = http.client.HTTPConnection("127.0.0.1", router.port,
                                              timeout=30)
            conn.request("POST", "/generate",
                         json.dumps({"tokens": [1],
                                     "max_new_tokens": 2}),
                         {"Content-Type": "application/json",
                          "X-Session-Id": "hdr-sess"})
            assert conn.getresponse().status == 200
            assert stub.requests[-1]["session_id"] == "hdr-sess"
        finally:
            router.shutdown()
            stub.stop()


class TestLongPromptBurstGrammar:
    def test_parse_long_prompt_burst(self):
        from horovod_tpu.adaptation.faults import parse_spec
        cs = parse_spec("rank=*:long_prompt_burst=2x120:from_step=6; "
                        "rank=0:long_prompt_burst=64")
        assert cs[0].long_prompt_burst == (2, 120)
        assert cs[0].from_step == 6
        assert cs[1].long_prompt_burst == (1, 64)   # bare count of 1
        assert "long_prompt_burst=2x120" in repr(cs[0])

    def test_bad_burst_fields_fail_loudly(self):
        from horovod_tpu.adaptation.faults import parse_spec
        for bad in ("rank=0:long_prompt_burst=abc",
                    "rank=0:long_prompt_burst=0x5",
                    "rank=0:long_prompt_burst=2x0",
                    "rank=0:long_prompt_burst="):
            with pytest.raises(ValueError,
                               match="long_prompt_burst"):
                parse_spec(bad)

    def test_burst_fires_once_inside_window(self):
        from horovod_tpu.adaptation.faults import (FaultInjector,
                                                   parse_spec)
        inj = FaultInjector(
            parse_spec("rank=*:long_prompt_burst=3x40:from_step=2"),
            rank=0)
        assert inj.take_long_prompt_bursts() == []   # tick 0: early
        inj.on_serving_decode()
        inj.on_serving_decode()
        assert inj.take_long_prompt_bursts() == [40, 40, 40]
        assert inj.take_long_prompt_bursts() == []   # once only
