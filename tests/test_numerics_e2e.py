"""Numerics-plane acceptance (ISSUE 20, docs/numerics.md) — slow tier.

1. Injected-NaN postmortem story: a 4-process job with a ``nan_at``
   fault poisoning rank 1's payload at a known step, the result fed
   back so the NaN cascades to every rank one step later. The
   same-step ``nonfinite_rate`` alert names rank 1 AND the injection
   step; the flight-recorder dump carries the ``numerics`` event; and
   ``tools/postmortem`` over the merged dumps attributes the first
   nonfinite observation to (step, rank 1) — not to the louder ranks
   that caught the cascade a step later.
2. Injected-bitflip divergence story: identical param trees on all
   ranks, one mantissa bit flipped on rank 1 mid-run by
   ``bitflip_param``. The periodic fingerprint probe ships digests to
   rank 0 over the coordinator channel, and the majority compare fires
   a ``rank_divergence`` alert naming the corrupted leaf and rank.
"""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from horovod_tpu.runner.api import run as plain_run  # noqa: E402

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_BASE_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
    "HOROVOD_TPU_DISABLE_NATIVE": "1",
    "HOROVOD_CYCLE_TIME": "1",
    "HOROVOD_TPU_NUMERICS": "1",
}


def _make_nan_worker():
    """Worker built inside a closure so cloudpickle ships it by value
    (the test module is not importable from the spawned workers)."""

    def worker(steps, nan_at):
        import numpy as np

        import jax.numpy as jnp

        import horovod_tpu as hvd
        from horovod_tpu.observability import history as _history

        hvd.init()
        x = jnp.ones((128,), jnp.float32)
        for step in range(steps):
            # ONE collective per step so the fault injector's enqueue
            # tick counter AND the numerics scan tick == the step
            # counter. Feeding the reduction back makes the injected
            # NaN cascade: rank 1 packs it at step nan_at, every other
            # rank first sees it in its own payload at nan_at + 1.
            x = hvd.allreduce(x, name=f"ne2e.{step}", average=True)
        nan_after = int(np.sum(~np.isfinite(np.asarray(x))))
        sampler = _history.sampler()
        if sampler is not None:
            sampler.final_flush()
        snap = hvd.metrics_snapshot(prefix="hvdtpu_")
        nonf = (snap.get("hvdtpu_numerics_nonfinite_total")
                or {"values": {}})["values"]
        monitor = sampler.monitor if sampler is not None else None
        return {
            "rank": hvd.process_rank(),
            "nonfinite_counts": nonf,
            "alerts": ([a.to_dict() for a in monitor.alerts]
                       if monitor is not None else []),
            "x_nonfinite_after": nan_after,
        }

    return worker


class TestInjectedNanPostmortemE2E:
    def test_nan_at_names_rank_and_step_everywhere(self, tmp_path):
        """ACCEPTANCE: the same-step alert, the flight-recorder dump,
        and the tools/postmortem attribution all name (step, rank 1)
        for an injected NaN — while the cascade pages every rank."""
        hist = tmp_path / "hist"
        blackbox = tmp_path / "blackbox"
        steps, nan_at = 14, 7
        env = dict(_BASE_ENV)
        env.update({
            "HOROVOD_TPU_HISTORY": str(hist),
            "HOROVOD_TPU_HISTORY_INTERVAL": "0.2",
            "HOROVOD_TPU_BLACKBOX": str(blackbox),
            "HOROVOD_TPU_FAULT_SPEC": f"rank=1:nan_at={nan_at}",
        })
        results = plain_run(_make_nan_worker(), args=(steps, nan_at),
                            np=4, extra_env=env, start_timeout=600)
        by_rank = {r["rank"]: r for r in results}

        # The cascade happened: averaging a NaN poisons the feedback
        # tensor on every rank.
        assert all(r["x_nonfinite_after"] >= 1 for r in results)

        # (1) SAME-STEP detection on the injected rank: the alert's
        # evidence names the exact injection step and rank 1 itself —
        # not a later step where the page would be ambiguous.
        r1 = by_rank[1]
        nf1 = [a for a in r1["alerts"] if a["kind"] == "nonfinite_rate"]
        assert nf1, f"rank 1 fired no nonfinite alert: {r1['alerts']}"
        assert nf1[0]["evidence"]["step"] == nan_at
        assert nf1[0]["evidence"]["rank"] == 1
        assert nf1[0]["evidence"]["source"] == "collective"
        assert r1["nonfinite_counts"].get('source="collective"', 0) >= 1

        # Every OTHER rank first observes the NaN one step later, in
        # its own fed-back payload — the louder-but-later evidence the
        # postmortem attribution must rank below rank 1's.
        for rank in (0, 2, 3):
            alerts = [a for a in by_rank[rank]["alerts"]
                      if a["kind"] == "nonfinite_rate"]
            assert alerts, f"rank {rank} never saw the cascade"
            assert alerts[0]["evidence"]["step"] == nan_at + 1

        # (2) Flight-recorder dump: rank 1's ring carries the numerics
        # event with the injection step.
        dump = blackbox / "blackbox-rank1.jsonl"
        assert dump.exists()
        events = [json.loads(line) for line in open(dump)][1:]
        numerics_ev = [e for e in events if e.get("kind") == "numerics"]
        assert any(e["event"] == "nonfinite" and e["step"] == nan_at
                   and e["who"] == 1 for e in numerics_ev), numerics_ev

        # ... and the injection itself is on the record (fault event),
        # so a postmortem reader can tell injected from organic.
        fault_ev = [e for e in events if e.get("kind") == "fault"]
        assert any(e.get("fault") == "nan" and e.get("tick") == nan_at
                   for e in fault_ev), fault_ev

        # (3) tools/postmortem over the merged dumps: first_nonfinite
        # is (step nan_at, rank 1) even though three other ranks
        # reported nonfinite payloads too.
        out_json = tmp_path / "postmortem.json"
        proc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.tools.postmortem",
             str(blackbox), "--json", str(out_json)],
            capture_output=True, text=True, timeout=300, cwd=ROOT)
        assert proc.returncode == 0, proc.stderr[-3000:]
        report = json.loads(out_json.read_text())
        numerics = report["numerics"]
        assert numerics is not None
        first = numerics["first_nonfinite"]
        assert first["step"] == nan_at
        assert first["rank"] == 1
        assert numerics["nonfinite_events"] >= 4
        assert set(numerics["nonfinite_ranks"]) == {0, 1, 2, 3}

        # Human rendering states the verdict.
        assert "First nonfinite" in proc.stdout
        assert f"step {nan_at} on rank 1" in proc.stdout


def _make_bitflip_worker():
    def worker(steps):
        import time

        import jax.numpy as jnp

        import horovod_tpu as hvd
        from horovod_tpu.observability import history as _history
        from horovod_tpu.observability import numerics as _numerics

        hvd.init()
        # Identical param trees on every rank; only the injected flip
        # on rank 1 may make them diverge.
        params = {"w": jnp.arange(1.0, 257.0, dtype=jnp.float32),
                  "b": jnp.zeros((16,), jnp.float32)}
        x = jnp.ones((64,), jnp.float32)
        for step in range(steps):
            hvd.allreduce(x, name=f"fp.{step}", average=False)
            params = _numerics.maybe_bitflip(params, step)
            _numerics.maybe_send_fingerprint(params, step)
        # Give rank 0's coordinator thread time to drain the last
        # probe messages before reading alert state.
        time.sleep(1.5)
        sampler = _history.sampler()
        if sampler is not None:
            sampler.final_flush()
        snap = hvd.metrics_snapshot(prefix="hvdtpu_")
        fp = (snap.get("hvdtpu_numerics_fingerprints_total")
              or {"values": {}})["values"]
        monitor = sampler.monitor if sampler is not None else None
        return {
            "rank": hvd.process_rank(),
            "fingerprint_counts": fp,
            "alerts": ([a.to_dict() for a in monitor.alerts]
                       if monitor is not None else []),
        }

    return worker


class TestInjectedBitflipDivergenceE2E:
    def test_bitflip_fires_rank_divergence_naming_leaf(self, tmp_path):
        """ACCEPTANCE: a single flipped mantissa bit on rank 1 is
        caught by the cross-rank fingerprint compare at rank 0, which
        names the corrupted leaf and the divergent rank."""
        hist = tmp_path / "hist"
        blackbox = tmp_path / "blackbox"
        steps, flip_at, interval = 16, 10, 5
        env = dict(_BASE_ENV)
        env.update({
            "HOROVOD_TPU_HISTORY": str(hist),
            "HOROVOD_TPU_HISTORY_INTERVAL": "0.2",
            "HOROVOD_TPU_BLACKBOX": str(blackbox),
            "HOROVOD_TPU_NUMERICS_FP_INTERVAL": str(interval),
            "HOROVOD_TPU_FAULT_SPEC":
                f"rank=1:bitflip_param={flip_at}:leaf=w",
        })
        results = plain_run(_make_bitflip_worker(), args=(steps,),
                            np=4, extra_env=env, start_timeout=600)
        by_rank = {r["rank"]: r for r in results}

        # Probes ran on every rank (steps 0, 5, 10, 15).
        for r in results:
            assert r["fingerprint_counts"].get('event="computed"',
                                               0) >= 4, r

        # Rank 0 is the collection point: it compared complete sets
        # and flagged the post-flip probes as mismatched.
        r0 = by_rank[0]
        assert r0["fingerprint_counts"].get('event="compared"', 0) >= 3
        assert r0["fingerprint_counts"].get('event="mismatch"', 0) >= 1

        # The typed alert names the corrupted leaf AND rank 1, at the
        # first probe step on/after the flip.
        div = [a for a in r0["alerts"] if a["kind"] == "rank_divergence"]
        assert div, f"rank 0 fired no divergence alert: {r0['alerts']}"
        ev = div[0]["evidence"]
        assert ev["rank"] == 1
        assert "w" in ev["leaf"]
        assert ev["step"] == flip_at
        assert sorted(ev["ranks_reporting"]) == [0, 1, 2, 3]

        # Clean ranks raised nothing.
        for rank in (2, 3):
            assert not [a for a in by_rank[rank]["alerts"]
                        if a["kind"] == "rank_divergence"]

        # The flight recorder on rank 0 carries the divergence event,
        # so tools/postmortem can attribute it after the fact.
        dump = blackbox / "blackbox-rank0.jsonl"
        assert dump.exists()
        events = [json.loads(line) for line in open(dump)][1:]
        div_ev = [e for e in events if e.get("kind") == "numerics"
                  and e.get("event") == "divergence"]
        assert any(e["who"] == 1 and "w" in str(e["detail"])
                   for e in div_ev), div_ev

        out_json = tmp_path / "postmortem.json"
        proc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.tools.postmortem",
             str(blackbox), "--json", str(out_json)],
            capture_output=True, text=True, timeout=300, cwd=ROOT)
        assert proc.returncode == 0, proc.stderr[-3000:]
        report = json.loads(out_json.read_text())
        rows = report["numerics"]["divergence"]
        assert rows and rows[0]["rank"] == 1
        assert "w" in rows[0]["leaf"]
        assert "Cross-rank divergence" in proc.stdout
