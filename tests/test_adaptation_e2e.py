"""Self-healing acceptance (ISSUE 6, docs/adaptation.md) — slow tier.

Three real multi-process scenarios over the TCP control plane:

  1. Injected-slow-rank recovery: 4 processes, rank 2 delayed 100 ms per
     step via ``HOROVOD_TPU_FAULT_SPEC``. The adaptation policy
     escalates degradation tiers, evicts the rank, the elastic driver
     re-rendezvouses at np=3, and steady-state step time recovers to
     >= 1.5x the unmitigated stalled throughput with no human
     intervention — the recovery curve lands in BENCH_STRAGGLER-shaped
     data and the transitions in ``hvdtpu_adaptation_*`` metrics.
  2. Evicted-host readmission: after the (generation-gated) fault
     clears, the blacklist expires, the readmission probe passes, and
     the host grows back in; the final training state matches a clean
     replay from the restored commit at rtol 1e-5 (the PR 1 elastic
     equivalence harness).
  3. drop_announce → failure plane: a mute-but-breathing rank is
     escalated from repeated stall reports to a typed WorkerFailure and
     the elastic driver relaunches past it.
"""

import json
import os
import sys
import time

import numpy as np
import pytest

pytestmark = pytest.mark.slow

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from horovod_tpu.elastic import FailureConfig, run_elastic  # noqa: E402
from horovod_tpu.elastic.discovery import host_alive        # noqa: E402
from horovod_tpu.runner.api import run as plain_run         # noqa: E402

_BASE_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
    "HOROVOD_TPU_DISABLE_NATIVE": "1",
    "HOROVOD_CYCLE_TIME": "1",
}


class TestSlowRankRecovery:
    def test_policy_escalates_evicts_and_recovers(self, tmp_path):
        import bench_engine

        raw = bench_engine.run_straggler_pair(str(tmp_path), steps=20,
                                              commit_every=2)
        med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731

        # Unmitigated arm: the whole fleet runs at the straggler's pace
        # (>= the injected delay) for every step.
        un = raw["unmitigated_steps"]
        assert len(un) == 20
        un_steady = med([r["t_ms"] for r in un[len(un) // 2:]])
        assert un_steady >= bench_engine.STRAGGLER_DELAY_MS

        # Adaptive arm: evicted the slow rank, finished at np=3 in a
        # later generation, and the post-recovery steady state beats the
        # stalled one by the acceptance margin.
        assert raw["final_world_size"] == bench_engine.STRAGGLER_NP - 1
        assert raw["final_generation"] >= 1
        tl = raw["adaptive_timeline"]
        assert {r["step"] for r in tl} == set(range(20))  # no step lost
        rec = [r["t_ms"] for r in tl if r["gen"] > 0]
        rec_steady = med(rec[len(rec) // 2:])
        assert un_steady / rec_steady >= 1.5

        # Adaptation events visible in the metrics: the full ladder ran
        # and the eviction names the injected straggler.
        g0 = raw["adaptation_metrics"]["g0"]
        trans = g0["hvdtpu_adaptation_transitions_total"]["values"]
        for tier in ("shrink", "bf16", "int8x256", "fp8x256", "evict"):
            assert trans.get(f'action="escalate",tier="{tier}"') == 1.0
        ev = g0["hvdtpu_adaptation_evictions_total"]["values"]
        assert ev.get(f'rank="{bench_engine.STRAGGLER_RANK}"') == 1.0


def _make_quadratic_worker():
    """Deterministic quadratic descent (the PR 1 elastic equivalence
    harness): data is a pure function of (step, rank), gradients are
    averaged over the world, so a trajectory depends only on (start
    state, world size) — a clean replay from the same commit at the
    same world size must match bit-for-bit up to float tolerance."""

    def worker(total_steps, commit_every, replay_from=None):
        import jax.numpy as jnp
        import numpy as np

        import horovod_tpu as hvd

        hvd.init()
        r = hvd.process_rank()
        gen = hvd.generation()
        state = hvd.ElasticState(params={"w": jnp.zeros((4,))})
        state.restore(step=replay_from)
        w = jnp.asarray(state.params["w"])
        start = int(state.step)
        target = jnp.asarray([1.0, -2.0, 3.0, 0.5])
        for step in range(start, total_steps):
            scale = 1.0 + 0.1 * ((step * 7 + r * 3) % 5)
            grad = scale * (w - target)
            grad = hvd.allreduce(grad, average=True, name=f"g.{step}")
            w = w - 0.1 * grad
            state.params = {"w": w}
            if replay_from is None and (step + 1) % commit_every == 0:
                state.commit(step + 1)
        return {"w": np.asarray(w).tolist(), "gen": gen,
                "size": hvd.size(), "start": start, "rank": r}

    return worker


class TestEvictedHostReadmission:
    def test_probe_readmits_and_state_matches_replay(self, tmp_path):
        """gen 0: rank 2 slow → evicted (short slow-rank blacklist).
        gen 1: np=3 (the slot is still penalized); an injected crash
        ends it. gen 2: the blacklist expired and the readmission probe
        passed → the world regrows to np=4 and finishes. The final
        state equals a clean np=4 replay from the commit gen 2 restored
        (rtol 1e-5)."""
        state_dir = str(tmp_path / "estate")
        total, commit_every = 24, 2
        probe_calls = []

        def probe(host):
            probe_calls.append(host)
            return host_alive(host)

        env = dict(_BASE_ENV, **{
            "HOROVOD_TPU_FAULT_SPEC":
                "rank=2:delay=100ms:gen=0; rank=0:crash_at=14:gen=1",
            "HOROVOD_TPU_ADAPTATION": "1",
            "HOROVOD_TPU_ADAPT_THRESHOLD": "0.03",
            "HOROVOD_TPU_ADAPT_SUSTAIN": "0.3",
            "HOROVOD_TPU_ADAPT_COOLDOWN": "30",
            "HOROVOD_TPU_ADAPT_INTERVAL": "0.1",
            "HOROVOD_TPU_STALL_CHECK_DISABLE": "1",
        })
        # Windows sized against the generation lifecycle: the slow-rank
        # blacklist (5 s) outlasts gen 1's launch (backoff 1 s) so the
        # evicted slot stays out, and expires before gen 2's discovery
        # (gen 1 runtime + 3 s backoff) so the probe can readmit it;
        # the crash blacklist (0.5 s) expires during the backoff alone.
        cfg = FailureConfig(failure_timeout_s=60.0, max_restarts=3,
                            backoff_s=1.0, backoff_factor=3.0,
                            blacklist_s=0.5, slow_blacklist_s=5.0,
                            readmit_probe=probe)
        results = run_elastic(
            _make_quadratic_worker(), args=(total, commit_every),
            min_np=1, max_np=4, hosts="localhost:4",
            state_dir=state_dir, config=cfg,
            extra_env=env, start_timeout=300)

        final = results[0]
        assert final["gen"] == 2            # evict, crash, then regrow
        assert final["size"] == 4           # the host was readmitted
        assert len(results) == 4
        assert probe_calls                  # the probe gated readmission
        restored_step = final["start"]
        assert 0 < restored_step < total

        # Equivalence harness: clean np=4 replay from the same commit.
        replay = plain_run(
            _make_quadratic_worker(), args=(total, commit_every),
            kwargs={"replay_from": restored_step}, np=4,
            extra_env=dict(_BASE_ENV,
                           HOROVOD_TPU_ELASTIC_DIR=state_dir),
            start_timeout=300)
        np.testing.assert_allclose(final["w"], replay[0]["w"], rtol=1e-5)
        assert replay[0]["start"] == restored_step


class TestDropAnnounceEscalation:
    def test_mute_rank_escalates_to_failure_and_recovers(self, tmp_path):
        """satellite: a stalled-tensor warning naming the same missing
        rank repeatedly surfaces as a WorkerFailure to the elastic
        driver (instead of warning forever), proven with a
        drop_announce fault; the relaunched generation (fault is
        gen-gated) completes."""
        # from_step=8 places the mute past rank 1's restore broadcasts
        # (~4 ticks) and past the first commit's barrier, so generation
        # 1 provably resumes from a commit instead of step 0.
        env = dict(_BASE_ENV, **{
            "HOROVOD_TPU_FAULT_SPEC":
                "rank=1:drop_announce:from_step=8:gen=0",
            "HOROVOD_TPU_STALL_WARNING": "0.5",
            "HOROVOD_TPU_FAILURE_TIMEOUT": "2",
        })
        cfg = FailureConfig(failure_timeout_s=2.0, max_restarts=2,
                            backoff_s=0.2)
        results = run_elastic(
            _make_quadratic_worker(), args=(8, 2),
            min_np=1, max_np=2, hosts="localhost:2",
            state_dir=str(tmp_path / "estate"), config=cfg,
            extra_env=env, start_timeout=300)
        # The mute generation died on a typed failure and the relaunch
        # (no fault in gen >= 1) finished the job from its last commit.
        assert all(r["gen"] >= 1 for r in results)
        assert all(r["start"] >= 2 for r in results)


class TestBenchStragglerReproducible:
    def test_bench_writes_json_and_recovery_ratio_above_one(self, tmp_path):
        import bench_engine

        out = tmp_path / "BENCH_STRAGGLER.json"
        result = bench_engine.main_straggler(str(out), steps=16)
        on_disk = json.loads(out.read_text())
        assert on_disk["metric"] == "straggler_recovery"
        # Deterministic fields: the eviction target, the final world
        # shape, and the complete ladder.
        assert on_disk["straggler_rank"] == bench_engine.STRAGGLER_RANK
        rows = on_disk["rows"]
        assert rows["adaptive"]["final_world_size"] == 3
        assert rows["adaptive"]["final_generation"] >= 1
        evs = on_disk["adaptation_events"]
        assert evs["evictions"].get(
            f'rank="{bench_engine.STRAGGLER_RANK}"') == 1.0
        # The headline: recovery beats the stalled baseline.
        assert on_disk["recovered_throughput_ratio"] is not None
        assert on_disk["recovered_throughput_ratio"] > 1.0
        assert result["recovered_throughput_ratio"] > 1.0
        # Step timeline covers every step exactly once.
        assert [r["step"] for r in on_disk["step_timeline"]] == \
            sorted({r["step"] for r in on_disk["step_timeline"]})
