"""Per-request serving trace plane (docs/serving.md#request-tracing):
histogram exemplars in the registry, the reqtrace writer + engine/server
span emission under one stable trace id, flight-recorder request
lifecycle events feeding the postmortem's in-flight listing, and the
``tools/trace serving`` latency-budget report (multi-process failover
chains included, via synthetic writers). The full-fleet acceptance e2e
(real replicas, injected crash, merged trace + exemplar link) is
test_fleet_e2e.py (slow tier)."""

import http.client
import json
import os
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import horovod_tpu as hvd
from horovod_tpu.models import transformer as tfm
from horovod_tpu.observability import flight_recorder as _flight
from horovod_tpu.observability.registry import (LATENCY_BUCKETS,
                                                Histogram, registry,
                                                set_enabled)
from horovod_tpu.parallel.mesh import create_mesh
from horovod_tpu.serving import InferenceEngine, ServingConfig
from horovod_tpu.serving import reqtrace
from horovod_tpu.serving.server import ServingServer
from horovod_tpu.tools import postmortem
from horovod_tpu.tools.trace import (expand_inputs, format_serving_report,
                                     load_rank_trace, load_traces,
                                     merge_traces, serving_report)


def _cfg(**over):
    kw = dict(vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
              max_seq=64, dtype=jnp.float32, remat=False)
    kw.update(over)
    return tfm.TransformerConfig(**kw)


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    params = tfm.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture(scope="module")
def mesh1():
    return create_mesh(devices=jax.devices()[:1], tp=1)


def _engine(params, cfg, mesh, **over):
    kw = dict(block_size=4, kv_blocks=40, max_batch_slots=4,
              max_queue=8, max_new_tokens=8, min_prefill_bucket=8)
    kw.update(over)
    return InferenceEngine(params, cfg, mesh, ServingConfig(**kw))


@pytest.fixture(autouse=True)
def _no_leftover_writer():
    yield
    reqtrace.stop()


# ---------------------------------------------------------------------------
# Histogram exemplars (registry)
# ---------------------------------------------------------------------------

class TestHistogramExemplars:
    def test_none_until_an_exemplar_observation(self):
        h = Histogram(LATENCY_BUCKETS)
        h.observe(0.5)
        assert h.exemplar is None
        assert "exemplar" not in h.snapshot()

    def test_worst_observation_wins(self):
        h = Histogram(LATENCY_BUCKETS)
        h.observe(0.2, exemplar="small", now=100.0)
        h.observe(0.9, exemplar="big", now=101.0)
        h.observe(0.4, exemplar="mid", now=102.0)
        ex = h.exemplar
        assert ex["trace_id"] == "big" and ex["value"] == 0.9
        snap = h.snapshot()
        assert snap["exemplar"]["trace_id"] == "big"
        # equal value also replaces (most recent worst is freshest link)
        h.observe(0.9, exemplar="big2", now=103.0)
        assert h.exemplar["trace_id"] == "big2"

    def test_stale_champion_expires(self):
        """'Worst RECENT': past the TTL any exemplar-carrying
        observation replaces the old champion, so the link never pins
        a request from an hour ago."""
        h = Histogram(LATENCY_BUCKETS, exemplar_ttl_s=10.0)
        h.observe(5.0, exemplar="ancient", now=100.0)
        h.observe(0.1, exemplar="later-smaller", now=105.0)
        assert h.exemplar["trace_id"] == "ancient"   # within TTL
        h.observe(0.1, exemplar="fresh", now=111.0)  # past TTL
        assert h.exemplar["trace_id"] == "fresh"
        assert h.exemplar["value"] == 0.1

    def test_zero_cost_when_metrics_disabled(self):
        h = Histogram(LATENCY_BUCKETS)
        set_enabled(False)
        try:
            h.observe(9.0, exemplar="never")
        finally:
            set_enabled(True)
        assert h.count == 0 and h.exemplar is None

    def test_family_passthrough_and_snapshot_surface(self):
        fam = registry().histogram(
            "hvdtpu_test_exemplar_seconds", "test only",
            buckets=LATENCY_BUCKETS)
        fam.observe(0.25, exemplar="req-xyz")
        snap = hvd.metrics_snapshot()
        val = snap["hvdtpu_test_exemplar_seconds"]["values"][""]
        assert val["exemplar"]["trace_id"] == "req-xyz"
        # strict-JSON export keeps it (the /metrics.json surface)
        from horovod_tpu.observability.export import json_safe_snapshot
        js = json_safe_snapshot()
        ex = js["hvdtpu_test_exemplar_seconds"]["values"][""]["exemplar"]
        json.dumps(ex)   # json-safe
        assert ex["trace_id"] == "req-xyz"


# ---------------------------------------------------------------------------
# Writer + engine span emission
# ---------------------------------------------------------------------------

def _rows_and_spans(path):
    """trace-id row name → list of span dicts, from one capture."""
    t = load_rank_trace(path)
    from horovod_tpu.tools.trace import _spans
    out = {}
    for s in _spans(t.events):
        row = t.tensor_of.get(s["pid"])
        out.setdefault(row, []).append(s)
    return t, out


class TestWriterAndEngineSpans:
    def test_writer_meta_and_span_roundtrip(self, tmp_path):
        path = str(tmp_path / "w.trace.json")
        reqtrace.start(path, rank=7, proc="replica7")
        t0 = time.monotonic()
        reqtrace.span("req-1", "PREFILL", t0, t0 + 0.01,
                      {"bucket": 16, "tokens": 9})
        reqtrace.stop()
        t, rows = _rows_and_spans(path)
        assert t.rank == 7 and t.proc == "replica7"
        assert t.meta.get("clock_synced") is True
        (s,) = rows["req-1"]
        assert s["name"] == "PREFILL"
        assert s["args"] == {"bucket": 16, "tokens": 9}
        assert 9000 <= s["dur"] <= 11000   # ~10ms in µs

    def test_engine_emits_request_lifecycle_spans(self, model, mesh1,
                                                  tmp_path):
        cfg, params = model
        eng = _engine(params, cfg, mesh1)
        path = str(tmp_path / "eng.trace.json")
        reqtrace.start(path, rank=1, proc="replica0")
        r1 = eng.submit([1, 2, 3, 4, 5], trace_id="trace-a")
        r2 = eng.submit([9, 8, 7], max_new_tokens=4)
        eng.run_until_idle()
        r1.result(), r2.result()
        reqtrace.stop()
        _, rows = _rows_and_spans(path)
        assert set(rows) >= {"trace-a", r2.trace_id}
        names_a = [s["name"] for s in rows["trace-a"]]
        assert names_a.count("QUEUE_WAIT") == 1
        assert names_a.count("ADMIT") == 1
        assert names_a.count("PREFILL") == 1
        # 8 tokens total, first from prefill → 7 decode chunks
        assert names_a.count("DECODE") == 7
        pre = next(s for s in rows["trace-a"] if s["name"] == "PREFILL")
        assert pre["args"]["bucket"] == 8 and pre["args"]["tokens"] == 5
        adm = next(s for s in rows["trace-a"] if s["name"] == "ADMIT")
        assert adm["args"]["blocks"] > 0

    def test_budget_report_attributes_engine_wall(self, model, mesh1,
                                                  tmp_path):
        """Single-process capture: queue+prefill+decode explain the
        span-extremes wall almost completely (the report's budget
        machinery, before any router/failover enters)."""
        cfg, params = model
        eng = _engine(params, cfg, mesh1, max_batch_slots=2)
        path = str(tmp_path / "budget.trace.json")
        reqtrace.start(path, rank=1, proc="replica0")
        reqs = [eng.submit([i + 1] * 6) for i in range(4)]
        eng.run_until_idle()
        for r in reqs:
            r.result()
        reqtrace.stop()
        report = serving_report(load_traces([path]))
        assert report["n_requests"] == 4
        for tid, row in report["requests"].items():
            assert row["wall_ms"] > 0
            assert 0.7 <= row["attributed_frac"] <= 1.02, (tid, row)
            # 2 slots, 4 requests: the late pair queued — its queue
            # share must be visible in the budget
        waited = [r for r in report["requests"].values()
                  if r["phase_share"]["queue"] > 0.2]
        assert len(waited) >= 2
        # slowest ranking covers all and is sorted
        walls = [r["wall_ms"] for r in
                 (report["requests"][s["trace"]]
                  for s in report["slowest"])]
        assert walls == sorted(walls, reverse=True)
        assert format_serving_report(report)   # renders

    def test_ttft_exemplar_links_to_a_traced_request(self, model,
                                                     mesh1):
        cfg, params = model
        eng = _engine(params, cfg, mesh1)
        r = eng.submit(list(range(1, 9)), trace_id="exemplar-probe")
        eng.run_until_idle()
        r.result()
        snap = hvd.metrics_snapshot()
        ex = snap["hvdtpu_serving_ttft_seconds"]["values"][""].get(
            "exemplar")
        assert ex is not None
        # the worst recent TTFT belongs to SOME engine request id; this
        # request just ran, so at minimum the id format links back
        assert isinstance(ex["trace_id"], str) and ex["trace_id"]
        qw = snap["hvdtpu_serving_queue_wait_seconds"]["values"][""]
        assert qw["count"] >= 1


# ---------------------------------------------------------------------------
# One identity through the HTTP front
# ---------------------------------------------------------------------------

class TestServerRequestId:
    def _server(self, model, mesh1):
        cfg, params = model
        eng = _engine(params, cfg, mesh1)
        srv = ServingServer(eng, port=0, host="127.0.0.1")
        srv.start()
        return srv

    def _post(self, port, body, headers=None):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        h = {"Content-Type": "application/json"}
        h.update(headers or {})
        conn.request("POST", "/generate", json.dumps(body), h)
        resp = conn.getresponse()
        raw = resp.read()
        return resp.status, raw

    def test_x_request_id_rides_into_engine_and_back(self, model,
                                                     mesh1):
        srv = self._server(model, mesh1)
        try:
            status, raw = self._post(
                srv.port, {"tokens": [1, 2, 3], "max_new_tokens": 3},
                headers={"X-Request-Id": "router-id-42"})
            assert status == 200
            body = json.loads(raw)
            assert body["trace_id"] == "router-id-42"

            # NDJSON: header and done lines both carry it
            status, raw = self._post(
                srv.port, {"tokens": [4, 5], "max_new_tokens": 3,
                           "stream": True},
                headers={"X-Request-Id": "router-id-43"})
            lines = [json.loads(ln) for ln in raw.splitlines()
                     if ln.strip()]
            assert lines[0]["trace_id"] == "router-id-43"
            assert lines[-1]["done"] and \
                lines[-1]["trace_id"] == "router-id-43"

            # absent header → engine mints one
            status, raw = self._post(
                srv.port, {"tokens": [6], "max_new_tokens": 2})
            assert json.loads(raw)["trace_id"]
        finally:
            srv.request_stop()
            srv.shutdown()


# ---------------------------------------------------------------------------
# Flight recorder request events → postmortem in-flight listing
# ---------------------------------------------------------------------------

class TestRequestEventsAndPostmortem:
    def test_engine_notes_request_lifecycle(self, model, mesh1):
        cfg, params = model
        _flight.reset()
        eng = _engine(params, cfg, mesh1)
        r = eng.submit([3, 1, 4], max_new_tokens=3,
                       trace_id="flight-req")
        eng.run_until_idle()
        r.result()
        events = [(kind, payload) for _, kind, payload
                  in _flight.recorder()._ring if kind == "request"]
        kinds = [p[0] for _, p in events
                 if p[1] == "flight-req"]
        assert kinds == ["admit", "first_token", "evict", "finish"]

    def test_postmortem_names_inflight_requests(self, model, mesh1,
                                                tmp_path):
        """A dump taken mid-generation (what a crashed replica leaves)
        lists the admitted-but-unfinished requests and their phase."""
        cfg, params = model
        _flight.reset()
        _flight.recorder().configure(rank=1, world=0)
        eng = _engine(params, cfg, mesh1)
        done = eng.submit([5, 5], max_new_tokens=2, trace_id="done-req")
        eng.run_until_idle()
        done.result()
        live = eng.submit([1, 2, 3, 4], max_new_tokens=8,
                          trace_id="live-req")
        eng.step()   # admit + prefill + first decode — then "crash"
        assert not live.done
        path = _flight.recorder().dump("fault_crash",
                                       directory=str(tmp_path))
        dump = postmortem.load_dump(path)
        report = postmortem.analyze([dump])
        infl = report["per_rank"]["1"]["inflight_requests"]
        assert infl == [{"trace": "live-req", "phase": "decode"}]
        text = postmortem.format_report(report)
        assert "In-flight requests on rank 1" in text
        assert "live-req (decode)" in text
        _flight.reset()


# ---------------------------------------------------------------------------
# Multi-process report: failover chains, merge, discovery
# ---------------------------------------------------------------------------

def _synthetic_fleet_capture(d):
    """Hand-build the three captures a failed-over request leaves:
    router (REQUEST/DISPATCH/FAILOVER), the replica that died, and the
    resume replica (re-prefill + remaining decode). Times in seconds on
    the shared monotonic clock."""
    t = time.monotonic()
    rt = reqtrace.start(os.path.join(d, "reqtrace-router.trace.json"),
                        rank=0, proc="router")
    rt.request_span("req-f", "REQUEST", t, t + 1.0,
                    {"status": "completed", "retries": 1})
    rt.request_span("req-f", "DISPATCH", t, t + 0.4,
                    {"replica": 1, "outcome": "crash"})
    rt.request_span("req-f", "FAILOVER", t + 0.4, t + 0.62,
                    {"phase": "midstream", "from": 1, "to": 2})
    rt.request_span("req-f", "DISPATCH", t + 0.41, t + 1.0,
                    {"replica": 2, "outcome": "done"})
    rt.request_span("req-ok", "REQUEST", t, t + 0.5,
                    {"status": "completed", "retries": 0})
    reqtrace.stop()

    r1 = reqtrace.start(
        os.path.join(d, "reqtrace-replica1-gen0.trace.json"),
        rank=101, proc="replica1")
    r1.request_span("req-f", "QUEUE_WAIT", t + 0.01, t + 0.02)
    r1.request_span("req-f", "PREFILL", t + 0.02, t + 0.10,
                    {"bucket": 16, "tokens": 12, "cached": 0,
                     "compile": False})
    r1.request_span("req-f", "DECODE", t + 0.10, t + 0.40, {"n": 1})
    reqtrace.stop()

    r2 = reqtrace.start(
        os.path.join(d, "reqtrace-replica2-gen0.trace.json"),
        rank=201, proc="replica2")
    # resume: re-prefill of prompt+emitted inside the failover window
    r2.request_span("req-f", "QUEUE_WAIT", t + 0.42, t + 0.44)
    r2.request_span("req-f", "PREFILL", t + 0.44, t + 0.60,
                    {"bucket": 32, "tokens": 24, "cached": 0,
                     "compile": False})
    r2.request_span("req-f", "DECODE", t + 0.60, t + 0.98, {"n": 1})
    r2.request_span("req-ok", "QUEUE_WAIT", t, t + 0.01)
    r2.request_span("req-ok", "PREFILL", t + 0.01, t + 0.09,
                    {"bucket": 16, "tokens": 10, "cached": 0,
                     "compile": False})
    r2.request_span("req-ok", "DECODE", t + 0.09, t + 0.5, {"n": 1})
    reqtrace.stop()


class TestServingReportMultiProcess:
    def test_failover_chain_budget_and_merge(self, tmp_path):
        d = str(tmp_path)
        _synthetic_fleet_capture(d)
        paths = expand_inputs([d])   # directory discovery
        assert len(paths) == 3
        traces = load_traces(paths)
        report = serving_report(traces)

        req = report["requests"]["req-f"]
        # spans cross all three processes under ONE trace id
        assert req["processes"] == ["replica1", "replica2", "router"]
        assert abs(req["wall_ms"] - 1000.0) < 1.0
        ph = req["phase_ms"]
        # queue 10+20ms, prefill 80+160ms, decode 300+380ms; failover
        # window 220ms minus the overlapped resume queue(20) +
        # prefill(160) + first decode slice(20) = 20 — only the true
        # detection/re-dispatch dead time counts as failover
        assert abs(ph["queue"] - 30.0) < 2.0
        assert abs(ph["prefill"] - 240.0) < 2.0
        assert abs(ph["decode"] - 680.0) < 2.0
        assert abs(ph["failover"] - 20.0) < 2.0
        assert 0.95 <= req["attributed_frac"] <= 1.01
        (chain,) = req["failovers"]
        assert chain["phase"] == "midstream"
        assert chain["from_replica"] == 1 and chain["to_replica"] == 2
        assert abs(chain["detect_to_resume_ms"] - 220.0) < 1.0
        assert abs(chain["reprefill_ms"] - 160.0) < 1.0
        assert chain["reprefill_tokens"] == 24
        assert chain["reprefill_proc"] == "replica2"
        # slowest-first ranking puts the failed-over request on top
        assert report["slowest"][0]["trace"] == "req-f"
        assert report["n_failovers"] == 1

        # the merged catapult view names processes, not ranks, and the
        # failed request's row appears under all three
        out = os.path.join(d, "merged.json")
        merge_traces(traces, out)
        merged = json.load(open(out))
        procs = {e["args"]["name"] for e in merged
                 if e.get("name") == "process_name"}
        assert {"router", "replica1", "replica2"} <= procs
        row_pids = {e["pid"] for e in merged
                    if e.get("name") == "thread_name"
                    and e.get("args", {}).get("name") == "req-f"}
        assert len(row_pids) == 3

    def test_cli_serving_subcommand(self, tmp_path):
        d = str(tmp_path)
        _synthetic_fleet_capture(d)
        import subprocess
        import sys
        out = tmp_path / "report.json"
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        proc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.tools.trace", "serving",
             d, "--report", str(out)],
            capture_output=True, text=True, timeout=120, cwd=root)
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "req-f" in proc.stdout and "Failover:" in proc.stdout
        report = json.loads(out.read_text())
        assert report["requests"]["req-f"]["failovers"]
