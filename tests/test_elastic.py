"""Elastic subsystem tests.

Fast tests cover discovery (including the TPU-pod path against a FAKE
metadata HTTP server — no GCP anywhere), failure typing/detection, the
ElasticState commit/rollback/restore contract, and the escalation
plumbing in the engine and coordinator.

The slow class is the acceptance scenario: a spawned multi-process
elastic run survives a SIGKILL of a non-coordinator worker — the job
shrinks, re-rendezvouses, resumes from the last committed ElasticState,
and the result matches a clean run replayed from that same commit
(rtol 1e-5).
"""

import os
import pickle
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer

import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.elastic import (ElasticState, FailureConfig,
                                 FailureDetector, HostfileProvider,
                                 SSHProbeProvider, StaticProvider,
                                 TPUPodProvider, WorkerFailure,
                                 get_provider)
from horovod_tpu.elastic.discovery import (WORKER_ENDPOINTS_PATH,
                                           _parse_worker_endpoints)

_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
}


# ---------------------------------------------------------------------------
# Discovery
# ---------------------------------------------------------------------------

class TestHostfileProvider:
    def test_parses_all_line_forms(self, tmp_path):
        hf = tmp_path / "hosts"
        hf.write_text(
            "# cluster A\n"
            "alpha slots=4\n"
            "beta:2\n"
            "gamma\n"
            "\n"
            "delta slots=1  # trailing comment\n")
        assert HostfileProvider(str(hf)).discover() == [
            ("alpha", 4), ("beta", 2), ("gamma", 1), ("delta", 1)]

    def test_reread_per_discover(self, tmp_path):
        """Elastic growth: an operator editing the hostfile changes the
        next discovery, not just the first."""
        hf = tmp_path / "hosts"
        hf.write_text("a:1\n")
        p = HostfileProvider(str(hf))
        assert p.discover() == [("a", 1)]
        hf.write_text("a:1\nb:2\n")
        assert p.discover() == [("a", 1), ("b", 2)]


class TestSSHProbeProvider:
    def test_filters_unreachable(self):
        p = SSHProbeProvider([("up1", 2), ("down", 2), ("up2", 1)],
                             probe=lambda h: h.startswith("up"))
        assert p.discover() == [("up1", 2), ("up2", 1)]

    def test_local_hosts_skip_probe(self):
        p = SSHProbeProvider([("localhost", 2)],
                             probe=lambda h: pytest.fail(
                                 "probed a local host"))
        assert p.discover() == [("localhost", 2)]


class TestWorkerEndpointParsing:
    def test_uid_ip_port_triples(self):
        assert _parse_worker_endpoints(
            "uid0:10.0.0.2:8470,uid1:10.0.0.3:8470") == [
                "10.0.0.2", "10.0.0.3"]

    def test_bare_and_mixed(self):
        assert _parse_worker_endpoints(
            "10.0.0.2, host-b:8470, uid:10.0.0.4:8470,,") == [
                "10.0.0.2", "host-b", "10.0.0.4"]


class _FakeMetadata(BaseHTTPRequestHandler):
    body = b"uid0:10.128.0.2:8470,uid1:10.128.0.3:8470"

    def do_GET(self):
        if self.headers.get("Metadata-Flavor") != "Google":
            self.send_response(403)
            self.end_headers()
            return
        if self.path == WORKER_ENDPOINTS_PATH:
            self.send_response(200)
            self.end_headers()
            self.wfile.write(self.body)
        else:
            self.send_response(404)
            self.end_headers()

    def log_message(self, *a):  # keep pytest output clean
        pass


@pytest.fixture
def fake_metadata_server():
    srv = HTTPServer(("127.0.0.1", 0), _FakeMetadata)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        yield f"http://127.0.0.1:{srv.server_address[1]}"
    finally:
        srv.shutdown()
        srv.server_close()


class TestTPUPodProvider:
    def test_discovers_through_fake_metadata_server(
            self, fake_metadata_server):
        p = TPUPodProvider(metadata_addr=fake_metadata_server,
                           slots_per_host=1)
        assert p.discover() == [("10.128.0.2", 1), ("10.128.0.3", 1)]

    def test_slots_per_host(self, fake_metadata_server):
        p = TPUPodProvider(metadata_addr=fake_metadata_server,
                           slots_per_host=4)
        assert p.discover() == [("10.128.0.2", 4), ("10.128.0.3", 4)]

    def test_metadata_addr_env(self, fake_metadata_server, monkeypatch):
        monkeypatch.setenv("HOROVOD_TPU_METADATA_ADDR",
                           fake_metadata_server)
        assert TPUPodProvider().discover() == [
            ("10.128.0.2", 1), ("10.128.0.3", 1)]

    def test_unreachable_metadata_raises_actionable_error(self):
        p = TPUPodProvider(metadata_addr="http://127.0.0.1:1",
                           timeout=0.5)
        with pytest.raises(RuntimeError,
                           match="HOROVOD_TPU_METADATA_ADDR"):
            p.discover()


class TestGetProvider:
    def test_factory_shapes(self, tmp_path):
        hf = tmp_path / "hosts"
        hf.write_text("a:2\n")
        assert isinstance(get_provider(None, hosts="a:2"), StaticProvider)
        assert isinstance(get_provider("hostfile", hostfile=str(hf)),
                          HostfileProvider)
        assert isinstance(get_provider("ssh", hosts="a:2"),
                          SSHProbeProvider)
        assert isinstance(get_provider("tpu-pod", metadata_addr="http://x"),
                          TPUPodProvider)
        with pytest.raises(ValueError, match="hostfile"):
            get_provider("hostfile")
        with pytest.raises(ValueError, match="unknown discovery"):
            get_provider("k8s")


# ---------------------------------------------------------------------------
# Failure typing / detection
# ---------------------------------------------------------------------------

class _FakeWorker:
    def __init__(self, rc=None):
        self.rc = rc

    def poll(self):
        return self.rc


class _FakeJob:
    def __init__(self, rcs):
        self.workers = [_FakeWorker(rc) for rc in rcs]
        self.terminated = False

    def terminate(self):
        self.terminated = True


class TestWorkerFailure:
    def test_typed_fields_and_pickle(self):
        wf = WorkerFailure(rank=3, host="tpu-w-3", kind="killed",
                           detail="exited with code -9")
        assert isinstance(wf, hvd.HorovodInternalError)
        wf2 = pickle.loads(pickle.dumps(wf))
        assert (wf2.rank, wf2.host, wf2.kind) == (3, "tpu-w-3", "killed")
        assert "tpu-w-3" in str(wf2)

    def test_backoff_schedule(self):
        cfg = FailureConfig(backoff_s=1.0, backoff_factor=2.0,
                            max_backoff_s=5.0)
        b = cfg.backoff_s
        seq = []
        for _ in range(4):
            b = cfg.next_backoff(b)
            seq.append(b)
        assert seq == [2.0, 4.0, 5.0, 5.0]


class TestFailureDetector:
    def test_detects_signal_death_as_killed(self):
        job = _FakeJob([None, -9])
        det = FailureDetector(job, ["hostA", "hostB"])
        with pytest.raises(WorkerFailure) as ei:
            det.check()
        assert ei.value.rank == 1
        assert ei.value.host == "hostB"
        assert ei.value.kind == "killed"
        assert job.terminated

    def test_nonzero_exit_is_exit_kind(self):
        det = FailureDetector(_FakeJob([2, None]), ["h0", "h1"])
        with pytest.raises(WorkerFailure) as ei:
            det.check()
        assert ei.value.kind == "exit"
        assert ei.value.rank == 0

    def test_healthy_job_passes(self):
        det = FailureDetector(_FakeJob([None, 0, None]), ["a", "b", "c"])
        det.check()  # no raise
        assert det.failures == []


# ---------------------------------------------------------------------------
# ElasticState
# ---------------------------------------------------------------------------

class TestElasticState:
    def test_commit_rollback_in_memory(self):
        st = ElasticState(params={"w": np.ones(3)})
        st.commit(5)
        st.params = {"w": np.full(3, 9.0)}
        assert st.step == 5
        st.rollback()
        np.testing.assert_array_equal(st.params["w"], np.ones(3))
        assert st.step == 5

    def test_commit_restore_roundtrip(self, tmp_path):
        d = str(tmp_path / "elastic")
        st = ElasticState(directory=d, params={"w": np.arange(4.0)},
                          opt={"m": np.zeros(4)})
        st.commit(5)
        st.params = {"w": np.arange(4.0) * 10}
        st.commit(10)

        fresh = ElasticState(directory=d, params={"w": np.zeros(4)},
                             opt={"m": np.ones(4)})
        fresh.restore()
        assert fresh.step == 10
        np.testing.assert_allclose(fresh.params["w"], np.arange(4.0) * 10)

        older = ElasticState(directory=d, params={"w": np.zeros(4)},
                             opt={"m": np.ones(4)})
        older.restore(step=5)
        assert older.step == 5
        np.testing.assert_allclose(older.params["w"], np.arange(4.0))
        np.testing.assert_allclose(older.opt["m"], np.zeros(4))

    def test_restore_without_commit_keeps_initial(self, tmp_path):
        st = ElasticState(directory=str(tmp_path / "none"),
                          params={"w": np.full(2, 7.0)})
        st.restore()
        assert st.step == 0
        np.testing.assert_array_equal(st.params["w"], np.full(2, 7.0))

    def test_latest_repointed_atomically(self, tmp_path):
        d = str(tmp_path / "e2")
        st = ElasticState(directory=d, params={"w": np.zeros(1)})
        st.commit(3)
        with open(os.path.join(d, "LATEST")) as f:
            assert f.read().strip() == "3"
        assert os.path.exists(os.path.join(d, "3.pkl"))

    def test_requires_trees(self):
        with pytest.raises(ValueError, match="named tree"):
            ElasticState()

    def test_pickle_commits_are_garbage_collected(self, tmp_path):
        """Disk commits are no longer unbounded: keep-last-N retention
        prunes old <step>.pkl files and never touches the one LATEST
        names."""
        d = str(tmp_path / "gc")
        st = ElasticState(directory=d, keep_last=3,
                          params={"w": np.ones(2)})
        for step in range(1, 9):
            st.commit(step)
        pkls = sorted(int(f[:-4]) for f in os.listdir(d)
                      if f.endswith(".pkl"))
        assert pkls == [6, 7, 8]
        with open(os.path.join(d, "LATEST")) as f:
            assert int(f.read().strip()) == 8
        # the retained window still restores
        older = ElasticState(directory=d, params={"w": np.zeros(2)})
        older.restore(step=6)
        assert older.step == 6

    def test_keep_env_knob(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HOROVOD_TPU_CHECKPOINT_KEEP", "2")
        d = str(tmp_path / "gcenv")
        st = ElasticState(directory=d, params={"w": np.ones(2)})
        for step in range(1, 6):
            st.commit(step)
        pkls = sorted(int(f[:-4]) for f in os.listdir(d)
                      if f.endswith(".pkl"))
        assert pkls == [4, 5]

    def test_unknown_backend_rejected(self):
        with pytest.raises(ValueError, match="backend"):
            ElasticState(backend="orbax", params={"w": np.ones(1)})
        with pytest.raises(ValueError, match="shared filesystem"):
            ElasticState(backend="sharded", params={"w": np.ones(1)})


class TestElasticStateShardedBackend:
    """backend='sharded': elastic commit/restore riding the checkpoint
    engine (docs/checkpoint.md) — async commits, manifest LATEST,
    engine retention, restore-from-shared-dir."""

    def _state(self, d, scale=1.0, **kw):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        mesh = Mesh(np.asarray(jax.devices(), dtype=object).reshape(8),
                    ("dp",))
        sharded = jax.device_put(
            jnp.arange(32.0) * scale, NamedSharding(mesh, P("dp")))
        return ElasticState(directory=d, backend="sharded",
                            params={"w": np.arange(4.0) * scale},
                            opt={"m": sharded}, **kw)

    def test_commit_restore_roundtrip(self, tmp_path):
        d = str(tmp_path / "sharded")
        st = self._state(d)
        st.commit(5)
        st.params = {"w": np.arange(4.0) * 10}
        st.commit(10, block=True)
        assert os.path.exists(os.path.join(d, "step-10",
                                           "manifest.json"))

        fresh = self._state(d, scale=0.0)
        fresh.restore()
        assert fresh.step == 10
        np.testing.assert_allclose(fresh.params["w"],
                                   np.arange(4.0) * 10)
        np.testing.assert_allclose(np.asarray(fresh.opt["m"]),
                                   np.arange(32.0))

        older = self._state(d, scale=0.0)
        older.restore(step=5)
        assert older.step == 5
        np.testing.assert_allclose(older.params["w"], np.arange(4.0))

    def test_async_commit_joined_by_next(self, tmp_path):
        d = str(tmp_path / "sharded2")
        st = self._state(d)
        st.commit(1)               # returns before the write finishes
        st.commit(2)               # joins 1, enqueues 2
        st.wait()
        from horovod_tpu.checkpoint import read_latest
        assert read_latest(d) == 2

    def test_rollback_and_restore_without_commit(self, tmp_path):
        st = self._state(str(tmp_path / "sharded3"))
        st.commit(3, block=True)
        st.params = {"w": np.full(4, 99.0)}
        st.rollback()
        np.testing.assert_allclose(st.params["w"], np.arange(4.0))
        assert st.step == 3

        st2 = self._state(str(tmp_path / "sharded4"))
        st2.restore()              # no commit on disk: initial trees
        assert st2.step == 0
        np.testing.assert_allclose(st2.params["w"], np.arange(4.0))

    def test_engine_retention_applies(self, tmp_path):
        d = str(tmp_path / "sharded5")
        st = self._state(d, keep_last=2)
        for step in range(1, 6):
            st.commit(step)
        st.wait()
        from horovod_tpu.checkpoint import list_steps
        assert list_steps(d) == [4, 5]


# ---------------------------------------------------------------------------
# Escalation plumbing (engine + coordinator)
# ---------------------------------------------------------------------------

class TestEngineStallEscalation:
    def test_overdue_request_fails_with_worker_failure(self):
        from horovod_tpu.ops import collective as coll

        eng = coll.CollectiveEngine()
        eng.stall_warning_s = 0.01
        eng.failure_timeout_s = 0.05
        eng._last_stall_check = time.monotonic() - 100
        h = eng.make_handle("stall.t")
        req = coll._Request("stall.t", coll.ALLREDUCE,
                            np.ones(4, np.float32), h)
        req.enqueued_at = time.monotonic() - 10
        eng._in_flight["stall.t"] = req
        eng._maybe_check_stalls()
        assert h.poll()
        with pytest.raises(WorkerFailure, match="failure timeout"):
            h.wait()
        assert "stall.t" not in eng._in_flight

    def test_disabled_timeout_keeps_warn_only(self):
        from horovod_tpu.ops import collective as coll

        eng = coll.CollectiveEngine()
        eng.stall_warning_s = 0.01
        eng.failure_timeout_s = 0.0   # seed behavior
        eng._last_stall_check = time.monotonic() - 100
        h = eng.make_handle("warn.t")
        req = coll._Request("warn.t", coll.ALLREDUCE,
                            np.ones(4, np.float32), h)
        req.enqueued_at = time.monotonic() - 10
        eng._in_flight["warn.t"] = req
        eng._maybe_check_stalls()
        assert not h.poll()           # still pending, only warned
        eng._in_flight.clear()

    def test_fetch_side_channel_failures_fail_pending(self):
        from horovod_tpu.ops import collective as coll
        from horovod_tpu.ops.control_plane import FetchResponse

        eng = coll.CollectiveEngine()
        h = eng.make_handle("mp.t")
        req = coll._Request("mp.t", coll.ALLREDUCE,
                            np.ones(2, np.float32), h)
        eng._in_flight["mp.t"] = req
        resp = FetchResponse([], False, failures=[
            {"rank": 1, "kind": "heartbeat_timeout",
             "detail": "rank 1 silent for 31.0s"}])
        eng._apply_fetch_side_channel(resp)
        with pytest.raises(WorkerFailure) as ei:
            h.wait()
        assert ei.value.rank == 1
        assert ei.value.kind == "heartbeat_timeout"


class TestCoordinatorFailureDetection:
    def _svc(self):
        from horovod_tpu.ops.control_plane import CoordinatorService
        from horovod_tpu.runner.secret import make_secret_key

        svc = CoordinatorService(2, make_secret_key(), native=False)
        svc.failure_timeout_s = 0.25
        return svc

    def _req(self, name):
        return {"name": name, "op": 0, "dtype": "float32",
                "shape": (4,), "root_rank": -1, "device": 0}

    def test_heartbeat_and_stall_escalation(self):
        from horovod_tpu.ops.control_plane import (AnnounceRequest,
                                                   FetchRequest)

        svc = self._svc()
        try:
            # Both ranks check in once; rank 0 announces a tensor rank 1
            # never will.
            svc._announce(AnnounceRequest(0, [self._req("e.t")],
                                          announce_id=1))
            svc._announce(AnnounceRequest(1, [], announce_id=1))
            resp = svc._fetch(FetchRequest(0, 0, 0.0))
            assert resp.failures == []          # nothing overdue yet
            time.sleep(0.35)
            resp = svc._fetch(FetchRequest(0, resp.groups[-1]["seq"] + 1
                                           if resp.groups else 0, 0.0))
            kinds = {f["kind"] for f in resp.failures}
            assert "heartbeat_timeout" in kinds  # rank 1 went silent
            assert "stall" in kinds              # e.t stuck partial
            ranks = {f["rank"] for f in resp.failures}
            assert 1 in ranks
            assert 0 not in ranks                # the fetching rank is alive
        finally:
            svc.shutdown()

    def test_never_seen_ranks_not_flagged(self):
        """Initial rendezvous may be slow; a rank that has never
        contacted the coordinator is not declared dead."""
        from horovod_tpu.ops.control_plane import FetchRequest

        svc = self._svc()
        try:
            time.sleep(0.3)
            resp = svc._fetch(FetchRequest(0, 0, 0.0))
            assert resp.failures == []
        finally:
            svc.shutdown()

    def test_disabled_by_default(self, monkeypatch):
        from horovod_tpu.ops.control_plane import CoordinatorService
        from horovod_tpu.runner.secret import make_secret_key

        monkeypatch.delenv("HOROVOD_TPU_FAILURE_TIMEOUT", raising=False)
        monkeypatch.delenv("HOROVOD_FAILURE_TIMEOUT", raising=False)
        svc = CoordinatorService(2, make_secret_key(), native=False)
        try:
            assert svc.failure_timeout_s == 0.0
            assert svc.check_failures() == []
        finally:
            svc.shutdown()


# ---------------------------------------------------------------------------
# CLI discovery
# ---------------------------------------------------------------------------

class TestRunnerDiscoveryCLI:
    def test_hostfile_discovery_sizes_and_runs(self, tmp_path, capsys):
        import sys
        from horovod_tpu.runner.__main__ import main

        hf = tmp_path / "hosts"
        hf.write_text("localhost slots=2\n")
        rc = main(["--discovery", "hostfile", "--hostfile", str(hf),
                   "--no-tag-output", "--",
                   sys.executable, "-c", "print('cli-ok')"])
        assert rc == 0
        err = capsys.readouterr().err
        assert "[discovery:hostfile] 1 host(s), 2 slot(s)" in err

    def test_tpu_pod_discovery_through_fake_metadata(
            self, tmp_path, capsys, monkeypatch):
        import sys
        from horovod_tpu.runner.__main__ import main

        srv = HTTPServer(("127.0.0.1", 0), _FakeMetadata)
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        try:
            body = _FakeMetadata.body
            _FakeMetadata.body = b"uid0:127.0.0.1:8470"
            rc = main(["--discovery", "tpu-pod",
                       "--metadata-addr",
                       f"http://127.0.0.1:{srv.server_address[1]}",
                       "--no-tag-output", "--",
                       sys.executable, "-c", "print('pod-ok')"])
            assert rc == 0
            err = capsys.readouterr().err
            assert "[discovery:tpu-pod]" in err
            assert "127.0.0.1:1" in err
        finally:
            _FakeMetadata.body = body
            srv.shutdown()
            srv.server_close()

    def test_missing_np_without_discovery_errors(self):
        import sys
        from horovod_tpu.runner.__main__ import main

        with pytest.raises(SystemExit):
            main(["--", sys.executable, "-c", "pass"])


# ---------------------------------------------------------------------------
# Acceptance: survive SIGKILL, shrink, resume from commit (slow)
# ---------------------------------------------------------------------------

def _make_elastic_worker():
    """Factory so cloudpickle ships the worker BY VALUE (a module-level
    function in tests/ would pickle by reference and be unimportable in
    the spawned workers)."""

    def _elastic_worker(total_steps, commit_every, kill_at,
                        replay_from=None):
        """Deterministic 4-dim quadratic descent; data is a pure
        function of (step, process_rank), gradients are averaged across
        the world, so a run's trajectory depends only on (start state,
        world size). Rank 1 SIGKILLs itself at ``kill_at`` in
        generation 0 — the host-loss simulation. ``replay_from`` builds
        the clean-replay control: restore an explicit commit, never
        commit again."""
        import os
        import signal

        import jax.numpy as jnp
        import numpy as np

        import horovod_tpu as hvd

        hvd.init()
        r = hvd.process_rank()
        gen = hvd.generation()

        state = hvd.ElasticState(params={"w": jnp.zeros((4,))})
        state.restore(step=replay_from)
        w = jnp.asarray(state.params["w"])
        start = int(state.step)

        target = jnp.asarray([1.0, -2.0, 3.0, 0.5])
        for step in range(start, total_steps):
            if gen == 0 and r == 1 and step == kill_at:
                os.kill(os.getpid(), signal.SIGKILL)
            # Per-rank data: scale depends on (step, rank); the averaged
            # gradient therefore depends on world size — exactly why the
            # replay control must run at the post-shrink size.
            scale = 1.0 + 0.1 * ((step * 7 + r * 3) % 5)
            grad = scale * (w - target)
            grad = hvd.allreduce(grad, average=True, name=f"g.{step}")
            w = w - 0.1 * grad
            state.params = {"w": w}
            if replay_from is None and (step + 1) % commit_every == 0:
                state.commit(step + 1)
        return {"w": np.asarray(w).tolist(), "gen": gen,
                "size": hvd.size(), "start": start}

    return _elastic_worker


@pytest.mark.slow
class TestElasticRecovery:
    def test_sigkill_shrink_resume_matches_replay(self, tmp_path):
        from horovod_tpu.elastic import run_elastic
        from horovod_tpu.runner.api import run as plain_run

        state_dir = str(tmp_path / "estate")
        total, commit_every, kill_at = 20, 5, 12

        worker = _make_elastic_worker()
        cfg = FailureConfig(failure_timeout_s=60.0, max_restarts=2,
                            backoff_s=0.2, backoff_factor=1.5,
                            blacklist_s=600.0)
        results = run_elastic(
            worker, args=(total, commit_every, kill_at),
            min_np=1, max_np=2, hosts="localhost:2",
            state_dir=state_dir, config=cfg,
            extra_env=dict(_ENV), start_timeout=300)

        # The world shrank to 1 and resumed in generation 1 from the
        # last commit before the kill (step 10, not 12 or 0).
        assert len(results) == 1
        final = results[0]
        assert final["gen"] == 1
        assert final["size"] == 1
        assert final["start"] == 10

        # Clean control: a fresh np=1 job replaying from the same
        # commit, never failing. Numeric equality rtol 1e-5.
        replay = plain_run(
            worker, args=(total, commit_every, kill_at),
            kwargs={"replay_from": 10}, np=1,
            extra_env=dict(_ENV, **{"HOROVOD_TPU_ELASTIC_DIR": state_dir}),
            start_timeout=300)
        np.testing.assert_allclose(final["w"], replay[0]["w"], rtol=1e-5)
        assert replay[0]["start"] == 10

    def test_no_failure_single_generation(self, tmp_path):
        """Control: without a kill the elastic driver is one generation
        of the full world."""
        from horovod_tpu.elastic import run_elastic

        results = run_elastic(
            _make_elastic_worker(), args=(6, 3, 10 ** 9),
            min_np=1, max_np=2, hosts="localhost:2",
            state_dir=str(tmp_path / "estate2"),
            config=FailureConfig(max_restarts=1, backoff_s=0.2),
            extra_env=dict(_ENV), start_timeout=300)
        assert len(results) == 2
        assert all(r["gen"] == 0 and r["size"] == 2 for r in results)
        np.testing.assert_allclose(results[0]["w"], results[1]["w"])
