"""Coordinator protocol unit tests — in-process, no subprocesses.

Covers the rank-0 negotiation logic the reference implements in
IncrementTensorCount / ConstructMPIResponse / the fusion loop
(operations.cc:287-313, 321-523, 2149-2265): quorum counting, cross-rank
validation errors, fusion grouping under the byte threshold, ordered
sequence delivery, history pruning, and shutdown propagation.
"""

import threading

import pytest

from horovod_tpu.ops.control_plane import (AnnounceRequest, CoordinatorClient,
                                           CoordinatorService, FetchRequest)
from horovod_tpu.runner.secret import make_secret_key


@pytest.fixture
def svc():
    s = CoordinatorService(nproc=2, key=make_secret_key(),
                           fusion_threshold=1024)
    yield s
    s.shutdown()


def _client(svc, rank):
    return CoordinatorClient([("127.0.0.1", svc.port)], svc.key, rank)


def _req(name, op=0, dtype="float32", shape=(4,), root=-1):
    # Payload bytes are derived from shape × dtype by both planners (the
    # native wire carries no byte count — mpi_message.h:44-86).
    return {"name": name, "op": op, "dtype": dtype, "shape": shape,
            "root_rank": root}


class TestNegotiation:
    def test_quorum_then_group(self, svc):
        c0, c1 = _client(svc, 0), _client(svc, 1)
        c0.announce([_req("t")])
        # only one rank announced: no group yet
        assert c0.fetch(wait_s=0.05).groups == []
        c1.announce([_req("t")])
        groups = c0.fetch(wait_s=2.0).groups
        assert len(groups) == 1
        assert groups[0]["names"] == ["t"] and groups[0]["error"] == ""
        # the other rank sees the same sequence
        g1 = c1.fetch(wait_s=2.0).groups
        assert g1 == groups

    def test_fusion_same_dtype_under_threshold(self, svc):
        c0, c1 = _client(svc, 0), _client(svc, 1)
        reqs = [_req("a", shape=(100,)), _req("b", shape=(100,)),
                _req("c", shape=(100,))]  # 400 bytes each (float32)
        c0.announce(reqs)
        c1.announce(reqs)
        groups = c0.fetch(wait_s=2.0).groups
        # 400+400 fits in 1024; c overflows into a second group
        assert [g["names"] for g in groups] == [["a", "b"], ["c"]]

    def test_lookahead_skips_mismatched_dtype(self, svc):
        c0, c1 = _client(svc, 0), _client(svc, 1)
        reqs = [_req("f1", dtype="float32"), _req("i1", dtype="int32"),
                _req("f2", dtype="float32")]
        c0.announce(reqs)
        c1.announce(reqs)
        groups = c0.fetch(wait_s=2.0).groups
        assert [g["names"] for g in groups] == [["f1", "f2"], ["i1"]]

    def test_shape_mismatch_error(self, svc):
        c0, c1 = _client(svc, 0), _client(svc, 1)
        c0.announce([_req("t", shape=(3,))])
        c1.announce([_req("t", shape=(5,))])
        groups = c0.fetch(wait_s=2.0).groups
        assert len(groups) == 1
        assert "Mismatched allreduce tensor shapes" in groups[0]["error"]

    @pytest.mark.parametrize("native", [True, False],
                             ids=["native", "python"])
    def test_execution_attribute_mismatch_error(self, native):
        """VERDICT r2 #5: (average, prescale, postscale, sharded) ride
        the wire's device slot as a fingerprint; ranks disagreeing get a
        Mismatched-execution-attributes error group instead of silently
        subdividing into divergent programs (operations.cc:480-497
        role)."""
        svc = CoordinatorService(nproc=2, key=make_secret_key(),
                                 fusion_threshold=1024, native=native)
        try:
            c0, c1 = _client(svc, 0), _client(svc, 1)
            r0 = dict(_req("t"), device=111)
            r1 = dict(_req("t"), device=222)
            c0.announce([r0])
            c1.announce([r1])
            groups = c0.fetch(wait_s=2.0).groups
            assert len(groups) == 1
            assert "Mismatched execution attributes" in groups[0]["error"]
        finally:
            svc.shutdown()

    def test_op_mismatch_error(self, svc):
        c0, c1 = _client(svc, 0), _client(svc, 1)
        c0.announce([_req("t", op=0)])
        c1.announce([_req("t", op=2, root=0)])
        groups = c0.fetch(wait_s=2.0).groups
        assert "Mismatched collective operations" in groups[0]["error"]

    def test_broadcast_root_mismatch(self, svc):
        c0, c1 = _client(svc, 0), _client(svc, 1)
        c0.announce([_req("t", op=2, root=0)])
        c1.announce([_req("t", op=2, root=1)])
        groups = c0.fetch(wait_s=2.0).groups
        assert "Mismatched root ranks" in groups[0]["error"]

    def test_allgather_sizes_per_rank(self, svc):
        c0, c1 = _client(svc, 0), _client(svc, 1)
        c0.announce([_req("g", op=1, shape=(2, 4))])
        c1.announce([_req("g", op=1, shape=(5, 4))])
        groups = c0.fetch(wait_s=2.0).groups
        assert groups[0]["error"] == ""
        assert groups[0]["sizes"]["g"] == [2, 5]

    def test_history_pruned_after_all_ack(self, svc):
        c0, c1 = _client(svc, 0), _client(svc, 1)
        for i in range(5):
            c0.announce([_req(f"t{i}", dtype="int32" if i % 2 else "float32",
                              shape=(500,))])
            c1.announce([_req(f"t{i}", dtype="int32" if i % 2 else "float32",
                              shape=(500,))])
            assert c0.fetch(wait_s=2.0).groups
            assert c1.fetch(wait_s=2.0).groups
        # both clients acked everything -> history collapses
        c0.fetch(wait_s=0.01)
        c1.fetch(wait_s=0.01)
        assert svc.history_len() <= 1
        assert svc.base_seq() >= 4

    def test_shutdown_propagates(self, svc):
        c0, c1 = _client(svc, 0), _client(svc, 1)
        c0.announce([], )  # no-op announce
        c1.announce_shutdown()
        resp = c0.fetch(wait_s=2.0)
        assert resp.shutdown

    def test_concurrent_announce_consistent_order(self, svc):
        """Both ranks see identical group order even with racing
        announcements from different threads."""
        c0, c1 = _client(svc, 0), _client(svc, 1)
        names = [f"x{i}" for i in range(20)]

        def announce(client, order):
            for n in order:
                client.announce([_req(n, shape=(150,))])  # 600 bytes

        t0 = threading.Thread(target=announce, args=(c0, names))
        t1 = threading.Thread(target=announce, args=(c1, list(reversed(
            names))))
        t0.start(); t1.start(); t0.join(); t1.join()
        g0, g1 = [], []
        while sum(len(g) for g in g0) < len(names):
            g0.extend(g["names"] for g in c0.fetch(wait_s=2.0).groups)
        while sum(len(g) for g in g1) < len(names):
            g1.extend(g["names"] for g in c1.fetch(wait_s=2.0).groups)
        assert g0 == g1
        assert sorted(n for g in g0 for n in g) == sorted(names)


class TestAnnounceIdempotency:
    def test_retried_announce_is_dropped(self, svc):
        """A retry of an announce whose response was lost (same
        announce_id re-delivered) must not resurrect a quorum-deleted
        entry with stale shape metadata (ADVICE r1, medium)."""
        c0, c1 = _client(svc, 0), _client(svc, 1)
        c0.announce([_req("t", op=1, shape=(3, 2))])
        c1.announce([_req("t", op=1, shape=(5, 2))])
        groups = c0.fetch(wait_s=2.0).groups
        assert len(groups) == 1
        assert groups[0]["sizes"]["t"] == [3, 5]
        # Simulate the retry: re-deliver rank 0's announce with the SAME
        # announce_id straight to the service handler (BasicClient would
        # do this after a lost response).
        svc._handle(AnnounceRequest(0, [_req("t", op=1, shape=(3, 2))],
                                    announce_id=c0._announce_seq), None)
        with svc._mu:
            assert "t" not in svc._table  # no stale one-rank entry
        # The next step's announce of the same tensor name must form a
        # FRESH quorum with the NEW shapes, not reuse last step's sizes.
        c0.announce([_req("t", op=1, shape=(7, 2))])
        c1.announce([_req("t", op=1, shape=(1, 2))])
        groups = c0.fetch(wait_s=2.0).groups
        assert len(groups) == 1
        assert groups[0]["sizes"]["t"] == [7, 1]


class TestStallDetection:
    @pytest.mark.parametrize("native", [True, False],
                             ids=["native", "python"])
    def test_missing_ranks_reported(self, native):
        """Coordinator names the missing ranks per stalled tensor
        (operations.cc:1644-1668) — with both the native controller and
        the Python fallback planner."""
        svc = CoordinatorService(nproc=2, key=make_secret_key(),
                                 fusion_threshold=1024, native=native,
                                 stall_warning_s=0.05)
        try:
            assert svc.native_active is native
            c0 = _client(svc, 0)
            c0.announce([_req("stuck.a"), _req("stuck.b")])
            import time as _t
            _t.sleep(0.1)
            svc._last_stall_check = 0.0
            lines = svc.check_stalls()
            assert len(lines) == 2
            name0, line0 = lines[0]
            assert name0 == "stuck.a"
            assert "stuck.a" in line0 and "missing ranks" in line0
            assert "1" in line0.split("missing ranks")[1]
        finally:
            svc.shutdown()

    def test_no_report_inside_window(self, svc):
        c0 = _client(svc, 0)
        c0.announce([_req("fresh")])
        svc.stall_warning_s = 60.0
        assert svc.check_stalls() == []


class TestBoundedPlanDefer:
    @pytest.mark.parametrize("native", [True, False],
                             ids=["native", "python"])
    def test_continuous_announces_cannot_starve_ready_work(self, native):
        """ADVICE r2: a fully-announced tensor must be planned even when
        the announce stream NEVER goes quiet (overlapping bursts from
        async submission keep refreshing last_announce). The bounded
        valve (PLAN_MAX_DEFER_FACTOR debounce windows, mirroring the
        client-side kDrainMaxDeferNs cap) fires regardless of quiet."""
        import time

        svc = CoordinatorService(nproc=2, key=make_secret_key(),
                                 fusion_threshold=1024, native=native)
        try:
            assert svc.native_active is native
            c0, c1 = _client(svc, 0), _client(svc, 1)
            c0.announce([_req("ready")])
            c1.announce([_req("ready")])
            # Noise: rank 0 announces a new PARTIAL tensor every ~1ms so
            # the 2ms quiet window never opens.
            got = []
            deadline = time.monotonic() + 2.0
            i = 0
            while time.monotonic() < deadline:
                c0.announce([_req(f"noise.{i}")])
                i += 1
                groups = c0.fetch(wait_s=0.003).groups
                if groups:
                    got = groups
                    break
            assert got, "ready tensor starved by continuous announces"
            assert got[0]["names"] == ["ready"]
            elapsed = 2.0 - (deadline - time.monotonic())
            assert elapsed < 1.0, f"valve fired too late: {elapsed:.3f}s"
        finally:
            svc.shutdown()


class TestClockSync:
    """Clock-alignment handshake (docs/tracing.md): NTP-style pings with
    round-trip halving over the coordinator channel."""

    def test_clock_sync_local_offset_near_zero(self, svc):
        c1 = _client(svc, 1)
        res = c1.clock_sync(probes=6)
        # Same host, same monotonic clock: the measured offset must be
        # tiny (bounded by scheduling noise) and the RTT positive.
        assert res["rtt_s"] > 0.0
        assert abs(res["offset_s"]) < 0.05
        assert res["probes"] == 6

    def test_min_rtt_sample_wins(self, svc, monkeypatch):
        """The kept offset is the one measured on the cleanest round
        trip, not the last or the mean."""
        from horovod_tpu.ops import control_plane as cp

        c1 = _client(svc, 1)
        rtts = iter([0.010, 0.002, 0.030])
        real_request = c1._client.request

        def jittered(req):
            import time as _t
            resp = real_request(req)
            if isinstance(req, cp.ClockProbeRequest):
                _t.sleep(next(rtts))   # inflate this probe's RTT
            return resp

        monkeypatch.setattr(c1._client, "request", jittered)
        res = c1.clock_sync(probes=3)
        # The winning sample is the middle one (min inflated RTT).
        assert 0.002 <= res["rtt_s"] < 0.010


class TestSkewTelemetry:
    """Live straggler metrics (docs/tracing.md): the coordinator turns
    its announce ticks into per-rank lateness histograms and a
    straggler gauge — visible on the Prometheus plane without traces."""

    def _lateness(self, snap, rank):
        fam = snap.get("hvdtpu_negotiate_lateness_seconds",
                       {"values": {}})["values"]
        return fam.get(f'rank="{rank}"')

    def test_late_rank_measured_and_elected(self, svc):
        import time

        from horovod_tpu.observability import metrics_snapshot

        before = self._lateness(metrics_snapshot(), 1)
        n0 = before["count"] if before else 0
        s0 = before["sum"] if before else 0.0
        c0, c1 = _client(svc, 0), _client(svc, 1)
        for step in range(3):
            c0.announce([_req(f"skew.{step}")])
            time.sleep(0.06)
            c1.announce([_req(f"skew.{step}")])
            assert c0.fetch(wait_s=2.0).groups
        snap = metrics_snapshot()
        h1 = self._lateness(snap, 1)
        assert h1["count"] - n0 == 3
        # Each quorum saw rank 1 ~60 ms behind rank 0.
        mean = (h1["sum"] - s0) / 3
        assert 0.03 <= mean <= 0.3
        assert snap["hvdtpu_straggler_rank"]["values"][""] == 1.0
        assert snap["hvdtpu_straggler_lateness_seconds"]["values"][""] \
            > 0.01

    def test_native_coordinator_decodes_payload_announces(self):
        """Skew telemetry must also work when announces arrive as
        pre-serialized RequestList bytes (native-engine workers)."""
        import time

        from horovod_tpu.observability import metrics_snapshot
        from horovod_tpu.ops import wire_format as wire

        svc = CoordinatorService(nproc=2, key=make_secret_key(),
                                 fusion_threshold=1024, native=True)
        if not svc.native_active:
            svc.shutdown()
            pytest.skip("native controller unavailable")
        try:
            before = self._lateness(metrics_snapshot(), 1)
            n0 = before["count"] if before else 0
            c0, c1 = _client(svc, 0), _client(svc, 1)
            payload = wire.encode_request_list(
                0, [dict(_req("native.skew"), device=0, nbytes=16)])
            c0.announce_bytes(payload)
            time.sleep(0.05)
            payload1 = wire.encode_request_list(
                1, [dict(_req("native.skew"), device=0, nbytes=16)])
            c1.announce_bytes(payload1)
            assert c0.fetch(wait_s=2.0).groups
            h1 = self._lateness(metrics_snapshot(), 1)
            assert h1 is not None and h1["count"] - n0 == 1
        finally:
            svc.shutdown()

    def test_stall_warning_includes_measured_lateness(self):
        """The upgraded stall report carries the per-rank lateness tail
        next to the missing-ranks line. (The horovod_tpu logger does not
        propagate to root — caplog misses it — so attach a handler
        directly.)"""
        import logging
        import time

        records = []

        class _Capture(logging.Handler):
            def emit(self, record):
                records.append(record)

        handler = _Capture(level=logging.WARNING)
        logging.getLogger("horovod_tpu.control_plane").addHandler(handler)
        svc = CoordinatorService(nproc=2, key=make_secret_key(),
                                 fusion_threshold=1024, native=False,
                                 stall_warning_s=0.05)
        try:
            c0, c1 = _client(svc, 0), _client(svc, 1)
            # One completed tensor establishes rank 1's lateness...
            c0.announce([_req("warm")])
            time.sleep(0.08)
            c1.announce([_req("warm")])
            assert c0.fetch(wait_s=2.0).groups
            # ...then a stuck one triggers the stall report.
            c0.announce([_req("stuck")])
            time.sleep(0.1)
            svc._last_stall_check = 0.0
            lines = svc.check_stalls()
            assert lines and lines[0][0] == "stuck"
            text = "\n".join(r.getMessage() for r in records)
            assert "Recent negotiate lateness by rank" in text
            assert "rank 1:" in text
        finally:
            svc.shutdown()
            logging.getLogger(
                "horovod_tpu.control_plane").removeHandler(handler)

    def test_partial_entries_pruned(self):
        """Ticks of tensors that never reach quorum are dropped after
        the stall window — coordinator memory must not grow with a
        misbehaving job."""
        import time

        svc = CoordinatorService(nproc=2, key=make_secret_key(),
                                 fusion_threshold=1024, native=False,
                                 stall_warning_s=0.05)
        try:
            c0 = _client(svc, 0)
            for i in range(5):
                c0.announce([_req(f"orphan.{i}")])
            assert len(svc._skew._pending) == 5
            time.sleep(0.15)
            svc._last_stall_check = 0.0
            svc.check_stalls()
            assert len(svc._skew._pending) == 0
        finally:
            svc.shutdown()
