"""Mid-run tuner fusion flip across real worker processes.

The global autotuner's fusion move is an epoch-stamped regroup: the
coordinator-side arbiter accepts the new cap, the planner (native C++
controller AND the pure-Python fallback) cuts all FUTURE groups with
it, and every rank learns the epoch list from its next fetch. Fusion
grouping never changes numerics — elementwise reductions produce the
same sums however the tensors are batched — so a flip landing mid-run
must leave every collective's result exactly at its closed form, with
both ranks agreeing, while the evidence plane (engine fusion_threshold,
the mirrored fusion_epochs list) shows the flip actually landed.

Parametrized over both planner paths: the native controller caches its
threshold behind the C ABI handle (hvdtpu_ctl_set_fusion_threshold),
the fallback reads CoordinatorService.fusion_threshold directly —
both must honour a mid-run move.
"""

import pytest

from horovod_tpu.runner.api import run

_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
}

pytestmark = pytest.mark.slow


class TestMidRunFusionFlip:
    @pytest.mark.parametrize("planner", ["native", "fallback"])
    def test_flip_lands_without_changing_results(self, planner):
        def worker():
            # Nested so cloudpickle ships it by value (module-level test
            # functions are not importable in the worker).
            import numpy as np
            import jax.numpy as jnp

            import horovod_tpu as hvd
            from horovod_tpu.ops import collective

            hvd.init()
            r = hvd.rank()
            eng = collective.engine()
            out = {"results": []}
            steps, flip_at, burst = 12, 4, 3
            for step in range(steps):
                if step == flip_at and r == 0:
                    # 512 bytes: smaller than one 100-float tensor, so
                    # every future burst MUST split into singleton
                    # groups.
                    out["verdict"] = eng._ensure_mp().tuner_move(
                        "fusion_threshold_mb", 512 / (1 << 20))
                handles = [
                    hvd.allreduce_async(
                        jnp.full((100,), float(r + 1 + i + step)),
                        average=False, name=f"flip.{step}.{i}")
                    for i in range(burst)]
                out["results"].append(
                    [float(np.asarray(hvd.synchronize(h))[0])
                     for h in handles])
            out["threshold"] = eng.fusion_threshold
            out["epochs"] = [list(e) for e in eng._fusion_epochs]
            # Sampled AFTER the collectives: the native core initializes
            # lazily at first enqueue.
            out["native"] = eng._native_core is not None
            return out

        env = dict(_ENV)
        if planner == "fallback":
            env["HOROVOD_TPU_DISABLE_NATIVE"] = "1"
        results = run(worker, np=2, extra_env=env, start_timeout=300)
        assert len(results) == 2
        for r in results:
            assert r["native"] == (planner == "native")
        # The move was accepted by the arbiter on rank 0...
        v = results[0]["verdict"]
        assert v["accepted"] and v["reason"] == "ok", v
        assert v["from_seq"] >= 0
        # ...and the epoch evidence reached EVERY rank's engine: the
        # threshold the planner now cuts with, plus the stamped epoch
        # list mirrored from coordinator params.
        for r in results:
            assert r["threshold"] == 512
            assert [e[1] for e in r["epochs"]] == [512]
            assert r["epochs"][0][0] == v["from_seq"]
        # Numerics: every collective before AND after the flip sits
        # exactly at its closed form (sum over ranks of r+1+i+step),
        # and both ranks saw identical values — regrouping is invisible
        # to the math.
        for r in results:
            for step, vals in enumerate(r["results"]):
                assert vals == [3.0 + 2 * (i + step) for i in range(3)]
        assert results[0]["results"] == results[1]["results"]
