"""Sharded async checkpoint engine (docs/checkpoint.md, ISSUE 4).

Multi-host layouts are SIMULATED on the 8-device single-process CPU
mesh via the layout layer's ``process_fn`` (``lambda d: d.id // k``
acts like ``8/k`` hosts): one engine instance per simulated rank saves
only its shards, non-zero ranks first and rank 0 (the manifest writer)
last — the order the real commit barrier enforces. That is what lets
the acceptance matrix (save at world size 4, restore at 2 and 1, and
the reverse) run inside tier 1, with the true multi-process path
covered by the existing runner-based slow tier.
"""

import glob
import json
import os
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import optax

import horovod_tpu as hvd
from horovod_tpu.checkpoint import (CheckpointEngine, CorruptShardError,
                                    read_latest, read_manifest,
                                    tree_layout)
from horovod_tpu.checkpoint import layout as _layout
from horovod_tpu.checkpoint import manifest as _manifest
from horovod_tpu.checkpoint import reader as _reader
from horovod_tpu.checkpoint.writer import AsyncWriter
from horovod_tpu.parallel.zero import zero1_init


@pytest.fixture(autouse=True)
def _init():
    hvd.init()
    yield


def _dp_mesh():
    return Mesh(np.asarray(jax.devices(), dtype=object).reshape(8),
                ("dp",))


def _proc_fn(world):
    """8 CPU devices grouped into ``world`` simulated hosts."""
    per = 8 // world
    return lambda d: d.id // per


def _sim_save(directory, tree, step, world, **kw):
    """Save ``tree`` as a simulated ``world``-process job: every rank's
    engine writes its shards; rank 0 last (it assembles the manifest
    after the shard barrier, which is a no-op in simulation)."""
    engines = [CheckpointEngine(directory, process_index=p,
                                process_count=world,
                                process_fn=_proc_fn(world),
                                barrier=lambda name: None, **kw)
               for p in range(world)]
    for p in list(range(1, world)) + [0]:
        engines[p].save(tree, step, block=True)
    return engines[0]


def _sharded_state(scale=1.0):
    """A ZeRO-ish mixed tree: one dp-sharded flat leaf, one replicated
    matrix, one scalar."""
    mesh = _dp_mesh()
    flat = jax.device_put(
        jnp.arange(64.0) * scale, NamedSharding(mesh, P("dp")))
    return {"moments": flat,
            "params": jnp.arange(12.0).reshape(3, 4) * scale,
            "count": np.int64(3)}


class TestLayout:
    def test_sharded_vs_replicated_leaves(self):
        tree = _sharded_state()
        layouts = tree_layout(tree, _proc_fn(4))
        lm = layouts["['moments']"]
        assert not lm.replicated
        assert len(lm.shards) == 8            # one block per device
        assert {s.process for s in lm.shards} == {0, 1, 2, 3}
        # contiguous cover of [0, 64)
        spans = sorted(s.index[0] for s in lm.shards)
        assert spans[0][0] == 0 and spans[-1][1] == 64
        for (a, b), (c, d) in zip(spans, spans[1:]):
            assert b == c
        lp = layouts["['params']"]
        assert lp.replicated and lp.shards[0].process == 0
        assert layouts["['count']"].shape == ()

    def test_replica_dedup_single_writer(self):
        """A dp-replicated jax array (P(None)) must be written once, by
        process 0 — never once per replica."""
        mesh = _dp_mesh()
        x = jax.device_put(jnp.ones((4, 2)), NamedSharding(mesh, P()))
        ll = _layout.leaf_layout(x, _proc_fn(4))
        assert ll.replicated and len(ll.shards) == 1
        assert ll.shards[0].process == 0

    def test_intersect_and_relative(self):
        a = ((0, 16),)
        b = ((8, 32),)
        assert _layout.intersect_spans(a, b) == ((8, 16),)
        assert _layout.intersect_spans(((0, 4),), ((4, 8),)) is None
        assert _layout.relative_slices(b, ((8, 16),)) == (slice(0, 8),)


class TestCommitProtocol:
    def test_manifest_schema_and_latest(self, tmp_path):
        d = str(tmp_path / "ck")
        tree = _sharded_state()
        _sim_save(d, tree, 7, world=4)
        assert read_latest(d) == 7
        man = read_manifest(d, 7)
        assert man["format"] == "horovod_tpu.checkpoint/1"
        assert man["step"] == 7 and man["process_count"] == 4
        keys = {e["key"] for e in man["leaves"]}
        assert keys == {"['moments']", "['params']", "['count']"}
        for entry in man["leaves"]:
            for shard in entry["shards"]:
                assert set(shard) == {"file", "index", "process",
                                      "crc32", "nbytes"}
                path = os.path.join(d, "step-7", shard["file"])
                assert os.path.getsize(path) == shard["nbytes"]
                # sidecar agrees with the manifest
                with open(path + ".crc32") as f:
                    crc, nbytes = f.read().split()
                assert crc == shard["crc32"]
                assert int(nbytes) == shard["nbytes"]

    def test_crash_between_shards_and_manifest(self, tmp_path,
                                               monkeypatch):
        """Shards of step 2 on disk but no manifest: LATEST stays on
        step 1 and restore returns step 1's data — a crash in the
        window between phase 1 and phase 2 loses nothing committed."""
        d = str(tmp_path / "ck")
        eng = CheckpointEngine(d, barrier=lambda name: None)
        eng.save({"w": np.arange(4.0)}, 1, block=True)

        def boom(self, handle, layouts, pcount, extra):
            raise RuntimeError("simulated crash before manifest")

        monkeypatch.setattr(CheckpointEngine, "_commit_rank0", boom)
        eng2 = CheckpointEngine(d, barrier=lambda name: None)
        eng2.save({"w": np.arange(4.0) * 2}, 2)
        with pytest.raises(RuntimeError, match="checkpoint write"):
            eng2.wait()
        monkeypatch.undo()
        # step-2 shards exist, but the commit never happened
        assert glob.glob(os.path.join(d, "step-2", "*.npy"))
        assert not os.path.exists(os.path.join(d, "step-2",
                                               "manifest.json"))
        assert read_latest(d) == 1
        eng3 = CheckpointEngine(d, barrier=lambda name: None)
        restored = eng3.restore()
        np.testing.assert_allclose(restored["w"], np.arange(4.0))

    def test_latest_flip_is_ordered(self, tmp_path):
        """LATEST only ever names a step whose manifest exists."""
        d = str(tmp_path / "ck")
        eng = CheckpointEngine(d, barrier=lambda name: None)
        for step in (1, 2, 3):
            eng.save({"w": np.full(8, float(step))}, step, block=True)
            latest = read_latest(d)
            assert latest == step
            assert os.path.exists(os.path.join(
                d, f"step-{latest}", "manifest.json"))

    def test_async_save_returns_before_commit(self, tmp_path):
        """save() must hand control back while the write is in flight:
        a gate inside the barrier holds the background commit open and
        the foreground still owns the handle."""
        d = str(tmp_path / "ck")
        gate = threading.Event()
        entered = threading.Event()

        def slow_barrier(name):
            if name.startswith("ckpt.shards."):
                entered.set()
                assert gate.wait(10)

        eng = CheckpointEngine(d, barrier=slow_barrier)
        handle = eng.save({"w": np.arange(32.0)}, 5)
        assert not handle.committed          # still in flight
        assert entered.wait(10)              # writer reached the barrier
        assert read_latest(d) is None        # not committed yet
        gate.set()
        eng.wait()
        assert handle.committed and read_latest(d) == 5

    def test_blocked_vs_total_seconds_reported(self, tmp_path):
        reg = hvd.metrics_snapshot()
        blocked0 = reg.get("hvdtpu_checkpoint_blocked_seconds_total",
                           {"values": {}})["values"].get("", 0.0)
        d = str(tmp_path / "ck")

        def slow_barrier(name):
            time.sleep(0.05)

        eng = CheckpointEngine(d, barrier=slow_barrier)
        t0 = time.perf_counter()
        eng.save({"w": np.arange(1024.0)}, 1)
        foreground = time.perf_counter() - t0
        eng.wait()
        snap = hvd.metrics_snapshot()
        blocked = snap["hvdtpu_checkpoint_blocked_seconds_total"][
            "values"][""] - blocked0
        # the loop never paid the two slow barriers (>= 0.1 s)
        assert foreground < 0.1
        assert blocked <= foreground + 0.01
        assert snap["hvdtpu_checkpoint_save_seconds"]["values"][""][
            "count"] >= 1

    def test_write_failure_surfaces_on_wait(self, tmp_path, monkeypatch):
        """A dead disk mid-write must fail the NEXT wait/save loudly —
        the loop cannot silently keep 'committing'."""
        from horovod_tpu.checkpoint import engine as _engine_mod

        d = str(tmp_path / "ck")
        eng = CheckpointEngine(d, barrier=lambda name: None)
        eng.save({"w": np.arange(4.0)}, 1, block=True)

        def dead_disk(directory, filename, arr):
            raise IOError("No space left on device")

        monkeypatch.setattr(_engine_mod, "write_shard", dead_disk)
        eng.save({"w": np.arange(4.0) * 2}, 2)
        with pytest.raises(RuntimeError, match="checkpoint write"):
            eng.wait()
        monkeypatch.undo()
        assert read_latest(d) == 1           # commit 2 never happened


class TestReshardedRestore:
    def test_ws4_to_ws2_ws1_and_reverse(self, tmp_path):
        """The acceptance matrix: a world-size-4 commit restores
        bit-exactly into world sizes 2 and 1 through the manifest
        overlap path (and a ws-2 commit restores into 4 and 1)."""
        tree = _sharded_state(scale=3.0)
        ref = {k: np.asarray(jax.device_get(v))
               for k, v in tree.items()}

        for save_ws, restore_ws in [(4, 2), (4, 1), (2, 4), (2, 1)]:
            d = str(tmp_path / f"ck{save_ws}to{restore_ws}")
            eng = _sim_save(d, tree, 11, world=save_ws)
            if restore_ws == 1:
                restored = eng.restore(template=tree)
                for k in ref:
                    np.testing.assert_allclose(
                        np.asarray(restored[k]), ref[k], rtol=1e-6)
                continue
            # per-rank resharded loads: each simulated new rank reads
            # only its overlapping spans; blocks reassemble exactly.
            new_layouts = tree_layout(tree, _proc_fn(restore_ws))
            got = np.full(64, np.nan)
            for p in range(restore_ws):
                blocks = eng.restore_addressable(
                    new_layouts, process_index=p)
                for shard, arr in blocks["['moments']"]:
                    got[slice(*shard.index[0])] = arr
                # replicated leaves come back whole to every rank
                np.testing.assert_allclose(
                    blocks["['params']"][0][1], ref["params"],
                    rtol=1e-6)
            np.testing.assert_allclose(got, ref["moments"], rtol=1e-6)

    def test_resharded_reads_only_overlapping_files(self, tmp_path):
        """ws4 → ws2: the new rank 1 needs only the second half of the
        sharded leaf — the files for the first half must not be read
        (delete them and the restore must still succeed)."""
        tree = _sharded_state()
        d = str(tmp_path / "ck")
        eng = _sim_save(d, tree, 4, world=4)
        man = read_manifest(d, 4)
        entry = {e["key"]: e for e in man["leaves"]}["['moments']"]
        upper = _layout.Shard(index=((32, 64),), process=1)
        needed = {s["file"] for s in
                  _reader.shards_overlapping(entry, upper.index)}
        all_files = {s["file"] for s in entry["shards"]}
        assert needed < all_files and len(needed) == 4
        for fname in all_files - needed:     # lower-half shards gone
            os.remove(os.path.join(d, "step-4", fname))
        block = _reader.read_block(os.path.join(d, "step-4"), entry,
                                   upper.index)
        np.testing.assert_allclose(block, np.arange(32.0, 64.0))
        # ...and reading the DELETED half is a typed corruption error
        with pytest.raises(CorruptShardError, match="missing"):
            _reader.read_block(os.path.join(d, "step-4"), entry,
                               ((0, 32),))

    def test_zero1_optimizer_state_roundtrip(self, tmp_path):
        """ZeRO-1 sharded AdamW moments (the motivating workload):
        committed at simulated ws 4, restored at ws 2 and fully — every
        leaf allclose at rtol 1e-6, through a NamedTuple optax state
        (template path)."""
        mesh = _dp_mesh()
        params = {"w": jnp.arange(24.0).reshape(4, 6) / 7.0,
                  "b": jnp.arange(5.0)}
        state = zero1_init(optax.adamw(1e-3), params, n_shards=8,
                           param_specs=jax.tree_util.tree_map(
                               lambda _: P(), params),
                           mesh=mesh)
        # Shard the flat moment leaves over dp as zero1 lays them out,
        # and fill them with distinct values so equality is meaningful.
        shard = NamedSharding(mesh, P("dp"))
        k = [0]

        def place(x):
            x = jnp.asarray(x)
            if x.ndim == 1 and x.size % 8 == 0:
                k[0] += 1
                return jax.device_put(
                    x + jnp.arange(x.size) * 0.25 + k[0], shard)
            return x

        state = jax.tree_util.tree_map(place, state)
        ref = jax.tree_util.tree_map(
            lambda x: np.asarray(jax.device_get(x)), state)

        d = str(tmp_path / "zero1")
        eng = _sim_save(d, state, 42, world=4)

        restored = eng.restore(template=state)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-6),
            restored, ref)

        # resharded: new ws 2, every sharded leaf reassembled from
        # per-rank overlap reads equals the original
        new_layouts = tree_layout(state, _proc_fn(2))
        flat_ref, _ = jax.tree_util.tree_flatten_with_path(ref)
        by_key = {jax.tree_util.keystr(p): np.asarray(v)
                  for p, v in flat_ref}
        for key, ll in new_layouts.items():
            if ll.replicated:
                continue
            got = np.full(ll.shape, np.nan, dtype=by_key[key].dtype)
            for p in range(2):
                for s, arr in eng.restore_addressable(
                        {key: ll}, process_index=p)[key]:
                    got[s.slices] = arr
            np.testing.assert_allclose(got, by_key[key], rtol=1e-6)

    def test_templateless_restore_dict_tree(self, tmp_path):
        d = str(tmp_path / "ck")
        tree = {"a": {"b": np.arange(6.0).reshape(2, 3)},
                "c": [np.ones(2), np.zeros(3)]}
        eng = CheckpointEngine(d, barrier=lambda name: None)
        eng.save(tree, 1, block=True)
        restored = eng.restore()
        np.testing.assert_allclose(restored["a"]["b"], tree["a"]["b"])
        np.testing.assert_allclose(restored["c"][0], 1.0)
        np.testing.assert_allclose(restored["c"][1], 0.0)

    def test_namedtuple_tree_needs_template(self, tmp_path):
        d = str(tmp_path / "ck")
        mesh = _dp_mesh()
        params = {"w": jnp.ones((8,))}
        state = zero1_init(optax.sgd(0.1), params, n_shards=8,
                           param_specs={"w": P()}, mesh=mesh)
        eng = CheckpointEngine(d, barrier=lambda name: None)
        eng.save(state, 1, block=True)
        with pytest.raises(ValueError, match="template"):
            eng.restore()
        restored = eng.restore(template=state)
        assert type(restored).__name__ == "Zero1State"


class TestCorruptionAndFallback:
    def _commit(self, d, step, scale):
        eng = CheckpointEngine(d, barrier=lambda name: None)
        eng.save({"w": np.arange(16.0) * scale,
                  "b": np.ones(3) * scale}, step, block=True)
        return eng

    def test_corrupt_shard_falls_back_to_previous_commit(self, tmp_path):
        d = str(tmp_path / "ck")
        self._commit(d, 1, 1.0)
        eng = self._commit(d, 2, 2.0)
        target = sorted(glob.glob(os.path.join(d, "step-2",
                                               "*.npy")))[0]
        with open(target, "r+b") as f:
            f.seek(80)
            f.write(b"\x13\x37\x13\x37")
        restored = eng.restore()            # falls back to step 1
        np.testing.assert_allclose(restored["w"], np.arange(16.0))
        with pytest.raises(CorruptShardError):
            eng.restore(strict=True)
        snap = hvd.metrics_snapshot()
        assert snap["hvdtpu_checkpoint_corrupt_shards_total"][
            "values"][""] >= 1

    def test_truncated_and_missing_shard_are_typed(self, tmp_path):
        d = str(tmp_path / "ck")
        eng = self._commit(d, 1, 1.0)
        files = sorted(glob.glob(os.path.join(d, "step-1", "*.npy")))
        with open(files[0], "r+b") as f:
            f.truncate(10)
        with pytest.raises(CorruptShardError, match="size"):
            eng.restore(strict=True)
        os.remove(files[0])
        with pytest.raises(CorruptShardError, match="missing"):
            eng.restore(strict=True)


class TestRetentionGC:
    def test_keep_last_n_never_latest(self, tmp_path):
        d = str(tmp_path / "ck")
        eng = CheckpointEngine(d, keep_last=3, barrier=lambda name: None)
        for step in range(1, 8):
            eng.save({"w": np.full(4, float(step))}, step, block=True)
        assert eng.steps() == [5, 6, 7]
        assert read_latest(d) == 7
        assert not os.path.exists(os.path.join(d, "step-1"))
        restored = eng.restore(step=5)
        np.testing.assert_allclose(restored["w"], 5.0)
        snap = hvd.metrics_snapshot()
        assert snap["hvdtpu_checkpoint_gc_steps_total"][
            "values"][""] >= 4

    def test_keep_zero_is_unlimited(self, tmp_path):
        d = str(tmp_path / "ck")
        eng = CheckpointEngine(d, keep_last=0, barrier=lambda name: None)
        for step in range(1, 6):
            eng.save({"w": np.zeros(2)}, step, block=True)
        assert eng.steps() == [1, 2, 3, 4, 5]

    def test_env_knob(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HOROVOD_TPU_CHECKPOINT_KEEP", "2")
        d = str(tmp_path / "ck")
        eng = CheckpointEngine(d, barrier=lambda name: None)
        assert eng.keep_last == 2
        for step in range(1, 5):
            eng.save({"w": np.zeros(2)}, step, block=True)
        assert eng.steps() == [3, 4]


class TestAsyncWriter:
    def test_fifo_and_wait(self):
        w = AsyncWriter()
        out = []
        for i in range(5):
            w.submit(lambda i=i: out.append(i))
        w.wait()
        assert out == [0, 1, 2, 3, 4]
        w.close()

    def test_error_poisons_until_waited(self):
        w = AsyncWriter()
        w.submit(lambda: (_ for _ in ()).throw(IOError("disk gone")))
        with pytest.raises(RuntimeError, match="checkpoint write"):
            w.wait()
        w.submit(lambda: None)               # usable again after wait
        w.wait()
        w.close()


class TestMultiProcessSharded:
    @pytest.mark.slow
    def test_two_process_commit_and_restore(self, tmp_path):
        """REAL two-process sharded commit: each rank writes only its
        shard of a dp-sharded leaf, the commit barrier is the actual
        cross-process collective (entered from the background writer
        thread), rank 0 writes the manifest, and both ranks restore the
        full tree from the shared directory."""
        from horovod_tpu.runner.api import run

        def worker(d):
            import jax
            import jax.numpy as jnp
            import numpy as np
            from jax.sharding import Mesh, NamedSharding
            from jax.sharding import PartitionSpec as P

            import horovod_tpu as hvd
            from horovod_tpu.checkpoint import read_manifest

            hvd.init()
            mesh = Mesh(np.asarray(jax.devices(),
                                   dtype=object).reshape(2), ("dp",))
            x = jax.device_put(jnp.arange(8.0),
                               NamedSharding(mesh, P("dp")))
            tree = {"x": x, "rep": jnp.full((3,), 2.0)}
            eng = hvd.CheckpointEngine(d)
            eng.save(tree, 7)
            eng.wait()
            man = read_manifest(d, 7)
            restored = eng.restore(template=tree)
            return {
                "rank": hvd.process_rank(),
                "latest": eng.latest_step(),
                "procs": sorted({s["process"]
                                 for e in man["leaves"]
                                 for s in e["shards"]}),
                "x": np.asarray(restored["x"]).tolist(),
                "rep": np.asarray(restored["rep"]).tolist(),
            }

        env = {"JAX_PLATFORMS": "cpu",
               "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}
        results = run(worker, args=(str(tmp_path / "mp"),), np=2,
                      extra_env=env, start_timeout=300)
        assert sorted(r["rank"] for r in results) == [0, 1]
        for r in results:
            assert r["latest"] == 7
            assert r["procs"] == [0, 1]     # both ranks wrote shards
            assert r["x"] == list(np.arange(8.0))
            assert r["rep"] == [2.0] * 3


@pytest.mark.slow
class TestCheckpointBenchReproducible:
    def test_bench_checkpoint_determinism_and_headline(self, tmp_path):
        """bench_engine.py --checkpoint regenerates BENCH_CHECKPOINT
        reproducibly (seeded byte/shard counts identical across runs)
        and supports the acceptance claim: the sharded-async save
        blocks the training loop for less time than the rank-0
        pickle."""
        import subprocess
        import sys

        root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        outs = []
        for i in range(2):
            out = tmp_path / f"bench{i}.json"
            subprocess.run(
                [sys.executable, os.path.join(root, "bench_engine.py"),
                 "--checkpoint", "--commits", "3", "--out", str(out)],
                check=True, capture_output=True, text=True, timeout=600,
                cwd=root)
            outs.append(json.loads(out.read_text()))
        a, b = outs
        assert a["logical_bytes"] == b["logical_bytes"]
        assert a["pickle"]["bytes_rank0"] == b["pickle"]["bytes_rank0"]
        assert a["sharded"]["bytes_per_rank"] == \
            b["sharded"]["bytes_per_rank"]
        assert a["sharded"]["shards_per_rank"] == \
            b["sharded"]["shards_per_rank"]
        # sharded state never funnels through one host: every rank
        # writes, and rank 0 writes well under the full pickle payload
        per_rank = {int(k): v
                    for k, v in a["sharded"]["bytes_per_rank"].items()}
        assert all(v > 0 for v in per_rank.values())
        assert per_rank[0] < a["pickle"]["bytes_rank0"] / 2
        # the headline delta (wall-clock, generous margin): async save
        # blocks the loop less than the serial rank-0 pickle
        for run in outs:
            assert run["blocked_ratio_sharded_vs_pickle"] < 1.0, run


class TestShimHooks:
    def test_torch_checkpoint_hook(self, tmp_path):
        torch = pytest.importorskip("torch")
        import horovod_tpu.torch as hvd_torch

        model = torch.nn.Linear(4, 2)
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        save = hvd_torch.checkpoint_hook(
            str(tmp_path / "pt"), model=model, optimizer=opt, every=2)
        assert save(1) is None               # off-cadence: no write
        handle = save(2, block=True)
        assert handle is not None and handle.committed
        restored = save.engine.restore()
        np.testing.assert_allclose(
            restored["model"]["weight"],
            model.state_dict()["weight"].detach().numpy())
        assert "optimizer" in restored
