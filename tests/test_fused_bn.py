"""Gradient parity of the fused BN(+residual)(+ReLU) op vs the flax/XLA
reference (VERDICT r3 item 1 'Done' criterion: gradient-parity test vs
the XLA BN backward). Covers the jnp fallback and the Pallas kernels via
the interpreter on shapes spanning the channel-folding (C < 128) and
plain (C >= 128) layouts, plus the residual-add join."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from horovod_tpu.ops import fused_bn

EPS = 1e-5


def _ref(x, gamma, beta, residual=None, relu=True):
    """flax-numerics reference: fp32 stats (mean of x, mean of x^2,
    biased var — flax.linen.normalization._compute_stats), fp32
    normalize, optional residual add then relu, cast back."""
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=tuple(range(x.ndim - 1)))
    var = jnp.mean(jnp.square(xf), axis=tuple(range(x.ndim - 1)))
    var = var - jnp.square(mean)
    rstd = jax.lax.rsqrt(var + EPS)
    z = (xf - mean) * (rstd * gamma) + beta
    if residual is not None:
        z = z + residual.astype(jnp.float32)
    if relu:
        z = jnp.maximum(z, 0.0)
    return z.astype(x.dtype), mean, var


def _inputs(shape, seed=0, dtype=jnp.bfloat16, residual=False):
    rng = np.random.RandomState(seed)
    c = shape[-1]
    x = jnp.asarray(rng.randn(*shape), dtype)
    g = jnp.asarray(rng.randn(*shape), dtype)  # upstream cotangent
    gamma = jnp.asarray(rng.uniform(0.5, 1.5, c), jnp.float32)
    beta = jnp.asarray(rng.randn(c) * 0.1, jnp.float32)
    r = jnp.asarray(rng.randn(*shape), dtype) if residual else None
    return x, g, gamma, beta, r


SHAPES = [
    (4, 8, 8, 256),   # plain layout
    (4, 8, 8, 64),    # folded layout (k=2)
    (8, 7, 7, 128),   # M with small pow2 factor (8*49)
    (2, 5, 3, 96),    # no 128-fold -> jnp fallback path
    (512, 1, 1, 384), # block cap (131072//384=341) must floor to pow2
]


@pytest.mark.parametrize("shape", SHAPES)
@pytest.mark.parametrize("relu", [True, False])
@pytest.mark.parametrize("residual", [False, True])
def test_grad_parity_interpret(shape, relu, residual):
    """Pallas (interpret) and the jnp fallback both match flax-numerics
    XLA autodiff for y, dx, dr, dgamma, dbeta, and the batch stats."""
    x, g, gamma, beta, r = _inputs(shape, residual=residual)
    impl = ("interpret" if fused_bn._can_pallas(x.size // shape[-1],
                                                shape[-1]) else "jnp")

    def loss_ref(x, gamma, beta, r):
        y, _, _ = _ref(x, gamma, beta, residual=r, relu=relu)
        return jnp.sum(y.astype(jnp.float32) * g.astype(jnp.float32))

    def loss_fused(x, gamma, beta, r):
        y, _, _ = fused_bn.bn_act(x, gamma, beta, residual=r, eps=EPS,
                                  relu=relu, impl=impl)
        return jnp.sum(y.astype(jnp.float32) * g.astype(jnp.float32))

    argnums = (0, 1, 2, 3) if residual else (0, 1, 2)
    ref_grads = jax.jit(jax.grad(loss_ref, argnums))(x, gamma, beta, r)
    fus_grads = jax.jit(jax.grad(loss_fused, argnums))(x, gamma, beta, r)

    y_ref, m_ref, v_ref = _ref(x, gamma, beta, residual=r, relu=relu)
    y_fus, m_fus, v_fus = fused_bn.bn_act(
        x, gamma, beta, residual=r, eps=EPS, relu=relu, impl=impl)
    np.testing.assert_allclose(np.asarray(y_fus, np.float32),
                               np.asarray(y_ref, np.float32),
                               atol=0.05, rtol=0.05)
    np.testing.assert_allclose(np.asarray(m_fus), np.asarray(m_ref),
                               atol=1e-3, rtol=1e-3)
    np.testing.assert_allclose(np.asarray(v_fus), np.asarray(v_ref),
                               atol=1e-3, rtol=1e-3)

    names = ["dx", "dgamma", "dbeta", "dr"][:len(argnums)]
    for name, a, b in zip(names, fus_grads, ref_grads):
        a = np.asarray(a, np.float32)
        b = np.asarray(b, np.float32)
        scale = max(1.0, float(np.max(np.abs(b))))
        assert np.max(np.abs(a - b)) <= 0.05 * scale, (
            name, np.max(np.abs(a - b)), scale)


def test_inference_matches_running_stats():
    x, g, gamma, beta, r = _inputs((4, 8, 8, 64), residual=True)
    rm = jnp.asarray(np.random.RandomState(1).randn(64) * 0.1, jnp.float32)
    rv = jnp.asarray(np.random.RandomState(2).uniform(0.5, 1.5, 64),
                     jnp.float32)
    y = fused_bn.bn_act_inference(x, gamma, beta, rm, rv, residual=r,
                                  eps=EPS, relu=True)
    rstd = jax.lax.rsqrt(rv + EPS)
    z = (x.astype(jnp.float32) - rm) * (rstd * gamma) + beta
    z = jnp.maximum(z + r.astype(jnp.float32), 0.0)
    np.testing.assert_allclose(np.asarray(y, np.float32),
                               np.asarray(z.astype(x.dtype), np.float32),
                               atol=0.05, rtol=0.05)


def test_block_rows_divides():
    # regression: a non-power-of-two cap (C=384 -> 341) must not yield a
    # block size that fails to divide the row count (truncated grid ->
    # silently skipped trailing rows)
    for m2, c2 in ((512, 384), (802816, 1024), (12544, 2048), (64, 640)):
        bm = fused_bn._block_rows(m2, c2)
        assert m2 % bm == 0, (m2, c2, bm)
        assert bm >= 8


def test_bad_impl_raises():
    import pytest as _pytest
    x = jnp.ones((4, 4, 4, 64), jnp.bfloat16)
    with _pytest.raises(ValueError):
        fused_bn.bn_act(x, jnp.ones(64), jnp.zeros(64), impl="palas")


def test_fold_helpers():
    assert fused_bn._fold(64) == 2
    assert fused_bn._fold(32) == 4
    assert fused_bn._fold(128) == 1
    assert fused_bn._fold(96) == 1
    assert fused_bn._pow2_div(802816) >= 512
    assert fused_bn._can_pallas(256 * 56 * 56, 256)
    assert fused_bn._can_pallas(256 * 112 * 112, 64)
    assert not fused_bn._can_pallas(30, 96)


def test_resnet_flax_vs_fused_parity():
    """The fused-BN ResNet shares the flax model's parameter tree
    (checkpoint compatibility) and computes the same function: same
    logits, same grads, same batch_stats update, on identical params."""
    import optax
    from horovod_tpu.models.resnet import ResNet

    model_flax = ResNet(stage_sizes=[1, 1], num_classes=10,
                        num_filters=8, bn_impl="flax")
    model_fused = ResNet(stage_sizes=[1, 1], num_classes=10,
                         num_filters=8, bn_impl="jnp")
    x = jnp.asarray(np.random.RandomState(0).randn(2, 32, 32, 3),
                    jnp.float32)
    labels = jnp.asarray([1, 2])
    v_flax = model_flax.init(jax.random.PRNGKey(0), x, train=True)
    v_fused = model_fused.init(jax.random.PRNGKey(0), x, train=True)
    # identical trees
    assert (jax.tree_util.tree_structure(v_flax)
            == jax.tree_util.tree_structure(v_fused))
    # run fused with flax's params to prove interchangeability
    def loss(params, model):
        logits, new_state = model.apply(
            {"params": params, "batch_stats": v_flax["batch_stats"]}, x,
            train=True, mutable=["batch_stats"])
        l = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()
        return l, (logits, new_state["batch_stats"])

    (l_a, (lg_a, bs_a)), g_a = jax.value_and_grad(
        loss, has_aux=True)(v_flax["params"], model_flax)
    (l_b, (lg_b, bs_b)), g_b = jax.value_and_grad(
        loss, has_aux=True)(v_flax["params"], model_fused)
    np.testing.assert_allclose(np.asarray(lg_a), np.asarray(lg_b),
                               atol=0.15, rtol=0.1)
    assert abs(float(l_a) - float(l_b)) < 0.05
    for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_leaves_with_path(g_a),
            jax.tree_util.tree_leaves_with_path(g_b)):
        a = np.asarray(a, np.float32)
        b = np.asarray(b, np.float32)
        scale = max(1.0, float(np.max(np.abs(a))))
        assert np.max(np.abs(a - b)) <= 0.07 * scale, (
            jax.tree_util.keystr(pa), np.max(np.abs(a - b)), scale)
    for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_leaves_with_path(bs_a),
            jax.tree_util.tree_leaves_with_path(bs_b)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-2, rtol=2e-2,
                                   err_msg=jax.tree_util.keystr(pa))
