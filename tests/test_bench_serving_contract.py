"""Fast-tier contract on BENCH_SPEED.json (docs/benchmarks.md): the
serving speed-lever file must keep the arm names and the seeded-
deterministic evidence fields the acceptance criteria read — the five
lever arms, the spec_adapt A/B row, and the chunked_prefill /
session_affinity rows this PR's tentpole claims live in. The numbers
themselves are re-measured by running bench_serving.py
(--speed / --spec-adapt / --chunked-prefill / --session-affinity);
this test pins the schema plus the invariants that must hold for ANY
honest run (token-identity checksums, counter arithmetic), so a
regenerated file cannot silently drop the claims."""

import json
import os

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PATH = os.path.join(ROOT, "BENCH_SPEED.json")

SPEED_ARMS = ("baseline", "quantized_kv", "speculative", "prefix_cache",
              "all_on")
# Seeded-deterministic per-arm evidence (greedy decode, deterministic
# scheduler) — wall-clock fields (*_ms, tokens_per_s) deliberately
# excluded: they vary run to run and must not be pinned.
SPEED_ARM_FIELDS = ("decode_steps", "draft_accepted", "draft_proposed",
                    "generated_tokens", "kv_bytes_resident",
                    "output_checksum", "prefill_tokens", "prefix_hits",
                    "prefix_misses")


@pytest.fixture(scope="module")
def bench():
    if not os.path.exists(PATH):
        pytest.skip("BENCH_SPEED.json not generated on this checkout")
    with open(PATH) as f:
        return json.load(f)


def test_metric_name_is_pinned(bench):
    assert bench["metric"] == "serving_speed_levers"


@pytest.mark.parametrize("arm", SPEED_ARMS)
def test_lever_arms_carry_deterministic_fields(bench, arm):
    assert arm in bench["arms"], f"lever arm {arm} missing"
    row = bench["arms"][arm]
    for key in SPEED_ARM_FIELDS:
        assert key in row, (arm, key)


def test_lever_headlines_hold(bench):
    h = bench["headlines"]
    assert h["quantized_outputs_equal_fp32"] is True
    assert h["speculative_outputs_equal_baseline"] is True
    assert h["all_on_outputs_equal_quantized"] is True
    assert h["quantized_kv_bytes_ratio"] < 0.5
    assert 0 < h["draft_acceptance"] <= 1.0
    assert h["prefix_prefill_tokens_ratio"] < 1.0


def test_spec_adapt_row(bench):
    row = bench["spec_adapt"]
    assert set(row["arms"]) == {"adaptive", "static"}
    h = row["headlines"]
    assert h["adaptive_backed_off_to_1"] is True
    assert h["outputs_equal_static"] is True


def test_chunked_prefill_arms_and_fields(bench):
    row = bench["chunked_prefill"]
    assert set(row["arms"]) == {"baseline_no_burst", "unchunked_burst",
                                "chunked_burst"}
    for arm, a in row["arms"].items():
        for key in ("bursts_injected", "decode_ticks", "decode_tick_ms",
                    "generated_tokens", "prefill_chunks",
                    "steady_outputs_checksum"):
            assert key in a, (arm, key)
        for p in ("p50", "p90", "p99"):
            assert a["decode_tick_ms"][p] > 0, (arm, p)


def test_chunked_prefill_burst_accounting(bench):
    """The fault grammar's burst is the experiment: both burst arms
    must have injected exactly the declared 2 long prompts, the
    baseline none; only the chunked arm runs the interleaved chunk
    path (a monolithic prefill never increments the chunk counter)."""
    arms = bench["chunked_prefill"]["arms"]
    assert arms["baseline_no_burst"]["bursts_injected"] == 0
    assert arms["unchunked_burst"]["bursts_injected"] == 2
    assert arms["chunked_burst"]["bursts_injected"] == 2
    assert arms["baseline_no_burst"]["prefill_chunks"] == 0
    assert arms["unchunked_burst"]["prefill_chunks"] == 0
    assert arms["chunked_burst"]["prefill_chunks"] > 0
    # Burst arms decode the extra burst tokens on top of the steady
    # load; their generated totals agree with each other.
    assert (arms["unchunked_burst"]["generated_tokens"]
            == arms["chunked_burst"]["generated_tokens"]
            > arms["baseline_no_burst"]["generated_tokens"])


def test_chunked_prefill_token_identity_and_tail_bound(bench):
    """The tentpole's two claims: chunking only reorders prefill work
    (steady outputs token-identical across all three arms — seeded,
    greedy, so the checksums are deterministic), and it bounds the
    decode-tick tail (chunked p99 within 2x the no-burst baseline
    while the monolithic arm exceeds 2x)."""
    row = bench["chunked_prefill"]
    sums = {a["steady_outputs_checksum"] for a in row["arms"].values()}
    assert len(sums) == 1, f"steady outputs diverged across arms: {sums}"
    h = row["headlines"]
    assert h["steady_outputs_equal_across_arms"] is True
    assert h["chunked_holds_2x_baseline"] is True
    assert h["unchunked_exceeds_2x_baseline"] is True
    assert h["chunked_p99_vs_baseline"] <= 2.0
    assert h["unchunked_p99_vs_baseline"] > 2.0


def test_session_affinity_arms_and_fields(bench):
    row = bench["session_affinity"]
    assert set(row["arms"]) == {"prefix_cache_only", "session_affinity"}
    for arm, a in row["arms"].items():
        for key in ("final_context_checksum", "followup_ttft_p50_ms",
                    "followup_turns_measured", "prefill_tokens",
                    "session_hits", "session_leases"):
            assert key in a, (arm, key)


def test_session_affinity_lease_accounting(bench):
    """Deterministic counter arithmetic: with leases on, every
    follow-up turn of every conversation resumes from its session
    lease (hits == sessions * (turns - 1)); with leases off the
    session counters stay zero and the prefix cache carries what it
    can. Leases skip re-prefilling the stored context, so the lease
    arm prefills strictly fewer prompt tokens."""
    row = bench["session_affinity"]
    sess = row["arms"]["session_affinity"]
    pfx = row["arms"]["prefix_cache_only"]
    followups = row["sessions"] * (row["turns"] - 1)
    assert sess["session_hits"] == followups
    assert sess["session_leases"] >= row["sessions"]
    assert pfx["session_hits"] == 0
    assert pfx["session_leases"] == 0
    assert pfx["prefix_hits"] > 0
    assert sess["prefill_tokens"] < pfx["prefill_tokens"]


def test_session_affinity_token_identity_and_ttft(bench):
    """Leases must be a pure latency lever: the final conversation
    contexts (prompt + every generated token, all turns) are
    token-identical across arms, and the follow-up TTFT p50 beats the
    prefix-cache-only arm — the headline the acceptance reads."""
    row = bench["session_affinity"]
    assert (row["arms"]["session_affinity"]["final_context_checksum"]
            == row["arms"]["prefix_cache_only"]["final_context_checksum"])
    h = row["headlines"]
    assert h["contexts_equal_across_arms"] is True
    assert h["session_beats_prefix_ttft"] is True
    assert h["prefill_tokens_ratio"] < 1.0
