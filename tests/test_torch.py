"""PyTorch shim tests — structural mirror of the reference's test_torch.py
(1211 LoC, 33 tests): dtype x dimension sweeps for the three collectives,
async handle poll/synchronize, in-place variants, autograd through
collectives, DistributedOptimizer end-to-end, broadcast_parameters /
broadcast_optimizer_state, compression, error cases.

Virtual-rank semantics (see tests/test_ops.py): every device is a rank and
eager inputs are replicated, so allreduce(x) == size * x etc.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import horovod_tpu as hvd
import horovod_tpu.torch as hvd_torch

SWEEP_DTYPES = [torch.uint8, torch.int8, torch.int32,
                torch.float16, torch.float32, torch.bfloat16]


@pytest.fixture(autouse=True)
def _init():
    hvd.init()
    yield


def _rand(shape, dtype):
    if dtype in (torch.uint8, torch.int8, torch.int32, torch.int64):
        return torch.randint(0, 10, shape, dtype=dtype)
    return torch.rand(*shape).to(dtype)


class TestTorchAllreduce:
    @pytest.mark.parametrize("dtype", SWEEP_DTYPES)
    @pytest.mark.parametrize("dim", [1, 2, 3])
    def test_allreduce_sum(self, dtype, dim):
        t = _rand([17] * dim, dtype)
        out = hvd_torch.allreduce(t, average=False)
        expected = t * hvd.size()
        assert out.dtype == dtype
        tol = 1e-2 if dtype in (torch.float16, torch.bfloat16) else 1e-5
        assert torch.allclose(out.float(), expected.float(),
                              rtol=tol, atol=tol)

    @pytest.mark.parametrize("dtype", [torch.int64, torch.float64])
    def test_allreduce_64bit_rejected_without_x64(self, dtype):
        # Without jax_enable_x64, a 64-bit reduction would silently narrow
        # to 32 bits — the shim must refuse rather than corrupt.
        t = torch.tensor([2 ** 40, 5], dtype=dtype)
        with pytest.raises(ValueError, match="64-bit"):
            hvd_torch.allreduce(t, average=False)

    @pytest.mark.parametrize("dtype", [torch.int64, torch.float64])
    def test_broadcast_allgather_64bit_exact(self, dtype):
        # Data-movement collectives transport 64-bit values as int32 bit
        # pairs — exact even in 32-bit JAX mode.
        t = torch.tensor([[2 ** 40 + 3, -7], [1, 2 ** 52 + 1]], dtype=dtype)
        out = hvd_torch.broadcast(t.clone(), root_rank=0)
        assert torch.equal(out, t)
        g = hvd_torch.allgather(t.clone())
        assert g.shape[0] == 2 * hvd.size()
        assert torch.equal(g[:2], t)

    def test_allreduce_average(self):
        t = torch.rand(5, 5)
        out = hvd_torch.allreduce(t, average=True)
        assert torch.allclose(out, t, rtol=1e-5, atol=1e-6)

    def test_allreduce_inplace(self):
        t = torch.ones(4, 4)
        ret = hvd_torch.allreduce_(t, average=False)
        assert ret is t
        assert torch.allclose(t, torch.full((4, 4), float(hvd.size())))

    def test_allreduce_async_poll_synchronize(self):
        t = torch.ones(8)
        handle = hvd_torch.allreduce_async(t, average=False)
        out = hvd_torch.synchronize(handle)
        assert torch.allclose(out, torch.full((8,), float(hvd.size())))
        # handle is cleared after synchronize (HandleManager semantics)
        with pytest.raises(ValueError):
            hvd_torch.synchronize(handle)

    def test_allreduce_async_poll_completes(self):
        t = torch.ones(8)
        handle = hvd_torch.allreduce_async(t, average=False)
        deadline = 100
        while not hvd_torch.poll(handle) and deadline:
            deadline -= 1
        hvd_torch.synchronize(handle)

    def test_allreduce_multiple_fused(self):
        tensors = [torch.rand(10) for _ in range(8)]
        handles = [hvd_torch.allreduce_async(t, average=False,
                                             name=f"fuse.{i}")
                   for i, t in enumerate(tensors)]
        for t, h in zip(tensors, handles):
            out = hvd_torch.synchronize(h)
            assert torch.allclose(out, t * hvd.size(), rtol=1e-5, atol=1e-6)

    def test_allreduce_grad(self):
        t = torch.rand(5, requires_grad=True)
        out = hvd_torch.allreduce(t, average=False)
        out.sum().backward()
        # backward of sum-allreduce is sum-allreduce of the ones grad
        assert torch.allclose(t.grad,
                              torch.full((5,), float(hvd.size())))

    def test_allreduce_compression_fp16(self):
        t = torch.rand(16)
        out = hvd_torch.allreduce(t, average=True,
                                  compression=hvd_torch.Compression.fp16)
        assert out.dtype == torch.float32
        assert torch.allclose(out, t, rtol=1e-2, atol=1e-2)


class TestTorchAllgather:
    @pytest.mark.parametrize("dtype", [torch.int32, torch.float32,
                                       torch.bfloat16])
    @pytest.mark.parametrize("dim", [1, 2, 3])
    def test_allgather(self, dtype, dim):
        t = _rand([17] * dim, dtype)
        out = hvd_torch.allgather(t)
        assert out.shape[0] == 17 * hvd.size()
        for r in range(hvd.size()):
            seg = out[r * 17:(r + 1) * 17]
            assert torch.equal(seg, t)

    def test_allgather_grad(self):
        t = torch.rand(3, 2, requires_grad=True)
        out = hvd_torch.allgather(t)
        out.sum().backward()
        assert t.grad.shape == t.shape
        # each of the size() copies contributes 1 through the sum, and the
        # backward sums across ranks
        assert torch.allclose(t.grad,
                              torch.full((3, 2), float(hvd.size())))


class TestTorchBroadcast:
    @pytest.mark.parametrize("dtype", [torch.int32, torch.float32])
    def test_broadcast(self, dtype):
        t = _rand([17, 17], dtype)
        out = hvd_torch.broadcast(t, root_rank=0)
        assert torch.equal(out, t)

    def test_broadcast_inplace(self):
        t = torch.rand(4)
        ret = hvd_torch.broadcast_(t, root_rank=0)
        assert ret is t

    def test_broadcast_invalid_root(self):
        with pytest.raises(ValueError):
            hvd_torch.broadcast(torch.ones(2), root_rank=hvd.size() + 7)

    def test_broadcast_grad_root(self):
        t = torch.rand(4, requires_grad=True)
        out = hvd_torch.broadcast(t, root_rank=0)
        out.sum().backward()
        if hvd_torch.rank() == 0:
            assert torch.allclose(t.grad,
                                  torch.full((4,), float(hvd.size())))


class TestDistributedOptimizer:
    def _model(self):
        torch.manual_seed(0)
        return torch.nn.Sequential(
            torch.nn.Linear(8, 16), torch.nn.ReLU(), torch.nn.Linear(16, 2))

    def test_end_to_end_step(self):
        model = self._model()
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        opt = hvd_torch.DistributedOptimizer(
            opt, named_parameters=model.named_parameters())
        x = torch.rand(4, 8)
        y = torch.randint(0, 2, (4,))
        before = [p.detach().clone() for p in model.parameters()]
        loss = torch.nn.functional.cross_entropy(model(x), y)
        loss.backward()
        opt.step()
        after = list(model.parameters())
        assert any(not torch.equal(b, a.detach())
                   for b, a in zip(before, after))

    def test_gradients_are_averaged(self):
        model = self._model()
        base = torch.optim.SGD(model.parameters(), lr=0.0)
        opt = hvd_torch.DistributedOptimizer(
            base, named_parameters=model.named_parameters())
        x = torch.rand(4, 8)
        loss = model(x).sum()
        loss.backward()
        expected = {n: p.grad.detach().clone()
                    for n, p in model.named_parameters()}
        opt.synchronize()
        # average over identical virtual ranks == local grad
        for n, p in model.named_parameters():
            assert torch.allclose(p.grad, expected[n], rtol=1e-4, atol=1e-5)

    def test_backward_passes_per_step(self):
        model = self._model()
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters(),
            backward_passes_per_step=2)
        x = torch.rand(4, 8)
        model(x).sum().backward()
        model(x).sum().backward()
        opt.step()

    def test_skip_synchronize_gradient_clipping(self):
        # The gradient-clipping recipe: explicit synchronize(), clip, then
        # step() inside skip_synchronize() — no second allreduce, no warning.
        import warnings as _warnings
        model = self._model()
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters())
        model(torch.rand(4, 8)).sum().backward()
        opt.synchronize()
        torch.nn.utils.clip_grad_norm_(model.parameters(), 1.0)
        with _warnings.catch_warnings():
            _warnings.simplefilter("error")
            with opt.skip_synchronize():
                opt.step()
        # step() right after synchronize() without the guard re-allreduces
        # and must warn about it
        model(torch.rand(4, 8)).sum().backward()
        opt.synchronize()
        with pytest.warns(UserWarning, match="skip_synchronize"):
            opt.step()

    def test_partial_accumulation_step_still_allreduces(self):
        # Early step() mid-accumulation (dataset not divisible by
        # backward_passes_per_step): every pending gradient must still be
        # flushed through allreduce, and delays reset, or replicas diverge.
        model = self._model()
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters(),
            backward_passes_per_step=2)
        model(torch.rand(4, 8)).sum().backward()  # one pass only
        opt.step()
        for group in opt.param_groups:
            for p in group["params"]:
                assert opt._allreduce_delay[id(p)] == 2
        assert not opt._handles

    def test_double_backward_raises_without_accumulation(self):
        model = self._model()
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters())
        x = torch.rand(4, 8)
        model(x).sum().backward()
        with pytest.raises((AssertionError, RuntimeError)):
            model(x).sum().backward()
        # drain in-flight handles so their names free up for later tests
        opt.synchronize()

    def test_named_parameters_validation(self):
        model = self._model()
        other = torch.nn.Linear(2, 2)
        with pytest.raises(ValueError):
            hvd_torch.DistributedOptimizer(
                torch.optim.SGD(model.parameters(), lr=0.1),
                named_parameters=other.named_parameters())

    def test_isinstance_preserved(self):
        model = self._model()
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters())
        assert isinstance(opt, torch.optim.SGD)

    def test_compression_fp16_optimizer(self):
        model = self._model()
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters(),
            compression=hvd_torch.Compression.fp16)
        model(torch.rand(4, 8)).sum().backward()
        opt.step()
        for p in model.parameters():
            assert p.grad.dtype == torch.float32


class TestBroadcastState:
    def test_broadcast_parameters_state_dict(self):
        model = torch.nn.Linear(4, 4)
        hvd_torch.broadcast_parameters(model.state_dict(), root_rank=0)

    def test_broadcast_parameters_named(self):
        model = torch.nn.Linear(4, 4)
        before = {n: p.detach().clone()
                  for n, p in model.named_parameters()}
        hvd_torch.broadcast_parameters(model.named_parameters(), root_rank=0)
        for n, p in model.named_parameters():
            assert torch.allclose(p.detach(), before[n])

    def test_broadcast_parameters_batchnorm_state_dict(self):
        # BatchNorm carries a 0-dim int64 buffer (num_batches_tracked) that
        # must survive the int32 bit-pair transport under 32-bit JAX.
        model = torch.nn.Sequential(torch.nn.Linear(4, 4),
                                    torch.nn.BatchNorm1d(4))
        model(torch.rand(8, 4))  # tick num_batches_tracked to 1
        tracked = model[1].num_batches_tracked.clone()
        hvd_torch.broadcast_parameters(model.state_dict(), root_rank=0)
        assert torch.equal(model[1].num_batches_tracked, tracked)

    def test_broadcast_0dim_int64_roundtrip(self):
        t = torch.tensor(2 ** 40 + 7, dtype=torch.int64)
        out = hvd_torch.broadcast(t.clone(), root_rank=0)
        assert out.shape == t.shape
        assert torch.equal(out, t)

    def test_broadcast_optimizer_state(self):
        model = torch.nn.Linear(4, 4)
        opt = torch.optim.Adam(model.parameters(), lr=1e-3)
        model(torch.rand(2, 4)).sum().backward()
        opt.step()
        lr_before = opt.param_groups[0]["lr"]
        hvd_torch.broadcast_optimizer_state(opt, root_rank=0)
        assert opt.param_groups[0]["lr"] == pytest.approx(lr_before)
        for st in opt.state.values():
            assert "exp_avg" in st

    def test_broadcast_optimizer_state_materializes_empty(self):
        model = torch.nn.Linear(4, 4)
        opt = torch.optim.Adam(model.parameters(), lr=1e-3)
        params_before = [p.detach().clone() for p in model.parameters()]
        hvd_torch.broadcast_optimizer_state(opt, root_rank=0)
        # zero-grad materialization must not move the parameters
        for b, p in zip(params_before, model.parameters()):
            assert torch.allclose(b, p.detach())
        assert len(opt.state) > 0

    def test_broadcast_optimizer_state_lbfgs_rejected(self):
        model = torch.nn.Linear(4, 4)
        opt = torch.optim.LBFGS(model.parameters())
        with pytest.raises(ValueError):
            hvd_torch.broadcast_optimizer_state(opt, root_rank=0)


class TestGradientBuckets:
    """Backward-overlap bucketing (docs/torch.md): per-bucket fused
    apply must be numerically indistinguishable from the per-tensor
    path — bitwise for a full-precision wire, within wire tolerance for
    quantized specs — with the error-feedback residual keyed by bucket."""

    def _model(self, seed=0):
        torch.manual_seed(seed)
        return torch.nn.Sequential(
            torch.nn.Linear(16, 32), torch.nn.Tanh(),
            torch.nn.Linear(32, 32), torch.nn.Tanh(),
            torch.nn.Linear(32, 4))

    def _grads_after_sync(self, bucket_cap_mb, compression=None, seed=0):
        model = self._model(seed)
        kwargs = dict(named_parameters=model.named_parameters(),
                      bucket_cap_mb=bucket_cap_mb)
        if compression is not None:
            kwargs["compression"] = compression
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.0), **kwargs)
        torch.manual_seed(7)
        model(torch.rand(8, 16)).sum().backward()
        opt.synchronize()
        return opt, {n: p.grad.detach().clone()
                     for n, p in model.named_parameters()}

    def test_bucket_partition_covers_every_param(self):
        model = self._model()
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters(),
            bucket_cap_mb=0.001)
        assert len(opt._buckets) > 1
        covered = {pid for b in opt._buckets for pid in b.offsets}
        want = {id(p) for p in model.parameters() if p.requires_grad}
        assert covered == want
        for b in opt._buckets:
            assert b.numel == sum(n for _, n in b.offsets.values())
            assert b.buffer.numel() == b.numel

    def test_bucket_equals_per_tensor_bitwise_fp32(self):
        _, bucketed = self._grads_after_sync(bucket_cap_mb=0.001)
        _, per_tensor = self._grads_after_sync(bucket_cap_mb=0)
        for n in per_tensor:
            assert torch.equal(bucketed[n], per_tensor[n]), n

    def test_bucket_cap_zero_disables(self):
        model = self._model()
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters(), bucket_cap_mb=0)
        assert opt._buckets == []
        model(torch.rand(4, 16)).sum().backward()
        opt.step()  # legacy per-tensor path still trains

    def test_bucket_equals_per_tensor_fp16_wire(self):
        _, bucketed = self._grads_after_sync(
            bucket_cap_mb=0.001, compression=hvd_torch.Compression.fp16)
        _, per_tensor = self._grads_after_sync(
            bucket_cap_mb=0, compression=hvd_torch.Compression.fp16)
        for n in per_tensor:
            # fp16 rounding is elementwise, so buffer layout cannot
            # change it — still bitwise.
            assert torch.equal(bucketed[n], per_tensor[n]), n

    def test_bucket_quantized_within_wire_tolerance(self):
        _, bucketed = self._grads_after_sync(
            bucket_cap_mb=0.001,
            compression=hvd_torch.Compression.int8_blockwise)
        _, per_tensor = self._grads_after_sync(
            bucket_cap_mb=0,
            compression=hvd_torch.Compression.int8_blockwise)
        for n in per_tensor:
            ref = per_tensor[n]
            tol = 2e-2 * (ref.abs().max().item() + 1e-8)
            assert (bucketed[n] - ref).abs().max().item() <= tol, n

    def test_error_feedback_residual_keyed_by_bucket(self):
        opt, _ = self._grads_after_sync(
            bucket_cap_mb=0.001,
            compression=hvd_torch.Compression.int8_blockwise)
        n_params = sum(len(b.params) for b in opt._buckets)
        assert len(opt._buckets) > 1 and n_params > len(opt._buckets)
        # One residual per FIRED BUCKET — not one per tensor.
        assert set(opt._bucket_residuals) <= {b.index
                                              for b in opt._buckets}
        assert len(opt._bucket_residuals) == len(opt._buckets)
        for idx, res in opt._bucket_residuals.items():
            b = opt._buckets[idx]
            assert res.shape == b.buffer.shape
            assert res.abs().sum().item() > 0  # int8 wire drops bits

    def test_no_error_feedback_without_blockwise(self):
        opt, _ = self._grads_after_sync(bucket_cap_mb=0.001)
        assert opt._bucket_residuals == {}

    def test_flush_trigger_mid_accumulation(self):
        from horovod_tpu import metrics_snapshot

        def fires():
            vals = metrics_snapshot().get(
                "hvdtpu_torch_bucket_fires_total", {}).get("values", {})
            return (vals.get('trigger="hook"', 0),
                    vals.get('trigger="flush"', 0))

        model = self._model()
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters(),
            backward_passes_per_step=2, bucket_cap_mb=0.001)
        nb = len(opt._buckets)
        h0, f0 = fires()
        model(torch.rand(4, 16)).sum().backward()   # one pass only
        opt.step()                                   # early step: flush
        h1, f1 = fires()
        assert (h1 - h0, f1 - f0) == (0, nb)
        for group in opt.param_groups:
            for p in group["params"]:
                assert opt._allreduce_delay[id(p)] == 2
        assert not opt._handles
        # A full two-pass step fires every bucket from its last HOOK.
        model(torch.rand(4, 16)).sum().backward()
        model(torch.rand(4, 16)).sum().backward()
        opt.step()
        h2, f2 = fires()
        assert (h2 - h1, f2 - f1) == (nb, 0)

    def test_custom_compressor_falls_back_to_per_tensor(self):
        # A subclass may override compress/decompress with arbitrary
        # logic the bucket pack cannot fuse — only the STOCK compressor
        # classes bucket; anything else keeps the per-tensor path where
        # the compressor runs verbatim (gradient-as-bucket-view cases
        # live in TestGradientAsBucketView below).
        class Doubler(hvd_torch.Compression.none):
            @staticmethod
            def compress(tensor):
                return tensor * 0.5, None

            @staticmethod
            def decompress(tensor, ctx):
                return tensor * 2.0

        model = self._model()
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.0),
            named_parameters=model.named_parameters(),
            compression=Doubler)
        assert opt._buckets == []
        torch.manual_seed(7)
        model(torch.rand(8, 16)).sum().backward()
        expected = {n: p.grad.detach().clone()
                    for n, p in model.named_parameters()}
        opt.synchronize()
        for n, p in model.named_parameters():
            assert torch.allclose(p.grad, expected[n],
                                  rtol=1e-5, atol=1e-6), n

    def test_steady_state_interop_all_dlpack(self):
        """The BENCH_SHIMS acceptance, fast-tier: a steady-state torch
        training step crosses the boundary via DLPack only — one
        crossing per bucket each way, zero numpy — when the egress
        capability probe holds (it always does on the CPU backend)."""
        from horovod_tpu.utils import interop
        if not interop.transfer_egress_supported():
            pytest.skip("no DLPack-capable egress on this backend")
        model = self._model()
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.01),
            named_parameters=model.named_parameters(),
            bucket_cap_mb=0.001)
        nb = len(opt._buckets)
        x = torch.rand(8, 16)

        def step():
            opt.zero_grad()
            model(x).sum().backward()
            opt.step()

        for _ in range(2):
            step()
        interop.reset_stats()
        step()
        s = interop.stats()
        assert s["numpy_out"] == 0 and s["numpy_in"] == 0, s
        assert s["dlpack_in"] == nb and s["dlpack_out"] == nb, (s, nb)

    def test_steady_state_reuses_compiled_programs(self):
        """Per-bucket programs are persistent: after warmup, a training
        step is all executor cache HITS — no recompiles (the acceptance
        criterion's compile-counter proof, fast tier)."""
        from horovod_tpu import metrics_snapshot

        def counters():
            snap = metrics_snapshot()
            return (snap.get("hvdtpu_executor_cache_misses_total",
                             {}).get("values", {}).get("", 0),
                    snap.get("hvdtpu_executor_cache_hits_total",
                             {}).get("values", {}).get("", 0))

        model = self._model()
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.01),
            named_parameters=model.named_parameters(),
            bucket_cap_mb=0.001)
        nb = len(opt._buckets)
        x = torch.rand(8, 16)

        def step():
            opt.zero_grad()
            model(x).sum().backward()
            opt.step()

        for _ in range(2):
            step()
        misses0, hits0 = counters()
        step()
        misses1, hits1 = counters()
        assert misses1 == misses0, "steady-state step recompiled"
        # Tiny test buckets all fit one fused engine group, so the
        # floor is >= 1 program reuse; at the default cap (== fusion
        # threshold) it is one reused program per bucket.
        assert hits1 - hits0 >= 1 and nb > 1


class TestGradientAsBucketView:
    """gradient_as_bucket_view (docs/torch.md): each eligible p.grad is
    a VIEW into its bucket's flat buffer, so autograd accumulates
    straight into the collective payload — no pack memcpy, no
    scatter-back — and the results stay bitwise identical to the
    copying path."""

    def _model(self, seed=0):
        torch.manual_seed(seed)
        return torch.nn.Sequential(
            torch.nn.Linear(16, 32), torch.nn.Tanh(),
            torch.nn.Linear(32, 32), torch.nn.Tanh(),
            torch.nn.Linear(32, 4))

    def _train(self, view, steps=3, compression=None, zero_none=False,
               seed=0):
        model = self._model(seed)
        kwargs = dict(named_parameters=model.named_parameters(),
                      bucket_cap_mb=0.001,
                      gradient_as_bucket_view=view)
        if compression is not None:
            kwargs["compression"] = compression
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.05), **kwargs)
        torch.manual_seed(7)
        for _ in range(steps):
            model(torch.rand(8, 16)).sum().backward()
            opt.step()
            opt.zero_grad(set_to_none=True) if zero_none \
                else opt.zero_grad()
        return model, opt

    def _rebinds(self):
        from horovod_tpu import metrics_snapshot
        vals = metrics_snapshot().get(
            "hvdtpu_torch_grad_view_rebinds_total", {}).get("values", {})
        return sum(vals.values()) if vals else 0

    def test_views_installed_and_aliased(self):
        _, opt = self._train(True, steps=1)
        n_bucketed = sum(len(b.params) for b in opt._buckets)
        assert len(opt._grad_views) == n_bucketed > 0
        for group in opt.param_groups:
            for p in group["params"]:
                assert opt._grad_is_view(p)
                b = opt._param_bucket[id(p)]
                assert p.grad.data_ptr() == b.view_of(p).data_ptr()

    def test_bitwise_equals_copy_path(self):
        m_copy, _ = self._train(False)
        m_view, _ = self._train(True)
        for (n, a), (_, b) in zip(m_copy.named_parameters(),
                                  m_view.named_parameters()):
            assert torch.equal(a, b), n

    def test_bitwise_equals_copy_path_blockwise_ef(self):
        # Per-bucket error feedback reads/writes the bucket buffer the
        # views alias — the quantized path must agree bitwise too.
        m_copy, _ = self._train(
            False, compression=hvd_torch.Compression.int8_blockwise)
        m_view, _ = self._train(
            True, compression=hvd_torch.Compression.int8_blockwise)
        for (n, a), (_, b) in zip(m_copy.named_parameters(),
                                  m_view.named_parameters()):
            assert torch.equal(a, b), n

    def test_fp16_wire_keeps_copy_path(self):
        # A cast compressor's pack IS a cast: fp32 params with an fp16
        # bucket buffer cannot alias — no views, copy path preserved.
        _, opt = self._train(True, steps=1,
                             compression=hvd_torch.Compression.fp16)
        assert opt._grad_views == {}

    def test_zero_grad_default_preserves_views(self):
        r0 = self._rebinds()
        m, opt = self._train(True)
        for group in opt.param_groups:
            for p in group["params"]:
                assert opt._grad_is_view(p)
        assert self._rebinds() == r0   # no alias was ever lost

    def test_set_to_none_rebinds_and_counts(self):
        r0 = self._rebinds()
        m_view, _ = self._train(True, zero_none=True)
        rebinds = self._rebinds() - r0
        assert rebinds > 0             # every post-zero step repaired
        m_copy, _ = self._train(False, zero_none=True)
        for (n, a), (_, b) in zip(m_copy.named_parameters(),
                                  m_view.named_parameters()):
            assert torch.equal(a, b), n

    def test_env_default(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_TPU_TORCH_GRAD_VIEW", "1")
        model = self._model()
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.0),
            named_parameters=model.named_parameters(),
            bucket_cap_mb=0.001)
        assert opt._grad_views


class TestResultAliasing:
    """ADVICE medium: out-of-place synchronize results must not alias
    engine-owned XLA buffers — in-place torch math on a returned tensor
    must never mutate an array the engine still retains."""

    def test_inplace_math_on_result_cannot_mutate_engine_array(self):
        from horovod_tpu.torch import mpi_ops

        t = torch.ones(16, dtype=torch.float32)
        h = mpi_ops.allreduce_async(t, average=False, name="alias.reg")
        # Hold the ENGINE handle before synchronize pops the torch-level
        # entry: its _result is exactly the engine-retained jax array the
        # DLPack egress would alias.
        inner = mpi_ops._handles[h].inner
        out = mpi_ops.synchronize(h)
        engine_arr = inner._result
        assert engine_arr is not None
        before = np.asarray(engine_arr).copy()
        out.mul_(0).sub_(123)          # hostile in-place math
        after = np.asarray(engine_arr)
        np.testing.assert_array_equal(before, after)

    def test_out_of_place_results_unshared(self):
        """Two out-of-place results of identical collectives must not
        share storage with each other either (distinct clones)."""
        t = torch.full((8,), 2.0)
        a = hvd_torch.allreduce(t, average=False)
        b = hvd_torch.allreduce(t, average=False)
        a.add_(7)
        assert not torch.equal(a, b)
        assert torch.allclose(b, torch.full((8,), 2.0 * hvd.size()))

    def test_inplace_variant_still_lands_in_target(self):
        t = torch.ones(8, dtype=torch.float32)
        out = hvd_torch.allreduce_(t, average=False)
        assert out is t
        assert torch.allclose(t, torch.full((8,), float(hvd.size())))


class TestBucketRepartition:
    """Online bucket re-partition (``set_bucket_cap_mb``) — the global
    autotuner's ``torch_bucket_mb`` knob, safety class ``boundary``
    (docs/torch.md, docs/autotune.md): gradients after a mid-run
    re-partition must equal a fresh optimizer built with the new cap
    from the start; the move must refuse to run while bucket
    collectives are in flight; grad views must re-alias into the new
    flat buffers."""

    def _model(self, seed=0):
        torch.manual_seed(seed)
        return torch.nn.Sequential(
            torch.nn.Linear(16, 32), torch.nn.Tanh(),
            torch.nn.Linear(32, 32), torch.nn.Tanh(),
            torch.nn.Linear(32, 4))

    def _wrap(self, model, **kw):
        return hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.0),
            named_parameters=model.named_parameters(), **kw)

    def test_repartition_equals_fresh_static_cap(self):
        model = self._model()
        opt = self._wrap(model, bucket_cap_mb=0.001)
        torch.manual_seed(7)
        model(torch.rand(8, 16)).sum().backward()
        opt.step()
        assert len(opt._buckets) > 1
        opt.zero_grad()
        opt.set_bucket_cap_mb(64)
        assert len(opt._buckets) == 1  # tiny model, one 64 MB bucket
        torch.manual_seed(7)
        model(torch.rand(8, 16)).sum().backward()
        opt.synchronize()
        moved = {n: p.grad.detach().clone()
                 for n, p in model.named_parameters()}

        fresh_model = self._model()
        fresh = self._wrap(fresh_model, bucket_cap_mb=64)
        torch.manual_seed(7)
        fresh_model(torch.rand(8, 16)).sum().backward()
        fresh.synchronize()
        for n, p in fresh_model.named_parameters():
            assert torch.equal(moved[n], p.grad), n

    def test_in_flight_collectives_refuse_the_move(self):
        model = self._model()
        opt = self._wrap(model, bucket_cap_mb=0.001)
        model(torch.rand(8, 16)).sum().backward()
        assert opt._handles  # bucket allreduces launched by the hooks
        with pytest.raises(RuntimeError, match="in flight"):
            opt.set_bucket_cap_mb(32)
        opt.synchronize()
        opt.set_bucket_cap_mb(32)  # boundary reached: now legal

    def test_bucketless_optimizer_rejects_repartition(self):
        model = self._model()
        opt = self._wrap(model, bucket_cap_mb=0)
        with pytest.raises(ValueError, match="already"):
            opt.set_bucket_cap_mb(32)
        opt2 = self._wrap(self._model(), bucket_cap_mb=0.001)
        with pytest.raises(ValueError, match="positive"):
            opt2.set_bucket_cap_mb(0)

    def test_grad_views_realias_and_content_survives(self):
        model = self._model()
        opt = self._wrap(model, bucket_cap_mb=0.001,
                         gradient_as_bucket_view=True)
        torch.manual_seed(7)
        model(torch.rand(8, 16)).sum().backward()
        opt.step()
        before = {n: p.grad.detach().clone()
                  for n, p in model.named_parameters()}
        old_buffers = [b.buffer.data_ptr() for b in opt._buckets]
        opt.set_bucket_cap_mb(64)
        new_buffers = {b.buffer.data_ptr() for b in opt._buckets}
        assert not new_buffers & set(old_buffers)
        for n, p in model.named_parameters():
            # Aliased into the NEW flat buffer, content preserved (the
            # move clones grads out of the dying storage first).
            assert opt._grad_is_view(p), n
            assert torch.equal(p.grad, before[n]), n
        # The re-targeted hooks keep training: next step bitwise-matches
        # a fresh static-cap optimizer with views.
        opt.zero_grad()
        torch.manual_seed(11)
        model(torch.rand(8, 16)).sum().backward()
        opt.synchronize()
        fresh_model = self._model()
        fresh = self._wrap(fresh_model, bucket_cap_mb=64,
                           gradient_as_bucket_view=True)
        torch.manual_seed(11)
        fresh_model(torch.rand(8, 16)).sum().backward()
        fresh.synchronize()
        for (n, p), (_, q) in zip(model.named_parameters(),
                                  fresh_model.named_parameters()):
            assert torch.equal(p.grad, q.grad), n

    def test_repartition_leaves_flight_note(self):
        from horovod_tpu.observability import flight_recorder as _fr
        model = self._model()
        opt = self._wrap(model, bucket_cap_mb=0.001)
        n0 = len(_fr.recorder()._snapshot())
        opt.set_bucket_cap_mb(16)
        notes = [p for _, kind, p in _fr.recorder()._snapshot()[n0:]
                 if kind == "autotune" and p[0] == "bucket_repartition"]
        assert notes and notes[0][1] == "torch_bucket_mb"
