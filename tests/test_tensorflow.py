"""TensorFlow shim tests — structural mirror of the reference's
test_tensorflow.py (806 LoC, 24 tests): dtype x dimension sweeps for the
three collectives, eager AND tf.function (graph-traced) execution,
registered gradients checked numerically, IndexedSlices sparse path,
DistributedGradientTape, variable broadcast.

Keras-optimizer integration runs in a subprocess with
KERAS_BACKEND=tensorflow (tests/test_keras_tf.py) to avoid pinning the
in-process Keras backend, which tests/test_keras.py sets to torch.
"""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

import horovod_tpu as hvd
import horovod_tpu.tensorflow as hvd_tf

SWEEP_DTYPES = [tf.uint8, tf.int8, tf.int32, tf.float16, tf.float32,
                tf.bfloat16]


@pytest.fixture(autouse=True)
def _init():
    hvd.init()
    yield


def _rand(shape, dtype):
    if dtype in (tf.uint8, tf.int8, tf.int32):
        return tf.cast(tf.random.uniform(shape, 0, 10, dtype=tf.int32),
                       dtype)
    return tf.cast(tf.random.uniform(shape), dtype)


class TestTFAllreduce:
    @pytest.mark.parametrize("dtype", SWEEP_DTYPES)
    @pytest.mark.parametrize("dim", [1, 2, 3])
    def test_allreduce_sum(self, dtype, dim):
        t = _rand([17] * dim, dtype)
        out = hvd_tf.allreduce(t, average=False)
        assert out.dtype == dtype
        expected = tf.cast(t, tf.float32) * hvd.size()
        tol = 1e-1 if dtype in (tf.float16, tf.bfloat16) else 1e-4
        assert np.allclose(tf.cast(out, tf.float32).numpy(),
                           expected.numpy(), rtol=tol, atol=tol)

    def test_allreduce_average(self):
        t = tf.constant([1.0, 2.0, 3.0])
        out = hvd_tf.allreduce(t, average=True)
        assert np.allclose(out.numpy(), t.numpy(), atol=1e-5)

    def test_allreduce_inside_tf_function(self):
        # The py_function bridge must survive graph tracing — the
        # AsyncOpKernel role (tensorflow/mpi_ops.cc:281-303).
        @tf.function
        def fn(x):
            return hvd_tf.allreduce(x, average=False)

        t = tf.constant([[1.0, 2.0], [3.0, 4.0]])
        out = fn(t)
        assert np.allclose(out.numpy(), t.numpy() * hvd.size())

    def test_allreduce_grad(self):
        # grad(allreduce(x)) = allreduce(grad) → for sum over identical
        # ranks: d(sum)/dx elementwise = size (test_tensorflow.py:334-368).
        t = tf.Variable([1.0, 2.0, 3.0])
        with tf.GradientTape() as tape:
            out = tf.reduce_sum(hvd_tf.allreduce(t, average=False))
        g = tape.gradient(out, t)
        assert np.allclose(g.numpy(), np.full(3, float(hvd.size())))

    def test_allreduce_compression_fp16(self):
        t = tf.constant([1.5, 2.5, 3.5])
        out = hvd_tf.allreduce(t, average=True,
                               compression=hvd_tf.Compression.fp16)
        assert out.dtype == tf.float32
        assert np.allclose(out.numpy(), t.numpy(), atol=1e-2)

    def test_allreduce_indexed_slices(self):
        # Sparse gradients travel as allgather(values)+allgather(indices)
        # (tensorflow/__init__.py:72-83).
        v = tf.IndexedSlices(values=tf.constant([[1.0, 2.0]]),
                             indices=tf.constant([3]),
                             dense_shape=tf.constant([8, 2]))
        out = hvd_tf.allreduce(v, average=False)
        assert isinstance(out, tf.IndexedSlices)
        assert out.values.shape[0] == hvd.size()
        assert out.indices.shape[0] == hvd.size()
        assert np.allclose(out.values.numpy()[0], [1.0, 2.0])


class TestTFAllgather:
    @pytest.mark.parametrize("dtype", [tf.int32, tf.float32])
    @pytest.mark.parametrize("dim", [1, 2])
    def test_allgather(self, dtype, dim):
        t = _rand([5] * dim, dtype)
        out = hvd_tf.allgather(t)
        assert out.shape[0] == 5 * hvd.size()
        assert np.allclose(tf.cast(out[:5], tf.float32).numpy(),
                           tf.cast(t, tf.float32).numpy())

    def test_allgather_grad(self):
        t = tf.Variable([[1.0, 2.0], [3.0, 4.0]])
        with tf.GradientTape() as tape:
            out = tf.reduce_sum(hvd_tf.allgather(t))
        g = tape.gradient(out, t)
        # Each rank's slice of the summed gathered grad = ones * size.
        assert np.allclose(g.numpy(), np.full((2, 2), float(hvd.size())))


class TestTFBroadcast:
    def test_broadcast(self):
        t = tf.constant([1.0, 2.0, 3.0])
        out = hvd_tf.broadcast(t, root_rank=0)
        assert np.allclose(out.numpy(), t.numpy())

    def test_broadcast_grad_root(self):
        t = tf.Variable([1.0, 2.0])
        with tf.GradientTape() as tape:
            out = tf.reduce_sum(hvd_tf.broadcast(t, root_rank=0))
        g = tape.gradient(out, t)
        if hvd.rank() == 0:
            assert np.allclose(g.numpy(), np.full(2, float(hvd.size())))

    def test_broadcast_variables(self):
        v1 = tf.Variable([1.0, 2.0])
        v2 = tf.Variable([[3.0]])
        before = [v1.numpy().copy(), v2.numpy().copy()]
        hvd_tf.broadcast_variables([v1, v2], root_rank=0)
        assert np.allclose(v1.numpy(), before[0])
        assert np.allclose(v2.numpy(), before[1])

    def test_broadcast_global_requires_variables(self):
        with pytest.raises(ValueError):
            hvd_tf.broadcast_global_variables(0)


class TestDistributedGradientTape:
    def test_tape_averages(self):
        v = tf.Variable([1.0, 2.0])
        with hvd_tf.DistributedGradientTape() as tape:
            loss = tf.reduce_sum(v * v)
        g = tape.gradient(loss, [v])[0]
        # average over identical ranks == local grad (2v)
        assert np.allclose(g.numpy(), 2 * v.numpy(), atol=1e-5)

    def test_tape_training_loop(self):
        v = tf.Variable([4.0])
        for _ in range(3):
            with hvd_tf.DistributedGradientTape() as tape:
                loss = tf.reduce_sum(v * v)
            (g,) = tape.gradient(loss, [v])
            v.assign_sub(0.1 * g)
        assert float(v.numpy()[0]) < 4.0

    def test_callback_hook(self):
        cb = hvd_tf.BroadcastGlobalVariablesCallback(0)

        class M:
            variables = [tf.Variable([1.0])]

        cb(model=M())
        assert cb._done


class TestGroupedBridge:
    def test_tape_many_variables_one_bridge(self):
        """VERDICT r1 #7 'done' condition: a tape with >= 20 variables
        crosses the host bridge ONCE per gradient call (one engine-fused
        burst), not once per variable."""
        n_vars = 24
        vs = [tf.Variable(tf.fill([3], float(i + 1))) for i in range(n_vars)]
        with hvd_tf.DistributedGradientTape() as tape:
            loss = tf.add_n([tf.reduce_sum(v * v) for v in vs])
        before = hvd_tf._bridge_calls[0]
        grads = tape.gradient(loss, vs)
        bridged = hvd_tf._bridge_calls[0] - before
        assert bridged == 1, f"{bridged} host bridges for {n_vars} grads"
        for i, g in enumerate(grads):
            # Replicated virtual ranks: average == local value (2 * v).
            np.testing.assert_allclose(g.numpy(), 2.0 * (i + 1), rtol=1e-5)

    def test_grouped_allreduce_values_and_grad(self):
        xs = [tf.constant([1.0, 2.0]), tf.constant([[3.0]]),
              tf.constant([4.0, 5.0, 6.0])]
        outs = hvd_tf.grouped_allreduce(xs, average=False)
        for x, o in zip(xs, outs):
            np.testing.assert_allclose(o.numpy(), x.numpy() * hvd.size())
        # Differentiable through the group.
        v = tf.Variable([2.0, 3.0])
        with tf.GradientTape() as tape:
            out = hvd_tf.grouped_allreduce([v * v], average=True)[0]
            loss = tf.reduce_sum(out)
        g = tape.gradient(loss, v)
        np.testing.assert_allclose(g.numpy(), 2.0 * v.numpy(), rtol=1e-5)

    def test_grouped_allreduce_mixed_dtypes(self):
        outs = hvd_tf.grouped_allreduce(
            [tf.constant([1.0, 2.0]), tf.constant([3], tf.int32)],
            average=False)
        np.testing.assert_allclose(outs[0].numpy(),
                                   [hvd.size(), 2.0 * hvd.size()])
        assert outs[1].numpy().tolist() == [3 * hvd.size()]
        assert outs[1].dtype == tf.int32

    def test_v1_optimizer_compute_gradients_one_bridge(self):
        """The reference-shaped v1 wrapper (compute_gradients override,
        tensorflow/__init__.py:151-249): 21 variables cross in ONE
        bridged group, and the update applies. (A Keras-3 optimizer is
        not used here because other suite files pin the in-process Keras
        backend to torch; the Keras path is covered in
        tests/test_keras_tf.py's subprocess.)"""
        vs = [tf.Variable(tf.ones([2]) * (i + 1)) for i in range(21)]
        opt = hvd_tf.DistributedOptimizer(
            tf.compat.v1.train.GradientDescentOptimizer(0.1))

        def loss():
            return tf.add_n([tf.reduce_sum(v * v) for v in vs])

        before = hvd_tf._bridge_calls[0]
        gvs = opt.compute_gradients(loss, var_list=vs)
        assert hvd_tf._bridge_calls[0] - before == 1
        opt.apply_gradients(gvs)
        for i, v in enumerate(vs):
            # g = 2v -> v' = v - 0.1 * 2v = 0.8 * (i+1)
            np.testing.assert_allclose(v.numpy(), 0.8 * (i + 1),
                                       rtol=1e-5)


class TestSessionRunHook:
    def test_broadcast_hook_graph_mode(self):
        """SessionRunHook-shaped estimator integration
        (tensorflow/__init__.py:117-148): begin() builds the grouped
        assign over global variables; after_create_session runs it."""
        with tf.Graph().as_default():
            v1 = tf.compat.v1.get_variable(
                "hook_v1", initializer=tf.constant([1.0, 2.0]))
            v2 = tf.compat.v1.get_variable(
                "hook_v2", initializer=tf.constant(5.0))
            hook = hvd_tf.BroadcastGlobalVariablesHook(root_rank=0)
            hook.begin()
            assert hook.bcast_op is not None
            with tf.compat.v1.Session() as sess:
                sess.run(tf.compat.v1.global_variables_initializer())
                hook.after_create_session(sess, None)
                out1, out2 = sess.run([v1, v2])
        np.testing.assert_allclose(out1, [1.0, 2.0])
        np.testing.assert_allclose(out2, 5.0)
