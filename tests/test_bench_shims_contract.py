"""Fast-tier contract on BENCH_SHIMS.json (docs/benchmarks.md): the
recorded torch-shim rows must carry the hot-path evidence the ISSUE-9
acceptance reads — per-arm interop and bucket counters, and a
steady-state numpy_out of ZERO whenever the arm recorded DLPack egress
as available. The numbers themselves are re-measured by running
bench_shims.py; this test pins the schema and the invariants that must
hold for ANY honest run, so a regenerated file cannot silently drop
them."""

import json
import os

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PATH = os.path.join(ROOT, "BENCH_SHIMS.json")


@pytest.fixture(scope="module")
def bench():
    if not os.path.exists(PATH):
        pytest.skip("BENCH_SHIMS.json not generated on this checkout")
    with open(PATH) as f:
        return json.load(f)


def test_carried_vs_measured_split_is_pinned(bench):
    """Every row must be explicitly accounted for as re-measured or
    carried (the --arms mechanism): the top-level lists partition the
    rows, and the per-row carried_from_previous_run markers agree with
    them — a regenerated file cannot silently present stale rows as
    fresh measurements (the PR-9 chip rows are the case in point:
    measured on a TPU box, carried ever since on CPU-only re-runs)."""
    measured = set(bench["measured_arms"])
    carried = set(bench["carried_arms"])
    assert measured, "no arm was re-measured — arms list rot?"
    assert not (measured & carried)
    assert measured | carried == set(bench["rows"])
    for name, row in bench["rows"].items():
        if name in carried:
            assert row.get("carried_from_previous_run"), (
                f"{name} is listed carried but lacks the row marker")
        else:
            assert not row.get("carried_from_previous_run"), (
                f"{name} is listed re-measured but still carries the "
                "stale-row marker")


def test_chip_rows_are_marked_carried_on_this_box(bench):
    """The stale chip-backend rows (jax / keras_fit / bucketed ran on
    TPU metal) must be explicitly carried, never silently mixed with
    rows measured on this CPU-only container."""
    for name, row in bench["rows"].items():
        if row.get("backend") == "tpu":
            assert name in bench["carried_arms"], (
                f"{name} claims backend=tpu but is not marked carried "
                "— re-measure it on metal or carry it explicitly")


def test_retention_fields_present(bench):
    assert "torch_shim_retention_chip" in bench
    assert "torch_shim_retention_cpu" in bench
    assert bench["torch_shim_retention_chip"] > 0
    assert bench["torch_shim_retention_cpu"] > 0


@pytest.mark.parametrize("arm", ["torch_shim", "torch_shim_cpu"])
def test_torch_arms_record_hot_path_counters(bench, arm):
    row = bench["rows"][arm]
    assert row["interop_one_step"], f"{arm} recorded no interop split"
    assert row["buckets"] >= 1
    one = row["one_step"]
    for key in ("compile_misses", "compile_hits", "bucket_fires_hook",
                "bucket_fires_flush", "bucket_bytes"):
        assert key in one, (arm, key)
    fires = one["bucket_fires_hook"] + one["bucket_fires_flush"]
    assert fires == row["buckets"], (
        f"{arm}: {row['buckets']} buckets but {fires} fires in the "
        "steady-state step")


@pytest.mark.parametrize("arm", ["torch_shim", "torch_shim_cpu"])
def test_steady_state_numpy_out_zero_when_dlpack_available(bench, arm):
    """The acceptance invariant: with DLPack egress capability-probed
    present, the steady-state step moves every gradient through
    dlpack_in/dlpack_out — numpy carries nothing."""
    row = bench["rows"][arm]
    if not row.get("dlpack_available"):
        pytest.skip(f"{arm} ran without DLPack egress capability")
    s = row["interop_one_step"]
    assert s["numpy_out"] == 0, s
    assert s["numpy_in"] == 0, s
    assert s["dlpack_in"] == row["buckets"], s
    assert s["dlpack_out"] == row["buckets"], s


@pytest.mark.parametrize("arm", ["torch_shim", "torch_shim_cpu"])
def test_steady_state_reuses_bucket_programs(bench, arm):
    """Per-bucket persistent programs: a steady-state step compiles
    nothing and reuses at least one fused program per engine group."""
    one = bench["rows"][arm]["one_step"]
    assert one["compile_misses"] == 0, one
    assert one["compile_hits"] >= 1, one
