"""Parallelism-layer tests: ring attention exactness, pipeline schedule,
MoE dispatch, and full train-step parity of sharded vs single-device runs.

These strategies are extensions beyond the reference (SURVEY.md §2.1 lists
TP/PP/SP/EP as absent there); the test strategy mirrors the reference's op
tests — numeric equality against an unsharded oracle."""

import functools

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.parallel import create_mesh
from horovod_tpu.parallel.ring_attention import (full_attention,
                                                 ring_attention)
from horovod_tpu.parallel.pipeline import pipeline_apply
from horovod_tpu.parallel.expert import moe_apply
from horovod_tpu.parallel.train import build_train_step
from horovod_tpu.models import transformer as tfm


@functools.lru_cache(maxsize=None)
def _flash_in_shardmap_supported():
    """Capability probe: some XLA builds (e.g. this container's CPU
    jaxlib) reject the Pallas interpret-mode flash kernels under
    jit+shard_map over a full single axis with ``UNIMPLEMENTED:
    PartitionId instruction is not supported for SPMD partitioning``.
    That is a backend capability gap, not a ring-attention bug — probe
    once on a tiny instance and skip (instead of fail) where the
    backend cannot run the construct. Any OTHER failure still fails
    the tests."""
    mesh = create_mesh(sp=8)
    q = jnp.ones((1, 16, 1, 4), jnp.float32)
    f = jax.jit(jax.shard_map(
        lambda q, k, v: ring_attention(
            q, k, v, axis_name="sp", causal=False, use_flash=True,
            flash_block=2, flash_interpret=True),
        mesh=mesh, in_specs=(P(None, "sp"),) * 3,
        out_specs=P(None, "sp"), check_vma=False))
    try:
        f(q, q, q)
        return True
    except Exception as e:
        if "PartitionId" in str(e):
            return False
        raise


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_full_attention(self, causal):
        mesh = create_mesh(sp=8)
        B, S, H, D = 2, 64, 4, 16
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
        k = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
        v = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
        ref = full_attention(q, k, v, causal=causal)
        f = jax.jit(jax.shard_map(
            lambda q, k, v: ring_attention(q, k, v, axis_name="sp",
                                           causal=causal),
            mesh=mesh, in_specs=(P(None, "sp"),) * 3,
            out_specs=P(None, "sp"), check_vma=False))
        out = f(q, k, v)
        assert float(jnp.max(jnp.abs(out - ref))) < 2e-5

    def test_grad_flows_through_ring(self):
        """Backward through ppermute routes cross-shard cotangents."""
        mesh = create_mesh(sp=4, dp=2)
        B, S, H, D = 2, 32, 2, 8
        rng = np.random.RandomState(1)
        q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
        k = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
        v = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)

        def loss_ring(q, k, v):
            def shard(q, k, v):
                out = ring_attention(q, k, v, axis_name="sp", causal=True)
                l = (out.astype(jnp.float32) ** 2).sum()
                return lax.psum(l, ("sp", "dp"))
            return jax.shard_map(
                shard, mesh=mesh,
                in_specs=(P("dp", "sp"),) * 3, out_specs=P(),
                check_vma=False)(q, k, v)

        def loss_full(q, k, v):
            out = full_attention(q, k, v, causal=True)
            return (out.astype(jnp.float32) ** 2).sum()

        g1 = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            assert float(jnp.max(jnp.abs(np.asarray(a) - np.asarray(b)))) \
                < 1e-3

    @pytest.mark.parametrize("causal", [True, False])
    def test_ring_flash_matches_full(self, causal):
        """Flash inner op (per-shard-pair Pallas kernels + logaddexp
        merge) against the unsharded oracle."""
        if not _flash_in_shardmap_supported():
            pytest.skip("backend lacks PartitionId under SPMD "
                        "partitioning (flash interpret in shard_map)")
        mesh = create_mesh(sp=8)
        B, S, H, D = 2, 64, 4, 16
        rng = np.random.RandomState(2)
        q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
        k = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
        v = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
        ref = full_attention(q, k, v, causal=causal)
        f = jax.jit(jax.shard_map(
            lambda q, k, v: ring_attention(
                q, k, v, axis_name="sp", causal=causal, use_flash=True,
                flash_interpret=True),
            mesh=mesh, in_specs=(P(None, "sp"),) * 3,
            out_specs=P(None, "sp"), check_vma=False))
        out = f(q, k, v)
        assert float(jnp.max(jnp.abs(out - ref))) < 2e-5

    def test_ring_flash_grads(self):
        """The custom ring backward (traveling dK/dV accumulators +
        global-lse per-block flash backward) against unsharded autodiff;
        shard 6 with block 4 also exercises the kernels' tail-block
        masked branch through the ring path."""
        mesh = create_mesh(sp=4, dp=2)
        B, S, H, D = 2, 24, 2, 8
        rng = np.random.RandomState(3)
        q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
        k = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
        v = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)

        def loss_ring(q, k, v):
            def shard(q, k, v):
                out = ring_attention(q, k, v, axis_name="sp", causal=True,
                                     use_flash=True, flash_block=4,
                                     flash_interpret=True)
                l = (out.astype(jnp.float32) ** 2).sum()
                return lax.psum(l, ("sp", "dp"))
            return jax.shard_map(
                shard, mesh=mesh,
                in_specs=(P("dp", "sp"),) * 3, out_specs=P(),
                check_vma=False)(q, k, v)

        def loss_full(q, k, v):
            out = full_attention(q, k, v, causal=True)
            return (out.astype(jnp.float32) ** 2).sum()

        g1 = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            assert float(jnp.max(jnp.abs(np.asarray(a) - np.asarray(b)))) \
                < 1e-3


class TestPipeline:
    def test_four_stage_product(self):
        mesh = create_mesh(pp=4, dp=2)
        scales = jnp.arange(1.0, 5.0)[:, None]
        xs = jnp.ones((3, 2, 8))

        def stage_fn(p, x):
            return x * p["scale"]

        def run(scale_local, x):
            return pipeline_apply(stage_fn, {"scale": scale_local[0]}, x,
                                  axis_name="pp")

        f = jax.jit(jax.shard_map(
            run, mesh=mesh, in_specs=(P("pp"), P(None, "dp")),
            out_specs=P(None, "dp"), check_vma=False))
        out = f(scales, xs)
        assert np.allclose(np.asarray(out), 24.0)  # 1*2*3*4

    def test_microbatch_identity_order(self):
        """Outputs keep microbatch order through the skewed schedule."""
        mesh = create_mesh(pp=4, dp=2)
        xs = jnp.arange(4 * 2 * 4, dtype=jnp.float32).reshape(4, 2, 4)

        def stage_fn(p, x):
            return x + p["b"]

        ones = jnp.ones((4, 1))

        def run(b_local, x):
            return pipeline_apply(stage_fn, {"b": b_local[0]}, x,
                                  axis_name="pp")

        f = jax.jit(jax.shard_map(
            run, mesh=mesh, in_specs=(P("pp"), P(None, "dp")),
            out_specs=P(None, "dp"), check_vma=False))
        out = f(ones, xs)
        assert np.allclose(np.asarray(out), np.asarray(xs) + 4.0)


class TestMoE:
    def test_matches_dense_with_ample_capacity(self):
        mesh = create_mesh(ep=8)
        F, H, E = 16, 32, 8
        rng = jax.random.PRNGKey(0)
        x = jax.random.normal(jax.random.PRNGKey(1), (64, F), jnp.float32)
        pw = {
            "router": jax.random.normal(rng, (F, E)) * 0.25,
            "wi": jax.random.normal(rng, (E, F, H)) * 0.1,
            "wo": jax.random.normal(rng, (E, H, F)) * 0.1,
        }

        def run_moe(p, xl):
            return moe_apply(p, xl, num_experts=E, capacity_factor=8.0,
                             axis_name="ep", act=jax.nn.gelu,
                             dtype=jnp.float32)

        f = jax.jit(jax.shard_map(
            run_moe, mesh=mesh,
            in_specs=({"router": P(), "wi": P("ep"), "wo": P("ep")},
                      P("ep")),
            out_specs=P("ep"), check_vma=False))
        out = f(pw, x)

        logits = x @ pw["router"]
        e = jnp.argmax(logits, -1)
        gate = jax.nn.softmax(logits, -1)
        g = jnp.take_along_axis(gate, e[:, None], 1)[:, 0]
        wi = np.asarray(pw["wi"])
        wo = np.asarray(pw["wo"])
        ref = jnp.stack([
            (jax.nn.gelu(x[i] @ wi[int(e[i])]) @ wo[int(e[i])]) * g[i]
            for i in range(64)])
        assert float(jnp.max(jnp.abs(out - ref))) < 1e-5


class TestTrainStepParity:
    """The flagship guarantee: a sharded multi-axis training step equals
    the single-device step bit-for-bit (up to fp reassociation)."""

    def _run(self, cfg, mesh, params, tok, tgt, opt):
        make, shard_p, shard_b = build_train_step(cfg, mesh, opt)
        state = opt.init(params)
        step, _ = make(params, state)
        p, _, loss = step(shard_p(params), state, shard_b(tok),
                          shard_b(tgt))
        leaves = [np.asarray(x, np.float32)
                  for x in jax.tree_util.tree_leaves(p)]
        return leaves, float(loss)

    def test_chunked_loss_matches_monolithic(self):
        """loss_chunk computes the identical loss AND gradients as the
        monolithic [B,S,V] path (it only changes memory layout), and
        logits_bf16 stays within bf16 rounding of the fp32 projection."""
        rng = jax.random.PRNGKey(0)
        cfg = tfm.TransformerConfig(
            vocab=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
            max_seq=32, dtype=jnp.float32, remat=False)
        params = tfm.init_params(cfg, rng)
        tok = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 64)
        tgt = jnp.roll(tok, -1, axis=1)

        def loss_with(**over):
            kw = dict(vocab=64, d_model=32, n_heads=2, n_layers=2,
                      d_ff=64, max_seq=32, dtype=jnp.float32, remat=False)
            kw.update(over)
            c = tfm.TransformerConfig(**kw)
            return jax.value_and_grad(
                lambda p: tfm.loss_fn(p, tok, tgt, c))(params)

        l0, g0 = loss_with()
        l1, g1 = loss_with(loss_chunk=8)
        # Chunking reassociates the fp32 mean; at loss ~21 one ulp is
        # ~1.9e-6, and a legitimate accumulation-order delta of exactly
        # that size was observed. Allow a few ulps, not bitwise equality.
        assert abs(float(l0) - float(l1)) < 5e-6
        err = max(float(jnp.max(jnp.abs(a - b))) for a, b in zip(
            jax.tree_util.tree_leaves(g0), jax.tree_util.tree_leaves(g1)))
        assert err < 1e-5, f"chunked-loss grad divergence {err}"
        # remat_policy="dots" changes memory, never values.
        ld, gd = loss_with(remat=True, remat_policy="dots")
        assert abs(float(l0) - float(ld)) < 5e-6
        errd = max(float(jnp.max(jnp.abs(a - b))) for a, b in zip(
            jax.tree_util.tree_leaves(g0), jax.tree_util.tree_leaves(gd)))
        assert errd < 1e-5, f"dots-policy grad divergence {errd}"
        # chunk must divide the sequence
        import pytest as _pytest
        with _pytest.raises(ValueError):
            loss_with(loss_chunk=7)
        # bf16 projection: same loss within rounding
        l2, _ = loss_with(logits_bf16=True, dtype=jnp.bfloat16)
        assert abs(float(l0) - float(l2)) < 0.1

    def test_dense_dp_tp_sp(self):
        rng = jax.random.PRNGKey(0)
        tok = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 64)
        tgt = jnp.roll(tok, -1, axis=1)
        opt = optax.sgd(0.1)
        cfg = tfm.TransformerConfig(
            vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq=32, dtype=jnp.float32, tp_axis="tp", sp_axis="sp",
            remat=True)
        params = tfm.init_params(cfg, rng)
        l1, loss1 = self._run(cfg, create_mesh(dp=2, tp=2, sp=2), params,
                              tok, tgt, opt)
        l2, loss2 = self._run(
            cfg, create_mesh(devices=jax.devices()[:1], dp=1, tp=1, sp=1),
            params, tok, tgt, opt)
        assert abs(loss1 - loss2) < 1e-5
        err = max(np.max(np.abs(a - b)) for a, b in zip(l1, l2))
        assert err < 1e-4, f"param divergence {err}"

    def test_moe_dp_ep(self):
        rng = jax.random.PRNGKey(0)
        tok = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 64)
        tgt = jnp.roll(tok, -1, axis=1)
        opt = optax.adam(1e-2)
        cfg = tfm.TransformerConfig(
            vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq=32, dtype=jnp.float32, ep_axis="ep", num_experts=4,
            capacity_factor=8.0, remat=False)
        params = tfm.init_params(cfg, rng)
        l1, loss1 = self._run(cfg, create_mesh(dp=2, ep=4), params, tok,
                              tgt, opt)
        l2, loss2 = self._run(
            cfg, create_mesh(devices=jax.devices()[:1], dp=1, ep=1),
            params, tok, tgt, opt)
        assert abs(loss1 - loss2) < 1e-4
        err = max(np.max(np.abs(a - b)) for a, b in zip(l1, l2))
        assert err < 1e-3, f"param divergence {err}"

    def test_loss_decreases_over_steps(self):
        rng = jax.random.PRNGKey(0)
        tok = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 64)
        tgt = jnp.roll(tok, -1, axis=1)
        opt = optax.adam(1e-2)
        cfg = tfm.TransformerConfig(
            vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq=32, dtype=jnp.float32, tp_axis="tp", sp_axis="sp")
        params = tfm.init_params(cfg, rng)
        mesh = create_mesh(dp=2, tp=2, sp=2)
        make, shard_p, shard_b = build_train_step(cfg, mesh, opt)
        state = opt.init(params)
        step, _ = make(params, state)
        p, s = shard_p(params), state
        tk, tg = shard_b(tok), shard_b(tgt)
        losses = []
        for _ in range(5):
            p, s, loss = step(p, s, tk, tg)
            losses.append(float(loss))
        assert losses[-1] < losses[0], losses


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_full_attention(self, causal):
        from horovod_tpu.parallel.ulysses import ulysses_attention
        mesh = create_mesh(sp=8)
        B, S, H, D = 2, 64, 8, 16     # H == sp size: 1 head per shard
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
        k = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
        v = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
        ref = full_attention(q, k, v, causal=causal)
        f = jax.jit(jax.shard_map(
            lambda q, k, v: ulysses_attention(q, k, v, axis_name="sp",
                                              causal=causal),
            mesh=mesh, in_specs=(P(None, "sp"),) * 3,
            out_specs=P(None, "sp"), check_vma=False))
        out = f(q, k, v)
        assert float(jnp.max(jnp.abs(out - ref))) < 2e-5

    def test_multiple_heads_per_shard(self):
        from horovod_tpu.parallel.ulysses import ulysses_attention
        mesh = create_mesh(sp=4, dp=2)
        B, S, H, D = 2, 32, 8, 8      # 2 heads per sp shard, dp batch
        rng = np.random.RandomState(1)
        q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
        k = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
        v = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
        ref = full_attention(q, k, v, causal=True)
        f = jax.jit(jax.shard_map(
            lambda q, k, v: ulysses_attention(q, k, v, axis_name="sp"),
            mesh=mesh, in_specs=(P("dp", "sp"),) * 3,
            out_specs=P("dp", "sp"), check_vma=False))
        out = f(q, k, v)
        assert float(jnp.max(jnp.abs(out - ref))) < 2e-5

    def test_grad_matches_full(self):
        from horovod_tpu.parallel.ulysses import ulysses_attention
        mesh = create_mesh(sp=4, dp=2)
        B, S, H, D = 1, 32, 4, 8
        rng = np.random.RandomState(2)
        q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
        k = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
        v = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)

        def loss_uly(q, k, v):
            def shard(q, k, v):
                out = ulysses_attention(q, k, v, axis_name="sp")
                return lax.psum((out.astype(jnp.float32) ** 2).sum(), "sp")
            return jax.shard_map(
                shard, mesh=mesh, in_specs=(P(None, "sp"),) * 3,
                out_specs=P(), check_vma=False)(q, k, v)

        def loss_full(q, k, v):
            out = full_attention(q, k, v, causal=True)
            return (out.astype(jnp.float32) ** 2).sum()

        g1 = jax.grad(loss_uly, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            assert float(jnp.max(jnp.abs(np.asarray(a) - np.asarray(b)))) \
                < 1e-3

    def test_head_divisibility_error(self):
        from horovod_tpu.parallel.ulysses import ulysses_attention
        mesh = create_mesh(sp=8)
        B, S, H, D = 1, 16, 4, 8      # 4 heads, 8 shards -> error
        q = jnp.ones((B, S, H, D), jnp.float32)
        with pytest.raises(ValueError, match="divisible"):
            jax.jit(jax.shard_map(
                lambda q: ulysses_attention(q, q, q, axis_name="sp"),
                mesh=mesh, in_specs=(P(None, "sp"),),
                out_specs=P(None, "sp"), check_vma=False))(q)

    def test_transformer_sp_impl_ulysses(self):
        """Flagship transformer trains a step with sp_impl='ulysses'."""
        import optax
        mesh = create_mesh(dp=2, sp=4)
        cfg = tfm.TransformerConfig(
            vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq=32, dtype=jnp.float32, sp_axis="sp",
            sp_impl="ulysses", remat=False)
        rng = jax.random.PRNGKey(0)
        params = tfm.init_params(cfg, rng)
        tokens = jax.random.randint(rng, (4, 32), 0, 64)
        targets = jnp.roll(tokens, -1, axis=1)
        opt = optax.adam(1e-3)
        make, shard_p, shard_b = build_train_step(cfg, mesh, opt)
        state = opt.init(params)
        step, _ = make(params, state)
        _, _, loss = step(shard_p(params), state, shard_b(tokens),
                          shard_b(targets))
        assert np.isfinite(float(loss))


class TestZero1:
    """ZeRO-1 optimizer-state sharding (parallel/zero.py): the sharded-
    state step must match the replicated-state step numerically, with
    every moment leaf stored as a 1/dp flat shard over 'dp'."""

    def _setup(self, opt):
        rng = jax.random.PRNGKey(0)
        tok = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, 64)
        tgt = jnp.roll(tok, -1, axis=1)
        cfg = tfm.TransformerConfig(
            vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq=32, dtype=jnp.float32, remat=False)
        params = tfm.init_params(cfg, rng)
        return cfg, params, tok, tgt

    def _train(self, cfg, mesh, params, tok, tgt, opt, state, steps=4):
        make, shard_p, shard_b = build_train_step(cfg, mesh, opt)
        step, _ = make(params, state)
        p, s = shard_p(params), state
        tk, tg = shard_b(tok), shard_b(tgt)
        losses = []
        for _ in range(steps):
            p, s, loss = step(p, s, tk, tg)
            losses.append(float(loss))
        leaves = [np.asarray(x, np.float32)
                  for x in jax.tree_util.tree_leaves(p)]
        return leaves, losses, s

    def test_matches_replicated_state_adamw(self):
        from horovod_tpu.parallel.zero import zero1_init
        opt = optax.adamw(1e-2)
        cfg, params, tok, tgt = self._setup(opt)
        mesh = create_mesh(dp=8)
        l_ref, losses_ref, _ = self._train(
            cfg, mesh, params, tok, tgt, opt, opt.init(params))
        zstate = zero1_init(opt, params, n_shards=8)
        l_z, losses_z, _ = self._train(
            cfg, mesh, params, tok, tgt, opt, zstate)
        np.testing.assert_allclose(losses_z, losses_ref, rtol=1e-5)
        err = max(np.max(np.abs(a - b)) for a, b in zip(l_z, l_ref))
        assert err < 1e-5, f"param divergence {err}"

    def test_moments_sharded_one_over_dp(self):
        from horovod_tpu.parallel.zero import zero1_init
        opt = optax.adam(1e-2)
        cfg, params, tok, tgt = self._setup(opt)
        mesh = create_mesh(dp=8)
        zstate = zero1_init(opt, params, n_shards=8)
        make, shard_p, shard_b = build_train_step(cfg, mesh, opt)
        step, opt_specs = make(params, zstate)
        p, s, _ = step(shard_p(params), zstate, shard_b(tok),
                       shard_b(tgt))
        import jax as _jax
        from jax.sharding import PartitionSpec as P
        # Every vector moment leaf: sharded over dp, local shard = 1/8.
        checked = 0
        for leaf in _jax.tree_util.tree_leaves(s):
            if getattr(leaf, "ndim", 0) >= 1 and leaf.size >= 8:
                assert len(leaf.sharding.device_set) == 8
                shard = leaf.addressable_shards[0].data
                assert shard.size == leaf.size // 8
                checked += 1
        assert checked >= 4  # adam mu+nu over several params

    def test_zero_with_tp_combination(self):
        """The model-axis interaction: a tp-sharded parameter's moments
        must live as per-tp-block flat shards further split over dp —
        AdamW (stateful) so a layout bug cannot hide in an empty state."""
        from horovod_tpu.parallel.zero import zero1_init
        opt = optax.adamw(1e-2)
        rng = jax.random.PRNGKey(0)
        tok = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 64)
        tgt = jnp.roll(tok, -1, axis=1)
        cfg = tfm.TransformerConfig(
            vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq=32, dtype=jnp.float32, tp_axis="tp", remat=False)
        params = tfm.init_params(cfg, rng)
        mesh = create_mesh(dp=4, tp=2)
        zstate = zero1_init(opt, params, n_shards=4,
                            param_specs=tfm.param_specs(cfg), mesh=mesh)
        l_z, losses_z, _ = self._train(cfg, mesh, params, tok, tgt, opt,
                                       zstate)
        l_ref, losses_ref, _ = self._train(cfg, mesh, params, tok, tgt,
                                           opt, opt.init(params))
        np.testing.assert_allclose(losses_z, losses_ref, rtol=1e-5)
        err = max(np.max(np.abs(a - b)) for a, b in zip(l_z, l_ref))
        assert err < 1e-5, f"param divergence {err}"

    def test_requires_dp_axis(self):
        from horovod_tpu.parallel.zero import zero1_init
        opt = optax.sgd(0.1)
        cfg, params, tok, tgt = self._setup(opt)
        mesh = create_mesh(devices=jax.devices()[:2], tp=2)
        cfg2 = tfm.TransformerConfig(
            vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq=32, dtype=jnp.float32, tp_axis="tp", remat=False)
        make, _, _ = build_train_step(cfg2, mesh, opt)
        with pytest.raises(ValueError, match="dp"):
            make(params, zero1_init(opt, params, n_shards=2))

    def test_n_shards_recorded_and_validated(self):
        """ADVICE low: a Zero1State built for one shard count must be
        rejected by make() against a mesh whose 'dp' axis differs — a
        clear ValueError naming both numbers, not an opaque jit
        sharding failure from mismatched flat-shard padding."""
        from horovod_tpu.parallel.zero import zero1_init
        opt = optax.adam(1e-2)
        cfg, params, tok, tgt = self._setup(opt)
        zstate = zero1_init(opt, params, n_shards=4)
        assert int(zstate.n_shards) == 4
        mesh = create_mesh(dp=8)
        make, _, _ = build_train_step(cfg, mesh, opt)
        with pytest.raises(ValueError,
                           match=r"n_shards=4.*'dp' axis has 8"):
            make(params, zstate)
        # The matching count passes validation and still trains.
        good = zero1_init(opt, params, n_shards=8)
        l_z, losses, s = self._train(cfg, mesh, params, tok, tgt, opt,
                                     good, steps=1)
        assert np.isfinite(losses[0])
        # n_shards survives the jitted step round-trip.
        assert int(np.asarray(s.n_shards)) == 8
