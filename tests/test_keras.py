"""Keras shim tests — structural mirror of the reference's test_keras.py
(246 LoC, 5 tests) + test_tensorflow_keras.py, targeting Keras 3 on the
torch backend (eager, so the collective path is exercised directly; the
tf.function and jitted-jax paths have their own tests below/elsewhere).
"""

import os

os.environ.setdefault("KERAS_BACKEND", "torch")

import numpy as np
import pytest

keras = pytest.importorskip("keras")

import horovod_tpu as hvd
import horovod_tpu.keras as hvd_keras


@pytest.fixture(autouse=True)
def _init():
    hvd.init()
    yield


def _model():
    keras.utils.set_random_seed(0)
    return keras.Sequential([
        keras.layers.Input((8,)),
        keras.layers.Dense(16, activation="relu"),
        keras.layers.Dense(2),
    ])


class TestDistributedOptimizer:
    def test_wraps_and_preserves_class_name(self):
        opt = hvd_keras.DistributedOptimizer(keras.optimizers.SGD(0.01))
        assert opt.__class__.__name__ == "SGD"
        assert isinstance(opt, keras.optimizers.SGD)
        assert opt._hvd_wrapped

    def test_fit_end_to_end(self):
        model = _model()
        opt = hvd_keras.DistributedOptimizer(
            keras.optimizers.SGD(learning_rate=0.1))
        model.compile(optimizer=opt, loss="mse", jit_compile=False)
        x = np.random.rand(16, 8).astype("float32")
        y = np.random.rand(16, 2).astype("float32")
        before = [np.array(w) for w in model.get_weights()]
        model.fit(x, y, batch_size=8, epochs=1, verbose=0)
        after = model.get_weights()
        assert any(not np.allclose(b, a) for b, a in zip(before, after))

    def test_gradients_are_averaged(self):
        # With identical virtual ranks, the averaged gradient equals the
        # local gradient — so a wrapped and an unwrapped optimizer must
        # take identical steps.
        x = np.random.rand(16, 8).astype("float32")
        y = np.random.rand(16, 2).astype("float32")

        def run(wrap):
            model = _model()
            opt = keras.optimizers.SGD(learning_rate=0.1)
            if wrap:
                opt = hvd_keras.DistributedOptimizer(opt)
            model.compile(optimizer=opt, loss="mse", jit_compile=False)
            model.fit(x, y, batch_size=16, epochs=1, shuffle=False,
                      verbose=0)
            return model.get_weights()

        for w_ref, w_hvd in zip(run(False), run(True)):
            assert np.allclose(w_ref, w_hvd, rtol=1e-4, atol=1e-5)

    def test_compression_fp16(self):
        model = _model()
        opt = hvd_keras.DistributedOptimizer(
            keras.optimizers.SGD(0.1), compression=hvd_keras.Compression.fp16)
        model.compile(optimizer=opt, loss="mse", jit_compile=False)
        x = np.random.rand(8, 8).astype("float32")
        y = np.random.rand(8, 2).astype("float32")
        model.fit(x, y, batch_size=8, epochs=1, verbose=0)


class TestHostCollectives:
    def test_allreduce_scalar(self):
        out = hvd_keras.allreduce(3.0, average=False, name="k.ar")
        assert out == pytest.approx(3.0 * hvd.size())

    def test_allgather(self):
        out = hvd_keras.allgather(np.array([1.0, 2.0], np.float32),
                                  name="k.ag")
        assert out.shape == (2 * hvd.size(),)

    def test_broadcast(self):
        out = hvd_keras.broadcast(np.arange(4.0, dtype=np.float32),
                                  root_rank=0, name="k.bc")
        assert np.allclose(out, np.arange(4.0))

    def test_allreduce_python_list(self):
        out = hvd_keras.allreduce([1.0, 2.0], average=True, name="k.arl")
        assert np.allclose(out, [1.0, 2.0])


class TestBroadcastVariables:
    def test_broadcast_variables_roundtrip(self):
        model = _model()
        before = [np.array(w) for w in model.get_weights()]
        hvd_keras.broadcast_variables(model.variables, root_rank=0)
        for b, a in zip(before, model.get_weights()):
            assert np.allclose(b, a)


class TestCallbacks:
    def test_broadcast_callback_fit(self):
        model = _model()
        model.compile(optimizer=hvd_keras.DistributedOptimizer(
            keras.optimizers.SGD(0.1)), loss="mse", jit_compile=False)
        cb = hvd_keras.callbacks.BroadcastGlobalVariablesCallback(0)
        x = np.random.rand(8, 8).astype("float32")
        y = np.random.rand(8, 2).astype("float32")
        model.fit(x, y, batch_size=4, epochs=1, callbacks=[cb], verbose=0)
        assert cb._model_done and cb._opt_done

    def test_metric_average_callback(self):
        cb = hvd_keras.callbacks.MetricAverageCallback()
        logs = {"loss": 2.5, "acc": 0.5}
        cb._average_metrics_in_place(logs)
        # identical virtual ranks → average == local value
        assert logs["loss"] == pytest.approx(2.5, rel=1e-5)
        assert logs["acc"] == pytest.approx(0.5, rel=1e-5)

    def test_lr_schedule_staircase(self):
        model = _model()
        model.compile(optimizer=keras.optimizers.SGD(
            learning_rate=0.1, momentum=0.9), loss="mse", jit_compile=False)
        cb = hvd_keras.callbacks.LearningRateScheduleCallback(
            multiplier=lambda epoch: 10 ** -epoch, start_epoch=0)
        x = np.random.rand(8, 8).astype("float32")
        y = np.random.rand(8, 2).astype("float32")
        hist = model.fit(x, y, batch_size=8, epochs=3, callbacks=[cb],
                         verbose=0)
        lrs = hist.history["lr"]
        assert lrs[0] == pytest.approx(0.1, rel=1e-5)
        assert lrs[1] == pytest.approx(0.01, rel=1e-5)
        assert lrs[2] == pytest.approx(0.001, rel=1e-5)
        # momentum restored after correction batches
        assert float(model.optimizer.momentum) == pytest.approx(0.9)

    def test_checkpoint_callback_commits_epochs(self, tmp_path):
        """CheckpointCallback hands weights to the sharded engine every
        N epochs; commits are atomic manifests and restore round-trips
        into model.set_weights."""
        from horovod_tpu.checkpoint import list_steps

        model = _model()
        model.compile(optimizer=keras.optimizers.SGD(0.1), loss="mse",
                      jit_compile=False)
        cb = hvd_keras.callbacks.CheckpointCallback(
            str(tmp_path / "kck"), every_epochs=2)
        x = np.random.rand(16, 8).astype("float32")
        y = np.random.rand(16, 2).astype("float32")
        model.fit(x, y, batch_size=8, epochs=4, callbacks=[cb], verbose=0)
        assert list_steps(str(tmp_path / "kck")) == [2, 4]
        weights = cb.engine.restore(
            template=list(model.get_weights()))
        for got, want in zip(weights, model.get_weights()):
            np.testing.assert_allclose(got, np.asarray(want), rtol=1e-6)
        with pytest.raises(ValueError, match="exactly one"):
            hvd_keras.callbacks.CheckpointCallback()

    def test_lr_warmup_reaches_initial(self):
        model = _model()
        model.compile(optimizer=keras.optimizers.SGD(learning_rate=0.8),
                      loss="mse", jit_compile=False)
        cb = hvd_keras.callbacks.LearningRateWarmupCallback(
            warmup_epochs=2, verbose=0)
        x = np.random.rand(16, 8).astype("float32")
        y = np.random.rand(16, 2).astype("float32")
        hist = model.fit(x, y, batch_size=4, epochs=3, callbacks=[cb],
                         verbose=0)
        # warmup starts near initial_lr/size and ends at initial_lr
        assert hist.history["lr"][0] < 0.8
        assert hist.history["lr"][-1] == pytest.approx(0.8, rel=1e-3)


class TestLoadModel:
    def test_load_model_rewraps_optimizer(self, tmp_path):
        model = _model()
        model.compile(optimizer=hvd_keras.DistributedOptimizer(
            keras.optimizers.SGD(0.05)), loss="mse", jit_compile=False)
        x = np.random.rand(8, 8).astype("float32")
        y = np.random.rand(8, 2).astype("float32")
        model.fit(x, y, batch_size=8, epochs=1, verbose=0)
        path = str(tmp_path / "model.keras")
        model.save(path)
        loaded = hvd_keras.load_model(path)
        assert getattr(loaded.optimizer, "_hvd_wrapped", False) or \
            loaded.optimizer.__class__.__name__ == "SGD"
        loaded.fit(x, y, batch_size=8, epochs=1, verbose=0)
