"""Autotuner tests — the parameter manager + Bayesian optimization stack
(reference parameter_manager.{h,cc} N5, optim/ N6). The GP/EI math runs in
the native core; here we check the end-to-end behavior: with
HOROVOD_AUTOTUNE=1 the runtime explores (fusion MB, cycle ms) points,
logs score samples to HOROVOD_AUTOTUNE_LOG, and keeps running correctly."""

import os
import subprocess
import sys

SCRIPT = r"""
import os, time
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
import horovod_tpu as hvd
from horovod_tpu.ops import collective

hvd.init()
x = jnp.ones((64, 64))
# Feed traffic across many cycles so the tuner collects samples
# (10 cycles/sample, 3 warmup, 5 samples/step — parameter_manager.cc:28-29).
for i in range(120):
    out = hvd.allreduce(x, average=False, name=f"tune.{i}")
    assert np.allclose(np.asarray(out), 8.0)
time.sleep(0.3)
core = collective.engine()._native_core
assert core is not None, "native core required for autotune test"
print("AUTOTUNE_ACTIVE", core.autotune_active())
print("FUSION", core.fusion_threshold, "CYCLE", core.cycle_time_ms)
collective.engine().shutdown()
"""


def test_autotune_explores_and_logs(tmp_path):
    log = tmp_path / "autotune.csv"
    env = dict(os.environ)
    env["HOROVOD_AUTOTUNE"] = "1"
    env["HOROVOD_AUTOTUNE_LOG"] = str(log)
    env["HOROVOD_CYCLE_TIME"] = "1"
    proc = subprocess.run([sys.executable, "-c", SCRIPT], env=env,
                          capture_output=True, text=True, timeout=300,
                          cwd=os.path.dirname(os.path.dirname(__file__)))
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert log.exists()
    lines = log.read_text().strip().splitlines()
    # Header + at least one score sample line.
    assert lines[0] == "fusion_mb,cycle_ms,hierarchical,score"
    assert len(lines) >= 2, proc.stdout + proc.stderr[-500:]
    # Sample lines are fusion_mb,cycle_ms,hier,score CSV.
    parts = lines[1].split(",")
    assert len(parts) == 4
    assert 0.0 <= float(parts[0]) <= 64.0
    assert 1.0 <= float(parts[1]) <= 100.0
