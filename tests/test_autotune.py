"""Autotuner tests — the parameter manager + Bayesian optimization stack
(reference parameter_manager.{h,cc} N5, optim/ N6). The GP/EI math runs in
the native core; here we check the end-to-end behavior: with
HOROVOD_AUTOTUNE=1 the runtime explores (fusion MB, cycle ms) points,
logs score samples to HOROVOD_AUTOTUNE_LOG, and keeps running correctly."""

import os
import subprocess
import sys

import pytest

SCRIPT = r"""
import os, time
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
import horovod_tpu as hvd
from horovod_tpu.ops import collective

hvd.init()
x = jnp.ones((64, 64))
# Feed traffic across many cycles so the tuner collects samples
# (10 cycles/sample, 3 warmup, 5 samples/step — parameter_manager.cc:28-29).
for i in range(120):
    out = hvd.allreduce(x, average=False, name=f"tune.{i}")
    assert np.allclose(np.asarray(out), 8.0)
time.sleep(0.3)
core = collective.engine()._native_core
assert core is not None, "native core required for autotune test"
print("AUTOTUNE_ACTIVE", core.autotune_active())
print("FUSION", core.fusion_threshold, "CYCLE", core.cycle_time_ms)
collective.engine().shutdown()
"""


CONVERGE_SCRIPT = r"""
import os, time, json
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
import horovod_tpu as hvd
from horovod_tpu.ops import collective

hvd.init()
x = jnp.ones((256, 256))
hvd.allreduce(x, average=False, name="cv.prime")  # attaches the native core
core = collective.engine()._native_core
assert core is not None, "native core required for autotune test"
# Keep traffic flowing until the tuner converges and freezes
# (kMaxSteps * kSamplesPerStep * kCyclesPerSample + warmups cycles at a
# 1 ms cycle): scores must be nonzero so freeze-to-best is meaningful.
deadline = time.monotonic() + 120
i = 0
while not core.autotune_done() and time.monotonic() < deadline:
    out = hvd.allreduce(x, average=False, name=f"cv.{i}")
    i += 1
out = hvd.allreduce(x, average=False, name="cv.final")
assert np.allclose(np.asarray(out), 8.0)
flags = core.current_flags()
ex = collective.engine().executor
print(json.dumps({
    "done": core.autotune_done(),
    "fusion_mb": core.fusion_threshold / (1024.0 * 1024.0),
    "cycle_ms": core.cycle_time_ms,
    "steps": i,
    "flag_hier_ar": bool(flags & 1),
    "flag_hier_ag": bool(flags & 2),
    "ex_hier_ar": bool(ex.hierarchical_allreduce),
    "ex_hier_ag": bool(ex.hierarchical_allgather),
}))
collective.engine().shutdown()
"""


def test_autotune_explores_and_logs(tmp_path):
    log = tmp_path / "autotune.csv"
    env = dict(os.environ)
    env["HOROVOD_AUTOTUNE"] = "1"
    env["HOROVOD_AUTOTUNE_LOG"] = str(log)
    env["HOROVOD_CYCLE_TIME"] = "1"
    proc = subprocess.run([sys.executable, "-c", SCRIPT], env=env,
                          capture_output=True, text=True, timeout=300,
                          cwd=os.path.dirname(os.path.dirname(__file__)))
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert log.exists()
    lines = log.read_text().strip().splitlines()
    # Header + at least one score sample line.
    assert lines[0] == ("fusion_mb,cycle_ms,hier_allreduce,"
                        "hier_allgather,score")
    assert len(lines) >= 2, proc.stdout + proc.stderr[-500:]
    # Sample lines are fusion_mb,cycle_ms,hier_ar,hier_ag,score CSV.
    parts = lines[1].split(",")
    assert len(parts) == 5
    assert 0.0 <= float(parts[0]) <= 64.0
    assert 1.0 <= float(parts[1]) <= 100.0


@pytest.mark.slow
def test_autotune_convergence_quality(tmp_path):
    """VERDICT r1 #9: BO must explore >= 3 distinct points, converge,
    freeze to the best-scoring sampled point (parameter_manager.cc:
    173-209), and the frozen knobs must be applied to the live engine."""
    log = tmp_path / "autotune.csv"
    env = dict(os.environ)
    env["HOROVOD_AUTOTUNE"] = "1"
    env["HOROVOD_AUTOTUNE_LOG"] = str(log)
    env["HOROVOD_CYCLE_TIME"] = "1"
    proc = subprocess.run([sys.executable, "-c", CONVERGE_SCRIPT], env=env,
                          capture_output=True, text=True, timeout=300,
                          cwd=os.path.dirname(os.path.dirname(__file__)))
    assert proc.returncode == 0, proc.stderr[-2000:]
    import json
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["done"], f"tuner did not converge: {out}"

    lines = log.read_text().strip().splitlines()
    assert lines[0] == ("fusion_mb,cycle_ms,hier_allreduce,"
                        "hier_allgather,score")
    rows = [tuple(float(v) for v in ln.split(",")) for ln in lines[1:]]
    # Exploration: >= 3 distinct (fusion, cycle) points, not an RNG's
    # single default.
    points = {(r[0], r[1]) for r in rows}
    assert len(points) >= 3, points
    # BOTH categoricals explored (parameter_manager.cc:41-54 tunes
    # hierarchical allreduce AND allgather): each flag takes value 1 in
    # at least one sampled row over the run.
    assert any(r[2] == 1.0 for r in rows), "hier allreduce never explored"
    assert any(r[3] == 1.0 for r in rows), "hier allgather never explored"
    # Freeze-to-best: the frozen knobs equal the best-scoring sampled
    # row (ties by score allowed). Two representation gaps separate the
    # CSV row from the read-back frozen value and both must fit inside
    # the tolerance: (a) the CSV logs the SAMPLED double at %.3f printf
    # precision (half-ULP 5e-4); (b) the APPLIED value is quantized by
    # the core's integer storage — cycle time is held in whole
    # microseconds, so the read-back can sit a full 1e-3 ms below the
    # sampled double (observed: sampled 77.8195 -> CSV "77.820" vs
    # applied 77819 us -> 77.819). fusion_mb's byte quantization is
    # ~1e-6 MB, so only the printf half-ULP applies there.
    best_score = max(r[4] for r in rows)
    best_points = {(r[0], r[1]) for r in rows
                   if abs(r[4] - best_score) < 1e-9}
    frozen = (out["fusion_mb"], out["cycle_ms"])
    assert any(abs(frozen[0] - p[0]) <= 6e-4 and
               abs(frozen[1] - p[1]) <= 1.6e-3
               for p in best_points), (frozen, best_points)
    # The SP tuner's execution-mode verdict is APPLIED: after the final
    # allreduce the live executor's hierarchical flags equal
    # hvdtpu_current_flags (VERDICT r2 #4 — a tuned flag must visibly
    # switch the execution path, not just live in the tuner).
    assert out["ex_hier_ar"] == out["flag_hier_ar"], out
    assert out["ex_hier_ag"] == out["flag_hier_ag"], out
