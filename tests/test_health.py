"""Online anomaly-detection health plane (docs/health.md): detector
state machines (incl. the noisy-but-flat false-positive guard), alert
fan-out (registry / flight recorder / log / webhook / policy queue),
the adaptation-ladder alert input with its hysteresis, the coordinator
AlertNoteRequest RPC, and the alert-kind ↔ docs drift check."""

import http.server
import json
import os
import random
import threading
import time

import pytest

from horovod_tpu.observability import flight_recorder as _flight
from horovod_tpu.observability import health as _health
from horovod_tpu.observability import registry as _reg

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestEwmaDetector:
    def test_fires_on_level_shift_not_on_noise(self):
        rng = random.Random(7)
        det = _health.EwmaDetector("up")
        fired = []
        for t in range(60):
            v = 0.010 if t < 40 else 0.013           # +30% shift at 40
            ev = det.update(float(t), v + rng.gauss(0, 2e-4))
            if ev:
                fired.append((t, ev))
        assert fired, "a 30% sustained shift must fire"
        first_t, ev = fired[0]
        assert 40 <= first_t <= 43, \
            f"must fire within 3 windows of the shift, fired at {first_t}"
        assert ev["baseline"] == pytest.approx(0.010, rel=0.1)
        assert ev["rel_change"] >= 0.2

    def test_quiet_on_stationary_noise(self):
        rng = random.Random(13)
        det = _health.EwmaDetector("up")
        assert not any(det.update(float(t), 0.01 + rng.gauss(0, 5e-4))
                       for t in range(200))

    def test_single_spike_does_not_fire_or_poison(self):
        rng = random.Random(3)
        det = _health.EwmaDetector("up", warmup=5)
        fired = []
        for t in range(60):
            v = 0.05 if t == 30 else 0.01            # one 5x outlier
            if det.update(float(t), v + rng.gauss(0, 2e-4)):
                fired.append(t)
        # The spike itself may trip one window (it IS 5x); the guard is
        # that the baseline doesn't absorb it: steady samples after it
        # must not keep firing.
        assert all(t == 30 for t in fired)

    def test_down_direction_for_mfu(self):
        det = _health.EwmaDetector("down", min_rel=0.1)
        fired = []
        for t in range(40):
            v = 0.45 if t < 25 else 0.30             # MFU droop
            if det.update(float(t), v):
                fired.append(t)
        assert fired and fired[0] == 25


class TestTrendDetector:
    def test_monotone_leak_trips(self):
        rng = random.Random(11)
        det = _health.TrendDetector()
        fired = [t for t in range(40)
                 if det.update(float(t), 1e6 + 5e4 * t
                               + rng.gauss(0, 1e3))]
        assert fired
        assert fired[0] < 15

    def test_noisy_but_flat_does_not_trip(self):
        """ACCEPTANCE (false-positive guard): a gauge with big noise
        and no trend must stay quiet."""
        rng = random.Random(17)
        det = _health.TrendDetector()
        assert not any(det.update(float(t), 1e6 + rng.gauss(0, 2e5))
                       for t in range(300))

    def test_decreasing_does_not_trip(self):
        det = _health.TrendDetector()
        assert not any(det.update(float(t), 1e6 - 1e4 * t)
                       for t in range(40))


class TestRateDetector:
    def test_spike_in_window_fires(self):
        det = _health.RateDetector(threshold=3, window_s=100)
        assert det.update(0.0, 0.0) is None
        assert det.update(10.0, 0.1) is None          # 1 restart
        assert det.update(20.0, 0.1) is None          # 2 restarts
        ev = det.update(30.0, 0.1)                    # 3 restarts
        assert ev and ev["events"] == pytest.approx(3.0)

    def test_slow_drip_outside_window_stays_quiet(self):
        det = _health.RateDetector(threshold=3, window_s=100)
        t = 0.0
        for _ in range(10):                           # 1 per 200s
            assert det.update(t, 0.005) is None
            t += 200.0


def _drive_regression(monitor, key="hvdtpu_step_seconds"
                                   '{framework="t"}|mean'):
    """Feed a clean baseline then a 30% shift; returns fired alerts."""
    fired = []
    for t in range(30):
        v = 0.010 if t < 20 else 0.013
        fired.extend(monitor.observe({key: v}, t=float(t),
                                     t_unix=1000.0 + t))
    return fired


class TestHealthMonitor:
    def test_alert_fans_out_to_metric_recorder_and_queue(self):
        _flight.reset()
        _health.drain_policy_alerts()                 # clear
        monitor = _health.HealthMonitor(rank=2)
        fired = _drive_regression(monitor)
        assert fired
        a = fired[0]
        assert a.kind == "step_time_regression"
        assert a.rank == 2
        assert a.severity == "warning"
        assert a.value == pytest.approx(0.013)
        # registry counter, labeled by kind+severity
        fam = _reg.registry().counter("hvdtpu_health_alerts_total", "")
        key = 'kind="step_time_regression",severity="warning"'
        assert dict(fam.items())[key].value >= 1
        # flight-recorder event
        events = [e for e in list(_flight.recorder()._ring)
                  if e[1] == "alert"]
        assert events
        assert events[0][2][0] == "step_time_regression"
        # policy queue (regression kinds feed the ladder)
        q = _health.drain_policy_alerts()
        assert q and q[0]["kind"] == "step_time_regression"
        assert q[0]["rank"] == 2
        assert _health.drain_policy_alerts() == []    # drained

    def test_refire_suppression(self):
        monitor = _health.HealthMonitor(rank=0, refire_s=1000.0)
        fired = _drive_regression(monitor)
        assert len(fired) == 1, \
            "a sustained regression must page once per refire window"

    def test_emit_false_collects_without_side_effects(self):
        _health.drain_policy_alerts()
        fam = _reg.registry().counter("hvdtpu_health_alerts_total", "")
        key = 'kind="step_time_regression",severity="warning"'
        before = (dict(fam.items()).get(key).value
                  if key in dict(fam.items()) else 0)
        monitor = _health.HealthMonitor(rank=0, emit=False)
        fired = _drive_regression(monitor)
        assert fired and monitor.alerts
        after = (dict(fam.items()).get(key).value
                 if key in dict(fam.items()) else 0)
        assert after == before
        assert _health.drain_policy_alerts() == []

    def test_replica_attribution(self):
        monitor = _health.HealthMonitor(replica=3, emit=False)
        fired = []
        for t in range(30):
            v = 0.0 if t < 10 else float(t - 10)      # queue runaway
            fired.extend(monitor.observe(
                {"hvdtpu_serving_queue_depth": v}, t=float(t) * 5))
        assert fired
        assert fired[0].kind == "queue_depth_runaway"
        assert fired[0].replica == 3
        assert "replica 3" in fired[0].message

    def test_webhook_posts_alert_json(self):
        received = []

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers["Content-Length"])
                received.append(json.loads(self.rfile.read(n)))
                self.send_response(200)
                self.end_headers()

            def log_message(self, *a):
                pass

        srv = http.server.HTTPServer(("127.0.0.1", 0), Handler)
        thread = threading.Thread(target=srv.serve_forever, daemon=True)
        thread.start()
        try:
            url = f"http://127.0.0.1:{srv.server_address[1]}/alerts"
            monitor = _health.HealthMonitor(rank=0, webhook_url=url)
            fired = []
            for t in range(40):
                v = 0.5 if t < 25 else 0.3
                fired.extend(monitor.observe(
                    {'hvdtpu_mfu{framework="t"}': v}, t=100.0 + t,
                    t_unix=2000.0 + t))
            assert fired
            deadline = time.monotonic() + 10
            while not received and time.monotonic() < deadline:
                time.sleep(0.02)
            assert received, "webhook never received the alert"
            body = received[0]
            assert body["kind"] == "mfu_droop"
            assert body["severity"] == "warning"
            assert "message" in body and "evidence" in body
        finally:
            srv.shutdown()
            srv.server_close()


class TestPolicyAlertInput:
    def _policy(self, **kw):
        from horovod_tpu.adaptation.policy import (AdaptationConfig,
                                                   AdaptationPolicy)
        cfg = AdaptationConfig(threshold_s=0.1, sustain_s=5.0,
                               cooldown_s=10.0, alert_hold_s=30.0,
                               tiers=("shrink", "bf16"), **kw)
        return AdaptationPolicy(cfg, allow_evict=False)

    def test_alert_pressure_escalates_after_sustain(self):
        """An alert starts the sustain clock like measured lateness —
        and escalates only after the full hysteresis window."""
        p = self._policy()
        p.note_alert("step_time_regression", rank=2, now=0.0)
        assert p.observe({}, now=0.0) == []            # clock starts
        assert p.observe({}, now=3.0) == []            # not sustained
        events = p.observe({}, now=6.0)                # > sustain_s
        assert [e["name"] for e in events] == ["shrink"]
        assert events[0]["rank"] == 2
        assert p.tier == 1

    def test_unrenewed_alert_decays_without_escalation(self):
        p = self._policy()
        p.note_alert("hbm_leak", rank=1, now=0.0)
        p.observe({}, now=0.0)
        # Past alert_hold_s the pressure is gone; the sustain clock
        # never completed → no escalation, ever.
        assert p.observe({}, now=31.0) == []
        assert p.observe({}, now=40.0) == []
        assert p.tier == 0

    def test_alert_pressure_merges_with_measured_lateness(self):
        p = self._policy()
        p.note_alert("step_time_regression", rank=2, now=0.0)
        # Measured lateness on another rank is WORSE than the alert
        # floor — the measured straggler wins the election.
        p.observe({3: 0.5}, now=0.0)
        events = p.observe({3: 0.5}, now=6.0)
        assert events and events[0]["rank"] == 3

    def test_alert_input_metric_counts(self):
        p = self._policy()
        p.note_alert("hbm_leak", rank=0, now=0.0)
        fam = _reg.registry().counter(
            "hvdtpu_adaptation_alert_inputs_total", "")
        assert dict(fam.items())['kind="hbm_leak"'].value >= 1


class TestAlertNoteRPC:
    def test_note_alert_reaches_coordinator_policy(self):
        from horovod_tpu.adaptation.policy import (AdaptationConfig,
                                                   AdaptationPolicy)
        from horovod_tpu.ops.control_plane import (CoordinatorClient,
                                                   CoordinatorService)
        from horovod_tpu.runner.secret import make_secret_key
        svc = CoordinatorService(nproc=2, key=make_secret_key(),
                                 fusion_threshold=1024)
        try:
            svc._policy = AdaptationPolicy(
                AdaptationConfig(tiers=("shrink",)), allow_evict=False)
            client = CoordinatorClient([("127.0.0.1", svc.port)],
                                       svc.key, rank=1)
            client.note_alert("step_time_regression", rank=1,
                              severity="warning", value=0.013)
            deadline = time.monotonic() + 10
            while not svc._policy._alert_until \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            assert ("step_time_regression", 1) in svc._policy._alert_until
            fam = _reg.registry().counter(
                "hvdtpu_coordinator_alert_notes_total", "")
            assert dict(fam.items())[
                'kind="step_time_regression"'].value >= 1
        finally:
            svc.shutdown()


class TestAlertKindDrift:
    """Satellite CI: every Alert kind must be documented in
    docs/health.md and fire a registered metric label."""

    def test_every_kind_documented_in_health_md(self):
        doc = open(os.path.join(ROOT, "docs", "health.md")).read()
        for kind in _health.ALERT_KINDS:
            assert f"`{kind}`" in doc, (
                f"alert kind {kind!r} missing from docs/health.md — "
                "document it in the detectors/alert-schema section")

    def test_every_kind_has_a_detector_spec(self):
        specs = {s.kind for s in _health.default_specs()}
        assert specs == set(_health.ALERT_KINDS)

    def test_every_kind_registers_its_metric_label(self):
        monitor = _health.HealthMonitor(rank=0, emit=True,
                                        webhook_url=None)
        _health.drain_policy_alerts()
        for spec in monitor.specs:
            monitor._fire(spec, "test_series", 1.0,
                          {"baseline": 0.5, "window_s": 1.0}, 0.0)
        _health.drain_policy_alerts()
        fam = dict(_reg.registry().counter(
            "hvdtpu_health_alerts_total", "").items())
        for kind in _health.ALERT_KINDS:
            assert any(f'kind="{kind}"' in key for key in fam), (
                f"alert kind {kind!r} fired no "
                "hvdtpu_health_alerts_total label")

    def test_policy_kinds_are_alert_kinds(self):
        assert set(_health.POLICY_ALERT_KINDS) <= set(
            _health.ALERT_KINDS)
