"""Multi-process eager collectives over the TCP control plane.

The reference runs its suite under ``mpirun -np 2`` so real collectives
cross process boundaries (.travis.yml:100-111). The TPU-native analogue:
these tests launch REAL worker subprocesses through the runner; each
worker initializes ``jax.distributed`` (CPU platform, 1 device each), and
eager collectives negotiate through the rank-0 TCP coordinator
(ops/control_plane.py) and execute as SPMD XLA programs over the
2-device global mesh.

Marked slow: each test pays subprocess + jax.distributed startup.
"""

import numpy as np
import pytest

from horovod_tpu.runner.api import run

# Workers must be plain CPU, one device each, or the axon/TPU platform
# plugin would fight over the single real chip.
_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
}

pytestmark = pytest.mark.slow


class TestMultiProcessCollectives:
    def test_two_process_collectives(self):
        def worker():
            # Nested so cloudpickle ships it by value (module-level test
            # functions are not importable in the worker).
            import jax.numpy as jnp
            import numpy as np

            import horovod_tpu as hvd

            hvd.init()
            r, n = hvd.rank(), hvd.size()
            out = {}

            # allreduce: per-process values; sum == sum over ranks.
            x = jnp.full((4,), float(r + 1))
            s = hvd.allreduce(x, average=False, name="mp.sum")
            out["sum"] = np.asarray(s).tolist()

            a = hvd.allreduce(jnp.full((3,), float(r)), average=True,
                              name="mp.avg")
            out["avg"] = np.asarray(a).tolist()

            # fused pair enqueued together (same cycle -> one group)
            h1 = hvd.allreduce_async(jnp.ones((5,)), average=False,
                                     name="mp.f1")
            h2 = hvd.allreduce_async(jnp.full((5,), 2.0), average=False,
                                     name="mp.f2")
            out["f1"] = np.asarray(hvd.synchronize(h1)).tolist()
            out["f2"] = np.asarray(hvd.synchronize(h2)).tolist()

            # broadcast from the last virtual rank (process 1, 1 dev/proc)
            b = hvd.broadcast(jnp.full((2,), float(10 * (r + 1))),
                              root_rank=n - 1, name="mp.bc")
            out["bcast"] = np.asarray(b).tolist()

            g = hvd.allgather(jnp.full((2,), float(r)), name="mp.ag")
            out["gather"] = np.asarray(g).tolist()

            # uint32 broadcast: the dtype of jax PRNG keys — must ride
            # the wire (HVD_UINT32), not die in the codec.
            key = hvd.broadcast(jnp.asarray([r + 7, r + 9], jnp.uint32),
                                root_rank=0, name="mp.key")
            out["key"] = np.asarray(key).tolist()

            # ragged allgather: rank r contributes r+1 rows
            rg = hvd.allgather(jnp.full((r + 1, 2), float(r)),
                               name="mp.agv")
            out["ragged"] = np.asarray(rg).tolist()
            return out

        results = run(worker, np=2, extra_env=dict(_ENV),
                      start_timeout=300)
        for r in results:
            assert r["sum"] == [3.0] * 4          # 1 + 2
            assert r["avg"] == [0.5] * 3          # (0+1)/2
            assert r["f1"] == [2.0] * 5
            assert r["f2"] == [4.0] * 5
            assert r["bcast"] == [20.0, 20.0]     # root = rank 1
            assert r["gather"] == [0.0, 0.0, 1.0, 1.0]
            assert r["key"] == [7, 9]             # rank 0's uint32 values
        ragged = np.array(results[0]["ragged"])
        assert ragged.shape == (3, 2)             # 1 row + 2 rows
        assert np.allclose(ragged, [[0, 0], [1, 1], [1, 1]])
        assert results[0] == results[1]

    def test_training_loop_end_to_end(self):
        def train():
            import jax
            import jax.numpy as jnp
            import optax

            import horovod_tpu as hvd

            hvd.init()
            r = hvd.rank()
            # Per-rank data shard: y = 2x, rank r sees offset slice.
            xs = jnp.arange(8.0) + 4 * r
            ys = 2.0 * xs
            params = {"w": jnp.asarray(0.0)}
            params = hvd.broadcast_parameters(params, root_rank=0)
            opt = optax.sgd(0.02)
            state = opt.init(params)
            for step in range(40):
                def loss_fn(p):
                    return jnp.mean((p["w"] * xs - ys) ** 2)
                loss, grads = jax.value_and_grad(loss_fn)(params)
                # Eager cross-process gradient averaging (the
                # DistributedOptimizer hook path).
                grads = {"w": hvd.allreduce(grads["w"], average=True,
                                            name=f"g.{step}")}
                updates, state = opt.update(grads, state, params)
                params = optax.apply_updates(params, updates)
            return float(params["w"])

        results = run(train, np=2, extra_env=dict(_ENV), start_timeout=300)
        assert len(results) == 2
        # Both ranks converge to the same w ~= 2 (identical averaged grads).
        assert abs(results[0] - results[1]) < 1e-6
        assert abs(results[0] - 2.0) < 0.1

    def test_mismatched_shapes_error(self):
        def fn():
            import jax.numpy as jnp

            import horovod_tpu as hvd
            from horovod_tpu.ops import HorovodInternalError

            hvd.init()
            shape = (3,) if hvd.rank() == 0 else (5,)
            try:
                hvd.allreduce(jnp.ones(shape), name="mp.bad")
                return "no error"
            except (HorovodInternalError, ValueError) as e:
                return f"error: {e}"

        results = run(fn, np=2, extra_env=dict(_ENV), start_timeout=300)
        for r in results:
            assert "Mismatched allreduce tensor shapes" in r

    def test_mismatched_average_errors_not_hangs(self):
        """VERDICT r2 #5 done-condition: two processes passing different
        ``average`` for one tensor get a Mismatched error, not a hang
        (the attribute rides the wire's device slot as an
        execution-semantics fingerprint)."""
        def fn():
            import jax.numpy as jnp

            import horovod_tpu as hvd
            from horovod_tpu.ops import HorovodInternalError

            hvd.init()
            avg = hvd.rank() == 0
            try:
                hvd.allreduce(jnp.ones((4,)), average=avg, name="mp.avgmix")
                return "no error"
            except (HorovodInternalError, ValueError) as e:
                return f"error: {e}"

        results = run(fn, np=2, extra_env=dict(_ENV), start_timeout=300)
        for r in results:
            assert "Mismatched execution attributes" in r


class TestMultiDevicePerProcess:
    def test_two_procs_two_devices_each(self):
        """2 processes x 2 virtual devices: size == 4 virtual ranks; each
        device contributes its process's eager value (the virtual-rank
        semantics extended across hosts), and ragged allgather expands
        per-process dims by local device count."""
        env = {
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        }

        def worker():
            import jax.numpy as jnp
            import numpy as np

            import horovod_tpu as hvd

            hvd.init()
            pr = hvd.process_rank()
            out = {"size": hvd.size(), "local_size": hvd.local_size()}

            # Each of this process's 2 devices contributes value pr+1:
            # sum = 2*(1) + 2*(2) = 6.
            s = hvd.allreduce(jnp.full((3,), float(pr + 1)),
                              average=False, name="md.sum")
            out["sum"] = np.asarray(s).tolist()

            # allgather: one segment per device -> 4 copies, grouped by
            # process (devices of a process are contiguous ranks).
            g = hvd.allgather(jnp.full((1, 2), float(pr)), name="md.ag")
            out["gather"] = np.asarray(g).tolist()

            # ragged: process 0 contributes 1 row/device, process 1 two.
            rg = hvd.allgather(jnp.full((pr + 1, 2), float(pr)),
                               name="md.agv")
            out["ragged_shape"] = list(np.asarray(rg).shape)
            return out

        results = run(worker, np=2, extra_env=env, start_timeout=300)
        for r in results:
            assert r["size"] == 4 and r["local_size"] == 2
            assert r["sum"] == [6.0] * 3
            assert r["gather"] == [[0.0, 0.0], [0.0, 0.0],
                                   [1.0, 1.0], [1.0, 1.0]]
            assert r["ragged_shape"] == [6, 2]   # 1+1+2+2 rows
        assert results[0] == results[1]


class TestHierarchicalMultiProcess:
    def test_hierarchical_allreduce_across_processes(self):
        """HOROVOD_TPU_HIERARCHICAL_ALLREDUCE=1 in a 2-process x 2-device
        job: psum_scatter over 'ici' + psum over 'dcn' + all_gather over
        'ici' must give the same sums as the flat path."""
        env = {
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
            "HOROVOD_TPU_HIERARCHICAL_ALLREDUCE": "1",
        }

        def worker():
            import jax.numpy as jnp
            import numpy as np

            import horovod_tpu as hvd

            hvd.init()
            pr = hvd.process_rank()
            # 2 devices/process each contribute pr+1: total = 2*1+2*2 = 6
            s = hvd.allreduce(jnp.full((5,), float(pr + 1)),
                              average=False, name="hier.sum")
            # odd size exercises the ici padding path
            s2 = hvd.allreduce(jnp.full((7,), 1.0), average=True,
                               name="hier.avg")
            return (np.asarray(s).tolist(), np.asarray(s2).tolist())

        results = run(worker, np=2, extra_env=env, start_timeout=300)
        for s, s2 in results:
            assert s == [6.0] * 5
            assert s2 == [1.0] * 7
        assert results[0] == results[1]


class TestNativeControlPlane:
    def test_native_core_is_mp_control_plane(self):
        """VERDICT r1 #1 'done' condition: with process_count > 1 the
        native core is ACTIVE (tensor table, cycle, wire, timeline in
        C++), the rank-0 service plans with the native controller, and no
        Python fallback loop runs."""
        def worker():
            import jax.numpy as jnp
            import numpy as np

            import horovod_tpu as hvd
            from horovod_tpu.ops.collective import engine

            hvd.init()
            r = hvd.rank()
            s = hvd.allreduce(jnp.full((4,), float(r + 1)),
                              average=False, name="native.sum")
            eng = engine()
            return {
                "sum": np.asarray(s).tolist(),
                "native_core": eng._native_core is not None,
                "coordinator_native": (eng._mp_service.native_active
                                       if eng._mp_service else None),
                "python_loop": eng._thread is not None,
            }

        results = run(worker, np=2, extra_env=dict(_ENV), start_timeout=300)
        for r in results:
            assert r["sum"] == [3.0] * 4
            assert r["native_core"], "native core not active in MP mode"
            assert not r["python_loop"], "python fallback loop is running"
        assert results[0]["coordinator_native"] is True

    def test_mixed_fleet_native_and_fallback(self):
        """A process without the native runtime (toolchain missing /
        HOROVOD_TPU_DISABLE_NATIVE=1) interoperates with native peers:
        both speak the message.cc wire format — the fallback via the
        byte-exact Python mirror (ops/wire_format.py)."""
        def worker():
            import os

            import jax.numpy as jnp
            import numpy as np

            import horovod_tpu as hvd
            from horovod_tpu.ops.collective import engine

            # Rank 1 runs the degraded pure-Python path; rank 0 native.
            if os.environ.get("HOROVOD_TPU_PROCESS_ID") == "1":
                os.environ["HOROVOD_TPU_DISABLE_NATIVE"] = "1"
            hvd.init()
            r = hvd.rank()
            out = {}
            out["sum"] = np.asarray(hvd.allreduce(
                jnp.full((4,), float(r + 1)), average=False,
                name="mix.sum")).tolist()
            out["ragged"] = np.asarray(hvd.allgather(
                jnp.full((r + 1, 2), float(r)), name="mix.agv")).tolist()
            out["bcast"] = np.asarray(hvd.broadcast(
                jnp.full((2,), float(10 * (r + 1))), root_rank=1,
                name="mix.bc")).tolist()
            out["native"] = engine()._native_core is not None
            return out

        results = run(worker, np=2, extra_env=dict(_ENV), start_timeout=300)
        assert results[0]["native"] is True
        assert results[1]["native"] is False
        for r in results:
            assert r["sum"] == [3.0] * 4
            assert np.allclose(np.array(r["ragged"]),
                               [[0, 0], [1, 1], [1, 1]])
            assert r["bcast"] == [20.0, 20.0]


class TestFourProcesses:
    def test_four_process_collectives_and_ordering(self):
        """VERDICT r1 weak #4: >= 3 processes, ragged cross-process
        allgather with differing per-process first dims, and a
        coordinator-ordering stress — many named ops enqueued in a
        DIFFERENT order on each process; the coordinator's agreed group
        sequence must keep every process's results identical."""
        def worker():
            import jax.numpy as jnp
            import numpy as np

            import horovod_tpu as hvd

            hvd.init()
            r, n = hvd.rank(), hvd.size()
            out = {}

            # Ragged MP allgather: rank r contributes r+1 rows of value r.
            rg = hvd.allgather(jnp.full((r + 1, 2), float(r)),
                               name="p4.agv")
            out["ragged"] = np.asarray(rg).tolist()

            # Ordering stress: 12 async ops enqueued in a rank-dependent
            # rotation; handles must all resolve to the right sums.
            names = [f"p4.x{i}" for i in range(12)]
            order = names[r:] + names[:r]
            handles = {}
            for i, nm in enumerate(order):
                val = float(int(nm.split("x")[1]) + 1)
                handles[nm] = hvd.allreduce_async(
                    jnp.full((3,), val), average=False, name=nm)
            out["sums"] = {nm: float(np.asarray(h.wait())[0])
                           for nm, h in handles.items()}

            # A broadcast from the last rank mixed into the stream.
            b = hvd.broadcast(jnp.full((2,), float(r)), root_rank=n - 1,
                              name="p4.bc")
            out["bcast"] = np.asarray(b).tolist()
            return out

        results = run(worker, np=4, extra_env=dict(_ENV), start_timeout=600)
        expect_ragged = []
        for r in range(4):
            expect_ragged += [[float(r)] * 2] * (r + 1)
        for r in results:
            assert np.allclose(np.array(r["ragged"]), expect_ragged)
            for nm, v in r["sums"].items():
                i = int(nm.split("x")[1])
                assert v == 4.0 * (i + 1), (nm, v)
            assert r["bcast"] == [3.0, 3.0]
        assert all(r == results[0] for r in results[1:])


class TestCrossProcessAutotune:
    def test_knobs_move_in_lockstep(self):
        """VERDICT r1 #4: with HOROVOD_AUTOTUNE=1 the rank-0 controller
        tunes (fusion threshold, cycle time) and serves them through the
        fetch response (SyncParams, parameter_manager.cc:64-78,213-246);
        every process must apply the same knob sequence — knobs MOVE
        (the tuner explores) and END identical across processes."""
        env = {
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            "HOROVOD_AUTOTUNE": "1",
            "HOROVOD_CYCLE_TIME": "1",
        }

        def worker():
            import jax.numpy as jnp

            import horovod_tpu as hvd
            from horovod_tpu.ops.collective import engine

            hvd.init()
            x = jnp.ones((64, 64))
            seen = []
            for i in range(160):
                hvd.allreduce(x, average=False, name=f"tune.{i}")
                eng = engine()
                knobs = (round(eng.fusion_threshold / (1024.0 * 1024.0), 3),
                         round(eng.cycle_time_s * 1000.0, 3))
                if not seen or seen[-1] != knobs:
                    seen.append(knobs)
            active = bool(engine().mp_params.get("autotune_active")
                          or engine().mp_params.get("autotune_done"))
            return {"seen": seen, "tuner_on": active}

        results = run(worker, np=2, extra_env=env, start_timeout=600)
        for r in results:
            assert r["tuner_on"], r
            # The tuner explored: at least one knob change was applied.
            assert len(r["seen"]) >= 2, r["seen"]
        # Lockstep: both processes end on the SAME coordinator-tuned
        # knobs (the sequences may be sampled at different cycle points,
        # but the final state must agree).
        assert results[0]["seen"][-1] == results[1]["seen"][-1], results


class TestDevicePack:
    def test_device_packed_collectives_match(self):
        """VERDICT r3 #5: the device-resident fusion-buffer pack
        (executor._pack_device + _mp_stacked_device) computes the same
        results as the host pack. Forced on via HOROVOD_TPU_DEVICE_PACK
        (CPU defaults it off), 2 processes, mixed sizes/dtypes and a
        fused burst so quantized buffers and cached DUS programs are
        exercised."""
        def worker():
            import jax.numpy as jnp
            import numpy as np

            import horovod_tpu as hvd
            from horovod_tpu.ops import collective

            hvd.init()
            r, n = hvd.rank(), hvd.size()
            ex = collective.engine().executor
            assert ex._device_pack() is True  # env forced
            out = {}
            s = hvd.allreduce(jnp.full((17,), float(r + 1)),
                              average=False, name="dp.sum")
            out["sum"] = np.asarray(s).tolist()
            h1 = hvd.allreduce_async(jnp.ones((5, 3)), average=False,
                                     name="dp.f1")
            h2 = hvd.allreduce_async(
                jnp.full((9,), 2.0, jnp.bfloat16), average=False,
                name="dp.f2")
            out["f1"] = np.asarray(hvd.synchronize(h1)).tolist()
            out["f2"] = np.asarray(hvd.synchronize(h2),
                                   dtype=np.float32).tolist()
            b = hvd.broadcast(jnp.full((4,), float(10 * (r + 1))),
                              root_rank=1, name="dp.bc")
            out["bcast"] = np.asarray(b).tolist()
            return out

        env = dict(_ENV)
        env["HOROVOD_TPU_DEVICE_PACK"] = "1"
        results = run(worker, np=2, extra_env=env, start_timeout=300)
        for r in results:
            assert r["sum"] == [3.0] * 17
            assert np.allclose(np.array(r["f1"]), 2.0)
            assert np.allclose(np.array(r["f2"]), 4.0)
            assert r["bcast"] == [20.0] * 4
        assert results[0] == results[1]

    def test_device_pack_multi_device_committed_inputs(self):
        """Device pack with 2 local devices per process and an input
        COMMITTED to the non-default local device: the pack must put it
        onto the buffer's device instead of raising 'incompatible
        devices' from the jitted update-slice (the host pack accepted
        any placement, so must this path)."""
        def worker():
            import jax
            import jax.numpy as jnp
            import numpy as np

            import horovod_tpu as hvd
            from horovod_tpu.ops import collective

            hvd.init()
            pr = hvd.process_rank()
            assert jax.local_device_count() == 2
            ex = collective.engine().executor
            assert ex._device_pack() is True
            x = jax.device_put(jnp.full((6,), float(pr + 1)),
                               jax.local_devices()[1])
            s = hvd.allreduce(x, average=False, name="dpm.sum")
            return {"sum": np.asarray(s).tolist()}

        env = {
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
            "HOROVOD_TPU_DEVICE_PACK": "1",
        }
        results = run(worker, np=2, extra_env=env, start_timeout=300)
        for r in results:
            assert r["sum"] == [6.0] * 6  # 2 devices x (1) + 2 x (2)
