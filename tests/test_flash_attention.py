"""Pallas flash-attention kernel tests — interpreter mode on the CPU
mesh (the compiled Mosaic path is exercised on real TPU by bench/dev
runs; the math is identical).

Covers: forward vs full attention (causal and not, ragged block
boundaries), backward dq/dk/dv vs autodiff of full attention, bf16
tolerance, and the transformer's use_flash path end-to-end.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.ops.flash_attention import flash_attention
from horovod_tpu.parallel.ring_attention import full_attention


def _rand(shape, dtype=jnp.float32, seed=0):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(*shape), dtype)


class TestFlashForward:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_full(self, causal):
        q = _rand((2, 64, 4, 16))
        k = _rand((2, 64, 4, 16), seed=1)
        v = _rand((2, 64, 4, 16), seed=2)
        out = flash_attention(q, k, v, causal, None, 32, 32, True)
        ref = full_attention(q, k, v, causal=causal)
        assert float(jnp.max(jnp.abs(out - ref))) < 2e-5

    def test_single_block(self):
        # Sequence smaller than the block: one grid step, no rescaling.
        q = _rand((1, 16, 2, 8))
        out = flash_attention(q, q, q, True, None, 128, 128, True)
        ref = full_attention(q, q, q, causal=True)
        assert float(jnp.max(jnp.abs(out - ref))) < 2e-5

    def test_uneven_blocks(self):
        # Blocks that do not divide the sequence evenly exercise cdiv
        # padding in the grid.
        q = _rand((1, 48, 2, 8))
        out = flash_attention(q, q, q, True, None, 32, 32, True)
        ref = full_attention(q, q, q, causal=True)
        assert float(jnp.max(jnp.abs(out - ref))) < 2e-5

    def test_bf16(self):
        q = _rand((1, 64, 4, 16), jnp.bfloat16)
        out = flash_attention(q, q, q, True, None, 32, 32, True)
        ref = full_attention(q, q, q, causal=True)
        err = float(jnp.max(jnp.abs(out.astype(jnp.float32)
                                    - ref.astype(jnp.float32))))
        assert err < 3e-2


class TestFlashBackward:
    def test_grads_match_full(self):
        q = _rand((1, 64, 2, 16))
        k = _rand((1, 64, 2, 16), seed=1)
        v = _rand((1, 64, 2, 16), seed=2)

        def loss_flash(q, k, v):
            o = flash_attention(q, k, v, True, None, 32, 32, True)
            return (o.astype(jnp.float32) ** 2).sum()

        def loss_full(q, k, v):
            o = full_attention(q, k, v, causal=True)
            return (o.astype(jnp.float32) ** 2).sum()

        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            assert float(jnp.max(jnp.abs(a - b))) < 1e-3

    def test_noncausal_grads(self):
        q = _rand((1, 32, 2, 8))

        def lf(q):
            return (flash_attention(q, q, q, False, None, 16, 16,
                                    True) ** 2).sum()

        def lr(q):
            return (full_attention(q, q, q, causal=False) ** 2).sum()

        g1 = jax.grad(lf)(q)
        g2 = jax.grad(lr)(q)
        assert float(jnp.max(jnp.abs(g1 - g2))) < 1e-3

    @pytest.mark.parametrize("causal", [True, False])
    def test_tail_block_grads(self, causal):
        """Backward through cdiv-padded tail blocks: seq 40 with 16x16
        blocks leaves a ragged tail row/column, exercising the
        masked=True branch of _block_dispatch in all three kernels
        (the even-seq tests only ever compile the unmasked branch)."""
        q = _rand((1, 40, 2, 8))
        k = _rand((1, 40, 2, 8), seed=1)
        v = _rand((1, 40, 2, 8), seed=2)

        def lf(q, k, v):
            o = flash_attention(q, k, v, causal, None, 16, 16, True)
            return (o.astype(jnp.float32) ** 2).sum()

        def lr(q, k, v):
            return (full_attention(q, k, v, causal=causal) ** 2).sum()

        g1 = jax.grad(lf, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            assert float(jnp.max(jnp.abs(a - b))) < 1e-3

    def test_unequal_block_grads(self):
        """block_q != block_k in the backward kernels (the compiled
        defaults are rectangular: dkv 512x1024, dq 1024x512)."""
        q = _rand((1, 64, 2, 8))

        def lf(q):
            o = flash_attention(q, q, q, True, None, 16, 32, True)
            return (o.astype(jnp.float32) ** 2).sum()

        def lr(q):
            return (full_attention(q, q, q, causal=True) ** 2).sum()

        g1 = jax.grad(lf)(q)
        g2 = jax.grad(lr)(q)
        assert float(jnp.max(jnp.abs(g1 - g2))) < 1e-3

    def test_bf16_grads(self):
        """bf16 inputs make the backward's operand casts (p, ds to
        bf16 before the MXU) real rather than no-ops; grads must stay
        within bf16 rounding of the full-attention autodiff."""
        q = _rand((1, 48, 2, 16), jnp.bfloat16)
        k = _rand((1, 48, 2, 16), jnp.bfloat16, seed=1)
        v = _rand((1, 48, 2, 16), jnp.bfloat16, seed=2)

        def lf(q, k, v):
            o = flash_attention(q, k, v, True, None, 16, 16, True)
            return (o.astype(jnp.float32) ** 2).sum()

        def lr(q, k, v):
            o = full_attention(q, k, v, causal=True)
            return (o.astype(jnp.float32) ** 2).sum()

        g1 = jax.grad(lf, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            err = float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                        - b.astype(jnp.float32))))
            # grads here are O(10); 1e-1 absolute is ~1% relative —
            # a few bf16 ulps across the two accumulation orders
            assert err < 1e-1, err


class TestTransformerFlash:
    def test_use_flash_train_step(self):
        import optax

        from horovod_tpu.models import transformer as tfm

        cfg = tfm.TransformerConfig(
            vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq=32, dtype=jnp.float32, use_flash=True, remat=False)
        rng = jax.random.PRNGKey(0)
        params = tfm.init_params(cfg, rng)
        tokens = jax.random.randint(rng, (2, 32), 0, 64)

        def loss_fn(p):
            logits = tfm.apply(p, tokens, cfg)
            tgt = jnp.roll(tokens, -1, axis=1)
            oh = jax.nn.one_hot(tgt, 64)
            return -jnp.mean(jnp.sum(
                jax.nn.log_softmax(logits) * oh, axis=-1))

        loss, grads = jax.value_and_grad(loss_fn)(params)
        assert np.isfinite(float(loss))
        gnorm = sum(float(jnp.sum(jnp.abs(g)))
                    for g in jax.tree_util.tree_leaves(grads))
        assert gnorm > 0

        # flash and full attention agree through the whole model
        cfg_full = tfm.TransformerConfig(
            vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq=32, dtype=jnp.float32, use_flash=False, remat=False)
        logits_flash = tfm.apply(params, tokens, cfg)
        logits_full = tfm.apply(params, tokens, cfg_full)
        assert float(jnp.max(jnp.abs(logits_flash - logits_full))) < 1e-3

    def test_flash_block_config_threads_through(self, monkeypatch):
        """cfg.flash_block REACHES the kernel (round-4 long-seq sweep
        knob): capture the block args at the flash_attention call and
        check the output still matches full attention. The capture is
        what makes this a real thread-through test — default-block
        flash would also match full attention numerically."""
        from horovod_tpu.models import transformer as tfm
        from horovod_tpu.ops import flash_attention as fa

        seen = []
        real = fa.flash_attention

        def spy(q, k, v, causal=True, scale=None, block_q=None,
                block_k=None, interpret=False):
            seen.append((block_q, block_k))
            return real(q, k, v, causal, scale, block_q, block_k,
                        interpret)

        monkeypatch.setattr(fa, "flash_attention", spy)

        base = dict(vocab=64, d_model=32, n_heads=4, n_layers=2,
                    d_ff=64, max_seq=64, dtype=jnp.float32, remat=False)
        cfg_b16 = tfm.TransformerConfig(use_flash=True, flash_block=16,
                                        **base)
        cfg_full = tfm.TransformerConfig(use_flash=False, **base)
        rng = jax.random.PRNGKey(1)
        params = tfm.init_params(cfg_b16, rng)
        tokens = jax.random.randint(rng, (2, 64), 0, 64)
        lo_b = tfm.apply(params, tokens, cfg_b16)
        assert seen and all(bq == 16 and bk == 16 for bq, bk in seen), seen
        lo_f = tfm.apply(params, tokens, cfg_full)
        assert float(jnp.max(jnp.abs(lo_b - lo_f))) < 1e-3
