"""Cross-rank trace merge + straggler analysis (docs/tracing.md), on
SYNTHETIC per-rank captures — no subprocesses, no engine.

The adversarial-clock tests are the satellite contract: per-rank files
written under deliberate ±50 ms clock skew with jittered sync offsets
must still merge into the correct global ordering, and lateness
attribution must match the ground truth within the sync-jitter
tolerance.
"""

import json

import numpy as np
import pytest

from horovod_tpu.observability import histogram_percentiles
from horovod_tpu.observability.registry import LATENCY_BUCKETS, Histogram
from horovod_tpu.tools import trace as trace_tool

MS = 1000  # µs per ms


def _write_trace(path, rank, world, events, start_mono_us, offset_us,
                 synced=True, meta_in_trace=True, sidecar=False):
    """A per-rank catapult file the way PyTimeline lays it out: meta
    header, process_name per tensor, B/E phase events."""
    out = []
    if meta_in_trace:
        out.append({"name": "horovod_tpu_trace_meta", "ph": "M",
                    "pid": 0, "tid": 0,
                    "args": {"rank": rank, "world": world,
                             "start_mono_us": start_mono_us,
                             "offset_to_rank0_us": offset_us,
                             "rtt_us": 40.0, "clock_synced": synced}})
    pids = {}
    for e in events:
        tensor = e.pop("tensor")
        if tensor not in pids:
            pids[tensor] = len(pids)
            out.append({"name": "process_name", "ph": "M",
                        "pid": pids[tensor], "args": {"name": tensor}})
        e["pid"] = pids[tensor]
        e.setdefault("tid", 0)
        out.append(e)
    path.write_text(json.dumps(out))
    if sidecar:
        sc = {"rank": rank, "world": world,
              "start_mono_us": start_mono_us,
              "offset_to_rank0_us": offset_us, "rtt_us": 40.0,
              "clock_synced": synced}
        (path.parent / (path.name + ".clock.json")).write_text(
            json.dumps(sc))


def _collective_events(tensor, group, arrival_us, neg_dur_us,
                       exec_dur_us=500):
    """One collective's lifecycle on one rank, in LOCAL trace ts."""
    t = arrival_us
    return [
        {"tensor": tensor, "ph": "B", "ts": t,
         "name": "NEGOTIATE_ALLREDUCE"},
        {"tensor": tensor, "ph": "E", "ts": t + neg_dur_us,
         "args": {"group": group}},
        {"tensor": tensor, "ph": "B", "ts": t + neg_dur_us,
         "name": "ALLREDUCE"},
        {"tensor": tensor, "ph": "B", "ts": t + neg_dur_us + 10,
         "name": "XLA_ALLREDUCE"},
        {"tensor": tensor, "ph": "E",
         "ts": t + neg_dur_us + 10 + exec_dur_us},
        {"tensor": tensor, "ph": "E",
         "ts": t + neg_dur_us + 20 + exec_dur_us},
    ]


def _make_cluster(tmp_path, skews_us, late_rank, late_by_us,
                  jitters_us=None, n_groups=10, sidecar_only=False):
    """World of len(skews_us) ranks. Rank clocks are skewed by
    ``skews_us`` (local = global + skew); recorded offsets are the true
    correction (-skew) plus per-rank ``jitters_us`` (sync error).
    ``late_rank`` arrives ``late_by_us`` after everyone in every group.
    Ground-truth global arrival of group g on a punctual rank:
    g * 10ms."""
    world = len(skews_us)
    jitters_us = jitters_us or [0.0] * world
    paths = []
    for rank, skew in enumerate(skews_us):
        start_global = 0
        start_mono = start_global + skew
        events = []
        for g in range(n_groups):
            arrive_global = g * 10 * MS + \
                (late_by_us if rank == late_rank else 0)
            # Local trace ts = global - start_global (the skew lives in
            # start_mono_us, exactly as a real capture records it).
            events += _collective_events(
                f"t.{g}", group=g, arrival_us=arrive_global - start_global,
                neg_dur_us=(late_by_us if rank != late_rank else 100))
        _write_trace(tmp_path / f"trace.{rank}.json", rank, world, events,
                     start_mono_us=start_mono,
                     offset_us=-skew + jitters_us[rank],
                     meta_in_trace=not sidecar_only, sidecar=sidecar_only)
        paths.append(str(tmp_path / f"trace.{rank}.json"))
    return paths


class TestAdversarialClocks:
    """±50 ms skews + jittered offsets: ordering and attribution must
    come out right after realignment."""

    SKEWS = [0.0, 50 * MS, -50 * MS, 17 * MS]
    JITTERS = [0.0, 1500.0, -2000.0, 900.0]   # sync error, µs
    LATE, LATE_BY = 2, 80 * MS                # rank 2 is 80 ms late

    def _traces(self, tmp_path):
        paths = _make_cluster(tmp_path, self.SKEWS, self.LATE,
                              self.LATE_BY, jitters_us=self.JITTERS)
        return trace_tool.load_traces([str(tmp_path / "trace.{rank}.json")])

    def test_merged_ordering_matches_ground_truth(self, tmp_path):
        traces = self._traces(tmp_path)
        out = tmp_path / "merged.json"
        trace_tool.merge_traces(traces, str(out))
        merged = json.loads(out.read_text())
        # One Perfetto process per rank, tensors as named threads.
        procs = {e["pid"]: e["args"]["name"] for e in merged
                 if e.get("name") == "process_name"}
        assert procs == {r: f"rank {r}" for r in range(4)}
        threads = [e for e in merged if e.get("name") == "thread_name"]
        assert {e["args"]["name"] for e in threads} >= {"t.0", "t.9"}
        # Realigned NEGOTIATE starts: within every group, the late
        # rank's tick is last, and all punctual ranks agree within the
        # injected sync jitter despite ±50 ms raw skew.
        starts = {}   # (rank, group-index by order) -> ts
        per_rank_counts = {r: 0 for r in range(4)}
        for e in merged:
            if e.get("ph") == "B" and e.get("name") == "NEGOTIATE_ALLREDUCE":
                r = e["pid"]
                starts[(r, per_rank_counts[r])] = e["ts"]
                per_rank_counts[r] += 1
        tol = 2 * max(abs(j) for j in self.JITTERS)
        for g in range(10):
            arr = {r: starts[(r, g)] for r in range(4)}
            assert max(arr, key=lambda r: arr[r]) == self.LATE
            punctual = [arr[r] for r in range(4) if r != self.LATE]
            assert max(punctual) - min(punctual) <= tol
            assert arr[self.LATE] - min(punctual) == pytest.approx(
                self.LATE_BY, abs=tol)

    def test_lateness_attribution_within_tolerance(self, tmp_path):
        traces = self._traces(tmp_path)
        report = trace_tool.analyze(traces, top=5)
        assert report["groups_scored"] == 10
        top = report["top_straggler"]
        assert top["rank"] == self.LATE
        # Within 2x of the injected 80 ms (log-bucket estimator + jitter).
        assert self.LATE_BY / 1e6 / 2 <= top["p50_s"] <= self.LATE_BY / 1e6 * 2
        assert top["groups_last"] == 10
        # The skew is injected UPSTREAM of the collective path (pure
        # arrival lateness), and the report says so.
        assert top["loses_most_in"] == "upstream(compute/input)"
        # Punctual ranks show ~zero lateness — the ±50 ms raw skews were
        # corrected away.
        for r in range(4):
            if r != self.LATE:
                assert report["per_rank"][str(r)]["lateness"]["p50_s"] \
                    < 0.01
        # Every worst group is attributed to the late rank.
        assert {g["critical_rank"] for g in report["worst_groups"]} \
            == {self.LATE}

    def test_unsynced_clock_flagged_in_report(self, tmp_path):
        paths = _make_cluster(tmp_path, [0.0, 30 * MS], late_rank=1,
                              late_by_us=0, n_groups=3)
        traces = trace_tool.load_traces(paths)
        traces[1].meta["clock_synced"] = False
        report = trace_tool.analyze(traces)
        assert report["clock"]["1"]["synced"] is False
        assert "unsynced" in trace_tool.format_report(report)


class TestClockMetaSources:
    def test_sidecar_fallback(self, tmp_path):
        """Native-writer captures carry clock meta only in the sidecar;
        the loader must pick it up."""
        paths = _make_cluster(tmp_path, [0.0, 40 * MS], late_rank=1,
                              late_by_us=20 * MS, n_groups=4,
                              sidecar_only=True)
        traces = trace_tool.load_traces(paths)
        assert traces[1].meta["offset_to_rank0_us"] == -40 * MS
        report = trace_tool.analyze(traces)
        assert report["top_straggler"]["rank"] == 1
        assert report["top_straggler"]["p50_s"] == pytest.approx(
            0.020, rel=1.0)

    def test_headerless_traces_fall_back_to_position(self, tmp_path):
        for i in range(2):
            _write_trace(tmp_path / f"t.{i}.json", rank=i, world=2,
                         events=_collective_events("a", 0, 100, 50),
                         start_mono_us=0, offset_us=0.0,
                         meta_in_trace=False)
        traces = trace_tool.load_traces(
            [str(tmp_path / "t.0.json"), str(tmp_path / "t.1.json")])
        assert [t.rank for t in traces] == [0, 1]

    def test_duplicate_rank_rejected(self, tmp_path):
        for name in ("a.json", "b.json"):
            _write_trace(tmp_path / name, rank=0, world=2,
                         events=_collective_events("a", 0, 100, 50),
                         start_mono_us=0, offset_us=0.0)
        with pytest.raises(ValueError, match="duplicate rank"):
            trace_tool.load_traces([str(tmp_path / "a.json"),
                                    str(tmp_path / "b.json")])


class TestPhaseAttribution:
    def test_execute_heavy_rank_attributed_to_execute(self, tmp_path):
        """A rank slow INSIDE the collective path (long XLA spans) is
        attributed to the execute phase, not 'upstream'."""
        world = 2
        for rank in range(world):
            events = []
            for g in range(6):
                events += _collective_events(
                    f"t.{g}", group=g, arrival_us=g * 10 * MS,
                    neg_dur_us=100,
                    exec_dur_us=(40 * MS if rank == 1 else 500))
            _write_trace(tmp_path / f"p.{rank}.json", rank, world, events,
                         start_mono_us=0, offset_us=0.0)
        traces = trace_tool.load_traces([str(tmp_path / "p.{rank}.json")])
        report = trace_tool.analyze(traces)
        assert report["per_rank"]["1"]["loses_most_in"] == "execute"
        assert report["per_rank"]["1"]["phase_mean_s"]["execute"] \
            == pytest.approx(0.040, rel=0.1)


class TestGroupFallback:
    def test_occurrence_pairing_without_group_ids(self, tmp_path):
        """Traces without recorded group seqs (the native C++ writer)
        pair NEGOTIATE spans by per-tensor occurrence order."""
        world = 2
        for rank in range(world):
            events = []
            for step in range(4):   # name reused every step
                late = 15 * MS if rank == 1 else 0
                evs = _collective_events(
                    "grad.w", group=None, arrival_us=step * 30 * MS + late,
                    neg_dur_us=100)
                for e in evs:
                    e.get("args", {}).pop("group", None)
                events += evs
            _write_trace(tmp_path / f"o.{rank}.json", rank, world, events,
                         start_mono_us=0, offset_us=0.0)
        traces = trace_tool.load_traces([str(tmp_path / "o.{rank}.json")])
        report = trace_tool.analyze(traces)
        assert report["groups_scored"] == 4
        assert report["top_straggler"]["rank"] == 1
        assert report["top_straggler"]["p50_s"] == pytest.approx(
            0.015, rel=1.0)


class TestTruncatedCapture:
    def test_killed_writer_tail_is_tolerated(self, tmp_path):
        """A rank killed mid-stream leaves an unterminated file with a
        possibly-unclosed span; the loader and analyzer must survive."""
        _write_trace(tmp_path / "k.0.json", 0, 2,
                     _collective_events("a", 0, 100, 50),
                     start_mono_us=0, offset_us=0.0)
        # Rank 1: valid prefix, then an unclosed B and a trailing comma.
        full = json.loads((tmp_path / "k.0.json").read_text())
        body = ",\n".join(json.dumps(e) for e in full[:-1])
        (tmp_path / "k.1.json").write_text(
            "[\n" + body.replace('"rank": 0', '"rank": 1') + ",\n")
        traces = trace_tool.load_traces([str(tmp_path / "k.{rank}.json")])
        report = trace_tool.analyze(traces)
        assert report["groups_scored"] >= 1


class TestCli:
    def test_merge_cli_writes_trace_and_report(self, tmp_path, capsys):
        _make_cluster(tmp_path, [0.0, 10 * MS], late_rank=1,
                      late_by_us=25 * MS, n_groups=5)
        out = tmp_path / "merged.json"
        rep = tmp_path / "report.json"
        trace_tool._main(["merge", str(tmp_path / "trace.{rank}.json"),
                          "-o", str(out), "--report", str(rep)])
        printed = capsys.readouterr().out
        assert "Top straggler: rank 1" in printed
        merged = json.loads(out.read_text())          # valid catapult JSON
        assert any(e.get("name") == "NEGOTIATE_ALLREDUCE" for e in merged)
        report = json.loads(rep.read_text())
        assert report["top_straggler"]["rank"] == 1

    def test_report_cli(self, tmp_path, capsys):
        _make_cluster(tmp_path, [0.0, 0.0], late_rank=0, late_by_us=0,
                      n_groups=2)
        trace_tool._main(["report", str(tmp_path / "trace.{rank}.json")])
        assert "fused groups scored" in capsys.readouterr().out

    def test_template_with_no_matches_errors(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            trace_tool.load_traces([str(tmp_path / "none.{rank}.json")])


class TestClockMetaDegrade:
    """Satellite: a missing or corrupt .clock.json sidecar degrades to
    zero offset with a warning in the report header — it must never
    fail the whole merge."""

    def _cluster_with_broken_sidecar(self, tmp_path, breakage):
        paths = _make_cluster(tmp_path, [0.0, 0.0], late_rank=1,
                              late_by_us=20 * MS, n_groups=4,
                              sidecar_only=True)
        victim = tmp_path / "trace.1.json.clock.json"
        if breakage == "missing":
            victim.unlink()
        else:
            victim.write_text('{"rank": 1, "world"')   # torn json
        return paths

    @pytest.mark.parametrize("breakage", ["missing", "corrupt"])
    def test_merge_and_report_survive(self, tmp_path, breakage):
        paths = self._cluster_with_broken_sidecar(tmp_path, breakage)
        traces = trace_tool.load_traces(paths)
        assert traces[1].clock_missing is True
        # Positional rank fallback kept the right identity.
        assert [t.rank for t in traces] == [0, 1]
        out = tmp_path / "merged.json"
        trace_tool.merge_traces(traces, str(out))     # no exception
        json.loads(out.read_text())
        report = trace_tool.analyze(traces)
        assert report["clock"]["1"]["meta_missing"] is True
        text = trace_tool.format_report(report)
        assert "no clock metadata" in text
        assert "zero-offset fallback" in text

    def test_intact_sidecars_not_flagged(self, tmp_path):
        paths = _make_cluster(tmp_path, [0.0, 0.0], late_rank=1,
                              late_by_us=10 * MS, n_groups=3,
                              sidecar_only=True)
        traces = trace_tool.load_traces(paths)
        report = trace_tool.analyze(traces)
        assert not any(c["meta_missing"]
                       for c in report["clock"].values())
        assert "no clock metadata" not in trace_tool.format_report(report)


def _step_spans(input_us, compute_us, n_steps, t0_us=0, gap_us=None):
    """StepTimer's STEP_* complete spans on the _step pseudo-process,
    as step_metrics emits them."""
    gap_us = gap_us if gap_us is not None else input_us
    out = []
    t = t0_us
    for _ in range(n_steps):
        t += gap_us
        if input_us:
            out.append({"tensor": "_step", "ph": "X", "ts": t - input_us,
                        "dur": input_us, "name": "STEP_INPUT"})
        out.append({"tensor": "_step", "ph": "X", "ts": t,
                    "dur": compute_us, "name": "STEP_COMPUTE"})
        t += compute_us
    return out


class TestBoundVerdicts:
    """Tentpole: per-rank and run-level input-bound vs compute-bound vs
    comm-bound verdicts from the STEP_* attribution spans."""

    def _cluster(self, tmp_path, input_us, compute_us, neg_us,
                 n_groups=5):
        world = 2
        for rank in range(world):
            events = []
            for g in range(n_groups):
                events += _collective_events(
                    f"t.{g}", group=g, arrival_us=g * 50 * MS,
                    neg_dur_us=neg_us)
            events += _step_spans(input_us, compute_us, n_groups)
            _write_trace(tmp_path / f"v.{rank}.json", rank, world,
                         events, start_mono_us=0, offset_us=0.0)
        traces = trace_tool.load_traces([str(tmp_path / "v.{rank}.json")])
        return trace_tool.analyze(traces)

    def test_input_dominated_run_is_input_bound(self, tmp_path):
        report = self._cluster(tmp_path, input_us=40 * MS,
                               compute_us=5 * MS, neg_us=100)
        assert report["bound"] == "input-bound"
        for r in ("0", "1"):
            assert report["per_rank"][r]["verdict"] == "input-bound"
            assert report["per_rank"][r]["phase_share"]["input"] > 0.5

    def test_compute_dominated_run_is_compute_bound(self, tmp_path):
        report = self._cluster(tmp_path, input_us=100,
                               compute_us=40 * MS, neg_us=100)
        assert report["bound"] == "compute-bound"
        assert report["per_rank"]["0"]["verdict"] == "compute-bound"

    def test_comm_dominated_run_is_comm_bound(self, tmp_path):
        # Long negotiate waits (a straggler fleet) dwarf input+compute.
        report = self._cluster(tmp_path, input_us=100,
                               compute_us=1 * MS, neg_us=60 * MS)
        assert report["bound"] == "comm-bound"
        assert report["per_rank"]["0"]["verdict"] == "comm-bound"
        assert report["fleet_share"]["comm"] > 0.5

    def test_no_step_spans_means_no_run_verdict(self, tmp_path):
        """Without StepTimer instrumentation the trace only contains
        collective spans — claiming comm-bound would be vacuous."""
        world = 2
        for rank in range(world):
            events = []
            for g in range(4):
                events += _collective_events(
                    f"t.{g}", group=g, arrival_us=g * 10 * MS,
                    neg_dur_us=100)
            _write_trace(tmp_path / f"n.{rank}.json", rank, world,
                         events, start_mono_us=0, offset_us=0.0)
        traces = trace_tool.load_traces([str(tmp_path / "n.{rank}.json")])
        report = trace_tool.analyze(traces)
        assert report["bound"] is None
        assert report["fleet_share"] is None

    def test_deviation_verdict_without_step_spans(self, tmp_path):
        """An execute-heavy rank still gets a comm-bound verdict from
        the deviation attribution even without step spans."""
        world = 2
        for rank in range(world):
            events = []
            for g in range(6):
                events += _collective_events(
                    f"t.{g}", group=g, arrival_us=g * 10 * MS,
                    neg_dur_us=100,
                    exec_dur_us=(40 * MS if rank == 1 else 500))
            _write_trace(tmp_path / f"d.{rank}.json", rank, world,
                         events, start_mono_us=0, offset_us=0.0)
        traces = trace_tool.load_traces([str(tmp_path / "d.{rank}.json")])
        report = trace_tool.analyze(traces)
        assert report["per_rank"]["1"]["verdict"] == "comm-bound"
        # Report renders the verdict column.
        assert "comm-bound" in trace_tool.format_report(report)


class TestHistogramPercentiles:
    """Satellite: p50/p90/p99 estimation from log-bucketed snapshots,
    exact to within one bucket width, shared by the trace report and the
    Prometheus endpoint's JSON view."""

    def _assert_within_bucket_width(self, est, exact):
        # The containing bucket's width bounds the interpolation error.
        bounds = [0.0] + list(LATENCY_BUCKETS)
        for lo, hi in zip(bounds[:-1], bounds[1:]):
            if lo <= exact <= hi:
                assert abs(est - exact) <= (hi - lo) + 1e-12, \
                    (est, exact, lo, hi)
                return
        assert est <= LATENCY_BUCKETS[-1]   # beyond the finite range

    @pytest.mark.parametrize("dist", ["uniform", "lognormal", "bimodal"])
    def test_against_exact_percentiles(self, dist):
        rng = np.random.RandomState(7)
        if dist == "uniform":
            samples = rng.uniform(1e-4, 5e-2, 4000)
        elif dist == "lognormal":
            samples = np.exp(rng.normal(-7.0, 1.5, 4000))
        else:
            # Modes sized so no tested percentile lands exactly on the
            # inter-mode mass boundary (where any bucket estimator and
            # sample interpolation legitimately diverge by the gap).
            samples = np.concatenate([rng.uniform(1e-5, 2e-5, 1800),
                                      rng.uniform(1e-2, 2e-2, 2200)])
        h = Histogram(LATENCY_BUCKETS)
        for v in samples:
            h.observe(float(v))
        pct = histogram_percentiles(h.snapshot(), qs=(0.5, 0.9, 0.99))
        for q, key in [(50, "p50"), (90, "p90"), (99, "p99")]:
            self._assert_within_bucket_width(
                pct[key], float(np.percentile(samples, q)))

    def test_json_safe_plus_inf_buckets(self):
        """The endpoint path feeds snapshots whose +Inf bound became the
        string "+Inf" (strict JSON); the estimator must accept them."""
        h = Histogram(LATENCY_BUCKETS)
        for v in [1e-3] * 10:
            h.observe(v)
        snap = h.snapshot()
        snap["buckets"] = [["+Inf" if b[0] == float("inf") else b[0], b[1]]
                           for b in snap["buckets"]]
        pct = histogram_percentiles(snap)
        self._assert_within_bucket_width(pct["p50"], 1e-3)

    def test_empty_histogram(self):
        assert histogram_percentiles({"buckets": [], "count": 0}) == {}

    def test_overflow_bucket_returns_top_bound(self):
        h = Histogram([1e-3, 1e-2])
        for v in [5.0] * 8:     # all beyond the finite bounds
            h.observe(v)
        pct = histogram_percentiles(h.snapshot(), qs=(0.5,))
        assert pct["p50"] == 1e-2
