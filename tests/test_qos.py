"""Multi-tenant QoS plane (docs/serving.md#qos): DWRR class queues,
reserved batch slots, deadline-aware predictive shedding, token-rate
quotas with drain-rate Retry-After, and the autoscaler's hysteresis
state machine. The fleet-level scale-up/scale-down e2e lives in
test_fleet_e2e.py (slow tier)."""

import json

import pytest

import jax
import jax.numpy as jnp

from horovod_tpu.models import transformer as tfm
from horovod_tpu.parallel.mesh import create_mesh
from horovod_tpu.serving import (InferenceEngine, QuotaExceededError,
                                 ServingConfig)
from horovod_tpu.serving import loadgen
from horovod_tpu.serving import qos
from horovod_tpu.serving import slo as _slo


class _Item:
    def __init__(self, name, qos_class=None):
        self.name = name
        if qos_class is not None:
            self.qos_class = qos_class

    def __repr__(self):
        return f"_Item({self.name})"


def _loaded_queues(weights=None, per_class=50):
    q = qos.ClassQueues(weights)
    for c in qos.PRIORITY_CLASSES:
        for i in range(per_class):
            q.append(_Item(f"{c}{i}"), qos_class=c)
    return q


class TestClassQueues:
    def test_weight_proportionality_under_saturation(self):
        """Deep backlogs in every class: admissions converge to the
        exact weight ratio (4:2:1 -> 40/20/10 over 70 picks)."""
        q = _loaded_queues({"interactive": 4, "default": 2, "bulk": 1})
        picks = {c: 0 for c in qos.PRIORITY_CLASSES}
        for _ in range(70):
            req = q.select()
            assert req is not None
            picks[req.qos_class] += 1
        assert picks == {"interactive": 40, "default": 20, "bulk": 10}

    def test_no_starvation(self):
        """Any backlogged class with weight > 0 is served within one
        replenish round — bulk appears in the first weight-sum picks."""
        q = _loaded_queues({"interactive": 4, "default": 2, "bulk": 1})
        first = [q.select().qos_class for _ in range(7)]
        assert "bulk" in first and "default" in first

    def test_fractional_weights_do_not_stall(self):
        q = qos.ClassQueues({"interactive": 0.4, "default": 0.2,
                             "bulk": 0.1})
        q.append(_Item("b0"), qos_class="bulk")
        req = q.select()
        assert req is not None and req.qos_class == "bulk"

    def test_fifo_within_class(self):
        q = qos.ClassQueues()
        for i in range(3):
            q.append(_Item(f"d{i}"), qos_class="default")
        assert [q.select().name for _ in range(3)] == \
            ["d0", "d1", "d2"]

    def test_allowed_predicate_filters_classes(self):
        q = _loaded_queues(per_class=2)
        only_top = q.select(lambda c: c == qos.TOP_CLASS)
        assert only_top.qos_class == "interactive"
        none = q.select(lambda c: False)
        assert none is None
        assert len(q) == 5   # nothing popped by the refused select

    def test_pushback_restores_head_and_deficit(self):
        q = qos.ClassQueues()
        q.append(_Item("a"), qos_class="default")
        q.append(_Item("b"), qos_class="default")
        first = q.select()
        assert first.name == "a"
        q.pushback(first)
        assert q.select().name == "a"   # back at the head, not the tail

    def test_remove_and_len_and_iter(self):
        q = _loaded_queues(per_class=1)
        assert len(q) == 3 and bool(q)
        victim = q.heads()[-1]
        assert q.remove(victim) is True
        assert q.remove(victim) is False
        assert len(q) == 2
        assert [getattr(r, "name") for r in q] == \
            ["interactive0", "default0"]

    def test_reserved_slot_simulation_bulk_cannot_squeeze_top(self):
        """The engine's _admit predicate over a full bulk backlog:
        non-top occupancy never exceeds slots - reserved, and an
        interactive arrival is admitted immediately even when bulk
        queued first."""
        slots, reserved = 4, 2
        q = qos.ClassQueues()
        for i in range(16):
            q.append(_Item(f"b{i}"), qos_class="bulk")
        active = []
        while len(active) < slots:
            non_top = sum(1 for r in active
                          if r.qos_class != qos.TOP_CLASS)
            req = q.select(
                lambda c, n=non_top: c == qos.TOP_CLASS
                or n < slots - reserved)
            if req is None:
                break
            active.append(req)
        assert len(active) == 2   # bulk stops at the reservation line
        q.append(_Item("vip"), qos_class="interactive")
        non_top = sum(1 for r in active
                      if r.qos_class != qos.TOP_CLASS)
        req = q.select(lambda c, n=non_top: c == qos.TOP_CLASS
                       or n < slots - reserved)
        assert req is not None and req.qos_class == "interactive"


class TestQosPolicy:
    def test_config_rows_parse_and_default(self, tmp_path):
        p = tmp_path / "slo.json"
        p.write_text(json.dumps({"tenants": {
            "vip": {"priority": "interactive", "weight": 8,
                    "ttft_ms": 100},
            "batch": {"priority": "bulk",
                      "quota_tokens_per_s": 500},
            "plain": {"ttft_ms": 200},
        }, "default": {"priority": "default", "weight": 3}}))
        pol = qos.QosPolicy(str(p))
        assert pol.class_of("vip") == "interactive"
        assert pol.spec_of("vip").weight == 8.0
        assert pol.class_of("batch") == "bulk"
        assert pol.quota_of("batch") == 500.0
        assert pol.spec_of("batch").weight == \
            qos.DEFAULT_WEIGHTS["bulk"]
        # A row with no QoS fields rides the default spec.
        assert pol.class_of("plain") == "default"
        assert pol.spec_of("plain").weight == 3.0
        assert pol.class_of(None) == "default"
        w = pol.class_weights()
        assert w["interactive"] == 8.0 and w["bulk"] == 1.0

    def test_malformed_file_degrades_to_default(self, tmp_path):
        p = tmp_path / "slo.json"
        p.write_text("{nope")
        pol = qos.QosPolicy(str(p))
        assert pol.class_of("anyone") == "default"

    def test_unknown_priority_rejected(self):
        with pytest.raises(ValueError):
            qos.TenantQos(priority="platinum")
        with pytest.raises(ValueError):
            qos.TenantQos(weight=0)

    def test_slo_policy_strips_qos_fields(self, tmp_path,
                                          monkeypatch):
        """The two planes share one config file: QoS fields must not
        invalidate SLO parsing, and SLO targets still resolve."""
        p = tmp_path / "slo.json"
        p.write_text(json.dumps({"tenants": {
            "vip": {"priority": "interactive", "weight": 8,
                    "ttft_ms": 123}}}))
        monkeypatch.delenv("HOROVOD_TPU_SLO_TTFT_MS", raising=False)
        monkeypatch.delenv("HOROVOD_TPU_SLO_TPOT_MS", raising=False)
        sp = _slo.SloPolicy(str(p))
        t = sp.resolve("vip")
        assert t is not None and t.ttft_ms == 123.0


class TestPredictiveShed:
    BUCKETS = {8: 0.010, 16: 0.022}

    @staticmethod
    def _bucket(n):
        b = 8
        while b < n:
            b *= 2
        return b

    def test_measured_bucket_and_fallback(self):
        f = qos.predict_prefill_s
        assert f(6, self.BUCKETS, self._bucket) == 0.010
        assert f(12, self.BUCKETS, self._bucket) == 0.022
        # Unmeasured 32-bucket: largest measured scaled by ratio.
        assert f(30, self.BUCKETS, self._bucket) == \
            pytest.approx(0.044)
        assert f(30, {}, self._bucket) == 0.0
        assert f(0, self.BUCKETS, self._bucket) == 0.0

    def test_chunked_path_multiplies_chunks(self):
        got = qos.predict_prefill_s(40, self.BUCKETS, self._bucket,
                                    chunk_tokens=16)
        assert got == pytest.approx(3 * 0.022)

    def test_shed_decision_semantics(self):
        # Cannot make it: remaining < prefill + decode budget.
        assert qos.shed_decision(0.02, 0.05, 0.01) is True
        assert qos.shed_decision(0.10, 0.05, 0.01) is False
        # No measurements yet -> never shed on a guess.
        assert qos.shed_decision(-5.0, 0.0, 0.0) is False


class TestQuotaLedger:
    def _policy(self, tmp_path, quota=100, priority="default"):
        p = tmp_path / "slo.json"
        p.write_text(json.dumps({"tenants": {
            "t": {"priority": priority,
                  "quota_tokens_per_s": quota}}}))
        return qos.QosPolicy(str(p))

    def test_burst_admits_then_rejects(self, tmp_path):
        led = qos.QuotaLedger(self._policy(tmp_path, quota=100))
        # Burst = 2s of rate = 200 tokens.
        assert led.admit("t", 150, now=0.0) is None
        assert led.admit("t", 50, now=0.0) is None
        retry = led.admit("t", 50, now=0.0)
        assert retry is not None and retry >= 1
        # Refill restores admission.
        assert led.admit("t", 50, now=1.0) is None

    def test_no_quota_tenant_always_admitted(self, tmp_path):
        led = qos.QuotaLedger(self._policy(tmp_path))
        assert led.admit("unknown", 10**9, now=0.0) is None
        assert led.admit(None, 10**9, now=0.0) is None

    def test_rejection_does_not_burn_tokens(self, tmp_path):
        led = qos.QuotaLedger(self._policy(tmp_path, quota=100))
        assert led.admit("t", 200, now=0.0) is None   # drain burst
        assert led.admit("t", 150, now=0.0) is not None
        # The failed take deducted nothing: 1s refill = 100 tokens.
        assert led.admit("t", 100, now=1.0) is None

    def test_retry_after_uses_measured_drain_rate(self, tmp_path):
        """ACCEPTANCE (satellite): Retry-After = deficit over the
        tenant's own completion rate, not the quota rate."""
        led = qos.QuotaLedger(self._policy(tmp_path, quota=100))
        # 10s window, 500 tokens completed -> ~50 tokens/s measured.
        for i in range(10):
            led.note_completion("t", 50, now=float(i))
        rate = led.drain_rate("t", now=10.0)
        assert rate == pytest.approx(500 / 10.0, rel=0.15)
        got = led.retry_after_s("t", deficit=100.0, now=10.0)
        assert got == int(-(-100.0 // rate))   # ceil(deficit/measured)
        # Fallback with no completions: the quota rate.
        led2 = qos.QuotaLedger(self._policy(tmp_path, quota=100))
        assert led2.retry_after_s("t", deficit=100.0, now=0.0) == 1

    def test_retry_after_clamps_floor_and_cap(self, tmp_path):
        bulk = qos.QuotaLedger(
            self._policy(tmp_path, quota=1000, priority="bulk"))
        # Tiny deficit still honors the bulk back-off floor.
        assert bulk.retry_after_s("t", deficit=1.0, now=0.0) == \
            qos.RETRY_AFTER_FLOOR_S["bulk"]
        slow = qos.QuotaLedger(self._policy(tmp_path, quota=1))
        assert slow.retry_after_s("t", deficit=10**6, now=0.0) == \
            qos.RETRY_AFTER_CAP_S

    def test_drain_window_expires(self, tmp_path):
        led = qos.QuotaLedger(self._policy(tmp_path, quota=100))
        led.note_completion("t", 100, now=0.0)
        assert led.drain_rate("t", now=1.0) is not None
        assert led.drain_rate("t", now=100.0) is None


class TestAutoscalerState:
    CFG = dict(high_load=1.5, low_load=0.25, sustain_s=3.0,
               cooldown_s=10.0, alert_hold_s=5.0)

    def _state(self, **over):
        kw = dict(self.CFG)
        kw.update(over)
        return qos.AutoscalerState(qos.AutoscalerConfig(2, 4, **kw))

    def test_up_needs_sustained_pressure(self):
        s = self._state()
        assert s.observe(0.0, 2, 2.0) is None
        assert s.observe(2.0, 2, 2.0) is None      # < sustain_s
        d = s.observe(3.5, 2, 2.0)
        assert d == {"direction": "up", "why": "queue_depth", "n": 3}
        # Clock reset: the next up needs a fresh sustain window.
        assert s.observe(4.0, 3, 2.0) is None

    def test_pressure_blip_resets_sustain(self):
        s = self._state()
        assert s.observe(0.0, 2, 2.0) is None
        assert s.observe(1.0, 2, 1.0) is None      # pressure cleared
        assert s.observe(2.0, 2, 2.0) is None
        assert s.observe(4.9, 2, 2.0) is None      # only 2.9s sustained
        assert s.observe(5.1, 2, 2.0) is not None

    def test_up_clamps_at_max(self):
        s = self._state()
        s.observe(0.0, 4, 2.0)
        assert s.observe(10.0, 4, 2.0) is None

    def test_down_needs_cooldown_and_respects_min(self):
        s = self._state()
        assert s.observe(0.0, 3, 0.1) is None
        assert s.observe(9.0, 3, 0.1) is None
        d = s.observe(10.5, 3, 0.1)
        assert d == {"direction": "down", "why": "recovered", "n": 2}
        s2 = self._state()
        s2.observe(0.0, 2, 0.1)
        assert s2.observe(100.0, 2, 0.1) is None   # at the floor

    def test_midband_load_resets_both_clocks(self):
        s = self._state()
        s.observe(0.0, 3, 0.1)
        assert s.observe(5.0, 3, 1.0) is None      # between thresholds
        assert s.observe(11.0, 3, 0.1) is None     # cooldown restarted

    def test_alert_hold_outranks_load(self):
        s = self._state()
        s.note_alert("queue_depth_runaway", 0.0)
        assert s.observe(0.0, 2, 0.0) is None
        d = s.observe(3.5, 2, 0.0)
        assert d is not None and d["why"] == "queue_runaway"
        # Hold expired: low load is low load again.
        s2 = self._state()
        s2.note_alert("queue_depth_runaway", 0.0)
        assert s2.observe(6.0, 2, 0.0) is None

    def test_retry_pressure_and_ttft_trend_reasons(self):
        s = self._state()
        s.observe(0.0, 2, 0.0, retry_pressure=2.0)
        d = s.observe(3.5, 2, 0.0, retry_pressure=2.0)
        assert d is not None and d["why"] == "retry_pressure"
        s2 = self._state(ttft_target_ms=500.0)
        s2.observe(0.0, 2, 0.0, ttft_p99_ms=900.0)
        d2 = s2.observe(3.5, 2, 0.0, ttft_p99_ms=900.0)
        assert d2 is not None and d2["why"] == "ttft_trend"

    def test_config_validation(self):
        with pytest.raises(ValueError):
            qos.AutoscalerConfig(0, 2)
        with pytest.raises(ValueError):
            qos.AutoscalerConfig(3, 2)


class _FakeFleet:
    def __init__(self, n=2):
        self.n = n
        self.calls = []

    def live_count(self):
        return self.n

    def load_views(self):
        return [{"active": 2, "queue_depth": 6, "slots": 2}
                for _ in range(self.n)]

    def scale_to(self, n):
        self.calls.append(n)
        self.n = n


class TestFleetAutoscaler:
    def test_tick_applies_decision_and_records(self):
        fleet = _FakeFleet(2)
        a = qos.FleetAutoscaler(
            fleet, qos.AutoscalerConfig(2, 4, sustain_s=1.0))
        assert a.tick(now=0.0) is None
        d = a.tick(now=1.5)
        assert d is not None and d["direction"] == "up"
        assert fleet.calls == [3]
        assert a.decisions == [d]

    def test_signal_source_injection(self):
        fleet = _FakeFleet(2)
        sig = {"load_per_slot": 0.0, "n_replicas": 2}
        a = qos.FleetAutoscaler(
            fleet, qos.AutoscalerConfig(2, 4, sustain_s=1.0),
            signals=lambda: sig)
        assert a.tick(now=0.0) is None
        assert a.tick(now=5.0) is None   # injected load is calm
        sig["load_per_slot"] = 9.0
        assert a.tick(now=6.0) is None
        assert a.tick(now=7.5)["direction"] == "up"


@pytest.fixture(scope="module")
def model():
    cfg = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4,
                                n_layers=2, d_ff=64, max_seq=64,
                                dtype=jnp.float32, remat=False)
    params = tfm.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture(scope="module")
def mesh1():
    return create_mesh(devices=jax.devices()[:1], tp=1)


@pytest.fixture
def qos_config(tmp_path, monkeypatch):
    p = tmp_path / "slo.json"
    p.write_text(json.dumps({"tenants": {
        "vip": {"priority": "interactive", "weight": 4},
        "batch": {"priority": "bulk", "weight": 1},
        "capped": {"priority": "default",
                   "quota_tokens_per_s": 20}}}))
    monkeypatch.setenv("HOROVOD_TPU_SLO_CONFIG", str(p))
    qos._reset_policy()
    _slo._reset_policy()
    _slo._reset_tenants()
    yield str(p)
    qos._reset_policy()
    _slo._reset_policy()
    _slo._reset_tenants()


def _engine(params, cfg, mesh, **over):
    kw = dict(block_size=4, kv_blocks=40, max_batch_slots=4,
              max_queue=16, max_new_tokens=8, min_prefill_bucket=8)
    kw.update(over)
    return InferenceEngine(params, cfg, mesh, ServingConfig(**kw))


class TestEngineQos:
    def test_reserved_slots_validation(self, model, mesh1):
        cfg, params = model
        with pytest.raises(ValueError):
            _engine(params, cfg, mesh1, reserved_slots=4)

    def test_reserved_slot_invariant_under_bulk_backlog(
            self, model, mesh1, qos_config):
        """ACCEPTANCE (tentpole): with 2 of 4 slots reserved, a deep
        bulk backlog occupies at most 2 slots, and interactive
        arrivals land in the reserve immediately."""
        cfg, params = model
        eng = _engine(params, cfg, mesh1, reserved_slots=2)
        for _ in range(8):
            eng.submit([1, 2, 3], max_new_tokens=6, tenant="batch")
        eng.step()
        counts = eng.class_counts()
        assert counts["bulk"]["active"] == 2
        assert counts["interactive"]["active"] == 0
        vips = [eng.submit([4, 5, 6], max_new_tokens=6, tenant="vip")
                for _ in range(2)]
        eng.step()
        counts = eng.class_counts()
        assert counts["interactive"]["active"] == 2
        assert counts["bulk"]["active"] == 2
        # Run to completion: nobody deadlocks under the reservation.
        for _ in range(200):
            if all(r.done for r in vips):
                break
            eng.step()
        assert all(r.status == "completed" for r in vips)

    def test_dwrr_admission_prefers_interactive(self, model, mesh1,
                                                qos_config):
        """Mixed backlog, no reservation: DWRR admits interactive
        ahead of an earlier-queued equal-length bulk run."""
        cfg, params = model
        eng = _engine(params, cfg, mesh1, max_batch_slots=2)
        bulk = [eng.submit([1, 2], max_new_tokens=4, tenant="batch")
                for _ in range(4)]
        vip = eng.submit([3, 4], max_new_tokens=4, tenant="vip")
        eng.step()
        counts = eng.class_counts()
        assert counts["interactive"]["active"] == 1, counts
        for _ in range(300):
            if all(r.done for r in bulk + [vip]):
                break
            eng.step()
        assert all(r.status == "completed" for r in bulk + [vip])

    def test_quota_429_with_retry_after(self, model, mesh1,
                                        qos_config):
        cfg, params = model
        eng = _engine(params, cfg, mesh1)
        # quota 20 tok/s, burst 40: prompt 3 + max_new 8 = 11 each.
        eng.submit([1, 2, 3], max_new_tokens=8, tenant="capped")
        eng.submit([1, 2, 3], max_new_tokens=8, tenant="capped")
        eng.submit([1, 2, 3], max_new_tokens=8, tenant="capped")
        with pytest.raises(QuotaExceededError) as ei:
            eng.submit([1, 2, 3], max_new_tokens=8, tenant="capped")
        assert ei.value.retry_after_s >= 1
        # Unquota'd tenants are untouched.
        eng.submit([1, 2, 3], max_new_tokens=8, tenant="vip")

    def test_predictive_shed_fails_hopeless_deadline(
            self, model, mesh1, qos_config):
        """Once the prefill EWMA warms up, a queued request whose
        deadline cannot cover prefill + one decode step is shed at
        admission with the 504 error (counted reason=shed)."""
        cfg, params = model
        eng = _engine(params, cfg, mesh1)
        warm = eng.submit([1] * 6, max_new_tokens=2)
        for _ in range(60):
            if warm.done:
                break
            eng.step()
        assert warm.status == "completed"
        # A second warm run: the EWMA skips compile runs, so it only
        # records once the bucket recompiles nothing.
        warm2 = eng.submit([2] * 6, max_new_tokens=2)
        for _ in range(60):
            if warm2.done:
                break
            eng.step()
        assert eng._prefill_cost, "prefill EWMA did not warm up"
        doomed = eng.submit([3] * 6, max_new_tokens=2,
                            deadline_s=1e-9, tenant="vip")
        eng.step()
        assert doomed.done
        assert doomed.status == "failed"
        assert doomed.shed or "deadline" in (doomed.error or "")


class TestLoadgenQos:
    def test_priority_field_omitted_when_none(self):
        """Checksum stability: pre-QoS schedules serialize byte-
        identically — priority only appears when set."""
        spec = loadgen.TenantSpec("t")
        arr = loadgen.Arrival(t_s=0.1, tenant="t", tokens=(1, 2),
                              max_new_tokens=4)
        assert "priority" not in spec.to_dict()
        assert "priority" not in arr.to_dict()
        tagged = loadgen.TenantSpec("t", priority="bulk")
        assert tagged.to_dict()["priority"] == "bulk"

    def test_schedule_roundtrip_preserves_priority(self, tmp_path):
        sched = loadgen.build_schedule(
            10.0, 1.0, 7, [loadgen.TenantSpec("t", priority="bulk")])
        assert all(a.priority == "bulk" for a in sched)
        path = tmp_path / "sched.jsonl"
        loadgen.save_schedule(sched, str(path))
        back = loadgen.load_schedule(str(path))
        assert loadgen.schedule_checksum(back) == \
            loadgen.schedule_checksum(sched)
        assert back[0].priority == "bulk"

    def test_summarize_by_class(self):
        run = {
            "offered": 4, "sent": 4, "dropped": 0,
            "results": [
                {"tenant": "a", "status": "completed",
                 "priority": "interactive", "ttft_ms": 5.0},
                {"tenant": "a", "status": "completed",
                 "priority": "interactive", "ttft_ms": 7.0},
                {"tenant": "b", "status": "rejected",
                 "priority": "bulk"},
                {"tenant": "b", "status": "completed",
                 "priority": "bulk", "ttft_ms": 50.0},
            ]}
        s = loadgen.summarize(run)
        assert s["by_class"]["interactive"]["completed"] == 2
        assert s["by_class"]["bulk"]["rejected"] == 1
        assert s["by_class"]["bulk"]["goodput_frac"] == 0.5

    def test_summarize_classes_mapping_overrides(self):
        run = {"offered": 1, "sent": 1, "dropped": 0,
               "results": [{"tenant": "a", "status": "completed"}]}
        s = loadgen.summarize(run, classes={"a": "interactive"})
        assert s["by_class"]["interactive"]["completed"] == 1
        assert loadgen.summarize(run).get("by_class") is None
