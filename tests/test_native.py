"""Native runtime core tests — exercise the C++ control plane directly via
the ctypes surface: wire protocol round-trips (mpi_message parity, N2),
ConstructResponse mismatch diagnostics (operations.cc:321-523), fp16
software conversion (half.{h,cc}, N8), and knob plumbing."""

import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.runtime import native

# Wire enums (runtime/src/common.h / message.h).
ALLREDUCE, ALLGATHER, BROADCAST, ERROR = 0, 1, 2, 3
F32 = 7


@pytest.fixture(scope="module")
def core():
    c = native.load(required=True)
    assert c is not None
    return c


class TestWire:
    def test_request_list_roundtrip(self, core):
        """Serialize → parse → serialize must be byte-identical
        (mpi_message.cc:134-230 SerializeToString/ParseFromBytes parity)."""
        reqs = b"".join([
            core.wire_make_request(r, ALLREDUCE, F32, f"grad/layer{r}",
                                   -1, -1, [17, 17]) for r in range(4)])
        # Wrap into a RequestList by hand: shutdown=0, count=4.
        import struct
        payload = struct.pack("<ii", 0, 4) + reqs
        out = core.wire_roundtrip_request_list(payload)
        assert out == payload

    def test_request_fields_survive(self, core):
        a = core.wire_make_request(3, BROADCAST, F32, "weights", 2, 5,
                                   [8, 4, 2])
        b = core.wire_make_request(3, BROADCAST, F32, "weights", 2, 5,
                                   [8, 4, 2])
        assert a == b
        c = core.wire_make_request(3, BROADCAST, F32, "weights", 1, 5,
                                   [8, 4, 2])
        assert a != c


class TestNegotiation:
    def _reqs(self, core, shapes, op=ALLREDUCE, dtypes=None, roots=None):
        dtypes = dtypes or [F32] * len(shapes)
        roots = roots or [-1] * len(shapes)
        ops = op if isinstance(op, list) else [op] * len(shapes)
        return b"".join([
            core.wire_make_request(r, ops[r], dtypes[r], "t", roots[r], -1,
                                   list(shapes[r]))
            for r in range(len(shapes))])

    def test_consistent_allreduce_ok(self, core):
        data = self._reqs(core, [[17, 17]] * 4)
        rtype, err, _ = core.negotiate(data, 4, 4)
        assert rtype == ALLREDUCE and err == ""

    def test_mismatched_shape_diagnosed(self, core):
        """Shape disagreement produces the reference's diagnostic instead of
        a deadlock (operations.cc:378-396; test_tensorflow.py:265-333)."""
        data = self._reqs(core, [[17, 17], [17, 17], [17, 18], [17, 17]])
        rtype, err, _ = core.negotiate(data, 4, 4)
        assert rtype == ERROR
        assert "Mismatched allreduce tensor shapes" in err

    def test_mismatched_dtype_diagnosed(self, core):
        data = self._reqs(core, [[4], [4]], dtypes=[F32, 5])
        rtype, err, _ = core.negotiate(data, 2, 2)
        assert rtype == ERROR and "Mismatched data types" in err

    def test_mismatched_op_diagnosed(self, core):
        data = self._reqs(core, [[4], [4]], op=[ALLREDUCE, ALLGATHER])
        rtype, err, _ = core.negotiate(data, 2, 2)
        assert rtype == ERROR and "Mismatched collective operations" in err

    def test_mismatched_root_diagnosed(self, core):
        data = self._reqs(core, [[4], [4]], op=BROADCAST, roots=[0, 1])
        rtype, err, _ = core.negotiate(data, 2, 2)
        assert rtype == ERROR and "Mismatched root ranks" in err

    def test_partial_submission_diagnosed(self, core):
        """Fewer submissions than world size (operations.cc:341 precheck)."""
        data = self._reqs(core, [[4], [4]])
        rtype, err, _ = core.negotiate(data, 2, 4)
        assert rtype == ERROR and "Only 2 out of 4" in err

    def test_allgather_sizes_collected(self, core):
        data = b"".join([
            core.wire_make_request(r, ALLGATHER, F32, "t", -1, -1, [r + 1, 3])
            for r in range(4)])
        rtype, err, sizes = core.negotiate(data, 4, 4)
        assert rtype == ALLGATHER and err == ""
        assert sizes == [1, 2, 3, 4]

    def test_allgather_trailing_dim_mismatch(self, core):
        data = b"".join([
            core.wire_make_request(0, ALLGATHER, F32, "t", -1, -1, [2, 3]),
            core.wire_make_request(1, ALLGATHER, F32, "t", -1, -1, [2, 4])])
        rtype, err, _ = core.negotiate(data, 2, 2)
        assert rtype == ERROR and "Mismatched allgather tensor shapes" in err


class TestHalf:
    def test_roundtrip_exact_halves(self, core):
        vals = np.array([0.0, 1.0, -1.5, 0.5, 65504.0, -65504.0], np.float32)
        bits = core.float_to_half(vals)
        back = core.half_to_float(bits)
        assert np.array_equal(vals, back)

    def test_matches_numpy_float16(self, core):
        rng = np.random.RandomState(7)
        vals = rng.uniform(-1000, 1000, size=1024).astype(np.float32)
        bits = core.float_to_half(vals)
        expected = vals.astype(np.float16).view(np.uint16)
        assert np.array_equal(bits, expected)
        back = core.half_to_float(bits)
        assert np.array_equal(back, vals.astype(np.float16).astype(np.float32))

    def test_special_values(self, core):
        vals = np.array([np.inf, -np.inf, np.nan, 1e10, -1e10, 1e-10],
                        np.float32)
        bits = core.float_to_half(vals)
        expected = vals.astype(np.float16)
        back = core.half_to_float(bits)
        assert np.isinf(back[0]) and back[0] > 0
        assert np.isinf(back[1]) and back[1] < 0
        assert np.isnan(back[2])
        assert np.array_equal(back[3:], expected[3:].astype(np.float32))

    def test_halfsum(self, core):
        """float16_sum MPI-op parity (half.cc:42-90)."""
        a = np.array([1.5, 2.5, -3.0], np.float16)
        b = np.array([0.5, 0.25, 1.0], np.float16)
        dst = a.view(np.uint16).copy()
        core.halfsum(b.view(np.uint16).copy(), dst)
        assert np.array_equal(dst.view(np.float16), a + b)


class TestKnobs:
    def test_fusion_threshold_roundtrip(self, core):
        # engine must be initialized (session fixture ran collectives)
        import jax.numpy as jnp
        hvd.allreduce(jnp.ones((2,)))  # force native init
        old = core.fusion_threshold
        try:
            core.fusion_threshold = 1234567
            assert core.fusion_threshold == 1234567
        finally:
            core.fusion_threshold = old

    def test_cycle_time_roundtrip(self, core):
        import jax.numpy as jnp
        hvd.allreduce(jnp.ones((2,)))
        old = core.cycle_time_ms
        try:
            core.cycle_time_ms = 7.5
            assert abs(core.cycle_time_ms - 7.5) < 1e-9
        finally:
            core.cycle_time_ms = old


class TestPyWireMirror:
    """ops/wire_format.py must be byte-exact against the native codec —
    it is the wire for processes without the toolchain (mixed fleets)."""

    def test_request_list_encoding_matches_native(self, core):
        from horovod_tpu.ops import wire_format as wf
        dicts = [
            {"name": "grad/a", "op": ALLREDUCE, "dtype": "float32",
             "shape": (17, 17)},
            {"name": "gath", "op": ALLGATHER, "dtype": "bfloat16",
             "shape": (3, 5)},
            {"name": "bc", "op": BROADCAST, "dtype": "int64",
             "shape": (2,), "root_rank": 3},
        ]
        py = wf.encode_request_list(2, dicts)
        # The native parser must accept it and re-serialize identically.
        assert core.wire_roundtrip_request_list(py) == py
        # And decoding recovers the fields.
        back, shutdown = wf.decode_request_list(py)
        assert not shutdown
        assert [r["name"] for r in back] == ["grad/a", "gath", "bc"]
        assert back[0]["nbytes"] == 17 * 17 * 4
        assert back[1]["dtype"] == "bfloat16"
        assert back[2]["root_rank"] == 3

    def test_response_list_decoding_matches_native(self, core):
        """Encode a response list with the Python mirror, decode it, and
        cross-check against a native controller's serialization of the
        same plan."""
        from horovod_tpu.ops import wire_format as wf
        ctl = native.NativeController(core, 2, 4, 1 << 20, 1.0, 60.0,
                                      False, False, False)
        for rank in range(2):
            ctl.announce(wf.encode_request_list(rank, [
                {"name": "x", "op": ALLREDUCE, "dtype": "float32",
                 "shape": (4,)},
                {"name": "g", "op": ALLGATHER, "dtype": "float32",
                 "shape": (rank + 1, 3)},
            ]))
        # Planning is deferred until the announce stream is quiescent
        # (or the service's fetch-timeout valve fires); driving the
        # controller directly, cut the groups explicitly.
        ctl.plan()
        raw = ctl.fetch(0, 0)
        groups, shutdown = wf.decode_response_list(raw, 2)
        assert not shutdown
        assert [g["names"] for g in groups] == [["x"], ["g"]]
        assert groups[1]["sizes"]["g"] == [1, 2]
        # Python re-encoding of the same plan decodes identically.
        py = wf.encode_response_list(groups, False, 2)
        again, _ = wf.decode_response_list(py, 2)
        for a, b in zip(groups, again):
            assert a["names"] == b["names"]
            assert a["sizes"] == b["sizes"]
            assert a["flags"] == b["flags"]


class TestPlannerEquivalence:
    """The native controller (controller.cc) and the Python fallback
    planner (control_plane.py) must emit IDENTICAL fusion plans for the
    same request stream — one planner contract, two implementations
    (VERDICT r1 weak #6)."""

    def _drive(self, native_mode, stream, nproc=2):
        from horovod_tpu.ops.control_plane import (AnnounceRequest,
                                                   CoordinatorService,
                                                   FetchRequest)
        from horovod_tpu.runner.secret import make_secret_key
        svc = CoordinatorService(nproc=nproc, key=make_secret_key(),
                                 fusion_threshold=1024, native=native_mode)
        try:
            assert svc.native_active is native_mode
            aid = 0
            for rank, reqs in stream:
                aid += 1
                svc._handle(AnnounceRequest(rank, reqs, announce_id=aid),
                            None)
            # Let the announce stream go quiescent, then fetch with a
            # window long enough for the timeout valve (which plans past
            # the deliberately-partial entries in some streams).
            import time as _t
            from horovod_tpu.ops.control_plane import PLAN_DEBOUNCE_S
            _t.sleep(PLAN_DEBOUNCE_S * 2)
            resp = svc._handle(
                FetchRequest(0, 0, wait_s=PLAN_DEBOUNCE_S * 4), None)
            return [(g["op"], tuple(g["names"]),
                     {k: tuple(v) for k, v in (g.get("sizes") or {}).items()},
                     bool(g["error"]), g.get("flags", 0))
                    for g in resp.groups]
        finally:
            svc.shutdown()

    def test_identical_plans(self):
        def r(name, op=ALLREDUCE, dtype="float32", shape=(100,), root=-1):
            return {"name": name, "op": op, "dtype": dtype, "shape": shape,
                    "root_rank": root}

        # A gnarly stream: fusion-threshold overflow, mixed dtypes with
        # look-ahead, ragged allgather sizes, a broadcast, a shape
        # mismatch error, and interleaved announce order across ranks.
        stream = [
            (0, [r("a"), r("b"), r("i1", dtype="int32"), r("c")]),
            (1, [r("a"), r("b")]),
            (1, [r("i1", dtype="int32"), r("c")]),
            (0, [r("g1", op=ALLGATHER, shape=(2, 8)),
                 r("bc", op=BROADCAST, shape=(4,), root=1)]),
            (1, [r("g1", op=ALLGATHER, shape=(5, 8)),
                 r("bc", op=BROADCAST, shape=(4,), root=1)]),
            (0, [r("bad", shape=(3,))]),
            (1, [r("bad", shape=(4,))]),
            (0, [r("d", shape=(50,)), r("e", shape=(300,))]),
            (1, [r("d", shape=(50,)), r("e", shape=(300,))]),
        ]
        native_plan = self._drive(True, stream)
        python_plan = self._drive(False, stream)
        assert native_plan == python_plan
        # Sanity on the shared plan: fusion respected the 1024-byte
        # threshold with look-ahead over the whole quiescent stream —
        # a+b = 800 bytes, c (400) would overflow and spilled, d (200)
        # was pulled forward into the 1000-byte group.
        names = [set(g[1]) for g in native_plan]
        assert {"a", "b", "d"} in names
        assert all("c" not in s for s in names if "a" in s)

    def test_identical_plans_under_hierarchical_env(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_TPU_HIERARCHICAL_ALLGATHER", "1")

        def r(name, op=ALLGATHER, shape=(4, 4)):
            return {"name": name, "op": op, "dtype": "float32",
                    "shape": shape, "root_rank": -1}

        stream = [(0, [r("g")]), (1, [r("g")])]
        native_plan = self._drive(True, stream)
        python_plan = self._drive(False, stream)
        assert native_plan == python_plan
        from horovod_tpu.ops import wire_format as wf
        assert native_plan[0][4] & wf.FLAG_HIERARCHICAL_ALLGATHER
