"""All-ranks tracing overhead guard (slow tier) — the cross-rank trace
capture must stay out of the hot path: ``bench_engine.py --trace`` A/Bs
a 2-process fused-allreduce loop with per-rank tracing on vs off (the
same p25-of-per-step method as BENCH_METRICS: interleaved
alternating-order repeats toggled IN-process — separate jobs differ by
±5% job-to-job, swamping the budget — pooled per-step times, 25th
percentile) and this guard holds the step-time overhead under 3%,
regenerating ``BENCH_TRACE.json``.

One re-measure is allowed before failing — a shared CI box can stay
saturated through one window (the BENCH_METRICS precedent)."""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

BUDGET = 0.03


def _run_bench(out_path: str, rounds: int) -> dict:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "bench_engine.py"), "--trace",
         "--trace-rounds", str(rounds), "--out", out_path],
        capture_output=True, text=True, timeout=600, cwd=root)
    assert proc.returncode == 0, proc.stderr[-3000:]
    return json.loads(open(out_path).read())


def test_trace_overhead_under_3_percent(tmp_path):
    out = tmp_path / "bench_trace.json"
    result = _run_bench(str(out), rounds=6)
    if result["overhead_frac"] >= BUDGET:   # one re-measure
        result = _run_bench(str(out), rounds=6)

    # Regenerate the committed artifact from the accepted run.
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(root, "BENCH_TRACE.json"), "w") as f:
        json.dump(result, f, indent=2, sort_keys=True)
        f.write("\n")

    assert result["rows"]["tracing_on"]["step_time_ms"] > 0
    assert result["overhead_frac"] < BUDGET, (
        f"all-ranks tracing cost {result['overhead_frac']:.2%} of the "
        f"2-process step time (on "
        f"{result['rows']['tracing_on']['step_time_ms']} ms vs off "
        f"{result['rows']['tracing_off']['step_time_ms']} ms; "
        f"budget {BUDGET:.0%})")
