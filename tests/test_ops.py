"""Collective op correctness — structural mirror of
test/test_tensorflow.py (MPITests) and test/test_torch.py:

  - dtype × dimension sweeps asserting allreduce == tensor * size with
    size-dependent float thresholds (test_tensorflow.py:77-139),
  - fusion tests batching many ops at once (test_tensorflow.py:107-139),
  - allgather incl. variable first dims (test_tensorflow.py:406-510),
  - broadcast from every root (test_tensorflow.py:645-673),
  - error tests: duplicate names, mismatched shapes
    (test_torch.py duplicate-name test; test_tensorflow.py:265-333),
  - async handle poll/synchronize (test_torch.py).
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd

DTYPES = [np.uint8, np.int8, np.int32, np.int64, np.float16, np.float32,
          np.float64, "bfloat16"]
DIMS = [1, 2, 3]


def _threshold(dtype, size):
    # test_tensorflow.py:84-97: fp16 loose, fp32/64 tight w/ size scaling.
    if str(dtype) in ("float16", "bfloat16"):
        return size
    return size * 1e-4 if str(dtype) in ("float32",) else 1e-6 * size


def _rand(dtype, dim, seed=1234):
    rng = np.random.RandomState(seed)
    shape = [17] * dim
    if str(dtype) == "bfloat16":
        x = rng.uniform(-100, 100, size=shape).astype(np.float32)
        return jnp.asarray(x, dtype=jnp.bfloat16)
    if np.issubdtype(np.dtype(dtype), np.floating):
        return jnp.asarray(rng.uniform(-100, 100, size=shape).astype(dtype))
    return jnp.asarray(rng.randint(0, 100, size=shape).astype(dtype))


class TestAllreduce:
    @pytest.mark.parametrize("dtype", DTYPES)
    @pytest.mark.parametrize("dim", DIMS)
    def test_allreduce_replicated(self, dtype, dim):
        """Every rank contributes the same tensor → sum = tensor * size
        (test_tensorflow.py:77-106)."""
        size = hvd.size()
        x = _rand(dtype, dim)
        out = hvd.allreduce(x, average=False)
        if np.issubdtype(np.dtype(x.dtype), np.integer):
            # Integer sums wrap in-dtype, as MPI_SUM does.
            expected = (np.asarray(x, np.int64) * size).astype(x.dtype)
            assert np.array_equal(np.asarray(out), expected)
        else:
            expected = np.asarray(x, dtype=np.float64) * size
            got = np.asarray(out, dtype=np.float64)
            assert np.allclose(got, expected, atol=_threshold(dtype, size))
        assert out.shape == x.shape

    def test_allreduce_average(self):
        x = _rand(np.float32, 2)
        out = hvd.allreduce(x, average=True)
        assert np.allclose(np.asarray(out), np.asarray(x), atol=1e-5)

    @pytest.mark.parametrize("dtype", [np.int64, np.float64, np.complex128])
    def test_allreduce_numpy_64bit_rejected_without_x64(self, dtype):
        # Any numpy input that jnp.asarray would narrow (including
        # complex128 → complex64) must be refused, not silently corrupted.
        import jax
        if jax.config.jax_enable_x64:
            pytest.skip("x64 enabled; narrowing cannot occur")
        x = np.ones((4,), dtype=dtype)
        with pytest.raises(ValueError, match="64-bit"):
            hvd.allreduce(x, average=False)

    def test_allreduce_sharded_per_rank(self):
        """Per-rank distinct values via a 'dp'-sharded leading axis."""
        size = hvd.size()
        x = np.arange(size * 4, dtype=np.float32).reshape(size, 4)
        xs = jax.device_put(x, NamedSharding(hvd.mesh(), P("dp")))
        out = hvd.allreduce(xs, average=False)
        assert np.allclose(np.asarray(out), x.sum(axis=0))

    def test_allreduce_fusion_many(self):
        """Many ops in one batch exercise the fusion planner
        (test_tensorflow.py:107-139)."""
        size = hvd.size()
        xs = [jnp.full((5, 5), float(i + 1), jnp.float32) for i in range(16)]
        handles = [hvd.allreduce_async(x, average=False) for x in xs]
        for i, h in enumerate(handles):
            out = hvd.synchronize(h)
            assert np.allclose(np.asarray(out), (i + 1) * size)

    def test_grouped_allreduce(self):
        size = hvd.size()
        xs = [jnp.ones((3,), jnp.float32) * i for i in range(4)]
        outs = hvd.grouped_allreduce(xs, average=False)
        for i, o in enumerate(outs):
            assert np.allclose(np.asarray(o), i * size)

    def test_allreduce_async_poll(self):
        h = hvd.allreduce_async(jnp.ones((8,)), average=False)
        out = hvd.synchronize(h)
        assert hvd.poll(h)
        assert np.allclose(np.asarray(out), hvd.size())

    def test_allreduce_prescale_postscale(self):
        size = hvd.size()
        x = jnp.ones((4,), jnp.float32)
        out = hvd.allreduce(x, average=False, prescale_factor=2.0)
        assert np.allclose(np.asarray(out), 2.0 * size)
        out = hvd.allreduce(x, average=False, postscale_factor=0.5)
        assert np.allclose(np.asarray(out), 0.5 * size)

    def test_duplicate_name_error(self, monkeypatch):
        """In-flight duplicate names must be rejected
        (DUPLICATE_NAME_ERROR, operations.cc:270-273; test_torch.py
        test_duplicate_names)."""
        import threading
        from horovod_tpu.ops import collective
        eng = collective.engine()
        gate = threading.Event()
        orig = eng._execute_group

        def slow_execute(ex, group):
            gate.wait(10)
            return orig(ex, group)

        monkeypatch.setattr(eng, "_execute_group", slow_execute)
        h1 = hvd.allreduce_async(jnp.ones((4,)), name="dup.name")
        try:
            with pytest.raises(ValueError, match="same name"):
                hvd.allreduce_async(jnp.ones((4,)), name="dup.name")
        finally:
            gate.set()
            hvd.synchronize(h1)


class TestAllgather:
    @pytest.mark.parametrize("dtype", [np.int32, np.float32, np.float64])
    @pytest.mark.parametrize("dim", DIMS)
    def test_allgather_replicated(self, dtype, dim):
        """All ranks same tensor → size stacked copies
        (test_tensorflow.py:370-405)."""
        size = hvd.size()
        x = _rand(dtype, dim)
        out = hvd.allgather(x)
        assert out.shape[0] == x.shape[0] * size
        expected = np.concatenate([np.asarray(x)] * size, axis=0)
        assert np.allclose(np.asarray(out, np.float64),
                           expected.astype(np.float64))

    def test_allgather_variable_first_dim(self):
        """Per-rank different first dims — MPI_Allgatherv parity
        (test_tensorflow.py:406-510)."""
        size = hvd.size()
        per_rank = [jnp.full((i + 1, 3), float(i), jnp.float32)
                    for i in range(size)]
        out = hvd.allgather(per_rank)
        assert out.shape[0] == sum(i + 1 for i in range(size))
        expected = np.concatenate([np.asarray(t) for t in per_rank], axis=0)
        assert np.allclose(np.asarray(out), expected)

    def test_allgather_mismatched_shape_error(self):
        """Ranks disagreeing on non-first dims must error
        (test_tensorflow.py:558-591)."""
        size = hvd.size()
        per_rank = [jnp.zeros((2, 3)) for _ in range(size - 1)]
        per_rank.append(jnp.zeros((2, 4)))
        with pytest.raises(ValueError):
            hvd.allgather(per_rank)


class TestBroadcast:
    @pytest.mark.parametrize("dtype", [np.int32, np.float32, "bfloat16"])
    @pytest.mark.parametrize("root", [0, 3, 7])
    def test_broadcast_from_root(self, dtype, root):
        """Broadcast returns root's tensor on every rank
        (test_tensorflow.py:645-673)."""
        size = hvd.size()
        per_rank = np.stack(
            [np.full((4, 4), float(r), np.float32) for r in range(size)])
        x = jax.device_put(
            jnp.asarray(per_rank, dtype=(
                jnp.bfloat16 if dtype == "bfloat16" else dtype)),
            NamedSharding(hvd.mesh(), P("dp")))
        out = hvd.broadcast(x, root_rank=root)
        assert np.allclose(np.asarray(out, np.float64), float(root))

    def test_broadcast_replicated_identity(self):
        x = jnp.arange(10.0)
        out = hvd.broadcast(x, root_rank=2)
        assert np.allclose(np.asarray(out), np.asarray(x))


class TestStateSync:
    def test_broadcast_parameters_tree(self):
        params = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,)),
                  "nested": {"x": jnp.full((2,), 7.0)}}
        synced = hvd.broadcast_parameters(params, root_rank=0)
        assert set(synced) == set(params)
        for k in ("w", "b"):
            assert np.allclose(np.asarray(synced[k]), np.asarray(params[k]))
        assert np.allclose(np.asarray(synced["nested"]["x"]), 7.0)

    def test_broadcast_optimizer_state(self):
        import optax
        opt = optax.adam(1e-3)
        params = {"w": jnp.ones((3,))}
        state = opt.init(params)
        synced = hvd.broadcast_optimizer_state(state, root_rank=0)
        l1 = jax.tree_util.tree_leaves(state)
        l2 = jax.tree_util.tree_leaves(synced)
        assert len(l1) == len(l2)
        for a, b in zip(l1, l2):
            assert np.allclose(np.asarray(a, np.float64),
                               np.asarray(b, np.float64))

    def test_broadcast_object(self):
        obj = {"lr": 0.1, "sched": [1, 2, 3]}
        out = hvd.broadcast_object(obj, root_rank=0)
        assert out == obj


class TestHierarchical:
    def test_hierarchical_allreduce_matches_flat(self):
        """psum_scatter('ici') + psum('dcn') + all_gather('ici') must equal
        the flat psum (operations.cc:1284-1436 parity)."""
        from horovod_tpu.executor import CollectiveExecutor
        import jax.numpy as jnp
        ex = CollectiveExecutor(hierarchical_allreduce=True)
        x = jnp.arange(37.0, dtype=jnp.float32)  # odd length → padding path
        (out,) = ex.allreduce_fused([x])
        assert np.allclose(np.asarray(out), np.asarray(x) * hvd.size())

    def test_hierarchical_allgather_matches_flat(self):
        """all_gather('ici') + all_gather('dcn') must be bit-identical to
        the flat all_gather over 'dp' (operations.cc:929-1032 parity),
        for both the fused and the ragged (Allgatherv) variants."""
        from horovod_tpu.executor import CollectiveExecutor
        flat = CollectiveExecutor(hierarchical_allgather=False)
        hier = CollectiveExecutor(hierarchical_allgather=True)

        x = jnp.arange(10.0, dtype=jnp.float32).reshape(5, 2)
        (a,) = flat.allgather_fused([x])
        (b,) = hier.allgather_fused([x])
        assert a.shape == b.shape
        assert np.array_equal(np.asarray(a), np.asarray(b))

        # Ragged: rank i contributes i+1 rows.
        per_rank = [jnp.full((i + 1, 3), float(i), jnp.float32)
                    for i in range(hvd.size())]
        ra = flat.allgather_ragged(per_rank)
        rb = hier.allgather_ragged(per_rank)
        assert ra.shape == rb.shape
        assert np.array_equal(np.asarray(ra), np.asarray(rb))

    def test_hierarchical_allgather_env_knob(self, monkeypatch):
        """HOROVOD_TPU_HIERARCHICAL_ALLGATHER is read by the default
        executor (the knob was previously dead — VERDICT r1 missing #2)."""
        import horovod_tpu.executor as _exec
        monkeypatch.setenv("HOROVOD_TPU_HIERARCHICAL_ALLGATHER", "1")
        _exec.reset_default_executor()
        try:
            ex = _exec.default_executor()
            assert ex.hierarchical_allgather is True
            (out,) = ex.allgather_fused([jnp.ones((2, 2), jnp.float32)])
            assert out.shape == (2 * hvd.size(), 2)
        finally:
            monkeypatch.delenv("HOROVOD_TPU_HIERARCHICAL_ALLGATHER")
            _exec.reset_default_executor()

    def test_sharded_prescale(self):
        size = hvd.size()
        x = np.ones((size, 4), np.float32)
        xs = jax.device_put(x, NamedSharding(hvd.mesh(), P("dp")))
        out = hvd.allreduce(xs, average=False, prescale_factor=2.0)
        assert np.allclose(np.asarray(out), 2.0 * size)

    def test_non_leading_axis_sharding_rejected(self):
        size = hvd.size()
        x = np.arange(size * size, dtype=np.float32).reshape(size, size)
        xs = jax.device_put(x, NamedSharding(hvd.mesh(), P(None, "dp")))
        with pytest.raises(ValueError, match="LEADING"):
            hvd.allreduce(xs, average=False)


class TestFP8Compression:
    def test_allreduce_fp8_wire(self):
        import horovod_tpu as hvd
        from horovod_tpu.compression import Compression

        x = jnp.asarray(np.linspace(-4.0, 4.0, 32), jnp.float32)
        out = hvd.allreduce(x, average=True, name="fp8.avg",
                            compression=Compression.fp8)
        # e4m3 has ~2 decimal digits; averaging replicated copies is
        # identity up to the quantization error.
        assert out.dtype == jnp.float32
        assert float(jnp.max(jnp.abs(out - x))) < 0.3

    def test_fp8_roundtrip_dtype(self):
        from horovod_tpu.compression import Compression

        x = jnp.asarray([1.0, -2.5, 0.125], jnp.float32)
        wire, ctx = Compression.fp8.compress(x)
        assert wire.dtype == jnp.float8_e4m3fn
        back = Compression.fp8.decompress(wire, ctx)
        assert back.dtype == jnp.float32

    def test_fp8_fuses_with_planner(self):
        import horovod_tpu as hvd
        from horovod_tpu.compression import Compression

        hs = [hvd.allreduce_async(
                  Compression.fp8.compress(jnp.full((16,), float(i)))[0],
                  average=False, name=f"fp8.f{i}")
              for i in range(3)]
        outs = [hvd.synchronize(h) for h in hs]
        for i, o in enumerate(outs):
            expected = float(jnp.float8_e4m3fn(float(i))) * hvd.size()
            assert abs(float(o[0].astype(jnp.float32)) - expected) < 1e-3


class TestStallWarning:
    def test_engine_stall_report_names_op_age_and_diagnosis(self):
        """VERDICT r1 #10: the engine-path stall warning carries the
        reference report's diagnostic quality (operations.cc:1625-1672)
        — per-tensor op type + wait duration, and in single-process mode
        an explicit no-missing-ranks diagnosis (all virtual ranks are
        local; in MP mode the coordinator's missing-ranks line is merged
        instead, covered by test_control_plane)."""
        import logging
        import time as _time

        from horovod_tpu.ops import collective as coll

        eng = coll.engine()
        fake = coll._Request("stall.probe", coll.ALLREDUCE,
                             jnp.ones((3,)), eng.make_handle("stall.probe"))
        fake.enqueued_at = _time.monotonic() - 120.0
        old_warn, old_last = eng.stall_warning_s, eng._last_stall_check
        with eng._lock:
            eng._in_flight["stall.probe"] = fake
        records = []

        class _Capture(logging.Handler):
            def emit(self, record):
                records.append(record)

        # The package logger does not propagate to root (its own stderr
        # handler), so capture with a handler attached directly.
        hvd_logger = logging.getLogger("horovod_tpu")
        cap = _Capture(level=logging.WARNING)
        hvd_logger.addHandler(cap)
        try:
            eng.stall_warning_s = 0.01
            eng._last_stall_check = 0.0
            eng._maybe_check_stalls()
        finally:
            hvd_logger.removeHandler(cap)
            with eng._lock:
                eng._in_flight.pop("stall.probe", None)
            eng.stall_warning_s = old_warn
            eng._last_stall_check = old_last
        text = "\n".join(r.getMessage() for r in records)
        assert "stall.probe" in text
        assert "allreduce" in text
        assert "waiting 120s" in text
        assert "no rank is missing" in text


class TestBurstForeignWaiter:
    """ADVICE r3: a blocking wait from a thread that owns NO open burst
    scope must not have its flush hint consumed by another thread's
    scope — that stalls the waiter until the 1 s burst max-defer valve.
    The fix tracks scope-owner threads (native core and Python fallback
    both) and lets a foreign waiter's hint cut the scope."""

    @pytest.mark.parametrize("disable_native", ["0", "1"])
    def test_foreign_wait_inside_open_scope_is_fast(self, disable_native):
        import subprocess
        import sys
        script = r"""
import os, threading, time
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import horovod_tpu as hvd
from horovod_tpu.ops import collective

hvd.init()
eng = collective.engine()
# Warmup: compile the 2-tensor fused program outside the timed window —
# the timed wait must measure drain latency, not the first-ever XLA CPU
# compile (which alone can exceed the threshold on a loaded host). Same
# composition (2 x 8-float allreduce) as the timed burst so the drain
# hits the program cache.
with eng.burst():
    w1 = hvd.allreduce_async(jnp.ones((8,), jnp.float32), name="warm.a",
                             average=False)
    w2 = hvd.allreduce_async(jnp.ones((8,), jnp.float32), name="warm.b",
                             average=False)
w1.wait(timeout=60.0); w2.wait(timeout=60.0)
# The parametrization must actually exercise the path it names: with
# native enabled, a silent fallback (toolchain/build failure) would
# leave the C++ foreign-cut logic untested while both cases pass green.
if os.environ.get("HOROVOD_TPU_DISABLE_NATIVE") == "1":
    assert eng._native_core is None
else:
    assert eng._native_core is not None, "native core failed to load"
elapsed = [None]
err = [None]

def foreign():
    try:
        h = hvd.allreduce_async(jnp.ones((8,), jnp.float32),
                                name="foreign.op", average=False)
        t0 = time.monotonic()
        h.wait(timeout=10.0)
        elapsed[0] = time.monotonic() - t0
    except BaseException as e:
        err[0] = e

with eng.burst():
    # Owner enqueues part of a burst, then stalls (descheduled / slow
    # producer) with the scope still open while a foreign thread waits.
    hvd.allreduce_async(jnp.ones((8,), jnp.float32), name="owner.op",
                        average=False)
    t = threading.Thread(target=foreign)
    t.start()
    t.join(timeout=15.0)
    assert not t.is_alive(), "foreign waiter wedged"
if err[0] is not None:
    raise err[0]
print("ELAPSED", elapsed[0])
assert elapsed[0] < 0.5, (
    "foreign waiter stalled %.3fs - flush hint was consumed by the "
    "open scope (the 1 s burst valve)" % elapsed[0])
"""
        env = dict(os.environ)
        env["HOROVOD_TPU_DISABLE_NATIVE"] = disable_native
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [sys.executable, "-c", script], env=env, capture_output=True,
            text=True, timeout=180,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        assert proc.returncode == 0, (proc.stdout[-2000:],
                                      proc.stderr[-2000:])


class TestFusedProgramStability:
    """Round-5 regression guards for the MP compile storm: padded sizes
    and unpack programs must be stable across timing-dependent group
    compositions (a 120-tensor group measured 11 s/step of
    per-composition recompiles before the fix)."""

    def test_padded_size_quantization(self):
        """<=12.5% overhead, <=8 distinct values per octave, multiples
        of 512 floor — the compile-stability/traffic compromise the
        round-5 scaling A/B settled on."""
        from horovod_tpu.executor import _fusion_padded_size
        for n in (1, 511, 512, 513, 100_000, 9_000_000, 15_500_000):
            p = _fusion_padded_size(n)
            assert p >= max(n, 512)
            assert p <= max(512, int(n * 1.125) + 1), (n, p)
            # at most 3 significant mantissa bits
            k = p.bit_length() - 1
            assert p % (1 << max(k - 3, 0)) == 0, (n, p)
        # Distinct values per octave are bounded (cache convergence):
        octave = {_fusion_padded_size(n)
                  for n in range(1 << 20, 1 << 21, 1 << 12)}
        assert len(octave) <= 9, sorted(octave)[:12]

    def test_unpack_cache_stable_across_compositions(self):
        """Same tensor shapes at DIFFERENT offsets (different group
        compositions) must reuse the same compiled slice programs —
        offsets are traced, not baked in."""
        import jax.numpy as jnp
        from horovod_tpu import executor as ex

        ex._UNPACK_CACHE.clear()
        buf = jnp.arange(2048, dtype=jnp.float32)
        arrs = [np.zeros((128,), np.float32), np.zeros((64,), np.float32)]
        res: list = [None, None]
        ex._unpack(buf, arrs, [0, 1], res)
        np.testing.assert_allclose(np.asarray(res[0]), np.arange(128.0))
        keys_after_first = len(ex._UNPACK_CACHE)
        # Second composition: same shapes, swapped order => new offsets.
        res2: list = [None, None]
        ex._unpack(buf, [arrs[1], arrs[0]], [0, 1], res2)
        np.testing.assert_allclose(np.asarray(res2[0]), np.arange(64.0))
        np.testing.assert_allclose(np.asarray(res2[1]),
                                   np.arange(64.0, 64.0 + 128.0))
        assert len(ex._UNPACK_CACHE) == keys_after_first, (
            "unpack compiled new programs for a recomposition of the "
            "same shapes - offsets are being baked in again")

    def test_unpack_cache_bounded_lru(self, monkeypatch):
        """ADVICE low: the unpack-program cache must not grow without
        bound under shape churn; eviction is LRU (a recently reused key
        survives)."""
        import jax.numpy as jnp
        from horovod_tpu import executor as ex

        ex._UNPACK_CACHE.clear()
        monkeypatch.setattr(ex, "_UNPACK_CACHE_MAX", 3)
        buf = jnp.arange(256, dtype=jnp.float32)

        def one(n):
            res = [None]
            ex._unpack(buf, [np.zeros((n,), np.float32)], [0], res)
            return res[0]

        for n in (8, 16, 32):
            one(n)
        assert len(ex._UNPACK_CACHE) == 3
        one(8)            # refresh 8 => 16 is now least-recently-used
        one(64)           # evicts 16
        assert len(ex._UNPACK_CACHE) == 3
        sizes = {k[0] for k in ex._UNPACK_CACHE}
        assert (8,) in sizes and (16,) not in sizes
        ex._UNPACK_CACHE.clear()

    def test_unpack_offset_overflow_guard(self):
        """Offsets ride as int32; a buffer too large for that must fail
        loudly with the knob named, not slice at a wrapped offset."""
        from horovod_tpu import executor as ex

        class Huge:
            size = 2 ** 31

        with pytest.raises(ValueError, match="int32"):
            ex._unpack(Huge(), [], [], [])

    def test_varying_composition_allreduce_values(self):
        """End-to-end: the same tensors fused in different per-step
        compositions (forced by distinct name sets) keep exact values."""
        rng = np.random.RandomState(3)
        tensors = [rng.randn(rng.randint(100, 5000)).astype(np.float32)
                   for _ in range(12)]
        for it in range(3):
            order = rng.permutation(len(tensors))
            hs = {i: hvd.allreduce_async(tensors[i], average=False,
                                         name=f"comp.{it}.{i}")
                  for i in order}
            for i, h in hs.items():
                np.testing.assert_allclose(
                    np.asarray(h.wait()), tensors[i] * hvd.size(),
                    rtol=1e-5)
