"""DLPack zero-copy boundary tests (utils/interop.py).

BASELINE.json's north star: framework shims hand gradients to the JAX
collective path via DLPack. These tests prove the no-copy claim directly
— pointer identity between the framework tensor and the jax buffer on
ingress, buffer aliasing on egress — plus exact fallback behavior for
everything DLPack cannot carry (64-bit truncation hazard, non-contiguous
tensors, sharded outputs). Reference parity anchor: the torch adapter
operates on the tensor's own memory (torch/adapter_v2.cc:40-105).
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax
import jax.numpy as jnp

import horovod_tpu as hvd
import horovod_tpu.torch as hvd_torch
from horovod_tpu.utils import interop


@pytest.fixture(autouse=True)
def _init():
    hvd.init()
    interop.reset_stats()
    yield


# ---------------------------------------------------------------------------
# Ingress: torch -> jax
# ---------------------------------------------------------------------------

def test_torch_ingress_zero_copy_pointer_identity():
    t = torch.arange(64, dtype=torch.float32)
    a = interop.try_torch_to_jax(t)
    assert a is not None
    assert t.data_ptr() == a.unsafe_buffer_pointer()
    assert interop.stats()["dlpack_in"] == 1


@pytest.mark.parametrize("dtype", [torch.float16, torch.bfloat16,
                                   torch.float32, torch.int32,
                                   torch.uint8, torch.int8])
def test_torch_ingress_dtypes_alias(dtype):
    t = torch.ones(32, dtype=dtype)
    a = interop.try_torch_to_jax(t)
    assert a is not None
    assert t.data_ptr() == a.unsafe_buffer_pointer()


def test_torch_ingress_bf16_carried_natively():
    t = torch.full((16,), 1.5, dtype=torch.bfloat16)
    a = interop.try_torch_to_jax(t)
    assert a is not None and a.dtype == jnp.bfloat16
    np.testing.assert_array_equal(np.asarray(a, dtype=np.float32), 1.5)


def test_torch_ingress_mutation_visible_through_alias():
    # Proof the buffer is shared, not snapshotted.
    t = torch.zeros(8, dtype=torch.float32)
    a = interop.try_torch_to_jax(t)
    t[0] = 42.0
    assert float(a[0]) == 42.0


def test_torch_ingress_64bit_falls_back():
    # jax.dlpack would TRUNCATE int64 (2**40 -> 0); must refuse.
    t = torch.tensor([2**40], dtype=torch.int64)
    assert interop.try_torch_to_jax(t) is None
    assert interop.stats()["numpy_in"] == 1


def test_torch_ingress_complex128_falls_back():
    # jax.dlpack would silently narrow complex128 -> complex64.
    t = torch.tensor([1 + 2j], dtype=torch.complex128)
    assert interop.try_torch_to_jax(t) is None


def test_tf_ingress_wide_dtypes_fall_back():
    tf = pytest.importorskip("tensorflow")
    for dt, val in [("uint64", 2**40 + 5), ("int64", 2**40),
                    ("float64", 1.0), ("complex128", 1 + 2j)]:
        t = tf.constant([val], dtype=getattr(tf, dt))
        assert interop.try_tf_to_jax(t) is None, dt


def test_torch_ingress_noncontiguous_falls_back():
    t = torch.arange(16, dtype=torch.float32).reshape(4, 4).t()
    assert interop.try_torch_to_jax(t) is None


def test_torch_ingress_requires_grad_ok():
    t = torch.ones(4, requires_grad=True)
    a = interop.try_torch_to_jax(t)
    assert a is not None  # detached internally


# ---------------------------------------------------------------------------
# Egress: jax -> torch
# ---------------------------------------------------------------------------

def test_jax_egress_unsharded_alias():
    x = jnp.arange(32, dtype=jnp.float32) * 2
    t = interop.try_jax_to_torch(x)
    assert t is not None
    assert t.data_ptr() == x.unsafe_buffer_pointer()


def test_jax_egress_replicated_uses_shard0():
    # Engine outputs are replicated over the mesh; egress must alias
    # shard 0 rather than copy.
    out = hvd.allreduce(np.arange(16, dtype=np.float32), average=False)
    assert len(out.sharding.device_set) > 1 and \
        out.sharding.is_fully_replicated
    t = interop.try_jax_to_torch(out)
    assert t is not None
    shard0 = out.addressable_shards[0].data
    assert t.data_ptr() == shard0.unsafe_buffer_pointer()
    np.testing.assert_allclose(t.numpy(),
                               np.arange(16, dtype=np.float32) * hvd.size())


def test_jax_egress_dp_sharded_falls_back():
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = hvd.topology.mesh()
    x = jax.device_put(jnp.arange(16, dtype=jnp.float32),
                       NamedSharding(mesh, P("dp")))
    assert interop.try_jax_to_torch(x) is None


def test_to_host_single_copy_on_replicated():
    out = hvd.allreduce(np.ones(8, dtype=np.float32), average=False)
    arr = interop.to_host(out)
    np.testing.assert_allclose(arr, hvd.size())


# ---------------------------------------------------------------------------
# Shim-level: the fast path actually runs through hvd.torch
# ---------------------------------------------------------------------------

def test_torch_allreduce_uses_dlpack_both_ways():
    t = torch.ones(128, dtype=torch.float32)
    out = hvd_torch.allreduce(t, average=False)
    np.testing.assert_allclose(out.numpy(), hvd.size())
    s = interop.stats()
    assert s["dlpack_in"] >= 1, "ingress took the numpy fallback"
    assert s["dlpack_out"] >= 1, "egress took the numpy fallback"


def test_torch_allreduce_bf16_dlpack():
    t = torch.full((64,), 2.0, dtype=torch.bfloat16)
    out = hvd_torch.allreduce(t, average=False)
    assert out.dtype == torch.bfloat16
    np.testing.assert_allclose(out.float().numpy(), 2.0 * hvd.size())
    assert interop.stats()["dlpack_in"] >= 1


def test_torch_inplace_allreduce_dlpack_source():
    t = torch.ones(32, dtype=torch.float32)
    hvd_torch.allreduce_(t, average=False)
    np.testing.assert_allclose(t.numpy(), hvd.size())


def test_torch_int64_movement_still_exact():
    # 64-bit movement collectives keep the int32 bit-pair transport.
    t = torch.tensor([2**40 + 7, -3], dtype=torch.int64)
    out = hvd_torch.broadcast(t, root_rank=0)
    assert out.tolist() == [2**40 + 7, -3]


def test_torch_egress_result_is_private_buffer():
    # Two successive collectives must not hand back the same buffer.
    a = hvd_torch.allreduce(torch.ones(16), average=False)
    b = hvd_torch.allreduce(torch.full((16,), 2.0), average=False)
    assert a.data_ptr() != b.data_ptr()
    np.testing.assert_allclose(a.numpy(), hvd.size())
    np.testing.assert_allclose(b.numpy(), 2.0 * hvd.size())


def test_synchronize_many_batched_readback():
    """Batch synchronize: mixed in-place / out-of-place / 64-bit-bits
    handles resolve correctly through the single device_get path."""
    t1 = torch.ones(16, dtype=torch.float32)            # in-place
    t2 = torch.full((8,), 2.0, dtype=torch.bfloat16)    # bf16
    t3 = torch.tensor([2**40 + 3], dtype=torch.int64)   # bits transport
    hs = [hvd_torch.allreduce_async_(t1, average=False, name="sm.a"),
          hvd_torch.allreduce_async(t2, average=False, name="sm.b"),
          hvd_torch.broadcast_async(t3, 0, name="sm.c")]
    outs = hvd_torch.synchronize_many(hs)
    assert outs[0] is t1
    np.testing.assert_allclose(t1.numpy(), hvd.size())
    assert outs[1].dtype == torch.bfloat16
    np.testing.assert_allclose(outs[1].float().numpy(), 2.0 * hvd.size())
    assert outs[2].tolist() == [2**40 + 3]
    with pytest.raises(ValueError):
        hvd_torch.synchronize(hs[0])  # already cleared


def test_to_host_many_matches_per_array():
    import jax.numpy as jnp
    outs = [hvd.allreduce(np.full(8, float(i), np.float32), average=False)
            for i in range(4)]
    hosts = interop.to_host_many(outs)
    for i, h in enumerate(hosts):
        np.testing.assert_allclose(h, float(i) * hvd.size())


def test_torch_grouped_many_tensors_fast_path():
    interop.reset_stats()
    ts = [torch.full((8,), float(i)) for i in range(10)]
    handles = [hvd_torch.allreduce_async(t, average=False) for t in ts]
    outs = [hvd_torch.synchronize(h) for h in handles]
    for i, o in enumerate(outs):
        np.testing.assert_allclose(o.numpy(), float(i) * hvd.size())
    s = interop.stats()
    assert s["dlpack_in"] == 10
    assert s["numpy_in"] == 0


# ---------------------------------------------------------------------------
# DLPack egress roundtrip matrix (docs/torch.md): every wire dtype the
# training hot path carries, in-place and out-of-place, plus the
# capability-probed fallbacks for a chip whose buffers refuse export.
# ---------------------------------------------------------------------------

EGRESS_DTYPES = [torch.float32, torch.bfloat16, torch.float16, torch.int32]


def _rand_t(dtype):
    if dtype == torch.int32:
        return torch.randint(0, 9, (33,), dtype=dtype)
    return torch.rand(33).to(dtype)


@pytest.mark.parametrize("dtype", EGRESS_DTYPES)
def test_egress_roundtrip_out_of_place(dtype):
    t = _rand_t(dtype)
    interop.reset_stats()
    out = hvd_torch.allreduce(t, average=False)
    assert out.dtype == dtype
    np.testing.assert_allclose(out.float().numpy(),
                               (t.float() * hvd.size()).numpy(),
                               rtol=1e-2 if dtype in (torch.float16,
                                                      torch.bfloat16)
                               else 1e-6)
    s = interop.stats()
    assert s["dlpack_out"] >= 1 and s["numpy_out"] == 0, s
    # out-of-place results must be private (mutating them must not
    # corrupt any engine state) — prove by a second identical reduce.
    out.mul_(0)
    out2 = hvd_torch.allreduce(t, average=False)
    np.testing.assert_allclose(out2.float().numpy(),
                               (t.float() * hvd.size()).numpy(),
                               rtol=1e-2 if dtype in (torch.float16,
                                                      torch.bfloat16)
                               else 1e-6)


@pytest.mark.parametrize("dtype", EGRESS_DTYPES)
def test_egress_roundtrip_in_place(dtype):
    t = _rand_t(dtype)
    expect = (t.float() * hvd.size()).numpy()
    interop.reset_stats()
    ret = hvd_torch.allreduce_(t, average=False)
    assert ret is t
    np.testing.assert_allclose(t.float().numpy(), expect,
                               rtol=1e-2 if dtype in (torch.float16,
                                                      torch.bfloat16)
                               else 1e-6)
    assert interop.stats()["numpy_out"] == 0


def test_torch_egress_many_alias_not_private():
    import jax.numpy as jnp
    xs = [jnp.arange(8, dtype=jnp.float32) * (i + 1) for i in range(3)]
    interop.reset_stats()
    outs = interop.torch_egress_many(xs)
    for i, exp in enumerate(outs):
        assert exp is not None
        t, private = exp
        # CPU-mesh egress aliases the jax buffer: zero copy, not private.
        assert not private
        assert t.data_ptr() == xs[i].unsafe_buffer_pointer()
    assert interop.stats()["dlpack_out"] == 3


def test_torch_egress_many_transfer_branch_is_private(monkeypatch):
    """Simulated chip: buffers claim a non-cpu platform, forcing the
    batched device→CPU transfer leg — results must come back correct
    and flagged private (safe to hand out unclone-d)."""
    import jax.numpy as jnp
    monkeypatch.setattr(interop, "_buffer_platform", lambda buf: "tpu")
    xs = [jnp.full((16,), float(i + 1), jnp.float32) for i in range(4)]
    interop.reset_stats()
    outs = interop.torch_egress_many(xs)
    for i, exp in enumerate(outs):
        assert exp is not None
        t, private = exp
        assert private
        np.testing.assert_allclose(t.numpy(), float(i + 1))
    assert interop.stats()["dlpack_out"] == 4


def test_torch_egress_many_chip_absent_fallback(monkeypatch):
    """Simulated chip WITHOUT a transfer-capable CPU backend: every slot
    degrades to the numpy fallback (None) and is counted as such."""
    import jax.numpy as jnp
    monkeypatch.setattr(interop, "_buffer_platform", lambda buf: "tpu")
    monkeypatch.setattr(interop, "transfer_egress_supported",
                        lambda: False)
    xs = [jnp.ones((4,), jnp.float32)]
    interop.reset_stats()
    assert interop.torch_egress_many(xs) == [None]
    assert interop.stats()["numpy_out"] == 1
    # ...and the shim still returns correct values through numpy.
    monkeypatch.setattr(interop, "torch_egress_many",
                        lambda arrays: [None] * len(arrays))
    t = torch.full((8,), 3.0)
    out = hvd_torch.allreduce(t, average=False)
    np.testing.assert_allclose(out.numpy(), 3.0 * hvd.size())


def test_egress_bf16_bitcast_transport(monkeypatch):
    """Where the DLPack exchange refuses bfloat16, the buffer crosses as
    a uint16 bitcast re-viewed as bf16 (bitcast transport)."""
    import jax.numpy as jnp
    real_from_dlpack = torch.from_dlpack

    def refusing(buf):
        if "bfloat16" in str(getattr(buf, "dtype", "")):
            raise BufferError("bfloat16 refused (simulated old exchange)")
        return real_from_dlpack(buf)

    monkeypatch.setattr(torch, "from_dlpack", refusing)
    x = jnp.full((16,), 2.5, jnp.bfloat16)
    out = interop.torch_egress_many([x])[0]
    assert out is not None
    t, _ = out
    assert t.dtype == torch.bfloat16
    np.testing.assert_allclose(t.float().numpy(), 2.5)
    assert interop.stats()["dlpack_out"] >= 1


def test_egress_kill_switch_forces_numpy(monkeypatch):
    monkeypatch.setenv("HOROVOD_TPU_DLPACK", "0")
    import jax.numpy as jnp
    interop.reset_stats()
    assert interop.torch_egress_many([jnp.ones(4)]) == [None]
    assert interop.stats()["numpy_out"] == 1
    t = torch.ones(8)
    out = hvd_torch.allreduce(t, average=False)
    np.testing.assert_allclose(out.numpy(), hvd.size())


def test_transfer_probe_true_on_cpu_backend():
    # The CPU backend trivially supports the transfer leg; the probe is
    # cached, so exercise the uncached path too.
    assert interop.transfer_egress_supported()
    assert interop._probe_transfer()
