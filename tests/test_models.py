"""Model zoo tests: shapes, dtypes, jit-ability, and a gradient step for
every family the benchmarks use (ResNet was covered implicitly by the
bench path; VGG / Inception V3 / MNIST / word2vec are covered here).

Runs on the virtual CPU mesh with small inputs — correctness of shapes
and finiteness, not accuracy.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.models import (InceptionV3, MnistConvNet, ResNet50, VGG16)
from horovod_tpu.models import word2vec as w2v


RNG = jax.random.PRNGKey(0)


def _forward(model, shape, train=False, dtype=jnp.float32):
    x = jnp.ones(shape, dtype)
    variables = model.init({"params": RNG, "dropout": RNG}, x, train=False)
    out = model.apply(variables, x, train=train,
                      rngs={"dropout": RNG} if train else None,
                      mutable=["batch_stats"] if train else False)
    return out[0] if train else out


class TestConvModels:
    def test_vgg16_forward(self):
        # fp32 on CPU test mesh; bf16 is the TPU default.
        out = _forward(VGG16(num_classes=10, dtype=jnp.float32),
                       (2, 32, 32, 3))
        assert out.shape == (2, 10)
        assert out.dtype == jnp.float32
        assert np.all(np.isfinite(out))

    def test_vgg16_param_count_imagenet(self):
        model = VGG16(num_classes=1000, dtype=jnp.float32)
        variables = model.init(RNG, jnp.ones((1, 224, 224, 3)), train=False)
        n = sum(int(np.prod(p.shape))
                for p in jax.tree_util.tree_leaves(variables["params"]))
        assert abs(n - 138_357_544) / 138_357_544 < 0.01  # the classic 138M

    def test_inception_v3_forward(self):
        out = _forward(InceptionV3(num_classes=12, dtype=jnp.float32),
                       (1, 128, 128, 3), train=True)
        assert out.shape == (1, 12)
        assert np.all(np.isfinite(out))

    def test_mnist_convnet_train_step(self):
        model = MnistConvNet()
        x = jnp.ones((4, 28, 28, 1))
        y = jnp.array([0, 1, 2, 3])
        variables = model.init(RNG, x, train=False)

        @jax.jit
        def loss_fn(params):
            logits = model.apply({"params": params}, x, train=True,
                                 rngs={"dropout": RNG})
            onehot = jax.nn.one_hot(y, 10)
            return -jnp.mean(jnp.sum(
                jax.nn.log_softmax(logits) * onehot, axis=-1))

        loss, grads = jax.value_and_grad(loss_fn)(variables["params"])
        assert np.isfinite(float(loss))
        gnorm = jnp.sqrt(sum(jnp.sum(g ** 2)
                             for g in jax.tree_util.tree_leaves(grads)))
        assert float(gnorm) > 0

    def test_resnet50_jit_forward(self):
        model = ResNet50(num_classes=10, dtype=jnp.float32)
        x = jnp.ones((1, 64, 64, 3))
        variables = model.init(RNG, x, train=False)
        out = jax.jit(lambda v, x: model.apply(v, x, train=False))(
            variables, x)
        assert out.shape == (1, 10)


class TestWord2Vec:
    def test_nce_loss_and_grad(self):
        params = w2v.init_params(vocab_size=100, embedding_dim=16, rng=RNG)
        centers = jnp.array([1, 2, 3, 4])
        contexts = jnp.array([2, 3, 4, 5])

        @jax.jit
        def loss_fn(p):
            return w2v.nce_loss(p, centers, contexts, RNG,
                                num_negatives=8)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        assert np.isfinite(float(loss))
        assert float(loss) > 0
        assert np.any(np.asarray(grads.embeddings) != 0)

    def test_skipgram_batch_static_shapes(self):
        data = jnp.arange(50, dtype=jnp.int32)
        c0, t0 = w2v.skipgram_batch(data, step=0, batch_size=8)
        c9, t9 = w2v.skipgram_batch(data, step=9, batch_size=8)
        assert c0.shape == t0.shape == (8,)
        assert c9.shape == (8,)
        # neighbors are +-skip_window away
        assert np.all(np.abs(np.asarray(t0) - np.asarray(c0)) == 1)

    def test_nearest(self):
        params = w2v.init_params(vocab_size=50, embedding_dim=8, rng=RNG)
        nn_ids = w2v.nearest(params, jnp.array([0, 1]), k=5)
        assert nn_ids.shape == (2, 5)
        assert not np.any(np.asarray(nn_ids[0]) == 0)  # self excluded
