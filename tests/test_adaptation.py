"""Self-healing collective plane — fast unit tests (docs/adaptation.md).

Covers the fault-spec grammar and injector windows, the policy ladder
(monotonic escalation/de-escalation, hysteresis on a borderline-slow
rank, edge-triggered eviction), the coordinator glue (fusion-threshold
shrink, seq-keyed wire epochs in params, slow_rank failure events, the
stall-blame escalation driven by a drop_announce fault), the engine's
wire-epoch selection, the hardened coordinator client
(retry/backoff/jitter + CoordinatorUnreachableError on a flapping
server), straggler-telemetry re-keying across world-size changes, the
typed WorkerFailure propagation through the driver service, slot-penalty
readmission probing, and error-feedback residual reset on a mid-run wire
spec switch.
"""

import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

import horovod_tpu as hvd
from horovod_tpu.adaptation import (AdaptationConfig, AdaptationPolicy,
                                    FaultInjector, parse_spec)
from horovod_tpu.adaptation import faults as faults_mod
from horovod_tpu.elastic import SlowRankFailure, WorkerFailure
from horovod_tpu.elastic.failure import failure_from_event
from horovod_tpu.ops.control_plane import (AnnounceRequest,
                                           CoordinatorClient,
                                           CoordinatorService,
                                           CoordinatorUnreachableError,
                                           FetchRequest)
from horovod_tpu.runner.secret import make_secret_key


# ---------------------------------------------------------------------------
# Fault-spec grammar + injector
# ---------------------------------------------------------------------------

class TestFaultSpec:
    def test_full_grammar(self):
        cl = parse_spec("rank=2:delay=80ms:from_step=50; "
                        "rank=1:crash_at=30:gen=0; "
                        "rank=*:slow_h2d=2ms; "
                        "rank=3:drop_announce:from_step=5:until_step=9")
        assert len(cl) == 4
        assert cl[0].rank == 2 and cl[0].delay_s == pytest.approx(0.08)
        assert cl[0].from_step == 50
        assert cl[1].crash_at == 30 and cl[1].gen == 0
        assert cl[2].rank is None and cl[2].slow_h2d_s == pytest.approx(2e-3)
        assert cl[3].drop_announce and cl[3].until_step == 9

    def test_duration_units(self):
        assert parse_spec("rank=0:delay=1.5s")[0].delay_s == 1.5
        assert parse_spec("rank=0:delay=500us")[0].delay_s == \
            pytest.approx(5e-4)
        assert parse_spec("rank=0:delay=0.25")[0].delay_s == 0.25

    def test_missing_rank_rejected(self):
        with pytest.raises(ValueError, match="rank"):
            parse_spec("delay=80ms")

    def test_unknown_field_rejected(self):
        with pytest.raises(ValueError, match="unknown fault-spec field"):
            parse_spec("rank=0:dealy=80ms")

    def test_injector_filters_rank_and_gen(self):
        cl = parse_spec("rank=2:delay=10ms; rank=1:delay=5ms:gen=1")
        assert len(FaultInjector(cl, rank=2, generation=0).clauses) == 1
        assert FaultInjector(cl, rank=1, generation=0).clauses == []
        assert len(FaultInjector(cl, rank=1, generation=1).clauses) == 1
        assert FaultInjector(cl, rank=0, generation=0).clauses == []

    def test_window_and_tick(self):
        inj = FaultInjector(
            parse_spec("rank=0:drop_announce:from_step=2:until_step=4"),
            rank=0)
        active = []
        for _ in range(6):
            active.append(inj.drop_announce_active())
            inj.on_enqueue()
        assert active == [False, False, True, True, False, False]

    def test_delay_applied_in_window(self):
        inj = FaultInjector(
            parse_spec("rank=0:delay=30ms:from_step=1:until_step=2"),
            rank=0)
        t0 = time.monotonic()
        inj.on_enqueue()                     # tick 0: outside window
        before = time.monotonic() - t0
        t0 = time.monotonic()
        inj.on_enqueue()                     # tick 1: 30 ms delay
        during = time.monotonic() - t0
        assert before < 0.02 and during >= 0.03

    def test_env_resolution_off_by_default(self, monkeypatch):
        monkeypatch.delenv("HOROVOD_TPU_FAULT_SPEC", raising=False)
        monkeypatch.delenv("HOROVOD_FAULT_SPEC", raising=False)
        faults_mod.reset()
        try:
            assert faults_mod.injector() is None
        finally:
            faults_mod.reset()

    def test_env_resolution_other_rank_is_none(self, monkeypatch):
        # This test process is rank 0; a spec targeting rank 7 resolves
        # to no injector at all (the zero-cost-when-inactive contract).
        monkeypatch.setenv("HOROVOD_TPU_FAULT_SPEC", "rank=7:delay=1ms")
        faults_mod.reset()
        try:
            assert faults_mod.injector() is None
        finally:
            faults_mod.reset()


# ---------------------------------------------------------------------------
# Policy ladder
# ---------------------------------------------------------------------------

def _cfg(**kw):
    base = dict(threshold_s=0.05, sustain_s=1.0, cooldown_s=2.0,
                interval_s=0.0)
    base.update(kw)
    return AdaptationConfig(**base)


class TestAdaptationPolicy:
    def test_monotonic_escalation_then_deescalation(self):
        p = AdaptationPolicy(_cfg())
        t, evs = 0.0, []
        for _ in range(20):
            evs += p.observe({2: 0.2, 0: 0.001}, t)
            t += 0.6
        esc = [e["name"] for e in evs if e["action"] == "escalate"]
        assert esc == ["shrink", "bf16", "int8x256", "fp8x256", "evict"]
        assert p.evicted == {2}
        # Straggler evicted → signal clears → the ladder unwinds in
        # exact reverse order, one cooldown window per step.
        deesc = [e["name"] for e in evs if e["action"] == "deescalate"]
        for _ in range(20):
            for e in p.observe({0: 0.001}, t):
                deesc.append(e["name"])
            t += 0.7
        assert deesc == ["fp8x256", "int8x256", "bf16", "shrink"]
        assert p.tier == 0 and p.wire_spec() is None

    def test_borderline_rank_no_flapping(self):
        """Lateness oscillating across the threshold faster than the
        sustain window produces ZERO transitions (the hysteresis band
        resets both clocks)."""
        p = AdaptationPolicy(_cfg())
        t, evs = 0.0, []
        for i in range(60):
            lat = 0.051 if i % 2 == 0 else 0.04
            evs += p.observe({1: lat}, t)
            t += 0.6
        assert evs == [] and p.tier == 0

    def test_each_step_needs_its_own_sustain_window(self):
        p = AdaptationPolicy(_cfg(sustain_s=1.0))
        evs = p.observe({1: 0.2}, 0.0)       # starts the clock
        evs += p.observe({1: 0.2}, 0.5)      # not sustained yet
        assert evs == []
        evs = p.observe({1: 0.2}, 1.1)       # first escalation
        assert [e["name"] for e in evs] == ["shrink"]
        evs = p.observe({1: 0.2}, 1.6)       # needs a NEW window
        assert evs == []

    def test_eviction_edge_triggered_second_straggler(self):
        p = AdaptationPolicy(_cfg(tiers=("shrink", "evict")))
        t, evs = 0.0, []
        for _ in range(10):
            evs += p.observe({1: 0.2, 3: 0.3}, t)
            t += 1.2
        # Worst rank 3 evicted first; rank 1 still slow → its own
        # sustain window earns a second eviction. Both gone, the signal
        # clears and the remaining shrink tier unwinds.
        evicts = [e["rank"] for e in evs if e["name"] == "evict"]
        assert evicts == [3, 1]
        assert p.evicted == {1, 3}
        assert p.tier == 0

    def test_evict_gated_without_failure_plane(self):
        p = AdaptationPolicy(_cfg(tiers=("shrink", "evict")),
                             allow_evict=False)
        t, evs = 0.0, []
        for _ in range(10):
            evs += p.observe({1: 0.2}, t)
            t += 1.2
        assert [e["name"] for e in evs] == ["shrink"]
        assert p.evicted == set()

    def test_wire_spec_tracks_strongest_active_tier(self):
        p = AdaptationPolicy(_cfg())
        assert p.wire_spec() is None
        p.tier = 2
        assert p.wire_spec() == "bf16"
        p.tier = 4
        assert p.wire_spec() == "fp8x256"


# ---------------------------------------------------------------------------
# Coordinator glue: shrink + wire epochs + eviction event
# ---------------------------------------------------------------------------

def _skew(svc, rank_late: int, lateness: float, n: int = 6):
    """Feed the service's skew tracker n completed tensors with
    ``rank_late`` announcing ``lateness`` behind the others."""
    nproc = svc._nproc
    base = time.monotonic()
    for i in range(n):
        t = base + i * 1e-3
        for rk in range(nproc):
            svc._skew.note(rk, [f"skew.{base}.{i}"],
                           t + (lateness if rk == rank_late else 0.0))


class TestCoordinatorAdaptation:
    def _svc(self, monkeypatch, timeout="5", tiers=None):
        monkeypatch.setenv("HOROVOD_TPU_ADAPTATION", "1")
        monkeypatch.setenv("HOROVOD_TPU_ADAPT_THRESHOLD", "0.01")
        monkeypatch.setenv("HOROVOD_TPU_ADAPT_SUSTAIN", "0")
        monkeypatch.setenv("HOROVOD_TPU_ADAPT_INTERVAL", "0")
        if tiers:
            monkeypatch.setenv("HOROVOD_TPU_ADAPT_TIERS", tiers)
        monkeypatch.setenv("HOROVOD_TPU_FAILURE_TIMEOUT", timeout)
        return CoordinatorService(nproc=2, key=make_secret_key(),
                                  fusion_threshold=1 << 20, native=False)

    def test_shrink_wire_epochs_and_eviction(self, monkeypatch):
        svc = self._svc(monkeypatch)
        try:
            _skew(svc, rank_late=1, lateness=0.05)
            for _ in range(8):
                svc._last_policy_tick = 0.0
                svc._maybe_adapt()
            # shrink tier: the PLANNER's threshold dropped.
            assert svc.fusion_threshold == (1 << 20) // 4
            # wire epochs published in escalation order, ascending seqs.
            specs = [sp for _, sp in svc._wire_epochs]
            assert specs == ["bf16", "int8x256", "fp8x256"]
            seqs = [s for s, _ in svc._wire_epochs]
            assert seqs == sorted(seqs)
            # eviction rode the failure side-channel, typed slow_rank.
            resp = svc._fetch(FetchRequest(0, 0, 0.0))
            kinds = {f["kind"] for f in resp.failures}
            assert "slow_rank" in kinds
            assert any(f["rank"] == 1 for f in resp.failures)
            # params carry the overlay for every engine.
            assert resp.params["fusion_threshold"] == (1 << 20) // 4
            assert [sp for _, sp in resp.params["wire_epochs"]] == specs
        finally:
            svc.shutdown()

    def test_policy_off_by_default(self, monkeypatch):
        monkeypatch.delenv("HOROVOD_TPU_ADAPTATION", raising=False)
        monkeypatch.delenv("HOROVOD_ADAPTATION", raising=False)
        svc = CoordinatorService(nproc=2, key=make_secret_key(),
                                 native=False)
        try:
            assert svc._policy is None
            _skew(svc, rank_late=1, lateness=0.1)
            svc._maybe_adapt()
            assert svc.fusion_threshold == svc._base_fusion_threshold
            assert svc._wire_epochs == []
        finally:
            svc.shutdown()

    def test_eviction_gated_without_failure_timeout(self, monkeypatch):
        svc = self._svc(monkeypatch, timeout="0", tiers="evict")
        try:
            _skew(svc, rank_late=1, lateness=0.05)
            for _ in range(6):
                svc._last_policy_tick = 0.0
                svc._maybe_adapt()
            assert svc._policy_failures == []
        finally:
            svc.shutdown()


# ---------------------------------------------------------------------------
# Stall blame → failure plane (drop_announce fault)
# ---------------------------------------------------------------------------

class TestStallEscalation:
    def test_drop_announce_blamed_and_escalated(self, monkeypatch):
        """A mute-but-breathing worker (drop_announce): its fetch
        heartbeat stays fresh, so only repeated stall reports can name
        it — past the failure timeout the repeat offender surfaces as a
        typed failure event instead of warning forever."""
        svc = CoordinatorService(nproc=2, key=make_secret_key(),
                                 native=False, stall_warning_s=0.05)
        svc.failure_timeout_s = 0.2
        # Rank 1's client carries a drop_announce injector — announces
        # are swallowed client-side, exactly the fault's shape.
        monkeypatch.setattr(
            faults_mod, "_injector",
            FaultInjector(parse_spec("rank=1:drop_announce"), rank=1))
        monkeypatch.setattr(faults_mod, "_resolved", True)
        c1 = CoordinatorClient([("127.0.0.1", svc.port)], svc.key, 1)
        try:
            svc._announce(AnnounceRequest(
                0, [{"name": "t0", "op": 0, "dtype": "float32",
                     "shape": (4,), "root_rank": -1, "device": 0}],
                announce_id=1))
            c1.announce([{"name": "t0", "op": 0, "dtype": "float32",
                          "shape": (4,), "root_rank": -1, "device": 0}])
            # The dropped announce never reached the table.
            assert "t0" in svc._table
            assert 1 not in svc._table["t0"].ranks
            # First report blames rank 1; heartbeats stay fresh the
            # whole time, so heartbeat detection alone would stay
            # silent.
            time.sleep(0.06)
            svc._last_stall_check = 0.0
            assert svc.check_stalls()
            assert 1 in svc._stall_blame
            resp = svc._fetch(FetchRequest(1, 0, 0.0))
            assert not any(f["kind"] == "heartbeat_timeout"
                           for f in resp.failures)
            # Past the failure window, a repeated report escalates.
            time.sleep(0.25)
            svc._last_stall_check = 0.0
            svc.check_stalls()
            resp = svc._fetch(FetchRequest(1, 0, 0.0))
            stalls = [f for f in resp.failures if f["kind"] == "stall"]
            assert any(f["rank"] == 1 for f in stalls)
        finally:
            faults_mod.reset()
            svc.shutdown()

    def test_blame_cleared_when_episode_resolves(self):
        svc = CoordinatorService(nproc=2, key=make_secret_key(),
                                 native=False, stall_warning_s=0.05)
        svc.failure_timeout_s = 10.0
        try:
            svc._announce(AnnounceRequest(
                0, [{"name": "t1", "op": 0, "dtype": "float32",
                     "shape": (4,), "root_rank": -1, "device": 0}],
                announce_id=1))
            time.sleep(0.06)
            svc._last_stall_check = 0.0
            svc.check_stalls()
            assert 1 in svc._stall_blame
            # Rank 1 finally announces: quorum completes, the next
            # check names nobody, the blame entry is dropped.
            svc._announce(AnnounceRequest(
                1, [{"name": "t1", "op": 0, "dtype": "float32",
                     "shape": (4,), "root_rank": -1, "device": 0}],
                announce_id=1))
            time.sleep(0.06)
            svc._last_stall_check = 0.0
            svc.check_stalls()
            assert svc._stall_blame == {}
        finally:
            svc.shutdown()


# ---------------------------------------------------------------------------
# Hardened coordinator client
# ---------------------------------------------------------------------------

class TestCoordinatorClientRetry:
    def test_dead_coordinator_raises_typed_error_bounded(self):
        svc = CoordinatorService(nproc=1, key=make_secret_key(),
                                 native=False)
        key, port = svc.key, svc.port
        client = CoordinatorClient([("127.0.0.1", port)], key, 0,
                                   retries=3, backoff_s=0.05)
        client.fetch(wait_s=0.0)            # rendezvous established
        svc.shutdown()
        # Emulate real coordinator death: the persistent connection
        # breaks too (in-process, shutdown only stops the listener).
        client._client.close()
        t0 = time.monotonic()
        with pytest.raises(CoordinatorUnreachableError,
                           match="unreachable after 3 attempts"):
            client.fetch(wait_s=0.0)
        # Bounded: 2 backoff sleeps of <= ~0.1*1.5 s plus connect
        # overhead — seconds, never the old hang.
        assert time.monotonic() - t0 < 5.0

    def test_recovers_across_flapping_coordinator(self):
        """The retry/backoff schedule rides out a coordinator restart on
        the same port (the flapping-server scenario)."""
        svc = CoordinatorService(nproc=1, key=make_secret_key(),
                                 native=False)
        key, port = svc.key, svc.port
        client = CoordinatorClient([("127.0.0.1", port)], key, 0,
                                   retries=8, backoff_s=0.05)
        client.fetch(wait_s=0.0)
        svc.shutdown()                      # flap down
        client._client.close()              # connection breaks with it
        holder = {}

        def restart():
            time.sleep(0.3)                 # a few failed retries first
            holder["svc"] = CoordinatorService(
                nproc=1, key=key, native=False, port=port)

        t = threading.Thread(target=restart, daemon=True)
        t.start()
        try:
            resp = client.fetch(wait_s=0.0)  # survives the flap
            assert resp.groups == []
        finally:
            t.join()
            holder["svc"].shutdown()

    def test_unreachable_is_connection_error(self):
        # Existing `except ConnectionError` transport handlers keep
        # catching the typed failure.
        assert issubclass(CoordinatorUnreachableError, ConnectionError)


# ---------------------------------------------------------------------------
# Straggler telemetry re-keyed across world sizes
# ---------------------------------------------------------------------------

class TestSkewRekey:
    def test_evicted_rank_does_not_linger(self):
        svc4 = CoordinatorService(nproc=4, key=make_secret_key(),
                                  native=False)
        try:
            _skew(svc4, rank_late=3, lateness=0.05)
            snap = hvd.metrics_snapshot()
            vals = snap["hvdtpu_negotiate_lateness_seconds"]["values"]
            assert 'rank="3"' in vals
            assert snap["hvdtpu_straggler_rank"]["values"][""] == 3
        finally:
            svc4.shutdown()
        # Re-rendezvous at world size 2: the evicted ranks' series are
        # re-keyed away and the straggler election resets.
        svc2 = CoordinatorService(nproc=2, key=make_secret_key(),
                                  native=False)
        try:
            snap = hvd.metrics_snapshot()
            vals = snap["hvdtpu_negotiate_lateness_seconds"]["values"]
            assert 'rank="3"' not in vals
            assert set(vals) == {'rank="0"', 'rank="1"'}
            assert snap["hvdtpu_straggler_rank"]["values"][""] == -1
            assert snap["hvdtpu_straggler_lateness_seconds"][
                "values"][""] == 0.0
        finally:
            svc2.shutdown()


# ---------------------------------------------------------------------------
# Engine: wire-epoch selection
# ---------------------------------------------------------------------------

class TestWireOverride:
    def _group(self, dtype=jnp.float32, **kw):
        from horovod_tpu.ops import collective as coll
        h = coll.Handle(1, "t")
        return [coll._Request("t", coll.ALLREDUCE,
                              jnp.ones((8,), dtype), h, **kw)]

    def test_epoch_selection_by_seq(self):
        from horovod_tpu.ops import collective as coll
        eng = coll.CollectiveEngine()
        eng._wire_epochs = [(5, "bf16"), (9, "int8x256"), (12, "")]
        g = self._group()
        assert eng._wire_override_for(4, g) is None
        assert eng._wire_override_for(5, g) == "bf16"
        assert eng._wire_override_for(8, g) == "bf16"
        assert eng._wire_override_for(9, g) == "int8x256"
        assert eng._wire_override_for(12, g) is None   # back to raw
        assert eng._wire_override_for(None, g) is None

    def test_ineligible_groups_untouched(self):
        from horovod_tpu.ops import collective as coll
        eng = coll.CollectiveEngine()
        eng._wire_epochs = [(0, "int8x256")]
        assert eng._wire_override_for(
            3, self._group(dtype=jnp.int32)) is None
        assert eng._wire_override_for(
            3, self._group(wire="fp8x256")) is None   # explicit user wire
        assert eng._wire_override_for(3, self._group()) == "int8x256"

    def test_side_channel_installs_epochs(self):
        from horovod_tpu.ops import collective as coll
        from horovod_tpu.ops.control_plane import FetchResponse
        eng = coll.CollectiveEngine()
        resp = FetchResponse([], False,
                             params={"wire_epochs": [[3, "bf16"]]})
        eng._apply_fetch_side_channel(resp)
        assert eng._wire_epochs == [(3, "bf16")]


# ---------------------------------------------------------------------------
# Typed failure plumbing + slot penalties
# ---------------------------------------------------------------------------

class TestTypedFailurePropagation:
    def test_failure_from_event_types(self):
        f = failure_from_event({"rank": 2, "kind": "slow_rank",
                                "detail": "late"})
        assert isinstance(f, SlowRankFailure) and f.rank == 2
        f = failure_from_event({"rank": 1, "kind": "heartbeat_timeout"})
        assert isinstance(f, WorkerFailure)
        assert not isinstance(f, SlowRankFailure)

    def test_slow_rank_failure_pickles(self):
        import pickle
        f = SlowRankFailure(rank=3, host="h1", detail="late")
        g = pickle.loads(pickle.dumps(f))
        assert isinstance(g, SlowRankFailure)
        assert (g.rank, g.host, g.kind) == (3, "h1", "slow_rank")

    def test_driver_service_reraises_typed_failure(self):
        from horovod_tpu.runner.driver_service import DriverService
        from horovod_tpu.runner.timeout import Timeout
        svc = DriverService(2, make_secret_key(), b"")
        try:
            svc._results[0] = (None, SlowRankFailure(rank=1, detail="x"))
            svc._results[1] = ({"ok": True}, None)
            svc._all_done.set()
            with pytest.raises(SlowRankFailure) as ei:
                svc.wait_for_results(Timeout(5, "t {timeout}"))
            assert ei.value.rank == 1
        finally:
            svc.shutdown()

    def test_plain_errors_keep_runtime_error(self):
        from horovod_tpu.runner.driver_service import DriverService
        from horovod_tpu.runner.timeout import Timeout
        svc = DriverService(1, make_secret_key(), b"")
        try:
            svc._results[0] = (None, "Traceback ... boom")
            svc._all_done.set()
            with pytest.raises(RuntimeError, match="rank 0"):
                svc.wait_for_results(Timeout(5, "t {timeout}"))
        finally:
            svc.shutdown()


class TestSlotPenaltyReadmission:
    def test_probe_gates_readmission_with_backoff(self):
        from horovod_tpu.elastic.driver import _SlotPenalties
        verdict = {"alive": False}
        calls = []

        def probe(host):
            calls.append(host)
            return verdict["alive"]

        p = _SlotPenalties(0.05, probe=probe, backoff_factor=2.0)
        p.penalize("h1", window_s=0.05)
        slots = [("h1", 2)]
        assert p.apply(slots) == [("h1", 1)]      # penalty active
        time.sleep(0.06)
        # Expired but probe fails → renewed with doubled window.
        assert p.apply(slots) == [("h1", 1)]
        assert calls == ["h1"]
        assert p._until["h1"][0][1] == pytest.approx(0.1)
        time.sleep(0.11)
        verdict["alive"] = True                   # host recovered
        assert p.apply(slots) == [("h1", 2)]      # readmitted
        assert calls == ["h1", "h1"]

    def test_no_probe_expiry_readmits(self):
        from horovod_tpu.elastic.driver import _SlotPenalties
        p = _SlotPenalties(0.03)
        p.penalize("h1")
        assert p.apply([("h1", 1)]) == []
        time.sleep(0.04)
        assert p.apply([("h1", 1)]) == [("h1", 1)]

    def test_slow_rank_window_distinct(self):
        from horovod_tpu.elastic.driver import _SlotPenalties
        p = _SlotPenalties(100.0)
        p.penalize("h1", window_s=0.02)           # slow-rank short window
        time.sleep(0.03)
        assert p.apply([("h1", 1)]) == [("h1", 1)]

    def test_host_alive_local(self):
        from horovod_tpu.elastic.discovery import host_alive
        assert host_alive("localhost")


# ---------------------------------------------------------------------------
# Error-feedback residual reset on a mid-run wire switch
# ---------------------------------------------------------------------------

class TestErrorFeedbackSpecSwitch:
    def test_residual_reset_on_set_compression(self):
        import optax
        from horovod_tpu.compression import Compression

        opt = hvd.DistributedOptimizer(optax.sgd(0.1),
                                       compression=Compression.int8_blockwise)
        rng = np.random.RandomState(0)
        w = jnp.asarray(rng.standard_normal(512).astype(np.float32))
        g = jnp.asarray(rng.standard_normal(512).astype(np.float32))
        state = opt.init(w)
        _, state = opt.update(g, state, w)
        # Residual is measured against the int8 roundtrip of g.
        expect_int8 = np.asarray(
            g - Compression.int8_blockwise.local_roundtrip(g))
        np.testing.assert_allclose(np.asarray(state.residual),
                                   expect_int8, rtol=1e-6, atol=1e-7)
        # Switch specs mid-run: the carried residual belongs to the OLD
        # quantizer — the next update must start from zero, so the new
        # residual is exactly g - fp8_roundtrip(g), NOT contaminated by
        # the int8 residual.
        opt.set_compression(Compression.fp8_blockwise)
        _, state = opt.update(g, state, w)
        expect_fp8 = np.asarray(
            g - Compression.fp8_blockwise.local_roundtrip(g))
        np.testing.assert_allclose(np.asarray(state.residual),
                                   expect_fp8, rtol=1e-6, atol=1e-7)

    def test_ef_default_rederived_unless_pinned(self):
        import optax
        from horovod_tpu.compression import Compression

        opt = hvd.DistributedOptimizer(optax.sgd(0.1),
                                       compression=Compression.int8_blockwise)
        assert opt.error_feedback
        opt.set_compression(Compression.none)
        assert not opt.error_feedback          # re-derived: no lossy wire
        pinned = hvd.DistributedOptimizer(optax.sgd(0.1),
                                          compression=Compression.none,
                                          error_feedback=True)
        pinned.set_compression(Compression.int8_blockwise)
        assert pinned.error_feedback           # explicit choice survives


# ---------------------------------------------------------------------------
# Runner CLI
# ---------------------------------------------------------------------------

class TestRunnerFaultCLI:
    def test_bad_fault_spec_rejected_at_launch(self):
        import sys
        from horovod_tpu.runner.__main__ import main
        with pytest.raises(ValueError, match="fault-spec"):
            main(["-np", "1", "--fault-spec", "delay=80ms", "--",
                  sys.executable, "-c", "pass"])

    def test_fault_spec_and_adaptation_exported(self):
        import sys
        from horovod_tpu.runner.__main__ import main
        rc = main(["-np", "1", "--no-tag-output",
                   "--fault-spec", "rank=9:delay=1ms", "--adaptation",
                   "--",
                   sys.executable, "-c",
                   "import os; assert os.environ['HOROVOD_TPU_FAULT_SPEC']"
                   " == 'rank=9:delay=1ms'; "
                   "assert os.environ['HOROVOD_TPU_ADAPTATION'] == '1'"])
        assert rc == 0
