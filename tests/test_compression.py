"""Wire compression tests — cast-compressor round trips, the block-scaled
quantized allreduce (EQuARX-style dual quantization inside the fused XLA
program), wire-byte accounting, and error-feedback convergence."""

import json
import os
import subprocess
import sys

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu import quantization as quant
from horovod_tpu.compression import Compression
from horovod_tpu.ops import collective as _coll


def _gradient_like(n, seed=0):
    """Realistic gradient sample: zero-mean with per-slice magnitude
    spread (layers differ by orders of magnitude)."""
    rng = np.random.RandomState(seed)
    x = rng.standard_normal(n).astype(np.float32)
    thirds = n // 3
    x[:thirds] *= 1e-3
    x[thirds:2 * thirds] *= 1e-1
    return x


ALL_COMPRESSORS = ["none", "fp16", "bf16", "fp8", "int8_blockwise",
                   "fp8_blockwise"]
INPUT_DTYPES = [jnp.float32, jnp.bfloat16, jnp.float8_e4m3fn]


class TestCastRoundTrip:
    @pytest.mark.parametrize("comp_name", ALL_COMPRESSORS)
    @pytest.mark.parametrize("in_dtype", INPUT_DTYPES,
                             ids=["fp32", "bf16", "fp8"])
    def test_compress_decompress_restores_dtype(self, comp_name, in_dtype):
        """Every compressor must hand back the caller's dtype — including
        the non-default floats (bf16/fp8 inputs), which older decompress
        logic silently left at the wire dtype."""
        comp = getattr(Compression, comp_name)
        x = jnp.asarray([1.0, -0.5, 0.25, 2.0], in_dtype)
        wire, ctx = comp.compress(x)
        back = comp.decompress(wire, ctx)
        assert back.dtype == x.dtype
        # Identity up to the wire format's resolution.
        got = np.asarray(back, np.float32)
        want = np.asarray(x, np.float32)
        assert np.allclose(got, want, rtol=0.2, atol=0.1)

    @pytest.mark.parametrize("comp_name", ALL_COMPRESSORS)
    @pytest.mark.parametrize("in_dtype", INPUT_DTYPES,
                             ids=["fp32", "bf16", "fp8"])
    def test_allreduce_roundtrip_restores_dtype(self, comp_name, in_dtype):
        """Same matrix through the full eager allreduce path."""
        comp = getattr(Compression, comp_name)
        x = jnp.asarray([1.0, -0.5, 0.25, 2.0], in_dtype)
        out = hvd.allreduce(x, average=True,
                            name=f"rt.{comp_name}.{in_dtype.__name__}",
                            compression=comp)
        assert out.dtype == x.dtype
        got = np.asarray(out, np.float32)
        want = np.asarray(x, np.float32)
        assert np.allclose(got, want, rtol=0.2, atol=0.1)

    def test_int_tensor_passthrough(self):
        x = jnp.asarray([1, 2, 3], jnp.int32)
        for comp_name in ALL_COMPRESSORS:
            comp = getattr(Compression, comp_name)
            wire, ctx = comp.compress(x)
            assert wire.dtype == jnp.int32
            assert comp.decompress(wire, ctx).dtype == jnp.int32


class TestBlockwiseQuantization:
    def test_roundtrip_error_bound(self):
        """local_roundtrip error is bounded by half a quantization step
        of each block's absmax."""
        x = jnp.asarray(_gradient_like(2048))
        spec = quant.parse("int8x256")
        back = np.asarray(quant.local_roundtrip(x, spec))
        err = np.abs(back - np.asarray(x)).reshape(-1, 256)
        absmax = np.abs(np.asarray(x)).reshape(-1, 256).max(axis=1)
        assert np.all(err.max(axis=1) <= absmax / 127.0 * 0.51 + 1e-12)

    def test_int8_blockwise_beats_fp8_cast(self):
        """On a realistic gradient distribution, blockwise int8's max
        relative error (normalized by tensor absmax) beats the plain fp8
        cast — the motivating accuracy claim."""
        x = np.asarray(_gradient_like(4096))
        scale = np.abs(x).max()
        int8_rt = np.asarray(
            quant.local_roundtrip(jnp.asarray(x), "int8x256"), np.float32)
        fp8_rt = np.asarray(
            jnp.asarray(x).astype(jnp.float8_e4m3fn), np.float32)
        int8_err = np.abs(int8_rt - x).max() / scale
        fp8_err = np.abs(fp8_rt - x).max() / scale
        assert int8_err < fp8_err
        assert int8_err <= 1e-2

    def test_zero_blocks_survive(self):
        x = jnp.zeros((512,), jnp.float32)
        back = np.asarray(quant.local_roundtrip(x, "int8x256"))
        assert np.all(back == 0.0)

    def test_wire_nbytes(self):
        # 1 payload byte per element (padded to whole blocks) + one fp32
        # scale per block.
        assert quant.wire_nbytes("int8x256", 256) == 256 + 4
        assert quant.wire_nbytes("int8x256", 257) == 512 + 8
        assert quant.wire_nbytes("fp8x256", 1024) == 1024 + 16


class TestQuantizedAllreduce:
    def test_int8_blockwise_allreduce_accuracy(self):
        """Acceptance: averaged allreduce of replicated tensors through
        the dual-quantized wire is the identity to within 1e-2 max
        relative error per tensor."""
        x = jnp.asarray(_gradient_like(3000, seed=3))
        out = hvd.allreduce(x, average=True, name="q.acc.int8",
                            compression=Compression.int8_blockwise)
        assert out.dtype == jnp.float32
        rel = float(jnp.max(jnp.abs(out - x))) / float(jnp.max(jnp.abs(x)))
        assert rel <= 1e-2, rel

    def test_fp8_blockwise_allreduce_sane(self):
        x = jnp.asarray(_gradient_like(1024, seed=4))
        out = hvd.allreduce(x, average=True, name="q.acc.fp8",
                            compression=Compression.fp8_blockwise)
        rel = float(jnp.max(jnp.abs(out - x))) / float(jnp.max(jnp.abs(x)))
        assert rel <= 0.1, rel

    def test_sum_scales_with_size(self):
        """average=False: every virtual rank contributes its copy."""
        x = jnp.asarray(_gradient_like(512, seed=5))
        out = hvd.allreduce(x, average=False, name="q.sum.int8",
                            compression=Compression.int8_blockwise)
        ref = np.asarray(x) * hvd.size()
        rel = float(np.max(np.abs(np.asarray(out) - ref))) / \
            float(np.max(np.abs(ref)))
        assert rel <= 1e-2, rel

    def test_bf16_input_quantized_wire(self):
        x = jnp.asarray(_gradient_like(512, seed=6)).astype(jnp.bfloat16)
        out = hvd.allreduce(x, average=True, name="q.bf16in",
                            compression=Compression.int8_blockwise)
        assert out.dtype == jnp.bfloat16
        got = np.asarray(out.astype(jnp.float32))
        want = np.asarray(x.astype(jnp.float32))
        rel = np.max(np.abs(got - want)) / np.max(np.abs(want))
        assert rel <= 2e-2, rel

    def test_mixed_wire_burst(self):
        """A burst mixing wire formats subdivides correctly — every
        result is right and nothing is silently cross-fused."""
        xs = [jnp.asarray(_gradient_like(300, seed=7 + i)) for i in range(4)]
        comps = [None, Compression.int8_blockwise, None,
                 Compression.int8_blockwise]
        with _coll.engine().burst():
            hs = [_coll.allreduce_async(x, average=True, name=f"mix.{i}",
                                        compression=c)
                  for i, (x, c) in enumerate(zip(xs, comps))]
        for x, h in zip(xs, hs):
            out = np.asarray(h.wait())
            rel = np.max(np.abs(out - np.asarray(x))) / \
                np.max(np.abs(np.asarray(x)))
            assert rel <= 1e-2, rel

    def test_wire_byte_accounting(self):
        """Acceptance: blockwise int8 allreduce of a gradient pytree is
        accounted at <= 0.30x the fp32 wire bytes."""
        tree = {"a": jnp.asarray(_gradient_like(5000, seed=8)),
                "b": jnp.asarray(_gradient_like(301, seed=9)),
                "c": jnp.asarray(_gradient_like(77, seed=10))}
        eng = _coll.engine()
        base = eng.wire_bytes_enqueued
        hvd.allreduce_gradients(tree, average=True)
        fp32_bytes = eng.wire_bytes_enqueued - base
        base = eng.wire_bytes_enqueued
        hvd.allreduce_gradients(tree, average=True,
                                compression=Compression.int8_blockwise)
        q_bytes = eng.wire_bytes_enqueued - base
        assert fp32_bytes == sum(int(v.size) * 4 for v in tree.values())
        assert q_bytes / fp32_bytes <= 0.30, (q_bytes, fp32_bytes)

    def test_multiprocess_fused_path_block_aligned(self):
        """allreduce_fused_mp with a wire spec must block-align each
        tensor's span in the packed buffer: back-to-back packing lets a
        large-magnitude neighbor's absmax swallow a small tensor's
        resolution (measured 32% rel err before the fix)."""
        from horovod_tpu import executor as ex_mod
        ex = ex_mod.CollectiveExecutor(mesh=hvd.mesh())
        small = jnp.asarray(_gradient_like(700, seed=20) * 0.01)
        big = jnp.asarray(_gradient_like(130, seed=21))
        for device_pack in (True, False):
            ex._device_pack_flag = device_pack
            outs = ex.allreduce_fused_mp(
                [small, big], postscale=1.0 / hvd.size(), wire="int8x256")
            for t, o in zip([small, big], outs):
                rel = float(np.max(np.abs(np.asarray(o) - np.asarray(t)))) \
                    / float(np.max(np.abs(np.asarray(t))))
                assert rel <= 1e-2, (device_pack, rel)
        # Non-float tensors in a wire group keep the exact psum path.
        out = ex.allreduce_fused_mp([jnp.arange(10, dtype=jnp.int32)],
                                    wire="int8x256")[0]
        np.testing.assert_array_equal(
            np.asarray(out), np.arange(10) * hvd.size())

    def test_quantized_allreduce_in_shard_map(self):
        """In-jit path: the dual-quantized reduce lowers inside the
        user's shard_map program and matches the psum reference."""
        mesh = hvd.mesh()
        n = hvd.size()

        def per_shard(g):
            return hvd.allreduce_gradients(
                g, average=True, axis_name="dp",
                compression=Compression.int8_blockwise)

        f = jax.jit(jax.shard_map(
            per_shard, mesh=mesh, in_specs=P("dp"), out_specs=P(),
            check_vma=False))
        x = jnp.asarray(_gradient_like(n * 64, seed=11))
        ref = np.asarray(x).reshape(n, 64).mean(axis=0)
        got = np.asarray(f(x))
        scale = np.abs(ref).max()
        assert np.max(np.abs(got - ref)) / scale <= 2e-2

    def test_not_under_shard_map_is_identity(self):
        """jit-over-sharded-data: grads are already global, nothing
        crosses a wire — blockwise compression must be the identity."""
        @jax.jit
        def f(g):
            return hvd.allreduce_gradients(
                g, average=True, compression=Compression.int8_blockwise)

        x = jnp.asarray(_gradient_like(128, seed=12))
        assert np.allclose(np.asarray(f(x)), np.asarray(x))


class TestErrorFeedback:
    def _train(self, comp, steps=50, error_feedback=None, lr=0.05):
        rng = np.random.RandomState(42)
        X = jnp.asarray(rng.standard_normal((64, 16)).astype(np.float32))
        w_true = jnp.asarray(rng.standard_normal(16).astype(np.float32))
        y = X @ w_true

        def loss(w):
            return jnp.mean((X @ w - y) ** 2)

        opt = hvd.DistributedOptimizer(optax.sgd(lr), compression=comp,
                                       error_feedback=error_feedback)
        w = jnp.zeros((16,))
        state = opt.init(w)
        for _ in range(steps):
            g = jax.grad(loss)(w)
            u, state = opt.update(g, state, w)
            w = optax.apply_updates(w, u)
        return float(loss(w)), state

    def test_int8_blockwise_converges_to_fp32(self):
        """Acceptance: 50-step quadratic run with int8_blockwise + error
        feedback lands within 1% of the fp32 loss."""
        l_fp32, _ = self._train(Compression.none)
        l_q, state = self._train(Compression.int8_blockwise)
        assert state.residual is not None  # EF on by default for blockwise
        assert abs(l_q - l_fp32) <= 0.01 * max(l_fp32, 1e-12), (l_q, l_fp32)

    def test_residual_tracks_wire_error(self):
        comp = Compression.int8_blockwise
        opt = hvd.DistributedOptimizer(optax.sgd(0.1), compression=comp)
        w = jnp.zeros((600,))
        state = opt.init(w)
        assert np.all(np.asarray(state.residual) == 0.0)
        g = jnp.asarray(_gradient_like(600, seed=13))
        _, state = opt.update(g, state, w)
        expected = np.asarray(g) - np.asarray(comp.local_roundtrip(g))
        assert np.allclose(np.asarray(state.residual), expected, atol=1e-7)

    def test_error_feedback_opt_out(self):
        _, state = self._train(Compression.int8_blockwise, steps=2,
                               error_feedback=False)
        assert state.residual is None

    def test_error_feedback_with_accumulation(self):
        """backward_passes_per_step > 1 + EF: residual only updates at
        sync steps and training still works."""
        comp = Compression.int8_blockwise
        opt = hvd.DistributedOptimizer(optax.sgd(1.0), compression=comp,
                                       backward_passes_per_step=2)
        w = jnp.zeros((300,))
        state = opt.init(w)
        g = jnp.asarray(_gradient_like(300, seed=14))
        u1, state = opt.update(g, state, w)
        assert np.all(np.asarray(u1) == 0.0)          # accumulating
        assert np.all(np.asarray(state.residual) == 0.0)
        u2, state = opt.update(g, state, w)
        assert not np.all(np.asarray(u2) == 0.0)      # sync step applied
        expected = np.asarray(g) - np.asarray(comp.local_roundtrip(g))
        assert np.allclose(np.asarray(state.residual), expected, atol=1e-7)

    def test_pre_ef_state_accepted(self):
        """A state without the residual field (pre-EF checkpoint shape)
        must not crash an EF-enabled update."""
        comp = Compression.int8_blockwise
        opt = hvd.DistributedOptimizer(optax.sgd(0.1), compression=comp)
        w = jnp.zeros((256,))
        state = opt.init(w)
        old_style = state._replace(residual=None)
        g = jnp.asarray(_gradient_like(256, seed=15))
        _, new_state = opt.update(g, old_style, w)
        assert new_state.residual is not None


@pytest.mark.slow
class TestCompressionBenchReproducible:
    def test_bench_compression_smoke_and_determinism(self, tmp_path):
        """bench_engine.py --compression regenerates BENCH_COMPRESSION
        rows reproducibly: two runs agree on every recorded delta
        (seeded, no wall-clock dependence)."""
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        outs = []
        for i in range(2):
            out = tmp_path / f"bench{i}.json"
            subprocess.run(
                [sys.executable, os.path.join(root, "bench_engine.py"),
                 "--compression", "--steps", "8", "--out", str(out)],
                check=True, capture_output=True, text=True, timeout=600,
                cwd=root)
            outs.append(json.loads(out.read_text()))
        for mode in ["fp32", "bf16_cast", "fp8_cast", "int8_blockwise",
                     "fp8_blockwise"]:
            a, b = outs[0]["rows"][mode], outs[1]["rows"][mode]
            for key in ["wire_bytes", "wire_ratio_vs_fp32", "max_rel_err",
                        "final_loss", "loss_ratio_vs_fp32"]:
                assert a[key] == b[key], (mode, key, a[key], b[key])
        row = outs[0]["rows"]["int8_blockwise"]
        assert row["wire_ratio_vs_fp32"] <= 0.30
        assert row["max_rel_err"] <= 1e-2
