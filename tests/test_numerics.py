"""Numerics observability plane units (ISSUE 20, docs/numerics.md):
nonfinite sentinels, deferred in-graph step stats, cross-rank
fingerprint compare, the bitflip fault hook, the adaptation policy's
quantization-drift quality backoff, and checkpoint value fingerprints.
"""

import math
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import horovod_tpu as hvd
from horovod_tpu.adaptation import faults as faults_mod
from horovod_tpu.adaptation.policy import (AdaptationConfig,
                                           AdaptationPolicy)
from horovod_tpu.checkpoint import CheckpointEngine, CorruptShardError
from horovod_tpu.checkpoint import engine as _ck_engine
from horovod_tpu.checkpoint import manifest as _manifest
from horovod_tpu.observability import numerics


@pytest.fixture(autouse=True)
def _init():
    hvd.init()
    yield


@pytest.fixture(autouse=True)
def _plane():
    """Arm the plane for the test, and leave no pending state behind."""
    numerics.set_enabled(True)
    numerics.reset_fingerprints()
    yield
    numerics.step_stats().flush()
    numerics.set_enabled(False)
    numerics.reset_fingerprints()


def _counter(family, key):
    snap = hvd.metrics_snapshot(prefix=family)
    return (snap.get(family) or {"values": {}})["values"].get(key, 0)


# ---------------------------------------------------------------------------
# Nonfinite sentinels
# ---------------------------------------------------------------------------

class TestCountNonfinite:
    def test_clean_buffer_is_zero(self):
        assert numerics.count_nonfinite(
            np.arange(1024, dtype=np.float32)) == 0

    def test_exact_count(self):
        a = np.zeros(64, np.float32)
        a[3] = np.nan
        a[10], a[11] = np.inf, -np.inf
        assert numerics.count_nonfinite(a) == 3

    def test_integer_dtype_is_finite_by_construction(self):
        assert numerics.count_nonfinite(np.arange(8)) == 0

    def test_overflowing_finite_buffer_is_zero(self):
        # The fast path (finite dot => all finite) overflows on large
        # finite values and must fall through to the exact count, not
        # report a false positive.
        a = np.full(16, 3e19, np.float32)     # square overflows f32
        assert not math.isfinite(float(np.dot(a, a)))
        assert numerics.count_nonfinite(a) == 0

    def test_multidim_buffer(self):
        a = np.ones((4, 4), np.float32)
        a[1, 2] = np.nan
        assert numerics.count_nonfinite(a) == 1


class TestScanPayload:
    def test_disabled_is_noop(self):
        numerics.set_enabled(False)
        a = np.full(8, np.nan, np.float32)
        assert numerics.scan_payload(a) == 0

    def test_poisoned_buffer_counts_and_alerts(self):
        key = 'source="collective"'
        fam = "hvdtpu_numerics_nonfinite_total"
        before = _counter(fam, key)
        a = np.ones(128, np.float32)
        a[17] = np.nan
        assert numerics.scan_payload(a) == 1
        assert _counter(fam, key) == before + 1
        # The same-step alert went through the health fan-out.
        akey = 'kind="nonfinite_rate",severity="critical"'
        assert _counter("hvdtpu_health_alerts_total", akey) >= 1


# ---------------------------------------------------------------------------
# Deferred step stats (the build_train_step aux channel's host sink)
# ---------------------------------------------------------------------------

class TestStepStats:
    def test_one_step_deferral(self):
        stats = numerics.StepStats()
        aux0 = {"grad_norm": np.float32(2.5),
                "update_ratio": np.float32(0.01),
                "nonfinite_by_rank": np.zeros(2, np.float32)}
        stats.note(0, np.float32(1.0), aux0)
        # Step 0 is pending: the gauges must not hold 2.5 yet unless a
        # later note materializes it.
        stats.note(1, np.float32(0.9), {"grad_norm": np.float32(3.5)})
        snap = hvd.metrics_snapshot(prefix="hvdtpu_numerics_")
        gn = snap["hvdtpu_numerics_grad_norm"]["values"][""]
        assert gn == pytest.approx(2.5)     # step 0, not step 1
        stats.flush()
        snap = hvd.metrics_snapshot(prefix="hvdtpu_numerics_")
        gn = snap["hvdtpu_numerics_grad_norm"]["values"][""]
        assert gn == pytest.approx(3.5)
        loss = snap["hvdtpu_numerics_loss"]["values"][""]
        assert loss == pytest.approx(0.9)

    def test_per_rank_nonfinite_vector_names_the_rank(self):
        fam = "hvdtpu_numerics_nonfinite_total"
        key = 'source="grad"'
        before = _counter(fam, key)
        stats = numerics.StepStats()
        stats.note(5, np.float32(1.0),
                   {"nonfinite_by_rank": np.array([0.0, 4.0, 0.0])})
        stats.flush()
        assert _counter(fam, key) == before + 4

    def test_nonfinite_loss_is_itself_a_sentinel(self):
        fam = "hvdtpu_numerics_nonfinite_total"
        key = 'source="loss"'
        before = _counter(fam, key)
        stats = numerics.StepStats()
        stats.note(9, np.float32(np.nan), {})
        stats.flush()
        assert _counter(fam, key) == before + 1


# ---------------------------------------------------------------------------
# Fingerprints + divergence compare
# ---------------------------------------------------------------------------

class TestFingerprints:
    def test_identical_values_identical_digests(self):
        a = np.arange(4096, dtype=np.float32) / 3.0
        assert numerics.fingerprint_leaf("w", a) == \
            numerics.fingerprint_leaf("w", a.copy())

    def test_element0_bitflip_changes_crc(self):
        a = np.arange(1.0, 4097.0, dtype=np.float32)
        fp = numerics.fingerprint_leaf("w", a)
        flipped = numerics.flip_mantissa_bit(a, index=0, bit=5)
        fp2 = numerics.fingerprint_leaf("w", flipped)
        assert fp2[1] != fp[1]              # element 0 is always sampled

    def test_unsampled_bitflip_still_changes_norm(self):
        a = np.arange(1.0, 4097.0, dtype=np.float32)
        fp = numerics.fingerprint_leaf("w", a)
        # Whichever element the seeded subsample skips, the float64
        # norm covers the whole buffer.
        flipped = numerics.flip_mantissa_bit(a, index=1234, bit=12)
        assert numerics.fingerprint_leaf("w", flipped)[0] != fp[0]

    def test_majority_compare_names_leaf_and_rank(self):
        a = np.arange(256, dtype=np.float32)
        good = numerics.fingerprint_tree({"w": a, "b": a[:8]})
        bad = numerics.fingerprint_tree(
            {"w": numerics.flip_mantissa_bit(a, index=0, bit=3),
             "b": a[:8]})
        out = numerics.compare_fingerprints({0: good, 1: bad, 2: good})
        assert out == [("['w']", 1)]

    def test_record_fingerprint_fires_rank_divergence(self):
        a = np.arange(64, dtype=np.float32)
        good = numerics.fingerprint_tree({"w": a})
        bad = numerics.fingerprint_tree(
            {"w": numerics.flip_mantissa_bit(a, index=0, bit=3)})
        fam = "hvdtpu_numerics_fingerprints_total"
        before = _counter(fam, 'event="mismatch"')
        assert numerics.record_fingerprint(0, 10, good, 3) == []
        assert numerics.record_fingerprint(2, 10, good, 3) == []
        out = numerics.record_fingerprint(1, 10, bad, 3)
        assert out == [("['w']", 1)]
        assert _counter(fam, 'event="mismatch"') == before + 1
        akey = 'kind="rank_divergence",severity="critical"'
        assert _counter("hvdtpu_health_alerts_total", akey) >= 1

    def test_stale_step_evicted_and_still_compared(self):
        a = np.arange(64, dtype=np.float32)
        good = numerics.fingerprint_tree({"w": a})
        bad = numerics.fingerprint_tree(
            {"w": numerics.flip_mantissa_bit(a, index=0, bit=3)})
        # Step 0 never completes (rank 1 of 3 missing); newer steps pile
        # up until the pending window (4) evicts it — the partial pair
        # must still be compared so the divergence is not lost.
        assert numerics.record_fingerprint(0, 0, good, 3) == []
        assert numerics.record_fingerprint(2, 0, bad, 3) == []
        out = []
        for step in range(1, 6):
            out += numerics.record_fingerprint(0, step, good, 3)
        assert ("['w']", 2) in out


# ---------------------------------------------------------------------------
# bitflip_param fault hook
# ---------------------------------------------------------------------------

class TestMaybeBitflip:
    def test_armed_clause_flips_target_leaf_once(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_TPU_FAULT_SPEC",
                           "rank=0:bitflip_param=2:leaf=w")
        faults_mod.reset()
        try:
            before = _counter("hvdtpu_fault_injections_total",
                              'kind="bitflip"')
            tree = {"w": jnp.arange(1.0, 9.0), "b": jnp.zeros(4)}
            same = numerics.maybe_bitflip(tree, 0)
            assert same is tree              # not armed for this step
            out = numerics.maybe_bitflip(tree, 2)
            w = np.asarray(out["w"])
            assert w[0] != 1.0               # element 0 of 'w' flipped
            np.testing.assert_array_equal(np.asarray(out["b"]),
                                          np.zeros(4))
            assert _counter("hvdtpu_fault_injections_total",
                            'kind="bitflip"') == before + 1
            # Fires once: replaying the step is a no-op.
            assert numerics.maybe_bitflip(tree, 2) is tree
        finally:
            faults_mod.reset()

    def test_unarmed_is_identity(self, monkeypatch):
        monkeypatch.delenv("HOROVOD_TPU_FAULT_SPEC", raising=False)
        faults_mod.reset()
        try:
            tree = {"w": jnp.ones(4)}
            assert numerics.maybe_bitflip(tree, 0) is tree
        finally:
            faults_mod.reset()


# ---------------------------------------------------------------------------
# Adaptation policy: quantization-drift quality backoff
# ---------------------------------------------------------------------------

class TestQualityBackoff:
    def _policy(self):
        cfg = AdaptationConfig(threshold_s=0.05, sustain_s=1.0,
                               cooldown_s=2.0, interval_s=0.0,
                               alert_hold_s=10.0)
        return AdaptationPolicy(cfg, allow_evict=False)

    def test_drift_unwinds_wire_tiers(self):
        p = self._policy()
        p.tier = 3                   # shrink + bf16 + int8x256 active
        p.note_alert("quantization_drift", rank=1, now=100.0)
        # Unwound until no wire tier is active; the structural shrink
        # tier survives (it does not change arithmetic).
        assert p.tier == 1
        assert p.config.tiers[:p.tier] == ("shrink",)

    def test_wire_reescalation_blocked_during_hold(self):
        p = self._policy()
        p.tier = 2                   # shrink + bf16
        p.note_alert("quantization_drift", rank=0, now=100.0)
        assert p.tier == 1
        # tiers[1] is bf16 — a wire rung; blocked while the hold is on.
        assert p._escalate(0, 1.0, now=105.0) is None
        assert p._escalate(0, 1.0, now=100.0 + 10.0 + 1.0) is not None

    def test_drift_does_not_add_escalation_pressure(self):
        p = self._policy()
        p.note_alert("quantization_drift", rank=0, now=50.0)
        # The usual alert path clamps lateness upward; drift must not.
        assert p.tier == 0
        assert p._alert_pressure(now=51.0) == {}


# ---------------------------------------------------------------------------
# Checkpoint value fingerprints
# ---------------------------------------------------------------------------

class TestCheckpointFingerprints:
    def test_manifest_carries_per_leaf_digests(self, tmp_path):
        d = str(tmp_path / "ck")
        eng = CheckpointEngine(d, barrier=lambda name: None)
        tree = {"w": np.arange(8.0), "b": np.zeros(3, np.float32)}
        eng.save(tree, 1, block=True)
        man = _manifest.read_manifest(d, 1)
        fps = man["fingerprints"]
        assert set(fps) == {"['w']", "['b']"}
        assert fps["['w']"] == numerics.fingerprint_leaf(
            "['w']", tree["w"])

    def test_verify_fingerprint_roundtrip_and_mismatch(self, tmp_path):
        d = str(tmp_path / "ck")
        eng = CheckpointEngine(d, barrier=lambda name: None)
        w = np.arange(8.0)
        eng.save({"w": w}, 1, block=True)
        man = _manifest.read_manifest(d, 1)
        _ck_engine.verify_fingerprint("['w']", w, man)   # clean: no raise
        with pytest.raises(CorruptShardError, match="fingerprint"):
            _ck_engine.verify_fingerprint(
                "['w']", numerics.flip_mantissa_bit(w, index=5, bit=2),
                man, where="step-1")

    def _corrupt_leaf_file(self, d, step, value):
        """Tamper the shard's VALUES and fix up the byte-crc sidecar —
        the corruption class only the value fingerprint can catch."""
        import glob as _glob
        import zlib
        sdir = os.path.join(d, f"step-{step}")
        path = sorted(_glob.glob(os.path.join(sdir, "*.npy")))[0]
        arr = np.load(path)
        arr = arr.copy()
        arr.flat[0] = value
        np.save(path, arr)
        man = _manifest.read_manifest(d, step)
        data = open(path, "rb").read()
        with open(path + ".crc32", "w") as f:
            f.write(f"{zlib.crc32(data) & 0xFFFFFFFF:08x} {len(data)}")
        for entry in man["leaves"]:
            for shard in entry["shards"]:
                if shard["file"] == os.path.basename(path):
                    shard["crc32"] = f"{zlib.crc32(data) & 0xFFFFFFFF:08x}"
                    shard["nbytes"] = len(data)
        with open(os.path.join(sdir, "manifest.json"), "wb") as f:
            f.write(_manifest.dumps(man))

    def test_restore_raises_on_value_corruption(self, tmp_path):
        d = str(tmp_path / "ck")
        eng = CheckpointEngine(d, barrier=lambda name: None)
        eng.save({"w": np.arange(8.0)}, 1, block=True)
        self._corrupt_leaf_file(d, 1, 99.0)
        with pytest.raises(CorruptShardError, match="fingerprint"):
            CheckpointEngine(d, barrier=lambda name: None).restore()

    def test_restore_falls_back_to_clean_commit(self, tmp_path):
        d = str(tmp_path / "ck")
        eng = CheckpointEngine(d, barrier=lambda name: None)
        eng.save({"w": np.arange(8.0)}, 1, block=True)
        eng.save({"w": np.arange(8.0) * 2}, 2, block=True)
        self._corrupt_leaf_file(d, 2, 99.0)
        restored = CheckpointEngine(d,
                                    barrier=lambda name: None).restore()
        np.testing.assert_allclose(restored["w"], np.arange(8.0))

    def test_restore_addressable_verifies_full_blocks(self, tmp_path):
        from horovod_tpu.checkpoint import tree_layout
        d = str(tmp_path / "ck")
        eng = CheckpointEngine(d, barrier=lambda name: None)
        eng.save({"w": np.arange(8.0)}, 1, block=True)
        self._corrupt_leaf_file(d, 1, 99.0)
        layouts = tree_layout({"w": np.arange(8.0)}, lambda dev: 0)
        with pytest.raises(CorruptShardError, match="fingerprint"):
            eng.restore_addressable(layouts, 1)

    def test_old_manifest_without_fingerprints_restores(self, tmp_path):
        d = str(tmp_path / "ck")
        eng = CheckpointEngine(d, barrier=lambda name: None)
        eng.save({"w": np.arange(8.0)}, 1, block=True)
        sdir = os.path.join(d, "step-1")
        man = _manifest.read_manifest(d, 1)
        del man["fingerprints"]                 # a pre-plane checkpoint
        with open(os.path.join(sdir, "manifest.json"), "wb") as f:
            f.write(_manifest.dumps(man))
        restored = CheckpointEngine(d,
                                    barrier=lambda name: None).restore()
        np.testing.assert_allclose(restored["w"], np.arange(8.0))
