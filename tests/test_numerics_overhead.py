"""Numerics-plane overhead guard (slow tier) — the sentinels piggyback
on packs the engine already pays for, so the armed plane must cost
under 1% of step time: ``bench_engine.py --numerics`` runs a 2-process
fused-allreduce loop toggling the plane PER STEP (each on-step paired
with its off-step twin; overhead is the median over paired step-time
ratios, which cancels the load drift that block-level A/Bs suffer on
a shared box), and this guard holds the overhead under 1%,
regenerating ``BENCH_NUMERICS.json``.

One re-measure is allowed before failing — a shared CI box can stay
saturated through one window (the BENCH_METRICS precedent)."""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

BUDGET = 0.01


def _run_bench(out_path: str, rounds: int) -> dict:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "bench_engine.py"),
         "--numerics", "--numerics-rounds", str(rounds),
         "--out", out_path],
        capture_output=True, text=True, timeout=600, cwd=root)
    assert proc.returncode == 0, proc.stderr[-3000:]
    return json.loads(open(out_path).read())


def test_numerics_overhead_under_1_percent(tmp_path):
    out = tmp_path / "bench_numerics.json"
    result = _run_bench(str(out), rounds=6)
    if result["overhead_frac"] >= BUDGET:   # one re-measure
        result = _run_bench(str(out), rounds=6)

    # Regenerate the committed artifact from the accepted run.
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(root, "BENCH_NUMERICS.json"), "w") as f:
        json.dump(result, f, indent=2, sort_keys=True)
        f.write("\n")

    assert result["rows"]["numerics_on"]["step_time_ms"] > 0
    # The sentinel must never cry wolf on clean payloads: the bench
    # ships all-finite tensors, so any nonfinite count is a bug (e.g.
    # the dot-product fast path misreading overflow).
    assert result["nonfinite_false_positives"] == 0
    assert result["overhead_frac"] < BUDGET, (
        f"armed numerics plane costs {result['overhead_frac']:.2%} of "
        f"the 2-process step time "
        f"(on {result['rows']['numerics_on']['step_time_ms']} ms vs "
        f"off {result['rows']['numerics_off']['step_time_ms']} ms; "
        f"budget {BUDGET:.0%})")

    # The seeded numerics smoke is deterministic: the sentinel counts
    # exactly the crafted NaN/Inf elements, the fingerprint catches a
    # single mantissa bitflip and blames the right rank, and the
    # nonfinite_rate detector fires on the sample carrying the event.
    smoke = result["numerics_smoke"]
    assert (smoke["nonfinite_elements_counted"]
            == smoke["nonfinite_elements_expected"])
    assert smoke["bitflip_changes_fingerprint"]
    assert smoke["bitflip_blamed"] == [["w", 1]]
    assert (smoke["nonfinite_rate_first_fired_at_sample"]
            == smoke["nonfinite_event_at_sample"])
