"""End-to-end cross-rank tracing acceptance (docs/tracing.md).

ISSUE 5 acceptance: a 4-process run with one artificially delayed rank
produces per-rank trace files; ``python -m horovod_tpu.tools.trace
merge`` emits a single valid Perfetto/catapult JSON whose straggler
report names the delayed rank as top straggler with lateness within 2x
of the injected delay; and the live
``hvdtpu_negotiate_lateness_seconds`` metric for that rank shows the
same signal WITHOUT traces enabled.

Marked slow: two real 4-process jobs over the TCP control plane.
"""

import json
import os

import pytest

from horovod_tpu.runner.api import run

pytestmark = pytest.mark.slow

_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
    # The Python writer records fused-group seqs + in-band clock meta;
    # forcing the fallback keeps the trace format deterministic here.
    "HOROVOD_TPU_DISABLE_NATIVE": "1",
}

NP = 4
DELAYED_RANK = 2
DELAY_S = 0.15
STEPS = 5


def _make_worker():
    """Nested so cloudpickle ships it by value (module-level test
    functions are not importable in the workers)."""

    def worker(trace_dir, steps, delay_s, delayed_rank):
        import os
        import time

        import jax.numpy as jnp

        import horovod_tpu as hvd
        from horovod_tpu.ops import collective

        if trace_dir:
            os.environ["HOROVOD_TPU_TIMELINE"] = os.path.join(
                trace_dir, "trace.{rank}.json")
        hvd.init()
        r = hvd.process_rank()
        for step in range(steps):
            if r == delayed_rank:
                time.sleep(delay_s)
            hvd.allreduce(jnp.full((16,), float(r)), average=False,
                          name=f"e2e.step{step}")
        snap = hvd.metrics_snapshot()
        collective.engine().shutdown()
        lat = snap.get("hvdtpu_negotiate_lateness_seconds", {}).get(
            "values", {})
        return {
            "rank": r,
            "lateness": {k: {"count": v["count"], "sum": v["sum"]}
                         for k, v in lat.items()},
            "straggler": snap.get("hvdtpu_straggler_rank", {}).get(
                "values", {}).get(""),
        }

    return worker


class TestCrossRankTraceAcceptance:
    def test_delayed_rank_diagnosed_from_traces(self, tmp_path):
        results = run(_make_worker(),
                      args=(str(tmp_path), STEPS, DELAY_S, DELAYED_RANK),
                      np=NP, extra_env=dict(_ENV), start_timeout=300)
        assert sorted(r["rank"] for r in results) == list(range(NP))

        # Every rank wrote a trace + clock sidecar.
        for r in range(NP):
            assert (tmp_path / f"trace.{r}.json").exists()
            sc = json.loads(
                (tmp_path / f"trace.{r}.json.clock.json").read_text())
            assert sc["rank"] == r and sc["world"] == NP
            assert sc["clock_synced"] is True

        # Merge CLI: one valid catapult JSON + straggler report.
        from horovod_tpu.tools import trace as trace_tool
        merged_path = tmp_path / "merged.json"
        report_path = tmp_path / "report.json"
        trace_tool._main(["merge", str(tmp_path / "trace.{rank}.json"),
                          "-o", str(merged_path),
                          "--report", str(report_path)])
        merged = json.loads(merged_path.read_text())
        assert {e["args"]["name"] for e in merged
                if e.get("name") == "process_name"} \
            == {f"rank {r}" for r in range(NP)}
        assert any(e.get("name") == "NEGOTIATE_ALLREDUCE" for e in merged)

        report = json.loads(report_path.read_text())
        top = report["top_straggler"]
        assert top["rank"] == DELAYED_RANK
        # Lateness within 2x of the injected delay.
        assert DELAY_S / 2 <= top["p50_s"] <= DELAY_S * 2
        assert top["groups_last"] >= STEPS - 1
        # Punctual ranks are near zero.
        for r in range(NP):
            if r != DELAYED_RANK:
                assert report["per_rank"][str(r)]["lateness"]["p50_s"] \
                    <= DELAY_S / 2

    def test_live_metric_shows_same_signal_without_traces(self, tmp_path):
        """Same job shape, NO timeline env: the coordinator's registry
        alone names the straggler with the right magnitude."""
        results = run(_make_worker(),
                      args=("", STEPS, DELAY_S, DELAYED_RANK),
                      np=NP, extra_env=dict(_ENV), start_timeout=300)
        for r in range(NP):
            assert not (tmp_path / f"trace.{r}.json").exists()
        rank0 = next(r for r in results if r["rank"] == 0)
        h = rank0["lateness"].get(f'rank="{DELAYED_RANK}"')
        assert h is not None and h["count"] >= STEPS - 1
        mean = h["sum"] / h["count"]
        assert DELAY_S / 2 <= mean <= DELAY_S * 2
        assert rank0["straggler"] == DELAYED_RANK
        # Punctual ranks' mean lateness stays well below the delay.
        for r in range(NP):
            if r == DELAYED_RANK:
                continue
            hr = rank0["lateness"].get(f'rank="{r}"')
            if hr and hr["count"]:
                assert hr["sum"] / hr["count"] <= DELAY_S / 2
