"""Flight-recorder + postmortem + attribution acceptance (ISSUE 7,
docs/postmortem.md) — slow tier.

  1. Crash e2e: a 4-process job with an injected ``crash_at`` on rank 1
     (the PR 6 fault spec). The crashed rank leaves a final-gasp dump at
     the injection point; every surviving rank dumps on its death path
     (coordinator failure escalation or the driver's SIGTERM); and
     ``python -m horovod_tpu.tools.postmortem`` names the crashed rank,
     its death phase, and the first divergent group seq.

  2. Attribution e2e: a delayed-input run is classified input-bound and
     an injected slow rank comm-bound in ``tools/trace report``; MFU and
     HBM gauges appear in ``hvd.metrics_snapshot()``.
"""

import json
import os
import time

import pytest

from horovod_tpu.runner.api import run

pytestmark = pytest.mark.slow

_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
    # Fallback control plane: deterministic coordinator seqs on the
    # Python writer/recorder paths.
    "HOROVOD_TPU_DISABLE_NATIVE": "1",
    "HOROVOD_CYCLE_TIME": "1",
}

NP = 4


def _load_dump(path):
    header, events = None, []
    for line in open(path):
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if header is None and obj.get("blackbox"):
            header = obj
        else:
            events.append(obj)
    return header, events


def _make_crash_worker():
    def worker(steps):
        import jax.numpy as jnp

        import horovod_tpu as hvd
        from horovod_tpu.observability import StepTimer

        hvd.init()
        r = hvd.process_rank()
        timer = StepTimer("e2e", batch_size=8)
        for step in range(steps):
            with timer:
                hvd.allreduce(jnp.full((16,), float(r)), average=False,
                              name=f"pm.step{step}")
        return r

    return worker


class TestCrashPostmortem:
    CRASH_RANK = 1
    CRASH_TICK = 6

    def test_crash_leaves_dumps_and_postmortem_names_the_rank(
            self, tmp_path):
        env = dict(_ENV, **{
            "HOROVOD_TPU_BLACKBOX": str(tmp_path),
            # Tight continuous-dump cadence: the JAX coordination
            # service hard-kills surviving clients ~100 ms after a peer
            # dies, so their evidence is the last in-flight snapshot.
            "HOROVOD_TPU_BLACKBOX_INTERVAL": "0.25",
            "HOROVOD_TPU_FAULT_SPEC":
                f"rank={self.CRASH_RANK}:crash_at={self.CRASH_TICK}",
            "HOROVOD_TPU_STALL_CHECK_DISABLE": "1",
            "HOROVOD_TPU_FAILURE_TIMEOUT": "2",
        })
        with pytest.raises(Exception):
            run(_make_crash_worker(), args=(30,), np=NP,
                extra_env=env, start_timeout=300)

        # Every rank dumped: rank 1 at the injected crash (final gasp
        # before SIGKILL), survivors on their own death paths. Dumps
        # may land a beat after the driver's exception — poll.
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if all((tmp_path / f"blackbox-rank{r}.jsonl").exists()
                   for r in range(NP)):
                break
            time.sleep(0.25)
        for r in range(NP):
            assert (tmp_path / f"blackbox-rank{r}.jsonl").exists(), \
                f"rank {r} left no blackbox dump"

        crash_header, crash_events = _load_dump(
            str(tmp_path / f"blackbox-rank{self.CRASH_RANK}.jsonl"))
        assert crash_header["reason"] == "fault_crash"
        done = [e["seq"] for e in crash_events
                if e["kind"] == "group_done"]
        # crash_at=N fires at the N+1-th enqueue, before it joins the
        # queue: exactly N completed fused groups (one per step).
        assert max(done) == self.CRASH_TICK - 1
        for r in range(NP):
            if r == self.CRASH_RANK:
                continue
            header, events = _load_dump(
                str(tmp_path / f"blackbox-rank{r}.jsonl"))
            # A survivor either got a final gasp (driver SIGTERM, or a
            # typed WorkerFailure raised from the wait) or was
            # hard-killed by the JAX coordination service — in which
            # case its file is the last in-flight snapshot.
            assert header["reason"] in ("sigterm", "exception",
                                        "inflight")
            assert any(e["kind"] == "group_done" for e in events)

        # Postmortem CLI: names the crashed rank, its death phase, and
        # the divergence point.
        from horovod_tpu.tools import postmortem
        out = tmp_path / "report.json"
        postmortem._main([str(tmp_path), "--json", str(out)])
        report = json.loads(out.read_text())
        assert report["world"] == NP
        assert report["died_first"]["rank"] == self.CRASH_RANK
        assert report["died_first"]["how"] == "fault_crash"
        assert "fault injection" in report["died_first"]["phase"]
        assert report["common_last_group_seq"] == self.CRASH_TICK - 1
        # Survivors began the next step / had its group in flight.
        assert report["first_divergent_group_seq"] == self.CRASH_TICK
        text = postmortem.format_report(report)
        assert f"rank {self.CRASH_RANK} went first" in text


def _make_attr_worker():
    def worker(trace_dir, steps, input_sleep_s, slow_rank, slow_sleep_s):
        import os
        import time

        import jax.numpy as jnp

        import horovod_tpu as hvd
        from horovod_tpu.observability import StepTimer
        from horovod_tpu.ops import collective

        os.environ["HOROVOD_TPU_TIMELINE"] = os.path.join(
            trace_dir, "trace.{rank}.json")
        hvd.init()
        r = hvd.process_rank()
        timer = StepTimer("attr_e2e", batch_size=8, flops_per_step=1e9)
        for step in range(steps):
            if input_sleep_s:
                time.sleep(input_sleep_s)   # the slow loader
            with timer:
                if r == slow_rank and slow_sleep_s:
                    time.sleep(slow_sleep_s)   # the slow rank, in-step
                hvd.allreduce(jnp.full((16,), float(r)), average=False,
                              name=f"attr.step{step}")
        snap = hvd.metrics_snapshot()
        collective.engine().shutdown()
        keep = ("hvdtpu_mfu", "hvdtpu_model_flops_per_second",
                "hvdtpu_hbm_bytes_in_use", "hvdtpu_hbm_peak_bytes",
                "hvdtpu_step_phase_share")
        return {"rank": r,
                "metrics": {k: snap[k]["values"]
                            for k in keep if k in snap}}

    return worker


class TestAttributionE2E:
    STEPS = 6

    def _run(self, trace_dir, input_sleep_s, slow_rank, slow_sleep_s,
             steps=None):
        env = dict(_ENV, HOROVOD_TPU_PEAK_FLOPS="1e12")
        return run(_make_attr_worker(),
                   args=(str(trace_dir), steps or self.STEPS,
                         input_sleep_s, slow_rank, slow_sleep_s),
                   np=NP, extra_env=env, start_timeout=300)

    def _report(self, trace_dir, out):
        from horovod_tpu.tools import trace as trace_tool
        trace_tool._main(["report",
                          str(trace_dir / "trace.{rank}.json"),
                          "--report", str(out)])
        return json.loads(out.read_text())

    def test_delayed_input_run_is_input_bound(self, tmp_path):
        # Enough steps that the steady-state input waits dwarf the
        # first-step XLA compile (which lands in the execute span).
        results = self._run(tmp_path, input_sleep_s=0.25,
                            slow_rank=-1, slow_sleep_s=0.0, steps=10)
        report = self._report(tmp_path, tmp_path / "report.json")
        assert report["bound"] == "input-bound"
        for r in range(NP):
            assert report["per_rank"][str(r)]["verdict"] == "input-bound"
            assert report["per_rank"][str(r)]["phase_share"]["input"] \
                > 0.4
        # MFU and HBM gauges appear in metrics_snapshot() (acceptance).
        for res in results:
            m = res["metrics"]
            assert m["hvdtpu_mfu"]['framework="attr_e2e"'] > 0
            assert m["hvdtpu_model_flops_per_second"][
                'framework="attr_e2e"'] > 0
            assert any(v > 0 for v in
                       m["hvdtpu_hbm_bytes_in_use"].values())
            assert any(v > 0 for v in
                       m["hvdtpu_hbm_peak_bytes"].values())
            # The live share gauge agrees with the offline verdict.
            assert m["hvdtpu_step_phase_share"][
                'framework="attr_e2e",phase="input"'] > 0.4

    def test_slow_rank_run_is_comm_bound(self, tmp_path):
        slow = 2
        self._run(tmp_path, input_sleep_s=0.0,
                  slow_rank=slow, slow_sleep_s=0.12)
        report = self._report(tmp_path, tmp_path / "report.json")
        assert report["bound"] == "comm-bound"
        # The slow rank is the top straggler; the punctual ranks lose
        # their time WAITING on it — comm-bound.
        assert report["top_straggler"]["rank"] == slow
        for r in range(NP):
            if r == slow:
                continue
            assert report["per_rank"][str(r)]["verdict"] == "comm-bound"
        # The straggler itself burns the time in-step, not in comm.
        assert report["per_rank"][str(slow)]["verdict"] == "compute-bound"
