"""Fleet acceptance e2e (slow tier, docs/serving.md#fleet): a
3-replica fleet under continuous load survives (a) a SIGTERM drain of
one replica and (b) an injected hard crash (``replica_crash_at``) of
another — with ZERO failed client requests, every output (including
mid-stream-resumed ones) token-identical to an uncontended reference,
the crashed replica restarted back into rotation, and the postmortem
tool naming the crashed replica from its blackbox dump."""

import http.client
import json
import os
import subprocess
import sys
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from horovod_tpu.checkpoint import CheckpointEngine
from horovod_tpu.models import transformer as tfm
from horovod_tpu.parallel.mesh import create_mesh
from horovod_tpu.serving import (InferenceEngine, Router, ServingConfig,
                                 config_from_manifest, load_params,
                                 serving_config, transformer_extra)
from horovod_tpu.serving.fleet import Fleet

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MAX_NEW = 32
N_REQUESTS = 24


def _write_checkpoint(ckpt):
    cfg = tfm.TransformerConfig(
        vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq=128, dtype=jnp.float32, remat=False)
    params = tfm.init_params(cfg, jax.random.PRNGKey(0))
    eng = CheckpointEngine(ckpt, process_count=1,
                           barrier=lambda n: None)
    eng.save(params, 1, block=True, extra=transformer_extra(cfg))
    return cfg, params


def _post(port, body, timeout=300):
    conn = http.client.HTTPConnection("127.0.0.1", port,
                                      timeout=timeout)
    conn.request("POST", "/generate", json.dumps(body),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    if body.get("stream"):
        lines = [json.loads(ln) for ln in resp.read().splitlines()
                 if ln.strip()]
        done = lines[-1]
        return (resp.status if done.get("done") else 599,
                {"tokens": [ln["t"] for ln in lines[1:-1]],
                 "status": done.get("status"),
                 "error": done.get("error")})
    return resp.status, json.loads(resp.read())


@pytest.mark.slow
class TestFleetFailoverE2E:
    def test_three_replica_fleet_survives_drain_and_crash(
            self, tmp_path):
        ckpt = str(tmp_path / "ckpt")
        bb = str(tmp_path / "bb")
        cfg, params = _write_checkpoint(ckpt)

        # --- uncontended reference outputs, in-process, greedy
        mesh1 = create_mesh(devices=jax.devices()[:1], tp=1)
        man = CheckpointEngine(ckpt).restore_manifest()
        scfg = serving_config(config_from_manifest(man), mesh1)
        ref_engine = InferenceEngine(
            load_params(ckpt, scfg, mesh1), scfg, mesh1,
            ServingConfig(block_size=4, kv_blocks=64,
                          max_batch_slots=4, max_new_tokens=MAX_NEW))
        rng = np.random.RandomState(11)
        prompts = [[int(t) for t in rng.randint(0, 64, int(n))]
                   for n in rng.randint(4, 16, N_REQUESTS)]
        expected = [ref_engine.generate(p) for p in prompts]

        # --- the fleet: 3 replicas, replica 1 hard-crashes (gen 0
        # only) at decode tick 40 — mid-load by construction.
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            "HOROVOD_TPU_BLACKBOX": bb,
            "HOROVOD_TPU_FAULT_SPEC":
                "rank=1:replica_crash_at=40:gen=0",
        })
        fleet = Fleet(3, ["--checkpoint-dir", ckpt, "--tp", "1",
                          "--block-size", "4", "--kv-blocks", "64",
                          "--slots", "4",
                          "--max-new-tokens", str(MAX_NEW)],
                      env=env)
        router = Router(fleet, port=0, host="127.0.0.1",
                        scrape_interval_s=0.1)
        fleet.start()
        try:
            fleet.wait_ready(600.0)
            router.start()

            # --- continuous load; drain replica 0 mid-flight
            def one(i):
                body = {"tokens": prompts[i],
                        "max_new_tokens": MAX_NEW}
                if i % 2:
                    body["stream"] = True
                return _post(router.port, body)

            with ThreadPoolExecutor(max_workers=6) as pool:
                futs = [pool.submit(one, i)
                        for i in range(N_REQUESTS)]
                time.sleep(1.5)
                fleet.drain_replica(0)     # (a) SIGTERM drain
                results = [f.result(timeout=600) for f in futs]

            # --- ZERO dropped/failed requests, outputs identical to
            # the uncontended reference (mid-stream resumes included)
            for i, (status, body) in enumerate(results):
                assert status == 200, (i, status, body)
                assert body["tokens"] == expected[i], i

            # the crash really happened and really was failed over
            snap_ok = False
            from horovod_tpu.observability import metrics_snapshot
            snap = metrics_snapshot()
            fail = snap.get("hvdtpu_fleet_failovers_total",
                            {"values": {}})["values"]
            midstream = fail.get('phase="midstream"', 0)
            prefill = fail.get('phase="prefill"', 0)
            assert midstream + prefill >= 1
            snap_ok = True
            assert snap_ok

            # --- (b) the crashed replica restarted and re-entered
            # rotation (new incarnation, clean fault spec)
            deadline = time.monotonic() + 300
            rep1 = fleet.replicas[1]
            while time.monotonic() < deadline:
                if rep1.restarts >= 1 and rep1.up \
                        and rep1.ready.is_set():
                    break
                time.sleep(0.2)
            assert rep1.restarts >= 1 and rep1.up
            assert rep1.generation >= 1
            # replica 0's drained incarnation also restarted
            deadline = time.monotonic() + 300
            while time.monotonic() < deadline:
                if all(r.up and r.ready.is_set()
                       for r in fleet.replicas):
                    break
                time.sleep(0.2)
            assert all(r.up for r in fleet.replicas)
            # the router sees all three ready again and still serves
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                with router._views_lock:
                    ready = sum(1 for v in router._views.values()
                                if v.ready)
                if ready == 3:
                    break
                time.sleep(0.2)
            assert ready == 3
            status, body = _post(router.port,
                                 {"tokens": prompts[0],
                                  "max_new_tokens": MAX_NEW})
            assert status == 200 and body["tokens"] == expected[0]
        finally:
            router.shutdown()
            fleet.stop()

        # --- postmortem names the crashed replica from its gen-0
        # blackbox dump (the supervisor quarantines dumps per
        # incarnation so the restart can't overwrite the evidence)
        gen0 = os.path.join(bb, "gen0")
        assert os.path.exists(
            os.path.join(gen0, "blackbox-rank1.jsonl"))
        out = tmp_path / "postmortem.json"
        proc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.tools.postmortem",
             gen0, "--json", str(out)],
            capture_output=True, text=True, timeout=300, cwd=ROOT)
        assert proc.returncode == 0, proc.stderr[-2000:]
        report = json.loads(out.read_text())
        assert report["died_first"]["rank"] == 1
        assert report["per_rank"]["1"]["reason"] == "fault_crash"
        assert "serving replica crash" in \
            report["died_first"]["phase"]
        assert "rank 1" in proc.stdout


@pytest.mark.slow
class TestFleetBenchReproducible:
    def test_bench_fleet_determinism_and_availability(self, tmp_path):
        """bench_serving.py --fleet regenerates BENCH_FLEET
        reproducibly (seeded counts + output checksum identical across
        runs) and supports the availability claim: an injected replica
        crash mid-load drops ZERO requests and leaves every output
        token-identical to an uncontended run."""
        outs = []
        for i in range(2):
            out = tmp_path / f"fleet{i}.json"
            subprocess.run(
                [sys.executable, os.path.join(ROOT, "bench_serving.py"),
                 "--fleet", "--out", str(out)],
                check=True, capture_output=True, text=True,
                timeout=900, cwd=ROOT)
            outs.append(json.loads(out.read_text()))
        a, b = outs
        for run in outs:
            assert run["requests_failed"] == 0, run
            assert run["requests_succeeded"] == \
                run["requests_attempted"]
            assert run["outputs_equal_uncontended"], run
            assert run["replica_restarts"] >= 1, run
        # the deterministic fields byte-compare across regenerations
        for key in ("requests_attempted", "requests_succeeded",
                    "requests_failed", "output_checksum", "replicas",
                    "fault", "outputs_equal_uncontended"):
            assert a[key] == b[key], key
