"""Fleet acceptance e2e (slow tier, docs/serving.md#fleet): a
3-replica fleet under continuous load survives (a) a SIGTERM drain of
one replica and (b) an injected hard crash (``replica_crash_at``) of
another — with ZERO failed client requests, every output (including
mid-stream-resumed ones) token-identical to an uncontended reference,
the crashed replica restarted back into rotation, and the postmortem
tool naming the crashed replica from its blackbox dump."""

import http.client
import json
import os
import subprocess
import sys
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from horovod_tpu.checkpoint import CheckpointEngine
from horovod_tpu.models import transformer as tfm
from horovod_tpu.parallel.mesh import create_mesh
from horovod_tpu.serving import (InferenceEngine, Router, ServingConfig,
                                 config_from_manifest, load_params,
                                 serving_config, transformer_extra)
from horovod_tpu.serving.fleet import Fleet

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MAX_NEW = 32
N_REQUESTS = 24


def _write_checkpoint(ckpt):
    cfg = tfm.TransformerConfig(
        vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq=128, dtype=jnp.float32, remat=False)
    params = tfm.init_params(cfg, jax.random.PRNGKey(0))
    eng = CheckpointEngine(ckpt, process_count=1,
                           barrier=lambda n: None)
    eng.save(params, 1, block=True, extra=transformer_extra(cfg))
    return cfg, params


def _post(port, body, timeout=300):
    conn = http.client.HTTPConnection("127.0.0.1", port,
                                      timeout=timeout)
    conn.request("POST", "/generate", json.dumps(body),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    if body.get("stream"):
        lines = [json.loads(ln) for ln in resp.read().splitlines()
                 if ln.strip()]
        done = lines[-1]
        return (resp.status if done.get("done") else 599,
                {"tokens": [ln["t"] for ln in lines[1:-1]],
                 "status": done.get("status"),
                 "error": done.get("error")})
    return resp.status, json.loads(resp.read())


@pytest.mark.slow
class TestFleetFailoverE2E:
    def test_three_replica_fleet_survives_drain_and_crash(
            self, tmp_path):
        ckpt = str(tmp_path / "ckpt")
        bb = str(tmp_path / "bb")
        cfg, params = _write_checkpoint(ckpt)

        # --- uncontended reference outputs, in-process, greedy
        mesh1 = create_mesh(devices=jax.devices()[:1], tp=1)
        man = CheckpointEngine(ckpt).restore_manifest()
        scfg = serving_config(config_from_manifest(man), mesh1)
        ref_engine = InferenceEngine(
            load_params(ckpt, scfg, mesh1), scfg, mesh1,
            ServingConfig(block_size=4, kv_blocks=64,
                          max_batch_slots=4, max_new_tokens=MAX_NEW))
        rng = np.random.RandomState(11)
        prompts = [[int(t) for t in rng.randint(0, 64, int(n))]
                   for n in rng.randint(4, 16, N_REQUESTS)]
        expected = [ref_engine.generate(p) for p in prompts]

        # --- the fleet: 3 replicas, replica 1 hard-crashes (gen 0
        # only) at decode tick 40 — mid-load by construction.
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            "HOROVOD_TPU_BLACKBOX": bb,
            "HOROVOD_TPU_FAULT_SPEC":
                "rank=1:replica_crash_at=40:gen=0",
        })
        fleet = Fleet(3, ["--checkpoint-dir", ckpt, "--tp", "1",
                          "--block-size", "4", "--kv-blocks", "64",
                          "--slots", "4",
                          "--max-new-tokens", str(MAX_NEW)],
                      env=env)
        router = Router(fleet, port=0, host="127.0.0.1",
                        scrape_interval_s=0.1)
        fleet.start()
        try:
            fleet.wait_ready(600.0)
            router.start()

            # --- continuous load; drain replica 0 mid-flight
            def one(i):
                body = {"tokens": prompts[i],
                        "max_new_tokens": MAX_NEW}
                if i % 2:
                    body["stream"] = True
                return _post(router.port, body)

            with ThreadPoolExecutor(max_workers=6) as pool:
                futs = [pool.submit(one, i)
                        for i in range(N_REQUESTS)]
                time.sleep(1.5)
                fleet.drain_replica(0)     # (a) SIGTERM drain
                results = [f.result(timeout=600) for f in futs]

            # --- ZERO dropped/failed requests, outputs identical to
            # the uncontended reference (mid-stream resumes included)
            for i, (status, body) in enumerate(results):
                assert status == 200, (i, status, body)
                assert body["tokens"] == expected[i], i

            # the crash really happened and really was failed over
            snap_ok = False
            from horovod_tpu.observability import metrics_snapshot
            snap = metrics_snapshot()
            fail = snap.get("hvdtpu_fleet_failovers_total",
                            {"values": {}})["values"]
            midstream = fail.get('phase="midstream"', 0)
            prefill = fail.get('phase="prefill"', 0)
            assert midstream + prefill >= 1
            snap_ok = True
            assert snap_ok

            # --- (b) the crashed replica restarted and re-entered
            # rotation (new incarnation, clean fault spec)
            deadline = time.monotonic() + 300
            rep1 = fleet.replicas[1]
            while time.monotonic() < deadline:
                if rep1.restarts >= 1 and rep1.up \
                        and rep1.ready.is_set():
                    break
                time.sleep(0.2)
            assert rep1.restarts >= 1 and rep1.up
            assert rep1.generation >= 1
            # replica 0's drained incarnation also restarted
            deadline = time.monotonic() + 300
            while time.monotonic() < deadline:
                if all(r.up and r.ready.is_set()
                       for r in fleet.replicas):
                    break
                time.sleep(0.2)
            assert all(r.up for r in fleet.replicas)
            # the router sees all three ready again and still serves
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                with router._views_lock:
                    ready = sum(1 for v in router._views.values()
                                if v.ready)
                if ready == 3:
                    break
                time.sleep(0.2)
            assert ready == 3
            status, body = _post(router.port,
                                 {"tokens": prompts[0],
                                  "max_new_tokens": MAX_NEW})
            assert status == 200 and body["tokens"] == expected[0]
        finally:
            router.shutdown()
            fleet.stop()

        # --- postmortem names the crashed replica from its gen-0
        # blackbox dump (the supervisor quarantines dumps per
        # incarnation so the restart can't overwrite the evidence)
        gen0 = os.path.join(bb, "gen0")
        assert os.path.exists(
            os.path.join(gen0, "blackbox-rank1.jsonl"))
        out = tmp_path / "postmortem.json"
        proc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.tools.postmortem",
             gen0, "--json", str(out)],
            capture_output=True, text=True, timeout=300, cwd=ROOT)
        assert proc.returncode == 0, proc.stderr[-2000:]
        report = json.loads(out.read_text())
        assert report["died_first"]["rank"] == 1
        assert report["per_rank"]["1"]["reason"] == "fault_crash"
        assert "serving replica crash" in \
            report["died_first"]["phase"]
        assert "rank 1" in proc.stdout
        # The flight recorder's request lifecycle events name exactly
        # what the replica took down with it: the admitted-but-
        # unfinished requests and their phase (the router failed these
        # over — docs/serving.md#request-tracing).
        infl = report["per_rank"]["1"]["inflight_requests"]
        assert infl, "crashed replica must name its in-flight requests"
        assert all(q["phase"] in ("prefill", "decode") for q in infl)
        assert any(q["phase"] == "decode" for q in infl)
        assert "In-flight requests on rank 1" in proc.stdout


@pytest.mark.slow
class TestRequestTraceE2E:
    def test_merged_trace_budget_and_exemplar(self, tmp_path):
        """Acceptance (docs/serving.md#request-tracing): a 3-replica
        fleet with an injected ``replica_crash_at`` yields a merged
        serving trace in which the failed request's spans cross all
        three processes (router, dead replica, resume replica) under
        ONE trace id; the report attributes its latency across
        queue/prefill/decode/failover phases summing to the measured
        wall within 10%; and the TTFT histogram's exemplar on the
        resume replica links to that trace id."""
        from horovod_tpu.serving import reqtrace

        ckpt = str(tmp_path / "ckpt")
        rt = str(tmp_path / "rt")
        cfg, params = _write_checkpoint(ckpt)
        max_new, n_req = 48, 6

        # Uncontended reference FIRST — before the router-side trace
        # writer exists, so the in-process reference engine cannot
        # pollute the router's capture. (No faults in THIS process:
        # the spec targets serving replica ranks via REPLICA_ID.)
        mesh1 = create_mesh(devices=jax.devices()[:1], tp=1)
        man = CheckpointEngine(ckpt).restore_manifest()
        scfg = serving_config(config_from_manifest(man), mesh1)
        ref_engine = InferenceEngine(
            load_params(ckpt, scfg, mesh1), scfg, mesh1,
            ServingConfig(block_size=4, kv_blocks=64,
                          max_batch_slots=2, max_new_tokens=max_new))
        rng = np.random.RandomState(23)
        prompts = [[int(t) for t in rng.randint(0, 64, int(n))]
                   for n in rng.randint(10, 15, n_req)]
        expected = [ref_engine.generate(p) for p in prompts]

        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            "HOROVOD_TPU_REQTRACE": rt,
            # slow_decode paces every replica's step to >= 25 ms so the
            # whole 6-request load is placed BEFORE replica 1's crash
            # at its decode tick 30, and the survivors' slots are still
            # busy when the resumes arrive — the resumes then QUEUE for
            # a deterministic, dominant wait (the exemplar mechanism
            # below rests on it).
            "HOROVOD_TPU_FAULT_SPEC":
                "rank=*:slow_decode=25ms; "
                "rank=1:replica_crash_at=35:gen=0",
            # Short exemplar window so the warmup requests' compile-
            # laden TTFTs expire before the measured load — the
            # exemplar then links the load's own worst request.
            "HOROVOD_TPU_EXEMPLAR_TTL": "3",
        })
        # slots=2 × 3 replicas == the 6-request load: every fresh
        # request admits instantly (ms TTFT), while a resume must wait
        # for a survivor's slot — the worst TTFT on any replica that
        # served a resume IS that resume.
        fleet = Fleet(3, ["--checkpoint-dir", ckpt, "--tp", "1",
                          "--block-size", "4", "--kv-blocks", "64",
                          "--slots", "2",
                          "--max-new-tokens", str(max_new)],
                      env=env)
        router = Router(fleet, port=0, host="127.0.0.1",
                        scrape_interval_s=0.1)
        os.makedirs(rt, exist_ok=True)
        reqtrace.start(os.path.join(rt, "reqtrace-router.trace.json"),
                       rank=0, proc="router")
        exemplars = {}
        try:
            fleet.start()
            fleet.wait_ready(600.0)
            router.start()

            # Warm every replica across every prefill bucket the load
            # (and its failover re-prefills) can touch — 16/32/64 —
            # so no measured TTFT carries an XLA compile. Sequential
            # unary warmups rotate round-robin over the tied fleet;
            # the response names the serving replica, so coverage is
            # asserted, not assumed.
            for length in (10, 20, 40):
                covered = set()
                for j in range(24):
                    # distinct prompts per attempt — identical ones
                    # would stick to one replica via the router's
                    # prefix-cache warmth bonus
                    warm_prompt = [(7 * j + i) % 64
                                   for i in range(length)]
                    status, body = _post(
                        router.port,
                        {"tokens": warm_prompt, "max_new_tokens": 2})
                    assert status == 200
                    covered.add(body["replica"])
                    if covered == {0, 1, 2}:
                        break
                assert covered == {0, 1, 2}, (length, covered)
            time.sleep(3.5)   # let the warmup exemplars expire (TTL 3)

            with ThreadPoolExecutor(max_workers=n_req) as pool:
                futs = []
                for i in range(n_req):
                    futs.append(pool.submit(
                        _post, router.port,
                        {"tokens": prompts[i],
                         "max_new_tokens": max_new}))
                    time.sleep(0.08)   # staggered dispatch: clean
                    #                    round-robin → 2/2/2 placement,
                    #                    all placed before the crash
                results = [f.result(timeout=600) for f in futs]
            for i, (status, body) in enumerate(results):
                assert status == 200, (i, status, body)
                assert body["tokens"] == expected[i], i
                assert body["trace_id"], i

            # Scrape each live replica's registry endpoint BEFORE the
            # teardown: the TTFT exemplar is the metrics↔traces link.
            for rep in fleet.replicas:
                if not (rep.up and rep.metrics_port):
                    continue
                try:
                    conn = http.client.HTTPConnection(
                        "127.0.0.1", rep.metrics_port, timeout=30)
                    conn.request("GET", "/metrics.json")
                    snap = json.loads(conn.getresponse().read())
                    conn.close()
                except OSError:
                    continue
                ex = snap.get("hvdtpu_serving_ttft_seconds",
                              {"values": {}})["values"].get(
                    "", {}).get("exemplar")
                if ex:
                    exemplars[rep.index] = ex["trace_id"]
        finally:
            router.shutdown()
            fleet.stop()
            reqtrace.stop()

        # --- the merged serving trace + per-request budget report
        out = tmp_path / "serving_report.json"
        proc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.tools.trace",
             "serving", rt, "--report", str(out)],
            capture_output=True, text=True, timeout=300, cwd=ROOT)
        assert proc.returncode == 0, proc.stderr[-2000:]
        report = json.loads(out.read_text())
        assert report["n_requests"] >= n_req
        failed = {tid: r for tid, r in report["requests"].items()
                  if r["failovers"]}
        assert failed, "the injected crash must have failed over at " \
                       "least one in-flight request"

        # The aggregate → concrete link: some replica's worst recent
        # TTFT IS one of the failed-over requests (its resume
        # re-prefill is the deterministically slowest first token).
        assert exemplars, "no replica exposed a TTFT exemplar"
        linked = [tid for tid in exemplars.values() if tid in failed]
        assert linked, (exemplars, sorted(failed))
        tid = linked[0]
        row = failed[tid]

        # ONE trace id crossing all three processes.
        assert "router" in row["processes"]
        assert len(row["processes"]) >= 3, row["processes"]
        assert "replica1" in row["processes"]   # the crashed gen-0

        # Latency budget: queue/prefill/decode/failover explain the
        # measured wall within 10%.
        assert 0.9 <= row["attributed_frac"] <= 1.1, row
        assert row["phase_ms"]["decode"] > 0
        assert row["phase_ms"]["prefill"] > 0

        # Failover chain shows the re-prefill cost on the resume
        # replica (prompt + emitted → the bigger bucket).
        chain = row["failovers"][0]
        assert chain["phase"] == "midstream"
        assert chain["from_replica"] == 1
        assert chain["reprefill_ms"] is not None
        # the re-prefill covers prompt + emitted-so-far — strictly more
        # than the prompt alone
        assert chain["reprefill_tokens"] > min(len(p) for p in prompts)

        # And the files merge into one Perfetto view with the failed
        # request's row present in all three process lanes.
        merged_path = tmp_path / "merged.json"
        proc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.tools.trace", "merge",
             rt, "-o", str(merged_path)],
            capture_output=True, text=True, timeout=300, cwd=ROOT)
        assert proc.returncode == 0, proc.stderr[-2000:]
        merged = json.loads(merged_path.read_text())
        row_pids = {e["pid"] for e in merged
                    if e.get("name") == "thread_name"
                    and e.get("args", {}).get("name") == tid}
        assert len(row_pids) >= 3


@pytest.mark.slow
class TestSloObservabilityE2E:
    def test_slo_attached_request_end_to_end(self, tmp_path):
        """ACCEPTANCE (docs/serving.md#slo): an SLO-attached request
        on a 3-replica fleet is followed end to end — the tenant and
        judged verdict come back in the response body, the router's
        fleet-side hvdtpu_slo_* counters and the serving replica's own
        registry both count it (the violation histogram's exemplar
        linking the violating request's trace id), the merged request
        trace's budget report names tenant + verdict, and the
        replica's flight-recorder blackbox carries the request finish
        event with its tenant and violation summary."""
        from horovod_tpu.observability import metrics_snapshot
        from horovod_tpu.serving import reqtrace

        ckpt = str(tmp_path / "ckpt")
        rt = str(tmp_path / "rt")
        bb = str(tmp_path / "bb")
        _write_checkpoint(ckpt)

        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            "HOROVOD_TPU_REQTRACE": rt,
            "HOROVOD_TPU_BLACKBOX": bb,
        })
        fleet = Fleet(3, ["--checkpoint-dir", ckpt, "--tp", "1",
                          "--block-size", "4", "--kv-blocks", "64",
                          "--slots", "2", "--max-new-tokens", "8"],
                      env=env)
        router = Router(fleet, port=0, host="127.0.0.1",
                        scrape_interval_s=0.1)
        os.makedirs(rt, exist_ok=True)
        reqtrace.start(os.path.join(rt, "reqtrace-router.trace.json"),
                       rank=0, proc="router")
        try:
            fleet.start()
            fleet.wait_ready(600.0)
            router.start()

            # Tenant "gold": unreachable targets — judged AND met.
            status, gold = _post(
                router.port,
                {"tokens": [3, 5, 7, 9], "max_new_tokens": 8,
                 "tenant": "gold",
                 "slo": {"ttft_ms": 1e6, "tpot_ms": 1e6}})
            assert status == 200, gold
            assert gold["tenant"] == "gold"
            assert gold["slo"]["slo_met"] is True
            assert gold["slo"]["ttft_violation"] is False

            # Same tenant over the streaming path: the done line
            # carries the verdict too.
            conn = http.client.HTTPConnection("127.0.0.1",
                                              router.port, timeout=300)
            conn.request("POST", "/generate",
                         json.dumps({"tokens": [4, 6, 8],
                                     "max_new_tokens": 8,
                                     "stream": True, "tenant": "gold",
                                     "slo": {"ttft_ms": 1e6}}),
                         {"Content-Type": "application/json"})
            lines = [json.loads(ln) for ln in
                     conn.getresponse().read().splitlines()
                     if ln.strip()]
            conn.close()
            done = lines[-1]
            assert done.get("done") and done["status"] == "completed"
            assert done["tenant"] == "gold"
            assert done["slo"]["slo_met"] is True

            # Tenant "bulk": a sub-millisecond TTFT target no real
            # request can meet — a guaranteed, judged violation.
            status, bulk = _post(
                router.port,
                {"tokens": [11, 13, 17, 19, 23], "max_new_tokens": 8,
                 "tenant": "bulk", "slo": {"ttft_ms": 0.0001}})
            assert status == 200, bulk
            assert bulk["tenant"] == "bulk"
            assert bulk["slo"]["slo_met"] is False
            assert bulk["slo"]["ttft_violation"] is True
            assert bulk["trace_id"]

            # Fleet-side accounting: the ROUTER process (this one)
            # re-counts verdicts from the replies it relayed.
            snap = metrics_snapshot()
            good = snap["hvdtpu_slo_goodput_total"]["values"]
            assert good.get('tenant="gold"', 0) >= 2, good
            viol = snap["hvdtpu_slo_violations_total"]["values"]
            assert viol.get('reason="ttft",tenant="bulk"', 0) >= 1

            # Replica-side: the replica that judged the bulk request
            # holds the violation counter AND the violation histogram
            # whose exemplar links the violating trace id.
            rep = fleet.replicas[bulk["replica"]]
            conn = http.client.HTTPConnection(
                "127.0.0.1", rep.metrics_port, timeout=30)
            conn.request("GET", "/metrics.json")
            rsnap = json.loads(conn.getresponse().read())
            conn.close()
            rviol = rsnap["hvdtpu_slo_violations_total"]["values"]
            assert rviol.get('reason="ttft",tenant="bulk"', 0) >= 1, \
                rviol
            hist = rsnap["hvdtpu_slo_violation_seconds"]["values"]
            ex = hist['tenant="bulk"']["exemplar"]
            assert ex["trace_id"] == bulk["trace_id"]
            # and the per-tenant request histogram saw both tenants
            reqh = rsnap["hvdtpu_slo_request_seconds"]["values"]
            assert 'tenant="bulk"' in reqh
        finally:
            router.shutdown()
            fleet.stop()
            reqtrace.stop()

        # --- merged request trace: the budget report names the tenant
        # and the judged verdict for both requests.
        out = tmp_path / "serving_report.json"
        proc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.tools.trace",
             "serving", rt, "--report", str(out)],
            capture_output=True, text=True, timeout=300, cwd=ROOT)
        assert proc.returncode == 0, proc.stderr[-2000:]
        report = json.loads(out.read_text())
        brow = report["requests"][bulk["trace_id"]]
        assert brow["tenant"] == "bulk"
        assert brow["slo_met"] is False
        grow = report["requests"][gold["trace_id"]]
        assert grow["tenant"] == "gold"
        assert grow["slo_met"] is True
        # the human table renders the verdict column + tenant suffix
        assert "MISS" in proc.stdout
        assert "tenant=bulk" in proc.stdout

        # --- flight recorder: the serving replica's blackbox (dumped
        # at the drained SIGTERM exit, quarantined per incarnation)
        # carries the request finish event with tenant + violation.
        gen0 = os.path.join(bb, "gen0")
        path = os.path.join(
            gen0, f"blackbox-rank{bulk['replica']}.jsonl")
        assert os.path.exists(path), os.listdir(bb)
        events = [json.loads(ln) for ln in open(path)
                  if ln.strip()]
        finishes = [e for e in events
                    if e.get("kind") == "request"
                    and e.get("event") == "finish"
                    and e.get("trace") == bulk["trace_id"]]
        assert finishes, "blackbox must carry the request's finish"
        assert "tenant=bulk" in finishes[0]["detail"]
        assert "slo=ttft" in finishes[0]["detail"]


@pytest.mark.slow
class TestFleetBenchReproducible:
    def test_bench_fleet_determinism_and_availability(self, tmp_path):
        """bench_serving.py --fleet regenerates BENCH_FLEET
        reproducibly (seeded counts + output checksum identical across
        runs) and supports the availability claim: an injected replica
        crash mid-load drops ZERO requests and leaves every output
        token-identical to an uncontended run."""
        outs = []
        for i in range(2):
            out = tmp_path / f"fleet{i}.json"
            subprocess.run(
                [sys.executable, os.path.join(ROOT, "bench_serving.py"),
                 "--fleet", "--out", str(out)],
                check=True, capture_output=True, text=True,
                timeout=900, cwd=ROOT)
            outs.append(json.loads(out.read_text()))
        a, b = outs
        for run in outs:
            assert run["requests_failed"] == 0, run
            assert run["requests_succeeded"] == \
                run["requests_attempted"]
            assert run["outputs_equal_uncontended"], run
            assert run["replica_restarts"] >= 1, run
        # the deterministic fields byte-compare across regenerations
        for key in ("requests_attempted", "requests_succeeded",
                    "requests_failed", "output_checksum", "replicas",
                    "fault", "outputs_equal_uncontended"):
            assert a[key] == b[key], key


@pytest.mark.slow
class TestFleetAutoscaleE2E:
    def test_fleet_scales_up_under_load_and_drains_back(
            self, tmp_path):
        """QoS autoscaling acceptance (docs/serving.md#qos): a
        2-replica fleet with decode capacity pinned by a slow_decode
        fault grows to 3 under sustained over-capacity load (scale
        event recorded with a valid why), then — once load subsides —
        drains the extra replica back down to the floor with ZERO
        dropped requests: every request fired during the load phase
        AND during the scale-down drain completes with 200."""
        import threading

        from horovod_tpu.observability import registry as _reg
        from horovod_tpu.serving import AutoscalerConfig, FleetAutoscaler

        ckpt = str(tmp_path / "ckpt")
        _write_checkpoint(ckpt)

        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            # Pin capacity: every decode tick costs >= 20ms, so two
            # 2-slot replicas sustain ~12 tok-bursts/s and a 12-wide
            # closed loop holds load_per_slot ~3 >> high_load.
            "HOROVOD_TPU_FAULT_SPEC": "rank=*:slow_decode=20ms",
        })
        fleet = Fleet(2, ["--checkpoint-dir", ckpt, "--tp", "1",
                          "--block-size", "4", "--kv-blocks", "64",
                          "--slots", "2",
                          "--max-new-tokens", "16"],
                      env=env)
        router = Router(fleet, port=0, host="127.0.0.1",
                        scrape_interval_s=0.1)
        scaler = FleetAutoscaler(
            fleet,
            AutoscalerConfig(2, 3, high_load=1.2, low_load=0.3,
                             sustain_s=1.0, cooldown_s=3.0,
                             alert_hold_s=2.0),
            signals=router.qos_signals, interval_s=0.25)
        fleet.on_alert = scaler.note_alert
        fleet.start()
        try:
            fleet.wait_ready(600.0)
            router.start()
            scaler.start()

            stop = threading.Event()
            lock = threading.Lock()
            load_results = []

            def pound(seed):
                rng = np.random.RandomState(seed)
                while not stop.is_set():
                    toks = [int(t) for t in rng.randint(0, 64, 6)]
                    status, body = _post(
                        router.port,
                        {"tokens": toks, "max_new_tokens": 16},
                        timeout=180)
                    with lock:
                        load_results.append(
                            (status, body.get("error")))

            # --- phase 1: sustained over-capacity load -> scale up
            with ThreadPoolExecutor(max_workers=12) as pool:
                futs = [pool.submit(pound, 100 + i)
                        for i in range(12)]
                deadline = time.monotonic() + 240.0
                grown = False
                while time.monotonic() < deadline:
                    ups = [d for d in scaler.decisions
                           if d["direction"] == "up"]
                    if ups and \
                            router.qos_signals()["n_replicas"] >= 3:
                        grown = True
                        break
                    time.sleep(0.5)
                assert grown, (scaler.decisions,
                               router.qos_signals())
                # goodput on the grown fleet: keep pounding briefly
                time.sleep(3.0)
                stop.set()
                for f in futs:
                    f.result(timeout=300)

            assert load_results, "load phase produced no requests"
            bad = [r for r in load_results if r[0] != 200]
            assert not bad, f"dropped during scale-up: {bad[:5]}"
            ups = [d for d in scaler.decisions
                   if d["direction"] == "up"]
            assert ups and all(
                d["why"] in ("queue_runaway", "ttft_trend",
                             "retry_pressure", "queue_depth")
                for d in ups), scaler.decisions
            assert all(2 <= d["n"] <= 3
                       for d in scaler.decisions), scaler.decisions

            # --- phase 2: load subsides -> drain back to the floor,
            # with a live trickle riding through the drain.
            trickle = []
            deadline = time.monotonic() + 180.0
            shrunk = False
            while time.monotonic() < deadline:
                downs = [d for d in scaler.decisions
                         if d["direction"] == "down"]
                if downs and fleet.live_count() == 2 and \
                        len(fleet.replicas) == 2:
                    shrunk = True
                    break
                status, _body = _post(
                    router.port,
                    {"tokens": [1, 2, 3], "max_new_tokens": 4},
                    timeout=60)
                trickle.append(status)
                time.sleep(0.5)
            assert shrunk, (scaler.decisions, fleet.live_count(),
                            len(fleet.replicas))
            assert trickle, "no trickle requests rode the drain"
            assert all(s == 200 for s in trickle), trickle

            # the fleet still serves at the floor
            status, body = _post(
                router.port,
                {"tokens": [4, 5, 6], "max_new_tokens": 4},
                timeout=60)
            assert status == 200, body

            # supervisor-side evidence: the scale-event counter saw
            # both directions.
            snap = _reg.registry().snapshot(
                "hvdtpu_fleet_scale_events_total")
            keys = list(snap["hvdtpu_fleet_scale_events_total"]
                        ["values"])
            assert any('direction="up"' in k for k in keys), keys
            assert any('direction="down"' in k for k in keys), keys
        finally:
            scaler.stop()
            router.shutdown()
            fleet.stop()
