"""Unified metrics & health telemetry (docs/metrics.md).

Acceptance coverage:
- metrics_snapshot() after a fused allreduce + allgather run contains
  per-op latency histograms (non-zero counts, monotone cumulative bucket
  sums) and wire-byte counters matching the engine's _Request accounting;
- the Prometheus endpoint serves the same values in valid text
  exposition format (parsed here, not eyeballed);
- the stall report surfaces as metrics in BOTH control planes: the
  coordinator (one rank withheld → a non-empty stalled-tensors gauge
  naming the missing rank, native and Python planners) and the engine;
- registry counters survive executor/engine resets (the ad-hoc-counter
  migration fix).
"""

import json
import math
import re
import time
import urllib.request

import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu import executor as _exec
from horovod_tpu.observability import (MetricsServer, StepTimer, enabled,
                                       get_registry, prometheus_text,
                                       set_enabled, write_json_snapshot)
from horovod_tpu.observability import registry as _reg
from horovod_tpu.ops import collective as _coll


def _hist(snap, name, labels):
    return snap[name]["values"][labels]


def _assert_monotone_histogram(h):
    cums = [c for _, c in h["buckets"]]
    assert cums == sorted(cums), "cumulative bucket sums must be monotone"
    assert h["buckets"][-1][0] == math.inf
    assert h["buckets"][-1][1] == h["count"]


class TestRegistry:
    def test_counter_gauge_histogram_basics(self):
        r = get_registry()
        c = r.counter("t_reg_counter", "test").labels(x="1")
        c.inc()
        c.inc(2.5)
        assert c.value == 3.5
        with pytest.raises(ValueError):
            c.inc(-1)
        g = r.gauge("t_reg_gauge", "test").labels()
        g.set(7)
        g.inc(3)
        g.dec(1)
        assert g.value == 9.0
        h = r.histogram("t_reg_hist", "test",
                        buckets=[0.1, 1.0, 10.0]).labels()
        for v in (0.05, 0.5, 5.0, 50.0):
            h.observe(v)
        snap = h.snapshot()
        assert snap["count"] == 4
        assert snap["sum"] == pytest.approx(55.55)
        assert [c for _, c in snap["buckets"]] == [1, 2, 3, 4]
        _assert_monotone_histogram(snap)

    def test_type_conflict_rejected(self):
        r = get_registry()
        r.counter("t_reg_conflict", "test")
        with pytest.raises(ValueError):
            r.gauge("t_reg_conflict", "test")

    def test_disabled_mode_is_noop(self):
        r = get_registry()
        c = r.counter("t_reg_disabled", "test").labels()
        assert enabled()
        set_enabled(False)
        try:
            c.inc(100)
            assert c.value == 0.0
        finally:
            set_enabled(True)
        c.inc(1)
        assert c.value == 1.0

    def test_snapshot_plain_dict(self):
        r = get_registry()
        r.counter("t_reg_snap", "help text").labels(a="b").inc(4)
        snap = _reg.snapshot()
        fam = snap["t_reg_snap"]
        assert fam["type"] == "counter"
        assert fam["help"] == "help text"
        assert fam["values"]['a="b"'] == 4.0


class TestEngineInstrumentation:
    def test_fused_allreduce_allgather_histograms_and_wire_bytes(self):
        """ACCEPTANCE: latency histograms for both ops with non-zero
        counts and monotone cumulative sums; wire-byte counter delta ==
        the engine's _Request accounting delta."""
        eng = _coll.engine()
        before = hvd.metrics_snapshot()

        def count_of(snap, op, phase):
            fam = snap.get("hvdtpu_op_phase_seconds", {"values": {}})
            key = f'op="{op}",phase="{phase}"'
            v = fam["values"].get(key)
            return v["count"] if v else 0

        def wire_total(snap):
            fam = snap.get("hvdtpu_wire_bytes_enqueued_total",
                           {"values": {}})
            return sum(fam["values"].values())

        wire_attr_before = eng.wire_bytes_enqueued
        with eng.burst():
            h1 = hvd.allreduce_async(jnp.ones((128,)), average=False,
                                     name="metrics.ar.a")
            h2 = hvd.allreduce_async(jnp.full((64,), 2.0), average=False,
                                     name="metrics.ar.b")
        hvd.synchronize(h1)
        hvd.synchronize(h2)
        hvd.allgather(jnp.ones((4, 4)), name="metrics.ag")

        after = hvd.metrics_snapshot()
        for op in ("allreduce", "allgather"):
            for phase in ("negotiate", "queue", "execute"):
                assert count_of(after, op, phase) > count_of(
                    before, op, phase), (op, phase)
            h = _hist(after, "hvdtpu_op_phase_seconds",
                      f'op="{op}",phase="execute"')
            _assert_monotone_histogram(h)
            assert h["sum"] > 0
        # Wire bytes: registry delta == attribute (_Request) delta.
        wire_delta = wire_total(after) - wire_total(before)
        assert wire_delta == eng.wire_bytes_enqueued - wire_attr_before
        assert wire_delta >= (128 + 64 + 16) * 4

    def test_wire_bytes_labeled_by_spec(self):
        from horovod_tpu.compression import Compression
        snap0 = hvd.metrics_snapshot()

        def spec_val(snap, spec):
            fam = snap.get("hvdtpu_wire_bytes_enqueued_total",
                           {"values": {}})
            return fam["values"].get(f'spec="{spec}"', 0.0)

        hvd.allreduce(jnp.ones((512,)), average=True,
                      name="metrics.wire.q",
                      compression=Compression.int8_blockwise)
        snap1 = hvd.metrics_snapshot()
        delta = spec_val(snap1, "int8x256") - spec_val(snap0, "int8x256")
        # 512 floats → 512 int8 payload + 2 blocks × 4B scales
        assert delta == 512 + 2 * 4

    def test_fused_group_size_observed(self):
        snap = hvd.metrics_snapshot()
        h = _hist(snap, "hvdtpu_fused_group_size", "")
        assert h["count"] >= 1
        _assert_monotone_histogram(h)

    def test_engine_stall_gauges(self):
        """Engine view: a request stuck past the warning window shows up
        in the stalled-tensor gauges; a clean check zeroes them."""
        eng = _coll.CollectiveEngine.__new__(_coll.CollectiveEngine)
        # Minimal fields _maybe_check_stalls touches.
        import threading
        eng._lock = threading.Lock()
        eng._metrics = _coll._EngineMetrics()
        eng.stall_warning_s = 0.01
        eng._last_stall_check = 0.0
        eng._coord_stall_lines = {}
        eng._mp = False
        h = _coll.Handle(1, "stuck.t")
        req = _coll._Request("stuck.t", _coll.ALLREDUCE,
                             jnp.ones((4,)), h)
        req.enqueued_at = time.monotonic() - 10.0
        eng._in_flight = {"stuck.t": req}
        eng._queue = []
        eng.failure_timeout_s = 0.0
        eng._maybe_check_stalls()
        snap = hvd.metrics_snapshot()
        assert snap["hvdtpu_engine_stalled_tensors"]["values"][""] == 1.0
        info = snap["hvdtpu_engine_stalled_tensor_seconds"]["values"]
        key = ('missing_ranks="none(single-process)",tensor="stuck.t"')
        assert key in info and info[key] >= 9.0
        # Episode resolves → gauges clear on the next check.
        eng._in_flight = {}
        eng._last_stall_check = 0.0
        eng._maybe_check_stalls()
        snap = hvd.metrics_snapshot()
        assert snap["hvdtpu_engine_stalled_tensors"]["values"][""] == 0.0
        assert snap["hvdtpu_engine_stalled_tensor_seconds"]["values"] == {}


class TestCoordinatorStallMetrics:
    @pytest.mark.parametrize("native", [True, False],
                             ids=["native", "python"])
    def test_withheld_rank_named_in_gauge(self, native):
        """ACCEPTANCE: coordinator mode with one rank withheld → a
        non-empty stalled-tensors gauge naming the missing rank, with
        both planners."""
        from horovod_tpu.ops.control_plane import (CoordinatorClient,
                                                   CoordinatorService)
        from horovod_tpu.runner.secret import make_secret_key
        svc = CoordinatorService(nproc=2, key=make_secret_key(),
                                 fusion_threshold=1024, native=native,
                                 stall_warning_s=0.05)
        try:
            c0 = CoordinatorClient([("127.0.0.1", svc.port)], svc.key, 0)
            c0.announce([{"name": "metrics.stuck", "op": 0,
                          "dtype": "float32", "shape": (4,),
                          "root_rank": -1}])       # rank 1 withheld
            time.sleep(0.1)
            svc._last_stall_check = 0.0
            lines = svc.check_stalls()
            assert lines
            snap = hvd.metrics_snapshot()
            count = snap["hvdtpu_coordinator_stalled_tensors"]["values"][""]
            assert count >= 1.0
            info = snap["hvdtpu_coordinator_stalled_tensor_seconds"][
                "values"]
            key = 'missing_ranks="1",tensor="metrics.stuck"'
            assert key in info, info
            assert info[key] > 0
        finally:
            svc.shutdown()

    def test_resolved_stall_clears_gauge(self):
        from horovod_tpu.ops.control_plane import (CoordinatorClient,
                                                   CoordinatorService)
        from horovod_tpu.runner.secret import make_secret_key
        svc = CoordinatorService(nproc=2, key=make_secret_key(),
                                 fusion_threshold=1024, native=False,
                                 stall_warning_s=0.05)
        try:
            c0 = CoordinatorClient([("127.0.0.1", svc.port)], svc.key, 0)
            c1 = CoordinatorClient([("127.0.0.1", svc.port)], svc.key, 1)
            c0.announce([{"name": "metrics.res", "op": 0,
                          "dtype": "float32", "shape": (4,),
                          "root_rank": -1}])
            time.sleep(0.1)
            svc._last_stall_check = 0.0
            assert svc.check_stalls()
            c1.announce([{"name": "metrics.res", "op": 0,
                          "dtype": "float32", "shape": (4,),
                          "root_rank": -1}])       # quorum → resolved
            svc._last_stall_check = 0.0
            svc.check_stalls()
            snap = hvd.metrics_snapshot()
            info = snap["hvdtpu_coordinator_stalled_tensor_seconds"][
                "values"]
            assert not any("metrics.res" in k for k in info)
        finally:
            svc.shutdown()


class TestExecutorMigration:
    def test_registry_counters_survive_executor_reset(self):
        """Satellite fix: reset_default_executor() used to silently
        discard counter state; the registry series accumulate across
        instances."""
        def totals():
            snap = hvd.metrics_snapshot()
            return tuple(
                sum(snap[n]["values"].values()) for n in
                ("hvdtpu_executor_cache_misses_total",
                 "hvdtpu_executor_cache_hits_total",
                 "hvdtpu_executor_device_puts_total"))

        before = totals()
        ex = _exec.CollectiveExecutor(mesh=hvd.mesh())
        xs = [jnp.full((32,), 3.0)]
        out = ex.allreduce_fused(xs)
        ex.allreduce_fused(out)
        inst = (ex.cache_misses, ex.cache_hits, ex.device_put_count)
        assert inst[0] >= 1 and inst[1] >= 1 and inst[2] >= 1
        _exec.reset_default_executor()   # must NOT lose registry totals
        after = totals()
        for b, a, i in zip(before, after, inst):
            assert a - b >= i

    def test_compile_seconds_recorded(self):
        snap0 = hvd.metrics_snapshot()
        n0 = _hist(snap0, "hvdtpu_executor_compile_seconds", "")["count"] \
            if "hvdtpu_executor_compile_seconds" in snap0 else 0
        ex = _exec.CollectiveExecutor(mesh=hvd.mesh())
        ex.allreduce_fused([jnp.full((48,), 1.0)])
        h = _hist(hvd.metrics_snapshot(),
                  "hvdtpu_executor_compile_seconds", "")
        assert h["count"] > n0
        assert h["sum"] > 0
        _assert_monotone_histogram(h)


_PROM_LINE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? ([-+0-9.eE]+|\+Inf|-Inf|NaN)$')


def _parse_prometheus(text):
    """Minimal text-exposition parser: validates every sample line and
    returns {series_name: {label_block: float}}."""
    out = {}
    types = {}
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ", 3)
            types[name] = kind
            continue
        if line.startswith("#"):
            continue
        m = _PROM_LINE.match(line)
        assert m, f"invalid exposition line: {line!r}"
        name = line.split("{")[0].split(" ")[0]
        labels = m.group(1) or ""
        out.setdefault(name, {})[labels] = float(m.group(2))
    return out, types


class TestPrometheusExposition:
    def test_text_format_valid_and_consistent(self):
        hvd.allreduce(jnp.ones((16,)), name="metrics.prom.ar")
        snap = hvd.metrics_snapshot()
        series, types = _parse_prometheus(prometheus_text(snap))
        assert types["hvdtpu_op_phase_seconds"] == "histogram"
        assert types["hvdtpu_wire_bytes_enqueued_total"] == "counter"
        # Histogram invariants in the exposition itself: per label set,
        # _bucket cumulative counts are monotone in le and the +Inf
        # bucket equals _count.
        buckets = series["hvdtpu_op_phase_seconds_bucket"]
        counts = series["hvdtpu_op_phase_seconds_count"]
        by_labelset = {}
        for lab, v in buckets.items():
            m = re.search(r'le="([^"]*)"', lab)
            base = lab.replace("{", "").replace("}", "")
            base = ",".join(p for p in base.split(",")
                            if not p.startswith('le='))
            le = math.inf if m.group(1) == "+Inf" else float(m.group(1))
            by_labelset.setdefault(base, []).append((le, v))
        for base, pairs in by_labelset.items():
            pairs.sort()
            cums = [v for _, v in pairs]
            assert cums == sorted(cums), base
            assert pairs[-1][0] == math.inf
            assert counts[f"{{{base}}}"] == pairs[-1][1]
        # Counter value matches the snapshot it was rendered from.
        fam = snap["hvdtpu_wire_bytes_enqueued_total"]["values"]
        for label_key, val in fam.items():
            assert series["hvdtpu_wire_bytes_enqueued_total"][
                f"{{{label_key}}}"] == val

    def test_http_endpoint_serves_both_formats(self):
        """ACCEPTANCE: the endpoint serves valid exposition (parsed, not
        eyeballed) and the JSON snapshot."""
        hvd.allreduce(jnp.ones((8,)), name="metrics.http.ar")
        srv = MetricsServer(0)
        try:
            base = f"http://127.0.0.1:{srv.port}"
            with urllib.request.urlopen(f"{base}/metrics",
                                        timeout=10) as resp:
                assert resp.status == 200
                assert resp.headers["Content-Type"].startswith(
                    "text/plain")
                text = resp.read().decode()
            series, types = _parse_prometheus(text)
            assert "hvdtpu_op_phase_seconds_bucket" in series
            assert any(v > 0 for v in
                       series["hvdtpu_ops_total"].values())
            with urllib.request.urlopen(f"{base}/metrics.json",
                                        timeout=10) as resp:
                assert resp.status == 200
                snap = json.loads(resp.read().decode())
            assert "hvdtpu_op_phase_seconds" in snap
            with urllib.request.urlopen(f"{base}/nope", timeout=10) as r:
                pass
        except urllib.error.HTTPError as e:
            assert e.code == 404
        finally:
            srv.stop()


class TestJsonSnapshotFile:
    def test_atomic_write_and_strict_json(self, tmp_path):
        hvd.allreduce(jnp.ones((8,)), name="metrics.json.ar")
        path = tmp_path / "metrics.json"
        write_json_snapshot(str(path))
        snap = json.loads(path.read_text())   # strict JSON (no Infinity)
        h = snap["hvdtpu_op_phase_seconds"]["values"][
            'op="allreduce",phase="execute"']
        assert h["buckets"][-1][0] == "+Inf"
        assert h["buckets"][-1][1] == h["count"]

    def test_periodic_writer_env_driven(self, tmp_path, monkeypatch):
        from horovod_tpu.observability import export as _export
        path = tmp_path / "periodic.json"
        monkeypatch.setenv("HOROVOD_TPU_METRICS_FILE", str(path))
        monkeypatch.setenv("HOROVOD_TPU_METRICS_INTERVAL", "0.05")
        _export.stop_exporters()   # reset the idempotency latch
        _export.maybe_start_exporters()
        try:
            deadline = time.monotonic() + 10
            while not path.exists() and time.monotonic() < deadline:
                time.sleep(0.02)
            assert path.exists()
            json.loads(path.read_text())
        finally:
            _export.stop_exporters()


class TestStepTimer:
    def test_samples_per_sec_and_allreduce_share(self):
        timer = StepTimer("test_fw", batch_size=32)
        timer.begin()
        hvd.allreduce(jnp.ones((256,)), name="metrics.step.ar")
        timer.end()
        assert timer.last_step_s > 0
        assert timer.last_samples_per_s > 0
        # The step WAS an allreduce, so its execute time is a real
        # fraction of the step.
        assert 0.0 < timer.last_allreduce_share <= 1.0
        snap = hvd.metrics_snapshot()
        assert snap["hvdtpu_samples_per_second"]["values"][
            'framework="test_fw"'] > 0
        h = _hist(snap, "hvdtpu_step_seconds", 'framework="test_fw"')
        assert h["count"] == 1

    def test_context_manager_form(self):
        timer = StepTimer("test_fw2", batch_size=4)
        with timer:
            np.ones((8,)).sum()
        assert timer.last_step_s > 0


class TestStepAttribution:
    """Tentpole: per-step input/h2d/compute/collective attribution, MFU
    and HBM gauges (docs/metrics.md)."""

    def test_collective_share_counts_all_ops(self):
        """Satellite fix: the share must count allgather/broadcast
        execute seconds, not only op="allreduce" — proven by feeding
        the registry counter directly (what the engine does)."""
        from horovod_tpu.observability import registry as _reg
        fam = _reg.registry().counter(
            "hvdtpu_op_execute_seconds_total",
            "Cumulative wall seconds executing fused collective groups")
        timer = StepTimer("attr_allops")
        timer.begin()
        fam.labels(op="allgather").inc(0.5)
        fam.labels(op="broadcast").inc(0.25)
        time.sleep(0.01)
        timer.end()
        # 0.75 s of collective execute inside a ~10 ms step: clamped
        # share of 1.0 — under the old allreduce-only read this was 0.
        assert timer.last_collective_share == 1.0
        assert timer.last_allreduce_share == 1.0    # alias, same value
        snap = hvd.metrics_snapshot()
        vals = snap["hvdtpu_collective_step_share"]["values"]
        legacy = snap["hvdtpu_allreduce_step_share"]["values"]
        assert vals['framework="attr_allops"'] == 1.0
        assert legacy['framework="attr_allops"'] == 1.0
        assert "DEPRECATED" in snap["hvdtpu_allreduce_step_share"]["help"]

    def test_input_wait_attributed_to_input_phase(self):
        timer = StepTimer("attr_input")
        with timer:
            pass
        time.sleep(0.05)           # "the loader" between steps
        with timer:
            time.sleep(0.01)       # "compute"
        phases = timer.last_phases
        assert phases["input"] >= 0.04
        assert phases["compute"] >= 0.005
        snap = hvd.metrics_snapshot()
        share = snap["hvdtpu_step_phase_share"]["values"]
        key = 'framework="attr_input",phase="input"'
        assert share[key] > 0.5    # the cycle was input-dominated
        h = _hist(snap, "hvdtpu_step_phase_seconds",
                  'framework="attr_input",phase="input"')
        assert h["count"] == 2

    def test_h2d_mark(self):
        timer = StepTimer("attr_h2d")
        with timer:
            time.sleep(0.02)
            timer.mark_h2d_done()
            time.sleep(0.005)
        assert timer.last_phases["h2d"] >= 0.015
        assert timer.last_phases["compute"] < timer.last_phases["h2d"]

    def test_mfu_and_flops_gauges(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_TPU_PEAK_FLOPS", "1e12")
        timer = StepTimer("attr_mfu", flops_per_step=1e9)
        with timer:
            time.sleep(0.01)
        snap = hvd.metrics_snapshot()
        flops = snap["hvdtpu_model_flops_per_second"]["values"][
            'framework="attr_mfu"']
        assert flops > 0
        mfu = snap["hvdtpu_mfu"]["values"]['framework="attr_mfu"']
        assert mfu == pytest.approx(flops / 1e12)
        assert 0 < mfu < 1

    def test_mfu_not_exported_without_peak(self, monkeypatch):
        monkeypatch.delenv("HOROVOD_TPU_PEAK_FLOPS", raising=False)
        timer = StepTimer("attr_nopeak", flops_per_step=1e9)
        with timer:
            pass
        snap = hvd.metrics_snapshot()
        # flops rate always exported; MFU needs a peak (none on CPU).
        assert snap["hvdtpu_model_flops_per_second"]["values"][
            'framework="attr_nopeak"'] > 0
        assert 'framework="attr_nopeak"' not in \
            snap.get("hvdtpu_mfu", {}).get("values", {})

    def test_hbm_gauges_present(self):
        """Acceptance: HBM gauges appear in metrics_snapshot() — on the
        CPU test backend via the host-RSS fallback."""
        timer = StepTimer("attr_hbm")
        with timer:
            pass
        snap = hvd.metrics_snapshot()
        live = snap["hvdtpu_hbm_bytes_in_use"]["values"]
        peak = snap["hvdtpu_hbm_peak_bytes"]["values"]
        assert any(v > 0 for v in live.values())
        assert any(v > 0 for v in peak.values())

    def test_flops_of_lowered(self):
        import jax

        from horovod_tpu.observability import flops_of_lowered
        f = jax.jit(lambda x: x @ x)
        lowered = f.lower(jnp.ones((64, 64)))
        flops = flops_of_lowered(lowered.compile())
        # CPU backends may or may not expose a cost analysis; when they
        # do, a 64x64 matmul is ~2*64^3 flops.
        if flops is not None:
            assert flops >= 64 * 64 * 64

    def test_step_spans_emitted_into_live_timeline(self, tmp_path):
        """With the engine's Python timeline active, end() emits STEP_*
        spans the trace report turns into the bound verdict."""
        from horovod_tpu.ops import collective as _coll
        from horovod_tpu.ops.timeline_py import PyTimeline
        eng = _coll.engine()
        old_tl = eng.timeline
        tl = PyTimeline(str(tmp_path / "steps.json"))
        eng.timeline = tl
        try:
            timer = StepTimer("attr_spans")
            with timer:
                time.sleep(0.002)
            time.sleep(0.02)   # input gap
            with timer:
                time.sleep(0.002)
        finally:
            eng.timeline = old_tl
            tl.close()
        events = json.loads((tmp_path / "steps.json").read_text())
        names = [e.get("name") for e in events if e.get("ph") == "X"]
        assert "STEP_COMPUTE" in names
        assert "STEP_INPUT" in names


class TestElasticMetrics:
    def test_health_line_and_gauges(self):
        """The driver's structured health line renders from the registry
        (world size, failures, last re-rendezvous ms)."""
        import logging
        from horovod_tpu.elastic.driver import _ElasticMetrics, _log
        m = _ElasticMetrics()
        m.world_size.set(4)
        m.generation.set(2)
        m.failure("sigkill")
        m.last_rendezvous_ms.set(123.0)
        records = []

        class _Capture(logging.Handler):
            def emit(self, record):
                records.append(record.getMessage())

        cap = _Capture(level=logging.INFO)
        old_level = _log.level
        _log.addHandler(cap)
        _log.setLevel(logging.INFO)
        try:
            m.health_line("shrink", 4, 2, "a:2,b:2")
        finally:
            _log.removeHandler(cap)
            _log.setLevel(old_level)
        text = " ".join(records)
        assert "elastic_health" in text
        assert "event=shrink" in text and "world_size=4" in text
        assert "last_rendezvous_ms=123" in text
        snap = hvd.metrics_snapshot()
        fails = snap["hvdtpu_elastic_worker_failures_total"]["values"]
        assert fails['kind="sigkill"'] >= 1
        assert fails['kind="all"'] >= 1


class TestPerRankMetricsPort:
    """Satellite: HOROVOD_TPU_METRICS_PORT {rank}/base+rank forms make
    every rank scrapeable in multi-process mode (docs/metrics.md)."""

    def test_plain_port_rank0_only(self, monkeypatch):
        from horovod_tpu.utils import env as _env
        monkeypatch.setenv("HOROVOD_TPU_METRICS_PORT", "9091")
        assert _env.metrics_port(0) == 9091
        assert _env.metrics_port(3) == 9091
        assert _env.metrics_port_per_rank() is False

    def test_placeholder_form(self, monkeypatch):
        from horovod_tpu.utils import env as _env
        monkeypatch.setenv("HOROVOD_TPU_METRICS_PORT", "909{rank}")
        assert _env.metrics_port(0) == 9090
        assert _env.metrics_port(7) == 9097
        assert _env.metrics_port_per_rank() is True

    def test_base_plus_rank_form(self, monkeypatch):
        from horovod_tpu.utils import env as _env
        monkeypatch.setenv("HOROVOD_TPU_METRICS_PORT", "9091+rank")
        assert _env.metrics_port(0) == 9091
        assert _env.metrics_port(5) == 9096
        assert _env.metrics_port_per_rank() is True

    def test_two_ranks_bind_distinct_ports(self, monkeypatch):
        """Two ranks' resolved ports bind two live endpoints, each
        serving the exposition."""
        import socket

        from horovod_tpu.utils import env as _env

        hvd.allreduce(jnp.ones((4,)), name="metrics.perrank.ar")
        for _ in range(5):   # free-port race: retry with a fresh base
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            base = s.getsockname()[1]
            s.close()
            monkeypatch.setenv("HOROVOD_TPU_METRICS_PORT",
                               f"{base}+rank")
            ports = [_env.metrics_port(r) for r in (0, 1)]
            assert ports == [base, base + 1]
            try:
                servers = [MetricsServer(p) for p in ports]
            except OSError:
                continue
            try:
                assert sorted(s.port for s in servers) == ports
                for p in ports:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{p}/metrics",
                            timeout=10) as resp:
                        assert b"hvdtpu_ops_total" in resp.read()
            finally:
                for srv in servers:
                    srv.stop()
            return
        pytest.skip("could not find two adjacent free ports")


class TestJsonPercentiles:
    """Satellite: the endpoint's JSON view carries p50/p90/p99 estimated
    from the log buckets (shared estimator with the trace report)."""

    def test_metrics_json_includes_percentiles(self):
        from horovod_tpu.observability import with_percentiles
        from horovod_tpu.observability.export import json_safe_snapshot

        hvd.allreduce(jnp.ones((16,)), name="metrics.pct.ar")
        snap = with_percentiles(json_safe_snapshot())
        fam = snap["hvdtpu_op_phase_seconds"]["values"]
        key = 'op="allreduce",phase="execute"'
        assert key in fam
        pct = fam[key]["percentiles"]
        assert set(pct) == {"p50", "p90", "p99"}
        assert 0 < pct["p50"] <= pct["p90"] <= pct["p99"]

    def test_http_json_view_serves_percentiles(self):
        hvd.allreduce(jnp.ones((16,)), name="metrics.pct.http")
        srv = MetricsServer(0)
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/metrics.json",
                    timeout=10) as resp:
                snap = json.loads(resp.read().decode())
            hists = [v for fam in snap.values()
                     if fam["type"] == "histogram"
                     for v in fam["values"].values() if v["count"]]
            assert hists
            assert all("percentiles" in v for v in hists)
        finally:
            srv.stop()
