"""Test harness — multi-device-without-a-pod.

The reference runs its whole pytest suite under ``mpirun -np 2`` on
localhost (.travis.yml:100-111) so real collectives exercise the full
negotiation path between two processes. The TPU-native analogue (SURVEY.md
§4) is an 8-device virtual CPU mesh via
``--xla_force_host_platform_device_count`` — the same XLA collectives and
sharding machinery as a real v5e-8, minus the ICI.
"""

import os
import tempfile

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# Persistent XLA compilation cache, shared by the suite and every
# subprocess it spawns (env vars are inherited): the suite compiles
# the same tiny models over and over — each InferenceEngine/worker
# re-jits identical HLO — and the cache collapses the repeats. Keyed
# on HLO + compile options, so mixed device counts are safe; set via
# env (not jax.config) so fleet replicas and bench workers get it too.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(tempfile.gettempdir(), "hvdtpu-test-xla-cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                      "0.5")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

import horovod_tpu as hvd  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running subprocess integration test")


@pytest.fixture(scope="session", autouse=True)
def _init_horovod():
    hvd.init()
    yield


@pytest.fixture(autouse=True)
def _fresh_names():
    # Each op name must be unique among in-flight ops only; tests reuse
    # names freely because they synchronize. Nothing to reset per-test, but
    # keep the hook for engine-level isolation if a test kills the engine.
    yield
