"""Contract on BENCH_SLO.json (docs/benchmarks.md#bench_slo): the
--slo bench artifact must keep the sweep arm names, the seeded-
deterministic evidence (schedule checksums, offered counts, the
identical-interactive-schedule invariant) and the headline shape the
acceptance criteria read. Wall-clock numbers (goodput fractions,
percentiles, the knee's location) are re-measured every run and are
NOT pinned here beyond basic sanity; the slow-tier class regenerates
the bench twice and byte-compares the deterministic fields."""

import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PATH = os.path.join(ROOT, "BENCH_SLO.json")

SWEEP_ARMS = ("rps4", "rps10", "rps25")
# Seeded-deterministic per-arm evidence. goodput / percentiles are
# wall-clock and deliberately excluded.
ARM_DETERMINISTIC = ("schedule_checksum", "offered", "offered_rps",
                     "duration_s")


def _deterministic_view(bench):
    """The byte-comparable subset of a BENCH_SLO.json: everything the
    seeded schedules pin, nothing the wall clock touches."""
    view = {"metric": bench["metric"], "config": bench["config"],
            "model": bench["model"], "sweep": {}, "two_tenant": {}}
    for name, arm in bench["sweep"].items():
        view["sweep"][name] = {k: arm[k] for k in ARM_DETERMINISTIC}
    tt = bench["two_tenant"]
    view["two_tenant"] = {
        "interactive_schedule_checksum":
            tt["with_bulk_burst"]["interactive_schedule_checksum"],
        "bulk_schedule_checksum":
            tt["with_bulk_burst"]["bulk_schedule_checksum"],
        "interactive_only_checksum":
            tt["interactive_only"]["schedule_checksum"],
        "interactive_schedules_identical":
            tt["interactive_schedules_identical"],
        "offered_alone": tt["interactive_only"]["offered"],
        "offered_burst": tt["with_bulk_burst"]["offered"],
    }
    q = bench["qos"]
    view["qos"] = {
        "policy": q["policy"],
        "reserved_slots": q["reserved_slots"],
        "interactive_schedule_checksum":
            q["with_bulk_burst"]["interactive_schedule_checksum"],
        "bulk_schedule_checksum":
            q["with_bulk_burst"]["bulk_schedule_checksum"],
        "interactive_only_checksum":
            q["interactive_only"]["schedule_checksum"],
        "interactive_schedules_identical":
            q["interactive_schedules_identical"],
        "autoscale_config": q["autoscale"]["config"],
        "autoscale_checksums": {
            name: arm["schedule_checksum"]
            for name, arm in q["autoscale"]["sweep"].items()},
    }
    return view


@pytest.fixture(scope="module")
def bench():
    if not os.path.exists(PATH):
        pytest.skip("BENCH_SLO.json not generated on this checkout")
    with open(PATH) as f:
        return json.load(f)


def test_metric_and_config_are_pinned(bench):
    assert bench["metric"] == "slo_goodput_vs_offered_load"
    cfg = bench["config"]
    assert cfg["replicas"] == 3
    assert cfg["slots_per_replica"] == 2
    assert cfg["arrival_process"] == "poisson"
    assert cfg["fault"] == "rank=*:slow_decode=20ms"
    assert cfg["sweep_rps"] == [4, 10, 25]
    assert cfg["slo"] == {"ttft_ms": 500.0, "tpot_ms": 100.0}
    assert bench["clean_stop"] is True


@pytest.mark.parametrize("arm", SWEEP_ARMS)
def test_sweep_arms_carry_deterministic_fields(bench, arm):
    assert arm in bench["sweep"], f"sweep arm {arm} missing"
    row = bench["sweep"][arm]
    for key in ARM_DETERMINISTIC:
        assert key in row, (arm, key)
    # The open-loop invariant: every scheduled arrival is accounted
    # for — completed, or shed (which folds in 429s, 504s, failures
    # and in-flight-cap drops).
    t = row["tenants"]["sweep"]
    assert t["offered"] == row["offered"]
    assert t["completed"] + t["shed"] == t["offered"], (arm, t)
    assert (t["dropped"] + t["rejected"] + t["deadline"] + t["failed"]
            == t["shed"]), (arm, t)
    # Judged tenant: goodput counts only SLO-met completions.
    assert t["goodput"] <= t["completed"], (arm, t)


def test_sweep_offered_counts_scale_with_rate(bench):
    """Seeded Poisson schedules: offered counts are deterministic and
    ordered by rate (rps25 fires more than rps10 fires more than
    rps4)."""
    o = {a: bench["sweep"][a]["offered"] for a in SWEEP_ARMS}
    assert o["rps4"] < o["rps10"] < o["rps25"], o


def test_two_tenant_interactive_schedule_is_identical(bench):
    """The A/B's validity rests on this: the interactive tenant's
    arrivals in the burst run are byte-identical (checksum) to the
    interactive-only run — any p99 movement is the bulk tenant's
    doing."""
    tt = bench["two_tenant"]
    assert tt["interactive_schedules_identical"] is True
    assert (tt["with_bulk_burst"]["interactive_schedule_checksum"]
            == tt["interactive_only"]["schedule_checksum"])
    assert tt["with_bulk_burst"]["bulk_schedule_checksum"]
    assert tt["interactive_p99_inflation"] > 0


def test_headlines_hold(bench):
    h = bench["headlines"]
    # The slow_decode fault pins capacity ~12 req/s; offered loads of
    # 4/10/25 straddle it, so a knee must exist (at rps25 or earlier)
    # with goodput visibly below offered there.
    assert h["has_knee"] is True
    assert h["knee_rps"] in (4.0, 10.0, 25.0)
    assert h["goodput_frac_at_knee"] < 1.0
    assert h["interactive_schedules_identical"] is True
    assert h["interactive_p99_inflation"] == \
        bench["two_tenant"]["interactive_p99_inflation"]


def test_qos_replay_is_byte_identical(bench):
    """ACCEPTANCE (docs/serving.md#qos): the QoS arm replays the SAME
    interactive schedule as the plain two-tenant A/B — identical
    checksum across all three runs — so the inflation numbers compare
    like for like."""
    q = bench["qos"]
    tt = bench["two_tenant"]
    assert q["interactive_schedules_identical"] is True
    assert (q["with_bulk_burst"]["interactive_schedule_checksum"]
            == q["interactive_only"]["schedule_checksum"])
    # Same schedule the PLAIN fleet saw: priority lives server-side in
    # the SLO config file, never in the arrival rows.
    assert (q["interactive_only"]["schedule_checksum"]
            == tt["interactive_only"]["schedule_checksum"])
    assert (q["with_bulk_burst"]["bulk_schedule_checksum"]
            == tt["with_bulk_burst"]["bulk_schedule_checksum"])


def test_qos_policy_and_classes_are_pinned(bench):
    q = bench["qos"]
    assert q["reserved_slots"] == 1
    assert q["policy"]["interactive"]["priority"] == "interactive"
    assert q["policy"]["bulk"]["priority"] == "bulk"
    assert q["policy"]["interactive"]["weight"] > \
        q["policy"]["bulk"]["weight"]
    # The class-tagged client rollup rode along.
    assert "interactive" in q["with_bulk_burst"]["by_class"]
    assert "bulk" in q["with_bulk_burst"]["by_class"]


def test_qos_bounds_interactive_inflation(bench):
    """ACCEPTANCE: with priority classes + reserved slot + class-aware
    routing on, the interactive tenant's burst TTFT p99 inflation is
    bounded (<= 3x its own-fleet alone run) instead of the unbounded
    queueing the plain fleet shows; bulk degrades gracefully (completes
    work, never starves interactive)."""
    q = bench["qos"]
    assert q["interactive_p99_inflation_qos"] > 0
    assert q["interactive_p99_inflation_qos"] <= 3.0, q
    bulk = q["with_bulk_burst"]["by_class"]["bulk"]
    assert bulk["completed"] > 0, bulk


def test_qos_headlines_hold(bench):
    h = bench["headlines"]
    q = bench["qos"]
    assert h["interactive_p99_inflation_qos"] == \
        q["interactive_p99_inflation_qos"]
    assert h["qos_schedules_identical"] is True
    assert h["fleet_scaled_up"] is True
    assert h["fleet_scaled_back_down"] is True


def test_autoscale_sweep_recorded(bench):
    a = bench["qos"]["autoscale"]
    assert a["config"]["min"] == 2
    assert a["config"]["max"] == 4
    for name in ("rps4", "rps10", "rps25", "rps25_scaled"):
        assert name in a["sweep"], name
    assert a["scaled_up"] is True
    ups = [e for e in a["scale_events"] if e["direction"] == "up"]
    assert ups and all(
        e["why"] in ("queue_runaway", "ttft_trend", "retry_pressure",
                     "queue_depth") for e in ups), a["scale_events"]
    assert all(2 <= e["n"] <= 4 for e in a["scale_events"])
    # The grown fleet is never below the floor.
    assert a["replicas_final"] >= 2


def test_past_knee_arm_sheds_or_violates(bench):
    """rps25 is ~2x pinned capacity: the fleet cannot be meeting every
    SLO there. Some of the offered load shows up as violations, shed,
    or in-flight-cap drops."""
    row = bench["sweep"]["rps25"]
    t = row["tenants"]["sweep"]
    assert t["slo_violations"] + t["shed"] > 0, t
    assert row["goodput_frac"] < 1.0, row


@pytest.mark.slow
class TestBenchSloReproducible:
    def test_slo_bench_deterministic_fields_byte_compare(self,
                                                         tmp_path):
        """ACCEPTANCE (reproducibility guard): bench_serving.py --slo
        regenerated twice produces byte-identical deterministic fields
        — seeded schedules, checksums, offered counts, config — while
        wall-clock goodput/percentiles are free to vary."""
        views = []
        for i in range(2):
            out = tmp_path / f"slo{i}.json"
            subprocess.run(
                [sys.executable, os.path.join(ROOT, "bench_serving.py"),
                 "--slo", "--out", str(out)],
                check=True, capture_output=True, text=True,
                timeout=2400, cwd=ROOT)
            bench = json.loads(out.read_text())
            assert bench["clean_stop"] is True
            views.append(_deterministic_view(bench))
        a, b = views
        assert json.dumps(a, sort_keys=True) == \
            json.dumps(b, sort_keys=True)
