"""Telemetry history ring (docs/health.md): snapshot-delta reduction,
the crash-safe rotating writer, the merger's torn-tail tolerance, the
prefix-filtered snapshot satellite, and the one-telemetry-thread
consolidation regression test."""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

import horovod_tpu as hvd
from horovod_tpu.observability import history as _history
from horovod_tpu.observability import registry as _reg
from horovod_tpu.observability import ticker as _ticker

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _hist_snap(values):
    """Build a cumulative histogram snapshot from raw observations
    through a real registry Histogram (the exact shape snapshots
    carry)."""
    h = _reg.Histogram(_reg.LATENCY_BUCKETS)
    for v in values:
        h.observe(v)
    return h.snapshot()


class TestSeriesReduction:
    def test_counter_becomes_rate(self):
        prev = {"hvdtpu_x_total": {"type": "counter", "help": "",
                                   "values": {"": 10.0}}}
        cur = {"hvdtpu_x_total": {"type": "counter", "help": "",
                                  "values": {"": 30.0}}}
        s = _history.series_from_snapshots(prev, cur, dt_s=2.0)
        assert s["hvdtpu_x_total"] == pytest.approx(10.0)

    def test_counter_reset_uses_prometheus_rate_semantics(self):
        """A scraped replica restarted: cur < prev must not emit a
        negative rate — the new value IS the delta since the reset."""
        prev = {"hvdtpu_x_total": {"type": "counter", "help": "",
                                   "values": {"": 100.0}}}
        cur = {"hvdtpu_x_total": {"type": "counter", "help": "",
                                  "values": {"": 4.0}}}
        s = _history.series_from_snapshots(prev, cur, dt_s=2.0)
        assert s["hvdtpu_x_total"] == pytest.approx(2.0)

    def test_gauge_passes_through(self):
        cur = {"hvdtpu_g": {"type": "gauge", "help": "",
                            "values": {'device="host"': 42.0}}}
        s = _history.series_from_snapshots({}, cur, dt_s=5.0)
        assert s['hvdtpu_g{device="host"}'] == 42.0

    def test_histogram_windowed_mean_is_exact(self):
        """The |mean series must reflect ONLY the window's
        observations, exactly — a 20% shift inside one log bucket is
        invisible to bucket percentiles but not to the mean."""
        prev = {"hvdtpu_h": {"type": "histogram", "help": "",
                             "values": {"": _hist_snap([0.010] * 50)}}}
        cur_h = _hist_snap([0.010] * 50 + [0.012] * 10)
        cur = {"hvdtpu_h": {"type": "histogram", "help": "",
                            "values": {"": cur_h}}}
        s = _history.series_from_snapshots(prev, cur, dt_s=1.0)
        assert s["hvdtpu_h|mean"] == pytest.approx(0.012, rel=1e-6)
        assert s["hvdtpu_h|rate"] == pytest.approx(10.0)
        assert s["hvdtpu_h|p50"] > 0
        assert s["hvdtpu_h|p99"] >= s["hvdtpu_h|p50"]

    def test_histogram_empty_window_emits_nothing(self):
        snap = {"hvdtpu_h": {"type": "histogram", "help": "",
                             "values": {"": _hist_snap([0.01])}}}
        s = _history.series_from_snapshots(snap, snap, dt_s=1.0)
        assert not [k for k in s if k.startswith("hvdtpu_h")]

    def test_json_safe_inf_bounds_tolerated(self):
        """Scraped /metrics.json snapshots carry "+Inf" strings."""
        raw = _hist_snap([0.01] * 4)
        prev_h = {"buckets": [["+Inf" if le == float("inf") else le, c]
                              for le, c in raw["buckets"][:1]] +
                             raw["buckets"][1:],
                  "sum": 0.0, "count": 0}
        cur_h = dict(raw)
        cur_h["buckets"] = [["+Inf" if le == float("inf") else le, c]
                            for le, c in raw["buckets"]]
        s = _history.series_from_snapshots(
            {"h": {"type": "histogram", "values": {"": prev_h}}},
            {"h": {"type": "histogram", "values": {"": cur_h}}}, 1.0)
        assert s["h|mean"] == pytest.approx(0.01, rel=1e-6)


class TestPrefixSnapshot:
    def test_metrics_snapshot_prefix_filters(self):
        r = _reg.registry()
        r.counter("hvdtpu_histtest_a_total", "x").inc()
        r.gauge("hvdtpu_othertest_b", "x").set(1)
        snap = hvd.metrics_snapshot(prefix="hvdtpu_histtest_")
        assert "hvdtpu_histtest_a_total" in snap
        assert all(k.startswith("hvdtpu_histtest_") for k in snap)
        # tuple prefixes work too (str.startswith semantics)
        snap2 = hvd.metrics_snapshot(
            prefix=("hvdtpu_histtest_", "hvdtpu_othertest_"))
        assert "hvdtpu_othertest_b" in snap2

    def test_endpoint_prefix_query(self):
        import urllib.request

        from horovod_tpu.observability import MetricsServer
        _reg.registry().counter("hvdtpu_histtest_ep_total", "x").inc()
        srv = MetricsServer(0)
        try:
            url = (f"http://127.0.0.1:{srv.port}/metrics.json"
                   f"?prefix=hvdtpu_histtest_")
            with urllib.request.urlopen(url, timeout=10) as resp:
                snap = json.loads(resp.read())
            assert "hvdtpu_histtest_ep_total" in snap
            assert all(k.startswith("hvdtpu_histtest_") for k in snap)
        finally:
            srv.stop()


class TestWriterRotation:
    def test_header_then_samples(self, tmp_path):
        w = _history.HistoryWriter(str(tmp_path), "rank0",
                                   meta=lambda: {"rank": 0, "world": 2})
        w.append({"t_us": 1, "s": {"a": 1.0}})
        w.append({"t_us": 2, "s": {"a": 2.0}})
        w.close()
        lines = [json.loads(x) for x in
                 open(tmp_path / "history-rank0.jsonl")]
        assert lines[0]["history"] == _history.SCHEMA_VERSION
        assert lines[0]["rank"] == 0
        assert [x["t_us"] for x in lines[1:]] == [1, 2]

    def test_rotation_bounds_disk_and_keeps_headers(self, tmp_path):
        w = _history.HistoryWriter(str(tmp_path), "rank0",
                                   max_bytes=400, segments=2,
                                   meta=lambda: {"rank": 0})
        for i in range(60):
            w.append({"t_us": i, "s": {"a": float(i)}})
        w.close()
        live = tmp_path / "history-rank0.jsonl"
        segs = sorted(tmp_path.glob("history-rank0.jsonl.*"))
        assert live.exists()
        assert len(segs) == 2            # bounded: .1 and .2 only
        for p in [live] + segs:
            assert p.stat().st_size <= 400 + 200  # cap + one line slack
            first = json.loads(open(p).readline())
            assert first["history"] == _history.SCHEMA_VERSION
        # The merger folds segments oldest-first with no duplicates.
        hf = _history.load_label(str(live))
        ts = [s["t_us"] for s in hf.samples]
        assert ts == sorted(ts)
        assert len(ts) == len(set(ts))
        assert ts[-1] == 59              # newest survived
        assert ts[0] > 0                 # oldest rotated away

    def test_torn_tail_tolerated(self, tmp_path):
        w = _history.HistoryWriter(str(tmp_path), "rank0",
                                   meta=lambda: {"rank": 0})
        for i in range(5):
            w.append({"t_us": i, "s": {"a": float(i)}})
        w.close()
        path = tmp_path / "history-rank0.jsonl"
        with open(path, "a") as f:
            f.write('{"t_us": 5, "s": {"a": 5')   # torn mid-write
        hf = _history.load_label(str(path))
        assert [s["t_us"] for s in hf.samples] == [0, 1, 2, 3, 4]

    def test_load_history_expands_directories(self, tmp_path):
        for label in ("rank0", "rank1", "replica0"):
            w = _history.HistoryWriter(str(tmp_path), label,
                                       meta=lambda: {})
            w.append({"t_us": 1, "s": {"a": 1.0}})
            w.close()
        files = _history.load_history([str(tmp_path)])
        assert sorted(f.label for f in files) == ["rank0", "rank1",
                                                  "replica0"]

    def test_load_history_missing_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            _history.load_history([str(tmp_path)])

    def test_clock_alignment_shifts_onto_rank0(self, tmp_path):
        w0 = _history.HistoryWriter(str(tmp_path), "rank0",
                                    meta=lambda: {"rank": 0,
                                                  "offset_to_rank0_us":
                                                  0.0})
        w0.append({"t_us": 1000, "s": {"a": 1.0}})
        w0.close()
        w1 = _history.HistoryWriter(str(tmp_path), "rank1",
                                    meta=lambda: {"rank": 1,
                                                  "offset_to_rank0_us":
                                                  500.0})
        w1.append({"t_us": 600, "s": {"a": 1.0}})
        w1.close()
        files = {f.label: f for f in _history.load_history(
            [str(tmp_path)])}
        assert files["rank1"].samples[0]["t_aligned_us"] == 1100.0
        assert files["rank0"].samples[0]["t_aligned_us"] == 1000.0


class TestSampler:
    def test_tick_writes_delta_sample(self, tmp_path):
        r = _reg.registry()
        c = r.counter("hvdtpu_histtest_tick_total", "x").labels()
        s = _history.HistorySampler(
            str(tmp_path), "rank0", interval_s=60,
            prefix="hvdtpu_histtest_", meta=lambda: {"rank": 0})
        assert s.tick() is None          # first tick: nothing to delta
        c.inc(10)
        sample = s.tick()
        s.writer.close()
        assert sample is not None
        key = "hvdtpu_histtest_tick_total"
        assert sample["s"][key] > 0
        hf = _history.load_label(str(tmp_path / "history-rank0.jsonl"))
        assert len(hf.samples) == 1

    def test_set_enabled_gates_sampling(self, tmp_path):
        s = _history.HistorySampler(
            str(tmp_path), "rank0", interval_s=60,
            prefix="hvdtpu_histtest_", meta=lambda: {})
        _history.set_enabled(False)
        try:
            assert s.tick() is None
            assert s.tick() is None
        finally:
            _history.set_enabled(True)

    def test_source_failure_counts_error_not_raise(self, tmp_path):
        def bad_source():
            raise ConnectionError("replica down")

        s = _history.HistorySampler(
            str(tmp_path), "replica9", interval_s=60,
            source=bad_source, meta=lambda: {})
        before = _reg.registry().counter(
            "hvdtpu_history_sample_errors_total", "").labels().value
        assert s.tick() is None
        after = _reg.registry().counter(
            "hvdtpu_history_sample_errors_total", "").labels().value
        assert after == before + 1


class TestSingleTelemetryThread:
    """Satellite bugfix regression: the periodic JSON metrics exporter
    and the history sampler must share ONE timer thread — each used to
    (or would) spawn its own."""

    def test_json_writer_and_sampler_share_one_thread(self, tmp_path):
        from horovod_tpu.observability.export import _JsonWriter
        jw = _JsonWriter(str(tmp_path / "m.json"), interval_s=60)
        sampler = _history.HistorySampler(
            str(tmp_path), "rank0", interval_s=60,
            prefix="hvdtpu_histtest_", meta=lambda: {}).start()
        try:
            names = [t.name for t in threading.enumerate()]
            assert names.count(_ticker.THREAD_NAME) == 1
            # The old per-exporter thread name must be gone for good.
            assert "hvd-tpu-metrics-file" not in names
            tasks = set(_ticker.ticker().tasks().values())
            assert "metrics-file" in tasks
            assert "history-rank0" in tasks
        finally:
            sampler.stop()
            jw.stop()
        # Removal ran both final flushes: the JSON file exists even
        # though the 60 s interval never elapsed.
        assert (tmp_path / "m.json").exists()

    def test_ticker_runs_tasks_at_interval(self):
        t = _ticker.Ticker()
        hits = []
        h = t.add("t", 0.05, lambda: hits.append(time.monotonic()))
        time.sleep(0.35)
        t.remove(h)
        n = len(hits)
        assert n >= 3
        time.sleep(0.15)
        assert len(hits) == n            # removed tasks stop firing
        t.stop()

    def test_ticker_survives_raising_task(self):
        t = _ticker.Ticker()
        hits = []

        def boom():
            raise RuntimeError("bad exporter")

        t.add("boom", 0.05, boom)
        t.add("good", 0.05, lambda: hits.append(1))
        time.sleep(0.3)
        t.stop()
        assert len(hits) >= 2            # one bad task != all dead


class TestFleetHistory:
    """The supervisor samples each replica's scraped serving metrics
    into history-replica{i}.jsonl (docs/health.md#fleet) — replica
    trends survive replica death because the files belong to the
    supervisor."""

    def test_supervisor_samples_replicas_and_fleet(self, tmp_path,
                                                   monkeypatch):
        from horovod_tpu.observability import MetricsServer
        from horovod_tpu.serving.fleet import Fleet

        # A live in-process registry endpoint stands in for the
        # replica's metrics server.
        _reg.registry().gauge(
            "hvdtpu_serving_queue_depth", "x").labels().set(3.0)
        srv = MetricsServer(0)
        monkeypatch.setenv("HOROVOD_TPU_HISTORY", str(tmp_path))
        monkeypatch.setenv("HOROVOD_TPU_HISTORY_INTERVAL", "3600")
        fleet = Fleet(1, [], host="127.0.0.1")

        class FakeProc:                      # alive, never polled out
            def poll(self):
                return None

        rep = fleet.replicas[0]
        rep.proc = FakeProc()
        rep.port = srv.port
        rep.metrics_port = srv.port
        try:
            fleet._maybe_start_history()
            labels = {s.writer.label for s in fleet._history}
            assert labels == {"replica0", "fleet"}
            for s in fleet._history:
                s.tick()                      # establish the baseline
            _reg.registry().gauge(
                "hvdtpu_serving_queue_depth", "x").labels().set(5.0)
            for s in fleet._history:
                s.tick()
        finally:
            for s in fleet._history:
                s.stop()
            fleet._history = []
            srv.stop()
        hf = _history.load_label(
            str(tmp_path / "history-replica0.jsonl"))
        assert hf.meta["replica"] == 0
        assert hf.meta["role"] == "serving_replica"
        depths = [s["s"].get("hvdtpu_serving_queue_depth")
                  for s in hf.samples]
        assert 5.0 in depths
        # Only serving families crossed the scrape (prefix= filter).
        for s in hf.samples:
            assert all(k.startswith("hvdtpu_serving_")
                       for k in s["s"])
        assert (tmp_path / "history-fleet.jsonl").exists()

    def test_replica_sampler_skipped_in_replica_process(
            self, tmp_path, monkeypatch):
        """A fleet replica must not start its own rank-named sampler —
        the supervisor owns replica history (two replicas would both
        claim history-rank0.jsonl)."""
        monkeypatch.setenv("HOROVOD_TPU_HISTORY", str(tmp_path))
        monkeypatch.setenv("HOROVOD_TPU_REPLICA_ID", "1")
        assert _history.maybe_start_sampler() is None


_KILL_SCRIPT = r"""
import os, sys, time
from horovod_tpu.observability import history as _history
from horovod_tpu.observability import registry as _reg

d = sys.argv[1]
r = _reg.registry()
c = r.counter("hvdtpu_histtest_kill_total", "x").labels()
# Tiny segments: rotation happens every few samples.
w = _history.HistoryWriter(d, "rank0", max_bytes=500, segments=3,
                           meta=lambda: {"rank": 0})
s = _history.HistorySampler(d, "rank0", interval_s=60,
                            prefix="hvdtpu_histtest_", writer=w)
i = 0
while True:
    c.inc(7)
    s.tick()
    i += 1
    if i == 3:
        print("SAMPLING", flush=True)
    time.sleep(0.002)
"""


class TestCrashSafety:
    def test_sigkill_mid_write_leaves_valid_prefixes(self, tmp_path):
        """ACCEPTANCE (satellite): SIGKILL a sampling subprocess
        mid-write; every rotated segment must be a valid JSONL prefix
        and the merger must tolerate the torn tail."""
        proc = subprocess.Popen(
            [sys.executable, "-c", _KILL_SCRIPT, str(tmp_path)],
            stdout=subprocess.PIPE, text=True, cwd=ROOT)
        try:
            assert proc.stdout.readline().strip() == "SAMPLING"
            # Let it rotate a few segments, then kill at a random
            # moment relative to the write cadence.
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if list(tmp_path.glob("history-rank0.jsonl.*")):
                    break
                time.sleep(0.01)
            time.sleep(0.013)
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=30)
        finally:
            if proc.poll() is None:
                proc.kill()
        live = tmp_path / "history-rank0.jsonl"
        segs = sorted(tmp_path.glob("history-rank0.jsonl.*"))
        assert segs, "subprocess never rotated a segment"
        # Every ROTATED segment is complete JSONL (rotation happens at
        # append boundaries); the live file may have one torn tail.
        for p in segs:
            for line in open(p):
                json.loads(line)
        lines = open(live).read().splitlines()
        for line in lines[:-1]:
            json.loads(line)
        # The merger reads everything, skipping any torn tail.
        hf = _history.load_label(str(live))
        assert hf is not None
        assert len(hf.samples) >= 3
        ts = [s["t_us"] for s in hf.samples]
        assert ts == sorted(ts)
        for s in hf.samples:
            assert "hvdtpu_histtest_kill_total" in s["s"]
