"""Input-pipeline acceptance (ISSUE 13, docs/data.md) — slow tier.

  1. Throttled-loader verdict e2e: a deliberately slow loader flips the
     ``tools/trace report`` run verdict to input-bound and populates
     ``hvdtpu_step_phase_seconds{phase="input"}``; an unthrottled
     prefetch-enabled run is NOT input-bound — both arms in the same
     test (the ROADMAP's "something to catch").
  2. Elastic exactly-once e2e: train with the sharded loader under
     ``run_elastic``, SIGKILL a worker mid-epoch, shrink 2→1, regrow
     1→2 — the multiset of consumed sample ids equals one clean epoch
     exactly (no duplicate, no gap) and the final state matches a clean
     replay at rtol 1e-5.
  3. ``bench_engine.py --data`` reproducibility guard for
     BENCH_DATA.json.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from horovod_tpu.elastic.failure import FailureConfig
from horovod_tpu.runner.api import run

pytestmark = pytest.mark.slow

_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
    "HOROVOD_TPU_DISABLE_NATIVE": "1",
    "HOROVOD_CYCLE_TIME": "1",
}

NP = 4


# ---------------------------------------------------------------------------
# 1. Throttled loader -> input-bound verdict; prefetch -> not input-bound
# ---------------------------------------------------------------------------

def _make_verdict_worker():
    def worker(trace_dir, steps, throttle_s, prefetch):
        import os
        import time

        import jax.numpy as jnp
        import numpy as np

        import horovod_tpu as hvd
        from horovod_tpu import data
        from horovod_tpu.observability import StepTimer
        from horovod_tpu.ops import collective

        os.environ["HOROVOD_TPU_TIMELINE"] = os.path.join(
            trace_dir, "trace.{rank}.json")
        hvd.init()
        r = hvd.process_rank()
        timer = StepTimer("data_e2e", batch_size=8)

        def slow(arrays):
            if throttle_s:
                time.sleep(throttle_s)
            return arrays

        src = data.synthetic("image", n=4 * 8 * (steps + 2),
                             image_size=8, num_classes=4, seed=7)
        loader = data.build_loader(src, batch_size=8, rank=r,
                                   world_size=4, seed=7,
                                   transform=slow)
        it = data.prefetch_to_device(loader, depth=2, timer=timer) \
            if prefetch else iter(loader)
        for step in range(steps):
            b = next(it)
            timer.begin()
            if not prefetch:
                b = data.stage(b, timer=timer)
            # Step compute derives from the delivered batch, and the
            # collective path stays exercised.
            v = jnp.full((16,), float(np.asarray(b.data[0]).mean()))
            hvd.allreduce(v, average=True, name=f"d.step{step}")
            timer.end()
        if prefetch:
            it.close()
        snap = hvd.metrics_snapshot()
        collective.engine().shutdown()
        input_hist = {
            k: v for k, v in snap["hvdtpu_step_phase_seconds"]
            ["values"].items() if 'phase="input"' in k}
        return {
            "rank": r,
            "input_sum": sum(h["sum"] for h in input_hist.values()),
            "wait_s": snap["hvdtpu_data_wait_seconds_total"]
            ["values"].get("", 0.0) if "hvdtpu_data_wait_seconds_total"
            in snap else 0.0,
            "samples": snap["hvdtpu_data_samples_total"]["values"][""],
        }

    return worker


class TestInputBoundVerdict:
    STEPS = 10

    def _report(self, trace_dir, out):
        from horovod_tpu.tools import trace as trace_tool
        trace_tool._main(["report",
                          str(trace_dir / "trace.{rank}.json"),
                          "--report", str(out)])
        return json.loads(out.read_text())

    def test_throttled_is_input_bound_and_prefetch_is_not(self,
                                                          tmp_path):
        # Arm A: 250 ms/batch source, synchronous staging.
        dir_a = tmp_path / "throttled"
        dir_a.mkdir()
        results = run(_make_verdict_worker(),
                      args=(str(dir_a), self.STEPS, 0.25, False),
                      np=NP, extra_env=dict(_ENV), start_timeout=300)
        report_a = self._report(dir_a, tmp_path / "report_a.json")
        assert report_a["bound"] == "input-bound", report_a["bound"]
        for r in range(NP):
            assert report_a["per_rank"][str(r)]["verdict"] == \
                "input-bound"
            assert report_a["per_rank"][str(r)]["phase_share"][
                "input"] > 0.4
        # The live counterpart of the verdict: the input phase
        # histogram carries the waits (the acceptance metric).
        for res in results:
            assert res["input_sum"] > 0.25 * (self.STEPS - 2), res
            assert res["samples"] == 8 * self.STEPS

        # Arm B: same workload, no throttle, prefetch on — must NOT be
        # input-bound in the same trace-report test.
        dir_b = tmp_path / "prefetched"
        dir_b.mkdir()
        run(_make_verdict_worker(),
            args=(str(dir_b), self.STEPS, 0.0, True),
            np=NP, extra_env=dict(_ENV), start_timeout=300)
        report_b = self._report(dir_b, tmp_path / "report_b.json")
        assert report_b["bound"] is not None
        assert report_b["bound"] != "input-bound", report_b["bound"]


# ---------------------------------------------------------------------------
# 2. Elastic exactly-once across SIGKILL + shrink + regrow
# ---------------------------------------------------------------------------

N_SAMPLES = 64
BATCH = 4
DATA_SEED = 21
COMMIT_EVERY_OFFSETS = 4      # commit whenever offset % 4 == 0


def _make_elastic_data_worker():
    """Factory so cloudpickle ships the worker by value (the spawned
    ranks cannot import tests/)."""

    def worker(kill_plan=None):
        """One epoch over the sharded loader; training state AND the
        loader cursor AND the consumed-id record commit together, so a
        rollback discards exactly the samples whose updates were lost.
        ``kill_plan`` maps (generation, rank) -> the loader offset at
        which to SIGKILL (the host-loss simulation)."""
        import os
        import signal

        import jax.numpy as jnp
        import numpy as np

        import horovod_tpu as hvd
        from horovod_tpu import data

        kill_plan = kill_plan or {}
        hvd.init()
        r = hvd.process_rank()
        gen = hvd.generation()
        world = hvd.size()

        src = data.synthetic("image", n=64, image_size=4,
                             num_classes=4, seed=3)
        loader = data.build_loader(src, batch_size=4, rank=r,
                                   world_size=world, seed=21, epochs=1)

        state = hvd.ElasticState(
            params={"w": jnp.zeros((4,))},
            consumed=np.zeros((0,), np.int64),
            data=loader.cursor())
        state.restore()
        loader.restore(state.data)
        w = jnp.asarray(state.params["w"])
        consumed = list(np.asarray(state.consumed).tolist())

        kill_at = kill_plan.get((gen, r))
        perm = loader.dataset.permutation(0)
        while True:
            prev_off = loader.offset
            try:
                batch = next(loader)
            except StopIteration:
                break
            if kill_at is not None and loader.offset > kill_at:
                # offset already advanced past the step that would
                # start at kill_at — die BEFORE folding this batch in.
                os.kill(os.getpid(), signal.SIGKILL)
            # Order- and world-independent update: w accumulates a
            # per-sample feature sum over the whole epoch, so the final
            # state is a pure function of the consumed multiset.
            local = np.zeros((4,), np.float32)
            if batch.weight:
                imgs = np.asarray(batch.data[0]).reshape(
                    batch.weight, -1)
                feat = imgs.mean(axis=1) + np.asarray(
                    batch.data[1], np.float32)
                for i, sid in enumerate(batch.ids):
                    local += feat[i] * np.asarray(
                        [1.0, 0.5, -1.0, 2.0]) * (1 + (int(sid) % 5))
            g = hvd.allreduce(jnp.asarray(local), average=False,
                              name=f"g.{gen}.{loader.epoch}."
                                   f"{loader.offset}")
            w = w - 0.01 * g / 64.0
            # The GLOBAL ids this step consumed, derived from the
            # shared epoch plan (every rank computes the same record —
            # only rank 0's copy is durably committed).
            consumed.extend(
                int(i) for i in perm[prev_off * 4:loader.offset * 4])
            if loader.offset % 4 == 0 or loader.offset >= \
                    loader.dataset.total_microbatches:
                state.params = {"w": w}
                state.consumed = np.asarray(sorted(consumed), np.int64)
                state.data = loader.commit_cursor()
                state.commit(loader.offset + 1000 * loader.epoch)
        return {"w": np.asarray(w).tolist(),
                "consumed": sorted(consumed),
                "gen": gen, "size": world}

    return worker


class TestElasticExactlyOnce:
    def test_sigkill_shrink_regrow_consumes_one_clean_epoch(
            self, tmp_path):
        from horovod_tpu import data
        from horovod_tpu.elastic import run_elastic
        from horovod_tpu.runner.api import run as plain_run

        state_dir = str(tmp_path / "estate")
        # gen 0 (np=2): rank 1 dies at offset 6 (last commit: offset 4)
        # gen 1 (np=1): rank 0 dies at offset 11 (last commit: 8)
        # gen 2 (np=2, regrown after the blacklist expires): finishes.
        kill_plan = {(0, 1): 6, (1, 0): 11}
        cfg = FailureConfig(failure_timeout_s=60.0, max_restarts=4,
                            backoff_s=0.3, backoff_factor=1.5,
                            blacklist_s=0.3)
        results = run_elastic(
            _make_elastic_data_worker(), kwargs={"kill_plan": kill_plan},
            min_np=1, max_np=2, hosts="localhost:2",
            state_dir=state_dir, config=cfg,
            extra_env=dict(_ENV), start_timeout=300)

        # Regrown: the final generation runs at the full world again.
        assert len(results) == 2
        assert all(res["gen"] == 2 and res["size"] == 2
                   for res in results)

        # Exactly-once: the committed record of consumed sample ids is
        # one clean epoch — no duplicate, no gap — despite two kills
        # and two world-size changes.
        src = data.synthetic("image", n=N_SAMPLES, image_size=4,
                             num_classes=4, seed=3)
        ds = data.ShardedDataset(src, batch_size=BATCH, seed=DATA_SEED)
        clean = sorted(ds.epoch_ids(0).tolist())
        for res in results:
            assert res["consumed"] == clean, (
                len(res["consumed"]), len(clean))

        # Final state matches a clean (never-failing) replay at
        # rtol 1e-5 — the update is a function of the multiset, so any
        # duplicate or gap would shift it.
        replay = plain_run(
            _make_elastic_data_worker(), np=2,
            extra_env=dict(_ENV, **{
                "HOROVOD_TPU_ELASTIC_DIR": str(tmp_path / "clean")}),
            start_timeout=300)
        assert replay[0]["consumed"] == clean
        np.testing.assert_allclose(results[0]["w"], replay[0]["w"],
                                   rtol=1e-5)


# ---------------------------------------------------------------------------
# 3. BENCH_DATA.json reproducibility guard
# ---------------------------------------------------------------------------

class TestBenchDataReproducible:
    def test_bench_data_determinism_and_exactly_once(self, tmp_path):
        """bench_engine.py --data regenerates BENCH_DATA reproducibly
        (seeded id checksums and counts identical across runs), the
        exactly-once block holds (0 duplicates / 0 gaps across the
        2→1→2 world path), and prefetch does not regress step time
        (loose bar — on the 1-core CI box only the source's sleep can
        overlap)."""
        root = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))
        outs = []
        for i in range(2):
            out = tmp_path / f"bench{i}.json"
            subprocess.run(
                [sys.executable, os.path.join(root, "bench_engine.py"),
                 "--data", "--data-steps", "15", "--out", str(out)],
                check=True, capture_output=True, text=True, timeout=600,
                cwd=root)
            outs.append(json.loads(out.read_text()))
        a, b = outs

        def deterministic(obj):
            if isinstance(obj, dict):
                return {k: deterministic(v) for k, v in obj.items()
                        if not (k.endswith("_ms") or k == "ms_per_step"
                                or k == "value" or k == "weights_sum")}
            return obj

        assert deterministic(a) == deterministic(b)
        for run_out in outs:
            eo = run_out["exactly_once"]
            assert eo["duplicates"] == 0
            assert eo["gaps"] == 0
            assert eo["ids_match_clean_epoch"] is True
            assert eo["consumed"] == eo["epoch_samples"]
            # The A/B changes staging, never the data.
            assert run_out["prefetch"]["on"]["ids_checksum"] == \
                run_out["prefetch"]["off"]["ids_checksum"]
            # Numerics identical across arms (same batches, same step).
            assert run_out["prefetch"]["on"]["weights_sum"] == \
                pytest.approx(run_out["prefetch"]["off"]["weights_sum"])
            # Loose no-regression bar on the wall-clock ratio.
            assert run_out["value"] < 1.15, run_out["value"]
