"""`python -m horovod_tpu.tools.health` (docs/health.md): merged
per-rank reports — sparklines, offline detector verdicts, the
top-regressions ranking, torn-tail tolerance, and the --baseline A/B
mode (identical runs quiet; an injected regression ranks on top)."""

import json
import subprocess
import sys
import os

import pytest

from horovod_tpu.observability import history as _history
from horovod_tpu.tools import health as _tool

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_run(directory, *, step_s=0.010, leak=False, ranks=2,
               samples=40, shift_at=None):
    """Synthesize a run's history dir: per-rank files with a step-time
    |mean series (optionally shifting at sample `shift_at`), an HBM
    gauge (optionally leaking), and a throughput counter rate."""
    for rank in range(ranks):
        w = _history.HistoryWriter(
            str(directory), f"rank{rank}",
            meta=lambda r=rank: {"rank": r, "world": ranks,
                                 "offset_to_rank0_us": 0.0,
                                 "clock_synced": True})
        for i in range(samples):
            v = step_s
            if shift_at is not None and i >= shift_at:
                v = step_s * 1.3
            hbm = 1e6 + (5e4 * i if leak else 0.0)
            w.append({"t_us": 1_000_000 + i * 100_000,
                      "u": 1000.0 + i, "dt_s": 0.1,
                      "s": {'hvdtpu_step_seconds{framework="t"}|mean': v,
                            'hvdtpu_hbm_bytes_in_use{device="host"}':
                                hbm,
                            "hvdtpu_samples_total": 320.0}})
        w.close()


class TestAnalyze:
    def test_healthy_run_reports_no_alerts(self, tmp_path):
        _write_run(tmp_path)
        report = _tool.analyze(_history.load_history([str(tmp_path)]))
        assert len(report["labels"]) == 2
        assert report["alerts"] == []
        assert report["top_regressions"] == []
        text = _tool.format_report(report)
        assert "healthy" in text

    def test_regression_fires_verdict_and_ranks_top(self, tmp_path):
        _write_run(tmp_path, shift_at=25)
        report = _tool.analyze(_history.load_history([str(tmp_path)]))
        kinds = {a["kind"] for a in report["alerts"]}
        assert kinds == {"step_time_regression"}
        assert {a["label"] for a in report["alerts"]} == {"rank0",
                                                         "rank1"}
        top = report["top_regressions"][0]
        assert "step_seconds" in top["series"]
        assert top["change_frac"] == pytest.approx(0.3, abs=0.05)

    def test_leak_verdict_names_offender_and_window(self, tmp_path):
        _write_run(tmp_path, leak=True, ranks=1)
        report = _tool.analyze(_history.load_history([str(tmp_path)]))
        leaks = [a for a in report["alerts"] if a["kind"] == "hbm_leak"]
        assert leaks
        assert leaks[0]["rank"] == 0
        assert leaks[0]["window_s"] > 0
        # The leaking series gets a sparkline even though HBM is a
        # headline family anyway; check the spark rendering shape.
        rows = report["sparklines"]["rank0"]
        key = 'hvdtpu_hbm_bytes_in_use{device="host"}'
        assert key in rows
        assert set(rows[key]["spark"]) <= set(_tool.SPARK_BLOCKS)

    def test_sparkline_resamples_long_series(self):
        assert len(_tool.sparkline(list(range(1000)), width=40)) == 40
        assert _tool.sparkline([1.0, 1.0, 1.0]) == "▁▁▁"
        assert _tool.sparkline([]) == ""


class TestBaseline:
    def test_identical_runs_report_no_regressions(self, tmp_path):
        _write_run(tmp_path / "a")
        _write_run(tmp_path / "b")
        cur = _history.load_history([str(tmp_path / "a")])
        base = _history.load_history([str(tmp_path / "b")])
        b = _tool.compare_baseline(cur, base)
        assert b["verdict"] == "no_regressions"
        assert b["regressions"] == []
        assert b["series_compared"] > 0

    def test_injected_regression_ranks_step_time_top(self, tmp_path):
        """ACCEPTANCE: a 20% step-time regression vs baseline ranks
        step time as the top regression."""
        _write_run(tmp_path / "base", step_s=0.010)
        _write_run(tmp_path / "cur", step_s=0.012)
        cur = _history.load_history([str(tmp_path / "cur")])
        base = _history.load_history([str(tmp_path / "base")])
        b = _tool.compare_baseline(cur, base)
        assert b["verdict"] == "regressions"
        top = b["regressions"][0]
        assert "step_seconds" in top["series"]
        assert top["change_frac"] == pytest.approx(0.2, abs=0.02)

    def test_improvement_is_not_a_regression(self, tmp_path):
        _write_run(tmp_path / "base", step_s=0.012)
        _write_run(tmp_path / "cur", step_s=0.010)
        cur = _history.load_history([str(tmp_path / "cur")])
        base = _history.load_history([str(tmp_path / "base")])
        b = _tool.compare_baseline(cur, base)
        assert b["verdict"] == "no_regressions"
        assert b["improvements"]


def _write_slo_run(directory, *, goodput_rps=10.0, violations_rps=0.5,
                   samples=40):
    """Synthesize a serving replica's history with the hvdtpu_slo_*
    families the fleet sampler scrapes (counters land as per-second
    rates under the bare series key)."""
    w = _history.HistoryWriter(
        str(directory), "replica0",
        meta=lambda: {"replica": 0, "generation": 0,
                      "role": "serving_replica",
                      "offset_to_rank0_us": 0.0,
                      "clock_synced": True})
    for i in range(samples):
        w.append({"t_us": 1_000_000 + i * 100_000,
                  "u": 1000.0 + i, "dt_s": 0.1,
                  "s": {'hvdtpu_slo_goodput_total{tenant="gold"}':
                            goodput_rps,
                        'hvdtpu_slo_violations_total'
                        '{reason="ttft",tenant="gold"}':
                            violations_rps}})
    w.close()


class TestSloSeries:
    def test_goodput_series_gets_headline_sparkline(self, tmp_path):
        _write_slo_run(tmp_path)
        report = _tool.analyze(_history.load_history([str(tmp_path)]))
        rows = report["sparklines"]["replica0"]
        assert 'hvdtpu_slo_goodput_total{tenant="gold"}' in rows
        assert ('hvdtpu_slo_violations_total'
                '{reason="ttft",tenant="gold"}') in rows

    def test_direction_semantics(self):
        # Goodput falling is worse; violations rising is worse. The
        # goodput marker must win over the generic counter suffix.
        assert _tool._direction(
            'hvdtpu_slo_goodput_total{tenant="a"}') == -1
        assert _tool._direction(
            'hvdtpu_slo_violations_total{reason="ttft",tenant="a"}') \
            == 1

    def test_goodput_drop_is_a_baseline_regression(self, tmp_path):
        _write_slo_run(tmp_path / "base", goodput_rps=10.0)
        _write_slo_run(tmp_path / "cur", goodput_rps=6.0)
        cur = _history.load_history([str(tmp_path / "cur")])
        base = _history.load_history([str(tmp_path / "base")])
        b = _tool.compare_baseline(cur, base)
        assert b["verdict"] == "regressions"
        assert any("slo_goodput" in r["series"]
                   for r in b["regressions"])


class TestCLI:
    def _run(self, *argv):
        proc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.tools.health",
             *argv], capture_output=True, text=True, timeout=120,
            cwd=ROOT)
        return proc

    def test_cli_json_end_to_end(self, tmp_path):
        _write_run(tmp_path, shift_at=25)
        proc = self._run(str(tmp_path), "--json")
        assert proc.returncode == 0, proc.stderr[-2000:]
        report = json.loads(proc.stdout)
        assert report["alerts"]
        assert report["top_regressions"]

    def test_cli_baseline_text(self, tmp_path):
        _write_run(tmp_path / "base", step_s=0.010)
        _write_run(tmp_path / "cur", step_s=0.012)
        proc = self._run(str(tmp_path / "cur"), "--baseline",
                         str(tmp_path / "base"))
        # A baseline diff that FOUND regressions exits 3 — the CI-gate
        # contract (docs/health.md#baseline).
        assert proc.returncode == 3, proc.stderr[-2000:]
        assert "REGRESSED" in proc.stdout
        assert "step_seconds" in proc.stdout

    def test_cli_baseline_clean_exits_0(self, tmp_path):
        """ACCEPTANCE (CI shape): two identical benches diffed with
        --baseline --json exit 0 with verdict no_regressions — the
        exact invocation a perf gate runs."""
        _write_run(tmp_path / "a", step_s=0.010)
        _write_run(tmp_path / "b", step_s=0.010)
        proc = self._run(str(tmp_path / "a"), "--baseline",
                         str(tmp_path / "b"), "--json")
        assert proc.returncode == 0, proc.stderr[-2000:]
        report = json.loads(proc.stdout)
        assert report["baseline"]["verdict"] == "no_regressions"
        assert report["baseline"]["regressions"] == []

    def test_cli_baseline_regressed_json_exits_3(self, tmp_path):
        _write_run(tmp_path / "base", step_s=0.010)
        _write_run(tmp_path / "cur", step_s=0.013)
        proc = self._run(str(tmp_path / "cur"), "--baseline",
                         str(tmp_path / "base"), "--json")
        assert proc.returncode == 3
        report = json.loads(proc.stdout)
        assert report["baseline"]["verdict"] == "regressions"

    def test_cli_missing_dir_exits_2(self, tmp_path):
        proc = self._run(str(tmp_path / "nope"))
        assert proc.returncode == 2
        assert "no history files" in proc.stderr

    def test_cli_tolerates_torn_tail(self, tmp_path):
        _write_run(tmp_path, ranks=1)
        with open(tmp_path / "history-rank0.jsonl", "a") as f:
            f.write('{"t_us": 99, "s": {"torn')
        proc = self._run(str(tmp_path), "--json")
        assert proc.returncode == 0, proc.stderr[-2000:]
        report = json.loads(proc.stdout)
        assert report["labels"][0]["samples"] == 40
