"""History-sampler + detector overhead guard (slow tier) — the health
plane runs entirely off the hot path, so its step cost must be
invisible: ``bench_engine.py --health`` A/Bs a 2-process
fused-allreduce + StepTimer loop with the sampler ticking at a 100 ms
cadence (50x the production default) vs disabled (the BENCH_METRICS
in-process interleaved method, p25 of pooled per-step wall times), and
this guard holds the overhead under 1%, regenerating
``BENCH_HEALTH.json``.

One re-measure is allowed before failing — a shared CI box can stay
saturated through one window (the BENCH_METRICS precedent)."""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

BUDGET = 0.01


def _run_bench(out_path: str, rounds: int) -> dict:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "bench_engine.py"),
         "--health", "--health-rounds", str(rounds),
         "--out", out_path],
        capture_output=True, text=True, timeout=600, cwd=root)
    assert proc.returncode == 0, proc.stderr[-3000:]
    return json.loads(open(out_path).read())


def test_health_overhead_under_1_percent(tmp_path):
    out = tmp_path / "bench_health.json"
    result = _run_bench(str(out), rounds=6)
    if result["overhead_frac"] >= BUDGET:   # one re-measure
        result = _run_bench(str(out), rounds=6)

    # Regenerate the committed artifact from the accepted run.
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(root, "BENCH_HEALTH.json"), "w") as f:
        json.dump(result, f, indent=2, sort_keys=True)
        f.write("\n")

    assert result["rows"]["health_on"]["step_time_ms"] > 0
    assert result["history_samples_written"] > 0, \
        "the on-arm sampler never wrote a sample — the A/B measured nothing"
    assert result["overhead_frac"] < BUDGET, (
        f"history sampler + detectors cost "
        f"{result['overhead_frac']:.2%} of the 2-process step time "
        f"(on {result['rows']['health_on']['step_time_ms']} ms vs off "
        f"{result['rows']['health_off']['step_time_ms']} ms; "
        f"budget {BUDGET:.0%})")

    # The seeded detector smoke is deterministic: the leak trips, the
    # noisy flat gauge does not, the 20% shift fires promptly.
    smoke = result["detector_smoke"]
    assert smoke["leak_windows_fired"] > 0
    assert smoke["noisy_flat_windows_fired"] == 0
    assert smoke["regression_first_fired_at_sample"] is not None
    assert (smoke["regression_first_fired_at_sample"]
            - smoke["regression_onset_sample"]) <= 3
