"""Flight recorder (docs/postmortem.md): ring-buffer semantics, dump
format, engine integration, and the final-gasp exit paths (excepthook,
SIGTERM, kill-mid-step) that must leave BOTH a valid blackbox dump and
a fresh metrics snapshot behind."""

import json
import os
import signal
import subprocess
import sys
import time

import jax.numpy as jnp
import pytest

import horovod_tpu as hvd
from horovod_tpu.observability import flight_recorder as fr

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_dump(path):
    header, events = None, []
    for line in open(path):
        line = line.strip()
        if not line:
            continue
        obj = json.loads(line)
        if header is None and obj.get("blackbox"):
            header = obj
        else:
            events.append(obj)
    return header, events


class TestRing:
    def test_bounded_capacity(self):
        rec = fr.FlightRecorder(capacity=8)
        for i in range(100):
            rec.note("step", (i,))
        assert len(rec._ring) == 8
        assert rec._ring[-1][2] == (99,)

    def test_set_enabled_gates_recording(self):
        rec = fr.FlightRecorder(capacity=8)
        fr.set_enabled(False)
        try:
            rec.note("step", (1,))
            rec.group_deliver(0, "allreduce", 1)
            rec.group_done(0, "allreduce", 1, 0.0, 0.0, 0.0)
        finally:
            fr.set_enabled(True)
        assert len(rec._ring) == 0

    def test_dump_returns_none_without_directory(self, monkeypatch):
        monkeypatch.delenv("HOROVOD_TPU_BLACKBOX", raising=False)
        rec = fr.FlightRecorder(capacity=8)
        rec.note("step", (1,))
        assert rec.dump("test") is None


class TestDumpFormat:
    def test_header_and_event_schema(self, tmp_path):
        rec = fr.FlightRecorder(capacity=32)
        rec.configure(rank=3, world=4, generation=1)
        rec.set_clock_meta(0.25, 0.001, True)
        rec.note("step", (7,))
        rec.group_deliver(12, "allreduce", 5)
        now = time.monotonic()
        rec.group_done(12, "allreduce", 5, now - 0.75, now - 0.25, now)
        rec.note("failure", (2, "heartbeat_timeout", "gone"))
        rec.note("fault", ("delay", 3))
        path = rec.dump("unit_test", directory=str(tmp_path))
        assert path == str(tmp_path / "blackbox-rank3.jsonl")
        header, events = _load_dump(path)
        assert header["rank"] == 3 and header["world"] == 4
        assert header["generation"] == 1
        assert header["reason"] == "unit_test"
        assert header["offset_to_rank0_us"] == pytest.approx(250000.0)
        assert header["clock_synced"] is True
        kinds = [e["kind"] for e in events]
        assert kinds == ["step", "group_deliver", "group_done",
                        "failure", "fault"]
        done = events[2]
        assert done["seq"] == 12 and done["op"] == "allreduce"
        assert done["queue_ms"] == pytest.approx(500.0)
        assert done["exec_ms"] == pytest.approx(250.0)
        # Payload field names must not collide with the event's own keys
        # (a 'failure' carries failure_kind, a 'fault' carries fault).
        assert events[3]["kind"] == "failure"
        assert events[3]["failure_kind"] == "heartbeat_timeout"
        assert events[4]["kind"] == "fault"
        assert events[4]["fault"] == "delay"

    def test_window_drops_old_events(self, tmp_path):
        rec = fr.FlightRecorder(capacity=128)
        rec.configure(0, 1)
        # Backdate one event far past any window by poking the ring.
        rec._ring.append((time.monotonic() - 3600.0, "step", (0,)))
        rec.note("step", (1,))
        path = rec.dump("w", directory=str(tmp_path), window_s=60.0)
        _, events = _load_dump(path)
        assert [e["idx"] for e in events] == [1]

    def test_dump_counter_metric(self, tmp_path):
        rec = fr.FlightRecorder(capacity=8)
        rec.configure(0, 1)
        rec.dump("metric_test", directory=str(tmp_path))
        snap = hvd.metrics_snapshot()
        vals = snap["hvdtpu_blackbox_dumps_total"]["values"]
        assert vals.get('reason="metric_test"', 0) >= 1


class TestEngineIntegration:
    def test_collectives_recorded_as_group_events(self, tmp_path):
        """The live engine's dispatch paths append group lifecycle
        events to the process-global recorder."""
        hvd.allreduce(jnp.ones((8,)), name="fr.groups.a")
        hvd.allgather(jnp.ones((2, 2)), name="fr.groups.b")
        path = fr.recorder().dump("engine_test", directory=str(tmp_path))
        _, events = _load_dump(path)
        done = [e for e in events if e["kind"] == "group_done"]
        assert any(e["op"] == "allreduce" for e in done)
        assert any(e["op"] == "allgather" for e in done)
        delivered = [e for e in events if e["kind"] == "group_deliver"]
        assert delivered, "no group_deliver events recorded"
        # Every completed group was delivered first, with a matching seq.
        done_seqs = {e["seq"] for e in done}
        assert done_seqs <= {e["seq"] for e in delivered}

    def test_step_timer_records_step_events(self, tmp_path):
        from horovod_tpu.observability import StepTimer
        t = StepTimer("fr_test", batch_size=2)
        with t:
            hvd.allreduce(jnp.ones((4,)), name="fr.step.a")
        path = fr.recorder().dump("step_test", directory=str(tmp_path))
        _, events = _load_dump(path)
        kinds = {e["kind"] for e in events}
        assert "step" in kinds and "step_end" in kinds
        end = [e for e in events if e["kind"] == "step_end"][-1]
        assert end["step_ms"] > 0
        for f in ("input_ms", "h2d_ms", "compute_ms", "comm_ms"):
            assert f in end


_FINAL_GASP_SCRIPT = r"""
import os, sys, time
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import horovod_tpu as hvd
from horovod_tpu.observability import StepTimer

hvd.init()
timer = StepTimer("gasp", batch_size=4)
mode = sys.argv[1]
for step in range(1000):
    with timer:
        hvd.allreduce(jnp.ones((16,)), name=f"gasp.{step}", average=False)
    if step == 5:
        print("MIDSTEP", flush=True)
        if mode == "raise":
            raise RuntimeError("boom at step 5")
        time.sleep(120)   # park mid-job; the test kills us here
"""


class TestFinalGasp:
    """Satellite: the excepthook/SIGTERM path must flush BOTH the
    flight-recorder dump and the last metrics snapshot — a job killed
    mid-step leaves neither file stale."""

    def _env(self, tmp_path):
        env = dict(os.environ)
        env.update({
            "HOROVOD_TPU_BLACKBOX": str(tmp_path),
            "HOROVOD_TPU_METRICS_FILE": str(tmp_path / "metrics.json"),
            # Long interval: the periodic writer alone would be stale;
            # only the final gasp can produce a fresh file.
            "HOROVOD_TPU_METRICS_INTERVAL": "3600",
        })
        return env

    def _assert_both_files_valid(self, tmp_path, expect_reason):
        header, events = _load_dump(str(tmp_path / "blackbox-rank0.jsonl"))
        assert header["reason"] == expect_reason
        assert any(e["kind"] == "group_done" for e in events)
        assert any(e["kind"] == "step_end" for e in events)
        metrics = json.loads((tmp_path / "metrics.json").read_text())
        steps = metrics["hvdtpu_step_seconds"]["values"]
        assert any(v["count"] >= 5 for v in steps.values())

    def test_uncaught_exception_dumps_and_flushes(self, tmp_path):
        proc = subprocess.run(
            [sys.executable, "-c", _FINAL_GASP_SCRIPT, "raise"],
            env=self._env(tmp_path), capture_output=True, text=True,
            timeout=300, cwd=ROOT)
        assert proc.returncode != 0
        assert "boom at step 5" in proc.stderr
        self._assert_both_files_valid(tmp_path, "exception")
        header, _ = _load_dump(str(tmp_path / "blackbox-rank0.jsonl"))
        assert "boom at step 5" in header["error"]

    def test_sigterm_kill_mid_step_dumps_and_flushes(self, tmp_path):
        proc = subprocess.Popen(
            [sys.executable, "-c", _FINAL_GASP_SCRIPT, "park"],
            env=self._env(tmp_path), stdout=subprocess.PIPE, text=True,
            cwd=ROOT)
        try:
            assert proc.stdout.readline().strip() == "MIDSTEP"
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=60)
        finally:
            if proc.poll() is None:
                proc.kill()
        self._assert_both_files_valid(tmp_path, "sigterm")

    def test_abrupt_kill_leaves_valid_prefix(self, tmp_path):
        """SIGKILL straight through a dump in progress: whatever made it
        to disk must parse line-by-line (the postmortem loader's
        valid-prefix contract). We SIGTERM (which starts the dump) and
        SIGKILL immediately after — on a slow box the dump may be
        mid-write."""
        proc = subprocess.Popen(
            [sys.executable, "-c", _FINAL_GASP_SCRIPT, "park"],
            env=self._env(tmp_path), stdout=subprocess.PIPE, text=True,
            cwd=ROOT)
        try:
            assert proc.stdout.readline().strip() == "MIDSTEP"
            proc.send_signal(signal.SIGTERM)
            time.sleep(0.05)
            proc.kill()
            proc.wait(timeout=60)
        finally:
            if proc.poll() is None:
                proc.kill()
        path = tmp_path / "blackbox-rank0.jsonl"
        if not path.exists():
            pytest.skip("kill landed before the dump opened the file")
        from horovod_tpu.tools.postmortem import load_dump
        dump = load_dump(str(path))
        # Whatever prefix exists parses; a complete dump has the header
        # (either the SIGTERM gasp or an earlier in-flight snapshot).
        if dump is not None and dump.header:
            assert dump.header["reason"] in ("sigterm", "inflight")
