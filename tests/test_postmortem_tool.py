"""Postmortem CLI (docs/postmortem.md) on SYNTHETIC per-rank dumps —
no subprocesses, no engine. Covers the satellite contract: deliberately
truncated dumps (killed mid-dump) and missing ranks (hard kill, no
final gasp) must still yield a correct who-died-first / where-diverged
verdict."""

import json

import pytest

from horovod_tpu.tools import postmortem

US = 1_000_000  # µs per second


def _write_dump(path, rank, world, events, *, reason="sigterm",
                offset_us=0.0, synced=True, mono_us=100 * US,
                generation=0, error=None):
    """One blackbox file the way FlightRecorder.dump lays it out."""
    header = {"blackbox": 1, "rank": rank, "world": world,
              "generation": generation, "reason": reason, "error": error,
              "time_unix": 1700000000.0, "mono_us": mono_us,
              "window_s": 120.0, "events": len(events),
              "offset_to_rank0_us": offset_us, "rtt_us": 40.0,
              "clock_synced": synced}
    lines = [json.dumps(header)]
    lines += [json.dumps(e) for e in events]
    path.write_text("\n".join(lines) + "\n")
    return path


def _groups(n, t0_us=0, step_us=10000, extra_open=None):
    """n completed groups (deliver+done), optionally one delivered-but-
    never-completed seq after them."""
    out = []
    for s in range(n):
        t = t0_us + s * step_us
        out.append({"t_us": t, "kind": "group_deliver", "seq": s,
                    "op": "allreduce", "n": 4})
        out.append({"t_us": t + 2000, "kind": "group_done", "seq": s,
                    "op": "allreduce", "n": 4, "queue_ms": 0.1,
                    "exec_ms": 1.5})
    if extra_open is not None:
        out.append({"t_us": t0_us + n * step_us, "kind": "group_deliver",
                    "seq": extra_open, "op": "allreduce", "n": 4})
    return out


class TestLoader:
    def test_truncated_dump_parses_valid_prefix(self, tmp_path):
        p = _write_dump(tmp_path / "blackbox-rank0.jsonl", 0, 2,
                        _groups(3))
        # Kill mid-line: append a torn JSON line.
        with open(p, "a") as f:
            f.write('{"t_us": 999, "kind": "group_del')
        dump = postmortem.load_dump(str(p))
        assert dump.rank == 0
        assert len(dump.events) == 6
        assert dump.truncated is True

    def test_headerless_dump_uses_filename_rank(self, tmp_path):
        p = tmp_path / "blackbox-rank7.jsonl"
        p.write_text(json.dumps(
            {"t_us": 1, "kind": "step", "idx": 0}) + "\n")
        dump = postmortem.load_dump(str(p))
        assert dump.rank == 7
        assert dump.truncated is True

    def test_empty_file_returns_none(self, tmp_path):
        p = tmp_path / "blackbox-rank0.jsonl"
        p.write_text("")
        assert postmortem.load_dump(str(p)) is None

    def test_discover_directory_and_missing(self, tmp_path):
        _write_dump(tmp_path / "blackbox-rank0.jsonl", 0, 1, _groups(1))
        assert len(postmortem.discover([str(tmp_path)])) == 1
        with pytest.raises(FileNotFoundError):
            postmortem.discover([str(tmp_path / "nope")])


class TestAnalysis:
    def test_crashed_rank_named_with_phase_and_divergence(self, tmp_path):
        """Rank 1 dumped at an injected crash after 5 groups; ranks 0/2/3
        were SIGTERMed later with a 6th group begun but never completed.
        The verdict must name rank 1, its death phase, and seq 5 as the
        divergence point."""
        world = 4
        for r in (0, 2, 3):
            events = _groups(6 if False else 5, extra_open=5)
            events.append({"t_us": 60000, "kind": "step", "idx": 5})
            events.append({"t_us": 65000, "kind": "failure", "rank": 1,
                           "failure_kind": "heartbeat_timeout",
                           "detail": "rank 1 gone"})
            _write_dump(tmp_path / f"blackbox-rank{r}.jsonl", r, world,
                        events, reason="sigterm", mono_us=200 * US)
        crash_events = _groups(5)
        crash_events.append({"t_us": 52000, "kind": "fault",
                             "fault": "crash", "tick": 5})
        _write_dump(tmp_path / "blackbox-rank1.jsonl", 1, world,
                    crash_events, reason="fault_crash",
                    mono_us=150 * US)

        dumps = [postmortem.load_dump(str(tmp_path / f))
                 for f in sorted(p.name for p in tmp_path.iterdir())]
        report = postmortem.analyze([d for d in dumps if d])
        assert report["world"] == 4
        assert report["ranks_missing"] == []
        assert report["died_first"]["rank"] == 1
        assert report["died_first"]["how"] == "fault_crash"
        assert "fault injection" in report["died_first"]["phase"]
        assert report["common_last_group_seq"] == 4
        assert report["first_divergent_group_seq"] == 5
        # Survivor evidence recorded too.
        assert report["failure_votes"] == {"1": 3}
        text = postmortem.format_report(report)
        assert "rank 1 went first" in text
        assert "First divergent group seq: 5" in text

    def test_missing_rank_is_primary_suspect(self, tmp_path):
        """No dump at all from rank 2 (hard SIGKILL): the report names
        it from absence + survivor votes."""
        world = 3
        for r in (0, 1):
            _write_dump(tmp_path / f"blackbox-rank{r}.jsonl", r, world,
                        _groups(4), reason="sigterm")
        dumps = [postmortem.load_dump(str(p))
                 for p in sorted(tmp_path.iterdir())]
        report = postmortem.analyze(dumps)
        assert report["ranks_missing"] == [2]
        assert report["died_first"]["rank"] == 2
        assert "no dump" in report["died_first"]["how"]
        text = postmortem.format_report(report)
        assert "died without a final gasp" in text

    def test_divergent_last_seqs(self, tmp_path):
        """Ranks stopped at different completed seqs: divergence is the
        floor + 1."""
        _write_dump(tmp_path / "blackbox-rank0.jsonl", 0, 2, _groups(8))
        _write_dump(tmp_path / "blackbox-rank1.jsonl", 1, 2, _groups(5),
                    reason="exception", error="RuntimeError: boom")
        dumps = [postmortem.load_dump(str(p))
                 for p in sorted(tmp_path.iterdir())]
        report = postmortem.analyze(dumps)
        assert report["common_last_group_seq"] == 5 - 1
        assert report["first_divergent_group_seq"] == 5
        # exception beats sigterm as origin evidence
        assert report["died_first"]["rank"] == 1

    def test_no_divergence_when_everyone_stopped_clean(self, tmp_path):
        for r in range(2):
            _write_dump(tmp_path / f"blackbox-rank{r}.jsonl", r, 2,
                        _groups(3), reason="sigterm")
        dumps = [postmortem.load_dump(str(p))
                 for p in sorted(tmp_path.iterdir())]
        report = postmortem.analyze(dumps)
        assert report["first_divergent_group_seq"] is None
        assert "No divergence recorded" in postmortem.format_report(report)

    def test_clock_alignment_orders_deaths(self, tmp_path):
        """Rank 1's local clock is 50 s behind rank 0's; with the
        recorded offset its (later) local dump time still lands AFTER
        rank 0's on the aligned clock, so rank 0 died first."""
        _write_dump(tmp_path / "blackbox-rank0.jsonl", 0, 2, _groups(2),
                    reason="sigterm", mono_us=100 * US)
        _write_dump(tmp_path / "blackbox-rank1.jsonl", 1, 2, _groups(2),
                    reason="sigterm", mono_us=60 * US,
                    offset_us=50.0 * US)
        dumps = [postmortem.load_dump(str(p))
                 for p in sorted(tmp_path.iterdir())]
        report = postmortem.analyze(dumps)
        assert report["died_first"]["rank"] == 0

    def test_adaptation_ladder_replayed(self, tmp_path):
        events = _groups(3)
        events.append({"t_us": 5000, "kind": "adapt",
                       "action": "escalate", "tier": 1, "name": "shrink",
                       "rank": 2, "lateness_ms": 120.0})
        events.append({"t_us": 15000, "kind": "adapt",
                       "action": "escalate", "tier": 2, "name": "bf16",
                       "rank": 2, "lateness_ms": 130.0})
        events.append({"t_us": 25000, "kind": "adapt",
                       "action": "escalate", "tier": 2, "name": "evict",
                       "rank": 2, "lateness_ms": 140.0})
        _write_dump(tmp_path / "blackbox-rank0.jsonl", 0, 2, events,
                    reason="eviction")
        _write_dump(tmp_path / "blackbox-rank1.jsonl", 1, 2, _groups(3),
                    reason="sigterm")
        dumps = [postmortem.load_dump(str(p))
                 for p in sorted(tmp_path.iterdir())]
        report = postmortem.analyze(dumps)
        ladder = report["adaptation_at_death"]
        assert ladder["tier"] == 2
        assert ladder["active_tiers"] == ["shrink", "bf16"]
        assert ladder["evicted_ranks"] == [2]
        assert "tier 2 (shrink, bf16)" in postmortem.format_report(report)


class TestCli:
    def test_cli_on_directory_writes_json(self, tmp_path, capsys):
        for r in range(2):
            _write_dump(tmp_path / f"blackbox-rank{r}.jsonl", r, 2,
                        _groups(3), reason="sigterm")
        out = tmp_path / "report.json"
        postmortem._main([str(tmp_path), "--json", str(out)])
        printed = capsys.readouterr().out
        assert "Post-mortem — world size 2" in printed
        report = json.loads(out.read_text())
        assert report["ranks_dumped"] == [0, 1]

    def test_cli_tolerates_truncated_input(self, tmp_path, capsys):
        p = _write_dump(tmp_path / "blackbox-rank0.jsonl", 0, 1,
                        _groups(2))
        with open(p, "a") as f:
            f.write('{"torn')
        postmortem._main([str(tmp_path)])
        assert "truncated dump" in capsys.readouterr().out
