"""Satellite CI check: the metric surface cannot silently drift from
its documentation. Every ``hvdtpu_*`` metric family registered anywhere
in ``horovod_tpu/`` must appear in docs/metrics.md's reference tables,
and every table entry must correspond to a registration in code —
in both directions, by static scan (no imports, no device runtime)."""

import os
import re

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(ROOT, "horovod_tpu")
DOC = os.path.join(ROOT, "docs", "metrics.md")

# A registration is a .counter(/.gauge(/.histogram( call whose first
# argument is an hvdtpu_* string literal — the only way families are
# created in this codebase. Comments/docstrings mentioning names and
# the native lib's hvdtpu_* C symbols don't match.
_REG_RE = re.compile(
    r"\.(?:counter|gauge|histogram)\(\s*\n?\s*[\"'](hvdtpu_[a-z0-9_]+)"
    r"[\"']", re.MULTILINE)

_BACKTICK_RE = re.compile(r"`([a-z0-9_]+)`")
_PAREN_RE = re.compile(r"\([^)]*\)")


def _code_metrics():
    names = set()
    for dirpath, dirnames, filenames in os.walk(PKG):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            with open(os.path.join(dirpath, fn)) as f:
                names.update(_REG_RE.findall(f.read()))
    return names


def _doc_metrics():
    """Metric names from the reference tables: the first cell of every
    `| ... | type | meaning |` row, parenthesized label lists stripped,
    remaining backticked tokens taken as (possibly several) metric
    names. Names are documented without the hvdtpu_ prefix."""
    names = set()
    in_reference = False
    for line in open(DOC):
        if line.startswith("## "):
            in_reference = line.strip() == "## Metric reference"
            continue
        if not in_reference or not line.startswith("|"):
            continue
        cells = line.split("|")
        if len(cells) < 3:
            continue
        first = _PAREN_RE.sub("", cells[1])
        if cells[2].strip() not in ("counter", "gauge", "histogram",
                                    "counter / gauge",
                                    "histogram / gauge"):
            continue  # header / separator rows
        for tok in _BACKTICK_RE.findall(first):
            names.add("hvdtpu_" + tok)
    return names


def test_every_registered_metric_is_documented():
    code, doc = _code_metrics(), _doc_metrics()
    assert code, "static scan found no metric registrations — regex rot?"
    missing = sorted(code - doc)
    assert not missing, (
        "metrics registered in code but absent from docs/metrics.md's "
        f"reference tables: {missing} — document them (the table name "
        "is the hvdtpu_-stripped family name)")


def test_every_documented_metric_exists_in_code():
    code, doc = _code_metrics(), _doc_metrics()
    assert doc, "doc table parse found no metrics — parser rot?"
    stale = sorted(doc - code)
    assert not stale, (
        "metrics documented in docs/metrics.md but registered nowhere "
        f"in horovod_tpu/: {stale} — remove or fix the table entries")
