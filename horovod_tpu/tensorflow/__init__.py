"""horovod_tpu.tensorflow — the TensorFlow framework shim.

Parity target: horovod/tensorflow/__init__.py (326) + mpi_ops.py (183) +
the C++ binding horovod/tensorflow/mpi_ops.cc (466): differentiable
``allreduce`` / ``allgather`` / ``broadcast`` on ``tf.Tensor``s with the
reference's registered gradients (tensorflow/mpi_ops.py:94-183),
``DistributedOptimizer`` overriding gradient computation
(tensorflow/__init__.py:151-249), ``DistributedGradientTape``
(tensorflow/__init__.py:252-326), ``broadcast_variables`` and a
``BroadcastGlobalVariablesCallback``-style hook.

Where the reference registers a TF ``AsyncOpKernel`` that enqueues into
the MPI coordinator (mpi_ops.cc:281-303), this shim bridges with
``tf.py_function`` into the TPU-native XLA engine: eager tensors cross
via numpy; inside a traced ``tf.function`` the py_function node plays the
AsyncOpKernel's role (a host callback that blocks on the engine handle).
TF stays the autograd engine; the collectives run on the XLA data plane.

Gradient registrations (all three, mirroring tensorflow/mpi_ops.py):
- grad(allreduce(x))  = allreduce(grad)            (94-105)
- grad(allgather(x))  = this rank's slice of the unsummed
                        allreduce of the gathered grad (127-148)
- grad(broadcast(x))  = allreduce(grad), zeroed on non-root (168-183)
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import tensorflow as tf

from .. import ops as _ops
from .. import topology as _topo
from ..compression import Compression
from ..topology import (init, shutdown, is_initialized, rank, local_rank,
                        size, local_size, mpi_threads_supported)

__all__ = [
    "init", "shutdown", "is_initialized", "rank", "local_rank", "size",
    "local_size", "mpi_threads_supported", "Compression",
    "allreduce", "allgather", "broadcast", "broadcast_variables",
    "broadcast_global_variables", "DistributedOptimizer",
    "DistributedGradientTape", "BroadcastGlobalVariablesCallback",
]


# ---------------------------------------------------------------------------
# Host bridge — the AsyncOpKernel analogue
# ---------------------------------------------------------------------------

def _np(x: tf.Tensor) -> np.ndarray:
    arr = x.numpy()
    if arr.dtype == np.float64 or arr.dtype == np.int64:
        # tf defaults many python constants to 64-bit; the engine's wire is
        # 32-bit unless jax_enable_x64 — the result is cast back by Tout.
        import jax
        if not jax.config.jax_enable_x64:
            arr = arr.astype(
                np.float32 if arr.dtype == np.float64 else np.int32)
    return arr


def _hvd_allreduce_host(x: tf.Tensor, average: bool, name: str) -> np.ndarray:
    out = _ops.allreduce(_np(x), average=average, name=name or None)
    return np.asarray(out)


def _py_collective(host_fn, inputs: tf.Tensor, out_dtype, out_shape):
    out = tf.py_function(host_fn, [inputs], Tout=out_dtype)
    if out_shape is not None:
        out.set_shape(out_shape)
    return out


_name_counter = [0]


def _auto_name(prefix: str, name: Optional[str]) -> str:
    if name:
        return name
    _name_counter[0] += 1
    return f"tf.{prefix}.{_name_counter[0]}"


# ---------------------------------------------------------------------------
# Differentiable collectives
# ---------------------------------------------------------------------------

def allreduce(tensor, average: bool = True, name: Optional[str] = None,
              compression=Compression.none):
    """Differentiable allreduce. ``tf.IndexedSlices`` inputs are handled
    as allgather(values)+allgather(indices) — the sparse data-parallel
    path (tensorflow/__init__.py:72-83)."""
    if isinstance(tensor, tf.IndexedSlices):
        values = allgather(tensor.values, name=_auto_name("ar.sv", name))
        indices = allgather(tensor.indices, name=_auto_name("ar.si", name))
        if average:
            values = values / float(_topo.size())
        return tf.IndexedSlices(values, indices,
                                dense_shape=tensor.dense_shape)

    nm = _auto_name("allreduce", name)

    @tf.custom_gradient
    def _op(x):
        wire = x
        ctx = None
        if compression is not Compression.none:
            warr = tf.cast(x, tf.float16) if x.dtype.is_floating else x
            wire, ctx = warr, x.dtype

        def host(v):
            return _hvd_allreduce_host(v, average, nm)

        out = _py_collective(host, wire, wire.dtype, wire.shape)
        if ctx is not None:
            out = tf.cast(out, ctx)

        def grad(dy):
            return allreduce(dy, average=average,
                             name=_auto_name("allreduce", None),
                             compression=compression)

        return out, grad

    return _op(tf.convert_to_tensor(tensor))


def allgather(tensor, name: Optional[str] = None):
    """Differentiable allgather along dim 0 (tensorflow/mpi_ops.py:107-148).
    Backward: sum-allreduce the gathered gradient, slice this rank's
    segment."""
    nm = _auto_name("allgather", name)

    @tf.custom_gradient
    def _op(x):
        dim0 = x.shape[0]

        def host(v):
            return np.asarray(_ops.allgather(_np(v), name=nm))

        out_shape = tf.TensorShape(
            [None if dim0 is None else dim0 * _topo.size()]
            + list(x.shape[1:]))
        out = _py_collective(host, x, x.dtype, out_shape)

        def grad(dy):
            summed = allreduce(dy, average=False,
                               name=_auto_name("allgather.grad", None))
            r = _topo.rank()
            n = tf.shape(summed)[0] // _topo.size()
            return summed[r * n:(r + 1) * n]

        return out, grad

    return _op(tf.convert_to_tensor(tensor))


def broadcast(tensor, root_rank: int = 0, name: Optional[str] = None):
    """Differentiable broadcast (tensorflow/mpi_ops.py:150-183).
    Backward: allreduce the gradient; non-root ranks contribute zeros."""
    nm = _auto_name("broadcast", name)

    @tf.custom_gradient
    def _op(x):
        def host(v):
            return np.asarray(_ops.broadcast(_np(v), root_rank, name=nm))

        out = _py_collective(host, x, x.dtype, x.shape)

        def grad(dy):
            g = allreduce(dy, average=False,
                          name=_auto_name("broadcast.grad", None))
            if _topo.rank() != root_rank:
                g = tf.zeros_like(g)
            return g

        return out, grad

    return _op(tf.convert_to_tensor(tensor))


# ---------------------------------------------------------------------------
# Variable sync
# ---------------------------------------------------------------------------

def broadcast_variables(variables, root_rank: int = 0) -> None:
    """Assign every variable the root rank's value
    (tensorflow/__init__.py:95-114)."""
    from ..utils.wire import movement_payload, movement_restore
    handles = []
    for i, v in enumerate(variables):
        arr = np.asarray(v.numpy())  # not ascontiguousarray: it promotes 0-dim to (1,)
        wire, from_bits = movement_payload(arr)
        handles.append((v, arr.dtype, arr.shape, from_bits,
                        _ops.broadcast_async(
                            wire, root_rank, name=f"tf.bcast.{i}.{v.name}")))
    for v, dtype, shape, from_bits, h in handles:
        v.assign(movement_restore(h.wait(), dtype, shape, from_bits))


def broadcast_global_variables(root_rank: int = 0, variables=None) -> None:
    """TF2 has no global-variables collection; pass the variables (e.g.
    ``model.variables``) explicitly."""
    if variables is None:
        raise ValueError(
            "TF2 has no global variable collection; pass variables= "
            "(e.g. model.variables + optimizer.variables)")
    broadcast_variables(variables, root_rank)


class BroadcastGlobalVariablesCallback:
    """Callable hook: invoke once after the first step (when optimizer
    slots exist) to sync all state from ``root_rank`` — the TF2 analogue
    of the reference's SessionRunHook (tensorflow/__init__.py:117-148)."""

    def __init__(self, root_rank: int = 0):
        self.root_rank = root_rank
        self._done = False

    def __call__(self, model=None, optimizer=None) -> None:
        if self._done:
            return
        vs = []
        if model is not None:
            vs += list(model.variables)
        if optimizer is not None:
            vs += list(optimizer.variables)
        broadcast_variables(vs, self.root_rank)
        self._done = True


# ---------------------------------------------------------------------------
# DistributedOptimizer / DistributedGradientTape
# ---------------------------------------------------------------------------

def DistributedOptimizer(optimizer, name: Optional[str] = None,
                         compression=Compression.none,
                         sparse_as_dense: bool = False):
    """Wrap a ``tf.keras.optimizers``-style optimizer: gradients passed to
    ``apply_gradients`` are allreduce-averaged first
    (tensorflow/__init__.py:151-249)."""
    prefix = name or f"Distributed{optimizer.__class__.__name__}"

    class _Wrapped(optimizer.__class__):
        _hvd_wrapped = True

        def apply_gradients(self, grads_and_vars, *args, **kwargs):
            gv = list(grads_and_vars)
            reduced = []
            for i, (g, v) in enumerate(gv):
                if g is None:
                    reduced.append((g, v))
                    continue
                if sparse_as_dense and isinstance(g, tf.IndexedSlices):
                    g = tf.convert_to_tensor(g)
                reduced.append((allreduce(
                    g, average=True, name=f"{prefix}.grad.{i}",
                    compression=compression), v))
            return super().apply_gradients(reduced, *args, **kwargs)

    new = _Wrapped.from_config(optimizer.get_config())
    return new


class DistributedGradientTape(tf.GradientTape):
    """``tf.GradientTape`` whose ``gradient()`` returns allreduce-averaged
    gradients (tensorflow/__init__.py:252-326)."""

    def __init__(self, *args, compression=Compression.none,
                 sparse_as_dense: bool = False, **kwargs):
        super().__init__(*args, **kwargs)
        self._hvd_compression = compression
        self._hvd_sparse_as_dense = sparse_as_dense

    def gradient(self, target, sources, *args, **kwargs):
        grads = super().gradient(target, sources, *args, **kwargs)
        flat = tf.nest.flatten(grads)
        out = []
        for i, g in enumerate(flat):
            if g is None:
                out.append(None)
                continue
            if self._hvd_sparse_as_dense and isinstance(g, tf.IndexedSlices):
                g = tf.convert_to_tensor(g)
            out.append(allreduce(g, average=True,
                                 name=_auto_name("tape.grad", None),
                                 compression=self._hvd_compression))
        return tf.nest.pack_sequence_as(grads, out)
